"""State regen + state caches over a real imported chain.

Reference behavior: packages/beacon-node/src/chain/regen/regen.ts
(getPreState / getCheckpointState replay from the nearest cached state),
chain/stateCache/stateContextCache.ts (LRU), queued.ts (serialized API).
"""

import hashlib

import pytest

from lodestar_tpu import params
from lodestar_tpu import types as T
from lodestar_tpu.chain.produce_block import produce_block
from lodestar_tpu.chain.regen import (
    QueuedStateRegenerator,
    RegenError,
    StateRegenerator,
)
from lodestar_tpu.chain.state_cache import (
    CheckpointStateCache,
    StateContextCache,
)
from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
from lodestar_tpu.crypto import bls as B
from lodestar_tpu.crypto import curves as C
from lodestar_tpu.db import BeaconDb
from lodestar_tpu.fork_choice import ForkChoice, ProtoArray
from lodestar_tpu.params import ForkName
from lodestar_tpu.state_transition import create_genesis_state

P = params.ACTIVE_PRESET
N_BLOCKS = 4


@pytest.fixture(scope="module")
def imported_chain(tmp_path_factory):
    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG, fork_epochs={ForkName.altair: 0}
    )
    sks = [B.keygen(b"regen-%d" % i) for i in range(16)]
    pks = [C.g1_compress(B.sk_to_pk(sk)) for sk in sks]
    genesis = create_genesis_state(cfg, pks, genesis_time=7)
    genesis_root = T.BeaconBlockHeader.hash_tree_root(
        dict(genesis.latest_block_header, state_root=genesis.hash_tree_root())
    ).hex()

    fork_choice = ForkChoice(
        ProtoArray(finalized_root=genesis_root), justified_root=genesis_root
    )
    db = BeaconDb(str(tmp_path_factory.mktemp("regen-db") / "kv"))
    regen = StateRegenerator(fork_choice, db)
    regen.block_state_roots[genesis_root] = genesis.hash_tree_root().hex()
    regen.state_cache.add(genesis)

    state = genesis
    roots = [genesis_root]
    posts = [genesis]
    for slot in range(1, N_BLOCKS + 1):
        block, post = produce_block(
            state, slot, hashlib.sha256(b"rv%d" % slot).digest() * 3
        )
        root = T.BeaconBlockAltair.hash_tree_root(block)
        signed = {"message": block, "signature": b"\x00" * 96}
        fork_choice.on_block(slot, root.hex(), block["parent_root"].hex())
        db.put_block(root, signed)
        regen.on_imported_block(root, post)
        state = post
        roots.append(root.hex())
        posts.append(post)
    yield cfg, regen, roots, posts
    db.close()


def test_pre_state_cached(imported_chain):
    _, regen, roots, posts = imported_chain
    # pre-state of a would-be block at head+1 == head post-state advanced
    st = regen.get_block_slot_state(roots[-1], N_BLOCKS)
    assert st.hash_tree_root() == posts[-1].hash_tree_root()
    advanced = regen.get_block_slot_state(roots[-1], N_BLOCKS + 2)
    assert advanced.slot == N_BLOCKS + 2
    # the cached head state must not have been mutated by the advance
    assert posts[-1].slot == N_BLOCKS


def test_replay_after_eviction(imported_chain):
    _, regen, roots, posts = imported_chain
    # evict every post-state; keep only genesis
    for post in posts[1:]:
        regen.state_cache.delete(post.hash_tree_root().hex())
    before = regen.replayed_blocks
    st = regen.get_block_slot_state(roots[-1], N_BLOCKS)
    assert st.hash_tree_root() == posts[-1].hash_tree_root()
    assert regen.replayed_blocks == before + N_BLOCKS


def test_get_pre_state_for_block(imported_chain):
    _, regen, roots, posts = imported_chain
    fake_next = {
        "parent_root": bytes.fromhex(roots[2]),
        "slot": 3,
    }
    st = regen.get_pre_state(fake_next)
    assert st.slot == 3
    # equals block-3's pre-state: post of block 2 advanced to slot 3
    manual = posts[2].clone()
    from lodestar_tpu.state_transition import process_slots

    process_slots(manual, 3)
    assert st.hash_tree_root() == manual.hash_tree_root()


def test_checkpoint_state(imported_chain):
    _, regen, roots, posts = imported_chain
    cp = {"epoch": 1, "root": bytes.fromhex(roots[-1])}
    st = regen.get_checkpoint_state(cp)
    assert st.slot == P.SLOTS_PER_EPOCH
    # second call is a cache hit (same object)
    assert regen.get_checkpoint_state(cp) is st


def test_regen_errors(imported_chain):
    _, regen, roots, posts = imported_chain
    with pytest.raises(RegenError):
        regen.get_state("ab" * 32)
    with pytest.raises(RegenError):
        regen.get_block_slot_state("cd" * 32, 5)
    with pytest.raises(RegenError):
        regen.get_block_slot_state(roots[-1], 0)  # slot before block


def test_state_cache_lru_bounds():
    cache = StateContextCache(max_states=3)

    class FakeState:
        def __init__(self, n):
            self.n = n

        def hash_tree_root(self):
            return bytes([self.n]) * 32

    for i in range(5):
        cache.add(FakeState(i))
    assert len(cache) == 3
    assert cache.get((b"\x00" * 32).hex()) is None  # oldest evicted
    assert cache.get((b"\x04" * 32).hex()) is not None
    cache.prune((b"\x04" * 32).hex())
    assert len(cache) == 1


def test_checkpoint_cache_pruning():
    cache = CheckpointStateCache(max_epochs=2)
    for epoch in range(4):
        cache.add({"epoch": epoch, "root": b"\xaa" * 32}, object())
    assert len(cache) == 2
    assert cache.get({"epoch": 0, "root": b"\xaa" * 32}) is None
    assert cache.get({"epoch": 3, "root": b"\xaa" * 32}) is not None
    latest = cache.get_latest((b"\xaa" * 32).hex(), max_epoch=10)
    assert latest is cache.get({"epoch": 3, "root": b"\xaa" * 32})
    cache.prune_finalized(4)
    assert len(cache) == 0


def test_block_state_roots_pruned_across_finalized_epochs(tmp_path):
    """ISSUE 15 satellite: `block_state_roots` used to grow one entry
    per imported block for the process lifetime.  Driving a chain
    through finalization (full fake-signature participation, the
    test_beacon_state idiom) must shrink the map in the finalization
    sweep — it tracks the live proto nodes, not every block ever seen."""
    from chaos.harness import StateWorld

    world = StateWorld(tmp_path / "fr", seed=2)
    try:
        chain = world.chain
        # prune on every finalization (the default 256-node threshold
        # defers the sweep far past this test's horizon)
        chain.fork_choice.proto.prune_threshold = 0
        peak = 0
        final_slot = None
        for _ in range(5 * P.SLOTS_PER_EPOCH):
            slot = world.tick_slot()
            world.churn_slot(slot, fork=False, attest=True)
            peak = max(peak, len(chain.regen.block_state_roots))
            if chain._finalized_epoch >= 2:
                final_slot = slot
                break
        assert final_slot is not None, "chain never finalized"
        live = len(chain.regen.block_state_roots)
        # the sweep dropped the pre-finalization tail...
        assert live < peak
        # ...down to exactly the surviving proto nodes (+ nothing else)
        assert live == len(chain.fork_choice.proto.nodes)
        # and regen still works across the pruned boundary: the head
        # regenerates bit-identical from what remains
        assert world.verify_regen(chain.head_root_hex)
    finally:
        world.close()


def test_queued_regen(imported_chain):
    _, regen, roots, posts = imported_chain
    q = QueuedStateRegenerator(regen)
    try:
        fut = q.get_block_slot_state(roots[1], 1)
        assert fut.result(timeout=30).hash_tree_root() == posts[
            1
        ].hash_tree_root()
        bad = q.get_state("ee" * 32)
        with pytest.raises(RegenError):
            bad.result(timeout=30)
    finally:
        q.close()
