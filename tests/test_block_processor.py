"""Block import pipeline: extract -> verify -> fork choice + db.

Reference: packages/beacon-node/src/chain/blocks/ (BlockProcessor,
verifyBlocksSignatures, importBlock).
"""

import pytest

from lodestar_tpu import params
from lodestar_tpu import types as T
from lodestar_tpu.chain.block_processor import BlockError, BlockProcessor
from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
from lodestar_tpu.crypto import bls as B
from lodestar_tpu.crypto import curves as C
from lodestar_tpu.db import BeaconDb
from lodestar_tpu.fork_choice import ForkChoice, ProtoArray
from lodestar_tpu.params import ForkName
from lodestar_tpu.state_transition import EpochCache
from lodestar_tpu.state_transition.signature_sets import BeaconStateView

pytestmark = pytest.mark.smoke

CFG = create_chain_config(
    MAINNET_CHAIN_CONFIG,
    genesis_validators_root=b"\x42" * 32,
    fork_epochs={ForkName.altair: 0},
)
N = 64


class OracleBls:
    """Sync CPU-oracle IBlsVerifier over decoded wire sets."""

    def __init__(self, pks):
        self.pks = pks
        self.jobs = 0

    def verify_signature_sets(self, sets, opts=None):
        from lodestar_tpu.crypto import pairing as P

        self.jobs += 1
        for ws in sets:
            dec = ws.decode()
            if dec.signature is None:
                return False
            agg = B.aggregate_pubkeys([self.pks[i] for i in dec.indices])
            if not P.multi_pairing_is_one(
                [(agg, dec.message), (B.NEG_G1_GEN, dec.signature)]
            ):
                return False
        return True


@pytest.fixture
def world():
    sks = [B.keygen(b"bp-%d" % i) for i in range(N)]
    pk_bytes = [C.g1_compress(B.sk_to_pk(sk)) for sk in sks]
    cache = EpochCache(pk_bytes, epoch=0, seed=b"\x07" * 32)
    genesis_root = b"\x33" * 32
    state = BeaconStateView(
        CFG, 1, cache, block_roots={0: genesis_root}
    )
    fc = ForkChoice(ProtoArray(genesis_root.hex()), genesis_root.hex())
    db = BeaconDb(None)  # in-memory store for the test
    bls = OracleBls([B.sk_to_pk(sk) for sk in sks])
    proc = BlockProcessor(state, bls, fork_choice=fc, db=db)
    yield sks, state, fc, db, proc
    proc.close()


def make_block(sks, state, slot, proposer, parent_root):
    randao_root = CFG.compute_signing_root(
        T.Epoch.hash_tree_root(slot // params.SLOTS_PER_EPOCH),
        CFG.get_domain(state.slot, params.DOMAIN_RANDAO, slot),
    )
    body = T.BeaconBlockBodyAltair.default()
    body["randao_reveal"] = C.g2_compress(B.sign(sks[proposer], randao_root))
    block = {
        "slot": slot,
        "proposer_index": proposer,
        "parent_root": parent_root,
        "state_root": bytes(32),
        "body": body,
    }
    sig_root = CFG.compute_signing_root(
        T.BeaconBlockAltair.hash_tree_root(block),
        CFG.get_domain(state.slot, params.DOMAIN_BEACON_PROPOSER, slot),
    )
    return {
        "message": block,
        "signature": C.g2_compress(B.sign(sks[proposer], sig_root)),
    }


def test_valid_segment_imports(world):
    sks, state, fc, db, proc = world
    b1 = make_block(sks, state, 1, 3, b"\x33" * 32)
    r1 = T.BeaconBlockAltair.hash_tree_root(b1["message"])
    b2 = make_block(sks, state, 2, 4, r1)
    roots = proc.process_blocks([b1, b2]).result(timeout=60)
    assert len(roots) == 2 and proc.imported == 2
    assert fc.has_block(r1.hex())
    assert db.block.get(r1)["message"]["slot"] == 1
    # imported roots become available to sync-aggregate extraction
    assert state.get_block_root_at_slot(1) == r1


def test_bad_proposer_signature_rejected(world):
    sks, state, fc, _db, proc = world
    b1 = make_block(sks, state, 1, 3, b"\x33" * 32)
    bad = dict(b1)
    sig = bytearray(bad["signature"])
    sig[10] ^= 1
    bad["signature"] = bytes(sig)
    with pytest.raises(BlockError) as err:
        proc.process_blocks([bad]).result(timeout=60)
    assert err.value.code == "INVALID_SIGNATURE"
    assert proc.imported == 0


def test_failed_fork_block_restores_prior_root(world):
    sks, state, _fc, _db, proc = world
    b1 = make_block(sks, state, 1, 3, b"\x33" * 32)
    r1 = T.BeaconBlockAltair.hash_tree_root(b1["message"])
    proc.process_blocks([b1]).result(timeout=60)
    assert state.get_block_root_at_slot(1) == r1
    # a competing fork block at the SAME slot with a bad signature must
    # not shadow the imported root after it fails
    fork = make_block(sks, state, 1, 5, b"\x44" * 32)
    sig = bytearray(fork["signature"])
    sig[10] ^= 1
    fork["signature"] = bytes(sig)
    with pytest.raises(BlockError):
        proc.process_blocks([fork]).result(timeout=60)
    assert state.get_block_root_at_slot(1) == r1


def test_non_increasing_slots_rejected(world):
    sks, state, _fc, _db, proc = world
    b1 = make_block(sks, state, 2, 3, b"\x33" * 32)
    b2 = make_block(sks, state, 2, 4, b"\x33" * 32)
    with pytest.raises(BlockError) as err:
        proc.process_blocks([b1, b2]).result(timeout=60)
    assert err.value.code == "NON_INCREASING_SLOTS"
