"""Device merkleization equivalence (ISSUE 16).

The batched SHA-256 kernels (kernels/sha256.py) and the supervised
backend seams (ssz/device_backend.py) must be BIT-IDENTICAL to the
host hash path for every input shape — the whole soundness story of
device-side state roots is "same bytes out, or the host path runs".
Randomized equivalence here runs under JAX_PLATFORMS=cpu (conftest),
so the kernels are exercised through real XLA, just not on a TPU.

Covers: hash_pairs_device vs hashlib, the hash_level padding/bucket
seam, the one-dispatch forest sweep through ChunkTree, the validator
leaf-packing kernel vs a host merkleize reference, fault degradation,
and a ChunkTree property test that interleaves backend switches
(host -> device -> host mid-update stream, both directions).
"""

import hashlib

import numpy as np
import pytest

from lodestar_tpu.bls.supervisor import DeviceSupervisor
from lodestar_tpu.ssz import ChunkTree, merkleize_chunks
from lodestar_tpu.ssz import device_backend as DB
from lodestar_tpu.ssz.hasher import hash_pairs
from lodestar_tpu.ssz.merkle_tree import hash_pairs_plane
from lodestar_tpu.utils.metrics import Registry

jax = pytest.importorskip("jax")

from lodestar_tpu.kernels import sha256 as SK  # noqa: E402


def _make_backend(min_level_rows: int = 1) -> DB.DeviceMerkleBackend:
    reg = Registry()
    sup = DeviceSupervisor(registry=reg, auto_probe=False, enabled=True)
    return DB.DeviceMerkleBackend(
        supervisor=sup,
        registry=reg,
        min_level_rows=min_level_rows,
        use_export=False,
    )


@pytest.fixture
def backend():
    b = _make_backend()
    DB.set_backend(b)
    yield b
    DB.reset_backend()


def _host_digests(pairs: np.ndarray) -> np.ndarray:
    return np.frombuffer(
        b"".join(hashlib.sha256(row.tobytes()).digest() for row in pairs),
        np.uint8,
    ).reshape(-1, 32)


# -- the raw kernel vs hashlib ----------------------------------------------


@pytest.mark.parametrize("n", [1, 5, 33])
def test_hash_pairs_device_matches_hashlib(n):
    rng = np.random.default_rng(n)
    pairs = rng.integers(0, 256, (n, 64), dtype=np.uint8)
    out = np.asarray(SK.hash_pairs_device(SK.pairs_to_blocks(pairs)))
    got = SK.digests_to_bytes(out)
    assert got.shape == (n, 32)
    np.testing.assert_array_equal(got, _host_digests(pairs))
    # and the host batch hasher agrees with hashlib too (both seams)
    assert hash_pairs(pairs.tobytes()) == got.tobytes()


def test_byte_conversion_roundtrip():
    rng = np.random.default_rng(0)
    pairs = rng.integers(0, 256, (9, 64), dtype=np.uint8)
    blocks = SK.pairs_to_blocks(pairs)
    assert blocks.dtype == np.uint32 and blocks.shape == (9, 16)
    # big-endian words: block word 0 is bytes 0..3 of the pair
    assert int(blocks[0, 0]) == int.from_bytes(pairs[0, :4].tobytes(), "big")
    rows = rng.integers(0, 256, (9, 32), dtype=np.uint8)
    np.testing.assert_array_equal(
        SK.digests_to_bytes(SK.rows_to_words(rows)), rows
    )
    # empty planes are well-formed no-ops
    assert SK.pairs_to_blocks(np.zeros((0, 64), np.uint8)).shape == (0, 16)
    assert SK.digests_to_bytes(np.zeros((0, 8), np.uint32)).shape == (0, 32)
    assert SK.rows_to_words(np.zeros((0, 32), np.uint8)).shape == (0, 8)


# -- the hash_level seam (padding buckets) ----------------------------------


@pytest.mark.parametrize("n", [5, 512])
def test_hash_level_pads_to_bucket_and_matches(backend, n):
    rng = np.random.default_rng(n)
    pairs = rng.integers(0, 256, (n, 64), dtype=np.uint8)
    before = backend.dispatches
    rows = backend.hash_level(pairs)
    assert rows is not None
    assert backend.dispatches == before + 1
    np.testing.assert_array_equal(rows, _host_digests(pairs))
    # the padded operand is the smallest runtime bucket >= n
    bucket = next(b for b in SK.HTR_RUNTIME_PAIR_BUCKETS if n <= b)
    assert backend.last_dispatch_bytes == bucket * 16 * 4 + bucket * 8 * 4


@pytest.mark.slow
@pytest.mark.parametrize("n", [513, 8192, 8193])
def test_hash_level_bucket_boundaries(n):
    """Crossing a bucket boundary (513 -> the 8192 bucket, 8193 -> the
    65536 bucket) stays bit-identical — padding lanes never leak."""
    backend = _make_backend()
    rng = np.random.default_rng(n)
    pairs = rng.integers(0, 256, (n, 64), dtype=np.uint8)
    rows = backend.hash_level(pairs)
    assert rows is not None
    np.testing.assert_array_equal(rows, _host_digests(pairs))


def test_hash_level_respects_min_rows_gate():
    backend = _make_backend(min_level_rows=1024)
    pairs = np.zeros((8, 64), np.uint8)
    assert backend.hash_level(pairs) is None
    assert backend.dispatches == 0  # gated out, not failed
    assert backend.supervisor.status()["state"] == "closed"


# -- the forest sweep through ChunkTree -------------------------------------


def test_chunktree_cold_build_is_one_sweep_dispatch(backend):
    rng = np.random.default_rng(1)
    leaves = rng.integers(0, 256, (50, 32), dtype=np.uint8)
    tree = ChunkTree(64)
    tree.update(leaves)
    assert backend.dispatches == 1  # the whole build, one round-trip
    assert tree.root == tree.full_root_reference()
    assert tree.root == merkleize_chunks(
        [leaves[i].tobytes() for i in range(50)], 64
    )


def test_chunktree_dirty_sweep_matches_host(backend):
    rng = np.random.default_rng(2)
    leaves = rng.integers(0, 256, (200, 32), dtype=np.uint8)
    tree = ChunkTree(1 << 10)
    tree.update(leaves)
    for step in range(4):
        idx = rng.integers(0, 200, 7)
        leaves[idx] = rng.integers(0, 256, (7, 32), dtype=np.uint8)
        before = backend.dispatches
        tree.update(leaves)
        assert backend.dispatches == before + 1
        assert tree.root == tree.full_root_reference()
    # growth mid-stream: appended chunks ride the same sweep
    leaves = np.concatenate(
        [leaves, rng.integers(0, 256, (30, 32), dtype=np.uint8)]
    )
    tree.update(leaves)
    assert tree.root == tree.full_root_reference()


def test_chunktree_bulk_update_skips_sweep_lane_bucket():
    """A dirty batch past HTR_SWEEP_LANES declines the sweep and runs
    the per-level loop (host here: the row gate keeps small levels
    off-device) — still bit-identical, zero dispatches."""
    backend = _make_backend(min_level_rows=10**9)
    DB.set_backend(backend)
    try:
        rng = np.random.default_rng(3)
        leaves = rng.integers(
            0, 256, (SK.HTR_SWEEP_LANES + 88, 32), dtype=np.uint8
        )
        tree = ChunkTree(1 << 11)
        tree.update(leaves)
        assert backend.dispatches == 0
        assert tree.root == tree.full_root_reference()
    finally:
        DB.reset_backend()


# -- backend interleaving (property test) -----------------------------------


@pytest.mark.parametrize("device_first", [True, False])
def test_chunktree_backend_interleaving(device_first):
    """Switching merkleization backends MID-update-stream (host ->
    device and device -> host, every step) must leave the incremental
    root bit-identical to a host-only twin and to the merkleize_chunks
    oracle — the planes the two paths write are interchangeable."""
    backend = _make_backend()
    rng = np.random.default_rng(17 if device_first else 71)
    n = 120
    leaves = rng.integers(0, 256, (n, 32), dtype=np.uint8)
    tree = ChunkTree(1 << 9)
    twin = ChunkTree(1 << 9)
    try:
        for step in range(10):
            on_device = (step % 2 == 0) == device_first
            k = int(rng.integers(1, 12))
            idx = rng.integers(0, leaves.shape[0], k)
            leaves[idx] = rng.integers(0, 256, (k, 32), dtype=np.uint8)
            if step == 5:  # grow once, mid-stream
                leaves = np.concatenate(
                    [leaves, rng.integers(0, 256, (13, 32), dtype=np.uint8)]
                )
            DB.set_backend(backend if on_device else None)
            tree.update(leaves)
            DB.set_backend(None)
            twin.update(leaves)
            assert tree.root == twin.root, (step, on_device)
            assert tree.root == tree.full_root_reference(), (step, on_device)
        assert backend.dispatches > 0  # the device legs actually ran
    finally:
        DB.reset_backend()


# -- the validators leaf-packing kernel -------------------------------------


def _host_validator_root(pk_root, cred, eb, aee, ae, ee, we, slashed):
    def u64(v):
        return int(v).to_bytes(8, "little") + b"\x00" * 24

    chunks = [
        bytes(pk_root),
        bytes(cred),
        u64(eb),
        (b"\x01" if slashed else b"\x00") + b"\x00" * 31,
        u64(aee),
        u64(ae),
        u64(ee),
        u64(we),
    ]
    return merkleize_chunks(chunks, 8)


def test_validator_roots_device_matches_host(backend):
    d = 7
    rng = np.random.default_rng(4)
    pk_rows = rng.integers(0, 256, (d, 32), dtype=np.uint8)
    cred_rows = rng.integers(0, 256, (d, 32), dtype=np.uint8)
    cols = [
        rng.integers(0, 1 << 62, d).astype(np.uint64) for _ in range(5)
    ]
    slashed = rng.integers(0, 2, d).astype(bool)
    out = backend.validator_roots(pk_rows, cred_rows, cols, slashed)
    assert out is not None and out.shape == (d, 32)
    for i in range(d):
        expected = _host_validator_root(
            pk_rows[i],
            cred_rows[i],
            cols[0][i],
            cols[1][i],
            cols[2][i],
            cols[3][i],
            cols[4][i],
            bool(slashed[i]),
        )
        assert bytes(out[i]) == expected, i
    # the empty plane short-circuits without a dispatch
    before = backend.dispatches
    empty = backend.validator_roots(
        np.zeros((0, 32), np.uint8),
        np.zeros((0, 32), np.uint8),
        [np.zeros(0, np.uint64)] * 5,
        np.zeros(0, bool),
    )
    assert empty.shape == (0, 32) and backend.dispatches == before


# -- fault degradation ------------------------------------------------------


def test_fault_degrades_to_host_and_root_survives(backend):
    rng = np.random.default_rng(5)
    leaves = rng.integers(0, 256, (64, 32), dtype=np.uint8)
    tree = ChunkTree(128)
    tree.update(leaves)
    assert tree.root == tree.full_root_reference()
    backend.fault = "backend"
    leaves[3] = rng.integers(0, 256, 32, dtype=np.uint8)
    tree.update(leaves)  # sweep fails -> breaker trips -> host loop
    assert tree.root == tree.full_root_reference()  # zero lost roots
    assert backend.supervisor.status()["state"] == "open"
    assert backend.supervisor.status()["last_failure"]["outcome"] == (
        "backend_init"
    )
    # a faulted hash_level degrades the same way: None, host hashes
    pairs = rng.integers(0, 256, (16, 64), dtype=np.uint8)
    assert backend.hash_level(pairs) is None
    plane = hash_pairs_plane(pairs)  # the seam falls through to host
    np.testing.assert_array_equal(plane, _host_digests(pairs))
