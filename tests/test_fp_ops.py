"""JAX Fp layer vs the pure-Python ground truth (`crypto.fields`).

All device work is funneled through a handful of jitted composite functions
so the suite pays a few compiles instead of per-op eager dispatch (the
library is designed to run under an outer jit in production anyway).
"""

import random

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lodestar_tpu.crypto import fields as GT
from lodestar_tpu.ops import fp, limbs as L

rng = random.Random(0xB15)


def rand_fp(n):
    return [rng.randrange(GT.P) for _ in range(n)]


RINV = pow(fp.R_INT, -1, GT.P)


def enc(xs):
    return jnp.asarray(np.stack([fp.const(x) for x in xs]))


def dec(arr):
    return [v * RINV % GT.P for v in L.batch_from_limbs(arr)]


N = 16


@jax.jit
def _ring_suite(a, b):
    return (
        fp.mont_mul(a, b),
        fp.add(a, b),
        fp.sub(a, b),
        fp.neg(a),
        fp.sqr(a),
        fp.is_zero(a),
        fp.mul_small(a, 2),
        fp.mul_small(a, 3),
        fp.mul_small(a, 12),
        fp.sgn(a),
    )


@jax.jit
def _exp_suite(a, sq):
    cand, ok = fp.sqrt(sq)
    return fp.pow_static(a, 5), fp.inv(a), cand, ok


def test_limb_roundtrip():
    for x in rand_fp(8) + [0, 1, GT.P - 1]:
        assert L.from_limbs(L.to_limbs(x)) == x


def test_mul_full_low():
    xs, ys = rand_fp(N), rand_fp(N)
    a = jnp.asarray(L.batch_to_limbs(xs))
    b = jnp.asarray(L.batch_to_limbs(ys))
    full, low = jax.jit(lambda a, b: (L.mul_full(a, b), L.mul_low(a, b)))(a, b)
    assert L.batch_from_limbs(full) == [x * y for x, y in zip(xs, ys)]
    assert L.batch_from_limbs(low) == [x * y % (1 << 384) for x, y in zip(xs, ys)]


def test_ring_ops():
    xs = rand_fp(N - 4) + [0, 1, GT.P - 1, GT.P - 2]
    ys = rand_fp(N - 4) + [GT.P - 1, 0, GT.P - 1, 1]
    a, b = enc(xs), enc(ys)
    mul, add_, sub_, neg_, sq, isz, m2, m3, m12, sg = _ring_suite(a, b)
    assert dec(mul) == [x * y % GT.P for x, y in zip(xs, ys)]
    assert dec(add_) == [(x + y) % GT.P for x, y in zip(xs, ys)]
    assert dec(sub_) == [(x - y) % GT.P for x, y in zip(xs, ys)]
    assert dec(neg_) == [(-x) % GT.P for x in xs]
    assert dec(sq) == [x * x % GT.P for x in xs]
    assert list(np.asarray(isz)) == [x == 0 for x in xs]
    assert dec(m2) == [2 * x % GT.P for x in xs]
    assert dec(m3) == [3 * x % GT.P for x in xs]
    assert dec(m12) == [12 * x % GT.P for x in xs]
    assert [int(v) for v in np.asarray(sg)] == [GT.fp_sgn(x) if x else 0 for x in xs]


def test_exp_ops():
    xs = rand_fp(4)
    sq = [x * x % GT.P for x in xs]
    p5, invs, cand, ok = _exp_suite(enc(xs), enc(sq))
    assert dec(p5) == [pow(x, 5, GT.P) for x in xs]
    assert dec(invs) == [GT.fp_inv(x) for x in xs]
    assert all(np.asarray(ok))
    for got, want in zip(dec(cand), sq):
        assert got * got % GT.P == want
    # non-residues: for p = 3 mod 4, -x^2 is never a QR (x != 0)
    nonres = [(GT.P - x * x) % GT.P for x in xs]
    _, _, _, ok2 = _exp_suite(enc(xs), enc(nonres))
    assert not any(np.asarray(ok2))


def test_to_from_mont():
    xs = rand_fp(N)
    plain = jnp.asarray(L.batch_to_limbs(xs))
    back = jax.jit(lambda a: fp.from_mont(fp.to_mont(a)))(plain)
    assert L.batch_from_limbs(back) == xs
