"""Observability parity: ns job timing + the full bls_thread_pool family.

Reference: packages/beacon-node/src/metrics/metrics/lodestar.ts:357-446
(every blsThreadPool + blsSingleThread instrument) and
chain/bls/multithread/types.ts:26-38 (BlsWorkResult ns fields).
"""

import pytest

from lodestar_tpu.bls.service import BlsVerifierService
from lodestar_tpu.bls.signature_set import WireSignatureSet
from lodestar_tpu.bls.single_thread import CpuBlsVerifier
from lodestar_tpu.bls.verifier import VerifyOptions
from lodestar_tpu.crypto import bls as B
from lodestar_tpu.crypto import curves as C
from lodestar_tpu.utils.metrics import BlsPoolMetrics, Registry

pytestmark = pytest.mark.smoke

# every metric name the reference defines for the pool + single thread
REFERENCE_METRIC_NAMES = (
    "lodestar_bls_thread_pool_time_seconds_sum",
    "lodestar_bls_thread_pool_success_jobs_signature_sets_count",
    "lodestar_bls_thread_pool_error_jobs_signature_sets_count",
    "lodestar_bls_thread_pool_queue_job_wait_time_seconds",
    "lodestar_bls_thread_pool_queue_length",
    "lodestar_bls_thread_pool_workers_busy",
    "lodestar_bls_thread_pool_job_groups_started_total",
    "lodestar_bls_thread_pool_jobs_started_total",
    "lodestar_bls_thread_pool_sig_sets_started_total",
    "lodestar_bls_thread_pool_batch_retries_total",
    "lodestar_bls_thread_pool_batch_sigs_success_total",
    "lodestar_bls_thread_pool_latency_to_worker",
    "lodestar_bls_thread_pool_latency_from_worker",
    "lodestar_bls_thread_pool_main_thread_time_seconds",
    "lodestar_bls_worker_thread_time_per_sigset_seconds",
    "lodestar_bls_single_thread_time_seconds",
    "lodestar_bls_single_thread_time_per_sigset_seconds",
)


@pytest.fixture(scope="module")
def world():
    sks = [B.keygen(b"obs-%d" % i) for i in range(4)]
    pks = [B.sk_to_pk(sk) for sk in sks]
    root = b"\x07" * 32
    sets = [
        WireSignatureSet.single(
            i, root, C.g2_compress(B.sign(sks[i], root))
        )
        for i in range(4)
    ]
    return sks, pks, sets


def test_exposition_covers_every_reference_instrument(world):
    sks, pks, sets = world
    registry = Registry()
    verifier = CpuBlsVerifier(pubkeys=pks, metrics=BlsPoolMetrics(registry))
    service = BlsVerifierService(verifier)
    try:
        assert service.verify_signature_sets(
            sets, VerifyOptions(batchable=True)
        )
        assert service.verify_signature_sets(
            sets[:1], VerifyOptions(verify_on_main_thread=True)
        )
    finally:
        service.close()
    text = registry.expose()
    missing = [n for n in REFERENCE_METRIC_NAMES if n not in text]
    assert not missing, f"missing reference instruments: {missing}"
    # the per-worker time gauge carries its label
    assert 'workerId="0"' in text


def test_ns_job_timing_records(world):
    sks, pks, sets = world
    verifier = CpuBlsVerifier(pubkeys=pks)
    service = BlsVerifierService(verifier)
    try:
        assert service.verify_signature_sets(
            sets, VerifyOptions(batchable=True)
        )
        timings = list(service.recent_job_timings)
        assert timings, "no BlsWorkResult-parity records"
        rec = timings[-1]
        # the exact BlsWorkResult field set (multithread/types.ts:26-38)
        for field in (
            "worker_id",
            "batch_retries",
            "batch_sigs_success",
            "worker_start_ns",
            "worker_end_ns",
        ):
            assert field in rec, field
        assert rec["worker_end_ns"] >= rec["worker_start_ns"] > 0
        assert rec["sig_sets"] == len(sets)
        m = verifier.metrics
        assert m.latency_to_worker.count >= 1
        assert m.latency_from_worker.count >= 1
        assert m.jobs_worker_time.get("0") > 0
        assert m.total_job_groups_started.value >= 1
        assert m.total_sig_sets_started.value >= len(sets)
    finally:
        service.close()


def test_single_thread_family_observed(world):
    sks, pks, sets = world
    verifier = CpuBlsVerifier(pubkeys=pks)
    assert verifier.verify_signature_sets(sets)
    st = verifier.single_thread_metrics
    assert st.duration.count == 1
    assert st.time_per_sig_set.count == 1


def test_timings_visible_over_rest(world):
    """The ns records reach the lodestar introspection endpoint."""
    import json
    import urllib.request

    from lodestar_tpu.api.server import BeaconApiServer, DefaultHandlers

    sks, pks, sets = world
    verifier = CpuBlsVerifier(pubkeys=pks)
    service = BlsVerifierService(verifier)
    server = BeaconApiServer(
        DefaultHandlers(
            bls_metrics=verifier.metrics, bls_service=service
        ),
        port=0,
    )
    server.listen()
    try:
        assert service.verify_signature_sets(
            sets, VerifyOptions(batchable=True)
        )
        with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/eth/v1/lodestar/bls-metrics",
            timeout=30,
        ) as resp:
            data = json.loads(resp.read())["data"]
        assert data["recent_job_timings"], data
        assert data["worker_time_seconds"] > 0
        assert data["recent_job_timings"][-1]["worker_end_ns"] > 0
    finally:
        server.close()
        service.close()


def test_beacon_metrics_family():
    """Spec gauges, import counter/timer, reorg detection, and source-
    counted gossip verdicts (reference: metrics/metrics/beacon.ts)."""
    from lodestar_tpu.chain.chain import BeaconChain
    from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
    from lodestar_tpu.crypto import bls as B
    from lodestar_tpu.crypto import curves as C
    from lodestar_tpu.params import ForkName
    from lodestar_tpu.state_transition import create_genesis_state
    from lodestar_tpu.state_transition.accessors import (
        get_beacon_proposer_index,
    )
    from lodestar_tpu.state_transition.slot import process_slots
    from lodestar_tpu.utils.beacon_metrics import BeaconMetrics
    from lodestar_tpu.utils.metrics import Registry
    from lodestar_tpu.validator import ValidatorStore

    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG, fork_epochs={ForkName.altair: 0}
    )
    sks = [B.keygen(b"bm-%d" % i) for i in range(4)]
    pks = [C.g1_compress(B.sk_to_pk(sk)) for sk in sks]
    genesis = create_genesis_state(cfg, pks, genesis_time=2)
    chain = BeaconChain(cfg, genesis)
    reg = Registry()
    m = BeaconMetrics(reg)
    m.observe_chain(chain)
    store = ValidatorStore(cfg, dict(enumerate(sks)))
    # a REAL import drives block/head events + the import timer
    st = genesis.clone()
    process_slots(st, 1)
    proposer = int(get_beacon_proposer_index(st))
    block = chain.produce_block(1, store.sign_randao(proposer, 1))
    chain.process_block(
        {"message": block, "signature": store.sign_block(proposer, block)}
    )
    assert m.blocks_imported.value == 1
    assert m.head_slot.value == 1  # the HEAD's slot, not the block arg
    assert m.block_import_time.count == 1
    assert m.reorg_count.value == 0  # linear advance is not a reorg
    assert m.op_pool_attestations.value == 0
    # engine residency sampled from the regen caches on head update
    assert m.state_root_engine_bytes.value > 0

    # gossip verdicts count AT the handler
    from lodestar_tpu.bls.single_thread import CpuBlsVerifier
    from lodestar_tpu.network.gossip_handlers import GossipHandlers

    handlers = GossipHandlers(chain, CpuBlsVerifier(pubkeys=[]))
    m.observe_gossip(handlers)
    handlers._count("beacon_block", "accept")
    handlers._count("beacon_block", "reject")
    handlers._count("beacon_block", "accept")
    assert m.gossip_verdicts["accept"].get("beacon_block") == 2
    assert m.gossip_verdicts["reject"].get("beacon_block") == 1

    class _PM:
        peers = {"a": 1, "b": 2}

    m.sample_peers(_PM())
    assert m.peers_connected.value == 2
    text = reg.expose()
    assert "beacon_head_slot 1" in text
    assert "# TYPE lodestar_gossip_accept_total counter" in text
    assert 'lodestar_gossip_accept_total{topic="beacon_block"} 2.0' in text
    assert "libp2p_peers 2" in text
