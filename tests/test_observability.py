"""Observability parity: ns job timing + the full bls_thread_pool family.

Reference: packages/beacon-node/src/metrics/metrics/lodestar.ts:357-446
(every blsThreadPool + blsSingleThread instrument) and
chain/bls/multithread/types.ts:26-38 (BlsWorkResult ns fields).
"""

import pytest

from lodestar_tpu.bls.service import BlsVerifierService
from lodestar_tpu.bls.signature_set import WireSignatureSet
from lodestar_tpu.bls.single_thread import CpuBlsVerifier
from lodestar_tpu.bls.verifier import VerifyOptions
from lodestar_tpu.crypto import bls as B
from lodestar_tpu.crypto import curves as C
from lodestar_tpu.utils.metrics import BlsPoolMetrics, Registry

pytestmark = pytest.mark.smoke

# every metric name the reference defines for the pool + single thread
REFERENCE_METRIC_NAMES = (
    "lodestar_bls_thread_pool_time_seconds_sum",
    "lodestar_bls_thread_pool_success_jobs_signature_sets_count",
    "lodestar_bls_thread_pool_error_jobs_signature_sets_count",
    "lodestar_bls_thread_pool_queue_job_wait_time_seconds",
    "lodestar_bls_thread_pool_queue_length",
    "lodestar_bls_thread_pool_workers_busy",
    "lodestar_bls_thread_pool_job_groups_started_total",
    "lodestar_bls_thread_pool_jobs_started_total",
    "lodestar_bls_thread_pool_sig_sets_started_total",
    "lodestar_bls_thread_pool_batch_retries_total",
    "lodestar_bls_thread_pool_batch_sigs_success_total",
    "lodestar_bls_thread_pool_latency_to_worker",
    "lodestar_bls_thread_pool_latency_from_worker",
    "lodestar_bls_thread_pool_main_thread_time_seconds",
    "lodestar_bls_worker_thread_time_per_sigset_seconds",
    "lodestar_bls_single_thread_time_seconds",
    "lodestar_bls_single_thread_time_per_sigset_seconds",
)


@pytest.fixture(scope="module")
def world():
    sks = [B.keygen(b"obs-%d" % i) for i in range(4)]
    pks = [B.sk_to_pk(sk) for sk in sks]
    root = b"\x07" * 32
    sets = [
        WireSignatureSet.single(
            i, root, C.g2_compress(B.sign(sks[i], root))
        )
        for i in range(4)
    ]
    return sks, pks, sets


def test_exposition_covers_every_reference_instrument(world):
    sks, pks, sets = world
    registry = Registry()
    verifier = CpuBlsVerifier(pubkeys=pks, metrics=BlsPoolMetrics(registry))
    service = BlsVerifierService(verifier)
    try:
        assert service.verify_signature_sets(
            sets, VerifyOptions(batchable=True)
        )
        assert service.verify_signature_sets(
            sets[:1], VerifyOptions(verify_on_main_thread=True)
        )
    finally:
        service.close()
    text = registry.expose()
    missing = [n for n in REFERENCE_METRIC_NAMES if n not in text]
    assert not missing, f"missing reference instruments: {missing}"
    # the per-worker time gauge carries its label
    assert 'workerId="0"' in text


def test_ns_job_timing_records(world):
    sks, pks, sets = world
    verifier = CpuBlsVerifier(pubkeys=pks)
    service = BlsVerifierService(verifier)
    try:
        assert service.verify_signature_sets(
            sets, VerifyOptions(batchable=True)
        )
        timings = list(service.recent_job_timings)
        assert timings, "no BlsWorkResult-parity records"
        rec = timings[-1]
        # the exact BlsWorkResult field set (multithread/types.ts:26-38)
        for field in (
            "worker_id",
            "batch_retries",
            "batch_sigs_success",
            "worker_start_ns",
            "worker_end_ns",
        ):
            assert field in rec, field
        assert rec["worker_end_ns"] >= rec["worker_start_ns"] > 0
        assert rec["sig_sets"] == len(sets)
        m = verifier.metrics
        assert m.latency_to_worker.count >= 1
        assert m.latency_from_worker.count >= 1
        assert m.jobs_worker_time.get("0") > 0
        assert m.total_job_groups_started.value >= 1
        assert m.total_sig_sets_started.value >= len(sets)
    finally:
        service.close()


def test_single_thread_family_observed(world):
    sks, pks, sets = world
    verifier = CpuBlsVerifier(pubkeys=pks)
    assert verifier.verify_signature_sets(sets)
    st = verifier.single_thread_metrics
    assert st.duration.count == 1
    assert st.time_per_sig_set.count == 1


def test_timings_visible_over_rest(world):
    """The ns records reach the lodestar introspection endpoint."""
    import json
    import urllib.request

    from lodestar_tpu.api.server import BeaconApiServer, DefaultHandlers

    sks, pks, sets = world
    verifier = CpuBlsVerifier(pubkeys=pks)
    service = BlsVerifierService(verifier)
    server = BeaconApiServer(
        DefaultHandlers(
            bls_metrics=verifier.metrics, bls_service=service
        ),
        port=0,
    )
    server.listen()
    try:
        assert service.verify_signature_sets(
            sets, VerifyOptions(batchable=True)
        )
        with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/eth/v1/lodestar/bls-metrics",
            timeout=30,
        ) as resp:
            data = json.loads(resp.read())["data"]
        assert data["recent_job_timings"], data
        assert data["worker_time_seconds"] > 0
        assert data["recent_job_timings"][-1]["worker_end_ns"] > 0
    finally:
        server.close()
        service.close()


def test_beacon_metrics_family():
    """Spec gauges, import counter/timer, reorg detection, and source-
    counted gossip verdicts (reference: metrics/metrics/beacon.ts)."""
    from lodestar_tpu.chain.chain import BeaconChain
    from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
    from lodestar_tpu.crypto import bls as B
    from lodestar_tpu.crypto import curves as C
    from lodestar_tpu.params import ForkName
    from lodestar_tpu.state_transition import create_genesis_state
    from lodestar_tpu.state_transition.accessors import (
        get_beacon_proposer_index,
    )
    from lodestar_tpu.state_transition.slot import process_slots
    from lodestar_tpu.utils.beacon_metrics import BeaconMetrics
    from lodestar_tpu.utils.metrics import Registry
    from lodestar_tpu.validator import ValidatorStore

    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG, fork_epochs={ForkName.altair: 0}
    )
    sks = [B.keygen(b"bm-%d" % i) for i in range(4)]
    pks = [C.g1_compress(B.sk_to_pk(sk)) for sk in sks]
    genesis = create_genesis_state(cfg, pks, genesis_time=2)
    chain = BeaconChain(cfg, genesis)
    reg = Registry()
    m = BeaconMetrics(reg)
    m.observe_chain(chain)
    store = ValidatorStore(cfg, dict(enumerate(sks)))
    # a REAL import drives block/head events + the import timer
    st = genesis.clone()
    process_slots(st, 1)
    proposer = int(get_beacon_proposer_index(st))
    block = chain.produce_block(1, store.sign_randao(proposer, 1))
    chain.process_block(
        {"message": block, "signature": store.sign_block(proposer, block)}
    )
    assert m.blocks_imported.value == 1
    assert m.head_slot.value == 1  # the HEAD's slot, not the block arg
    assert m.block_import_time.count == 1
    assert m.reorg_count.value == 0  # linear advance is not a reorg
    assert m.op_pool_attestations.value == 0
    # engine residency sampled from the regen caches on head update
    assert m.state_root_engine_bytes.value > 0

    # gossip verdicts count AT the handler
    from lodestar_tpu.bls.single_thread import CpuBlsVerifier
    from lodestar_tpu.network.gossip_handlers import GossipHandlers

    handlers = GossipHandlers(chain, CpuBlsVerifier(pubkeys=[]))
    m.observe_gossip(handlers)
    handlers._count("beacon_block", "accept")
    handlers._count("beacon_block", "reject")
    handlers._count("beacon_block", "accept")
    assert m.gossip_verdicts["accept"].get("beacon_block") == 2
    assert m.gossip_verdicts["reject"].get("beacon_block") == 1

    class _PM:
        peers = {"a": 1, "b": 2}

    m.sample_peers(_PM())
    assert m.peers_connected.value == 2
    text = reg.expose()
    assert "beacon_head_slot 1" in text
    assert "# TYPE lodestar_gossip_accept_total counter" in text
    assert 'lodestar_gossip_accept_total{topic="beacon_block"} 2.0' in text
    assert "libp2p_peers 2" in text


# ---------------------------------------------------------------------------
# ISSUE 8: hot-path tracing + conformant exposition
# ---------------------------------------------------------------------------


@pytest.fixture()
def tracing():
    """Enable the process tracer for one test, restore disabled+empty."""
    from lodestar_tpu import observability as OB

    tracer = OB.configure(enabled=True, capacity=OB.get_tracer().capacity)
    tracer.clear()
    try:
        yield OB
    finally:
        OB.configure(enabled=False)
        OB.get_tracer().clear()


def test_histogram_exposition_is_prometheus_conformant():
    """Golden format: `le` rendered float-style incl. +Inf, cumulative
    bucket counts, `_sum`/`_count` lines — the text any Prometheus
    client parses identically (satellite: exposition conformance)."""
    reg = Registry()
    h = reg.histogram("x_seconds", "An example timing", [0.005, 1, 2.5])
    h.observe(0.001)
    h.observe(2.0)
    h.observe(30.0)
    assert reg.expose() == (
        "# HELP x_seconds An example timing\n"
        "# TYPE x_seconds histogram\n"
        'x_seconds_bucket{le="0.005"} 1\n'
        'x_seconds_bucket{le="1.0"} 1\n'
        'x_seconds_bucket{le="2.5"} 2\n'
        'x_seconds_bucket{le="+Inf"} 3\n'
        "x_seconds_sum 32.001\n"
        "x_seconds_count 3\n"
    )


def test_labeled_histogram_exposition_merges_labels():
    reg = Registry()
    h = reg.labeled_histogram(
        "phase_seconds", "Per-phase timing", "phase", [1]
    )
    h.observe("stf", 0.5)
    h.observe("stf", 3.0)
    h.observe("state_root", 0.1)
    text = reg.expose()
    assert 'phase_seconds_bucket{phase="stf",le="1.0"} 1' in text
    assert 'phase_seconds_bucket{phase="stf",le="+Inf"} 2' in text
    assert 'phase_seconds_sum{phase="stf"} 3.5' in text
    assert 'phase_seconds_count{phase="state_root"} 1' in text
    # ONE metadata pair for the whole family
    assert text.count("# TYPE phase_seconds histogram") == 1
    assert h.sum("stf") == 3.5 and h.count("stf") == 2
    assert h.label_values() == ["state_root", "stf"]


def test_tracer_nesting_and_parenting(tracing):
    OB = tracing
    with OB.trace_span("outer", layer="test"):
        with OB.trace_span("mid"):
            with OB.trace_span("leaf"):
                pass
        with OB.trace_span("mid2"):
            pass
    recs = {r.name: r for r in OB.get_tracer().snapshot()}
    assert recs["leaf"].parent_id == recs["mid"].span_id
    assert recs["mid"].parent_id == recs["outer"].span_id
    assert recs["mid2"].parent_id == recs["outer"].span_id
    assert recs["outer"].parent_id is None
    assert recs["outer"].attrs["layer"] == "test"
    # durations contain the children
    assert recs["outer"].dur_us >= recs["mid"].dur_us


def test_tracer_parenting_across_asyncio_tasks(tracing):
    """contextvars propagate into tasks at creation: every task's spans
    parent to the creating span, and interleaved awaits in sibling
    tasks cannot corrupt each other's lineage."""
    import asyncio

    OB = tracing

    async def worker(i):
        with OB.trace_span(f"task-{i}"):
            await asyncio.sleep(0.001)
            with OB.trace_span(f"task-{i}-inner"):
                await asyncio.sleep(0.001)

    async def main():
        with OB.trace_span("root"):
            await asyncio.gather(*[worker(i) for i in range(4)])

    asyncio.run(main())
    recs = {r.name: r for r in OB.get_tracer().snapshot()}
    root = recs["root"]
    for i in range(4):
        assert recs[f"task-{i}"].parent_id == root.span_id
        assert recs[f"task-{i}-inner"].parent_id == recs[f"task-{i}"].span_id


def test_tracer_ring_is_bounded(tracing):
    OB = tracing
    OB.configure(capacity=16)
    try:
        for i in range(200):
            with OB.trace_span("spam", i=i):
                pass
        recs = OB.get_tracer().snapshot()
        assert len(recs) == 16
        # the ring keeps the MOST RECENT spans
        assert [r.attrs["i"] for r in recs] == list(range(184, 200))
    finally:
        OB.configure(capacity=65536)


def test_tracer_thread_safety(tracing):
    import threading

    OB = tracing
    OB.configure(capacity=100_000)
    errors = []

    def hammer(tid):
        try:
            for i in range(300):
                with OB.trace_span(f"thread-{tid}"):
                    with OB.trace_span(f"thread-{tid}-inner"):
                        pass
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [
        threading.Thread(target=hammer, args=(t,)) for t in range(8)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    recs = OB.get_tracer().snapshot()
    assert len(recs) == 8 * 300 * 2
    # per-thread lineage stays intact: every inner span's parent is a
    # span of the SAME thread (contextvars are per-thread roots)
    by_id = {r.span_id: r for r in recs}
    for r in recs:
        if r.name.endswith("-inner"):
            assert by_id[r.parent_id].name == r.name[: -len("-inner")]
    OB.configure(capacity=65536)


def test_disabled_tracer_overhead_bound():
    """The asserted cost contract: with tracing DISABLED, a trace_span
    on the verify hot path is bounded below 25 us/call (it measures
    ~0.5 us — one allocation + one flag check; the bound is slack for
    CI noise)."""
    import time as _time

    from lodestar_tpu import observability as OB

    assert not OB.enabled()
    n = 20_000
    t0 = _time.perf_counter()
    for i in range(n):
        with OB.trace_span("hot", batch_size=512):
            pass
    per_call = (_time.perf_counter() - t0) / n
    assert per_call < 25e-6, f"disabled trace_span costs {per_call*1e6:.2f}us"
    # near-zero check: nothing recorded, no contextvar residue
    assert OB.current_id() is None
    assert len(OB.get_tracer()) == 0


def test_trace_span_decorator_respects_runtime_toggle(tracing):
    OB = tracing
    OB.configure(enabled=False)

    @OB.trace_span("decorated.fn", kind="test")
    def fn(x):
        return x * 2

    assert fn(2) == 4
    assert len(OB.get_tracer()) == 0  # disabled at call time: no record
    OB.configure(enabled=True)
    assert fn(3) == 6
    recs = OB.get_tracer().snapshot()
    assert recs[-1].name == "decorated.fn"
    assert recs[-1].attrs["kind"] == "test"


def test_chrome_trace_export_loadable_and_summary(tracing):
    import json

    OB = tracing
    with OB.trace_span("parent"):
        with OB.trace_span("child"):
            pass
    doc = json.loads(json.dumps(OB.dump_chrome_trace()))
    events = doc["traceEvents"]
    assert {e["name"] for e in events} == {"parent", "child"}
    child = next(e for e in events if e["name"] == "child")
    parent = next(e for e in events if e["name"] == "parent")
    assert child["ph"] == "X" and parent["ph"] == "X"
    assert child["args"]["parent_id"] == parent["args"]["span_id"]
    # timestamp containment (what the flamegraph renders as nesting)
    assert parent["ts"] <= child["ts"]
    assert child["ts"] + child["dur"] <= parent["ts"] + parent["dur"] + 1
    summary = OB.trace_summary()
    names = {row["name"]: row for row in summary["spans"]}
    assert names["parent"]["count"] == 1
    # self-time excludes the child's duration
    assert names["parent"]["self_s"] <= names["parent"]["total_s"]


def test_observability_cli_summary_and_dump(tracing, tmp_path):
    import json
    import subprocess
    import sys

    OB = tracing
    with OB.trace_span("cli.span"):
        pass
    path = tmp_path / "trace.json"
    OB.write_chrome_trace(str(path))
    out = subprocess.run(
        [
            sys.executable, "-m", "lodestar_tpu.observability",
            "summary", str(path), "--json",
        ],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr
    summary = json.loads(out.stdout)
    assert any(r["name"] == "cli.span" for r in summary["spans"])
    dumped = tmp_path / "out.json"
    out = subprocess.run(
        [
            sys.executable, "-m", "lodestar_tpu.observability",
            "dump", str(path), "--out", str(dumped),
        ],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr
    assert json.loads(dumped.read_text())["traceEvents"]


def test_observability_cli_url_source(tracing):
    """ISSUE 12 satellite: the CLI's --url leg (summary AND dump
    against a live metrics server's GET /trace) was untested."""
    import json

    from lodestar_tpu.observability.__main__ import main as obs_main
    from lodestar_tpu.utils.metrics_server import HttpMetricsServer

    OB = tracing
    with OB.trace_span("url.span"):
        pass
    srv = HttpMetricsServer(Registry(), port=0)
    srv.start()
    try:
        url = f"http://127.0.0.1:{srv.port}"
        import contextlib
        import io

        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            # both the bare base URL and an explicit /trace resolve
            assert obs_main(["summary", "--url", url, "--json"]) == 0
        summary = json.loads(buf.getvalue())
        assert any(r["name"] == "url.span" for r in summary["spans"])
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            assert obs_main(["dump", "--url", url + "/trace"]) == 0
        doc = json.loads(buf.getvalue())
        assert any(
            e["name"] == "url.span" for e in doc["traceEvents"]
        )
    finally:
        srv.close()


def test_observability_cli_load_error_exit_code(tmp_path):
    from lodestar_tpu.observability.__main__ import main as obs_main

    assert obs_main(["summary", str(tmp_path / "missing.json")]) == 2
    bad = tmp_path / "not_json.json"
    bad.write_text("this is not a trace")
    assert obs_main(["dump", str(bad)]) == 2


def test_tracer_snapshot_under_concurrent_writers(tracing):
    """ISSUE 12 satellite: snapshot() while writer threads append must
    return a consistent list (bounded, fully-formed records) and never
    raise — the flight recorder drains the ring mid-anomaly, exactly
    when the hot paths are busiest."""
    import threading

    OB = tracing
    OB.configure(capacity=512)
    try:
        stop = threading.Event()
        errors = []

        def writer(tid):
            try:
                i = 0
                while not stop.is_set():
                    with OB.trace_span(f"w{tid}", i=i):
                        pass
                    i += 1
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [
            threading.Thread(target=writer, args=(t,)) for t in range(4)
        ]
        for t in threads:
            t.start()
        try:
            for _ in range(300):
                snap = OB.get_tracer().snapshot()
                assert len(snap) <= 512
                for rec in snap:
                    # every record is FINISHED: full field set, sane tid
                    assert rec.span_id > 0 and rec.dur_us >= 0
                    assert rec.name.startswith("w")
                # the sinks built on snapshot() hold up too
                OB.dump_chrome_trace(snap)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=10)
        assert not errors
    finally:
        OB.configure(capacity=65536)


def test_metrics_server_trace_endpoint_and_global_merge(tracing, tmp_path):
    """Acceptance slice: /metrics exposes the compile/cache and
    gossip-queue series (process-global registry merged into the node
    registry's exposition) and GET /trace serves a loadable Chrome
    trace."""
    import json
    import urllib.request

    import jax
    import jax.numpy as jnp

    from lodestar_tpu.kernels import export_cache as EC
    from lodestar_tpu.network.gossip_queues import (
        GOSSIP_QUEUE_OPTS, GossipType, create_gossip_queues,
    )
    from lodestar_tpu.utils.metrics_server import HttpMetricsServer

    OB = tracing
    # one fresh export (compile) + one cache hit, against a tmp dir
    specs = [jax.ShapeDtypeStruct((4,), jnp.int32)]
    EC.load_or_export(
        "obs_endpoint_test", lambda x: x * 2, specs, "cpu", str(tmp_path)
    )
    EC._LOADED.clear()
    EC.load_or_export(
        "obs_endpoint_test", lambda x: x * 2, specs, "cpu", str(tmp_path)
    )
    # queue traffic -> latency/depth series (global registry default)
    queues = create_gossip_queues()
    q = queues[GossipType.beacon_attestation]
    q.add("a")
    q.add("b")
    assert q.next() == "b"  # LIFO

    reg = Registry()
    reg.counter("node_local_total", "node-registry metric").inc()
    srv = HttpMetricsServer(reg, port=0)
    srv.start()
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics", timeout=30
        ).read().decode()
        # node-local AND process-global series in one exposition
        assert "node_local_total 1.0" in body
        assert (
            'lodestar_tpu_export_cache_misses_total{entry="obs_endpoint_test"}'
            in body
        )
        assert (
            'lodestar_tpu_export_cache_hits_total{entry="obs_endpoint_test"}'
            in body
        )
        assert 'lodestar_tpu_export_trace_seconds_count{entry="obs_endpoint_test"} 1' in body
        assert (
            'lodestar_gossip_queue_latency_seconds_count{topic="beacon_attestation"} 1'
            in body
        )
        assert 'lodestar_gossip_queue_length{topic="beacon_attestation"} 1.0' in body
        trace = json.loads(
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/trace", timeout=30
            ).read()
        )
        names = {e["name"] for e in trace["traceEvents"]}
        assert "kernels.export_trace" in names
        assert "kernels.export_load" in names
    finally:
        srv.close()


def test_gossip_queue_drop_accounting():
    from lodestar_tpu.network.gossip_queues import (
        DropByCount, GossipQueue, GossipQueueMetrics, GossipQueueOpts,
        QueueType,
    )

    reg = Registry()
    metrics = GossipQueueMetrics(reg)
    q = GossipQueue(
        GossipQueueOpts(QueueType.FIFO, 4, DropByCount(1)),
        topic="t", metrics=metrics,
    )
    for i in range(6):
        q.add(i)
    # FIFO drops newest on overflow; timestamps stay aligned with items
    assert len(q) == 4
    assert q.next() == 0
    assert metrics.dropped.get("t") == 2.0
    assert metrics.latency.count("t") == 1
    assert metrics.depth.get("t") == 3.0


def test_gossip_verify_import_nested_span_tree(tracing):
    """The acceptance trace shape on the REAL pipeline: a gossip block
    handled end-to-end produces gossip.handle -> chain.import ->
    {validation, signature_verify, stf, state_root, fork_choice} spans,
    with the device-side bls.job span linked across threads to the
    signature_verify span, and the phase histogram filled for every
    phase."""
    from lodestar_tpu.bls.service import BlsVerifierService
    from lodestar_tpu.chain.chain import BeaconChain
    from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
    from lodestar_tpu.network.gossip import encode_message, topic_string
    from lodestar_tpu.network.gossip import GossipTopicName
    from lodestar_tpu.network.gossip_handlers import GossipHandlers
    from lodestar_tpu.params import ForkName
    from lodestar_tpu.state_transition import create_genesis_state
    from lodestar_tpu.state_transition.accessors import (
        get_beacon_proposer_index,
    )
    from lodestar_tpu.state_transition.slot import process_slots
    from lodestar_tpu.utils.beacon_metrics import BeaconMetrics
    from lodestar_tpu.validator import ValidatorStore

    OB = tracing
    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG, fork_epochs={ForkName.altair: 0}
    )
    sks = [B.keygen(b"obs-trace-%d" % i) for i in range(4)]
    pk_points = [B.sk_to_pk(sk) for sk in sks]
    pks = [C.g1_compress(p) for p in pk_points]
    genesis = create_genesis_state(cfg, pks, genesis_time=2)
    service = BlsVerifierService(CpuBlsVerifier(pubkeys=pk_points))
    chain = BeaconChain(cfg, genesis, bls_verifier=service)
    reg = Registry()
    bm = BeaconMetrics(reg)
    bm.observe_chain(chain)
    handlers = GossipHandlers(chain, service.verifier)
    store = ValidatorStore(cfg, dict(enumerate(sks)))
    try:
        st = genesis.clone()
        process_slots(st, 1)
        proposer = int(get_beacon_proposer_index(st))
        block = chain.produce_block(1, store.sign_randao(proposer, 1))
        signed = {
            "message": block,
            "signature": store.sign_block(proposer, block),
        }
        digest = cfg.fork_digest(0)
        action = handlers.handle(
            topic_string(digest, GossipTopicName.beacon_block),
            encode_message(cfg.get_fork_types(1)[1].serialize(signed)),
        )
        assert action is None  # ACCEPT
    finally:
        service.close()

    recs = OB.get_tracer().snapshot()
    by_name = {}
    for r in recs:
        by_name.setdefault(r.name, []).append(r)
    gossip = by_name["gossip.handle"][0]
    assert gossip.attrs["topic"] == "beacon_block"
    assert gossip.attrs["verdict"] == "accept"
    imp = by_name["chain.import"][0]
    assert imp.parent_id == gossip.span_id
    for phase in (
        "validation", "signature_verify", "stf", "state_root",
        "fork_choice",
    ):
        span = by_name["import." + phase][0]
        assert span.parent_id == imp.span_id, phase
    # cross-thread link: the resolver thread's bls.job span parents to
    # the signature_verify span that queued the work
    sig = by_name["import.signature_verify"][0]
    job = by_name["bls.job"][0]
    assert job.parent_id == sig.span_id
    assert job.tid != sig.tid  # genuinely another thread
    # cpu verifier's own span nests under the job via explicit parent?
    # (no — it runs in the resolver thread's context) — it must at
    # least exist with the batch size attribute
    bls_spans = by_name["bls.verify"]
    assert any(s.attrs.get("batch_size", 0) >= 1 for s in bls_spans)

    # every phase landed in the labeled histogram, and the whole import
    # equals roughly the sum of its phases (no unaccounted 2x)
    phases = bm.block_import_phase
    for phase in (
        "validation", "signature_verify", "stf", "state_root",
        "fork_choice",
    ):
        assert phases.count(phase) == 1, phase
    assert bm.block_import_time.count == 1
    phase_sum = sum(phases.sum(p) for p in phases.label_values())
    assert phase_sum <= bm.block_import_time.sum * 1.05
    text = reg.expose()
    assert 'lodestar_block_import_phase_seconds_count{phase="stf"} 1' in text

    # the Chrome document for this run is loadable and keeps the tree
    import json as _json

    doc = _json.loads(_json.dumps(OB.dump_chrome_trace()))
    ids = {
        e["args"]["span_id"]: e for e in doc["traceEvents"]
    }
    child = ids[imp.span_id]
    assert ids[child["args"]["parent_id"]]["name"] == "gossip.handle"


def test_bls_batch_size_and_verify_seconds_series(world):
    sks, pks, sets = world
    registry = Registry()
    verifier = CpuBlsVerifier(pubkeys=pks, metrics=BlsPoolMetrics(registry))
    assert verifier.verify_signature_sets(sets)
    m = verifier.metrics
    assert m.batch_size.count == 1
    assert m.verify_seconds.count("total") == 1
    text = registry.expose()
    assert 'lodestar_bls_batch_size_bucket{le="4.0"} 1' in text
    assert 'lodestar_bls_verify_seconds_count{phase="total"} 1' in text


def test_ops_jit_names_first_dispatch_compile(tracing):
    """ISSUE 11 satellite: the ops-boundary `ops_jit` wrapper brackets
    the FIRST dispatch of each input signature in an `ops.jit_compile`
    span + `lodestar_tpu_ops_jit_compile_seconds{fn}` histogram, so
    XLA:CPU compile time is named in trace_summary() like export traces
    are — and warm dispatches add neither."""
    import jax.numpy as jnp

    from lodestar_tpu.observability import trace_summary
    from lodestar_tpu.ops.dispatch import ops_jit
    from lodestar_tpu.utils.metrics import global_registry

    hist = global_registry().get("lodestar_tpu_ops_jit_compile_seconds")
    before = hist.count("_obs_probe") if hist is not None else 0

    @ops_jit(name="_obs_probe")
    def probe(a):
        return a * 2 + 1

    x = jnp.arange(8, dtype=jnp.int32)
    assert int(probe(x).sum()) == sum(2 * i + 1 for i in range(8))
    probe(x)  # warm: same signature, no new compile record
    probe(jnp.arange(16, dtype=jnp.int32))  # new signature: new record

    hist = global_registry().get("lodestar_tpu_ops_jit_compile_seconds")
    assert hist is not None and hist.count("_obs_probe") == before + 2
    spans = [
        r
        for r in tracing.get_tracer().snapshot()
        if r.name == "ops.jit_compile" and r.attrs.get("fn") == "_obs_probe"
    ]
    assert len(spans) == 2
    assert {s.attrs["signature"] for s in spans} == {1, 2}
    summary = trace_summary()
    assert any(s["name"] == "ops.jit_compile" for s in summary["spans"])
    assert summary["kernels"]["ops_jit_compiles"] >= 2
    assert summary["kernels"]["ops_jit_compile_seconds"] > 0


def test_ops_jit_disabled_tracer_and_nested_trace_are_silent():
    """With tracing off the wrapper still verifies correctly and emits
    no spans; called under an OUTER trace (tracer args) it bypasses the
    instrumentation so inner inlining is never misattributed."""
    import jax
    import jax.numpy as jnp

    from lodestar_tpu import observability as OB
    from lodestar_tpu.ops.dispatch import ops_jit
    from lodestar_tpu.utils.metrics import global_registry

    @ops_jit(name="_obs_probe_nested")
    def inner(a):
        return a + 1

    @jax.jit
    def outer(a):
        return inner(a) * 3

    OB.get_tracer().clear()
    x = jnp.arange(4, dtype=jnp.int32)
    assert int(outer(x).sum()) == sum((i + 1) * 3 for i in range(4))
    hist = global_registry().get("lodestar_tpu_ops_jit_compile_seconds")
    # the nested call saw tracers: no compile record under this label
    assert hist is None or hist.count("_obs_probe_nested") == 0
    assert not [
        r for r in OB.get_tracer().snapshot() if r.name == "ops.jit_compile"
    ]
