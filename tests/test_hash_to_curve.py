"""Spec hash-to-curve (BLS12381G2_XMD:SHA-256_SSWU_RO_, RFC 9380).

Two tiers:
  1. Algebraic invariants that any wrong constant breaks (always run).
  2. Byte-level known-answer vectors, gated on fixture files in
     tests/fixtures/hash_to_curve/ (the ethereum/bls12-381-tests
     `hash_to_G2` JSON format, reference:
     packages/beacon-node/test/spec/specTestVersioning.ts:26-31).  The
     sealed build environment has no network access to fetch them; drop
     the files in and this test gates byte-exactness permanently.
"""

import glob
import json
import os

import pytest

from lodestar_tpu.crypto import bls as B
from lodestar_tpu.crypto import curves as C
from lodestar_tpu.crypto import fields as F
from lodestar_tpu.crypto import hash_to_curve as H

pytestmark = pytest.mark.smoke

FIXDIR = os.path.join(os.path.dirname(__file__), "fixtures", "hash_to_curve")


def test_sswu_output_on_iso_curve():
    for i in range(8):
        (u,) = H.hash_to_field_fp2(b"t%d" % i, 1, b"TESTDST")
        x, y = H.map_to_curve_sswu_g2(u)
        lhs = F.fp2_sqr(y)
        rhs = F.fp2_add(F.fp2_mul(F.fp2_add(F.fp2_sqr(x), H._A2), x), H._B2)
        assert F.fp2_eq(lhs, rhs)
        # sign condition
        assert H._sgn0_fp2(u) == H._sgn0_fp2(y)


def test_iso_map_lands_on_e2_and_is_homomorphic_enough():
    pts = []
    for i in range(4):
        (u,) = H.hash_to_field_fp2(b"i%d" % i, 1, b"TESTDST")
        p = H.iso3_map(H.map_to_curve_sswu_g2(u))
        assert p is not None and C.is_on_curve(C.FP2_OPS, p)
        pts.append(p)


def test_hash_to_g2_in_subgroup_and_deterministic():
    p1 = H.hash_to_g2(b"msg")
    p2 = H.hash_to_g2(b"msg")
    p3 = H.hash_to_g2(b"msg2")
    assert p1 == p2 and p1 != p3
    assert C.g2_subgroup_check(p1) and C.g2_subgroup_check(p3)


def test_dst_separation():
    assert H.hash_to_g2(b"m", b"DST-A") != H.hash_to_g2(b"m", b"DST-B")


def test_sign_verify_roundtrip_with_sswu():
    sk = B.keygen(b"h2c")
    pk = B.sk_to_pk(sk)
    sig = B.sign(sk, b"the message")
    assert B.verify(pk, b"the message", sig)
    assert not B.verify(pk, b"another message", sig)


def test_expand_message_xmd_shapes():
    out = H.expand_message_xmd(b"abc", b"DST", 96)
    assert len(out) == 96
    # deterministic + prefix-free in len
    assert out == H.expand_message_xmd(b"abc", b"DST", 96)
    assert out[:32] != H.expand_message_xmd(b"abc", b"DST", 32)[:32] or True


def test_sgn0():
    assert H._sgn0_fp2((0, 0)) == 0
    assert H._sgn0_fp2((1, 0)) == 1
    assert H._sgn0_fp2((0, 1)) == 1
    assert H._sgn0_fp2((2, 1)) == 0  # x0 nonzero even: x1 ignored


@pytest.mark.parametrize(
    "path",
    sorted(glob.glob(os.path.join(FIXDIR, "*.json"))) or [None],
)
def test_known_answer_vectors(path):
    """ethereum/bls12-381-tests hash_to_G2 vectors (skip if absent)."""
    if path is None:
        pytest.skip("no hash_to_curve fixtures present (sealed environment)")
    with open(path) as fh:
        case = json.load(fh)
    msg = case["input"]["msg"].encode()
    dst = case["input"].get("dst", H.DST_G2.decode()).encode()
    want_x = [int(v, 16) for v in case["output"]["x"].split(",")]
    want_y = [int(v, 16) for v in case["output"]["y"].split(",")]
    got = H.hash_to_g2(msg, dst)
    assert got == ((want_x[0], want_x[1]), (want_y[0], want_y[1]))
