"""Gossip validation layer: every topic over a two-node bus.

Reference behaviors: packages/beacon-node/src/chain/validation/
{attestation,aggregateAndProof,syncCommittee,
syncCommitteeContributionAndProof,attesterSlashing,proposerSlashing,
voluntaryExit}.ts and network/processor/gossipHandlers.ts.

Node A signs objects with the ValidatorStore; node B receives the raw
bytes over the InMemoryGossipBus, deserializes, validates (signatures
through the injected verifier — aggregate objects as THREE sets in ONE
job), and applies pool/fork-choice side effects.  Bad signatures REJECT;
duplicates IGNORE.
"""

import dataclasses

import pytest

from lodestar_tpu import params
from lodestar_tpu import types as T
from lodestar_tpu.bls.single_thread import CpuBlsVerifier
from lodestar_tpu.chain.chain import BeaconChain
from lodestar_tpu.chain.validation import (
    GossipAction,
    GossipValidationError,
    GossipValidators,
    _hash_mod,
)
from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
from lodestar_tpu.crypto import bls as B
from lodestar_tpu.crypto import curves as C
from lodestar_tpu.network.gossip import (
    GossipTopicName,
    InMemoryGossipBus,
    encode_message,
    topic_string,
)
from lodestar_tpu.network.gossip_handlers import GossipHandlers
from lodestar_tpu.params import ForkName
from lodestar_tpu.state_transition import create_genesis_state
from lodestar_tpu.state_transition.accessors import get_beacon_committee
from lodestar_tpu.validator import ValidatorStore

P = params.ACTIVE_PRESET
N_KEYS = 64
SUBCOM = P.SYNC_COMMITTEE_SIZE // params.SYNC_COMMITTEE_SUBNET_COUNT

pytestmark = pytest.mark.smoke


class CountingVerifier(CpuBlsVerifier):
    """Records per-call set counts (asserts the one-job contract)."""

    def __init__(self, pks):
        super().__init__(pubkeys=pks)
        self.calls = []

    def verify_signature_sets(self, sets, opts=None):
        self.calls.append(len(sets))
        return super().verify_signature_sets(sets, opts)


@pytest.fixture(scope="module")
def world():
    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG, fork_epochs={ForkName.altair: 0}
    )
    cfg = dataclasses.replace(cfg, SHARD_COMMITTEE_PERIOD=0)
    sks = [B.keygen(b"val-%d" % i) for i in range(N_KEYS)]
    pk_points = [B.sk_to_pk(sk) for sk in sks]
    pks = [C.g1_compress(p) for p in pk_points]
    genesis = create_genesis_state(cfg, pks, genesis_time=2)
    chain_a = BeaconChain(cfg, genesis)
    chain_b = BeaconChain(cfg, genesis)
    verifier = CountingVerifier(pk_points)
    handlers = GossipHandlers(chain_b, verifier)
    bus = InMemoryGossipBus()
    digest = cfg.fork_digest(0)
    handlers.subscribe_all(
        bus, "b", digest, attnets=(0,), syncnets=(0, 1, 2, 3)
    )
    store = ValidatorStore(cfg, dict(enumerate(sks)))
    return {
        "cfg": cfg,
        "sks": sks,
        "pks": pks,
        "genesis": genesis,
        "chain_a": chain_a,
        "chain_b": chain_b,
        "verifier": verifier,
        "handlers": handlers,
        "bus": bus,
        "digest": digest,
        "store": store,
    }


def fresh_store(w) -> ValidatorStore:
    """Stores carry slashing protection; tests that legitimately re-sign
    the same (validator, target) need an independent store."""
    return ValidatorStore(w["cfg"], dict(enumerate(w["sks"])))


def _publish(w, name: GossipTopicName, sszt, obj, subnet=None) -> int:
    topic = topic_string(w["digest"], name, subnet=subnet)
    return w["bus"].publish("a", topic, encode_message(sszt.serialize(obj)))


def _make_attestation(w, slot=0, committee_index=0, member_pos=0):
    data = w["chain_a"].produce_attestation_data(committee_index, slot)
    committee = get_beacon_committee(w["genesis"], slot, committee_index)
    v = int(committee[member_pos])
    bits = [False] * len(committee)
    bits[member_pos] = True
    sig = fresh_store(w).sign_attestation(v, data)
    return {
        "aggregation_bits": bits,
        "data": data,
        "signature": sig,
    }, v, committee


def test_attestation_accept_reject_dup(world):
    w = world
    att, v, _c = _make_attestation(w, member_pos=0)
    assert _publish(w, GossipTopicName.beacon_attestation, T.Attestation, att, 0) == 1
    res = w["handlers"].results["beacon_attestation_0"]
    assert res.get("accept") == 1
    # side effects landed on node B
    assert w["chain_b"].attestation_pool._by_slot  # landed in the pool
    assert v in w["chain_b"].fork_choice._latest
    # replaying the same attester is an IGNORE (seen cache), not a reject
    att2 = dict(att)
    v2 = GossipValidators(w["chain_b"], w["verifier"])
    v2.seen_attesters = w["handlers"].validators.seen_attesters
    with pytest.raises(GossipValidationError) as ei:
        w["handlers"].validators.validate_attestation(att2)
    assert ei.value.action == GossipAction.IGNORE
    # a corrupted signature REJECTs
    att3, _, c = _make_attestation(w, slot=1, committee_index=0, member_pos=0)
    att3["signature"] = att3["signature"][:-1] + bytes(
        [att3["signature"][-1] ^ 1]
    )
    with pytest.raises(GossipValidationError) as ei:
        w["handlers"].validators.validate_attestation(att3)
    assert ei.value.action == GossipAction.REJECT


def test_attestation_requires_single_bit(world):
    w = world
    slot, committee = _find_committee_slot(w)
    att, _v, committee = _make_attestation(w, slot=slot)
    if len(committee) < 2:
        pytest.skip("committee too small at this slot")
    att["aggregation_bits"] = [True] * len(committee)
    with pytest.raises(GossipValidationError) as ei:
        w["handlers"].validators.validate_attestation(att)
    assert ei.value.action == GossipAction.REJECT


def _find_committee_slot(w, min_size=2):
    # only the head slot and head+1 are inside the gossip clock window
    for slot in (0, 1):
        committee = get_beacon_committee(w["genesis"], slot, 0)
        if len(committee) >= min_size:
            return slot, committee
    pytest.skip("no committee of size >= 2 in the clock window")


def test_aggregate_and_proof_three_sets_one_job(world):
    w = world
    slot, committee = _find_committee_slot(w)
    data = w["chain_a"].produce_attestation_data(0, slot)
    members = [int(v) for v in committee]
    st = fresh_store(w)
    sigs = [st.sign_attestation(v, data) for v in members]
    agg_sig = C.g2_compress(
        B.aggregate_signatures([C.g2_decompress(s) for s in sigs])
    )
    aggregator = members[0]
    proof = w["store"].sign_selection_proof(aggregator, slot)
    # sanity: small committees make everyone an aggregator (modulo 1)
    assert _hash_mod(proof, len(committee) // params.TARGET_AGGREGATORS_PER_COMMITTEE)
    agg_and_proof = {
        "aggregator_index": aggregator,
        "aggregate": {
            "aggregation_bits": [True] * len(committee),
            "data": data,
            "signature": agg_sig,
        },
        "selection_proof": proof,
    }
    signed = {
        "message": agg_and_proof,
        "signature": w["store"].sign_aggregate_and_proof(
            aggregator, agg_and_proof
        ),
    }
    before = len(w["verifier"].calls)
    assert (
        _publish(
            w,
            GossipTopicName.beacon_aggregate_and_proof,
            T.SignedAggregateAndProof,
            signed,
        )
        == 1
    )
    assert w["handlers"].results["beacon_aggregate_and_proof"]["accept"] == 1
    # THE contract: all three statements went as ONE verifier job
    assert w["verifier"].calls[before:] == [3]
    # every attester's vote landed in fork choice
    for v in members:
        assert v in w["chain_b"].fork_choice._latest
    # duplicate aggregator -> IGNORE
    with pytest.raises(GossipValidationError) as ei:
        w["handlers"].validators.validate_aggregate_and_proof(signed)
    assert ei.value.action == GossipAction.IGNORE


def test_aggregate_bad_signature_rejected(world):
    w = world
    slot, committee = _find_committee_slot(w)
    data = w["chain_a"].produce_attestation_data(0, slot)
    members = [int(v) for v in committee]
    aggregator = members[1] if len(members) > 1 else members[0]
    proof = w["store"].sign_selection_proof(aggregator, slot)
    agg_and_proof = {
        "aggregator_index": aggregator,
        "aggregate": {
            "aggregation_bits": [True] * len(committee),
            "data": data,
            # aggregate signed by the WRONG key set
            "signature": fresh_store(w).sign_attestation(members[0], data),
        },
        "selection_proof": proof,
    }
    signed = {
        "message": agg_and_proof,
        "signature": w["store"].sign_aggregate_and_proof(
            aggregator, agg_and_proof
        ),
    }
    with pytest.raises(GossipValidationError) as ei:
        w["handlers"].validators.validate_aggregate_and_proof(signed)
    assert ei.value.action == GossipAction.REJECT


def test_sync_committee_message_flow(world):
    w = world
    head_root = bytes.fromhex(w["chain_b"].head_root_hex)
    # find a validator with a position in subnet 0
    head = w["chain_b"].head_state
    sub0_pk = head.current_sync_committee["pubkeys"][0]
    vindex = int(head.pubkey_index(sub0_pk))
    msg = w["store"].sign_sync_committee_message(vindex, 0, head_root)
    assert (
        _publish(
            w, GossipTopicName.sync_committee, T.SyncCommitteeMessage, msg, 0
        )
        == 1
    )
    assert w["handlers"].results["sync_committee_0"]["accept"] == 1
    # duplicate -> IGNORE
    with pytest.raises(GossipValidationError) as ei:
        w["handlers"].validators.validate_sync_committee_message(msg, 0)
    assert ei.value.action == GossipAction.IGNORE
    # wrong subnet -> REJECT (validator position not in that subnet);
    # with few keys tiled into the committee a validator may legitimately
    # cover every subnet — only assert when an uncovered subnet exists
    positions = w["handlers"].validators._sync_committee_positions(vindex)
    uncovered = [
        s
        for s in range(params.SYNC_COMMITTEE_SUBNET_COUNT)
        if all(p // SUBCOM != s for p in positions)
    ]
    if uncovered:
        with pytest.raises(GossipValidationError) as ei:
            w["handlers"].validators.validate_sync_committee_message(
                msg, uncovered[0]
            )
        assert ei.value.action == GossipAction.REJECT


def _find_sync_aggregator(w):
    """(validator, subnet, proof) passing the sync selection modulo."""
    for vindex in range(N_KEYS):
        for subnet in range(params.SYNC_COMMITTEE_SUBNET_COUNT):
            proof = w["store"].sign_sync_selection_proof(vindex, 0, subnet)
            if _hash_mod(
                proof,
                SUBCOM // params.TARGET_AGGREGATORS_PER_SYNC_SUBCOMMITTEE,
            ):
                return vindex, subnet, proof
    pytest.skip("no sync aggregator found (deterministic; unexpected)")


def test_contribution_and_proof_flow(world):
    w = world
    head = w["chain_b"].head_state
    head_root = bytes.fromhex(w["chain_b"].head_root_hex)
    aggregator, subnet, proof = _find_sync_aggregator(w)
    # participants: first two positions of the subnet
    bits = [False] * SUBCOM
    part_validators = []
    sigs = []
    for pos in (0, 1):
        bits[pos] = True
        pk = head.current_sync_committee["pubkeys"][subnet * SUBCOM + pos]
        v = int(head.pubkey_index(pk))
        part_validators.append(v)
        m = fresh_store(w).sign_sync_committee_message(v, 0, head_root)
        sigs.append(C.g2_decompress(m["signature"]))
    contribution = {
        "slot": 0,
        "beacon_block_root": head_root,
        "subcommittee_index": subnet,
        "aggregation_bits": bits,
        "signature": C.g2_compress(B.aggregate_signatures(sigs)),
    }
    cap = {
        "aggregator_index": aggregator,
        "contribution": contribution,
        "selection_proof": proof,
    }
    signed = {
        "message": cap,
        "signature": w["store"].sign_contribution_and_proof(aggregator, cap),
    }
    before = len(w["verifier"].calls)
    assert (
        _publish(
            w,
            GossipTopicName.sync_committee_contribution_and_proof,
            T.SignedContributionAndProof,
            signed,
        )
        == 1
    )
    assert (
        w["handlers"].results["sync_committee_contribution_and_proof"][
            "accept"
        ]
        == 1
    )
    assert w["verifier"].calls[before:] == [3]  # one job, three statements
    # duplicate -> IGNORE
    with pytest.raises(GossipValidationError) as ei:
        w["handlers"].validators.validate_contribution_and_proof(signed)
    assert ei.value.action == GossipAction.IGNORE


def test_attester_slashing_flow(world):
    w = world
    slot, committee = _find_committee_slot(w, min_size=1)
    equivocator = int(committee[0])
    data1 = w["chain_a"].produce_attestation_data(0, slot)
    data2 = dict(data1, beacon_block_root=b"\x13" * 32)
    store = fresh_store(w)

    def indexed(data):
        return {
            "attesting_indices": [equivocator],
            "data": data,
            "signature": fresh_store(w).sign_attestation(equivocator, data),
        }

    slashing = {"attestation_1": indexed(data1), "attestation_2": indexed(data2)}
    assert (
        _publish(
            w, GossipTopicName.attester_slashing, T.AttesterSlashing, slashing
        )
        == 1
    )
    assert w["handlers"].results["attester_slashing"]["accept"] == 1
    # side effects: pool + fork-choice equivocator zeroing
    assert w["chain_b"].op_pool._attester_slashings
    assert equivocator in w["chain_b"].fork_choice._equivocating
    # replay -> IGNORE (already slashed)
    with pytest.raises(GossipValidationError) as ei:
        w["handlers"].validators.validate_attester_slashing_gossip(slashing)
    assert ei.value.action == GossipAction.IGNORE


def test_proposer_slashing_flow(world):
    w = world
    proposer = 3
    root1 = w["chain_a"].get_head_root()

    def signed_header(body_root):
        header = {
            "slot": 0,
            "proposer_index": proposer,
            "parent_root": root1,
            "state_root": b"\x00" * 32,
            "body_root": body_root,
        }
        root = w["cfg"].compute_signing_root(
            T.BeaconBlockHeader.hash_tree_root(header),
            w["cfg"].get_domain(0, params.DOMAIN_BEACON_PROPOSER, 0),
        )
        return {
            "message": header,
            "signature": C.g2_compress(B.sign(w["sks"][proposer], root)),
        }

    slashing = {
        "signed_header_1": signed_header(b"\x01" * 32),
        "signed_header_2": signed_header(b"\x02" * 32),
    }
    assert (
        _publish(
            w, GossipTopicName.proposer_slashing, T.ProposerSlashing, slashing
        )
        == 1
    )
    assert w["handlers"].results["proposer_slashing"]["accept"] == 1
    assert proposer in w["chain_b"].op_pool._proposer_slashings
    # duplicate -> IGNORE
    with pytest.raises(GossipValidationError) as ei:
        w["handlers"].validators.validate_proposer_slashing_gossip(slashing)
    assert ei.value.action == GossipAction.IGNORE


def test_voluntary_exit_flow(world):
    w = world
    signed_exit = w["store"].sign_voluntary_exit(7, 0)
    assert (
        _publish(
            w, GossipTopicName.voluntary_exit, T.SignedVoluntaryExit, signed_exit
        )
        == 1
    )
    assert w["handlers"].results["voluntary_exit"]["accept"] == 1
    assert 7 in w["chain_b"].op_pool._voluntary_exits
    with pytest.raises(GossipValidationError) as ei:
        w["handlers"].validators.validate_voluntary_exit_gossip(signed_exit)
    assert ei.value.action == GossipAction.IGNORE
    # a bad exit signature REJECTs
    bad = w["store"].sign_voluntary_exit(8, 0)
    bad = {
        "message": bad["message"],
        "signature": bad["signature"][:-1]
        + bytes([bad["signature"][-1] ^ 1]),
    }
    with pytest.raises(GossipValidationError) as ei:
        w["handlers"].validators.validate_voluntary_exit_gossip(bad)
    assert ei.value.action == GossipAction.REJECT


def test_blob_sidecar_validation(world):
    """deneb blob sidecar: inclusion proof + KZG proof + proposer sig
    (reference role: validation/blobsSidecar.ts, modern per-blob shape)."""
    import hashlib as _hl

    from lodestar_tpu.chain import blobs as BL
    from lodestar_tpu.chain.validation import (
        GossipValidationError,
        GossipValidators,
    )
    from lodestar_tpu.crypto import kzg as K

    w = world
    setup = K.insecure_dev_setup(8)
    width_bytes = 8 * 32
    blobs = [
        K.polynomial_to_blob(
            [
                int.from_bytes(_hl.sha256(b"bl-%d-%d" % (j, i)).digest(), "big")
                % K.R
                for i in range(8)
            ]
        )
        for j in range(2)
    ]
    commitments = [K.blob_to_kzg_commitment(b, setup) for b in blobs]
    body = T.BeaconBlockBodyDeneb.default()
    body["blob_kzg_commitments"] = list(commitments)
    # the claimed proposer must be the shuffle-expected one for the slot
    duties = w["chain_a"].get_proposer_duties(0)
    proposer = int(duties[1]["validator_index"])
    anchor = bytes.fromhex(w["chain_a"].anchor_root_hex)
    block = {
        "slot": 1,
        "proposer_index": proposer,
        "parent_root": anchor,
        "state_root": b"\x02" * 32,
        "body": body,
    }
    # proposer signature over the header (the sidecar carries the block's
    # signature next to the header)
    header = {
        "slot": 1,
        "proposer_index": proposer,
        "parent_root": anchor,
        "state_root": b"\x02" * 32,
        "body_root": T.BeaconBlockBodyDeneb.hash_tree_root(body),
    }
    root = w["cfg"].compute_signing_root(
        T.BeaconBlockHeader.hash_tree_root(header),
        w["cfg"].get_domain(1, params.DOMAIN_BEACON_PROPOSER, 1),
    )
    sig = C.g2_compress(B.sign(w["sks"][proposer], root))
    signed = {"message": block, "signature": sig}
    sidecars = BL.make_blob_sidecars(
        signed, T.BeaconBlockBodyDeneb, blobs, setup
    )
    assert len(sidecars) == 2
    # inclusion proofs verify standalone
    for sc in sidecars:
        assert BL.verify_blob_inclusion(sc, T.BeaconBlockBodyDeneb)

    v = GossipValidators(w["chain_a"], w["verifier"])
    got_root = v.validate_blob_sidecar(
        sidecars[0], setup, body_type=T.BeaconBlockBodyDeneb
    )
    assert got_root == T.BeaconBlockHeader.hash_tree_root(header)
    # duplicate -> IGNORE
    with pytest.raises(GossipValidationError, match="duplicate"):
        v.validate_blob_sidecar(
            sidecars[0], setup, body_type=T.BeaconBlockBodyDeneb
        )
    # wrong blob content -> KZG REJECT
    bad = dict(sidecars[1])
    bad["blob"] = blobs[0]
    with pytest.raises(GossipValidationError, match="KZG"):
        v.validate_blob_sidecar(bad, setup, body_type=T.BeaconBlockBodyDeneb)
    # tampered inclusion proof -> REJECT
    bad2 = dict(sidecars[1])
    proof = list(bad2["kzg_commitment_inclusion_proof"])
    proof[0] = b"\x55" * 32
    bad2["kzg_commitment_inclusion_proof"] = proof
    with pytest.raises(GossipValidationError, match="inclusion"):
        v.validate_blob_sidecar(bad2, setup, body_type=T.BeaconBlockBodyDeneb)
    # out-of-range index -> REJECT
    bad3 = dict(sidecars[1])
    bad3["index"] = params.MAX_BLOBS_PER_BLOCK
    with pytest.raises(GossipValidationError, match="range"):
        v.validate_blob_sidecar(bad3, setup, body_type=T.BeaconBlockBodyDeneb)
    # wrong proposer signature -> REJECT
    bad4 = dict(sidecars[1])
    bad4["signed_block_header"] = {
        "message": header,
        "signature": C.g2_compress(
            B.sign(w["sks"][(proposer + 1) % N_KEYS], root)  # wrong key
        ),
    }
    with pytest.raises(GossipValidationError, match="signature"):
        v.validate_blob_sidecar(bad4, setup, body_type=T.BeaconBlockBodyDeneb)
    # a header naming a NON-expected proposer (self-signed) -> REJECT,
    # even with a self-consistent signature
    imposter = (proposer + 1) % N_KEYS
    fake_header = dict(header, proposer_index=imposter)
    fake_root = w["cfg"].compute_signing_root(
        T.BeaconBlockHeader.hash_tree_root(fake_header),
        w["cfg"].get_domain(1, params.DOMAIN_BEACON_PROPOSER, 1),
    )
    bad5 = dict(sidecars[1])
    bad5["signed_block_header"] = {
        "message": fake_header,
        "signature": C.g2_compress(B.sign(w["sks"][imposter], fake_root)),
    }
    with pytest.raises(GossipValidationError, match="expected"):
        v.validate_blob_sidecar(bad5, setup, body_type=T.BeaconBlockBodyDeneb)
    # the untampered second sidecar still accepts
    assert v.validate_blob_sidecar(
        sidecars[1], setup, body_type=T.BeaconBlockBodyDeneb
    ) == bytes(got_root)


def test_bls_to_execution_change_gossip_flow(world):
    """capella: change rides the bus, validates, lands in the op pool;
    duplicates IGNORE; junk pubkeys REJECT."""
    from lodestar_tpu.chain.validation import (
        GossipValidationError,
        GossipValidators,
    )

    w = world
    index = 5
    change = {
        "validator_index": index,
        "from_bls_pubkey": w["pks"][index],
        "to_execution_address": b"\x55" * 20,
    }
    domain = w["cfg"].compute_domain(
        params.DOMAIN_BLS_TO_EXECUTION_CHANGE,
        w["cfg"].fork_versions[ForkName.phase0],
        w["genesis"].genesis_validators_root,
    )
    root = w["cfg"].compute_signing_root(
        T.BLSToExecutionChange.hash_tree_root(change), domain
    )
    signed = {
        "message": change,
        "signature": C.g2_compress(B.sign(w["sks"][index], root)),
    }
    n = _publish(
        w,
        GossipTopicName.bls_to_execution_change,
        T.SignedBLSToExecutionChange,
        signed,
    )
    assert n == 1
    res = w["handlers"].results["bls_to_execution_change"]
    assert res.get("accept") == 1
    assert index in w["chain_b"].op_pool._bls_to_execution_changes
    # a SECOND change for the same validator (different address, so the
    # bus message-id dedup does not swallow it) -> validator IGNORE
    change2 = dict(change, to_execution_address=b"\x66" * 20)
    root2 = w["cfg"].compute_signing_root(
        T.BLSToExecutionChange.hash_tree_root(change2), domain
    )
    signed2 = {
        "message": change2,
        "signature": C.g2_compress(B.sign(w["sks"][index], root2)),
    }
    _publish(
        w,
        GossipTopicName.bls_to_execution_change,
        T.SignedBLSToExecutionChange,
        signed2,
    )
    assert res.get("ignore") == 1
    # wrong withdrawal pubkey -> REJECT
    v = GossipValidators(w["chain_a"], w["verifier"])
    bad = {
        "message": dict(change, from_bls_pubkey=w["pks"][(index + 1) % N_KEYS]),
        "signature": signed["signature"],
    }
    with pytest.raises(GossipValidationError, match="invalid change"):
        v.validate_bls_to_execution_change_gossip(bad)


def test_blob_sidecar_gossip_flow(world):
    """deneb blob sidecars over the bus: index-matched subnet ACCEPTs;
    a sidecar published on the wrong subnet REJECTs."""
    import hashlib as _hl

    from lodestar_tpu.chain import blobs as BL
    from lodestar_tpu.crypto import kzg as K
    from lodestar_tpu.network.gossip import InMemoryGossipBus
    from lodestar_tpu.network.gossip_handlers import GossipHandlers

    w = world
    setup = K.insecure_dev_setup(8)
    handlers = GossipHandlers(w["chain_a"], w["verifier"], kzg_setup=setup)
    bus = InMemoryGossipBus()
    handlers.subscribe_all(bus, "blobnode", w["digest"], attnets=(), syncnets=())

    blob = K.polynomial_to_blob(
        [int.from_bytes(_hl.sha256(b"gb-%d" % i).digest(), "big") % K.R
         for i in range(8)]
    )
    commitment = K.blob_to_kzg_commitment(blob, setup)
    body = T.BeaconBlockBodyDeneb.default()
    body["blob_kzg_commitments"] = [commitment]
    duties = w["chain_a"].get_proposer_duties(0)
    proposer = int(duties[1]["validator_index"])
    anchor = bytes.fromhex(w["chain_a"].anchor_root_hex)
    block = {
        "slot": 1, "proposer_index": proposer,
        "parent_root": anchor, "state_root": b"\x02" * 32,
        "body": body,
    }
    header_root = w["cfg"].compute_signing_root(
        T.BeaconBlockHeader.hash_tree_root(
            {
                "slot": 1, "proposer_index": proposer,
                "parent_root": anchor, "state_root": b"\x02" * 32,
                "body_root": T.BeaconBlockBodyDeneb.hash_tree_root(body),
            }
        ),
        w["cfg"].get_domain(1, params.DOMAIN_BEACON_PROPOSER, 1),
    )
    signed = {
        "message": block,
        "signature": C.g2_compress(B.sign(w["sks"][proposer], header_root)),
    }
    sidecars = BL.make_blob_sidecars(
        signed, T.BeaconBlockBodyDeneb, [blob], setup
    )
    # NOTE: the SSZ Blob type is preset-width; the dev setup is width 8,
    # so drive the handler's value-level entry (the _dispatch branch
    # calls the same method after deserializing)
    from lodestar_tpu.chain.validation import GossipValidationError

    # sidecar's own validator needs the deneb-shaped body type: swap the
    # config fork dispatch for this altair test world
    handlers.validators.validate_blob_sidecar = (
        lambda sc, st, _orig=handlers.validators.validate_blob_sidecar: _orig(
            sc, st, body_type=T.BeaconBlockBodyDeneb
        )
    )
    # correct subnet (index 0) ACCEPTs through the handler entry
    handlers.handle_blob_sidecar(sidecars[0], subnet=0)
    # wrong subnet REJECTs through the SAME handler entry
    with pytest.raises(GossipValidationError, match="subnet"):
        handlers.handle_blob_sidecar(sidecars[0], subnet=3)
    # without a KZG setup the topic IGNOREs
    handlers_no_kzg = GossipHandlers(w["chain_a"], w["verifier"])
    with pytest.raises(GossipValidationError, match="no KZG setup"):
        handlers_no_kzg.handle_blob_sidecar(sidecars[0], subnet=0)
