"""Multi-chip sharding correctness on the virtual 8-device CPU mesh.

The driver's `dryrun_multichip` proves the full step compiles and runs over
a mesh; these tests pin the *correctness* of the two sharded building
blocks against the CPU ground truth (SURVEY.md §2.4 P1/P8):

  - data-parallel signature sets: `verify_batch` jitted with the sets axis
    sharded over the mesh,
  - the sharded device-resident pubkey table: cross-device gather +
    point-add (the Index2PubkeyCache analog, reference:
    packages/state-transition/src/cache/pubkeyCache.ts:29-47).
"""

import numpy as np
import os

import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from lodestar_tpu.crypto import bls as GTB
from lodestar_tpu.crypto import curves as C
from lodestar_tpu.crypto.hash_to_curve import hash_to_g2
from lodestar_tpu.ops import bls_kernels as BK
from lodestar_tpu.ops import curve as K
from lodestar_tpu.ops import fp, fp2

pytestmark = pytest.mark.slow

N_DEV = 8


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < N_DEV:
        pytest.skip(f"needs {N_DEV} devices (virtual CPU platform)")
    return Mesh(np.array(jax.devices()[:N_DEV]), ("sets",))


def _enc_g1(pts):
    return (
        jnp.asarray(np.stack([fp.const(p[0]) for p in pts])),
        jnp.asarray(np.stack([fp.const(p[1]) for p in pts])),
    )


def _enc_g2(pts):
    return (
        jnp.asarray(fp2.stack_consts([p[0] for p in pts])),
        jnp.asarray(fp2.stack_consts([p[1] for p in pts])),
    )


def test_sets_axis_sharded_verify_batch(mesh):
    """verify_batch over a sets-sharded batch == unsharded == ground truth."""
    n = N_DEV
    sks = [GTB.keygen(b"mesh-%d" % i) for i in range(n)]
    msgs = [b"mesh root %d" % (i % 2) for i in range(n)]
    pk_aff = _enc_g1([GTB.sk_to_pk(sk) for sk in sks])
    msg_aff = _enc_g2([hash_to_g2(m) for m in msgs])
    # One tampered signature => the sharded batch verdict must be False.
    sigs = [GTB.sign(sk, m) for sk, m in zip(sks, msgs)]
    good_sig_aff = _enc_g2(sigs)
    bad_sigs = list(sigs)
    bad_sigs[3] = C.scalar_mul(C.FP2_OPS, bad_sigs[3], 2)
    bad_sig_aff = _enc_g2(bad_sigs)

    rand = jnp.asarray(BK.make_rand_bits(n, np.random.default_rng(3)))
    valid = jnp.ones((n,), bool)

    s_sets = NamedSharding(mesh, P("sets"))
    s_bits = NamedSharding(mesh, P(None, "sets"))
    s_rep = NamedSharding(mesh, P())

    def shard(tree, sh):
        return jax.tree_util.tree_map(lambda a: jax.device_put(a, sh), tree)

    fn = jax.jit(BK.verify_batch, out_shardings=(s_rep, s_sets))
    for sig_aff, want in ((good_sig_aff, True), (bad_sig_aff, False)):
        ok, sig_ok = fn(
            shard(pk_aff, s_sets),
            shard(msg_aff, s_sets),
            shard(sig_aff, s_sets),
            jax.device_put(rand, s_bits),
            jax.device_put(valid, s_sets),
        )
        assert bool(ok) is want
        assert bool(jnp.all(sig_ok))  # tampering by doubling stays in G2


def test_sharded_pubkey_table_gather_aggregate(mesh):
    """Gather + point-add from a table sharded over the mesh == oracle."""
    v, n, kk = 2 * N_DEV, N_DEV, 3
    sks = [GTB.keygen(b"tbl-%d" % i) for i in range(v)]
    pks = [GTB.sk_to_pk(sk) for sk in sks]
    table_x, table_y = _enc_g1(pks)

    rng = np.random.default_rng(11)
    idx = rng.integers(0, v, size=(n, kk)).astype(np.int32)
    mask = rng.random((n, kk)) < 0.8
    mask[:, 0] = True  # at least one live pubkey per set

    s_rows = NamedSharding(mesh, P("sets"))  # table rows over devices
    s_sets = NamedSharding(mesh, P("sets"))

    def step(tx, ty, idx, mask):
        agg = BK.aggregate_pubkeys(tx, ty, idx, mask)
        aff, inf = K.to_affine(K.FP_OPS, agg)
        return aff, inf

    aff, inf = jax.jit(step)(
        jax.device_put(table_x, s_rows),
        jax.device_put(table_y, s_rows),
        jax.device_put(jnp.asarray(idx), s_sets),
        jax.device_put(jnp.asarray(mask), s_sets),
    )
    got_x = np.asarray(aff[0])
    got_y = np.asarray(aff[1])
    inf = np.asarray(inf)
    for i in range(n):
        want = C.multi_add(
            C.FP_OPS, [pks[j] for j, m in zip(idx[i], mask[i]) if m]
        )
        if want is None:
            assert inf[i]
        else:
            assert not inf[i]
            assert fp.decode(got_x[i]) == want[0]
            assert fp.decode(got_y[i]) == want[1]


# -- production pallas engine sharding (round 4) ----------------------------


@pytest.mark.smoke
def test_sharded_wire_verifier_builds(mesh):
    """Construction-level check (cheap): the sharded production-path
    verifier builds over the mesh with the documented spec layout.
    Full execution is the slow-tier test below / GRAFT_DRYRUN=kernels
    (interpret-mode trace+compile is minutes-expensive — dev/NOTES.md
    'CPU-host costs')."""
    from lodestar_tpu.kernels import verify as KV

    fn = KV.make_sharded_wire_verifier(mesh)
    assert callable(fn)


@pytest.mark.skipif(
    os.environ.get("LODESTAR_TPU_RUN_SHARDED_KERNELS") != "1",
    reason="XLA:CPU cannot compile the monolithic interpret-mode pipeline "
    "(round-4 measurement: algebraic-simplifier loop, >42 min without "
    "terminating — dev/NOTES.md 'CPU-host costs'); opt in on capable "
    "hosts / real multi-chip with LODESTAR_TPU_RUN_SHARDED_KERNELS=1",
)
def test_sharded_wire_verifier_runs(mesh):
    """One sharded wire-path job over the mesh — per-device local
    pipelines + one all_gather/psum combine + replicated tail."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    import __graft_entry__ as G
    from lodestar_tpu.kernels import verify as KV

    n = KV.BT * mesh.devices.size
    fn_args = G._wire_example(n, distinct=8, seed=b"mesh-kernels")
    _fn, args = fn_args
    sharded = KV.make_sharded_wire_verifier(mesh)
    placed = [
        jax.device_put(a, NamedSharding(mesh, s))
        for a, s in zip(args, KV.wire_shard_specs())
    ]
    ok, sub_ok = jax.jit(sharded)(*placed)
    assert bool(ok)
    assert bool(jnp.all(sub_ok))
