"""Wire-path verification: bytes in -> verdicts out, vs the CPU oracle.

Drives TpuBlsVerifier with WireSignatureSets (32B signing roots + 96B
compressed signatures): device hash-to-curve via MessageCache, device
signature decompression inside the pipeline (reference equivalent: blst
deserialize+hash inside the worker, multithread/worker.ts:30-106).
"""

import numpy as np
import pytest

from lodestar_tpu.bls.ingest import parse_signature_bytes
from lodestar_tpu.bls.pubkey_table import PubkeyTable
from lodestar_tpu.bls.signature_set import WireSignatureSet
from lodestar_tpu.bls.verifier import TpuBlsVerifier, VerifyOptions
from lodestar_tpu.crypto import bls as B
from lodestar_tpu.crypto import curves as C

pytestmark = pytest.mark.slow

N_KEYS = 8


@pytest.fixture(scope="module")
def world():
    sks = [B.keygen(b"wire-%d" % i) for i in range(N_KEYS)]
    pks = [B.sk_to_pk(sk) for sk in sks]
    table = PubkeyTable(capacity=N_KEYS)
    table.register(pks)
    verifier = TpuBlsVerifier(table, rng=np.random.default_rng(5))
    return sks, table, verifier


def wire_set(sks, i, root):
    sig = C.g2_compress(B.sign(sks[i % N_KEYS], root))
    return WireSignatureSet.single(i % N_KEYS, root, sig)


def test_parse_signature_bytes_checks():
    good = C.g2_compress(B.sign(B.keygen(b"x"), b"m"))
    x0, x1, sign, inf, ok = parse_signature_bytes(good)
    assert ok and not inf
    assert parse_signature_bytes(good[:-1])[4] is False  # truncated
    assert parse_signature_bytes(bytes([good[0] & 0x7F]) + good[1:])[4] is False
    inf_enc = bytes([0xC0]) + b"\x00" * 95
    assert parse_signature_bytes(inf_enc) == (0, 0, 0, 1, True)
    bad_inf = bytes([0xC0]) + b"\x01" + b"\x00" * 94
    assert parse_signature_bytes(bad_inf)[4] is False
    too_big = bytes([0x9F]) + b"\xff" * 95  # x >= p
    assert parse_signature_bytes(too_big)[4] is False


def test_wire_batch_accepts_valid(world):
    sks, _t, verifier = world
    roots = [b"wire root %d" % (i % 3) for i in range(16)]
    roots = [r.ljust(32, b"\x00") for r in roots]
    sets = [wire_set(sks, i, roots[i]) for i in range(16)]
    assert verifier.verify_signature_sets(sets, VerifyOptions(batchable=True))
    assert verifier.metrics.batch_sigs_success.value >= 16


def test_wire_batch_rejects_bad_and_retries(world):
    sks, _t, verifier = world
    roots = [(b"wr2 %d" % i).ljust(32, b"\x00") for i in range(8)]
    sets = [wire_set(sks, i, roots[i]) for i in range(8)]
    # wrong message for set 3
    bad = WireSignatureSet.single(
        3 % N_KEYS, roots[4], sets[3].signature
    )
    mixed = sets[:3] + [bad] + sets[4:]
    before = verifier.metrics.batch_retries.value
    assert not verifier.verify_signature_sets(mixed, VerifyOptions(batchable=True))
    assert verifier.metrics.batch_retries.value == before + 1
    verdicts = verifier.verify_signature_sets_individually(mixed)
    assert verdicts == [True] * 3 + [False] + [True] * 4


def test_wire_undecodable_and_infinity(world):
    sks, _t, verifier = world
    roots = [(b"wr3 %d" % i).ljust(32, b"\x00") for i in range(4)]
    sets = [wire_set(sks, i, roots[i]) for i in range(4)]
    corrupted = bytearray(sets[1].signature)
    corrupted[7] ^= 0x01  # off-curve x (almost surely)
    mixed = [
        sets[0],
        WireSignatureSet.single(1 % N_KEYS, roots[1], bytes(corrupted)),
        WireSignatureSet.single(2 % N_KEYS, roots[2], bytes([0xC0]) + b"\x00" * 95),
        sets[3],
    ]
    verdicts = verifier.verify_signature_sets_individually(mixed)
    assert verdicts[0] is True and verdicts[3] is True
    assert verdicts[1] is False and verdicts[2] is False
    assert not verifier.verify_signature_sets(mixed, VerifyOptions(batchable=True))


def test_wire_aggregate_sets(world):
    sks, _t, verifier = world
    root = b"wire agg root".ljust(32, b"\x00")
    members = [0, 2, 5]
    agg = B.aggregate_signatures([B.sign(sks[i], root) for i in members])
    ws = WireSignatureSet.aggregate(members, root, C.g2_compress(agg))
    other = wire_set(sks, 1, b"other".ljust(32, b"\x00"))
    assert verifier.verify_signature_sets([ws, other], VerifyOptions(batchable=True))
    # wrong membership fails
    ws_bad = WireSignatureSet.aggregate([0, 2, 6], root, C.g2_compress(agg))
    assert verifier.verify_signature_sets_individually([ws_bad]) == [False]


def test_message_cache_device_matches_host(world):
    _sks, _t, verifier = world
    from lodestar_tpu.crypto.hash_to_curve import hash_to_g2

    roots = [(b"mc %d" % i).ljust(32, b"\x00") for i in range(5)]
    got = verifier.messages.get_many(roots)
    for r, g in zip(roots, got):
        assert g == hash_to_g2(r)
    h0 = verifier.messages.hits
    verifier.messages.get_many(roots)
    assert verifier.messages.hits == h0 + 5
