"""StateMemoryGovernor — ledger reconciliation, the demotion ladder,
pins, and the degradation rungs (ISSUE 15).

The ledger's incremental COW-aware accounting is checked against the
ground-truth walk (`state_root_engine_bytes` over the live cache
states) after every operation of randomized add/evict/clone/demote/
touch interleavings — the oracle the old per-head-update metric paid on
every sample.  The ladder property: ANY interleaving of touch/demote/
spill/evict/regen yields `hash_tree_root` bit-identical to the
never-evicted twin, and pinned states survive an adversarial budget of
approximately zero.
"""

import hashlib
import itertools

import numpy as np
import pytest

from lodestar_tpu import params
from lodestar_tpu import types as T
from lodestar_tpu.chain.memory_governor import (
    DEFAULT_BUDGET_BYTES,
    SpilledState,
    StateMemoryGovernor,
    budget_from_env,
    memory_snapshot,
)
from lodestar_tpu.chain.regen import RegenError, StateRegenerator
from lodestar_tpu.chain.state_cache import (
    CheckpointStateCache,
    StateContextCache,
)
from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
from lodestar_tpu.crypto import bls as B
from lodestar_tpu.crypto import curves as C
from lodestar_tpu.params import ForkName
from lodestar_tpu.state_transition import create_genesis_state
from lodestar_tpu.state_transition.state_root import (
    state_root_engine_bytes,
)
from lodestar_tpu.utils.metrics import Registry

P = params.ACTIVE_PRESET
N_KEYS = 16


@pytest.fixture(scope="module")
def cfg():
    return create_chain_config(
        MAINNET_CHAIN_CONFIG, fork_epochs={ForkName.altair: 0}
    )


@pytest.fixture(scope="module")
def genesis(cfg):
    pks = [
        C.g1_compress(B.sk_to_pk(B.keygen(b"gov-%d" % i)))
        for i in range(N_KEYS)
    ]
    st = create_genesis_state(cfg, pks, genesis_time=3)
    st.hash_tree_root()  # warm the engine
    return st


def _governed(cfg, budget):
    gov = StateMemoryGovernor(budget, config=cfg, registry=Registry())
    sc = StateContextCache(governor=gov)
    cc = CheckpointStateCache(governor=gov)
    gov.attach(sc, cc)
    return gov, sc, cc


def _walk(sc, cc) -> int:
    return state_root_engine_bytes(
        itertools.chain(sc.states(), cc.states())
    )


def _mutated(rng, parent, salt: int):
    st = parent.clone()
    st.balances[int(rng.integers(0, st.num_validators))] += np.uint64(
        1 + salt
    )
    st.slot = int(st.slot) + 1
    return st, st.hash_tree_root().hex()


def _run_interleaving(cfg, genesis, seed, ops, budget):
    """Drive `ops` random ledger operations; after EVERY op the
    incremental ledger must equal the walk, and at the end every
    cache-visible state must hash to its recorded twin root."""
    rng = np.random.default_rng(seed)
    gov, sc, cc = _governed(cfg, budget)
    twins = {}  # root hex -> the never-evicted state object
    # (hash_tree_root also re-warms the shared fixture's engine if an
    # earlier test's demotion released its planes)
    g_root = genesis.hash_tree_root().hex()
    twins[g_root] = genesis
    sc.add_with_root(g_root, genesis)
    evicted = []
    for i in range(ops):
        roots = sorted(twins)
        op = rng.integers(0, 6)
        if op == 0 or len(sc) == 0:  # add a mutated child
            parent = twins[roots[int(rng.integers(0, len(roots)))]]
            st, rhex = _mutated(rng, parent, i)
            twins[rhex] = st
            sc.add_with_root(rhex, st)
        elif op == 1:  # touch (rehydrates a spill)
            sc.get(roots[int(rng.integers(0, len(roots)))])
        elif op == 2:  # demote (forced tier 1)
            gov.demote_state(roots[int(rng.integers(0, len(roots)))])
        elif op == 3:  # evict
            victim = roots[int(rng.integers(0, len(roots)))]
            if victim in sc._map:
                sc.delete(victim)
                evicted.append(victim)
        elif op == 4:  # regen: an evicted root replays back in
            if evicted:
                back = evicted.pop()
                sc.add_with_root(back, twins[back])
        else:  # checkpoint add (same object, second cache)
            rhex = roots[int(rng.integers(0, len(roots)))]
            cc.add(
                {"epoch": int(i % 4), "root": bytes.fromhex(rhex)},
                twins[rhex],
            )
        assert gov.ledger.plane_bytes == _walk(sc, cc), (i, op)
    # the ladder property: everything still visible hashes bit-identical
    for rhex in list(sc._map):
        got = sc.get(rhex)
        assert got.hash_tree_root().hex() == rhex
    for (epoch, rhex) in list(cc._map):
        got = cc.get({"epoch": epoch, "root": bytes.fromhex(rhex)})
        assert got.hash_tree_root().hex() == rhex
    # the hash sweep above built engines IN PLACE on rehydrated cache
    # objects (planes their snapshots predate) — the per-tick reconcile
    # is the documented healer for exactly that drift class
    gov.reconcile()
    assert gov.ledger.plane_bytes == _walk(sc, cc)
    return gov


def test_ledger_matches_walk_randomized(cfg, genesis):
    _run_interleaving(cfg, genesis, seed=7, ops=40, budget=1 << 40)


def test_ladder_property_under_tight_budget(cfg, genesis):
    """Same interleaving with the budget squeezing the whole time:
    auto-demote/evict interleave with the scripted ops and roots stay
    bit-identical."""
    genesis.hash_tree_root()  # re-warm: an earlier demotion may have
    # released the shared fixture's planes (the external-holder design)
    gov = _run_interleaving(
        cfg, genesis, seed=11, ops=40,
        budget=genesis._root_engine.engine_bytes() // 2,
    )
    assert sum(gov.evictions.values()) > 0


@pytest.mark.slow
def test_ledger_matches_walk_randomized_long(cfg, genesis):
    for seed in (1, 2, 3):
        _run_interleaving(cfg, genesis, seed=seed, ops=200, budget=1 << 40)


def test_cow_shared_planes_counted_once(cfg, genesis):
    genesis.hash_tree_root()  # re-warm the shared fixture
    gov, sc, cc = _governed(cfg, 1 << 40)
    g_root = genesis.hash_tree_root().hex()
    sc.add_with_root(g_root, genesis)
    solo = gov.ledger.plane_bytes
    # a clone shares every plane COW: adding it must cost ~nothing
    clone = genesis.clone()
    clone.hash_tree_root()
    sc.add_with_root("ff" * 32, clone)
    assert gov.ledger.plane_bytes < solo * 1.05
    assert gov.ledger.plane_bytes == _walk(sc, cc)


def test_pinned_states_survive_adversarial_budget(cfg, genesis):
    genesis.hash_tree_root()  # re-warm the shared fixture
    rng = np.random.default_rng(3)
    gov, sc, cc = _governed(cfg, 1 << 40)
    g_root = genesis.hash_tree_root().hex()
    sc.add_with_root(g_root, genesis)
    others = []
    for i in range(5):
        st, rhex = _mutated(rng, genesis, i)
        sc.add_with_root(rhex, st)
        others.append(rhex)
    gov.pinned_fn = lambda: ({g_root}, lambda _e, _r: False)
    gov.set_budget(1)  # ~zero: everything unpinned must go
    # the pinned state is still LIVE (never spilled, never evicted)
    assert isinstance(sc._map[g_root], type(genesis))
    assert sc.get(g_root) is genesis
    for rhex in others:
        assert rhex not in sc._map
    assert gov.ledger.plane_bytes == _walk(sc, cc)
    # and it still hashes correctly
    assert sc.get(g_root).hash_tree_root().hex() == g_root


def test_degradation_rungs_escalate_and_restore(cfg, genesis):
    rng = np.random.default_rng(5)
    gov, sc, cc = _governed(cfg, 1 << 40)
    base_epochs = cc.max_epochs
    g_root = genesis.hash_tree_root().hex()
    sc.add_with_root(g_root, genesis)
    # pin EVERYTHING: eviction can never converge -> strain climbs
    gov.pinned_fn = lambda: (set(sc._map.keys()), lambda _e, _r: True)
    gov.set_budget(1)
    assert gov.pressure_active
    assert gov.pressure_level == 1
    assert cc.max_epochs == max(2, base_epochs // 2)  # rung 1
    assert not gov.skip_precompute()
    st, rhex = _mutated(rng, genesis, 0)
    sc.add_with_root(rhex, st)  # wave 2
    assert gov.skip_precompute()  # rung 2
    assert not gov.regen_rejected(10**6)
    st2, rhex2 = _mutated(rng, st, 1)
    sc.add_with_root(rhex2, st2)  # wave 3
    assert gov.pressure_level == 3
    assert gov.regen_rejected(gov.replay_depth_bound + 1)  # rung 3
    assert not gov.regen_rejected(gov.replay_depth_bound)
    # relief: a big budget resets strain; a quiet compliant tick closes
    # the episode and restores the checkpoint window
    gov.set_budget(1 << 40)
    gov.on_slot(1)
    assert not gov.pressure_active
    assert gov.pressure_level == 0
    assert cc.max_epochs == base_epochs
    # exactly one pressure episode was counted
    assert gov._pressure_events == 1


def test_pressure_callback_fires_once_per_episode(cfg, genesis):
    events = []
    gov, sc, cc = _governed(cfg, 1 << 40)
    gov.on_pressure = events.append
    rng = np.random.default_rng(9)
    g_root = genesis.hash_tree_root().hex()
    sc.add_with_root(g_root, genesis)
    gov.set_budget(gov.ledger.plane_bytes // 2)
    for i in range(4):  # more waves inside the same episode
        st, rhex = _mutated(rng, genesis, i)
        sc.add_with_root(rhex, st)
    assert len(events) == 1
    assert events[0]["budget_bytes"] == gov.budget
    # close the episode, squeeze again -> a SECOND episode, one event.
    # Two ticks: the first absorbs the wave's eviction count (a tick
    # right after evictions is not "quiet"), the second closes.
    gov.set_budget(1 << 40)
    gov.on_slot(1)
    gov.on_slot(2)
    assert not gov.pressure_active
    # repopulate (the first squeeze drained everything unpinned — and
    # demotion RELEASED the shared object's planes, so re-warm the
    # engine first), then squeeze again -> a second episode, one event
    genesis.hash_tree_root()
    sc.add_with_root(g_root, genesis)
    gov.set_budget(1)
    assert len(events) == 2


def test_regen_rejects_with_typed_memory_pressure_error(cfg, genesis):
    """Rung 3 end-to-end through StateRegenerator: a deep replay under
    sustained pressure raises RegenError("MEMORY_PRESSURE") — typed, so
    callers can tell it from a missing anchor."""
    from lodestar_tpu.chain.produce_block import produce_block
    from lodestar_tpu.db import BeaconDb
    from lodestar_tpu.fork_choice import ForkChoice, ProtoArray

    g_root = T.BeaconBlockHeader.hash_tree_root(
        dict(
            genesis.latest_block_header,
            state_root=genesis.hash_tree_root(),
        )
    ).hex()
    fork_choice = ForkChoice(
        ProtoArray(finalized_root=g_root), justified_root=g_root
    )
    db = BeaconDb(None)
    gov = StateMemoryGovernor(1 << 40, config=cfg, registry=Registry())
    regen = StateRegenerator(fork_choice, db, governor=gov)
    regen.block_state_roots[g_root] = genesis.hash_tree_root().hex()
    regen.state_cache.add_with_root(genesis.hash_tree_root().hex(), genesis)

    state = genesis
    roots = [g_root]
    for slot in range(1, 5):
        block, post = produce_block(
            state, slot, hashlib.sha256(b"mp%d" % slot).digest() * 3
        )
        root = T.BeaconBlockAltair.hash_tree_root(block)
        fork_choice.on_block(slot, root.hex(), block["parent_root"].hex())
        db.put_block(root, {"message": block, "signature": b"\x00" * 96})
        regen.on_imported_block(root, post)
        state = post
        roots.append(root.hex())
    # evict the whole tail so a regen of the tip must replay 4 blocks
    for rhex in roots[1:]:
        regen.state_cache.delete(regen.block_state_roots[rhex])
    gov._strain = 3  # sustained pressure
    gov.replay_depth_bound = 2
    with pytest.raises(RegenError) as err:
        regen.get_block_slot_state(roots[-1], 4)
    assert err.value.code == "MEMORY_PRESSURE"
    # relief lifts the rejection and the replay works, bit-identical
    gov._strain = 0
    st = regen.get_block_slot_state(roots[-1], 4)
    assert st.hash_tree_root().hex() == regen.block_state_roots[roots[-1]]


def test_regen_on_finalized_prunes_block_state_roots(cfg, genesis):
    """Unit leg of the unbounded-growth fix: on_finalized forgets the
    pruned nodes' entries and their cached states."""
    from lodestar_tpu.fork_choice import ForkChoice, ProtoArray

    class Node:
        def __init__(self, root):
            self.root = root

    g_root = "aa" * 32
    fork_choice = ForkChoice(
        ProtoArray(finalized_root=g_root), justified_root=g_root
    )
    regen = StateRegenerator(fork_choice, None)
    regen.block_state_roots[g_root] = genesis.hash_tree_root().hex()
    regen.state_cache.add_with_root(genesis.hash_tree_root().hex(), genesis)
    dead = []
    for i in range(6):
        st = genesis.clone()
        st.slot = i + 1
        rhex = st.hash_tree_root().hex()
        block_hex = bytes([i + 1]).hex() * 32
        regen.block_state_roots[block_hex] = rhex
        regen.state_cache.add_with_root(rhex, st)
        dead.append(Node(block_hex))
    before = len(regen.block_state_roots)
    assert regen.on_finalized(dead) == 6
    assert len(regen.block_state_roots) == before - 6
    assert g_root in regen.block_state_roots
    assert len(regen.state_cache) == 1  # only genesis remains


def test_budget_env_parsing(monkeypatch):
    monkeypatch.delenv("LODESTAR_TPU_STATE_BUDGET", raising=False)
    assert budget_from_env() == DEFAULT_BUDGET_BYTES
    monkeypatch.setenv("LODESTAR_TPU_STATE_BUDGET", "0")
    assert budget_from_env() is None  # the escape hatch
    monkeypatch.setenv("LODESTAR_TPU_STATE_BUDGET", "1234")
    assert budget_from_env() == 1234
    monkeypatch.setenv("LODESTAR_TPU_STATE_BUDGET", "512m")
    assert budget_from_env() == 512 << 20
    monkeypatch.setenv("LODESTAR_TPU_STATE_BUDGET", "2g")
    assert budget_from_env() == 2 << 30
    monkeypatch.setenv("LODESTAR_TPU_STATE_BUDGET", "64k")
    assert budget_from_env() == 64 << 10
    monkeypatch.setenv("LODESTAR_TPU_STATE_BUDGET", "garbage")
    assert budget_from_env() == DEFAULT_BUDGET_BYTES  # fail safe


def test_pressure_properties_take_the_lock(cfg):
    """Regression (tpulint guarded-by): pressure_active / pressure_level
    read `_episode_active` / `_strain` — written under `_lock` by the
    clock-tick thread — and used to read them lock-free from the regen
    and prepare paths.  The governor lock is an RLock, so taking it in
    the properties stays re-entrant for callers already inside it
    (e.g. status())."""
    gov, _, _ = _governed(cfg, 1 << 40)
    inner = gov._lock
    acquisitions = []

    class RecordingLock:
        def __enter__(self):
            acquisitions.append(1)
            return inner.__enter__()

        def __exit__(self, *exc):
            return inner.__exit__(*exc)

        def __getattr__(self, name):
            return getattr(inner, name)

    gov._lock = RecordingLock()
    try:
        assert gov.pressure_active is False
        assert gov.pressure_level == 0
        assert gov.skip_precompute() is False
        assert gov.regen_rejected(replay_depth=10 ** 6) is False
    finally:
        gov._lock = inner
    assert len(acquisitions) >= 4
    # re-entrancy: reading the property while the lock is held must
    # not deadlock (status()'s snapshot path)
    with gov._lock:
        assert gov.pressure_level == 0


def test_memory_snapshot_aggregates(cfg, genesis):
    genesis.hash_tree_root()  # re-warm the shared fixture
    gov, sc, cc = _governed(cfg, 1 << 40)
    sc.add_with_root(genesis.hash_tree_root().hex(), genesis)
    snap = memory_snapshot()
    assert snap["governors"] >= 1
    assert snap["resident_bytes"] >= gov.ledger.resident_bytes > 0
    assert set(snap["evictions"]) == {"demote", "evict", "drain"}


def test_release_planes_rebuilds_bit_identical(genesis):
    """The tier-1 spill primitive: release_planes frees every node
    plane (engine_bytes -> 0) and the next hash rebuilds cold to the
    SAME root; ChunkTree.release behaves identically at tree level."""
    st = genesis.clone()
    st.balances[0] += np.uint64(7)
    root = st.hash_tree_root()
    engine = st._root_engine
    assert engine.engine_bytes() > 0
    freed = engine.release_planes()
    assert freed > 0
    assert engine.engine_bytes() == 0
    assert st.hash_tree_root() == root  # cold rebuild, bit-identical
    # tree-level twin
    from lodestar_tpu.ssz import ChunkTree

    plane = np.arange(4 * 32, dtype=np.uint8).reshape(4, 32)
    tree = ChunkTree(8)
    tree.update(plane)
    r = tree.root
    tree.release()
    assert tree.plane_bytes() == 0 and tree.count == 0
    tree.update(plane)
    assert tree.root == r


def test_demote_releases_unshared_planes(cfg, genesis):
    """_try_demote actively releases the outgoing engine's planes when
    no other ledger entry shares them — a lingering external reference
    to the demoted object must not pin the node planes."""
    gov, sc, cc = _governed(cfg, 1 << 40)
    st = genesis.clone()
    st.balances[1] += np.uint64(3)
    rhex = st.hash_tree_root().hex()
    sc.add_with_root(rhex, st)
    held = st  # an external holder surviving the demotion
    assert gov.demote_state(rhex)
    assert held._root_engine.engine_bytes() == 0  # planes freed NOW
    # and the held object still hashes correctly (cold rebuild)
    assert held.hash_tree_root().hex() == rhex
    # the cache side rehydrates bit-identical too
    assert sc.get(rhex).hash_tree_root().hex() == rhex


def test_rehydration_enforces_budget(cfg, genesis):
    """A read burst over spilled entries re-books ledger bytes — the
    budget must bind at rehydration time, not only at add/tick."""
    rng = np.random.default_rng(21)
    gov, sc, cc = _governed(cfg, 1 << 40)
    roots = []
    for i in range(4):
        st, rhex = _mutated(rng, genesis, i)
        sc.add_with_root(rhex, st)
        roots.append(rhex)
    for rhex in roots:
        gov.demote_state(rhex)
    budget = max(1, gov.ledger.resident_bytes + (1 << 20))
    gov.set_budget(budget)
    # touching every spill would rebuild the full working set; the
    # rehydration-path enforce keeps residency at the budget instead
    for rhex in roots:
        st = sc.get(rhex)
        if st is not None:
            assert st.hash_tree_root().hex() == rhex
    assert gov.ledger.resident_bytes <= budget


def test_checkpoint_pins_survive_side_fork_imports(tmp_path):
    """A side-fork import's post-state carries STALE justified/
    finalized checkpoints — the governor's checkpoint pins must stay
    on the chain-wide (monotonic) values, not last-import-wins."""
    import sys

    sys.path.insert(0, "tests")
    from chaos.harness import StateWorld

    world = StateWorld(tmp_path / "fr", seed=4)
    try:
        chain = world.chain
        old_parent = None
        for _ in range(3 * P.SLOTS_PER_EPOCH + 2):
            slot = world.tick_slot()
            world.churn_slot(slot, fork=False, attest=True)
            if slot == 2:
                old_parent = chain.head_root_hex  # an epoch-0 ancestor
        assert chain._pin_justified[0] >= 1  # justification progressed
        pinned_before = (chain._pin_justified, chain._pin_finalized)
        # a deep side-fork block on the epoch-0 ancestor: its post-state
        # carries STALE (epoch-0) checkpoints; importing it must not
        # clobber the canonical pins (last-import-wins would)
        side = world._produce_on(old_parent, slot + 1, b"\x55" * 32)
        chain.process_block(side)
        assert (chain._pin_justified, chain._pin_finalized) == pinned_before
        # and the pins match the chain-wide justification
        assert chain._pin_justified[0] == int(
            chain.head_state.current_justified_checkpoint["epoch"]
        )
        assert chain._pin_finalized[0] == chain._finalized_epoch
    finally:
        world.close()


def test_checkpoint_epoch_prune_respects_pins(cfg, genesis):
    """The count-based epoch window must not evict pinned checkpoint
    entries (the non-governor eviction path): pinned keys survive
    prune_epoch and the add-time window loop stops at them."""
    gov, sc, cc = _governed(cfg, 1 << 40)
    cc.max_epochs = 2
    pinned_root = b"\xaa" * 32
    gov.pinned_fn = lambda: (
        set(),
        lambda e, r: (e, r) == (0, pinned_root.hex()),
    )
    cc.add({"epoch": 0, "root": pinned_root}, genesis)
    for epoch in (1, 2, 3, 4):
        cc.add({"epoch": epoch, "root": b"\xbb" * 32}, genesis.clone())
    # the pinned epoch-0 entry is still there; unpinned old epochs went
    assert cc.get({"epoch": 0, "root": pinned_root}) is genesis
    assert cc.get({"epoch": 1, "root": b"\xbb" * 32}) is None
    # and prune_finalized cannot remove it either
    cc.prune_finalized(4)
    assert cc.get({"epoch": 0, "root": pinned_root}) is genesis


def test_engine_diff_columns_are_counted(genesis):
    """An OWNED engine's validator diff columns (_ValidatorsCell.cols —
    a second full copy of the numeric registry columns) count in both
    the walk and the ledger; a COW clone shares them for free."""
    st = genesis.clone()
    st.balances[2] += np.uint64(5)
    st.hash_tree_root()
    engine = st._root_engine
    cols = engine.validators.cols
    assert cols, "hashing must have materialized the diff columns"
    col_ids = {id(a) for a in cols.values()}
    plane_ids = {id(p) for p in engine.iter_planes()}
    assert col_ids <= plane_ids  # enumerated for the ledger
    # and the walk counts them (engine_bytes >= the raw column sum)
    assert engine.engine_bytes() >= sum(a.nbytes for a in cols.values())


def test_peer_score_book_forget_retains_penalties():
    """forget() on disconnect drops churn records but RETAINS negative
    scores — a flooder cycling connections must keep accumulating
    toward the ban instead of resetting to a clean slate."""
    from lodestar_tpu.network.peers import PeerAction, PeerScoreBook

    book = PeerScoreBook(clock=lambda: 1000.0)
    book.apply_action("flooder", PeerAction.low_tolerance)  # negative
    assert book.score("flooder") < -1.0
    before = book.score("flooder")
    book.forget("flooder")
    assert abs(book.score("flooder") - before) < 1e-6  # retained
    # a churned near-zero peer IS dropped
    book.score("bystander")  # creates a clean record
    assert "bystander" in book._peers
    book.forget("bystander")
    assert "bystander" not in book._peers


def test_spilled_state_marker_is_inert():
    sp = SpilledState(b"\x01" * 10, "ab" * 32)
    assert len(sp) == 10
    assert getattr(sp, "_root_engine", None) is None


# -- bench probe stubs (ISSUE 15 satellite) ---------------------------------


def _quiet_bench(monkeypatch):
    import bench

    monkeypatch.setattr(bench, "_FLIGHT_RECORDER", None)
    monkeypatch.setattr(bench, "_FLIGHTREC_ON", False)
    monkeypatch.delenv("BENCH_FLIGHTREC_DIR", raising=False)
    return bench


def test_bench_regen_probe_timeout_emits_skip(capsys, monkeypatch):
    """A dead probe leaves a typed skip record (value null, skipped
    true, the metric/unit pair bench_compare expects), never a hang or
    a measured zero."""
    import json
    import subprocess

    bench = _quiet_bench(monkeypatch)

    def boom(*_a, **_k):
        raise subprocess.TimeoutExpired(cmd="microbench_regen", timeout=1)

    monkeypatch.setattr(bench.subprocess, "run", boom)
    bench._probe_regen_pressure()
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["metric"] == "regen_under_pressure_states_per_s"
    assert rec["unit"] == "states/s"
    assert rec["value"] is None and rec["skipped"] is True
    assert "memory" in rec  # every record carries the memory snapshot


def test_bench_regen_probe_forwards_child_record(capsys, monkeypatch):
    import json

    bench = _quiet_bench(monkeypatch)
    child = {
        "metric": "regen_under_pressure_states_per_s",
        "value": 10.2,
        "unit": "states/s",
        "working_set_bytes": 123,
        "budgets": {
            "unbounded": {"states_per_s": 100.0},
            "0.5x": {"states_per_s": 20.0},
            "0.25x": {"states_per_s": 10.2},
        },
    }

    class P:
        returncode = 0
        stdout = json.dumps(child) + "\n"
        stderr = ""

    monkeypatch.setattr(bench.subprocess, "run", lambda *a, **k: P)
    bench._probe_regen_pressure()
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["value"] == 10.2
    assert rec["budgets"]["0.25x"]["states_per_s"] == 10.2
    assert rec.get("skipped") is None
    # parent-side snapshots attach like every other bench record
    for field in ("phases", "slo", "memory", "vs_baseline"):
        assert field in rec


def test_bench_regen_probe_child_failure_emits_skip(capsys, monkeypatch):
    import json

    bench = _quiet_bench(monkeypatch)

    class P:
        returncode = 3
        stdout = ""
        stderr = "boom: no such chain"

    monkeypatch.setattr(bench.subprocess, "run", lambda *a, **k: P)
    bench._probe_regen_pressure()
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["skipped"] is True and rec["value"] is None
    assert "boom" in rec["error"]


def test_bench_failure_records_carry_memory_snapshot(capsys, monkeypatch):
    import json

    bench = _quiet_bench(monkeypatch)
    bench._emit_failure("run", "stub failure")
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert "memory" in rec
    assert set(rec["memory"]["evictions"]) == {"demote", "evict", "drain"}


@pytest.mark.slow
def test_microbench_regen_real_run():
    """The dev script end-to-end at toy scale: a parseable record with
    all three budget legs and a positive throughput floor."""
    import json
    import os
    import subprocess
    import sys

    script = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "dev",
        "microbench_regen.py",
    )
    p = subprocess.run(
        [sys.executable, script, "--json", "--keys", "8", "--slots", "6",
         "--touches", "8"],
        capture_output=True,
        text=True,
        timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert p.returncode == 0, p.stderr[-2000:]
    rec = json.loads(
        [l for l in p.stdout.splitlines() if l.startswith("{")][-1]
    )
    assert rec["metric"] == "regen_under_pressure_states_per_s"
    assert rec["value"] > 0
    assert set(rec["budgets"]) == {"unbounded", "0.5x", "0.25x"}
    assert rec["budgets"]["0.25x"]["evictions"]["evict"] >= 0
    assert rec["working_set_bytes"] > 0
