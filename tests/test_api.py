"""Beacon REST API: server + client round trips.

Reference: packages/api (routes/client) + beacon-node/src/api/rest.
"""

import pytest

from lodestar_tpu.api import ApiClient, BeaconApiServer
from lodestar_tpu.api.client import ApiError
from lodestar_tpu.api.routes import match
from lodestar_tpu.api.server import DefaultHandlers
from lodestar_tpu.network.gossip_queues import GossipType
from lodestar_tpu.network.processor import NetworkProcessor, PendingGossipMessage
from lodestar_tpu.utils.metrics import BlsPoolMetrics

pytestmark = pytest.mark.smoke


@pytest.fixture
def server():
    proc = NetworkProcessor(lambda m: None, [lambda: False])
    proc.queues[GossipType.beacon_attestation].add(
        PendingGossipMessage(GossipType.beacon_attestation, None)
    )
    metrics = BlsPoolMetrics()
    metrics.success_jobs.inc(7)
    handlers = DefaultHandlers(
        genesis_time=1606824023,
        genesis_validators_root=b"\x4b" * 32,
        processor=proc,
        bls_metrics=metrics,
        spec={"SECONDS_PER_SLOT": 12},
    )
    srv = BeaconApiServer(handlers)
    srv.listen()
    yield srv
    srv.close()


def client(srv):
    return ApiClient([f"http://127.0.0.1:{srv.port}"])


def test_route_matching():
    r, p = match("GET", "/eth/v2/beacon/blocks/head")
    assert r.handler == "get_block" and p == {"block_id": "head"}
    assert match("GET", "/eth/v1/nope") is None
    assert match("POST", "/eth/v1/node/health") is None  # wrong method


def test_node_and_beacon_routes(server):
    c = client(server)
    assert c.get_version().startswith("lodestar-tpu")
    assert c.get_syncing()["is_syncing"] is False
    g = c.get_genesis()
    assert g["genesis_time"] == "1606824023"
    assert g["genesis_validators_root"] == "0x" + "4b" * 32
    assert c.get_spec()["SECONDS_PER_SLOT"] == "12"


def test_lodestar_introspection(server):
    c = client(server)
    q = c.dump_gossip_queue("beacon_attestation")
    assert q["length"] == 1
    m = c.get_bls_metrics()
    assert m["success_jobs"] == 7.0


def test_unknown_gossip_type_and_unimplemented(server):
    c = client(server)
    with pytest.raises(ApiError) as err:
        c.dump_gossip_queue("not_a_topic")
    assert err.value.status == 400
    with pytest.raises(ApiError) as err:
        c._request("GET", "/eth/v2/beacon/blocks/head")
    assert err.value.status == 501  # handler not implemented in defaults


def test_client_falls_back_across_base_urls(server):
    c = ApiClient(
        ["http://127.0.0.1:1", f"http://127.0.0.1:{server.port}"], timeout=2
    )
    assert c.get_version().startswith("lodestar-tpu")
