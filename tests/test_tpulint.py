"""tpulint: the tier-1 static-analysis gate + analyzer goldens.

Two jobs: (1) the REPO gate — `lodestar_tpu/` must produce zero
non-suppressed findings, in bounded wall-clock, so every tier-1 pass
re-proves the kernel invariants (Mosaic purity, gather-freedom,
export-cache fingerprint completeness); (2) analyzer correctness —
each rule fires on its known-bad fixture and stays silent on the
known-clean twin (tests/fixtures/tpulint/), suppressions parse with
mandatory reasons, JSON output keeps its shape.
"""

import json
import os
import subprocess
import sys
import time
from pathlib import Path

import pytest

from lodestar_tpu.analysis import analyze, findings_to_json

pytestmark = pytest.mark.smoke

REPO = Path(__file__).resolve().parent.parent
FIXTURES = REPO / "tests" / "fixtures" / "tpulint"


@pytest.fixture(scope="module")
def fixture_findings():
    return analyze([str(FIXTURES)])


def _by_file(findings, name):
    return [f for f in findings if Path(f.path).name == name]


def _rules(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# the repo gate
# ---------------------------------------------------------------------------


def test_repo_tree_is_clean_and_fast():
    t0 = time.monotonic()
    findings = analyze([str(REPO / "lodestar_tpu")])
    elapsed = time.monotonic() - t0
    active = [f for f in findings if not f.suppressed]
    assert not active, "tpulint findings in lodestar_tpu/:\n" + "\n".join(
        f"{f.path}:{f.line}: {f.rule}: {f.message}" for f in active
    )
    assert elapsed < 10.0, f"tpulint full-tree pass took {elapsed:.1f}s"


def test_dev_and_tests_trees_are_clean():
    """ROADMAP follow-up (ISSUE 8): the tier-1 gate lints dev/ and
    tests/ alongside lodestar_tpu/ (dev/lint.sh dev tests).  The
    tpulint fixture package is the ONE tree allowed findings — it
    exists to contain them."""
    findings = analyze([str(REPO / "dev"), str(REPO / "tests")])
    active = [
        f
        for f in findings
        if not f.suppressed
        and not f.path.startswith("tests/fixtures/tpulint")
    ]
    assert not active, "tpulint findings in dev//tests/:\n" + "\n".join(
        f"{f.path}:{f.line}: {f.rule}: {f.message}" for f in active
    )


def test_cli_exits_zero_on_repo_and_nonzero_on_fixtures():
    ok = subprocess.run(
        [sys.executable, "-m", "lodestar_tpu.analysis", "lodestar_tpu"],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert ok.returncode == 0, ok.stdout + ok.stderr
    bad = subprocess.run(
        [
            sys.executable,
            "-m",
            "lodestar_tpu.analysis",
            "--json",
            str(FIXTURES),
        ],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert bad.returncode == 1, bad.stdout + bad.stderr
    payload = json.loads(bad.stdout)
    assert payload["counts"]["active"] > 0


# ---------------------------------------------------------------------------
# per-rule goldens (positive + negative per rule)
# ---------------------------------------------------------------------------


def test_kernel_purity_positive(fixture_findings):
    hits = _by_file(fixture_findings, "purity_bad.py")
    msgs = [f.message for f in hits if f.rule == "kernel-purity"]
    assert any("array constant" in m for m in msgs), msgs
    assert any(".item()" in m for m in msgs), msgs
    assert any("int(x)" in m for m in msgs), msgs
    assert any("Python `if`" in m for m in msgs), msgs


def test_kernel_purity_negative(fixture_findings):
    assert not _by_file(fixture_findings, "purity_ok.py")


def test_gather_hazard_positive(fixture_findings):
    hits = _by_file(fixture_findings, "gather_bad.py")
    msgs = [f.message for f in hits if f.rule == "gather-hazard"]
    assert any("boolean-mask" in m for m in msgs), msgs
    assert any("2-D advanced" in m for m in msgs), msgs


def test_gather_hazard_negative(fixture_findings):
    assert not _by_file(fixture_findings, "gather_ok.py")


def test_dtype_discipline_positive(fixture_findings):
    hits = _by_file(fixture_findings, "dtype_bad.py")
    msgs = [f.message for f in hits if f.rule == "dtype-discipline"]
    assert any("jnp.zeros" in m for m in msgs), msgs
    assert any("jnp.arange" in m for m in msgs), msgs
    assert any("64-bit int literal" in m for m in msgs), msgs


def test_dtype_discipline_negative(fixture_findings):
    assert not _by_file(fixture_findings, "dtype_ok.py")


def test_node_hygiene_positive(fixture_findings):
    hits = _by_file(fixture_findings, "hygiene_bad.py")
    msgs = [f.message for f in hits if f.rule == "node-hygiene"]
    assert any("bare `except:`" in m for m in msgs), msgs
    assert any("time.sleep" in m for m in msgs), msgs
    assert any("jax.device_get" in m for m in msgs), msgs
    assert any("block_until_ready" in m for m in msgs), msgs
    # blocking observability sinks in async bodies — both the
    # attribute form and the bare-imported form
    assert any("dump_chrome_trace()" in m for m in msgs), msgs
    assert any("write_chrome_trace()" in m for m in msgs), msgs


def test_node_hygiene_sync_verdict_waits(fixture_findings):
    """ISSUE 19 satellite: synchronous verdict waits in network/ async
    handler bodies — `.result()` on a verify future plus both forms of
    a direct blocking verify call — are flagged toward the
    DeferredVerdict continuation seam."""
    hits = _by_file(fixture_findings, "hygiene_bad.py")
    msgs = [
        f.message
        for f in hits
        if f.rule == "node-hygiene" and "synchronous verdict wait" in f.message
    ]
    assert any(".result()" in m for m in msgs), msgs
    assert any("verify_signature_sets()" in m for m in msgs), msgs
    assert any(
        "verify_signature_sets_individually()" in m for m in msgs
    ), msgs
    assert all("DeferredVerdict continuation" in m for m in msgs), msgs


def test_node_hygiene_negative(fixture_findings):
    assert not _by_file(fixture_findings, "hygiene_ok.py")


def test_device_dispatch_bypass_positive(fixture_findings):
    """ISSUE 14 satellite: direct device-dispatch calls in bls/ async
    bodies that bypass the breaker supervisor seam are flagged — both
    the attribute form and the bare-imported form."""
    hits = _by_file(fixture_findings, "dispatch_bad.py")
    msgs = [f.message for f in hits if f.rule == "node-hygiene"]
    assert any(
        "verify_each_device_wire()" in m
        and "bypasses the breaker supervisor seam" in m
        for m in msgs
    ), msgs
    assert any("load_or_export()" in m for m in msgs), msgs
    assert len(msgs) == 2, msgs


def test_device_dispatch_bypass_allowlist(fixture_findings):
    """The supervisor module itself (and kernels/) may dispatch
    directly; sync functions are out of scope everywhere."""
    hits = [
        f
        for f in _by_file(fixture_findings, "supervisor.py")
        if f.rule == "node-hygiene"
    ]
    assert not hits, [f.message for f in hits]


def test_cache_hygiene_positive(fixture_findings):
    """ISSUE 15 satellite: unbounded module/instance-level containers
    in chain/network/bls modules — the block_state_roots bug class —
    are flagged: the module-level dict plus all three class attrs."""
    hits = _by_file(fixture_findings, "cache_bad.py")
    msgs = [f.message for f in hits if f.rule == "cache-hygiene"]
    assert any("module-level `_SEEN_ROOTS`" in m for m in msgs), msgs
    assert any("`self.block_map`" in m for m in msgs), msgs
    assert any("`self.recent`" in m for m in msgs), msgs
    assert any("`self.ordered`" in m for m in msgs), msgs
    assert len(msgs) == 4, msgs


def test_cache_hygiene_negative(fixture_findings):
    """Bounded shapes stay silent: max_* ctor arg, direct shrink
    methods, alias-based pruning (incl. the getattr form), rebuild-by-
    reassignment, and never-grown plain state."""
    assert not _by_file(fixture_findings, "cache_ok.py")


def test_cache_hygiene_covers_proofs_dir(fixture_findings):
    """ISSUE 17 satellite: the proofs/ package joined the cache-hygiene
    gate — an unbounded proof-bundle memo (grown per request, never
    evicted/invalidated/drained) is exactly the bug class."""
    hits = _by_file(fixture_findings, "proof_cache_bad.py")
    msgs = [f.message for f in hits if f.rule == "cache-hygiene"]
    assert any("`self.bundles`" in m for m in msgs), msgs
    assert any("`self.recent_keys`" in m for m in msgs), msgs
    assert len(msgs) == 2, msgs


def test_cache_hygiene_proofs_negative(fixture_findings):
    """The governed shapes (max_* bound + drain, event invalidation)
    stay silent — the contract ProofBundleCache itself follows."""
    assert not _by_file(fixture_findings, "proof_cache_ok.py")


def test_metric_hygiene_positive(fixture_findings):
    hits = _by_file(fixture_findings, "metrics_bad.py")
    msgs = [f.message for f in hits if f.rule == "metric-hygiene"]
    assert any("lacks the lodestar_ prefix" in m for m in msgs), msgs
    assert any("re-registered as gauge" in m for m in msgs), msgs
    assert any(
        "label 'peer_id'" in m and "unbounded-cardinality" in m
        for m in msgs
    ), msgs
    assert any("label value built from `peer_id`" in m for m in msgs), msgs
    assert len(msgs) == 4, msgs


def test_metric_hygiene_negative(fixture_findings):
    assert not _by_file(fixture_findings, "metrics_ok.py")


def test_fingerprint_completeness_positive(fixture_findings):
    hits = _by_file(fixture_findings, "entries_bad.py")
    msgs = [
        f.message for f in hits if f.rule == "fingerprint-completeness"
    ]
    # the seeded violation: BOTH the traced module and its transitive
    # dep must be reported missing
    assert any("pkg.extmod" in m for m in msgs), msgs
    assert any("pkg.extdep" in m for m in msgs), msgs


def test_fingerprint_completeness_multi_entry_point(fixture_findings):
    """RLC-style sibling entries over one traced module graph: the
    entry with a PARTIAL source set is reported (for exactly the
    missing module), and its complete sibling neither masks it nor
    produces findings of its own."""
    hits = _by_file(fixture_findings, "entries_bad.py")
    msgs = [
        f.message for f in hits if f.rule == "fingerprint-completeness"
    ]
    each = [m for m in msgs if "fixture_rlc_each" in m]
    assert each and all("pkg.extdep" in m for m in each), msgs
    assert not any("pkg.extmod" in m for m in each), msgs
    assert not any("fixture_rlc_batch" in m for m in msgs), msgs


def test_fingerprint_completeness_negative(fixture_findings):
    # registering the traced modules clears the finding; in-kernels
    # traced functions need no registration
    assert not _by_file(fixture_findings, "entries_ok.py")


def test_bucket_coverage_positive(fixture_findings):
    """bucketed_entry tables that can't be audited offline are errors:
    dynamic, empty, and misordered tables each fire exactly one
    bucket finding on their own entry."""
    hits = _by_file(fixture_findings, "entries_bad.py")
    msgs = [
        f.message for f in hits if f.rule == "fingerprint-completeness"
    ]
    dyn = [m for m in msgs if "fixture_bucketed_dynamic" in m]
    assert dyn == [m for m in dyn if "not statically resolvable" in m]
    assert len(dyn) == 1, msgs
    empty = [m for m in msgs if "fixture_bucketed_empty" in m]
    assert len(empty) == 1 and "empty bucket table" in empty[0], msgs
    mis = [m for m in msgs if "fixture_bucketed_misordered" in m]
    assert len(mis) == 1 and "strictly increasing" in mis[0], msgs


def test_bucket_tables_resolve_statically():
    """The clean fixtures' three bucket-table spellings (call-site
    literal with arithmetic, local module constant built by tuple
    concatenation, constant imported from another module) all resolve
    to the runtime values."""
    from lodestar_tpu.analysis.engine import Project

    p = Project()
    p.load_paths([str(FIXTURES)])
    by_name = {e.name: e for e in p.export_entries}
    assert by_name["fixture_bucketed_literal_ok"].buckets == (64, 256)
    assert by_name["fixture_bucketed_const_ok"].buckets == (128, 512, 2048)
    assert by_name["fixture_bucketed_imported_ok"].buckets == (16, 64, 512)
    # plain register_entry sites carry no bucket table at all
    assert by_name["fixture_span_update_ok"].buckets is None
    assert not by_name["fixture_span_update_ok"].unresolved_buckets


def test_repo_bucket_tables_match_runtime_registry():
    """The shipped bucketed entries' statically-resolved tables must
    equal what kernels/export_cache.py registers at import (the lint
    gate audits exactly the shapes export_registered pre-traces)."""
    from lodestar_tpu.analysis.engine import Project
    from lodestar_tpu.kernels import export_cache as EC

    p = Project()
    p.load_paths([str(REPO / "lodestar_tpu")])
    static = {
        e.name: e.buckets
        for e in p.export_entries
        if e.buckets is not None
    }
    runtime = EC.entry_buckets()
    assert static == runtime, (static, runtime)
    # the HTR acceptance shapes: all four headline pair buckets
    assert static["htr_hash_pairs"] == (
        128 * 1024, 512 * 1024, 1024 * 1024, 2 * 1024 * 1024,
    )


# ---------------------------------------------------------------------------
# the concurrency tier (ISSUE 20)
# ---------------------------------------------------------------------------


def _rule_msgs(findings, name, rule):
    return [f.message for f in _by_file(findings, name) if f.rule == rule]


def test_lock_order_positive(fixture_findings):
    """Direct inversion, inversion hidden behind a call, and both
    reports of the plain-Lock self-deadlock (the direct re-acquire in
    the helper and the call edge from the outer frame)."""
    msgs = _rule_msgs(fixture_findings, "lockorder_bad.py", "lock-order")
    assert any(
        "lock-order inversion" in m and "Transfer._lock_a" in m
        for m in msgs
    ), msgs
    assert any(
        "lock-order inversion" in m and "Chained._back" in m for m in msgs
    ), msgs
    assert any(
        "self-deadlock" in m
        and "via call to `SelfDeadlock._helper`" in m
        for m in msgs
    ), msgs
    assert len(msgs) == 4, msgs


def test_lock_order_negative(fixture_findings):
    """Consistent ordering, re-entrant RLock, and a lock handed to a
    helper function stay silent."""
    assert not _by_file(fixture_findings, "lockorder_ok.py")


def test_guarded_by_positive(fixture_findings):
    """Fields written under a lock on a worker-thread / clock-tick
    root but touched lock-free from the external-caller root."""
    msgs = _rule_msgs(fixture_findings, "guardedby_bad.py", "guarded-by")
    assert any(
        "`self._count`" in m
        and "read lock-free in `Counter.snapshot`" in m
        for m in msgs
    ), msgs
    assert any(
        "`self._count`" in m
        and "written lock-free in `Counter.reset`" in m
        for m in msgs
    ), msgs
    assert any(
        "`self._slot`" in m and "TickState.describe" in m for m in msgs
    ), msgs
    assert len(msgs) == 3, msgs


def test_guarded_by_negative(fixture_findings):
    """Locked reads, init-only config, single-root classes, and the
    `_locked`-suffix context convention stay silent."""
    assert not _by_file(fixture_findings, "guardedby_ok.py")


def test_async_lock_safety_positive(fixture_findings):
    msgs = _rule_msgs(
        fixture_findings, "asyncsafety_bad.py", "async-lock-safety"
    )
    assert any("user callback `on_drop`" in m for m in msgs), msgs
    assert any("time.sleep()" in m for m in msgs), msgs
    assert any(".result()" in m for m in msgs), msgs
    assert any("settles a future" in m for m in msgs), msgs
    assert any("acquired in coroutine" in m for m in msgs), msgs
    assert len(msgs) == 5, msgs


def test_async_lock_safety_negative(fixture_findings):
    """The swap-and-fire contract (callback captured under the lock,
    invoked after release), blocking work outside the critical
    section, and Condition wait/notify stay silent."""
    assert not _by_file(fixture_findings, "asyncsafety_ok.py")


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------


def test_suppression_with_reason_suppresses(fixture_findings):
    hits = _by_file(fixture_findings, "suppress.py")
    sup = [f for f in hits if f.suppressed]
    assert len(sup) == 1
    assert sup[0].rule == "dtype-discipline"
    assert "proves suppression works" in sup[0].suppress_reason


def test_suppression_without_reason_is_a_finding(fixture_findings):
    hits = _by_file(fixture_findings, "suppress.py")
    bad = [
        f
        for f in hits
        if f.rule == "bad-suppression" and "without a reason" in f.message
    ]
    assert len(bad) == 1
    # ... and the underlying finding stays ACTIVE
    active_dtype = [
        f
        for f in hits
        if f.rule == "dtype-discipline" and not f.suppressed
    ]
    assert len(active_dtype) == 1


def test_unknown_rule_suppression_is_a_finding(fixture_findings):
    hits = _by_file(fixture_findings, "suppress.py")
    assert any(
        f.rule == "bad-suppression" and "made-up-rule" in f.message
        for f in hits
    )


# ---------------------------------------------------------------------------
# output shapes
# ---------------------------------------------------------------------------


def test_json_output_shape(fixture_findings):
    payload = json.loads(findings_to_json(fixture_findings))
    assert payload["version"] == 1
    assert set(payload["counts"]) == {
        "active",
        "suppressed",
        "errors",
        "warnings",
    }
    for f in payload["findings"]:
        assert set(f) == {
            "rule",
            "path",
            "line",
            "col",
            "severity",
            "message",
            "suppressed",
            "suppress_reason",
        }
        assert f["severity"] in ("error", "warning")
        assert f["line"] >= 1
    assert payload["counts"]["active"] == sum(
        1 for f in payload["findings"] if not f["suppressed"]
    )


def test_sarif_output_shape(fixture_findings):
    """ISSUE 20 satellite: SARIF 2.1.0 golden shape — tool metadata,
    per-rule default levels, 1-based columns, and suppressed findings
    carried as `inSource` suppressions with their justification."""
    from lodestar_tpu.analysis import findings_to_sarif

    doc = json.loads(findings_to_sarif(fixture_findings))
    assert doc["version"] == "2.1.0"
    assert "sarif-2.1.0" in doc["$schema"]
    (run,) = doc["runs"]
    driver = run["tool"]["driver"]
    assert driver["name"] == "tpulint"
    rule_ids = {r["id"] for r in driver["rules"]}
    for rid in (
        "lock-order",
        "guarded-by",
        "async-lock-safety",
        "kernel-purity",
        "bad-suppression",
        "parse-error",
    ):
        assert rid in rule_ids, rid
    for r in driver["rules"]:
        assert r["defaultConfiguration"]["level"] in ("error", "warning")
    assert len(run["results"]) == len(fixture_findings)
    by_key = {}
    for res in run["results"]:
        assert res["level"] in ("error", "warning")
        assert res["message"]["text"]
        (loc,) = res["locations"]
        phys = loc["physicalLocation"]
        assert phys["artifactLocation"]["uri"].endswith(".py")
        assert phys["region"]["startLine"] >= 1
        assert phys["region"]["startColumn"] >= 1  # SARIF is 1-based
        by_key.setdefault(res["ruleId"], []).append(res)
    # the one reasoned suppression in suppress.py surfaces as an
    # inSource suppression with its justification
    sup = [
        r
        for rs in by_key.values()
        for r in rs
        if r.get("suppressions")
    ]
    assert any(
        s["suppressions"][0]["kind"] == "inSource"
        and "proves suppression works"
        in s["suppressions"][0]["justification"]
        for s in sup
    ), sup
    # columns are shifted exactly +1 from the Finding model
    col0 = {(f.path, f.line, f.col) for f in fixture_findings}
    for res in run["results"]:
        phys = res["locations"][0]["physicalLocation"]
        key = (
            phys["artifactLocation"]["uri"],
            phys["region"]["startLine"],
            phys["region"]["startColumn"] - 1,
        )
        assert key in col0, key


def test_cli_sarif_and_profile_rules():
    res = subprocess.run(
        [
            sys.executable,
            "-m",
            "lodestar_tpu.analysis",
            "--sarif",
            "--profile-rules",
            "lodestar_tpu/analysis",
        ],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert res.returncode == 0, res.stdout + res.stderr
    doc = json.loads(res.stdout)
    assert doc["version"] == "2.1.0"
    assert "rule timings" in res.stderr
    # every rule (and the parse pass) reports a timing line
    for name in ("(parse+index)", "lock-order", "kernel-purity"):
        assert name in res.stderr, res.stderr
    both = subprocess.run(
        [
            sys.executable,
            "-m",
            "lodestar_tpu.analysis",
            "--json",
            "--sarif",
            "lodestar_tpu/analysis",
        ],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert both.returncode == 2


def test_findings_are_sorted_and_deduped(fixture_findings):
    keys = [
        (f.path, f.line, f.col, f.rule, f.message)
        for f in fixture_findings
    ]
    assert keys == sorted(keys)
    assert len(keys) == len(set(keys)), "duplicate findings emitted"


# ---------------------------------------------------------------------------
# engine robustness (review regressions)
# ---------------------------------------------------------------------------


def test_broken_file_is_a_finding_not_a_crash(tmp_path):
    (tmp_path / "ok.py").write_text("X = 1\n")
    (tmp_path / "broken.py").write_text("def f(:\n")
    findings = analyze([str(tmp_path)])
    pe = [f for f in findings if f.rule == "parse-error"]
    assert len(pe) == 1 and "broken.py" in pe[0].path
    assert pe[0].severity == "error"


def test_jit_decorated_methods_are_traced(tmp_path):
    (tmp_path / "meth.py").write_text(
        "import jax\n"
        "import jax.numpy as jnp\n\n\n"
        "class Stepper:\n"
        "    @jax.jit\n"
        "    def step(self, x):\n"
        "        return x + jnp.zeros((4,))\n"
    )
    findings = analyze([str(tmp_path)])
    assert any(
        f.rule == "dtype-discipline" and "Stepper.step" in f.message
        for f in findings
    ), [f.message for f in findings]


def test_changed_mode_paths_are_repo_root_anchored():
    from lodestar_tpu.analysis.__main__ import _git_changed_files

    changed = _git_changed_files()
    assert changed is not None
    # this test file is modified/untracked in the working tree of this
    # PR; regardless, every returned path must exist (the subdir-cwd
    # bug produced phantom cwd-relative paths)
    for p in changed:
        assert Path(p).is_absolute()
        assert Path(p).exists(), p


def test_changed_mode_reports_only_new_findings(tmp_path):
    """ISSUE 20 satellite: --changed is a pre-push gate — it exits
    nonzero on NEW findings only, baselining each git-touched file
    against its HEAD revision, so pre-existing debt in an edited file
    never fails the push."""
    env = dict(os.environ, PYTHONPATH=str(REPO))

    def git(*a):
        subprocess.run(
            ["git", *a], cwd=tmp_path, check=True, capture_output=True
        )

    git("init", "-q")
    git("config", "user.email", "t@example.com")
    git("config", "user.name", "t")
    mod = tmp_path / "mod.py"
    mod.write_text(
        "import threading\n"
        "import time\n\n\n"
        "class Box:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n\n"
        "    def one(self):\n"
        "        with self._lock:\n"
        "            time.sleep(0.1)\n"
    )
    git("add", "-A")
    git("commit", "-q", "-m", "seed")

    def run(*extra):
        return subprocess.run(
            [sys.executable, "-m", "lodestar_tpu.analysis", *extra, "."],
            cwd=tmp_path,
            capture_output=True,
            text=True,
            env=env,
            timeout=120,
        )

    # nothing touched: --changed is clean even though the tree is not
    clean = run("--changed")
    assert clean.returncode == 0, clean.stdout + clean.stderr

    # an edit ADDING a finding: only the new one is reported, the
    # pre-existing one is hidden (and counted on stderr)
    mod.write_text(
        mod.read_text()
        + "\n    def two(self, fut):\n"
        "        with self._lock:\n"
        "            fut.set_result(True)\n"
    )
    res = run("--changed")
    assert res.returncode == 1, res.stdout + res.stderr
    assert ".set_result()" in res.stdout
    assert "time.sleep" not in res.stdout
    assert "1 pre-existing finding(s) hidden" in res.stderr

    # an untracked file has no baseline: everything in it is new
    (tmp_path / "fresh.py").write_text(
        "import threading\n\n\n"
        "class Fresh:\n"
        "    def __init__(self, on_done):\n"
        "        self.on_done = on_done\n"
        "        self._lock = threading.Lock()\n\n"
        "    def fire(self):\n"
        "        with self._lock:\n"
        "            self.on_done(1)\n"
    )
    res2 = run("--changed")
    assert res2.returncode == 1
    assert "fresh.py" in res2.stdout and "on_done" in res2.stdout

    # the full (non-changed) run still sees the pre-existing debt
    full = run()
    assert full.returncode == 1
    assert "time.sleep" in full.stdout

    # committing everything makes --changed clean again
    git("add", "-A")
    git("commit", "-q", "-m", "accepted debt")
    assert run("--changed").returncode == 0


def test_bare_source_suffix_does_not_cover(tmp_path):
    """Declaring a bare final segment ('batch') must NOT satisfy the
    fingerprint rule — export_cache could not resolve it to a file."""
    from lodestar_tpu.analysis.rules import FingerprintCompletenessRule

    covers = FingerprintCompletenessRule._covers
    assert covers("lodestar_tpu.slasher.batch", "lodestar_tpu.slasher.batch")
    assert covers("pkg.extmod", "fixtures.tpulint.pkg.extmod")
    assert covers("lodestar_tpu.slasher.batch", "slasher.batch")
    assert not covers("batch", "lodestar_tpu.slasher.batch")
    assert not covers("extmod", "pkg.extmod")
