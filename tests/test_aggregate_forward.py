"""Aggregate-forward gossip (ISSUE 19, network/forwarding.py).

Four layers of the tentpole contract:

  1. `DeferredVerdict` / `DeferredForwardQueue` semantics — resolution
     fires continuations exactly once, drop (slot expiry, backpressure
     shed) WINS over a late resolution so a stale verdict neither
     forwards nor scores, and a shed charges the publisher (P7) while
     releasing its deferred slot;
  2. `AggregateForwarder` re-packing — verified disjoint layers map
     back onto committee aggregation bits, publish as
     PACKED_AGGREGATOR_INDEX `SignedAggregateAndProof`s that never echo
     to the publisher (the self-publish seen-cache rule), and the best
     (largest) pack per vote serves the local aggregation duty;
  3. the async subnet path end-to-end over real crypto — the verdict
     defers through the pipeline standard lane (the raw verifier is
     verifiably NOT called on the flood path), accept-side effects land
     on resolution, REJECTs score through the bus continuation, and
     `LODESTAR_TPU_BLS_AGGFWD=0` restores the raw-sync behaviour;
  4. breaker interplay — a breaker trip mid-defer resolves the verdict
     via the host fallback path with the forward continuation still
     firing (degraded, not dropped).
"""

import dataclasses
import threading
import time

import pytest

from lodestar_tpu import params
from lodestar_tpu import types as T
from lodestar_tpu.bls.pipeline import BlsVerificationPipeline
from lodestar_tpu.bls.signature_set import WireSignatureSet
from lodestar_tpu.bls.single_thread import CpuBlsVerifier
from lodestar_tpu.bls.verifier import VerifyOptions
from lodestar_tpu.chain.chain import BeaconChain
from lodestar_tpu.chain.validation import GossipAction, GossipValidationError
from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
from lodestar_tpu.crypto import bls as B
from lodestar_tpu.crypto import curves as C
from lodestar_tpu.network.forwarding import (
    PACKED_AGGREGATOR_INDEX,
    AggregateForwarder,
    DeferredForwardQueue,
    DeferredVerdict,
    aggfwd_enabled,
)
from lodestar_tpu.network.gossip import (
    GossipTopicName,
    InMemoryGossipBus,
    decode_message,
    encode_message,
    topic_string,
)
from lodestar_tpu.network.gossip_handlers import GossipHandlers
from lodestar_tpu.params import ForkName
from lodestar_tpu.state_transition import create_genesis_state
from lodestar_tpu.state_transition.accessors import get_beacon_committee
from lodestar_tpu.validator import ValidatorStore

pytestmark = pytest.mark.smoke

N_KEYS = 64


def _wait_for(pred, timeout=20.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.005)
    return False


# ---------------------------------------------------------------------------
# DeferredVerdict
# ---------------------------------------------------------------------------


def test_deferred_verdict_resolution_fires_continuations_once():
    d = DeferredVerdict(slot=3)
    got = []
    d.on_resolve(got.append)
    d.on_resolve(got.append)
    assert not d.resolved
    d.resolve(None)
    assert d.resolved and got == [None, None]
    d.resolve(GossipAction.REJECT)  # idempotent: first resolution wins
    assert d.verdict is None and got == [None, None]
    # a continuation registered AFTER resolution fires immediately
    d.on_resolve(got.append)
    assert got == [None, None, None]


def test_deferred_verdict_drop_wins_over_late_resolution():
    d = DeferredVerdict(slot=3)
    got = []
    d.on_resolve(got.append)
    assert d.drop("expired") is True
    assert d.drop_reason == "expired"
    d.resolve(GossipAction.REJECT)  # the late verdict lands into nothing
    assert got == []
    d.on_resolve(got.append)  # nor does any later registration fire
    assert got == []


def test_deferred_verdict_drop_after_resolution_is_too_late():
    d = DeferredVerdict()
    d.resolve(None)
    assert d.drop("expired") is False
    assert not d.dropped


# ---------------------------------------------------------------------------
# DeferredForwardQueue
# ---------------------------------------------------------------------------


class _ShedScorer:
    def __init__(self):
        self.backpressure = []

    def on_backpressure_drop(self, peer_id, topic=None):
        self.backpressure.append((peer_id, topic))


def test_queue_expiry_drops_late_verdict():
    """A verdict resolving after its slot's forward window DROPS: no
    forward continuation fires, no scoring, the entry is gone."""
    q = DeferredForwardQueue()
    d = DeferredVerdict(slot=2)
    q.register(d, peer_id="p1", topic="beacon_attestation_0")
    forwarded = []
    d.on_resolve(forwarded.append)
    q.on_clock_slot(3)  # still inside slot + DEFERRED_EXPIRY_SLOTS
    assert len(q) == 1 and not d.dropped
    q.on_clock_slot(4)  # out of the window
    assert len(q) == 0 and d.dropped and d.drop_reason == "expired"
    d.resolve(None)  # the verdict lands late...
    assert forwarded == []  # ...and forwards nothing
    s = q.stats_snapshot()
    assert s["expired"] == 1 and s["fired"] == 0


def test_queue_shed_charges_publisher_and_releases_slot():
    """At capacity the OLDEST deferral is shed: its slot frees up, its
    continuations never fire, and the publisher is charged (P7)."""
    scorer = _ShedScorer()
    q = DeferredForwardQueue(scorer=scorer, max_entries=2)
    oldest = DeferredVerdict(slot=1)
    q.register(oldest, peer_id="flooder", topic="beacon_attestation_7")
    forwarded = []
    oldest.on_resolve(forwarded.append)
    q.register(DeferredVerdict(slot=1), peer_id="p2", topic="t")
    q.register(DeferredVerdict(slot=1), peer_id="p3", topic="t")
    assert len(q) == 2  # the slot was released
    assert oldest.dropped and oldest.drop_reason == "shed"
    assert scorer.backpressure == [("flooder", "beacon_attestation_7")]
    oldest.resolve(None)
    assert forwarded == []
    assert q.stats_snapshot()["shed"] == 1


def test_queue_normal_resolution_cleans_up_entry():
    q = DeferredForwardQueue()
    d = DeferredVerdict(slot=5)
    q.register(d, peer_id="p", topic="t")
    assert len(q) == 1
    d.resolve(None)
    assert len(q) == 0
    s = q.stats_snapshot()
    assert s["fired"] == 1 and s["expired"] == 0 and s["shed"] == 0


def test_bus_scoring_continuation_suppressed_by_drop():
    """The bus scores a deferred verdict when it lands — unless the
    deferral was dropped first (a stale verdict must not score)."""

    class _Scorer:
        def __init__(self):
            self.verdicts = []

        def is_banned(self, peer_id):
            return False

        def on_verdict(self, peer_id, topic, verdict):
            self.verdicts.append((peer_id, verdict))

    for drop_first in (False, True):
        bus = InMemoryGossipBus()
        scorer = _Scorer()
        d = DeferredVerdict(slot=0)

        def handler(topic, data, peer_id, d=d):
            return d

        bus.subscribe("b", "topic/x", handler, scorer=scorer)
        bus.publish("a", "topic/x", b"payload-%d" % drop_first)
        assert scorer.verdicts == []  # nothing scored at delivery time
        if drop_first:
            d.drop("expired")
        d.resolve(GossipAction.REJECT)
        expected = [] if drop_first else [("a", GossipAction.REJECT)]
        assert scorer.verdicts == expected


# ---------------------------------------------------------------------------
# AggregateForwarder
# ---------------------------------------------------------------------------

DIGEST = b"\xaa\xbb\xcc\xdd"


def _data(slot=1, index=0):
    zero = b"\x00" * 32
    return {
        "slot": slot,
        "index": index,
        "beacon_block_root": zero,
        "source": {"epoch": 0, "root": zero},
        "target": {"epoch": 0, "root": zero},
    }


def _forwarder_with_recorder():
    bus = InMemoryGossipBus()
    received = []
    topic = topic_string(DIGEST, GossipTopicName.beacon_aggregate_and_proof)
    bus.subscribe("rx", topic, lambda t, d: received.append(d))
    fwd = AggregateForwarder(bus=bus, node_id="tx", fork_digest=DIGEST)
    return fwd, bus, received, topic


def test_forwarder_repacks_layer_onto_committee_bits():
    fwd, _bus, received, _topic = _forwarder_with_recorder()
    root = b"\x11" * 32
    data = _data()
    committee = (5, 9, 12, 30)
    fwd.register_root(root, 1, data, committee)
    sig = b"\x42" * 96
    fwd.on_layer_verified(
        WireSignatureSet.aggregate((9, 30), root, sig), 2
    )
    assert len(received) == 1
    signed = T.SignedAggregateAndProof.deserialize(
        decode_message(received[0])
    )
    msg = signed["message"]
    assert int(msg["aggregator_index"]) == PACKED_AGGREGATOR_INDEX
    agg = msg["aggregate"]
    assert list(agg["aggregation_bits"]) == [False, True, False, True]
    assert bytes(agg["signature"]) == sig
    assert int(agg["data"]["slot"]) == 1
    s = fwd.stats_snapshot()
    assert s["published"] == 1 and s["members_forwarded"] == 2
    assert s["bytes_published"] == len(received[0])


def test_forwarder_skips_unpackable_layers():
    fwd, _bus, received, _topic = _forwarder_with_recorder()
    root = b"\x22" * 32
    fwd.register_root(root, 1, _data(), (1, 2, 3))
    # single-member "layer": no bandwidth win, never published
    fwd.on_layer_verified(WireSignatureSet.single(2, root, b"\x01" * 96), 1)
    # unknown signing root: nothing registered it
    fwd.on_layer_verified(
        WireSignatureSet.aggregate((1, 2), b"\x33" * 32, b"\x02" * 96), 2
    )
    # indices escaping the registered committee: refuse to fabricate bits
    fwd.on_layer_verified(
        WireSignatureSet.aggregate((2, 7), root, b"\x03" * 96), 2
    )
    assert received == []
    assert fwd.stats_snapshot()["skipped"] == 2


def test_forwarder_keeps_best_pack_for_aggregation_duty():
    fwd, _bus, received, _topic = _forwarder_with_recorder()
    root = b"\x44" * 32
    data = _data(slot=2)
    data_root = bytes(T.AttestationData.hash_tree_root(data))
    fwd.register_root(root, 2, data, (0, 1, 2, 3, 4))
    fwd.on_layer_verified(
        WireSignatureSet.aggregate((0, 1, 2), root, b"\x05" * 96), 3
    )
    fwd.on_layer_verified(  # smaller: publishes but does not displace
        WireSignatureSet.aggregate((3, 4), root, b"\x06" * 96), 2
    )
    assert len(received) == 2
    best = fwd.get_packed_aggregate(2, data_root)
    assert bytes(best["signature"]) == b"\x05" * 96
    assert fwd.get_packed_aggregate(2, b"\x99" * 32) is None
    # per-slot pruning forgets old roots and packs
    fwd.on_clock_slot(2 + 3)
    assert fwd.get_packed_aggregate(2, data_root) is None


def test_forwarder_self_publish_never_echoes_back():
    """The self-publish seen-cache rule: the publishing node is marked
    as having seen its own pack, so a relayed copy cannot come back for
    re-verification (and no peer is ever charged for it)."""
    fwd, bus, received, topic = _forwarder_with_recorder()
    echoes = []
    bus.subscribe("tx", topic, lambda t, d: echoes.append(d))
    root = b"\x55" * 32
    fwd.register_root(root, 1, _data(), (3, 4))
    fwd.on_layer_verified(
        WireSignatureSet.aggregate((3, 4), root, b"\x07" * 96), 2
    )
    assert len(received) == 1
    # "rx" relays the identical pack: the origin's seen cache eats it
    bus.publish("rx", topic, received[0])
    assert echoes == []
    assert bus.duplicates == 1


# ---------------------------------------------------------------------------
# end-to-end: the async subnet path over real crypto
# ---------------------------------------------------------------------------


class PipelinedCpuVerifier(CpuBlsVerifier):
    """CpuBlsVerifier with a begin/finish device seam so the service
    takes the handle path — any `verify_signature_sets` call is then a
    RAW-VERIFIER call the async flood path must never make."""

    max_job_sets = 128

    class _Handle:
        def __init__(self, sets, verdicts):
            self.sets = sets
            self.ok_big = True
            self.batch_retries = 0
            self.batch_sigs_success = sum(verdicts)
            self.verdicts = verdicts

    def __init__(self, pks):
        super().__init__(pubkeys=pks)
        self.raw_calls = 0

    def verify_signature_sets(self, sets, opts=None):
        self.raw_calls += 1
        return super().verify_signature_sets(sets, opts)

    def begin_job(self, sets, batchable):
        return self._Handle(
            list(sets), [self._verify_one(s) for s in sets]
        )

    def finish_job(self, handle):
        return all(handle.verdicts)


@pytest.fixture(scope="module")
def world():
    assert aggfwd_enabled()  # the default-on contract
    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG, fork_epochs={ForkName.altair: 0}
    )
    cfg = dataclasses.replace(cfg, SHARD_COMMITTEE_PERIOD=0)
    sks = [B.keygen(b"val-%d" % i) for i in range(N_KEYS)]
    pk_points = [B.sk_to_pk(sk) for sk in sks]
    pks = [C.g1_compress(p) for p in pk_points]
    genesis = create_genesis_state(cfg, pks, genesis_time=2)
    chain_a = BeaconChain(cfg, genesis)
    chain_b = BeaconChain(cfg, genesis)
    verifier = PipelinedCpuVerifier(pk_points)
    pipe = BlsVerificationPipeline(verifier, standard_wait_ms=10.0)
    handlers = GossipHandlers(chain_b, verifier, bls_service=pipe)
    handlers.deferred_forwards = DeferredForwardQueue()
    w = {
        "cfg": cfg,
        "sks": sks,
        "genesis": genesis,
        "chain_a": chain_a,
        "chain_b": chain_b,
        "verifier": verifier,
        "pipe": pipe,
        "handlers": handlers,
        "digest": cfg.fork_digest(0),
    }
    yield w
    pipe.close()


def _signed_att(w, slot, member_pos, bad_sig=False):
    data = w["chain_a"].produce_attestation_data(0, slot)
    committee = get_beacon_committee(w["genesis"], slot, 0)
    v = int(committee[member_pos])
    bits = [i == member_pos for i in range(len(committee))]
    store = ValidatorStore(w["cfg"], dict(enumerate(w["sks"])))
    if bad_sig:  # a valid signature by the WRONG key
        other = int(committee[(member_pos + 1) % len(committee)])
        sig = store.sign_attestation(other, data)
    else:
        sig = store.sign_attestation(v, data)
    return {"aggregation_bits": bits, "data": data, "signature": sig}, v


def _subnet_topic(w, subnet=0):
    return topic_string(
        w["digest"], GossipTopicName.beacon_attestation, subnet=subnet
    )


def test_async_subnet_accept_defers_and_lands_effects(world):
    """The tentpole: the handler returns an UNRESOLVED DeferredVerdict
    (the gossip loop never blocks on the 250 ms window), the verdict
    resolves ACCEPT through the pipeline, the pool/fork-choice effects
    land on resolution — and the raw verifier is never called."""
    w = world
    att, v_idx = _signed_att(w, slot=0, member_pos=0)
    payload = encode_message(T.Attestation.serialize(att))
    before_raw = w["verifier"].raw_calls
    action = w["handlers"].handle(_subnet_topic(w), payload, peer_id="peer-a")
    assert isinstance(action, DeferredVerdict)
    assert len(w["handlers"].deferred_forwards) == 1
    done = threading.Event()
    action.on_resolve(lambda verdict: done.set())
    assert done.wait(timeout=30.0)
    assert action.verdict is None  # ACCEPT
    assert v_idx in w["chain_b"].fork_choice._latest
    assert w["handlers"].results["beacon_attestation_0"]["accept"] == 1
    assert len(w["handlers"].deferred_forwards) == 0  # slot released
    # pipeline-routing proof: the flood path made ZERO raw-verifier calls
    assert w["verifier"].raw_calls == before_raw


def test_async_subnet_reject_resolves_reject(world):
    w = world
    att, _v = _signed_att(w, slot=0, member_pos=1, bad_sig=True)
    payload = encode_message(T.Attestation.serialize(att))
    action = w["handlers"].handle(_subnet_topic(w), payload, peer_id="peer-b")
    assert isinstance(action, DeferredVerdict)
    done = threading.Event()
    action.on_resolve(lambda verdict: done.set())
    assert done.wait(timeout=30.0)
    assert action.verdict == GossipAction.REJECT
    assert w["handlers"].results["beacon_attestation_0"]["reject"] == 1


def test_async_precheck_failures_stay_synchronous(world):
    """Pre-signature failures (wrong subnet, malformed bits) raise
    through the sync path exactly as before — no deferral is created."""
    w = world
    att, _v = _signed_att(w, slot=0, member_pos=1)
    payload = encode_message(T.Attestation.serialize(att))
    action = w["handlers"].handle(
        _subnet_topic(w, subnet=63), payload, peer_id="peer-c"
    )
    assert action == GossipAction.REJECT  # wrong subnet, decided now
    assert len(w["handlers"].deferred_forwards) == 0


def test_escape_hatch_restores_raw_sync_path(world, monkeypatch):
    """LODESTAR_TPU_BLS_AGGFWD=0: the handler verdict is synchronous
    and the raw verifier does the signature work, bit-for-bit the
    pre-ISSUE-19 behaviour."""
    w = world
    monkeypatch.setenv("LODESTAR_TPU_BLS_AGGFWD", "0")
    assert not aggfwd_enabled()
    sync_handlers = GossipHandlers(
        w["chain_b"], w["verifier"], bls_service=w["pipe"]
    )
    assert sync_handlers.aggfwd is False
    att, v_idx = _signed_att(w, slot=0, member_pos=1)
    payload = encode_message(T.Attestation.serialize(att))
    before_raw = w["verifier"].raw_calls
    action = sync_handlers.handle(_subnet_topic(w), payload, peer_id="peer-d")
    assert action is None  # ACCEPT, decided before returning
    assert w["verifier"].raw_calls == before_raw + 1
    assert v_idx in w["chain_b"].fork_choice._latest


def test_packed_aggregate_accept_end_to_end(world):
    """A PACKED_AGGREGATOR_INDEX re-publication verifies through the
    standard lane, marks every fresh packed attester seen, feeds fork
    choice, and lands in the aggregated pool; a duplicate IGNOREs."""
    w = world
    slot = 1
    committee = get_beacon_committee(w["genesis"], slot, 0)
    members = [int(v) for v in committee]
    assert len(members) >= 2
    data = w["chain_a"].produce_attestation_data(0, slot)
    store = ValidatorStore(w["cfg"], dict(enumerate(w["sks"])))
    sigs = [store.sign_attestation(v, data) for v in members]
    agg_sig = C.g2_compress(
        B.aggregate_signatures([C.g2_decompress(s) for s in sigs])
    )
    signed = {
        "message": {
            "aggregator_index": PACKED_AGGREGATOR_INDEX,
            "aggregate": {
                "aggregation_bits": [True] * len(members),
                "data": data,
                "signature": agg_sig,
            },
            "selection_proof": b"\x00" * 96,
        },
        "signature": b"\x00" * 96,
    }
    payload = encode_message(T.SignedAggregateAndProof.serialize(signed))
    topic = topic_string(
        w["digest"], GossipTopicName.beacon_aggregate_and_proof
    )
    action = w["handlers"].handle(topic, payload, peer_id="peer-e")
    assert isinstance(action, DeferredVerdict)
    done = threading.Event()
    action.on_resolve(lambda verdict: done.set())
    assert done.wait(timeout=30.0)
    assert action.verdict is None
    for v in members:
        assert v in w["chain_b"].fork_choice._latest
        assert w["handlers"].validators.seen_attesters.is_known(
            int(data["target"]["epoch"]), v
        )
    # every packed attester already seen -> the duplicate IGNOREs (sync)
    with pytest.raises(GossipValidationError) as ei:
        w["handlers"].validators.validate_packed_aggregate(signed)
    assert ei.value.action == GossipAction.IGNORE


def test_packed_sentinel_rejected_when_aggfwd_off(world, monkeypatch):
    """With the hatch off the sentinel falls through to the normal
    aggregate validator and REJECTs (never in any committee) — stray
    packs cannot poison a node running the escape hatch."""
    w = world
    monkeypatch.setenv("LODESTAR_TPU_BLS_AGGFWD", "0")
    sync_handlers = GossipHandlers(
        w["chain_b"], w["verifier"], bls_service=w["pipe"]
    )
    data = w["chain_a"].produce_attestation_data(0, 0)
    committee = get_beacon_committee(w["genesis"], 0, 0)
    signed = {
        "message": {
            "aggregator_index": PACKED_AGGREGATOR_INDEX,
            "aggregate": {
                "aggregation_bits": [True] * len(committee),
                "data": data,
                "signature": b"\x0c" * 96,
            },
            "selection_proof": b"\x00" * 96,
        },
        "signature": b"\x00" * 96,
    }
    payload = encode_message(T.SignedAggregateAndProof.serialize(signed))
    topic = topic_string(
        w["digest"], GossipTopicName.beacon_aggregate_and_proof
    )
    action = sync_handlers.handle(topic, payload, peer_id="peer-f")
    assert action == GossipAction.REJECT


# ---------------------------------------------------------------------------
# breaker trip mid-defer (chaos harness)
# ---------------------------------------------------------------------------


def test_breaker_trip_mid_defer_resolves_via_host_and_forwards(tmp_path):
    """A device fault between submission and resolution must DEGRADE
    the deferral, not drop it: the verdict resolves through the host
    fallback path and the forward continuation still fires."""
    from chaos.harness import FloodWorld, chaos_sig

    world = FloodWorld(tmp_path / "fr", standard_wait_ms=10.0)
    try:
        world.verifier.fault = {"begin": "backend"}  # trip on dispatch
        queue = DeferredForwardQueue()
        deferred = DeferredVerdict(slot=1)
        queue.register(deferred, peer_id="p", topic="beacon_attestation_0")
        forwarded = []
        deferred.on_resolve(forwarded.append)
        root = b"mid-defer breaker trip token 32b"
        ws = WireSignatureSet.single(3, root, chaos_sig(root, (3,)))
        fut = world.pipeline.verify_signature_sets_async(
            [ws], VerifyOptions(batchable=True)
        )

        def _on_verdict(f):
            try:
                ok = f.result()
            except Exception:
                deferred.resolve(GossipAction.IGNORE)
                return
            deferred.resolve(None if ok else GossipAction.REJECT)

        fut.add_done_callback(_on_verdict)
        assert fut.result(timeout=30.0) is True
        assert _wait_for(lambda: forwarded == [None])
        # the verdict came from the HOST path, after the breaker saw
        # the backend fault — degraded, never lost
        assert world.verifier.host_sets >= 1
        assert world.supervisor.trip_count >= 1
        assert len(queue) == 0
        assert queue.stats_snapshot()["fired"] == 1
    finally:
        world.close()
