"""Optimistic-sync fork choice: ExecutionStatus, LVH invalidation,
unrealized-checkpoint viability.

Reference behaviors: packages/fork-choice/src/protoArray/interface.ts:16-40
(ExecutionStatus / LVH responses), protoArray.ts:245-446 (validateLatestHash,
propagateInValidExecutionStatusByIndex, consensus-failure latching) and
protoArray.ts:725-753 (nodeIsViableForHead with unrealized checkpoints).
"""

import numpy as np
import pytest

from lodestar_tpu import params
from lodestar_tpu.fork_choice import (
    ExecutionStatus,
    ForkChoice,
    LVHConsensusError,
    ProtoArray,
    ProtoArrayError,
)

pytestmark = pytest.mark.smoke

SPE = params.SLOTS_PER_EPOCH


def exec_chain():
    """genesis(PreMerge) -> a(Valid) -> b(Syncing) -> (c, d)(Syncing);
    c and d compete on top of b."""
    pa = ProtoArray("genesis")
    pa.on_block(
        1, "a", "genesis", 0, 0,
        execution_status=ExecutionStatus.Valid, execution_block_hash="aa" * 32,
    )
    pa.on_block(
        2, "b", "a", 0, 0,
        execution_status=ExecutionStatus.Syncing, execution_block_hash="bb" * 32,
    )
    pa.on_block(
        3, "c", "b", 0, 0,
        execution_status=ExecutionStatus.Syncing, execution_block_hash="cc" * 32,
    )
    pa.on_block(
        3, "d", "b", 0, 0,
        execution_status=ExecutionStatus.Syncing, execution_block_hash="dd" * 32,
    )
    return pa


# -- invalidation ---------------------------------------------------------


def test_invalid_payload_evicts_descendants_from_head():
    """An EL-invalid verdict on b (LVH=a) must evict b, c, d from head
    candidacy: the head falls back to a."""
    pa = exec_chain()
    fc = ForkChoice(pa, "genesis", np.array([10, 10], np.int64))
    fc.on_attestation(0, 1, "c")
    fc.on_attestation(1, 1, "d")
    assert fc.update_head() in ("c", "d")

    # EL: the branch ending at d is invalid, last valid payload is a's
    pa.validate_latest_hash(
        ExecutionStatus.Invalid, "aa" * 32, invalidate_from_block_root="d"
    )
    for root in ("b", "c", "d"):
        assert (
            pa.nodes[pa.indices[root]].execution_status
            == ExecutionStatus.Invalid
        )
    assert pa.nodes[pa.indices["a"]].execution_status == ExecutionStatus.Valid
    # votes for c/d still exist but invalid nodes are not viable
    assert fc.update_head() == "a"


def test_invalid_without_lvh_invalidates_only_named_node():
    """Null/unknown LVH: be forgiving — only the named payload flips
    (reference protoArray.ts:296-311)."""
    pa = exec_chain()
    pa.validate_latest_hash(
        ExecutionStatus.Invalid, None, invalidate_from_block_root="c"
    )
    assert pa.nodes[pa.indices["c"]].execution_status == ExecutionStatus.Invalid
    assert pa.nodes[pa.indices["b"]].execution_status == ExecutionStatus.Syncing
    assert pa.nodes[pa.indices["d"]].execution_status == ExecutionStatus.Syncing
    # d remains a viable head
    assert pa.find_head("genesis") == "d"


def test_invalidation_of_unknown_root_errors():
    pa = exec_chain()
    with pytest.raises(ProtoArrayError):
        pa.validate_latest_hash(
            ExecutionStatus.Invalid, None, invalidate_from_block_root="zz"
        )


def test_invalid_child_of_invalid_sibling_branch():
    """Pass 2: descendants of invalidated nodes flip even when they were
    not on the reported ancestry walk."""
    pa = exec_chain()
    pa.on_block(
        4, "e", "c", 0, 0,
        execution_status=ExecutionStatus.Syncing, execution_block_hash="ee" * 32,
    )
    # report names d (sibling of c); the walk invalidates d and b, and
    # pass 2 sweeps c (child of b) and e (child of c)
    pa.validate_latest_hash(
        ExecutionStatus.Invalid, "aa" * 32, invalidate_from_block_root="d"
    )
    for root in ("b", "c", "d", "e"):
        assert (
            pa.nodes[pa.indices[root]].execution_status
            == ExecutionStatus.Invalid
        )


def test_invalidated_subtree_weight_stops_counting():
    """Votes parked on an invalidated subtree must stop counting toward
    its ancestors (reference protoArray.ts:146-150: an Invalid node's
    delta is forced to -weight).  Branch A carries heavy votes on a
    subtree the EL rules invalid plus light votes on a clean sibling;
    branch B carries medium votes — B must win."""
    pa = ProtoArray("genesis")
    pa.on_block(1, "A", "genesis", 0, 0,
                execution_status=ExecutionStatus.Syncing,
                execution_block_hash="a1" * 32)
    pa.on_block(2, "A1", "A", 0, 0,
                execution_status=ExecutionStatus.Syncing,
                execution_block_hash="a2" * 32)
    pa.on_block(2, "A2", "A", 0, 0,
                execution_status=ExecutionStatus.Syncing,
                execution_block_hash="a3" * 32)
    pa.on_block(1, "B", "genesis", 0, 0,
                execution_status=ExecutionStatus.Syncing,
                execution_block_hash="b1" * 32)
    fc = ForkChoice(pa, "genesis", np.array([100, 10, 50], np.int64))
    fc.on_attestation(0, 1, "A1")  # 100 on the soon-invalid subtree
    fc.on_attestation(1, 1, "A2")  # 10 on A's clean sibling subtree
    fc.on_attestation(2, 1, "B")   # 50 on branch B
    assert fc.update_head() == "A1"
    # EL: A1 invalid, LVH = A's payload
    pa.validate_latest_hash(
        ExecutionStatus.Invalid, "a1" * 32, invalidate_from_block_root="A1"
    )
    # A1's 100 no longer counts: A carries only 10, B's 50 wins
    assert fc.update_head() == "B"
    assert pa.nodes[pa.indices["A1"]].weight == 0
    assert pa.nodes[pa.indices["A"]].weight == 10
    assert pa.nodes[pa.indices["B"]].weight == 50


# -- valid propagation ----------------------------------------------------


def test_valid_verdict_propagates_to_ancestors():
    pa = exec_chain()
    pa.validate_latest_hash(ExecutionStatus.Valid, "cc" * 32)
    assert pa.nodes[pa.indices["c"]].execution_status == ExecutionStatus.Valid
    assert pa.nodes[pa.indices["b"]].execution_status == ExecutionStatus.Valid
    # sibling branch untouched
    assert pa.nodes[pa.indices["d"]].execution_status == ExecutionStatus.Syncing


def test_valid_child_insert_validates_ancestry():
    """Inserting a Valid block proves its whole Syncing ancestry
    (reference protoArray.ts:227-229)."""
    pa = exec_chain()
    pa.on_block(
        4, "e", "c", 0, 0,
        execution_status=ExecutionStatus.Valid, execution_block_hash="ee" * 32,
    )
    assert pa.nodes[pa.indices["c"]].execution_status == ExecutionStatus.Valid
    assert pa.nodes[pa.indices["b"]].execution_status == ExecutionStatus.Valid


def test_unknown_valid_hash_is_noop():
    pa = exec_chain()
    pa.validate_latest_hash(ExecutionStatus.Valid, "99" * 32)
    assert pa.nodes[pa.indices["b"]].execution_status == ExecutionStatus.Syncing


# -- consensus-failure latching -------------------------------------------


def test_invalidating_valid_node_latches_error():
    pa = exec_chain()
    pa.validate_latest_hash(ExecutionStatus.Valid, "dd" * 32)  # d now Valid
    with pytest.raises(LVHConsensusError):
        # EL flip-flop: now claims the whole branch below d is invalid
        pa.validate_latest_hash(
            ExecutionStatus.Invalid, "aa" * 32, invalidate_from_block_root="d"
        )
    # the array is perma-damaged: every head lookup raises
    with pytest.raises(LVHConsensusError):
        pa.find_head("genesis")


def test_insert_invalid_block_rejected():
    pa = exec_chain()
    with pytest.raises(ProtoArrayError):
        pa.on_block(
            4, "e", "c", 0, 0, execution_status=ExecutionStatus.Invalid
        )


# -- LVH anchored at the pre-merge boundary -------------------------------


def test_lvh_zero_hash_matches_premerge_anchor():
    """LVH = 0x00..00 means 'everything post-merge is bad': the walk must
    stop at the PreMerge genesis and invalidate the whole exec chain."""
    pa = exec_chain()
    # a is Valid — invalidating it is a consensus failure; build a purely
    # Syncing chain instead
    pa2 = ProtoArray("genesis")
    pa2.on_block(
        1, "x", "genesis", 0, 0,
        execution_status=ExecutionStatus.Syncing, execution_block_hash="11" * 32,
    )
    pa2.on_block(
        2, "y", "x", 0, 0,
        execution_status=ExecutionStatus.Syncing, execution_block_hash="22" * 32,
    )
    pa2.validate_latest_hash(
        ExecutionStatus.Invalid, "00" * 32, invalidate_from_block_root="y"
    )
    assert pa2.nodes[pa2.indices["x"]].execution_status == ExecutionStatus.Invalid
    assert pa2.nodes[pa2.indices["y"]].execution_status == ExecutionStatus.Invalid
    assert pa2.find_head("genesis") == "genesis"


# -- unrealized-checkpoint viability --------------------------------------


def test_prev_epoch_node_filtered_on_unrealized_justification():
    """A prev-epoch block whose UNREALIZED justification does not match
    the store's justified checkpoint is not viable, even if its realized
    justified epoch matches (protoArray.ts:733-736)."""
    pa = ProtoArray("genesis")
    # two competing epoch-1 blocks: p pulled up to epoch 2, q stuck at 0
    pa.on_block(
        SPE + 1, "p", "genesis", 0, 0,
        unrealized_justified_epoch=2, unrealized_finalized_epoch=0,
    )
    pa.on_block(
        SPE + 2, "q", "genesis", 0, 0,
        unrealized_justified_epoch=0, unrealized_finalized_epoch=0,
    )
    # clock enters epoch 3; the store justifies epoch 2
    pa.current_slot = 3 * SPE
    pa.apply_score_changes([0, 0, 0], justified_epoch=2, finalized_epoch=0)
    # p (voting source = unrealized 2) is viable; q (unrealized 0) is not
    assert pa._node_is_viable_for_head(pa.nodes[pa.indices["p"]])
    assert not pa._node_is_viable_for_head(pa.nodes[pa.indices["q"]])
    assert pa.find_head("genesis") == "p"


def test_pulled_up_allowance_two_epoch_stale_source():
    """Current-epoch node with a stale realized source stays viable while
    the store justified the previous epoch and the node's unrealized
    justification caught up (protoArray.ts:742-746)."""
    pa = ProtoArray("genesis")
    cur_epoch = 3
    pa.current_slot = cur_epoch * SPE + 1
    # node in the CURRENT epoch: realized source epoch 1 (two back),
    # unrealized justification reached epoch 2
    pa.on_block(
        cur_epoch * SPE + 1, "r", "genesis", 1, 0,
        unrealized_justified_epoch=2, unrealized_finalized_epoch=0,
    )
    pa.apply_score_changes([0, 0], justified_epoch=2, finalized_epoch=0)
    assert pa._node_is_viable_for_head(pa.nodes[pa.indices["r"]])
    # but a realized source three epochs back is out of the allowance
    pa.on_block(
        cur_epoch * SPE + 2, "s", "genesis", 0, 0,
        unrealized_justified_epoch=2, unrealized_finalized_epoch=0,
    )
    assert not pa._node_is_viable_for_head(pa.nodes[pa.indices["s"]])


def test_finalized_root_ancestor_check():
    """With finalized_root tracked, viability requires descending from
    the finalized block, not merely matching its epoch."""
    pa = ProtoArray("genesis")
    pa.on_block(SPE, "f", "genesis", 0, 1)  # finalized epoch-1 block
    pa.on_block(SPE + 1, "m", "f", 1, 1)
    pa.on_block(SPE + 1, "n", "genesis", 1, 1)  # NOT descending from f
    pa.current_slot = SPE + 2
    pa.finalized_root = "f"
    pa.apply_score_changes([0] * 4, justified_epoch=1, finalized_epoch=1)
    assert pa._node_is_viable_for_head(pa.nodes[pa.indices["m"]])
    assert not pa._node_is_viable_for_head(pa.nodes[pa.indices["n"]])


def test_prune_after_invalidation():
    """maybe_prune must survive a tree containing Invalid nodes (their
    best links are cleared; index remapping must not trip on them)."""
    pa = ProtoArray("genesis", prune_threshold=2)
    prev = "genesis"
    for i in range(6):
        pa.on_block(
            i + 1, f"n{i}", prev, 0, 0,
            execution_status=ExecutionStatus.Syncing,
            execution_block_hash=("%02x" % i) * 32,
        )
        prev = f"n{i}"
    # invalidate the tail pair
    pa.validate_latest_hash(
        ExecutionStatus.Invalid, "03" * 32, invalidate_from_block_root="n5"
    )
    assert pa.nodes[pa.indices["n5"]].execution_status == ExecutionStatus.Invalid
    assert pa.nodes[pa.indices["n4"]].execution_status == ExecutionStatus.Invalid
    # finalize at n2: nodes before it drop, indices remap, statuses keep
    removed = pa.maybe_prune("n2")
    assert [n.root for n in removed] == ["genesis", "n0", "n1"]
    assert pa.nodes[pa.indices["n4"]].execution_status == ExecutionStatus.Invalid
    assert pa.nodes[pa.indices["n3"]].execution_status == ExecutionStatus.Syncing
    # head from the new anchor avoids the invalid tail
    assert pa.find_head("n2") == "n3"


def test_invalidation_emits_head_event():
    """_after_invalidation announces the replacement head — API event
    subscribers must see the eviction, not a silent reassignment."""
    import numpy as np

    from lodestar_tpu.chain.chain import BeaconChain
    from lodestar_tpu.chain.emitter import ChainEvent
    from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
    from lodestar_tpu.crypto import bls as B, curves as C
    from lodestar_tpu.state_transition import create_genesis_state

    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG, fork_epochs={params.ForkName.altair: 0}
    )
    pks = [C.g1_compress(B.sk_to_pk(B.keygen(b"he-%d" % i))) for i in range(4)]
    chain = BeaconChain(cfg, create_genesis_state(cfg, pks, genesis_time=2))
    pa = chain.fork_choice.proto
    anchor = chain.anchor_root_hex
    pa.on_block(1, "x1", anchor, 0, 0,
                execution_status=ExecutionStatus.Syncing,
                execution_block_hash="aa" * 32)
    chain.head_root_hex = "x1"
    chain.optimistic_roots.add("x1")
    heads = []
    chain.emitter.on(ChainEvent.head, lambda root, slot: heads.append(root))
    chain.fork_choice.validate_latest_hash(
        ExecutionStatus.Invalid, None, invalidate_from_block_root="x1"
    )
    chain._after_invalidation(1)
    assert chain.head_root_hex == anchor
    assert heads and heads[-1] == bytes.fromhex(anchor)
    assert "x1" not in chain.optimistic_roots
