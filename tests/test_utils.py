"""utils layer: JobItemQueue, retry/sleep/MapDef, logger, metrics server.

Reference: packages/beacon-node/src/util/queue/itemQueue.ts,
packages/utils/src/{retry,map}.ts, packages/logger,
packages/beacon-node/src/metrics/server/http.ts.
"""

import threading
import time
import urllib.request

import pytest

from lodestar_tpu.utils.logger import Logger
from lodestar_tpu.utils.metrics import Registry
from lodestar_tpu.utils.metrics_server import HttpMetricsServer
from lodestar_tpu.utils.misc import AbortSignal, ErrorAborted, MapDef, retry
from lodestar_tpu.utils.queue import JobItemQueue, QueueError, QueueType

pytestmark = pytest.mark.smoke


# -- JobItemQueue -----------------------------------------------------------


def test_queue_processes_in_order():
    done = []
    q = JobItemQueue(lambda x: done.append(x) or x * 2)
    futs = [q.push(i) for i in range(5)]
    assert [f.result(timeout=5) for f in futs] == [0, 2, 4, 6, 8]
    assert done == list(range(5))
    q.stop()


def test_fifo_overflow_rejects_newest():
    gate = threading.Event()
    q = JobItemQueue(lambda x: gate.wait(5) and x, max_length=2)
    f0 = q.push(0)  # starts processing (blocked on gate)
    time.sleep(0.05)
    q.push(1)
    q.push(2)
    f3 = q.push(3)  # over max_length -> rejected
    with pytest.raises(QueueError) as err:
        f3.result(timeout=1)
    assert err.value.reason == "QUEUE_MAX_LENGTH"
    gate.set()
    assert f0.result(timeout=5) == 0
    q.stop()


def test_lifo_overflow_evicts_oldest():
    gate = threading.Event()
    q = JobItemQueue(
        lambda x: gate.wait(5) and x, max_length=2, queue_type=QueueType.LIFO
    )
    q.push("busy")
    time.sleep(0.05)
    f1 = q.push(1)
    q.push(2)
    f3 = q.push(3)  # evicts job 1, keeps 2 and 3
    with pytest.raises(QueueError):
        f1.result(timeout=1)
    gate.set()
    assert f3.result(timeout=5) == 3
    q.stop()


def test_stop_rejects_pending():
    gate = threading.Event()
    q = JobItemQueue(lambda x: gate.wait(5) and x, max_length=10)
    q.push(0)
    time.sleep(0.05)
    f1 = q.push(1)
    gate.set()
    q.stop()
    # f1 either completed before stop drained it or was aborted
    try:
        f1.result(timeout=1)
    except QueueError as e:
        assert e.reason == "QUEUE_ABORTED"
    assert q.push(9).exception(timeout=1) is not None


def test_queue_rejections_settle_outside_lock(monkeypatch):
    """Regression (tpulint async-lock-safety): push() used to call
    fut.set_exception() while holding the Condition on the stopped and
    FIFO-overflow paths.  set_exception runs done-callbacks
    synchronously on the calling thread, so a continuation that
    re-enters the queue (or blocks) would do so INSIDE the lock."""
    import lodestar_tpu.utils.queue as queue_mod

    violations = []
    locks = []

    class ProbeFuture(queue_mod.Future):
        def set_exception(self, exc):
            if any(lk._is_owned() for lk in locks):
                violations.append(repr(exc))
            super().set_exception(exc)

    monkeypatch.setattr(queue_mod, "Future", ProbeFuture)
    gate = threading.Event()
    q = JobItemQueue(lambda x: gate.wait(5) and x, max_length=1)
    locks.append(q._lock)
    q.push(0)  # starts processing (blocked on gate)
    time.sleep(0.05)
    q.push(1)
    f_rej = q.push(2)  # FIFO overflow -> incoming rejected
    with pytest.raises(QueueError):
        f_rej.result(timeout=1)
    # LIFO eviction path too
    q2 = JobItemQueue(
        lambda x: gate.wait(5) and x, max_length=1,
        queue_type=QueueType.LIFO,
    )
    locks.append(q2._lock)
    q2.push("busy")
    time.sleep(0.05)
    f_old = q2.push(1)
    q2.push(2)  # evicts f_old
    with pytest.raises(QueueError):
        f_old.result(timeout=1)
    q.stop()
    f_stopped = q.push(3)  # stopped path
    with pytest.raises(QueueError):
        f_stopped.result(timeout=1)
    gate.set()
    q2.stop()
    assert violations == []


def test_can_accept_work_threshold():
    gate = threading.Event()
    q = JobItemQueue(lambda x: gate.wait(5), max_length=64)
    assert q.can_accept_work(threshold=2)
    q.push(0)
    time.sleep(0.05)
    q.push(1)
    q.push(2)
    assert not q.can_accept_work(threshold=2)
    gate.set()
    q.stop()


# -- misc -------------------------------------------------------------------


def test_retry_retries_then_succeeds():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ValueError("flaky")
        return "ok"

    assert retry(flaky, retries=5) == "ok"
    assert calls["n"] == 3


def test_retry_exhausts():
    with pytest.raises(ValueError):
        retry(lambda: (_ for _ in ()).throw(ValueError("always")), retries=2)


def test_retry_should_retry_predicate():
    calls = {"n": 0}

    def fail():
        calls["n"] += 1
        raise KeyError("nope")

    with pytest.raises(KeyError):
        retry(fail, retries=5, should_retry=lambda e: not isinstance(e, KeyError))
    assert calls["n"] == 1


def test_abort_signal_sleep():
    sig = AbortSignal()
    threading.Timer(0.05, sig.abort).start()
    with pytest.raises(ErrorAborted):
        sig.sleep(5)


def test_mapdef():
    m = MapDef(list)
    m.get_or_default("a").append(1)
    m.get_or_default("a").append(2)
    assert m["a"] == [1, 2]


# -- logger -----------------------------------------------------------------


def test_logger_children_and_format(capsys=None):
    log = Logger(level="debug")
    child = log.child("chain").child("bls")
    assert child.module == "chain/bls"
    line = child._fmt(" info", "verified", {"sets": 128})
    assert "[chain/bls]" in line and "sets=128" in line


# -- metrics server ---------------------------------------------------------


def test_metrics_http_server_scrapes():
    reg = Registry()
    c = reg.counter("lodestar_test_total", "test counter")
    c.inc(3)
    srv = HttpMetricsServer(reg, port=0)
    srv.start()
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics", timeout=5
        ).read().decode()
        assert "lodestar_test_total 3.0" in body
        assert "# TYPE lodestar_test_total counter" in body
    finally:
        srv.close()
