"""Op pools: aggregation, selection, and pool-built blocks that verify.

Reference: packages/beacon-node/src/chain/opPools/ — attestationPool
naive aggregation, aggregatedAttestationPool block selection, opPool
dedupe, sync message/contribution pools feeding the block SyncAggregate.
The end-to-end test builds a block purely from pools and imports it with
FULL signature verification.
"""

import hashlib

import pytest

from lodestar_tpu import params
from lodestar_tpu import types as T
from lodestar_tpu.chain.op_pools import (
    AggregatedAttestationPool,
    AttestationPool,
    OpPool,
    SyncCommitteeMessagePool,
    SyncContributionAndProofPool,
)
from lodestar_tpu.chain.produce_block import (
    produce_block,
    produce_block_from_pools,
)
from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
from lodestar_tpu.crypto import bls as B
from lodestar_tpu.crypto import curves as C
from lodestar_tpu.params import ForkName
from lodestar_tpu.ssz import uint64
from lodestar_tpu.state_transition import (
    create_genesis_state,
    process_slots,
    state_transition,
)
from lodestar_tpu.state_transition.accessors import (
    get_beacon_committee,
    get_beacon_proposer_index,
    get_block_root_at_slot,
    get_committee_count_per_slot,
)

P = params.ACTIVE_PRESET
N_KEYS = 16


@pytest.fixture(scope="module")
def world():
    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG, fork_epochs={ForkName.altair: 0}
    )
    sks = [B.keygen(b"pool-%d" % i) for i in range(N_KEYS)]
    pks = [C.g1_compress(B.sk_to_pk(sk)) for sk in sks]
    genesis = create_genesis_state(cfg, pks, genesis_time=3)
    return cfg, sks, pks, genesis


def _att_data(state, slot, index, head_root):
    epoch = slot // P.SLOTS_PER_EPOCH
    start = epoch * P.SLOTS_PER_EPOCH
    target_root = (
        head_root if start >= state.slot else get_block_root_at_slot(state, start)
    )
    return {
        "slot": slot,
        "index": index,
        "beacon_block_root": head_root,
        "source": dict(state.current_justified_checkpoint),
        "target": {"epoch": epoch, "root": target_root},
    }


def _sign_att(cfg, sk, state, data):
    domain = cfg.get_domain(
        state.slot, params.DOMAIN_BEACON_ATTESTER, data["slot"]
    )
    root = cfg.compute_signing_root(
        T.AttestationData.hash_tree_root(data), domain
    )
    return B.sign_bytes(sk, root)


def test_attestation_pool_aggregates(world):
    cfg, sks, pks, genesis = world
    st = genesis.clone()
    process_slots(st, 2)
    committee = get_beacon_committee(st, 1, 0)
    head = get_block_root_at_slot(st, 1)
    data = _att_data(st, 1, 0, head)

    pool = AttestationPool()
    n = len(committee)
    for pos, vidx in enumerate(committee):
        bits = [i == pos for i in range(n)]
        att = {
            "aggregation_bits": bits,
            "data": data,
            "signature": _sign_att(cfg, sks[int(vidx)], st, data),
        }
        status = pool.add(att)
        assert status == ("added" if pos == 0 else "aggregated")
        # duplicate is rejected
        assert pool.add(att) == "already_known"

    agg = pool.get_aggregate(1, T.AttestationData.hash_tree_root(data))
    assert all(agg["aggregation_bits"])
    # the aggregate signature is the valid aggregate over all members
    from lodestar_tpu.state_transition.block import is_valid_indexed_attestation

    indexed = {
        "attesting_indices": sorted(int(v) for v in committee),
        "data": data,
        "signature": agg["signature"],
    }
    assert is_valid_indexed_attestation(st, indexed)


def test_aggregated_pool_subset_and_ranking(world):
    cfg, sks, pks, genesis = world
    st = genesis.clone()
    process_slots(st, 2)
    committee = get_beacon_committee(st, 1, 0)
    head = get_block_root_at_slot(st, 1)
    data = _att_data(st, 1, 0, head)
    n = len(committee)

    pool = AggregatedAttestationPool()
    full = {
        "aggregation_bits": [True] * n,
        "data": data,
        "signature": bytes([0xC0]) + b"\x00" * 95,
    }
    assert pool.add(full) == "added"
    subset = dict(full, aggregation_bits=[True] + [False] * (n - 1))
    assert pool.add(subset) == "already_known"

    atts = pool.get_attestations_for_block(st)
    assert len(atts) == 1 and all(atts[0]["aggregation_bits"])

    # attestation from the future is not includable
    future = dict(full, data=dict(data, slot=st.slot))
    pool.add(future)
    assert len(pool.get_attestations_for_block(st)) == 1

    pool.prune(clock_slot=2 + P.SLOTS_PER_EPOCH)
    assert pool.size() == 1  # slot-1 pruned, slot-2 (future) survives
    pool.prune(clock_slot=3 + P.SLOTS_PER_EPOCH)
    assert pool.size() == 0


def test_op_pool_dedupe_and_selection(world):
    cfg, sks, pks, genesis = world
    st = genesis.clone()
    process_slots(st, 1)
    op = OpPool()
    h1 = {
        "slot": 1,
        "proposer_index": 2,
        "parent_root": b"\x01" * 32,
        "state_root": b"\x02" * 32,
        "body_root": b"\x03" * 32,
    }
    sl = {
        "signed_header_1": {"message": h1, "signature": b"\x00" * 96},
        "signed_header_2": {
            "message": dict(h1, body_root=b"\x04" * 32),
            "signature": b"\x00" * 96,
        },
    }
    op.insert_proposer_slashing(sl)
    op.insert_proposer_slashing(sl)  # dedupe
    ps, atts, exits = op.get_slashings_and_exits(st)
    assert len(ps) == 1 and not atts and not exits

    # after the offender is slashed, selection skips it
    st.slashed[2] = True
    ps2, _, _ = op.get_slashings_and_exits(st)
    assert not ps2
    op.prune_all(st)
    ps3, _, _ = op.get_slashings_and_exits(genesis)
    assert not ps3


def test_attester_slashing_offender_coverage_dedupe(world):
    """Regression: attester slashings key by offender intersection, and
    an offence whose offenders are ALL already covered is a no-op — the
    slasher re-submitting a detection must not grow the pool."""
    _cfg, _sks, _pks, _genesis = world

    def slashing(indices_1, indices_2, tag):
        def indexed(indices, root_byte):
            return {
                "attesting_indices": sorted(indices),
                "data": {
                    "slot": 0,
                    "index": 0,
                    "beacon_block_root": bytes([root_byte]) * 32,
                    "source": {"epoch": 0, "root": b"\x00" * 32},
                    "target": {"epoch": 1, "root": b"\x00" * 32},
                },
                "signature": b"\x00" * 96,
            }

        return {
            "attestation_1": indexed(indices_1, tag),
            "attestation_2": indexed(indices_2, tag + 1),
        }

    op = OpPool()
    assert op.insert_attester_slashing(slashing([1, 2, 3], [2, 3, 4], 1))
    assert set(op._attester_slashings) == {(2, 3)}
    # same offenders, different evidence: no-op
    assert not op.insert_attester_slashing(slashing([2, 3], [2, 3], 5))
    # a strict subset of covered offenders: no-op
    assert not op.insert_attester_slashing(slashing([2], [2], 7))
    assert len(op._attester_slashings) == 1
    # at least one NEW offender: inserted under its own key
    assert op.insert_attester_slashing(slashing([3, 9], [3, 9], 9))
    assert set(op._attester_slashings) == {(2, 3), (3, 9)}
    # disjoint attestations never insert
    assert not op.insert_attester_slashing(slashing([5], [6], 11))


def test_sync_pools_and_contribution(world):
    cfg, sks, pks, genesis = world
    st = genesis.clone()
    process_slots(st, 2)
    head = get_block_root_at_slot(st, 1)

    domain = cfg.get_domain(st.slot, params.DOMAIN_SYNC_COMMITTEE, 1)
    root = cfg.compute_signing_root(head, domain)
    sk_of = {pks[i]: sks[i] for i in range(len(sks))}

    msg_pool = SyncCommitteeMessagePool()
    contrib_pool = SyncContributionAndProofPool()
    subnet_size = P.SYNC_COMMITTEE_SIZE // params.SYNC_COMMITTEE_SUBNET_COUNT
    for pos, pk in enumerate(st.current_sync_committee["pubkeys"]):
        subnet, idx = divmod(pos, subnet_size)
        msg = {
            "slot": 1,
            "beacon_block_root": head,
            "validator_index": 0,
            "signature": B.sign_bytes(sk_of[pk], root),
        }
        msg_pool.add(subnet, msg, idx)
    for subnet in range(params.SYNC_COMMITTEE_SUBNET_COUNT):
        contrib = msg_pool.get_contribution(1, head, subnet)
        assert contrib is not None and all(contrib["aggregation_bits"])
        assert contrib_pool.add(contrib) == "added"

    agg = contrib_pool.produce_sync_aggregate(1, head)
    assert all(agg["sync_committee_bits"])
    # the merged signature verifies inside process_sync_aggregate
    from lodestar_tpu.state_transition.block import process_sync_aggregate

    process_sync_aggregate(st, agg, True)


def test_block_from_pools_verifies_end_to_end(world):
    """The produceBlock path: pools -> block -> full verification."""
    cfg, sks, pks, genesis = world

    # block 1: empty
    b1, post1 = produce_block(
        genesis, 1, _signed_reveal(cfg, sks, genesis, 1)
    )
    head1 = T.BeaconBlockAltair.hash_tree_root(b1)

    # gossip: every committee member attests block 1...
    agg_pool = AggregatedAttestationPool()
    att_pool = AttestationPool()
    epoch = 1 // P.SLOTS_PER_EPOCH
    for index in range(get_committee_count_per_slot(post1, epoch)):
        committee = get_beacon_committee(post1, 1, index)
        data = _att_data(post1, 1, index, head1)
        n = len(committee)
        for pos, vidx in enumerate(committee):
            att_pool.add(
                {
                    "aggregation_bits": [i == pos for i in range(n)],
                    "data": data,
                    "signature": _sign_att(cfg, sks[int(vidx)], post1, data),
                }
            )
        agg_pool.add(
            att_pool.get_aggregate(1, T.AttestationData.hash_tree_root(data))
        )

    # ...and the sync committee signs it
    msg_pool = SyncCommitteeMessagePool()
    contrib_pool = SyncContributionAndProofPool()
    domain = cfg.get_domain(2, params.DOMAIN_SYNC_COMMITTEE, 1)
    sroot = cfg.compute_signing_root(head1, domain)
    sk_of = {pks[i]: sks[i] for i in range(len(pks))}
    subnet_size = P.SYNC_COMMITTEE_SIZE // params.SYNC_COMMITTEE_SUBNET_COUNT
    for pos, pk in enumerate(post1.current_sync_committee["pubkeys"]):
        subnet, idx = divmod(pos, subnet_size)
        msg_pool.add(
            subnet,
            {
                "slot": 1,
                "beacon_block_root": head1,
                "validator_index": 0,
                "signature": B.sign_bytes(sk_of[pk], sroot),
            },
            idx,
        )
    for subnet in range(params.SYNC_COMMITTEE_SUBNET_COUNT):
        contrib_pool.add(msg_pool.get_contribution(1, head1, subnet))

    # block 2 assembled from the pools, then fully verified
    b2, post2 = produce_block_from_pools(
        post1,
        2,
        _signed_reveal(cfg, sks, post1, 2),
        aggregated_attestation_pool=agg_pool,
        op_pool=OpPool(),
        contribution_pool=contrib_pool,
        head_root=head1,
    )
    assert len(b2["body"]["attestations"]) >= 1
    assert all(b2["body"]["sync_aggregate"]["sync_committee_bits"])

    pdomain = cfg.get_domain(2, params.DOMAIN_BEACON_PROPOSER)
    proot = cfg.compute_signing_root(
        T.BeaconBlockAltair.hash_tree_root(b2), pdomain
    )
    signed = {
        "message": b2,
        "signature": B.sign_bytes(sks[b2["proposer_index"]], proot),
    }
    post = state_transition(
        post1,
        signed,
        verify_state_root=True,
        verify_proposer=True,
        verify_signatures=True,
    )
    assert post.hash_tree_root() == b2["state_root"]
    # attesters got their participation flags
    assert post.current_epoch_participation.sum() > 0


def _signed_reveal(cfg, sks, state, slot):
    pre = state.clone()
    process_slots(pre, slot)
    proposer = get_beacon_proposer_index(pre)
    epoch = slot // P.SLOTS_PER_EPOCH
    domain = cfg.get_domain(slot, params.DOMAIN_RANDAO)
    root = cfg.compute_signing_root(uint64.hash_tree_root(epoch), domain)
    return B.sign_bytes(sks[proposer], root)
