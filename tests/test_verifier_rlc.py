"""RLC batch-mode host semantics: bisection fallback, escape hatch,
fallback accounting, span attributes.

The device kernels are replaced by a host ORACLE here (the bisection
planner never needs them), so the adversarial cases — one tampered set
in a 2048-set job, an all-invalid job — run in milliseconds in the
default tier.  The same bisection driving REAL device sub-batches is
covered by the slow tier (test_verifier.py), and RLC==per-set verdict
equivalence on the real kernels by test_kernels_verify.py.
"""

import numpy as np
import pytest

from lodestar_tpu.bls import (
    PubkeyTable,
    SignatureSet,
    TpuBlsVerifier,
    VerifyOptions,
)
from lodestar_tpu.bls.verifier import _DeviceJob
from lodestar_tpu.crypto import bls as GTB
from lodestar_tpu.crypto.hash_to_curve import hash_to_g2

pytestmark = pytest.mark.smoke


class FakeSet:
    """A stand-in signature set: only truth value + sliceability matter
    to the bisection planner."""

    __slots__ = ("ok",)

    def __init__(self, ok: bool):
        self.ok = ok


class OracleVerifier(TpuBlsVerifier):
    """TpuBlsVerifier with the three device seams replaced by a host
    oracle that reads FakeSet.ok, recording the call pattern."""

    def __init__(self, bisect_leaf):
        super().__init__(
            PubkeyTable(capacity=2),
            rng=np.random.default_rng(0),
            bisect_leaf=bisect_leaf,
        )
        self.batch_calls = []
        self.leaf_calls = []

    def _dispatch_batch(self, sets, wire):
        self.batch_calls.append(len(sets))
        return all(s.ok for s in sets)

    def _batch_verdict(self, handle):
        return handle

    def _per_set_verdicts(self, sets, wire):
        self.leaf_calls.append(len(sets))
        return np.array([s.ok for s in sets])


def _job(sets, n_bucket=None):
    job = _DeviceJob(list(sets), True, True, wire=False)
    job.batch_ok = False  # the dispatched whole-job batch check failed
    job.decodable = np.ones(len(sets), bool)
    job.n_bucket = n_bucket or max(128, len(sets))
    return job


def test_bisection_isolates_single_bad_set_in_2048():
    v = OracleVerifier(bisect_leaf=16)
    sets = [FakeSet(True) for _ in range(2048)]
    sets[1337].ok = False
    verdicts, depth = v._bisect(sets, False, 1)
    assert verdicts.shape == (2048,)
    assert not verdicts[1337] and verdicts.sum() == 2047
    # one bad set: two sub-batches per level down to the 16-set leaf
    assert len(v.batch_calls) <= 2 * 7
    assert depth == 8  # 2048 -> 1024 -> ... -> 16 (leaf)
    # honest half-batches cleared in bulk, not per set
    assert v.metrics.batch_sigs_success.value == 2047 - 15


def test_bisection_all_invalid_job_terminates_and_rejects_all():
    v = OracleVerifier(bisect_leaf=16)
    sets = [FakeSet(False) for _ in range(256)]
    verdicts, _depth = v._bisect(sets, False, 1)
    assert not verdicts.any()
    # degenerates to a full per-set sweep via the leaves (every batch
    # fails), bounded by the tree's internal nodes
    assert sum(v.leaf_calls) == 256
    assert len(v.batch_calls) == 2 + 4 + 8 + 16


def test_bisection_randomized_matches_oracle_on_odd_sizes():
    rng = np.random.default_rng(7)
    for size, leaf in ((100, 8), (33, 4), (517, 16), (2, 1)):
        v = OracleVerifier(bisect_leaf=leaf)
        truth = rng.random(size) > 0.3
        sets = [FakeSet(bool(t)) for t in truth]
        verdicts, _ = v._bisect(sets, False, 1)
        assert (verdicts == truth).all(), (size, leaf)


def test_finish_job_bisects_and_accounts():
    v = OracleVerifier(bisect_leaf=16)
    sets = [FakeSet(True) for _ in range(512)]
    sets[3].ok = False
    job = _job(sets)
    assert v._finish_job(job) is False
    assert (~job.verdicts).nonzero()[0].tolist() == [3]
    assert v.metrics.batch_retries.value == 1
    assert v.metrics.rlc_fallback.value == 1
    assert v.metrics.rlc_bisect_depth.count == 1
    assert v.metrics.success_jobs.value == 511
    assert v.metrics.invalid_sets.value == 1


def test_finish_job_small_batch_skips_bisection():
    """At or under the one-tile leaf the fallback is the plain per-set
    retry (bisection cannot shed device work below one lane tile)."""
    v = OracleVerifier(bisect_leaf=128)
    sets = [FakeSet(True), FakeSet(False), FakeSet(True)]
    job = _job(sets)
    job.args, job.valid = (), np.ones(3, np.int32)  # unused by the oracle

    def fake_device_call(name, fn, args):
        assert name == "each_decoded"
        return np.array([s.ok for s in sets] + [True] * 125)

    v._device_call = fake_device_call
    assert v._finish_job(job) is False
    assert job.verdicts.tolist() == [True, False, True]
    assert v.batch_calls == [] and v.leaf_calls == []
    assert v.metrics.rlc_fallback.value == 1
    assert v.metrics.rlc_bisect_depth.count == 0  # no bisection ran


def test_rlc_batch_span_carries_bucket_and_depth(tracing):
    v = OracleVerifier(bisect_leaf=16)
    sets = [FakeSet(True) for _ in range(512)]
    sets[100].ok = False
    v._finish_job(_job(sets, n_bucket=512))
    spans = [
        s for s in tracing.get_tracer().snapshot() if s.name == "bls.rlc_batch"
    ]
    assert len(spans) == 1
    assert spans[0].attrs["n_bucket"] == 512
    assert spans[0].attrs["accepted"] is False
    assert spans[0].attrs["bisect_depth"] == 6  # 512 -> ... -> 16


@pytest.fixture()
def tracing():
    from lodestar_tpu import observability as OB

    tracer = OB.configure(enabled=True, capacity=OB.get_tracer().capacity)
    tracer.clear()
    try:
        yield OB
    finally:
        OB.configure(enabled=False)
        OB.get_tracer().clear()


# -- dispatch-path selection + escape hatch ---------------------------------


def _world(n_keys=3):
    sks = [GTB.keygen(b"rlc-%d" % i) for i in range(n_keys)]
    pks = [GTB.sk_to_pk(sk) for sk in sks]
    table = PubkeyTable(capacity=n_keys)
    assert table.register(pks) == list(range(n_keys))
    return sks, table


class RecordingCall:
    """Stub _device_call: records entry names, returns all-pass shapes."""

    def __init__(self):
        self.names = []

    def __call__(self, name, fn, args):
        self.names.append(name)
        n = int(np.asarray(args[-1]).shape[0])
        if name.startswith("batch"):
            return np.True_, np.ones(n, bool)
        return np.ones(n, bool)


def _sets(sks, n):
    out = []
    for i in range(n):
        msg = b"root-%d" % i
        out.append(
            SignatureSet.single(
                i % len(sks), hash_to_g2(msg), GTB.sign(sks[i % len(sks)], msg)
            )
        )
    return out


def test_rlc_default_dispatches_batch_entry():
    sks, table = _world()
    v = TpuBlsVerifier(table, rng=np.random.default_rng(1))
    assert v._use_rlc
    rec = RecordingCall()
    v._device_call = rec
    job = v.begin_job(_sets(sks, 3), batchable=True)
    assert rec.names == ["batch_decoded"]
    assert v.finish_job(job) is True


def test_rlc_escape_hatch_forces_per_set(monkeypatch):
    monkeypatch.setenv("LODESTAR_TPU_BLS_RLC", "0")
    sks, table = _world()
    v = TpuBlsVerifier(table, rng=np.random.default_rng(1))
    assert not v._use_rlc
    rec = RecordingCall()
    v._device_call = rec
    job = v.begin_job(_sets(sks, 3), batchable=True)
    assert rec.names == ["each_decoded"]
    assert v.finish_job(job) is True
    # nothing was batched, so nothing counts as a batch retry
    assert v.metrics.batch_retries.value == 0
    assert v.metrics.batchable_sigs.value == 3
