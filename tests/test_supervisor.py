"""Device circuit breaker (bls/supervisor.py) — state machine,
failure classification, watchdog, canary re-probe, and the ISSUE 14
verdict-equivalence property: a breaker trip landing at ANY pipeline
stage boundary leaves every verdict bit-identical to the device path,
for in-flight and newly submitted sets alike.
"""

import random
import threading
import time

import numpy as np
import pytest

from lodestar_tpu.bls.pipeline import BlsVerificationPipeline
from lodestar_tpu.bls.signature_set import WireSignatureSet
from lodestar_tpu.bls.supervisor import (
    OUTCOME_BACKEND_INIT,
    OUTCOME_BAD_OUTPUT,
    OUTCOME_ERROR,
    OUTCOME_TIMEOUT,
    STATE_CLOSED,
    STATE_OPEN,
    BadDeviceOutput,
    DeviceSupervisor,
    DeviceTimeout,
    breaker_snapshot,
    check_verdict_plane,
    classify_failure,
)
from lodestar_tpu.bls.verifier import VerifyOptions
from lodestar_tpu.utils.metrics import BlsPoolMetrics

from chaos.harness import ChaosVerifier, FakeClock, chaos_sig

pytestmark = pytest.mark.smoke


def make_supervisor(**kw):
    metrics = BlsPoolMetrics()
    fake = FakeClock()
    kw.setdefault("registry", metrics.registry)
    kw.setdefault("clock", fake)
    kw.setdefault("auto_probe", False)
    kw.setdefault("enabled", True)
    kw.setdefault("rng", random.Random(0))
    return DeviceSupervisor(**kw), fake, metrics


# ---------------------------------------------------------------------------
# classification
# ---------------------------------------------------------------------------


def test_failure_classification():
    assert classify_failure(DeviceTimeout("x")) == OUTCOME_TIMEOUT
    assert classify_failure(BadDeviceOutput("x")) == OUTCOME_BAD_OUTPUT
    assert (
        classify_failure(RuntimeError("TPU backend UNAVAILABLE"))
        == OUTCOME_BACKEND_INIT
    )
    assert (
        classify_failure(RuntimeError("failed to initialize backend"))
        == OUTCOME_BACKEND_INIT
    )
    assert (
        classify_failure(RuntimeError("axon tunnel reset by peer"))
        == OUTCOME_BACKEND_INIT
    )
    assert classify_failure(ValueError("shape mismatch")) == OUTCOME_ERROR


def test_check_verdict_plane():
    ok = check_verdict_plane(np.ones(8, bool), 8)
    assert ok.shape == (8,)
    with pytest.raises(BadDeviceOutput):
        check_verdict_plane(np.ones(3, bool), 8)
    with pytest.raises(BadDeviceOutput):
        check_verdict_plane(np.float64(1.0), 1)


# ---------------------------------------------------------------------------
# state machine
# ---------------------------------------------------------------------------


def test_threshold_trips_and_canary_recovers():
    probes = {"n": 0, "ok": False}

    def canary():
        probes["n"] += 1
        return probes["ok"]

    sup, fake, metrics = make_supervisor(
        canary=canary, failure_threshold=2, backoff_initial_s=1.0
    )
    trips, recoveries = [], []
    sup.on_trip = trips.append
    sup.on_recover = recoveries.append

    sup.record_failure(OUTCOME_ERROR, "finish_job", "boom")
    assert sup.state == STATE_CLOSED  # below threshold
    sup.record_success()
    sup.record_failure(OUTCOME_ERROR, "finish_job", "boom")
    assert sup.state == STATE_CLOSED  # success reset the streak
    sup.record_failure(OUTCOME_ERROR, "finish_job", "boom")
    assert sup.state == STATE_OPEN and sup.trip_count == 1
    assert trips and trips[0]["trip_count"] == 1
    assert not sup.device_allowed() and sup.is_open()

    # not due yet: poll is a no-op
    sup.poll()
    assert probes["n"] == 0 and sup.state == STATE_OPEN
    # due, but the canary fails: backoff doubles
    fake.advance(2.0)
    sup.poll()
    assert probes["n"] == 1 and sup.state == STATE_OPEN
    st1 = sup.status()
    assert st1["next_probe_in_s"] > 1.0  # doubled (with jitter >= 1.5)
    # eventually the canary passes: breaker closes, degraded time books
    probes["ok"] = True
    fake.advance(10.0)
    sup.poll()
    assert sup.state == STATE_CLOSED and sup.device_allowed()
    assert recoveries and recoveries[0]["degraded_s"] > 0
    assert sup.time_in_degraded_s() == pytest.approx(12.0)
    assert metrics.registry.get(
        "lodestar_bls_breaker_degraded_seconds_total"
    ).value == pytest.approx(12.0)


def test_backoff_is_jittered_and_capped():
    sup, fake, _ = make_supervisor(
        canary=lambda: False,
        backoff_initial_s=1.0,
        backoff_max_s=4.0,
        rng=random.Random(3),
    )
    sup.record_failure(OUTCOME_ERROR, "x")
    waits = []
    for _ in range(6):
        fake.advance(1000.0)
        sup.poll()
        waits.append(sup.status()["next_probe_in_s"])
    # jitter stays inside +/- 25%, and the cap holds
    for w in waits:
        assert w <= 4.0 * 1.25
    assert waits[-1] >= 4.0 * 0.75
    assert len(set(waits)) > 1  # actually jittered


def test_disabled_supervisor_is_a_passthrough():
    sup, _, _ = make_supervisor(enabled=False)
    sup.record_failure(OUTCOME_ERROR, "x")
    assert sup.device_allowed() and not sup.is_open()
    assert sup.run_guarded(lambda: 42) == 42
    assert sup.status()["enabled"] is False


def test_breaker_env_escape_hatch(monkeypatch):
    from lodestar_tpu.bls.supervisor import breaker_enabled_env

    monkeypatch.setenv("LODESTAR_TPU_BLS_BREAKER", "0")
    assert breaker_enabled_env() is False
    sup = DeviceSupervisor(registry=BlsPoolMetrics().registry)
    sup.record_failure(OUTCOME_ERROR, "x")
    assert sup.device_allowed()  # supervision off
    monkeypatch.setenv("LODESTAR_TPU_BLS_BREAKER", "1")
    assert breaker_enabled_env() is True


def test_run_guarded_watchdog_times_out_and_recovers():
    sup, _, _ = make_supervisor(job_deadline_s=0.1)
    release = threading.Event()
    with pytest.raises(DeviceTimeout):
        sup.run_guarded(lambda: release.wait(timeout=10.0), "hang")
    release.set()  # let the abandoned worker die
    # the poisoned executor was replaced: the next call works
    assert sup.run_guarded(lambda: "fine") == "fine"
    sup.close()


def test_breaker_snapshot_aggregates_live_supervisors():
    sup, fake, _ = make_supervisor()
    snap = breaker_snapshot()
    assert snap["supervisors"] >= 1 and snap["state"] in (
        "closed", "half_open", "open",
    )
    sup.record_failure(OUTCOME_ERROR, "x")
    fake.advance(5.0)
    snap = breaker_snapshot()
    assert snap["state"] == "open" and snap["trips"] >= 1
    assert snap["time_in_degraded_s"] >= 5.0
    sup.close()


# ---------------------------------------------------------------------------
# verifier integration
# ---------------------------------------------------------------------------


def _chaos_world(deadline=None, seed=0, threshold=1):
    metrics = BlsPoolMetrics()
    fake = FakeClock()
    sup = DeviceSupervisor(
        registry=metrics.registry,
        clock=fake,
        auto_probe=False,
        enabled=True,
        job_deadline_s=deadline,
        failure_threshold=threshold,
        rng=random.Random(seed),
    )
    v = ChaosVerifier(supervisor=sup, metrics=metrics)
    return v, sup, fake


def test_open_breaker_routes_individually_through_host():
    v, sup, _ = _chaos_world()
    root = b"r" * 32
    sets = [
        WireSignatureSet.single(1, root, chaos_sig(root, (1,))),
        WireSignatureSet.single(2, root, b"\x01" * 96),
    ]
    sup.record_failure(OUTCOME_ERROR, "x")
    assert v.verify_signature_sets_individually(sets) == [True, False]
    assert v.host_sets == 2 and v.device_jobs == 0


def test_begin_job_fault_degrades_without_losing_the_job():
    v, sup, _ = _chaos_world()
    root = b"q" * 32
    sets = [WireSignatureSet.single(3, root, chaos_sig(root, (3,)))]
    v.fault = {"begin": "raise"}
    job = v.begin_job(sets, True)
    assert job.host_mode is True
    assert sup.state == STATE_OPEN
    assert v.finish_job(job) is True
    assert list(job.verdicts) == [True]


def test_aggregate_seam_records_failure_and_falls_back(monkeypatch):
    v, sup, _ = _chaos_world()
    monkeypatch.setattr(v, "_use_agg_device", lambda: True)

    def boom(groups):
        raise RuntimeError("UNAVAILABLE: tunnel")

    monkeypatch.setattr(v, "_aggregate_wire_device", boom)
    out = v.aggregate_wire_signatures([[b"\x01" * 96]])
    # fake bytes don't decompress: host fallback reports None (caller
    # dispatches unaggregated) — the point is no exception escaped
    assert out == [None]
    assert sup.state == STATE_OPEN
    assert sup.status()["last_failure"]["seam"] == "agg_g2_sum"
    assert (
        sup.status()["last_failure"]["outcome"] == OUTCOME_BACKEND_INIT
    )
    # open breaker: the device leg is not attempted at all
    calls = {"n": 0}
    monkeypatch.setattr(
        v, "_aggregate_wire_device",
        lambda groups: calls.__setitem__("n", calls["n"] + 1),
    )
    v.aggregate_wire_signatures([[b"\x01" * 96]])
    assert calls["n"] == 0


def test_service_breaker_status_passthrough():
    from lodestar_tpu.bls.service import BlsVerifierService

    v, sup, _ = _chaos_world()
    svc = BlsVerifierService(v)
    try:
        st = svc.breaker_status()
        assert st is not None and st["state"] == "closed"
    finally:
        svc.close()

    class Bare:
        metrics = BlsPoolMetrics()

        def close(self):
            pass

    svc2 = BlsVerifierService(Bare())
    try:
        assert svc2.breaker_status() is None
    finally:
        svc2.close()


# ---------------------------------------------------------------------------
# ISSUE 14 satellite: verdict equivalence under mid-job breaker trips
# ---------------------------------------------------------------------------

STAGES = ("open_before_submit", "begin", "finish", "output", "hang")


def _random_messages(rng, n):
    msgs = []
    for _ in range(n):
        root = bytes(rng.integers(0, 256, 32, dtype=np.uint8).tobytes())
        vi = int(rng.integers(0, 64))
        valid = bool(rng.random() > 0.3)
        sig = chaos_sig(root, (vi,)) if valid else b"\x77" * 96
        msgs.append((WireSignatureSet.single(vi, root, sig), valid))
    return msgs


@pytest.mark.parametrize("stage", STAGES)
def test_verdict_equivalence_under_mid_job_trip(stage):
    """Randomized property: whatever pipeline stage boundary the trip
    lands on, every in-flight and newly submitted set resolves with the
    verdict the device path would have produced (the oracle truth)."""
    rng = np.random.default_rng(hash(stage) % (2**32))
    trials = 1 if stage == "hang" else 2  # hang leaves a parked thread
    expected_outcome = {
        "begin": OUTCOME_ERROR,
        "finish": OUTCOME_BACKEND_INIT,
        "output": OUTCOME_BAD_OUTPUT,
        "hang": OUTCOME_TIMEOUT,
    }
    for trial in range(trials):
        n = int(rng.integers(6, 40))
        msgs = _random_messages(rng, n)
        expected = [valid for _, valid in msgs]
        v, sup, _ = _chaos_world(
            deadline=(0.2 if stage == "hang" else None),
            seed=trial,
        )
        pipe = BlsVerificationPipeline(
            v, preagg=False, standard_wait_ms=20.0
        )
        try:
            futs = []
            half = n // 2
            for i, (ws, _valid) in enumerate(msgs):
                if i == half:
                    if stage == "open_before_submit":
                        sup.record_failure(OUTCOME_ERROR, "test", "forced")
                    elif stage == "begin":
                        v.fault = {"begin": "raise"}
                    elif stage == "finish":
                        v.fault = {"finish": "backend"}
                    elif stage == "output":
                        v.fault = {"output": "truncated"}
                    elif stage == "hang":
                        v.fault = {"finish": "hang"}
                futs.append(
                    pipe.verify_signature_sets_async(
                        [ws], VerifyOptions(batchable=True)
                    )
                )
            got = [f.result(timeout=60) for f in futs]
            assert got == expected, (stage, trial)
            assert sup.trip_count >= 1, (stage, trial)
            if stage in expected_outcome:
                assert (
                    sup.status()["last_failure"]["outcome"]
                    == expected_outcome[stage]
                ), sup.status()["last_failure"]
        finally:
            v.heal()
            pipe.close()


def test_breaker_metrics_registered_with_lodestar_prefix():
    v, sup, _ = _chaos_world()
    reg = v.metrics.registry
    for name in (
        "lodestar_bls_breaker_state",
        "lodestar_bls_breaker_trips_total",
        "lodestar_bls_breaker_failures_total",
        "lodestar_bls_breaker_probes_total",
        "lodestar_bls_breaker_degraded_seconds_total",
        "lodestar_bls_breaker_host_fallback_sets_total",
    ):
        assert reg.get(name) is not None, name
    v.fault = {"finish": "raise"}
    job = v.begin_job(
        [WireSignatureSet.single(0, b"m" * 32, chaos_sig(b"m" * 32, (0,)))],
        True,
    )
    v.finish_job(job)
    assert reg.get("lodestar_bls_breaker_trips_total").value == 1
    assert (
        reg.get("lodestar_bls_breaker_failures_total").get("error") == 1
    )
    assert (
        reg.get("lodestar_bls_breaker_host_fallback_sets_total").value == 1
    )
    # wall-time watchdog defaults stay OFF on the CPU test backend (a
    # first-dispatch compile must never be classified as a hang)
    assert DeviceSupervisor(
        registry=BlsPoolMetrics().registry, enabled=True
    ).job_deadline_s is None


def test_run_guarded_concurrent_calls_have_independent_deadlines():
    """Review fix: thread-per-call — a guarded call queued while
    another (healthy but slow) call runs must NOT have that wait
    counted against its own deadline."""
    sup, _, _ = make_supervisor(job_deadline_s=0.25)
    results = []

    def slow_ok():
        time.sleep(0.15)
        return "a"

    t = threading.Thread(
        target=lambda: results.append(sup.run_guarded(slow_ok, "a"))
    )
    t.start()
    time.sleep(0.02)  # overlap: a shared 1-worker executor would queue
    assert sup.run_guarded(slow_ok, "b") == "a"
    t.join()
    assert results == ["a"]
    sup.close()


def test_abandoned_device_thread_cannot_corrupt_host_verdicts():
    """Review fix: the guarded device finish runs on a shallow CLONE —
    an orphan thread that out-lives its watchdog deadline and then
    writes (wrong) verdicts mutates only the clone, never the job the
    service reads."""
    v, sup, _ = _chaos_world(deadline=0.1)
    root = b"z" * 32
    sets = [WireSignatureSet.single(1, root, chaos_sig(root, (1,)))]
    release = threading.Event()

    def evil_finish(job):
        release.wait(timeout=5.0)  # hang past the watchdog...
        job.verdicts = np.zeros(len(job.sets), bool)  # ...then lie
        return False

    v._finish_job = evil_finish
    job = v.begin_job(sets, True)
    assert v.finish_job(job) is True  # host fallback: the set IS valid
    assert list(job.verdicts) == [True]
    assert sup.status()["last_failure"]["outcome"] == OUTCOME_TIMEOUT
    release.set()
    time.sleep(0.3)  # let the orphan complete its late mutation
    assert list(job.verdicts) == [True]  # it only touched the clone
