"""Block-proposal + sync-committee duty services (validator client).

Reference: packages/validator/src/services/block.ts,
syncCommittee.ts, blockDuties.ts, syncCommitteeDuties.ts — duty
polling, produce/sign/publish, slashing-protection refusal, aggregator
selection.
"""

import pytest

from lodestar_tpu import params
from lodestar_tpu import types as T
from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
from lodestar_tpu.params import ForkName

# altair-activated schedule: this framework's produced bodies are the
# altair family, and signing containers are fork-dispatched (the raw
# mainnet schedule would put early slots in phase0)
CFG = create_chain_config(
    MAINNET_CHAIN_CONFIG, fork_epochs={ForkName.altair: 0}
)
from lodestar_tpu.crypto import bls as B
from lodestar_tpu.crypto import curves as C
from lodestar_tpu.validator import (
    BlockProposalService,
    SyncCommitteeService,
    ValidatorStore,
)
from lodestar_tpu.validator import sync_committee_service as scs_mod

P = params.ACTIVE_PRESET


@pytest.fixture()
def store():
    sks = {i: B.keygen(b"vsvc-%d" % i) for i in range(2)}
    return ValidatorStore(CFG, sks)


class FakeBlockApi:
    def __init__(self):
        self.published = []

    def get_proposer_duties(self, epoch):
        return [
            {"validator_index": 0, "slot": epoch * P.SLOTS_PER_EPOCH + 5},
            {"validator_index": 99, "slot": epoch * P.SLOTS_PER_EPOCH + 6},
        ]

    def produce_block_v2(self, slot, randao_reveal, graffiti):
        return {
            "slot": slot,
            "proposer_index": 0,
            "parent_root": b"\x01" * 32,
            "state_root": b"\x02" * 32,
            "body": dict(
                T.BeaconBlockBodyAltair.default(), randao_reveal=randao_reveal
            ),
        }

    def publish_block(self, signed):
        self.published.append(signed)


def test_block_service_proposes_and_protects(store):
    api = FakeBlockApi()
    svc = BlockProposalService(store, api)
    svc.poll_duties(0)
    # duty for foreign validator 99 filtered out
    assert len(svc._duties[0]) == 1
    assert svc.run_block_tasks(0, 5) == 1
    assert len(api.published) == 1
    signed = api.published[0]
    # published signature verifies against the store's pubkey
    root = store.config.compute_signing_root(
        T.BeaconBlockAltair.hash_tree_root(signed["message"]),
        store.config.get_domain(5, params.DOMAIN_BEACON_PROPOSER, 5),
    )
    assert B.verify_bytes(store.pubkeys[0], root, signed["signature"])
    # same-slot re-proposal is refused by slashing protection
    svc2 = BlockProposalService(store, api)
    svc2.poll_duties(0)
    assert svc2.run_block_tasks(0, 5) == 0
    assert svc2.skipped_slashable == 1
    # nothing scheduled at another slot
    assert svc.run_block_tasks(0, 7) == 0


class FakeSyncApi:
    def __init__(self):
        self.messages = []
        self.contributions = []
        self.head = b"\x77" * 32

    def get_sync_committee_duties(self, epoch, indices):
        return [{"validator_index": 0, "positions": [0, 130]}]

    def get_head_root(self, slot):
        return self.head

    def submit_sync_committee_message(self, subnet, message, index_in_subnet):
        self.messages.append((subnet, message, index_in_subnet))

    def produce_sync_contribution(self, slot, root, subnet):
        size = P.SYNC_COMMITTEE_SIZE // params.SYNC_COMMITTEE_SUBNET_COUNT
        return {
            "slot": slot,
            "beacon_block_root": root,
            "subcommittee_index": subnet,
            "aggregation_bits": [True] + [False] * (size - 1),
            "signature": bytes([0xC0]) + b"\x00" * 95,
        }

    def publish_contribution_and_proof(self, signed):
        self.contributions.append(signed)


def test_sync_committee_service(store, monkeypatch):
    api = FakeSyncApi()
    svc = SyncCommitteeService(store, api)
    svc.poll_duties(0)
    monkeypatch.setattr(
        scs_mod, "is_sync_committee_aggregator", lambda proof: True
    )
    n = svc.run_sync_committee_tasks(0, 3)
    assert n == 2  # two positions
    subnet_size = P.SYNC_COMMITTEE_SIZE // params.SYNC_COMMITTEE_SUBNET_COUNT
    subnets = sorted(s for s, _, _ in api.messages)
    assert subnets == sorted([0, 130 // subnet_size])
    # message signature verifies over the head root
    _, message, _ = api.messages[0]
    root = store.config.compute_signing_root(
        api.head, store.config.get_domain(3, params.DOMAIN_SYNC_COMMITTEE, 3)
    )
    assert B.verify_bytes(store.pubkeys[0], root, message["signature"])
    # aggregator leg produced signed contributions
    assert len(api.contributions) == 2
    cap = api.contributions[0]
    root = store.config.compute_signing_root(
        T.ContributionAndProof.hash_tree_root(cap["message"]),
        store.config.get_domain(3, params.DOMAIN_CONTRIBUTION_AND_PROOF, 3),
    )
    assert B.verify_bytes(store.pubkeys[0], root, cap["signature"])


def test_aggregator_selection_distribution():
    # ~1/modulo of random proofs select as aggregator
    hits = sum(
        1
        for i in range(256)
        if scs_mod.is_sync_committee_aggregator(i.to_bytes(96, "big"))
    )
    modulo = max(
        1,
        P.SYNC_COMMITTEE_SIZE
        // params.SYNC_COMMITTEE_SUBNET_COUNT
        // scs_mod.TARGET_AGGREGATORS_PER_SYNC_SUBCOMMITTEE,
    )
    assert 0 < hits < 256
    assert abs(hits - 256 // modulo) < 256 // modulo  # loose band
