"""Verified execution provider: proof-gated account state.

Reference behaviors: packages/prover/src/web3_provider.ts +
verified_requests/*.ts — account queries answer only after eth_getProof
verification against a trusted state root; a lying EL surfaces as a
VerificationError, never as a wrong value.
"""

import pytest

from lodestar_tpu.prover.keccak import keccak256
from lodestar_tpu.prover.mpt import rlp_encode
from lodestar_tpu.prover.web3_provider import (
    ExecutionHeader,
    VerificationError,
    VerifiedExecutionProvider,
)

pytestmark = pytest.mark.smoke

ADDRESS = "0x" + (b"\xaa" * 20).hex()
CODE = b"\x60\x60\x60"
SLOT = "0x" + (1).to_bytes(32, "big").hex()
STORAGE_VALUE = 0x2A


def _leaf(path_nibbles, value):
    """Hex-prefix encode a LEAF covering `path_nibbles` + RLP."""
    odd = len(path_nibbles) % 2
    flags = 2 + odd  # leaf flag
    if odd:
        packed = bytes([16 * flags + path_nibbles[0]]) + bytes(
            16 * a + b
            for a, b in zip(path_nibbles[1::2], path_nibbles[2::2])
        )
    else:
        packed = bytes([16 * flags]) + bytes(
            16 * a + b for a, b in zip(path_nibbles[0::2], path_nibbles[1::2])
        )
    return rlp_encode([packed, value])


def _nibbles(b):
    out = []
    for byte in b:
        out += [byte >> 4, byte & 0x0F]
    return out


@pytest.fixture(scope="module")
def trie_world():
    """A one-account state trie + one-slot storage trie, both single-leaf."""
    slot_key = keccak256((1).to_bytes(32, "big"))
    storage_leaf = _leaf(_nibbles(slot_key), rlp_encode((STORAGE_VALUE).to_bytes(1, "big")))
    storage_root = keccak256(storage_leaf)

    account = [
        (7).to_bytes(1, "big"),        # nonce
        (10**18).to_bytes(8, "big"),   # balance
        storage_root,
        keccak256(CODE),
    ]
    addr_key = keccak256(bytes.fromhex(ADDRESS[2:]))
    account_leaf = _leaf(_nibbles(addr_key), rlp_encode(account))
    state_root = keccak256(account_leaf)
    header = ExecutionHeader(
        block_number=100, block_hash=b"\x0b" * 32, state_root=state_root
    )

    def transport(method, params):
        if method == "eth_getProof":
            return {
                "accountProof": ["0x" + account_leaf.hex()],
                "storageProof": [
                    {
                        "proof": ["0x" + storage_leaf.hex()],
                        "value": hex(STORAGE_VALUE),
                    }
                ]
                if params[1]
                else [],
            }
        if method == "eth_getCode":
            return "0x" + CODE.hex()
        if method == "eth_chainId":
            return "0x1"
        raise AssertionError(f"unexpected {method}")

    return header, transport, account_leaf, storage_leaf


def test_verified_balance_nonce_code_storage(trie_world):
    header, transport, _al, _sl = trie_world
    p = VerifiedExecutionProvider(transport, lambda tag: header)
    assert p.get_balance(ADDRESS) == 10**18
    assert p.get_transaction_count(ADDRESS) == 7
    assert p.get_code(ADDRESS) == CODE
    assert p.get_storage_at(ADDRESS, SLOT) == STORAGE_VALUE
    # the JSON-RPC facade answers hex
    assert p.request("eth_getBalance", [ADDRESS, "latest"]) == hex(10**18)


def test_lying_provider_rejected(trie_world):
    header, transport, account_leaf, storage_leaf = trie_world

    def lying(method, params):
        if method == "eth_getProof":
            # a forged account leaf claiming 2x the balance
            fake = bytearray(account_leaf)
            return {
                "accountProof": ["0x" + bytes(fake[:-1] + b"\x99").hex()],
                "storageProof": [],
            }
        return transport(method, params)

    p = VerifiedExecutionProvider(lying, lambda tag: header)
    with pytest.raises(VerificationError):
        p.get_balance(ADDRESS)

    def lying_code(method, params):
        if method == "eth_getCode":
            return "0x" + (CODE + b"\x01").hex()  # wrong code bytes
        return transport(method, params)

    p2 = VerifiedExecutionProvider(lying_code, lambda tag: header)
    with pytest.raises(VerificationError, match="code"):
        p2.get_code(ADDRESS)

    def lying_storage(method, params):
        out = transport(method, params)
        if method == "eth_getProof" and params[1]:
            out = dict(out)
            out["storageProof"] = [
                dict(out["storageProof"][0], value=hex(STORAGE_VALUE + 1))
            ]
        return out

    p3 = VerifiedExecutionProvider(lying_storage, lambda tag: header)
    with pytest.raises(VerificationError, match="claimed"):
        p3.get_storage_at(ADDRESS, SLOT)


def test_strict_mode_blocks_unverifiable(trie_world):
    header, transport, _al, _sl = trie_world
    p = VerifiedExecutionProvider(transport, lambda tag: header, strict=True)
    with pytest.raises(VerificationError, match="strict"):
        p.request("eth_chainId", [])
    loose = VerifiedExecutionProvider(
        transport, lambda tag: header, strict=False
    )
    assert loose.request("eth_chainId", []) == "0x1"


def test_missing_header_rejects(trie_world):
    _h, transport, _al, _sl = trie_world
    p = VerifiedExecutionProvider(transport, lambda tag: None)
    with pytest.raises(VerificationError, match="header"):
        p.get_balance(ADDRESS)


def test_malformed_proof_response_is_verification_error(trie_world):
    header, transport, _al, _sl = trie_world

    def broken(method, params):
        if method == "eth_getProof":
            return {"storageProof": []}  # accountProof missing entirely
        return transport(method, params)

    p = VerifiedExecutionProvider(broken, lambda tag: header)
    with pytest.raises(VerificationError, match="malformed"):
        p.get_balance(ADDRESS)

    def empty_storage(method, params):
        out = transport(method, params)
        if method == "eth_getProof":
            out = dict(out, storageProof=[])
        return out

    p2 = VerifiedExecutionProvider(empty_storage, lambda tag: header)
    with pytest.raises(VerificationError, match="malformed"):
        p2.get_storage_at(ADDRESS, SLOT)
