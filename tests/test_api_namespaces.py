"""The wider REST namespaces: light_client, debug fork-choice, builder,
node peers, proof, keymanager.

Reference behaviors: packages/api/src/beacon/routes/{lightclient,debug,
node,proof}.ts, routes/beacon/state.ts getExpectedWithdrawals, and
api/src/keymanager/routes.ts.
"""

import json
import urllib.request

import pytest

from lodestar_tpu import params
from lodestar_tpu import types as T
from lodestar_tpu.api.server import BeaconApiServer, DefaultHandlers
from lodestar_tpu.chain.chain import BeaconChain
from lodestar_tpu.chain.light_client_server import LightClientServer
from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
from lodestar_tpu.crypto import bls as B
from lodestar_tpu.crypto import curves as C
from lodestar_tpu.db import BeaconDb
from lodestar_tpu.params import ForkName
from lodestar_tpu.state_transition import create_genesis_state
from lodestar_tpu.state_transition.accessors import get_beacon_proposer_index
from lodestar_tpu.state_transition.slot import process_slots
from lodestar_tpu.validator import ValidatorStore

pytestmark = pytest.mark.smoke

P = params.ACTIVE_PRESET
N_KEYS = 8


class _FakePeerManager:
    node_id = "self-node"

    def __init__(self):
        from lodestar_tpu.network.peer_manager import PeerData

        self.peers = {
            "peer-x": PeerData(direction="outbound", connected_at=0.0)
        }


@pytest.fixture(scope="module")
def world():
    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG,
        fork_epochs={
            ForkName.altair: 0,
            ForkName.bellatrix: 0,
            ForkName.capella: 0,
        },
    )
    sks = [B.keygen(b"ns-%d" % i) for i in range(N_KEYS)]
    pks = [C.g1_compress(B.sk_to_pk(sk)) for sk in sks]
    genesis = create_genesis_state(cfg, pks, genesis_time=2)
    # capella-from-genesis devnet: apply the scheduled upgrades to the
    # anchor state (genesis builders construct at the live fork)
    from lodestar_tpu.state_transition.slot import (
        upgrade_to_bellatrix,
        upgrade_to_capella,
    )

    upgrade_to_bellatrix(genesis)
    upgrade_to_capella(genesis)
    from lodestar_tpu.execution import ExecutionEngineMock

    chain = BeaconChain(
        cfg, genesis, db=BeaconDb(config=cfg), execution=ExecutionEngineMock()
    )
    lc = LightClientServer(chain)
    store = ValidatorStore(cfg, dict(enumerate(sks)))
    server = BeaconApiServer(
        DefaultHandlers(
            genesis_time=cfg.genesis_time,
            genesis_validators_root=cfg.genesis_validators_root,
            chain=chain,
            light_client_server=lc,
            peer_manager=_FakePeerManager(),
            validator_store=store,
            keymanager_token="km-secret",
        )
    )
    server.listen()
    base = f"http://127.0.0.1:{server.port}"
    yield cfg, sks, chain, lc, store, base
    server.close()


def _get(base, path, token=None):
    req = urllib.request.Request(base + path)
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


def test_debug_fork_choice_and_heads(world):
    cfg, sks, chain, lc, store, base = world
    fc = _get(base, "/eth/v1/debug/fork_choice")
    assert fc["fork_choice_nodes"], "proto array dump empty"
    heads = _get(base, "/eth/v2/debug/beacon/heads")
    assert len(heads["data"]) >= 1


def test_node_identity_and_peers(world):
    cfg, sks, chain, lc, store, base = world
    ident = _get(base, "/eth/v1/node/identity")
    assert ident["data"]["peer_id"] == "self-node"
    peers = _get(base, "/eth/v1/node/peers")
    assert peers["meta"]["count"] == 1
    assert peers["data"][0]["peer_id"] == "peer-x"


def test_builder_expected_withdrawals(world):
    cfg, sks, chain, lc, store, base = world
    # capella-from-genesis: bookkeeping exists; nobody withdrawable yet
    out = _get(base, "/eth/v1/builder/states/head/expected_withdrawals")
    assert out["data"] == []


def test_proof_namespace_state_proof(world):
    cfg, sks, chain, lc, store, base = world
    from lodestar_tpu.ssz.core import is_valid_merkle_branch

    out = _get(base, "/eth/v0/beacon/proof/state/head?paths=finalized_checkpoint")
    d = out["data"]
    assert is_valid_merkle_branch(
        bytes.fromhex(d["leaf"][2:]),
        [bytes.fromhex(b[2:]) for b in d["branch"]],
        d["depth"],
        d["index"],
        bytes.fromhex(d["state_root"][2:]),
    )


def test_keymanager_lists_and_deletes_remote_keys(world):
    import urllib.error

    cfg, sks, chain, lc, store, base = world
    # unauthenticated access to keymanager routes is rejected
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(base, "/eth/v1/keystores")
    assert ei.value.code == 401
    keys = _get(base, "/eth/v1/keystores", token="km-secret")
    assert len(keys["data"]) == N_KEYS  # LOCAL keystores only
    assert all(not k["readonly"] for k in keys["data"])
    # add a remote key record directly (import path needs a signer URL)
    extra_pk = C.g1_compress(B.sk_to_pk(B.keygen(b"remote-x")))
    store.external_signer = object()
    store.pubkeys[99] = extra_pk
    keys2 = _get(base, "/eth/v1/keystores", token="km-secret")
    assert len(keys2["data"]) == N_KEYS  # the remote key is NOT a keystore
    remote = _get(base, "/eth/v1/remotekeys", token="km-secret")
    assert [r["pubkey"] for r in remote["data"]] == ["0x" + extra_pk.hex()]
    req = urllib.request.Request(
        base + "/eth/v1/remotekeys",
        data=json.dumps(
            {"pubkeys": ["0xzz-malformed", "0x" + extra_pk.hex()]}
        ).encode(),
        method="DELETE",
        headers={
            "Content-Type": "application/json",
            "Authorization": "Bearer km-secret",
        },
    )
    with urllib.request.urlopen(req, timeout=30) as r:
        out = json.loads(r.read())
    # per-key statuses: the malformed entry errors, the valid one deletes
    assert out["data"] == [{"status": "error"}, {"status": "deleted"}]
    assert 99 not in store.pubkeys


def test_light_client_endpoints_serve_updates(world):
    cfg, sks, chain, lc, store, base = world
    # import one signed block so the LC server has an optimistic update
    st = chain.head_state.clone()
    if st.slot < 1:
        process_slots(st, 1)
    proposer = get_beacon_proposer_index(st)
    block = chain.produce_block(1, store.sign_randao(proposer, 1))
    bt = cfg.get_fork_types(1)[0]
    root = cfg.compute_signing_root(
        bt.hash_tree_root(block),
        cfg.get_domain(1, params.DOMAIN_BEACON_PROPOSER, 1),
    )
    signed = {
        "message": block,
        "signature": C.g2_compress(B.sign(sks[proposer], root)),
    }
    block_root = chain.process_block(signed)
    lc.on_imported_block(signed, bytes(block_root))
    # bootstrap for the imported root
    boot = _get(
        base,
        "/eth/v1/beacon/light_client/bootstrap/0x" + bytes(block_root).hex(),
    )
    assert boot["data"]["header"]["slot"] == "1"
    # optimistic update (sync aggregate signs the parent; the server
    # produces one on import when participation suffices — empty sync
    # aggregates yield 404, which is also a valid serving path)
    import urllib.error

    try:
        upd = _get(base, "/eth/v1/beacon/light_client/optimistic_update")
        assert "attested_header" in upd["data"]
    except urllib.error.HTTPError as e:
        assert e.code == 404  # no participation in this tiny world
