"""Device batch-verification kernels vs the pure-Python ground truth.

Covers the jitted entry points the verifier service calls (the work the
reference performs in its BLS worker threads, reference:
packages/beacon-node/src/chain/bls/multithread/worker.ts:30-106):
verify_batch (random-linear-combination batch), verify_each (retry path),
aggregate_pubkeys (device-resident table), g2_subgroup_check_fast.
"""

import random

import numpy as np

import jax
import jax.numpy as jnp

from lodestar_tpu.crypto import bls as GTB
from lodestar_tpu.crypto import curves as C
from lodestar_tpu.crypto import fields as GT
from lodestar_tpu.crypto.hash_to_curve import hash_to_g2
from lodestar_tpu.ops import bls_kernels as BK
from lodestar_tpu.ops import curve as K
from lodestar_tpu.ops import fp, fp2

rng = random.Random(0xB15)
nprng = np.random.default_rng(0xB15)


def enc_g1_affine(pts):
    xs = jnp.asarray(np.stack([fp.const(p[0]) for p in pts]))
    ys = jnp.asarray(np.stack([fp.const(p[1]) for p in pts]))
    return (xs, ys)


def enc_g2_affine(pts):
    return (
        jnp.asarray(fp2.stack_consts([p[0] for p in pts])),
        jnp.asarray(fp2.stack_consts([p[1] for p in pts])),
    )


def make_sets(n, bad=()):
    """n signature sets [(pk, H(m), sig)]; indices in `bad` get a wrong sig."""
    pks, hms, sigs = [], [], []
    for i in range(n):
        sk = GTB.keygen(b"kernel-test-%d" % i)
        msg = b"signing root %d" % i
        sig = GTB.sign(sk, msg)
        if i in bad:
            sig = C.scalar_mul(C.FP2_OPS, sig, 2)  # valid point, wrong sig
        pks.append(GTB.sk_to_pk(sk))
        hms.append(hash_to_g2(msg))
        sigs.append(sig)
    return pks, hms, sigs


def run_batch(pks, hms, sigs, valid):
    n = len(valid)
    rand_bits = jnp.asarray(BK.make_rand_bits(n, nprng))
    ok, sig_ok = jax.jit(BK.verify_batch)(
        enc_g1_affine(pks),
        enc_g2_affine(hms),
        enc_g2_affine(sigs),
        rand_bits,
        jnp.asarray(valid),
    )
    return bool(ok), np.asarray(sig_ok)


def test_verify_batch_accepts_valid_sets_with_padding():
    pks, hms, sigs = make_sets(3)
    # pad slot 3 with garbage-but-encodable data (the generator itself)
    pks.append(C.G1_GEN)
    hms.append(C.G2_GEN)
    sigs.append(C.G2_GEN)
    ok, sig_ok = run_batch(pks, hms, sigs, [True, True, True, False])
    assert ok
    assert sig_ok.all()


def test_verify_batch_rejects_one_bad_sig():
    pks, hms, sigs = make_sets(4, bad={2})
    ok, _ = run_batch(pks, hms, sigs, [True] * 4)
    assert not ok


def test_verify_batch_ignores_bad_sig_in_padded_slot():
    pks, hms, sigs = make_sets(4, bad={2})
    ok, _ = run_batch(pks, hms, sigs, [True, True, False, True])
    assert ok


def test_verify_each_pinpoints_bad_sets():
    pks, hms, sigs = make_sets(4, bad={1, 3})
    ok = jax.jit(BK.verify_each)(
        enc_g1_affine(pks),
        enc_g2_affine(hms),
        enc_g2_affine(sigs),
        jnp.asarray([True, True, True, False]),
    )
    # slot 3 is padding -> forced True even though its sig is bad
    assert np.asarray(ok).tolist() == [True, False, True, True]


def test_verify_batch_rejects_non_subgroup_signature():
    pks, hms, sigs = make_sets(2)
    # An on-curve G2 point outside the r-torsion (the cofactor is huge, so
    # a random curve point is ~never in the subgroup): scan x = (ctr, 1).
    ctr, h = 0, None
    while h is None:
        x = (ctr, 1)
        rhs = GT.fp2_add(GT.fp2_mul(GT.fp2_mul(x, x), x), C.FP2_OPS.b_coeff)
        y = GT.fp2_sqrt(rhs)
        ctr += 1
        if y is not None and not C.g2_subgroup_check((x, y)):
            h = (x, y)
    sigs[1] = h
    ok, sig_ok = run_batch(pks, hms, sigs, [True, True])
    assert not ok
    assert sig_ok.tolist() == [True, False]


def test_aggregate_pubkeys_matches_ground_truth():
    V, N, Kk = 8, 3, 4
    pks = [GTB.sk_to_pk(GTB.keygen(b"table-%d" % i)) for i in range(V)]
    table_x = jnp.asarray(np.stack([fp.const(p[0]) for p in pks]))
    table_y = jnp.asarray(np.stack([fp.const(p[1]) for p in pks]))
    idx = np.zeros((N, Kk), np.int32)
    mask = np.zeros((N, Kk), bool)
    want = []
    for i in range(N):
        k = rng.randrange(1, Kk + 1)
        sel = rng.sample(range(V), k)
        idx[i, :k] = sel
        mask[i, :k] = True
        want.append(GTB.aggregate_pubkeys([pks[j] for j in sel]))
    agg = jax.jit(BK.aggregate_pubkeys)(
        table_x, table_y, jnp.asarray(idx), jnp.asarray(mask)
    )
    got = K.decode_points(K.FP_OPS, agg)
    assert got == want


def test_g2_subgroup_check_fast_matches_full_check():
    good = C.scalar_mul(C.FP2_OPS, C.G2_GEN, rng.randrange(1, GT.R))
    pts = [good, C.G2_GEN]
    xs, ys = enc_g2_affine(pts)
    one = fp2.broadcast_to(fp2.ONE, (len(pts),))
    ok = jax.jit(BK.g2_subgroup_check_fast)((xs, ys, one))
    assert np.asarray(ok).all()
