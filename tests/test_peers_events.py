"""Peer score book + the SSE events stream.

Reference: network/peers/score (decayed bounded scores, ban states,
relevance handshake) and routes/events.ts (head/block SSE topics).
"""

import threading

import pytest

from lodestar_tpu import params
from lodestar_tpu import types as T
from lodestar_tpu.api.client import ApiClient
from lodestar_tpu.api.server import BeaconApiServer, DefaultHandlers
from lodestar_tpu.chain.chain import BeaconChain
from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
from lodestar_tpu.crypto import bls as B
from lodestar_tpu.crypto import curves as C
from lodestar_tpu.network.peers import (
    PeerAction,
    PeerScoreBook,
    PeerStatus,
    ScoreState,
)
from lodestar_tpu.params import ForkName
from lodestar_tpu.ssz import uint64
from lodestar_tpu.state_transition import create_genesis_state, process_slots
from lodestar_tpu.state_transition.accessors import get_beacon_proposer_index

P = params.ACTIVE_PRESET


def test_peer_scores_decay_and_ban():
    now = [1000.0]
    book = PeerScoreBook(clock=lambda: now[0])
    assert book.state("p1") == ScoreState.healthy

    book.apply_action("p1", PeerAction.mid_tolerance)  # -5
    book.apply_action("p1", PeerAction.mid_tolerance)
    book.apply_action("p1", PeerAction.mid_tolerance)
    book.apply_action("p1", PeerAction.mid_tolerance)
    book.apply_action("p1", PeerAction.low_tolerance)  # -30 total
    assert book.state("p1") == ScoreState.disconnected

    book.apply_action("p2", PeerAction.fatal)
    assert book.state("p2") == ScoreState.banned

    # exponential half-life decay recovers the disconnected peer
    now[0] += 600.0 * 4
    assert book.state("p1") == ScoreState.healthy
    # score is clamped
    for _ in range(30):
        book.add("p3", 10.0)
    assert book.score("p3") == 100.0
    assert book.best_peers()[0] == "p3"


def test_peer_relevance():
    book = PeerScoreBook()
    ours = b"\x01\x02\x03\x04"
    status = PeerStatus(
        fork_digest=ours,
        finalized_root=b"\xaa" * 32,
        finalized_epoch=5,
        head_root=b"\xbb" * 32,
        head_slot=200,
    )
    book.on_status("p", status)
    assert book.status_of("p") == status
    assert book.is_relevant(status, ours, our_finalized_epoch=3)
    # wrong network
    assert not book.is_relevant(status, b"\xff" * 4, 3)
    # peer finalized at/behind us on a DIFFERENT history -> irrelevant
    assert not book.is_relevant(
        status, ours, 7, root_at_epoch=lambda e: b"\xcc" * 32
    )
    assert book.is_relevant(
        status, ours, 7, root_at_epoch=lambda e: b"\xaa" * 32
    )
    # unknown local root at that epoch: cannot judge, accept
    assert book.is_relevant(status, ours, 7, root_at_epoch=lambda e: None)
    # peer finalized AHEAD of us: no root check possible
    assert book.is_relevant(
        status, ours, 2, root_at_epoch=lambda e: b"\xcc" * 32
    )


def test_events_stream_over_http():
    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG, fork_epochs={ForkName.altair: 0}
    )
    sks = [B.keygen(b"evt-%d" % i) for i in range(16)]
    pks = [C.g1_compress(B.sk_to_pk(sk)) for sk in sks]
    genesis = create_genesis_state(cfg, pks, genesis_time=4)
    chain = BeaconChain(cfg, genesis)
    server = BeaconApiServer(DefaultHandlers(chain=chain))
    server.listen()
    client = ApiClient([f"http://127.0.0.1:{server.port}"], timeout=30)

    got = []
    done = threading.Event()

    def listen():
        client.stream_events(
            ["head", "block"],
            lambda topic, data: got.append((topic, data)),
            max_events=2,
            timeout=20.0,
        )
        done.set()

    t = threading.Thread(target=listen, daemon=True)
    t.start()
    # wait until the SSE handler's emitter subscriptions are attached
    # (no fixed sleep: that races on a loaded machine)
    import time

    from lodestar_tpu.chain.emitter import ChainEvent

    deadline = time.time() + 10
    while time.time() < deadline and not (
        chain.emitter._subs[ChainEvent.head]
        and chain.emitter._subs[ChainEvent.block]
    ):
        time.sleep(0.05)
    assert chain.emitter._subs[ChainEvent.head], "subscription never attached"

    # propose + import one block -> block and head events fire
    pre = genesis.clone()
    process_slots(pre, 1)
    proposer = get_beacon_proposer_index(pre)
    reveal = B.sign_bytes(
        sks[proposer],
        cfg.compute_signing_root(
            uint64.hash_tree_root(0), cfg.get_domain(1, params.DOMAIN_RANDAO)
        ),
    )
    block = chain.produce_block(1, reveal)
    root = cfg.compute_signing_root(
        T.BeaconBlockAltair.hash_tree_root(block),
        cfg.get_domain(1, params.DOMAIN_BEACON_PROPOSER, 1),
    )
    chain.process_block(
        {"message": block, "signature": B.sign_bytes(sks[proposer], root)}
    )

    assert done.wait(timeout=25), "event stream did not complete"
    topics = sorted(t_ for t_, _ in got)
    assert topics == ["block", "head"]
    for _topic, data in got:
        assert data["block"].startswith("0x")
        assert data["slot"] == "1"
    server.close()
