"""End-to-end pallas verification pipeline vs the crypto oracle.

Runs in pallas interpret mode on the CPU test platform (the driver and
dev runs exercise the same kernels compiled through Mosaic on the chip).
"""

import random

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lodestar_tpu.crypto import bls as GB
from lodestar_tpu.crypto import curves as GC
from lodestar_tpu.crypto.hash_to_curve import hash_to_g2
from lodestar_tpu.kernels import layout as LY
from lodestar_tpu.kernels import verify as KV
from lodestar_tpu.ops import bls_kernels as BK

pytestmark = pytest.mark.slow

random.seed(0xACE5)
N = 128  # one kernel lane tile (kernels/verify.py BT)


def enc_plane(vals):
    # msg/sig planes ship as PLAIN limbs (ingest wire split)
    return jnp.asarray(LY.encode_plain_batch(vals))


def world(v=6):
    sks = [GB.keygen(b"kv-%d" % i) for i in range(v)]
    pks = [GB.sk_to_pk(sk) for sk in sks]
    # the table is stored in Montgomery form (registration-time encode)
    tx = jnp.asarray(LY.encode_batch([p[0] for p in pks]))
    ty = jnp.asarray(LY.encode_batch([p[1] for p in pks]))
    return sks, pks, tx, ty


def encode_sets(sets, n, kmax):
    """sets: list of (indices, msg_point, sig_point_or_None)."""
    idx = np.zeros((n, kmax), np.int32)
    kmask = np.zeros((n, kmax), np.int32)
    valid = np.zeros((n,), np.int32)
    sig_inf = np.zeros((n,), np.int32)
    msgs, sigs = [], []
    g2 = GC.G2_GEN
    for i, (ids, msg, sig) in enumerate(sets):
        idx[i, : len(ids)] = ids
        kmask[i, : len(ids)] = 1
        valid[i] = 1
        msgs.append(msg)
        if sig is None:
            sig_inf[i] = 1
            sigs.append(g2)
        else:
            sigs.append(sig)
    for _ in range(n - len(sets)):
        msgs.append(g2)
        sigs.append(g2)
    planes = dict(
        idx=jnp.asarray(idx),
        kmask=jnp.asarray(kmask),
        msg_x0=enc_plane([m[0][0] for m in msgs]),
        msg_x1=enc_plane([m[0][1] for m in msgs]),
        msg_y0=enc_plane([m[1][0] for m in msgs]),
        msg_y1=enc_plane([m[1][1] for m in msgs]),
        sig_x0=enc_plane([s[0][0] for s in sigs]),
        sig_x1=enc_plane([s[0][1] for s in sigs]),
        sig_y0=enc_plane([s[1][0] for s in sigs]),
        sig_y1=enc_plane([s[1][1] for s in sigs]),
        sig_inf=jnp.asarray(sig_inf),
        valid=jnp.asarray(valid),
    )
    return planes


def bits_for(n, seed):
    return jnp.asarray(
        BK.make_rand_words(n, np.random.default_rng(seed))
    )


def run_batch(tx, ty, planes, bits):
    ok, sub = KV.verify_batch_device(
        tx, ty, planes["idx"], planes["kmask"],
        planes["msg_x0"], planes["msg_x1"], planes["msg_y0"], planes["msg_y1"],
        planes["sig_x0"], planes["sig_x1"], planes["sig_y0"], planes["sig_y1"],
        planes["sig_inf"], bits, planes["valid"],
    )
    return bool(ok), list(np.asarray(sub))


def run_each(tx, ty, planes):
    ok = KV.verify_each_device(
        tx, ty, planes["idx"], planes["kmask"],
        planes["msg_x0"], planes["msg_x1"], planes["msg_y0"], planes["msg_y1"],
        planes["sig_x0"], planes["sig_x1"], planes["sig_y0"], planes["sig_y1"],
        planes["sig_inf"], planes["valid"],
    )
    return list(np.asarray(ok))


def test_batch_singles_accept_and_reject():
    sks, pks, tx, ty = world()
    msgs = [b"root-%d" % (i % 2) for i in range(3)]
    sets = [
        ((i,), hash_to_g2(msgs[i]), GB.sign(sks[i], msgs[i])) for i in range(3)
    ]
    planes = encode_sets(sets, N, 1)
    ok, sub = run_batch(tx, ty, planes, bits_for(N, 1))
    assert ok and all(sub)

    # tamper one signature (stays in subgroup)
    bad = list(sets)
    bad[1] = (bad[1][0], bad[1][1], GC.scalar_mul(GC.FP2_OPS, bad[1][2], 2))
    planes = encode_sets(bad, N, 1)
    ok, sub = run_batch(tx, ty, planes, bits_for(N, 2))
    assert not ok and all(sub)
    each = run_each(tx, ty, planes)
    assert each[:3] == [True, False, True] and all(each[3:])


def test_batch_aggregate_sets():
    sks, pks, tx, ty = world()
    msg = b"agg-root"
    hm = hash_to_g2(msg)
    ids = [1, 3, 4]
    agg_sig = GB.aggregate_signatures([GB.sign(sks[i], msg) for i in ids])
    single = ((0,), hash_to_g2(b"s"), GB.sign(sks[0], b"s"))
    sets = [single, (tuple(ids), hm, agg_sig)]
    planes = encode_sets(sets, N, 4)
    ok, sub = run_batch(tx, ty, planes, bits_for(N, 3))
    assert ok and all(sub)
    assert all(run_each(tx, ty, planes))

    # wrong aggregate membership must fail
    sets_bad = [single, ((1, 3, 5), hm, agg_sig)]
    planes = encode_sets(sets_bad, N, 4)
    ok, _ = run_batch(tx, ty, planes, bits_for(N, 4))
    assert not ok
    each = run_each(tx, ty, planes)
    assert each[:2] == [True, False] and all(each[2:])


def test_out_of_subgroup_signature_rejected():
    from lodestar_tpu.crypto import hash_to_curve as GH

    sks, pks, tx, ty = world()
    bad_sig = GH.map_to_curve_svdw(
        GC.FP2_OPS, GH.hash_to_field_fp2(b"oos", 1, b"T")[0]
    )
    assert not GC.g2_subgroup_check(bad_sig)
    sets = [
        ((0,), hash_to_g2(b"m"), GB.sign(sks[0], b"m")),
        ((1,), hash_to_g2(b"m2"), bad_sig),
    ]
    planes = encode_sets(sets, N, 1)
    ok, sub = run_batch(tx, ty, planes, bits_for(N, 5))
    assert not ok
    assert sub[:2] == [True, False] and all(sub[2:])
    each = run_each(tx, ty, planes)
    assert each[:2] == [True, False] and all(each[2:])


def test_rlc_pairing_budget_is_one_final_exp_per_job():
    """The RLC acceptance invariant (ISSUE 10): an N-set batch job
    dispatches exactly N+1 Miller-loop lanes of real pairing work and
    ONE final exponentiation; the per-set path pays 2N and N.  Asserted
    via the pipeline's explicit kernel-call tally (kernels/verify.py
    PIPELINE_TALLY), which ticks at dispatch time on the direct path."""
    sks, pks, tx, ty = world()
    sets = [
        ((i,), hash_to_g2(b"budget-%d" % i), GB.sign(sks[i], b"budget-%d" % i))
        for i in range(4)
    ]
    planes = encode_sets(sets, N, 1)

    KV.PIPELINE_TALLY.clear()
    ok, _ = run_batch(tx, ty, planes, bits_for(N, 11))
    assert ok
    assert KV.PIPELINE_TALLY["miller_pair"] == N + 1
    assert KV.PIPELINE_TALLY["final_exp"] == 1

    KV.PIPELINE_TALLY.clear()
    assert all(run_each(tx, ty, planes))
    assert KV.PIPELINE_TALLY["miller_pair"] == 2 * N
    assert KV.PIPELINE_TALLY["final_exp"] == N


def test_rlc_verdict_matches_per_set_randomized():
    """Randomized cross-check over mixed valid/invalid jobs: the RLC
    batch verdict equals the conjunction of per-set verdicts, and the
    per-set verdicts flag exactly the tampered sets — including the
    all-invalid job."""
    sks, pks, tx, ty = world()
    rng = np.random.default_rng(0x51C)
    scenarios = [rng.random(5) < 0.4 for _ in range(2)]
    scenarios.append(np.ones(5, bool))  # all-invalid
    for round_i, bad_mask in enumerate(scenarios):
        sets = []
        for i in range(5):
            msg = b"rlc-eq-%d-%d" % (round_i, i)
            sig = GB.sign(sks[i], msg)
            if bad_mask[i]:
                sig = GC.scalar_mul(GC.FP2_OPS, sig, 2)  # wrong, in-subgroup
            sets.append(((i,), hash_to_g2(msg), sig))
        planes = encode_sets(sets, N, 1)
        ok, sub = run_batch(tx, ty, planes, bits_for(N, 100 + round_i))
        each = run_each(tx, ty, planes)
        assert all(sub), "tampered-by-doubling sigs stay in-subgroup"
        assert ok == all(each[:5]), (round_i, bad_mask, each[:5])
        assert ok == (not bad_mask.any())
        assert each[:5] == [not b for b in bad_mask], (round_i, bad_mask)
        assert all(each[5:])


def test_infinity_signature_rejected():
    sks, pks, tx, ty = world()
    sets = [
        ((0,), hash_to_g2(b"m"), GB.sign(sks[0], b"m")),
        ((1,), hash_to_g2(b"m2"), None),  # infinity/undecodable
    ]
    planes = encode_sets(sets, N, 1)
    ok, _ = run_batch(tx, ty, planes, bits_for(N, 6))
    assert not ok
    each = run_each(tx, ty, planes)
    assert each[:2] == [True, False] and all(each[2:])
