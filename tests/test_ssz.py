"""SSZ serialization + merkleization.

Known-answer vectors are computed from the consensus-spec SSZ rules;
structural tests check round-trips and merkle math (zero-padding,
mix_in_length).  Reference consumes the same rules via @chainsafe/ssz
(packages/types/src/sszTypes.ts).
"""

import hashlib

import pytest

from lodestar_tpu import params
from lodestar_tpu import types as T
from lodestar_tpu.ssz import (
    Bitlist,
    Bitvector,
    Boolean,
    Bytes32,
    Container,
    List,
    Vector,
    merkleize_chunks,
    uint8,
    uint16,
    uint64,
)

pytestmark = pytest.mark.smoke

sha = lambda b: hashlib.sha256(b).digest()
Z = b"\x00" * 32


def test_uint_serialization():
    assert uint64.serialize(0x0102030405060708) == bytes(
        [8, 7, 6, 5, 4, 3, 2, 1]
    )
    assert uint16.serialize(0xABCD) == b"\xcd\xab"
    assert uint64.deserialize(uint64.serialize(12345)) == 12345
    assert uint64.hash_tree_root(1) == (1).to_bytes(8, "little") + b"\x00" * 24


def test_boolean():
    assert Boolean.serialize(True) == b"\x01"
    assert Boolean.deserialize(b"\x00") is False
    with pytest.raises(ValueError):
        Boolean.deserialize(b"\x02")


def test_merkleize_basics():
    # single chunk: root == chunk
    c = bytes(range(32))
    assert merkleize_chunks([c]) == c
    # two chunks: root == H(a || b)
    a, b = bytes([1]) * 32, bytes([2]) * 32
    assert merkleize_chunks([a, b]) == sha(a + b)
    # three chunks pad to four
    d = bytes([3]) * 32
    assert merkleize_chunks([a, b, d]) == sha(sha(a + b) + sha(d + Z))
    # empty with limit: zero-tree root
    assert merkleize_chunks([], 4) == sha(sha(Z + Z) + sha(Z + Z))


def test_merkleize_with_limit_pads_depth():
    a = bytes([7]) * 32
    z1 = sha(Z + Z)
    # limit 4 -> depth 2 even with one chunk
    assert merkleize_chunks([a], 4) == sha(sha(a + Z) + z1)


def test_vector_fixed_round_trip():
    v = Vector(uint16, 3)
    data = v.serialize([1, 2, 3])
    assert data == b"\x01\x00\x02\x00\x03\x00"
    assert v.deserialize(data) == [1, 2, 3]
    # root: packed into one chunk
    assert v.hash_tree_root([1, 2, 3]) == data + b"\x00" * 26


def test_list_mixes_in_length():
    l = List(uint64, 1024)
    root_empty = l.hash_tree_root([])
    root_one = l.hash_tree_root([5])
    assert root_empty != root_one
    # mix_in_length structure: H(merkle_root || len)
    limit_chunks = 1024 * 8 // 32
    packed = (5).to_bytes(8, "little").ljust(32, b"\x00")
    inner = merkleize_chunks([packed], limit_chunks)
    assert root_one == sha(inner + (1).to_bytes(32, "little"))


def test_list_of_variable_size_elements():
    inner = List(uint8, 10)
    outer = List(inner, 4)
    val = [[1, 2], [], [3]]
    data = outer.serialize(val)
    assert outer.deserialize(data) == val


def test_bitvector():
    bv = Bitvector(10)
    bits = [True, False] * 5
    data = bv.serialize(bits)
    assert len(data) == 2
    assert bv.deserialize(data) == bits
    with pytest.raises(ValueError):
        bv.deserialize(b"\xff\xff")  # padding bits set


def test_bitlist_delimiter():
    bl = Bitlist(12)
    assert bl.serialize([]) == b"\x01"
    assert bl.serialize([True]) == b"\x03"
    bits = [True, False, True, True]
    assert bl.deserialize(bl.serialize(bits)) == bits
    with pytest.raises(ValueError):
        bl.deserialize(b"\x00")
    # root differs from same bits at different length
    assert bl.hash_tree_root([True]) != bl.hash_tree_root([True, False])


def test_container_fixed_and_variable():
    c = Container(
        (
            ("a", uint64),
            ("items", List(uint8, 8)),
            ("b", Bytes32),
        ),
        name="Mix",
    )
    val = {"a": 7, "items": [1, 2, 3], "b": bytes(32)}
    data = c.serialize(val)
    # offset table: a(8) + offset(4) + b(32) = 44 fixed; items start at 44
    assert data[8:12] == (44).to_bytes(4, "little")
    assert c.deserialize(data) == val
    # root = merkleize of 3 field roots
    roots = [
        uint64.hash_tree_root(7),
        c.fields[1][1].hash_tree_root([1, 2, 3]),
        Bytes32.hash_tree_root(bytes(32)),
    ]
    assert c.hash_tree_root(val) == merkleize_chunks(roots)


def test_attestation_data_known_root():
    """Cross-checked structural root for a beacon type."""
    data = {
        "slot": 1,
        "index": 2,
        "beacon_block_root": bytes([3]) * 32,
        "source": {"epoch": 0, "root": bytes(32)},
        "target": {"epoch": 1, "root": bytes([4]) * 32},
    }
    root = T.AttestationData.hash_tree_root(data)
    # manual: 5 field roots -> depth-3 tree (padded to 8)
    f = [
        (1).to_bytes(8, "little").ljust(32, b"\x00"),
        (2).to_bytes(8, "little").ljust(32, b"\x00"),
        bytes([3]) * 32,
        T.Checkpoint.hash_tree_root({"epoch": 0, "root": bytes(32)}),
        T.Checkpoint.hash_tree_root({"epoch": 1, "root": bytes([4]) * 32}),
    ]
    l0 = sha(sha(f[0] + f[1]) + sha(f[2] + f[3]))
    l1 = sha(sha(f[4] + Z) + sha(Z + Z))
    assert root == sha(l0 + l1)
    # checkpoint root is a 2-leaf tree (no padding to 4)
    assert T.Checkpoint.hash_tree_root({"epoch": 5, "root": Z}) == sha(
        (5).to_bytes(8, "little").ljust(32, b"\x00") + Z
    )


def test_signed_block_round_trip():
    block = T.BeaconBlockAltair.default()
    block["slot"] = 123
    block["proposer_index"] = 7
    signed = {"message": block, "signature": b"\x11" * 96}
    data = T.SignedBeaconBlockAltair.serialize(signed)
    back = T.SignedBeaconBlockAltair.deserialize(data)
    assert back["message"]["slot"] == 123
    assert back["signature"] == b"\x11" * 96
    assert T.SignedBeaconBlockAltair.hash_tree_root(signed) == (
        T.SignedBeaconBlockAltair.hash_tree_root(back)
    )


def test_capella_deneb_block_families_roundtrip():
    """The later-fork containers (reference: types/src/{capella,deneb}/
    sszTypes.ts) serialize + hash; their STF variants are future forks
    (COVERAGE.md descope)."""
    from lodestar_tpu import types as T

    payload = {
        "parent_hash": b"\x01" * 32,
        "fee_recipient": b"\x02" * 20,
        "state_root": b"\x03" * 32,
        "receipts_root": b"\x04" * 32,
        "logs_bloom": b"\x00" * 256,
        "prev_randao": b"\x05" * 32,
        "block_number": 9,
        "gas_limit": 30_000_000,
        "gas_used": 21_000,
        "timestamp": 12,
        "extra_data": b"cap",
        "base_fee_per_gas": 7,
        "block_hash": b"\x06" * 32,
        "transactions": [b"\xaa\xbb"],
        "withdrawals": [
            {
                "index": 0,
                "validator_index": 3,
                "address": b"\x07" * 20,
                "amount": 64,
            }
        ],
    }
    data = T.ExecutionPayloadCapella.serialize(payload)
    back = T.ExecutionPayloadCapella.deserialize(data)
    assert T.ExecutionPayloadCapella.serialize(back) == data
    assert T.ExecutionPayloadCapella.hash_tree_root(payload)

    deneb_payload = dict(payload, blob_gas_used=1, excess_blob_gas=2)
    d2 = T.ExecutionPayloadDeneb.serialize(deneb_payload)
    assert T.ExecutionPayloadDeneb.serialize(
        T.ExecutionPayloadDeneb.deserialize(d2)
    ) == d2
