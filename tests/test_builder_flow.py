"""MEV builder flow: blinded production, signing, unblinding, import.

Reference behaviors: packages/beacon-node/src/execution/builder/http.ts
(getHeader/submitBlindedBlock with transactions_root verification,
circuit breaker), api/impl/validator/index.ts:188-230
(produceBlindedBlock), and validatorStore.ts (signValidatorRegistration
with the builder domain, blinded-block signing).
"""

import pytest

from lodestar_tpu import params
from lodestar_tpu import types as T
from lodestar_tpu.chain.chain import BeaconChain
from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
from lodestar_tpu.crypto import bls as B
from lodestar_tpu.crypto import curves as C
from lodestar_tpu.execution import (
    BuilderError,
    ExecutionBuilderMock,
    ExecutionEngineMock,
    unblind_signed_block,
    verify_revealed_payload,
)
from lodestar_tpu.execution.builder import _FaultWindow
from lodestar_tpu.params import ForkName
from lodestar_tpu.state_transition import create_genesis_state
from lodestar_tpu.state_transition.accessors import get_beacon_proposer_index
from lodestar_tpu.state_transition.slot import process_slots
from lodestar_tpu.validator import ValidatorStore

pytestmark = pytest.mark.smoke

P = params.ACTIVE_PRESET
N_KEYS = 8


@pytest.fixture(scope="module")
def world():
    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG,
        fork_epochs={ForkName.altair: 0, ForkName.bellatrix: 1},
    )
    sks = [B.keygen(b"mev-%d" % i) for i in range(N_KEYS)]
    pks = [C.g1_compress(B.sk_to_pk(sk)) for sk in sks]
    genesis = create_genesis_state(cfg, pks, genesis_time=2)

    el = ExecutionEngineMock()
    chain = BeaconChain(cfg, genesis, execution=el)
    store = ValidatorStore(cfg, dict(enumerate(sks)))

    def proposer_at(slot):
        st = genesis.clone()
        process_slots(st, slot)
        return get_beacon_proposer_index(st)

    def sign_full(block):
        slot = int(block["slot"])
        bt = cfg.get_fork_types(slot)[0]
        root = cfg.compute_signing_root(
            bt.hash_tree_root(block),
            cfg.get_domain(slot, params.DOMAIN_BEACON_PROPOSER, slot),
        )
        return {
            "message": block,
            "signature": C.g2_compress(
                B.sign(sks[int(block["proposer_index"])], root)
            ),
        }

    # reach a post-merge head: altair block, then the merge block
    for slot in (1, P.SLOTS_PER_EPOCH + 1):
        p = proposer_at(slot)
        blk = chain.produce_block(slot, store.sign_randao(p, slot))
        chain.process_block(sign_full(blk))
    return cfg, sks, chain, store, el, proposer_at


def test_blinded_block_produced_unblinded_imported(world):
    """The VERDICT done-criterion: a blinded block produced via a mock
    builder, signed, unblinded through submitBlindedBlock, imported."""
    cfg, sks, chain, store, el, proposer_at = world
    builder = ExecutionBuilderMock(el)
    chain.execution_builder = builder

    slot = P.SLOTS_PER_EPOCH + 2
    proposer = proposer_at(slot)

    # validator registration reaches the relay
    reg = store.sign_validator_registration(
        proposer, b"\x0b" * 20, timestamp=123
    )
    builder.register_validator([reg])
    assert bytes(reg["message"]["pubkey"]) in builder.registrations

    blinded = chain.produce_blinded_block(
        slot, store.sign_randao(proposer, slot)
    )
    assert "execution_payload_header" in blinded["body"]
    assert "execution_payload" not in blinded["body"]

    sig = store.sign_blinded_block(proposer, blinded)
    signed_blinded = {"message": blinded, "signature": sig}
    root = chain.submit_blinded_block(signed_blinded)
    assert chain.head_root_hex == bytes(root).hex()
    assert builder.revealed == 1
    # the imported block is FULL: payload restored, header dropped
    head = chain.head_state
    header = blinded["body"]["execution_payload_header"]
    assert bytes(
        head.latest_execution_payload_header["block_hash"]
    ) == bytes(header["block_hash"])


def test_blinded_and_full_roots_agree(world):
    """hash_tree_root(blinded) == hash_tree_root(unblinded): the
    proposer's signature covers both shapes identically."""
    cfg, sks, chain, store, el, proposer_at = world
    builder = ExecutionBuilderMock(el)
    chain.execution_builder = builder
    slot = P.SLOTS_PER_EPOCH + 3
    proposer = proposer_at(slot)
    blinded = chain.produce_blinded_block(
        slot, store.sign_randao(proposer, slot)
    )
    signed_blinded = {
        "message": blinded,
        "signature": store.sign_blinded_block(proposer, blinded),
    }
    payload, _bundle = builder.submit_blinded_block(signed_blinded)
    full = unblind_signed_block(signed_blinded, payload)
    blinded_root = cfg.get_blinded_fork_types(slot)[0].hash_tree_root(
        blinded
    )
    full_root = cfg.get_fork_types(slot)[0].hash_tree_root(full["message"])
    assert bytes(blinded_root) == bytes(full_root)


def test_substituted_payload_rejected(world):
    """A relay revealing a payload that does not match the signed header
    is caught by the transactions_root/block_hash verification."""
    cfg, sks, chain, store, el, proposer_at = world
    builder = ExecutionBuilderMock(el)
    chain.execution_builder = builder
    slot = P.SLOTS_PER_EPOCH + 4
    proposer = proposer_at(slot)
    blinded = chain.produce_blinded_block(
        slot, store.sign_randao(proposer, slot)
    )
    signed_blinded = {
        "message": blinded,
        "signature": store.sign_blinded_block(proposer, blinded),
    }
    payload, _bundle = builder.submit_blinded_block(signed_blinded)
    evil = dict(payload, block_hash=b"\x66" * 32)
    with pytest.raises(BuilderError, match="block_hash"):
        verify_revealed_payload(signed_blinded, evil)
    evil2 = dict(payload, transactions=[b"\xde\xad"])
    with pytest.raises(BuilderError, match="transactions_root"):
        verify_revealed_payload(signed_blinded, evil2)


def test_builder_disabled_errors(world):
    cfg, sks, chain, store, el, proposer_at = world
    builder = ExecutionBuilderMock(el)
    builder.update_status(False)
    chain.execution_builder = builder
    with pytest.raises(ValueError, match="disabled"):
        chain.produce_blinded_block(P.SLOTS_PER_EPOCH + 5, b"\x00" * 96)
    chain.execution_builder = None
    with pytest.raises(ValueError, match="not set"):
        chain.produce_blinded_block(P.SLOTS_PER_EPOCH + 5, b"\x00" * 96)


def test_relay_faults_trip_breaker_through_chain(world):
    """Repeated produce-time relay faults must disable the builder via
    the circuit breaker (review r5: on_slot_fault had no callers)."""
    from lodestar_tpu.execution import ExecutionBuilderHttp

    cfg, sks, chain, store, el, proposer_at = world
    builder = ExecutionBuilderHttp(
        "http://127.0.0.1:1",  # nothing listens: every call faults
        cfg,
        timeout=0.05,
        fault_inspection_window=params.SLOTS_PER_EPOCH,
        allowed_faults=2,
    )
    builder.update_status(True)
    chain.execution_builder = builder
    base = P.SLOTS_PER_EPOCH + 8
    for i in range(4):
        with pytest.raises(Exception):
            chain.produce_blinded_block(base + i, b"\x00" * 96)
        if not builder.status:
            break
    assert not builder.status, "breaker must trip after allowed faults"


def test_fault_window_circuit_breaker():
    w = _FaultWindow(window=params.SLOTS_PER_EPOCH, allowed=2)
    assert not w.record_fault(10)
    assert not w.record_fault(11)
    assert w.record_fault(12)  # third fault in window trips
    # faults age out of the window
    w2 = _FaultWindow(window=params.SLOTS_PER_EPOCH, allowed=2)
    w2.record_fault(1)
    w2.record_fault(2)
    assert not w2.record_fault(2 + 2 * params.SLOTS_PER_EPOCH)


def test_api_blinded_roundtrip(world):
    """REST surface: produce_blinded_block -> sign -> publish_blinded_block
    imports through the builder; register_validator reaches the relay."""
    from lodestar_tpu.api.encoding import to_json
    from lodestar_tpu.api.server import DefaultHandlers

    cfg, sks, chain, store, el, proposer_at = world
    builder = ExecutionBuilderMock(el)
    chain.execution_builder = builder
    handlers = DefaultHandlers(chain=chain)

    slot = P.SLOTS_PER_EPOCH + 7
    proposer = proposer_at(slot)
    reveal = store.sign_randao(proposer, slot)
    code, resp = handlers.produce_blinded_block(
        {"slot": str(slot), "randao_reveal": "0x" + reveal.hex()}, None
    )
    assert code == 200 and "execution_payload_header" in resp["data"]["body"]

    blinded_type, signed_type, _ = cfg.get_blinded_fork_types(slot)
    from lodestar_tpu.api.encoding import from_json

    blinded = from_json(blinded_type, resp["data"])
    signed = {
        "message": blinded,
        "signature": store.sign_blinded_block(proposer, blinded),
    }
    code, _ = handlers.publish_blinded_block(
        None, to_json(signed_type, signed)
    )
    assert code == 200
    assert builder.revealed >= 1
    assert chain.head_state.slot == slot

    code, _ = handlers.register_validator(
        None,
        [
            to_json(
                T.SignedValidatorRegistrationV1,
                store.sign_validator_registration(proposer, b"\x0c" * 20),
            )
        ],
    )
    assert code == 200
    assert builder.registrations


def test_builder_blobs_bundle_registers_availability():
    """A deneb reveal's blobs bundle becomes validated sidecars in the
    DA tracker before import — the proposer's own blob block passes the
    availability gate (review r5)."""
    import hashlib as _hl

    from lodestar_tpu.crypto import kzg as K
    from lodestar_tpu.state_transition import create_genesis_state

    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG,
        fork_epochs={
            ForkName.altair: 0,
            ForkName.bellatrix: 0,
            ForkName.capella: 0,
            ForkName.deneb: 0,
        },
    )
    sks = [B.keygen(b"bb-%d" % i) for i in range(4)]
    pks = [C.g1_compress(B.sk_to_pk(sk)) for sk in sks]
    setup = K.insecure_dev_setup(8)
    chain = BeaconChain(
        cfg, create_genesis_state(cfg, pks, genesis_time=2), kzg_setup=setup
    )

    blobs = [
        K.polynomial_to_blob(
            [
                int.from_bytes(_hl.sha256(b"bf-%d" % i).digest(), "big") % K.R
                for i in range(8)
            ]
        )
    ]
    commitments = [K.blob_to_kzg_commitment(b, setup) for b in blobs]
    body = T.BeaconBlockBodyDeneb.default()
    body["blob_kzg_commitments"] = list(commitments)
    signed = {
        "message": {
            "slot": 1,
            "proposer_index": 0,
            "parent_root": b"\x01" * 32,
            "state_root": b"\x02" * 32,
            "body": body,
        },
        "signature": b"\x00" * 96,
    }
    chain._register_builder_blobs(
        signed, commitments, {"blobs": blobs, "commitments": commitments, "proofs": []}
    )
    header = dict(signed["message"])
    del header["body"]
    header["body_root"] = T.BeaconBlockBodyDeneb.hash_tree_root(body)
    root = T.BeaconBlockHeader.hash_tree_root(header)
    # the DA gate now passes for this block
    chain._check_data_availability(signed["message"], root)

    # missing bundle or mismatched blob -> hard errors
    with pytest.raises(ValueError, match="bundle"):
        chain._register_builder_blobs(signed, commitments, None)
    bad = {"blobs": [bytes(len(blobs[0]))], "commitments": [], "proofs": []}
    with pytest.raises(ValueError, match="commitment"):
        chain._register_builder_blobs(signed, commitments, bad)


def test_unknown_header_not_revealed(world):
    """The relay only reveals payloads it actually bid."""
    cfg, sks, chain, store, el, proposer_at = world
    builder = ExecutionBuilderMock(el)
    fake_header = T.ExecutionPayloadHeader.default()
    signed_blinded = {
        "message": {
            "slot": P.SLOTS_PER_EPOCH + 6,
            "proposer_index": 0,
            "parent_root": b"\x00" * 32,
            "state_root": b"\x00" * 32,
            "body": {"execution_payload_header": fake_header},
        },
        "signature": b"\x00" * 96,
    }
    with pytest.raises(BuilderError, match="never bid"):
        builder.submit_blinded_block(signed_blinded)
