"""Windowed scalar multiplication vs the oracle at tiny lane widths.

The kernels' value-level curve ops run under plain XLA here (fast on
CPU at [NL, 8]); the slow interpret-mode tier exercises the same code
inside pallas kernels at full tile width.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lodestar_tpu.crypto import curves as GC
from lodestar_tpu.crypto import fields as GF
from lodestar_tpu.kernels import curve as CV
from lodestar_tpu.kernels import layout as LY

pytestmark = pytest.mark.smoke

B = 8
RAND_BITS = 64


def _bits_planes(scalars):
    """MSB-first bit planes int32[RAND_BITS, B]."""
    out = np.zeros((RAND_BITS, len(scalars)), np.int32)
    for j, k in enumerate(scalars):
        for i in range(RAND_BITS):
            out[i, j] = (k >> (RAND_BITS - 1 - i)) & 1
    return jnp.asarray(out)


def _decode_g1(planes, inf):
    xs = LY.decode_batch(np.asarray(planes[0]))
    ys = LY.decode_batch(np.asarray(planes[1]))
    zs = LY.decode_batch(np.asarray(planes[2]))
    out = []
    for x, y, z, i in zip(xs, ys, zs, np.asarray(inf)):
        if i:
            out.append(None)
            continue
        zi = GF.fp_inv(z)
        zi2 = GF.fp_mul(zi, zi)
        out.append((GF.fp_mul(x, zi2), GF.fp_mul(y, GF.fp_mul(zi2, zi))))
    return out


def test_windowed_scalar_mul_matches_oracle_g1():
    rng = np.random.default_rng(0xC0FE)
    pts = [
        GC.scalar_mul(GC.FP_OPS, GC.G1_GEN, int(k))
        for k in rng.integers(2, 1 << 30, B)
    ]
    # edge scalars alongside random 64-bit ones: 0, 1, 2, 3 hit the
    # window table directly; all-ones exercises every add
    scalars = [0, 1, 2, 3, (1 << 64) - 1] + [
        int(k)
        for k in rng.integers(1, 1 << 63, B - 5, dtype=np.uint64)
    ]
    px = jnp.asarray(LY.encode_batch([p[0] for p in pts]))
    py = jnp.asarray(LY.encode_batch([p[1] for p in pts]))
    pz = jnp.asarray(LY.encode_batch([1] * B))
    bits = _bits_planes(scalars)
    q_inf = jnp.zeros((B,), bool)

    @jax.jit
    def run(px, py, pz, bits, q_inf):
        (X, Y, Z), inf = CV.scalar_mul_bits_jac(
            CV.FP_OPS, (px, py, pz), q_inf, lambda i: bits[i], RAND_BITS
        )
        return X, Y, Z, inf.astype(jnp.int32)

    X, Y, Z, inf = run(px, py, pz, bits, q_inf)
    got = _decode_g1((X, Y, Z), inf)
    for pt, k, g in zip(pts, scalars, got):
        want = GC.scalar_mul(GC.FP_OPS, pt, k % GF.R)
        assert g == want, f"k={k}"


def test_windowed_scalar_mul_infinity_base():
    # an infinity base stays infinity for any scalar
    px = jnp.asarray(LY.encode_batch([GC.G1_GEN[0]] * B))
    py = jnp.asarray(LY.encode_batch([GC.G1_GEN[1]] * B))
    pz = jnp.asarray(LY.encode_batch([1] * B))
    bits = _bits_planes([7] * B)
    q_inf = jnp.ones((B,), bool)
    (X, Y, Z), inf = CV.scalar_mul_bits_jac(
        CV.FP_OPS, (px, py, pz), q_inf, lambda i: bits[i], RAND_BITS
    )
    assert bool(jnp.all(inf))
