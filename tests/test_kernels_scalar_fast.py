"""Windowed scalar multiplication vs the oracle at tiny lane widths.

The kernels' value-level curve ops run under plain XLA here (fast on
CPU at [NL, 8]); the slow interpret-mode tier exercises the same code
inside pallas kernels at full tile width.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lodestar_tpu.crypto import curves as GC
from lodestar_tpu.crypto import fields as GF
from lodestar_tpu.kernels import curve as CV
from lodestar_tpu.kernels import layout as LY

pytestmark = pytest.mark.smoke

B = 8
RAND_BITS = 64


def _bits_planes(scalars):
    """MSB-first bit planes int32[RAND_BITS, B]."""
    out = np.zeros((RAND_BITS, len(scalars)), np.int32)
    for j, k in enumerate(scalars):
        for i in range(RAND_BITS):
            out[i, j] = (k >> (RAND_BITS - 1 - i)) & 1
    return jnp.asarray(out)


def _decode_g1(planes, inf):
    xs = LY.decode_batch(np.asarray(planes[0]))
    ys = LY.decode_batch(np.asarray(planes[1]))
    zs = LY.decode_batch(np.asarray(planes[2]))
    out = []
    for x, y, z, i in zip(xs, ys, zs, np.asarray(inf)):
        if i:
            out.append(None)
            continue
        zi = GF.fp_inv(z)
        zi2 = GF.fp_mul(zi, zi)
        out.append((GF.fp_mul(x, zi2), GF.fp_mul(y, GF.fp_mul(zi2, zi))))
    return out


def test_windowed_scalar_mul_matches_oracle_g1():
    rng = np.random.default_rng(0xC0FE)
    pts = [
        GC.scalar_mul(GC.FP_OPS, GC.G1_GEN, int(k))
        for k in rng.integers(2, 1 << 30, B)
    ]
    # edge scalars alongside random 64-bit ones: 0, 1, 2, 3 hit the
    # window table directly; all-ones exercises every add
    scalars = [0, 1, 2, 3, (1 << 64) - 1] + [
        int(k)
        for k in rng.integers(1, 1 << 63, B - 5, dtype=np.uint64)
    ]
    px = jnp.asarray(LY.encode_batch([p[0] for p in pts]))
    py = jnp.asarray(LY.encode_batch([p[1] for p in pts]))
    pz = jnp.asarray(LY.encode_batch([1] * B))
    bits = _bits_planes(scalars)
    q_inf = jnp.zeros((B,), bool)

    @jax.jit
    def run(px, py, pz, bits, q_inf):
        (X, Y, Z), inf = CV.scalar_mul_bits_jac(
            CV.FP_OPS, (px, py, pz), q_inf, lambda i: bits[i], RAND_BITS
        )
        return X, Y, Z, inf.astype(jnp.int32)

    X, Y, Z, inf = run(px, py, pz, bits, q_inf)
    got = _decode_g1((X, Y, Z), inf)
    for pt, k, g in zip(pts, scalars, got):
        want = GC.scalar_mul(GC.FP_OPS, pt, k % GF.R)
        assert g == want, f"k={k}"


def test_windowed_scalar_mul_infinity_base():
    # an infinity base stays infinity for any scalar
    px = jnp.asarray(LY.encode_batch([GC.G1_GEN[0]] * B))
    py = jnp.asarray(LY.encode_batch([GC.G1_GEN[1]] * B))
    pz = jnp.asarray(LY.encode_batch([1] * B))
    bits = _bits_planes([7] * B)
    q_inf = jnp.ones((B,), bool)
    (X, Y, Z), inf = CV.scalar_mul_bits_jac(
        CV.FP_OPS, (px, py, pz), q_inf, lambda i: bits[i], RAND_BITS
    )
    assert bool(jnp.all(inf))


# -- 128-bit, 4-bit-window path (the RLC randomizer scalar mul) -------------

RLC_BITS, W = 128, 4


def _digit_planes(scalars, nbits=RLC_BITS, w=W):
    """MSB-first w-bit window digits int32[nbits/w, B]."""
    out = np.zeros((nbits // w, len(scalars)), np.int32)
    for j, k in enumerate(scalars):
        for t in range(nbits // w):
            out[t, j] = (k >> (nbits - w * (t + 1))) & ((1 << w) - 1)
    return jnp.asarray(out)


def _edge_scalars_128(rng, n_random):
    # 0 (stays infinity), the window-table entries 1..15 boundary cases,
    # a single-bit-above-a-word scalar, and all-ones (every add taken)
    edges = [0, 1, 2, 15, 16, 1 << 64, (1 << 128) - 1]
    return edges + [
        int.from_bytes(rng.bytes(16), "big") | 1 for _ in range(n_random)
    ]


def test_windowed_scalar_mul_narrow_window_matches_oracle_g1():
    """scalar_mul_window_jac at w=2, nbits=32: the same table-build
    recurrence (even entries double, odd entries add Q), digit select
    chain, and int32 infinity carry as the production w=4/128-bit RLC
    configuration, on a traced graph small enough for the fast tier —
    trace+lower cost scales with the 2^w-1 multiple table, so the w=4
    full-width runs (~3 min/core each) live in the slow tier below."""
    rng = np.random.default_rng(0xD0CE)
    nbits, w = 32, 2
    # 0 (stays infinity), every table entry as a leading digit, all-ones
    # (every window add taken, digit 3)
    scalars = [0, 1, 2, 3, (1 << 32) - 1] + [
        int(k) for k in rng.integers(1, 1 << 32, B - 5, dtype=np.uint64)
    ]
    pts = [
        GC.scalar_mul(GC.FP_OPS, GC.G1_GEN, int(k))
        for k in rng.integers(2, 1 << 30, B)
    ]
    px = jnp.asarray(LY.encode_batch([p[0] for p in pts]))
    py = jnp.asarray(LY.encode_batch([p[1] for p in pts]))
    pz = jnp.asarray(LY.encode_batch([1] * B))
    digits = _digit_planes(scalars, nbits=nbits, w=w)
    q_inf = jnp.zeros((B,), bool)

    @jax.jit
    def run(px, py, pz, digits, q_inf):
        (X, Y, Z), inf = CV.scalar_mul_window_jac(
            CV.FP_OPS, (px, py, pz), q_inf, lambda t: digits[t], nbits, w
        )
        return X, Y, Z, inf.astype(jnp.int32)

    X, Y, Z, inf = run(px, py, pz, digits, q_inf)
    got = _decode_g1((X, Y, Z), inf)
    for pt, k, g in zip(pts, scalars, got):
        want = GC.scalar_mul(GC.FP_OPS, pt, k % GF.R)
        assert g == want, f"k={k:#x}"


def test_word_digit_extraction_matches_python():
    """kernels/verify._word_digit (the in-kernel traced-shift digit
    extraction over packed big-endian scalar words) against the python
    ground truth, eager mode — the trickiest indexing in the RLC path."""
    from lodestar_tpu.kernels import verify as KV
    from lodestar_tpu.ops import bls_kernels as BK

    rng = np.random.default_rng(0xD16)
    rwords = BK.make_rand_words(B, rng)
    assert rwords.shape == (KV.RAND_WORDS, B)
    words = np.asarray(rwords).view(np.uint32)  # [RAND_WORDS, B] big-endian
    scalars = [
        sum(
            int(words[i, j]) << (32 * (KV.RAND_WORDS - 1 - i))
            for i in range(KV.RAND_WORDS)
        )
        for j in range(B)
    ]
    w = KV.WINDOW
    for t in range(KV.RAND_BITS // w):
        got = np.asarray(
            KV._word_digit(jnp.asarray(rwords), jnp.int32(t))
        )
        want = [
            (k >> (KV.RAND_BITS - w * (t + 1))) & ((1 << w) - 1)
            for k in scalars
        ]
        assert got.tolist() == want, f"t={t}"


@pytest.mark.slow
def test_windowed128_scalar_mul_matches_oracle_g1():
    rng = np.random.default_rng(0xD1CE)
    scalars = _edge_scalars_128(rng, B - 7)
    pts = [
        GC.scalar_mul(GC.FP_OPS, GC.G1_GEN, int(k))
        for k in rng.integers(2, 1 << 30, B)
    ]
    px = jnp.asarray(LY.encode_batch([p[0] for p in pts]))
    py = jnp.asarray(LY.encode_batch([p[1] for p in pts]))
    pz = jnp.asarray(LY.encode_batch([1] * B))
    digits = _digit_planes(scalars)
    q_inf = jnp.zeros((B,), bool)

    @jax.jit
    def run(px, py, pz, digits, q_inf):
        (X, Y, Z), inf = CV.scalar_mul_window_jac(
            CV.FP_OPS, (px, py, pz), q_inf, lambda t: digits[t], RLC_BITS, W
        )
        return X, Y, Z, inf.astype(jnp.int32)

    X, Y, Z, inf = run(px, py, pz, digits, q_inf)
    got = _decode_g1((X, Y, Z), inf)
    for pt, k, g in zip(pts, scalars, got):
        want = GC.scalar_mul(GC.FP_OPS, pt, k % GF.R)
        assert g == want, f"k={k:#x}"


def _decode_g2(planes, inf):
    x0 = LY.decode_batch(np.asarray(planes[0][0]))
    x1 = LY.decode_batch(np.asarray(planes[0][1]))
    y0 = LY.decode_batch(np.asarray(planes[1][0]))
    y1 = LY.decode_batch(np.asarray(planes[1][1]))
    z0 = LY.decode_batch(np.asarray(planes[2][0]))
    z1 = LY.decode_batch(np.asarray(planes[2][1]))
    out = []
    for a0, a1, b0, b1, c0, c1, i in zip(x0, x1, y0, y1, z0, z1, np.asarray(inf)):
        if i:
            out.append(None)
            continue
        zi = GF.fp2_inv((c0, c1))
        zi2 = GF.fp2_mul(zi, zi)
        out.append(
            (
                GF.fp2_mul((a0, a1), zi2),
                GF.fp2_mul((b0, b1), GF.fp2_mul(zi2, zi)),
            )
        )
    return out


@pytest.mark.slow
def test_windowed128_scalar_mul_matches_oracle_g2():
    rng = np.random.default_rng(0xD2CE)
    scalars = _edge_scalars_128(rng, B - 7)
    pts = [
        GC.scalar_mul(GC.FP2_OPS, GC.G2_GEN, int(k))
        for k in rng.integers(2, 1 << 30, B)
    ]
    qx = (
        jnp.asarray(LY.encode_batch([p[0][0] for p in pts])),
        jnp.asarray(LY.encode_batch([p[0][1] for p in pts])),
    )
    qy = (
        jnp.asarray(LY.encode_batch([p[1][0] for p in pts])),
        jnp.asarray(LY.encode_batch([p[1][1] for p in pts])),
    )
    one2 = (
        jnp.asarray(LY.encode_batch([1] * B)),
        jnp.asarray(LY.encode_batch([0] * B)),
    )
    digits = _digit_planes(scalars)
    q_inf = jnp.zeros((B,), bool)

    @jax.jit
    def run(digits, q_inf):
        (X, Y, Z), inf = CV.scalar_mul_window_jac(
            CV.FP2_OPS, (qx, qy, one2), q_inf, lambda t: digits[t], RLC_BITS, W
        )
        return (X, Y, Z), inf.astype(jnp.int32)

    planes, inf = run(digits, q_inf)
    got = _decode_g2(planes, inf)
    for pt, k, g in zip(pts, scalars, got):
        want = GC.scalar_mul(GC.FP2_OPS, pt, k % GF.R)
        assert g == want, f"k={k:#x}"


@pytest.mark.slow
def test_windowed128_scalar_mul_large_lane_width_g1():
    """Full lane-tile width (the shape the pipeline kernels run at)
    against the numpy/bigint ground truth."""
    n = 128
    rng = np.random.default_rng(0xD3CE)
    pts = [
        GC.scalar_mul(GC.FP_OPS, GC.G1_GEN, int(k))
        for k in rng.integers(2, 1 << 62, n, dtype=np.uint64)
    ]
    scalars = [int.from_bytes(rng.bytes(16), "big") | 1 for _ in range(n)]
    px = jnp.asarray(LY.encode_batch([p[0] for p in pts]))
    py = jnp.asarray(LY.encode_batch([p[1] for p in pts]))
    pz = jnp.asarray(LY.encode_batch([1] * n))
    digits = _digit_planes(scalars)
    q_inf = jnp.zeros((n,), bool)

    @jax.jit
    def run(px, py, pz, digits, q_inf):
        (X, Y, Z), inf = CV.scalar_mul_window_jac(
            CV.FP_OPS, (px, py, pz), q_inf, lambda t: digits[t], RLC_BITS, W
        )
        return X, Y, Z, inf.astype(jnp.int32)

    X, Y, Z, inf = run(px, py, pz, digits, q_inf)
    got = _decode_g1((X, Y, Z), inf)
    for pt, k, g in zip(pts, scalars, got):
        assert g == GC.scalar_mul(GC.FP_OPS, pt, k % GF.R), f"k={k:#x}"
