"""Gossip topics/encoding + a two-node block broadcast over the bus.

Reference: packages/beacon-node/src/network/gossip/ — topic strings,
raw-snappy payloads, altair message ids, publish/dedup semantics.
"""

import pytest

from lodestar_tpu import params
from lodestar_tpu import types as T
from lodestar_tpu.chain.chain import BeaconChain
from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
from lodestar_tpu.crypto import bls as B
from lodestar_tpu.crypto import curves as C
from lodestar_tpu.network.gossip import (
    GossipTopicName,
    InMemoryGossipBus,
    compute_message_id,
    decode_message,
    encode_message,
    parse_topic,
    topic_string,
)
from lodestar_tpu.params import ForkName
from lodestar_tpu.ssz import uint64
from lodestar_tpu.state_transition import create_genesis_state, process_slots
from lodestar_tpu.state_transition.accessors import get_beacon_proposer_index

P = params.ACTIVE_PRESET

pytestmark = pytest.mark.smoke


def test_topic_strings_roundtrip():
    digest = b"\x01\x02\x03\x04"
    t = topic_string(digest, GossipTopicName.beacon_block)
    assert t == "/eth2/01020304/beacon_block/ssz_snappy"
    assert parse_topic(t) == (digest, "beacon_block")

    ta = topic_string(digest, GossipTopicName.beacon_attestation, subnet=7)
    assert "beacon_attestation_7" in ta
    with pytest.raises(ValueError):
        topic_string(digest, GossipTopicName.beacon_attestation)
    with pytest.raises(ValueError):
        parse_topic("/eth1/xx/beacon_block/ssz_snappy")


def test_message_encoding_and_id():
    payload = b"attestation bytes" * 10
    wire = encode_message(payload)
    assert decode_message(wire) == payload
    topic = "/eth2/01020304/beacon_block/ssz_snappy"
    mid = compute_message_id(topic, wire)
    assert len(mid) == 20
    # id binds BOTH topic and content
    assert mid != compute_message_id(topic, encode_message(payload + b"!"))
    assert mid != compute_message_id(
        "/eth2/01020304/voluntary_exit/ssz_snappy", wire
    )
    # undecodable payload still produces a stable id (invalid domain)
    bad = b"\xff" * 30
    assert compute_message_id(topic, bad) == compute_message_id(topic, bad)


def test_bus_dedup_and_isolation():
    bus = InMemoryGossipBus()
    got = {"b": 0, "c": 0}
    bus.subscribe("b", "t", lambda t_, d: got.__setitem__("b", got["b"] + 1))

    def boom(t_, d):
        got["c"] += 1
        raise RuntimeError("bad subscriber")

    bus.subscribe("c", "t", boom)
    wire = encode_message(b"hello")
    assert bus.publish("a", "t", wire) == 1  # c's failure is isolated
    assert got == {"b": 1, "c": 1}
    # duplicate suppressed per node
    assert bus.publish("a", "t", wire) == 0
    assert bus.duplicates >= 1
    # the publisher itself is skipped: only the failing subscriber c
    # remains, so nothing is delivered but c was attempted once more
    assert bus.publish("b", "t", encode_message(b"hello2")) == 0
    assert got == {"b": 1, "c": 2}


def test_two_node_block_broadcast():
    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG, fork_epochs={ForkName.altair: 0}
    )
    sks = [B.keygen(b"goss-%d" % i) for i in range(16)]
    pks = [C.g1_compress(B.sk_to_pk(sk)) for sk in sks]
    genesis = create_genesis_state(cfg, pks, genesis_time=2)
    chain_a = BeaconChain(cfg, genesis)
    chain_b = BeaconChain(cfg, genesis)

    bus = InMemoryGossipBus()
    topic = topic_string(cfg.fork_digest(0), GossipTopicName.beacon_block)

    def b_handler(t, data):
        signed = T.SignedBeaconBlockAltair.deserialize(decode_message(data))
        chain_b.process_block(signed)

    bus.subscribe("b", topic, b_handler)

    # node A proposes and broadcasts
    pre = genesis.clone()
    process_slots(pre, 1)
    proposer = get_beacon_proposer_index(pre)
    reveal = B.sign_bytes(
        sks[proposer],
        cfg.compute_signing_root(
            uint64.hash_tree_root(0), cfg.get_domain(1, params.DOMAIN_RANDAO)
        ),
    )
    block = chain_a.produce_block(1, reveal)
    root = cfg.compute_signing_root(
        T.BeaconBlockAltair.hash_tree_root(block),
        cfg.get_domain(1, params.DOMAIN_BEACON_PROPOSER, 1),
    )
    signed = {"message": block, "signature": B.sign_bytes(sks[proposer], root)}
    chain_a.process_block(signed)
    wire = encode_message(T.SignedBeaconBlockAltair.serialize(signed))
    assert bus.publish("a", topic, wire) == 1

    # node B imported the exact same chain
    assert chain_b.head_root_hex == chain_a.head_root_hex
    assert chain_b.head_state.hash_tree_root() == (
        chain_a.head_state.hash_tree_root()
    )
