"""BlsVerifierService: buffering, backpressure, retry, shutdown semantics.

Uses a stub verifier (host-only) so the service contract is tested
without device time; the device paths are covered by test_verifier.py.
Reference: packages/beacon-node/src/chain/bls/multithread/index.ts.
"""

import threading
import time

import pytest

from lodestar_tpu.bls.service import BlsVerifierService
from lodestar_tpu.bls.signature_set import SignatureSet, WireSignatureSet
from lodestar_tpu.bls.verifier import VerifyOptions
from lodestar_tpu.utils.metrics import BlsPoolMetrics

pytestmark = pytest.mark.smoke


class StubVerifier:
    """Scriptable IBlsVerifier: records calls, configurable delay/verdict."""

    def __init__(self, delay=0.0, verdict=True):
        self.metrics = BlsPoolMetrics()
        self.delay = delay
        self.verdict = verdict
        self.calls = []
        self._lock = threading.Lock()

    def verify_signature_sets(self, sets, opts=None):
        with self._lock:
            self.calls.append((len(sets), opts))
        if self.delay:
            time.sleep(self.delay)
        v = self.verdict
        return v(sets) if callable(v) else v

    def close(self):
        pass


def fake_set(i):
    return SignatureSet.single(i, ("m", i), ("s", i))


def test_small_batchable_jobs_coalesce():
    stub = StubVerifier()
    svc = BlsVerifierService(stub, buffer_wait_ms=30)
    futs = [
        svc.verify_signature_sets_async([fake_set(i)], VerifyOptions(batchable=True))
        for i in range(3)
    ]
    assert all(f.result(timeout=5) for f in futs)
    svc.close()
    # all three 1-set jobs merged into one 3-set device call
    merged_calls = [c for c in stub.calls if c[0] == 3]
    assert len(merged_calls) == 1 and len(stub.calls) == 1


def test_buffer_flushes_at_max_sigs_without_waiting():
    stub = StubVerifier()
    svc = BlsVerifierService(stub, max_buffered_sigs=4, buffer_wait_ms=10_000)
    futs = [
        svc.verify_signature_sets_async([fake_set(i)], VerifyOptions(batchable=True))
        for i in range(4)
    ]
    t0 = time.perf_counter()
    assert all(f.result(timeout=5) for f in futs)
    assert time.perf_counter() - t0 < 5  # did not wait for the 10 s window
    svc.close()


class HandleStub(StubVerifier):
    """Stub with the begin/finish device-handle protocol, so dispatched
    jobs land in the service's job_timings records."""

    max_job_sets = 512

    class _Handle:
        def __init__(self, sets):
            self.sets = sets
            self.ok_big = True
            self.batch_retries = 0
            self.batch_sigs_success = len(sets)
            self.verdicts = None

    def begin_job(self, sets, batchable):
        with self._lock:
            self.calls.append((len(sets), batchable))
        return self._Handle(sets)

    def finish_job(self, handle):
        return True


def test_exact_bucket_fill_flushes_without_deadline():
    """RLC coalescing: buffered batchable sets that exactly fill the
    current N-bucket dispatch immediately — waiting out the deadline
    could only add padding-free latency or spill into the next bucket
    (regression: ISSUE 10 satellite, asserted on job_timings)."""
    stub = HandleStub()
    svc = BlsVerifierService(
        stub, max_buffered_sigs=512, buffer_wait_ms=10_000
    )
    t0 = time.perf_counter()
    futs = [
        svc.verify_signature_sets_async(
            [fake_set(i)], VerifyOptions(batchable=True)
        )
        for i in range(128)  # == the smallest N-bucket, < max_buffered
    ]
    assert all(f.result(timeout=5) for f in futs)
    assert time.perf_counter() - t0 < 5  # did not wait out the window
    svc.close()
    timings = svc.job_timings()
    assert len(timings) == 1 and timings[0]["sig_sets"] == 128
    # one merged 128-set device job, dispatched as one run
    assert stub.calls == [(128, True)]


def test_mixed_kind_buffer_fill_does_not_flush_early():
    """The exact-fill trigger keys on the LAST dispatch run (contiguous
    same-kind sets, wire vs decoded): 100 wire + 28 decoded sets total
    128, but dispatch would split them into a 100-set and a 28-set
    device job — neither padding-free — so the buffer keeps coalescing;
    once the trailing decoded run itself reaches 128 the flush fires."""
    stub = HandleStub()
    svc = BlsVerifierService(stub, max_buffered_sigs=512, buffer_wait_ms=8000)
    t0 = time.perf_counter()
    futs = [
        svc.verify_signature_sets_async(
            [WireSignatureSet.single(i, b"m" * 32, b"\xc0" + b"\x00" * 95)],
            VerifyOptions(batchable=True),
        )
        for i in range(100)
    ] + [
        svc.verify_signature_sets_async(
            [fake_set(i)], VerifyOptions(batchable=True)
        )
        for i in range(28)
    ]
    time.sleep(0.05)
    assert stub.calls == []  # 128 buffered, but the last run holds 28
    futs += [
        svc.verify_signature_sets_async(
            [fake_set(100 + i)], VerifyOptions(batchable=True)
        )
        for i in range(100)  # trailing decoded run: 28 -> 128 == bucket
    ]
    assert all(f.result(timeout=5) for f in futs)
    assert time.perf_counter() - t0 < 5  # did not wait out the window
    svc.close()
    assert sum(c[0] for c in stub.calls) == 228


def test_partial_bucket_still_waits_for_deadline():
    stub = HandleStub()
    # deadline far above the 50ms probe sleep so a stalled CI scheduler
    # cannot legitimately flush before the mid-test assert
    svc = BlsVerifierService(stub, max_buffered_sigs=512, buffer_wait_ms=1000)
    futs = [
        svc.verify_signature_sets_async(
            [fake_set(i)], VerifyOptions(batchable=True)
        )
        for i in range(20)  # under the 128 bucket: no immediate flush
    ]
    time.sleep(0.05)
    assert stub.calls == []  # still buffering toward the deadline
    assert all(f.result(timeout=5) for f in futs)
    svc.close()
    # flushed by the deadline, not the bucket rule (tolerate a stalled
    # scheduler splitting the window into more than one group)
    assert sum(c[0] for c in stub.calls) == 20


def test_flush_deadline_anchors_on_oldest_set():
    """Regression (ISSUE 11 satellite): the flush timer anchors on the
    OLDEST buffered set's enqueue time (`_Job.t_submit`, stamped before
    lock acquisition) — staggered submits must flush one window after
    the FIRST submit, so p99 submit->flush is actually bounded by
    MAX_BUFFER_WAIT_MS."""
    stub = HandleStub()
    svc = BlsVerifierService(stub, buffer_wait_ms=400)
    t0 = time.perf_counter()
    fa = svc.verify_signature_sets_async(
        [fake_set(0)], VerifyOptions(batchable=True)
    )
    time.sleep(0.35)  # inside the window
    fb = svc.verify_signature_sets_async(
        [fake_set(1)], VerifyOptions(batchable=True)
    )
    assert fa.result(timeout=5) and fb.result(timeout=5)
    elapsed = time.perf_counter() - t0
    svc.close()
    # correct anchor: ~0.40s after the first submit; a timer re-anchored
    # at the second submit would stretch to ~0.75s
    assert elapsed < 0.62, f"flush took {elapsed:.3f}s — deadline re-anchored?"
    assert sum(c[0] for c in stub.calls) == 2


def test_non_batchable_jobs_bypass_buffer():
    stub = StubVerifier()
    svc = BlsVerifierService(stub, buffer_wait_ms=10_000)
    fut = svc.verify_signature_sets_async([fake_set(0)], VerifyOptions())
    assert fut.result(timeout=5)
    svc.close()
    assert stub.calls and stub.calls[0][0] == 1


def test_merged_batch_failure_gives_per_job_verdicts():
    # verdict: merged call (3 sets) fails; per-job retries succeed for the
    # two jobs without the poisoned set
    def verdict(sets):
        ids = [s.indices[0] for s in sets]
        return 666 not in ids

    stub = StubVerifier(verdict=verdict)
    svc = BlsVerifierService(stub, buffer_wait_ms=20)
    good1 = svc.verify_signature_sets_async([fake_set(1)], VerifyOptions(batchable=True))
    bad = svc.verify_signature_sets_async([fake_set(666)], VerifyOptions(batchable=True))
    good2 = svc.verify_signature_sets_async([fake_set(2)], VerifyOptions(batchable=True))
    assert good1.result(timeout=5) is True
    assert bad.result(timeout=5) is False
    assert good2.result(timeout=5) is True
    svc.close()


def test_backpressure_flips_under_load():
    stub = StubVerifier(delay=0.05)
    svc = BlsVerifierService(stub, max_pending_jobs=4, buffer_wait_ms=1)
    assert svc.can_accept_work()
    futs = [
        svc.verify_signature_sets_async([fake_set(i)], VerifyOptions())
        for i in range(5)
    ]
    assert not svc.can_accept_work()          # >= 4 pending
    assert svc.metrics.queue_length.value >= 4
    assert all(f.result(timeout=5) for f in futs)
    deadline = time.time() + 5
    while not svc.can_accept_work() and time.time() < deadline:
        time.sleep(0.01)
    assert svc.can_accept_work()              # drained
    assert svc.metrics.job_wait_time.count >= 5
    svc.close()


def test_verify_on_main_thread_is_synchronous():
    calls = []

    class SyncStub(StubVerifier):
        def verify_signature_sets(self, sets, opts=None):
            calls.append(threading.current_thread().name)
            return True

    svc = BlsVerifierService(SyncStub())
    fut = svc.verify_signature_sets_async(
        [fake_set(0)], VerifyOptions(verify_on_main_thread=True)
    )
    assert fut.done() and fut.result() is True
    assert calls == [threading.current_thread().name]  # caller thread
    svc.close()


def test_close_rejects_queued_jobs():
    stub = StubVerifier(delay=0.2)
    svc = BlsVerifierService(stub, buffer_wait_ms=1)
    running = svc.verify_signature_sets_async([fake_set(0)], VerifyOptions())
    time.sleep(0.05)  # let the dispatcher pick up the first job
    queued = svc.verify_signature_sets_async([fake_set(1)], VerifyOptions())
    svc.close()
    assert running.result(timeout=5) is True
    with pytest.raises(RuntimeError):
        queued.result(timeout=5)
    late = svc.verify_signature_sets_async([fake_set(2)], VerifyOptions())
    with pytest.raises(RuntimeError):
        late.result(timeout=5)
