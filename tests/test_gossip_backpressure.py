"""Gossip-queue drop policies under sustained backpressure (ISSUE 11).

The coupling under test: while the verification pipeline's high-water
mark holds `can_accept_work()` False, the NetworkProcessor stops
pulling, the per-topic queues overflow, and on every shed message the
depth gauge, the dropped counter, AND the peer scorer's backpressure
penalty fire together — then all three recover once the pipeline drains
and the processor resumes.
"""

import pytest

from lodestar_tpu.network.gossip_queues import (
    DropByCount,
    DropByRatio,
    GossipQueue,
    GossipQueueOpts,
    GossipType,
    QueueType,
)
from lodestar_tpu.network.processor import NetworkProcessor, PendingGossipMessage
from lodestar_tpu.network.scoring import (
    GOSSIP_SCORE_THRESHOLDS,
    GossipPeerScorer,
    PeerScoreParams,
)
from lodestar_tpu.utils.metrics import Registry

pytestmark = pytest.mark.smoke


def make_scorer():
    return GossipPeerScorer(
        PeerScoreParams(
            behaviour_penalty_weight=-100.0,
            behaviour_penalty_threshold=2.0,
            behaviour_penalty_decay=0.2,
            decay_to_zero=0.01,
        )
    )


def make_processor(topic, opts, accept_flag, registry, scorer):
    done = []
    proc = NetworkProcessor(
        lambda msg: done.append(msg),
        [lambda: accept_flag["ok"]],
        registry=registry,
        scorer=scorer,
    )
    # shrink the topic's queue so overflow is reachable in a fast test;
    # reuse the processor's metrics object (so the gauge/counter series
    # under test are the production ones) and its per-item drop hook
    metrics = proc.queues[topic].metrics
    proc.queues[topic] = GossipQueue(
        opts,
        topic=topic.value,
        metrics=metrics,
        on_drop=proc._on_queue_drop if scorer is not None else None,
    )
    return proc, done


def msg(topic, i, peer="flooder"):
    return PendingGossipMessage(topic, ("payload", i), peer_id=peer)


def test_drop_by_count_backpressure_fires_all_three_signals_and_recovers():
    topic = GossipType.beacon_aggregate_and_proof  # LIFO, DropByCount
    reg = Registry()
    scorer = make_scorer()
    accept = {"ok": False}  # pipeline saturated: processor must not pull
    proc, done = make_processor(
        topic,
        GossipQueueOpts(QueueType.LIFO, 8, DropByCount(1)),
        accept,
        reg,
        scorer,
    )
    for i in range(12):
        proc.on_gossip_message(msg(topic, i))
    assert done == []  # nothing pulled under backpressure
    # the three signals fire together:
    depth = reg.get("lodestar_gossip_queue_length")
    dropped = reg.get("lodestar_gossip_queue_dropped_total")
    assert depth.get(topic.value) == 8.0
    assert dropped.get(topic.value) == 4.0
    assert proc.stats.dropped == 4
    assert scorer.behaviour_penalty("flooder") == 4.0
    # 4 penalties, threshold 2 -> P7 = -100 * (4-2)^2
    assert scorer.gossip_score("flooder") == pytest.approx(-400.0)
    assert proc.stats.cannot_accept_ticks > 0

    # drain: the pipeline catches up, the processor resumes pulling
    accept["ok"] = True
    proc.execute_work()
    assert len(done) == 8
    assert depth.get(topic.value) == 0.0
    # no new drops or penalties after the drain
    proc.on_gossip_message(msg(topic, 99))
    assert dropped.get(topic.value) == 4.0
    assert scorer.behaviour_penalty("flooder") == 4.0
    # and the peer's score recovers as the penalty counter decays
    for _ in range(10):
        scorer.decay()
    assert scorer.behaviour_penalty("flooder") == 0.0
    assert scorer.gossip_score("flooder") == 0.0
    assert not scorer.is_banned("flooder")


def test_drop_by_ratio_escalates_and_charges_per_shed_message():
    topic = GossipType.beacon_attestation  # LIFO, DropByRatio
    reg = Registry()
    scorer = make_scorer()
    accept = {"ok": False}
    proc, done = make_processor(
        topic,
        GossipQueueOpts(QueueType.LIFO, 10, DropByRatio(0.2, 0.2)),
        accept,
        reg,
        scorer,
    )
    q = proc.queues[topic]
    total_dropped = 0
    for i in range(40):
        proc.on_gossip_message(msg(topic, i))
    dropped = reg.get("lodestar_gossip_queue_dropped_total")
    depth = reg.get("lodestar_gossip_queue_length")
    total_dropped = dropped.get(topic.value)
    assert total_dropped > 0
    # escalation: the ratio stepped past its start after repeat overflows
    assert q.drop_ratio > 0.2
    # every shed message charged the publisher, 1:1
    assert scorer.behaviour_penalty("flooder") == total_dropped
    assert depth.get(topic.value) == float(len(q))
    assert scorer.gossip_score("flooder") < 0

    # sustained flooding puts the peer past the graylist threshold
    for i in range(300):
        proc.on_gossip_message(msg(topic, 1000 + i))
    assert scorer.is_banned("flooder")
    assert (
        scorer.gossip_score("flooder")
        <= GOSSIP_SCORE_THRESHOLDS.graylist_threshold
    )

    # drain and recover
    accept["ok"] = True
    while proc.execute_work():
        pass
    assert depth.get(topic.value) == 0.0
    for _ in range(60):
        scorer.decay()
    assert not scorer.is_banned("flooder")


def test_drops_without_peer_attribution_do_not_charge():
    topic = GossipType.beacon_aggregate_and_proof
    reg = Registry()
    scorer = make_scorer()
    proc, _ = make_processor(
        topic,
        GossipQueueOpts(QueueType.LIFO, 4, DropByCount(1)),
        {"ok": False},
        reg,
        scorer,
    )
    for i in range(8):
        proc.on_gossip_message(msg(topic, i, peer=None))
    assert reg.get("lodestar_gossip_queue_dropped_total").get(topic.value) == 4.0
    assert scorer.behaviour_penalty("flooder") == 0.0
    assert scorer._behaviour_penalties == {}


def test_drops_charge_the_shed_messages_publisher_not_the_trigger():
    """Review fix: a LIFO ratio-drop sheds the OLDEST backlog — the
    flooder's — so an honest peer whose single publish overflows the
    queue must not be the one charged."""
    topic = GossipType.beacon_attestation
    reg = Registry()
    scorer = make_scorer()
    proc, _ = make_processor(
        topic,
        GossipQueueOpts(QueueType.LIFO, 10, DropByRatio(0.2, 0.2)),
        {"ok": False},
        reg,
        scorer,
    )
    for i in range(10):  # the flooder fills the queue exactly
        proc.on_gossip_message(msg(topic, i, peer="flooder"))
    assert scorer.behaviour_penalty("flooder") == 0.0  # no overflow yet
    # one honest publish overflows: the shed messages are the flooder's
    proc.on_gossip_message(msg(topic, 99, peer="honest"))
    dropped = reg.get("lodestar_gossip_queue_dropped_total").get(topic.value)
    assert dropped > 0
    assert scorer.behaviour_penalty("honest") == 0.0
    assert scorer.behaviour_penalty("flooder") == dropped
    # the honest peer's message survived (LIFO keeps the newest)
    assert any(
        m.peer_id == "honest" for m in proc.queues[topic].get_all()
    )
