"""Slasher: golden surround/double-vote cases, vectorized-vs-naive
cross-checks, persistence, and the service-level gossip -> detection ->
op-pool -> block-inclusion round trip.

Reference semantics: spec is_slashable_attestation_data (double vote /
surround vote) and the lighthouse-style min-max span arrays the
vectorized path implements (lodestar_tpu/slasher/batch.py).
"""

import dataclasses

import numpy as np
import pytest

from lodestar_tpu import params
from lodestar_tpu import types as T
from lodestar_tpu.bls.single_thread import CpuBlsVerifier
from lodestar_tpu.chain.chain import BeaconChain
from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
from lodestar_tpu.crypto import bls as B
from lodestar_tpu.crypto import curves as C
from lodestar_tpu.network.gossip import (
    GossipTopicName,
    InMemoryGossipBus,
    encode_message,
    topic_string,
)
from lodestar_tpu.network.gossip_handlers import GossipHandlers
from lodestar_tpu.params import ForkName
from lodestar_tpu.slasher import (
    AttesterSlasher,
    NaiveAttesterSlasher,
    ProposerSlasher,
    SlasherService,
    is_double_vote,
    is_surround_vote,
)
from lodestar_tpu.state_transition import create_genesis_state, state_transition
from lodestar_tpu.state_transition.accessors import get_beacon_committee
from lodestar_tpu.utils.metrics import Registry
from lodestar_tpu.validator import ValidatorStore

P = params.ACTIVE_PRESET
N_KEYS = 16


def _data(source, target, root=b"\x07" * 32, slot=0, index=0):
    return {
        "slot": slot,
        "index": index,
        "beacon_block_root": root,
        "source": {"epoch": source, "root": b"\x00" * 32},
        "target": {"epoch": target, "root": b"\x11" * 32},
    }


def _att(validators, source, target, root=b"\x07" * 32, slot=0, index=0):
    return {
        "attesting_indices": sorted(int(v) for v in validators),
        "data": _data(source, target, root=root, slot=slot, index=index),
        "signature": b"\x00" * 96,
    }


# -- golden cases -----------------------------------------------------------


def test_golden_double_vote():
    a = _data(0, 3, root=b"\x01" * 32)
    b = _data(0, 3, root=b"\x02" * 32)
    assert is_double_vote(a, b) and is_double_vote(b, a)
    # identical data is NOT a double vote
    assert not is_double_vote(a, _data(0, 3, root=b"\x01" * 32))
    # same root different target: neither
    assert not is_double_vote(a, _data(0, 4, root=b"\x01" * 32))
    # different source, same target, different data -> still double
    assert is_double_vote(a, _data(1, 3, root=b"\x01" * 32))


def test_golden_surround():
    # strict on both sides
    assert is_surround_vote(_data(0, 5), _data(1, 4))
    assert not is_surround_vote(_data(1, 4), _data(0, 5))
    assert not is_surround_vote(_data(0, 5), _data(0, 4))  # equal sources
    assert not is_surround_vote(_data(0, 5), _data(1, 5))  # equal targets
    # distance-1 edges: the tightest possible surround
    assert is_surround_vote(_data(0, 3), _data(1, 2))
    assert not is_surround_vote(_data(0, 2), _data(1, 2))
    assert not is_surround_vote(_data(1, 2), _data(1, 3))
    # source == target: can be surrounded, can never surround
    assert is_surround_vote(_data(4, 6), _data(5, 5))
    assert not is_surround_vote(_data(5, 5), _data(4, 6))
    assert not is_surround_vote(_data(5, 5), _data(5, 5))


def test_span_detector_golden_cases():
    s = AttesterSlasher(history_length=64, chunk_size=8)
    assert s.process_batch([_att([1], 1, 4, root=b"\x01" * 32)]) == []
    # surrounding vote detected, attestation_1 is the surrounding one
    dets = s.process_batch([_att([1], 0, 5, root=b"\x02" * 32)])
    assert [k for k, _ in dets] == ["surround"]
    sl = dets[0][1]
    assert int(sl["attestation_1"]["data"]["source"]["epoch"]) == 0
    assert int(sl["attestation_2"]["data"]["source"]["epoch"]) == 1
    # a vote surrounded by an existing one
    dets = s.process_batch([_att([1], 2, 3, root=b"\x03" * 32)])
    assert "surrounded" in [k for k, _ in dets]
    # double vote at target 4 with a different root
    dets = s.process_batch([_att([1], 2, 4, root=b"\x04" * 32)])
    assert "double_vote" in [k for k, _ in dets]
    # replaying an identical attestation is a no-op
    assert s.process_batch([_att([1], 1, 4, root=b"\x01" * 32)]) == []


def test_span_detector_source_equals_target_edges():
    s = AttesterSlasher(history_length=64, chunk_size=8)
    s.process_batch([_att([3], 4, 6, root=b"\x01" * 32)])
    # (5,5) is surrounded by (4,6)
    dets = s.process_batch([_att([3], 5, 5, root=b"\x02" * 32)])
    assert [k for k, _ in dets] == ["surrounded"]
    # distance-1: (3,7) surrounds (4,6)
    dets = s.process_batch([_att([3], 3, 7, root=b"\x03" * 32)])
    assert [k for k, _ in dets] == ["surround"]


def test_intra_batch_detection():
    """Conflicting attestations arriving in the SAME batch detect."""
    s = AttesterSlasher(history_length=64, chunk_size=8)
    dets = s.process_batch(
        [
            _att([2], 1, 4, root=b"\x01" * 32),
            _att([2], 0, 5, root=b"\x02" * 32),
        ]
    )
    kinds = {k for k, _ in dets}
    assert kinds & {"surround", "surrounded"}


def test_old_source_surround_still_caught_after_prune():
    """An attestation whose SOURCE predates the pruned window base must
    still poison the max-spans inside the window, so a later inner vote
    is detected (the classic old-source surround attack)."""
    s = AttesterSlasher(history_length=16, chunk_size=4)
    s.prune(8)  # window base advances to epoch 8
    assert s.spans.base_epoch == 8
    # outer vote with source BELOW the base
    assert s.process_batch([_att([1], 4, 20, root=b"\x01" * 32)]) == []
    # inner vote inside the window: surrounded by the outer one
    dets = s.process_batch([_att([1], 9, 15, root=b"\x02" * 32)])
    assert [k for k, _ in dets] == ["surrounded"]
    sl = dets[0][1]
    assert int(sl["attestation_1"]["data"]["source"]["epoch"]) == 4


def test_span_window_advance():
    s = AttesterSlasher(history_length=16, chunk_size=4)
    s.process_batch([_att([0], 1, 2)])
    # a target far past the window forces a chunk-aligned base advance
    s.process_batch([_att([0], 40, 41, root=b"\x09" * 32)])
    assert s.spans.base_epoch > 0
    assert s.spans.base_epoch % 4 == 0
    assert 41 < s.spans.base_epoch + s.spans.history_length
    # pruning drops records below the floor
    s.prune(40)
    assert all(
        t >= 40 for recs in s._records.values() for (_s, t) in recs
    )


def _offender_pairs(dets):
    out = set()
    for kind, sl in dets:
        if kind in ("surround", "surrounded"):
            kind = "surround*"  # intra-batch group order can flip the side
        inter = set(
            int(i) for i in sl["attestation_1"]["attesting_indices"]
        ) & set(int(i) for i in sl["attestation_2"]["attesting_indices"])
        out.update((kind, v) for v in inter)
    return out


def _random_cross_check(
    n_validators, n_epochs, n_atts, batch_size, seed, span_backend="numpy"
):
    rng = np.random.default_rng(seed)
    fast = AttesterSlasher(
        history_length=max(64, n_epochs * 2),
        chunk_size=16,
        num_validators=n_validators,
        span_backend=span_backend,
    )
    naive = NaiveAttesterSlasher()
    atts = []
    for i in range(n_atts):
        t = int(rng.integers(1, n_epochs))
        s = int(rng.integers(0, t + 1))
        k = int(rng.integers(1, 4))
        vs = rng.choice(n_validators, size=k, replace=False)
        # small root space so double votes actually occur
        root = bytes([int(rng.integers(0, 6))]) * 32
        atts.append(_att(vs, s, t, root=root))
    total_fast, total_naive = set(), set()
    for i in range(0, n_atts, batch_size):
        batch = atts[i : i + batch_size]
        total_fast |= _offender_pairs(fast.process_batch(batch))
        total_naive |= _offender_pairs(naive.process_batch(batch))
    assert total_fast == total_naive
    return total_fast


def test_randomized_cross_check_small():
    hits = _random_cross_check(
        n_validators=64, n_epochs=48, n_atts=300, batch_size=16, seed=11
    )
    assert hits  # the load is dense enough that conflicts exist


def test_randomized_cross_check_single_steps():
    """Batch size 1: exact kind agreement (no intra-batch order skew)."""

    def exact_pairs(dets):
        out = set()
        for kind, sl in dets:
            inter = set(
                int(i) for i in sl["attestation_1"]["attesting_indices"]
            ) & set(int(i) for i in sl["attestation_2"]["attesting_indices"])
            out.update((kind, v) for v in inter)
        return out

    rng = np.random.default_rng(5)
    fast = AttesterSlasher(history_length=128, chunk_size=8)
    naive = NaiveAttesterSlasher()
    for _ in range(250):
        t = int(rng.integers(1, 40))
        s = int(rng.integers(0, t + 1))
        v = int(rng.integers(0, 24))
        root = bytes([int(rng.integers(0, 5))]) * 32
        batch = [_att([v], s, t, root=root)]
        assert exact_pairs(fast.process_batch(batch)) == exact_pairs(
            naive.process_batch(batch)
        )


@pytest.mark.slow
def test_randomized_cross_check_1k():
    """Acceptance-scale cross-check: 1k validators x 1k epochs."""
    hits = _random_cross_check(
        n_validators=1000, n_epochs=1000, n_atts=4000, batch_size=64, seed=3
    )
    assert hits


# -- jitted span kernel (slasher/device.py) ---------------------------------


def test_jax_span_planes_match_numpy_kernel():
    """The whole-window jitted update is bit-identical to the chunked
    numpy ground truth across random apply/advance/growth sequences."""
    import random as _random

    from lodestar_tpu.slasher import JaxSpanState, SpanState

    rng = _random.Random(17)
    a = SpanState(num_validators=8, history_length=64, chunk_size=8)
    b = JaxSpanState(
        num_validators=8, history_length=64, chunk_size=8, use_export=False
    )
    for step in range(40):
        t = rng.randint(0, 90)
        s = rng.randint(0, t)
        rows = np.array(
            sorted(rng.sample(range(24), rng.randint(1, 5))), np.intp
        )
        for sp in (a, b):
            sp.ensure_epoch(t)
            sp.ensure_validators(int(rows.max()) + 1)
            sp.apply(rows, s, t)
        assert a.base_epoch == b.base_epoch
        if s >= a.base_epoch:
            la, lb = a.lookup(rows, s), b.lookup(rows, s)
            assert (np.asarray(la[0]) == np.asarray(lb[0])).all()
            assert (np.asarray(la[1]) == np.asarray(lb[1])).all()
        if step % 11 == 10:
            a.advance_base(a.base_epoch + 16)
            b.advance_base(b.base_epoch + 16)
    snap = b.snapshot()
    assert (snap.min_spans == a.min_spans).all()
    assert (snap.max_spans == a.max_spans).all()


@pytest.mark.slow
def test_randomized_cross_check_jax_backend():
    """Full detector over the device-resident span planes == naive."""
    hits = _random_cross_check(
        n_validators=128,
        n_epochs=96,
        n_atts=600,
        batch_size=32,
        seed=23,
        span_backend="jax",
    )
    assert hits


# -- proposer detection -----------------------------------------------------


def _signed_header(slot, proposer, body_root, sig=b"\x00" * 96):
    return {
        "message": {
            "slot": slot,
            "proposer_index": proposer,
            "parent_root": b"\x01" * 32,
            "state_root": b"\x02" * 32,
            "body_root": body_root,
        },
        "signature": sig,
    }


def test_proposer_double_propose_index():
    p = ProposerSlasher()
    assert p.process(_signed_header(3, 7, b"\x0a" * 32)) is None
    # identical header re-observed: no-op
    assert p.process(_signed_header(3, 7, b"\x0a" * 32)) is None
    # same slot+proposer, different body: double proposal
    sl = p.process(_signed_header(3, 7, b"\x0b" * 32))
    assert sl is not None
    assert sl["signed_header_1"]["message"]["body_root"] == b"\x0a" * 32
    # a different proposer at the same slot is clean
    assert p.process(_signed_header(3, 8, b"\x0c" * 32)) is None
    p.prune(4)
    assert p.record_count() == 0


# -- persistence ------------------------------------------------------------


def _signed_block(slot, proposer, graffiti=b"\x00" * 32):
    body = _empty_altair_body()
    body["graffiti"] = graffiti
    return {
        "message": {
            "slot": slot,
            "proposer_index": proposer,
            "parent_root": b"\x00" * 32,
            "state_root": b"\x00" * 32,
            "body": body,
        },
        "signature": b"\x00" * 96,
    }


def test_store_roundtrip_and_restart_detection():
    from lodestar_tpu.db.beacon_db import BeaconDb

    db = BeaconDb(None)
    svc = SlasherService(chain=None, db=db, history_length=64, chunk_size=8)
    svc.start()
    svc.ingest_attestation(_att([4], 1, 4, root=b"\x01" * 32))
    svc.flush()
    svc.stop()

    # a fresh service over the same db replays the evidence and detects
    # the surround against PRE-RESTART history
    svc2 = SlasherService(chain=None, db=db, history_length=64, chunk_size=8)
    svc2.start()
    assert svc2.attester.record_count() == 1
    assert svc2.attester.spans.num_validators >= 5
    svc2.ingest_attestation(_att([4], 0, 5, root=b"\x02" * 32))
    svc2.flush()
    assert svc2.detections["surround"] == 1

    # proposer equivocation: BOTH headers persist (root-keyed), and the
    # double proposal is detected live
    svc2.ingest_block(_signed_block(9, 2))
    svc2.ingest_block(_signed_block(9, 2, graffiti=b"\x42" * 32))
    assert svc2.detections["double_propose"] == 1

    # a restart between detection and block inclusion RE-EMITS both the
    # attester and the proposer detections from persisted evidence
    svc3 = SlasherService(chain=None, db=db, history_length=64, chunk_size=8)
    svc3.start()
    assert svc3.detections["surround"] == 1
    assert svc3.detections["double_propose"] == 1
    assert svc3.proposer.record_count() == 1


def test_proposer_rejection_cap_bounds_forged_duplicates():
    """A flood of forged duplicate headers for one (slot, proposer) is
    written off after MAX_PROPOSER_REJECTIONS failed dry-runs — the
    per-candidate head-state clone + BLS cost is bounded."""
    from lodestar_tpu.slasher.service import MAX_PROPOSER_REJECTIONS

    class RejectingChain:
        config = None

        def __init__(self):
            self.calls = 0

        def validate_proposer_slashing(self, _sl):
            self.calls += 1
            raise ValueError("forged signature")

    chain = RejectingChain()
    svc = SlasherService(chain)
    svc.ingest_block(_signed_block(3, 1), body_root=b"\x00" * 32)
    for i in range(1, 20):
        svc.ingest_block(
            _signed_block(3, 1), body_root=bytes([i]) + b"\x00" * 31
        )
    assert chain.calls == MAX_PROPOSER_REJECTIONS
    assert svc.rejected == MAX_PROPOSER_REJECTIONS
    # a different (slot, proposer) is unaffected
    svc.ingest_block(_signed_block(4, 1), body_root=b"\x00" * 32)
    svc.ingest_block(_signed_block(4, 1), body_root=b"\x01" * 32)
    assert chain.calls == MAX_PROPOSER_REJECTIONS + 1


def test_equivocation_probe_gating():
    """The suppressed-double-vote probe gate: conflicts are visible in
    flushed records AND the pending queue; keys are consumed on OUTCOME
    (a forged failure cannot burn the real vote's key, but failures are
    bounded per key)."""
    from lodestar_tpu.slasher.service import MAX_EQUIVOCATION_PROBE_FAILURES
    from lodestar_tpu.types import AttestationData

    svc = SlasherService(chain=None, history_length=64, chunk_size=8)
    a = _att([7], 1, 4, root=b"\x01" * 32)
    root_a = bytes(AttestationData.hash_tree_root(a["data"]))
    b = _att([7], 1, 4, root=b"\x02" * 32)
    root_b = bytes(AttestationData.hash_tree_root(b["data"]))

    # nothing known yet: no probe
    assert not svc.should_check_equivocation(7, 4, root_b)
    # first vote QUEUED (not yet flushed): the queue scan sees it
    svc.ingest_attestation(a)
    assert svc.should_check_equivocation(7, 4, root_b)
    assert not svc.should_check_equivocation(7, 4, root_a)  # same data
    # flushed records keep answering
    svc.flush()
    assert svc.should_check_equivocation(7, 4, root_b)
    # failed verifications (forged copies) bound the per-key cost but
    # do NOT consume the key until the bound is hit
    for _ in range(MAX_EQUIVOCATION_PROBE_FAILURES - 1):
        svc.record_equivocation_probe([7], 4, root_b, ok=False)
        assert svc.should_check_equivocation(7, 4, root_b)
    svc.record_equivocation_probe([7], 4, root_b, ok=False)
    assert not svc.should_check_equivocation(7, 4, root_b)
    # a successful probe marks the key done
    c = _att([7], 2, 4, root=b"\x03" * 32)
    root_c = bytes(AttestationData.hash_tree_root(c["data"]))
    assert svc.should_check_equivocation(7, 4, root_c)
    svc.record_equivocation_probe([7], 4, root_c, ok=True)
    assert not svc.should_check_equivocation(7, 4, root_c)


def _empty_altair_body():
    return {
        "randao_reveal": b"\x00" * 96,
        "eth1_data": {
            "deposit_root": b"\x00" * 32,
            "deposit_count": 0,
            "block_hash": b"\x00" * 32,
        },
        "graffiti": b"\x00" * 32,
        "proposer_slashings": [],
        "attester_slashings": [],
        "attestations": [],
        "deposits": [],
        "voluntary_exits": [],
        "sync_aggregate": {
            "sync_committee_bits": [False] * P.SYNC_COMMITTEE_SIZE,
            "sync_committee_signature": bytes([0xC0]) + b"\x00" * 95,
        },
    }


# -- service level: gossip -> detection -> pool -> API -> block -------------


# The chain anchors on a BLOCK at epoch 2's second slot: the gossip
# clock window (head-32 .. head+1) then spans epoch 1 (slots 33-63) AND
# epoch 2 (slots 64-66), so one validator can legitimately sign
# attestations with two different target epochs — required now that
# gossip enforces the p2p spec rule target.epoch == epoch_of(slot).
ANCHOR_SLOT = 65


@pytest.fixture(scope="module")
def world():
    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG, fork_epochs={ForkName.altair: 0}
    )
    cfg = dataclasses.replace(cfg, SHARD_COMMITTEE_PERIOD=0)
    sks = [B.keygen(b"slash-%d" % i) for i in range(N_KEYS)]
    pk_points = [B.sk_to_pk(sk) for sk in sks]
    pks = [C.g1_compress(p) for p in pk_points]
    genesis = create_genesis_state(cfg, pks, genesis_time=2)
    # anchor on a produced block (its post state), checkpoint-sync style
    from lodestar_tpu.chain.produce_block import produce_block
    from lodestar_tpu.ssz import uint64
    from lodestar_tpu.state_transition import process_slots
    from lodestar_tpu.state_transition.accessors import (
        get_beacon_proposer_index,
    )

    pre = genesis.clone()
    process_slots(pre, ANCHOR_SLOT)
    proposer = get_beacon_proposer_index(pre)
    reveal = B.sign_bytes(
        sks[proposer],
        cfg.compute_signing_root(
            uint64.hash_tree_root(ANCHOR_SLOT // params.SLOTS_PER_EPOCH),
            cfg.get_domain(ANCHOR_SLOT, params.DOMAIN_RANDAO),
        ),
    )
    _b, anchor = produce_block(genesis, ANCHOR_SLOT, reveal)
    chain = BeaconChain(cfg, anchor)
    verifier = CpuBlsVerifier(pubkeys=pk_points)
    handlers = GossipHandlers(chain, verifier)
    slasher = SlasherService(
        chain, registry=Registry(), history_length=64, chunk_size=8
    )
    slasher.start()
    chain.slasher = slasher
    handlers.slasher = slasher
    bus = InMemoryGossipBus()
    digest = cfg.fork_digest(0)
    handlers.subscribe_all(
        bus,
        "b",
        digest,
        attnets=tuple(range(params.ATTESTATION_SUBNET_COUNT)),
        syncnets=(),
    )
    return {
        "cfg": cfg,
        "sks": sks,
        "pks": pks,
        "state": anchor,
        "chain": chain,
        "handlers": handlers,
        "slasher": slasher,
        "bus": bus,
        "digest": digest,
    }


def _publish(w, name, sszt, obj, subnet=None):
    topic = topic_string(w["digest"], name, subnet=subnet)
    return w["bus"].publish("a", topic, encode_message(sszt.serialize(obj)))


def _cps(state, epoch):
    from lodestar_tpu.state_transition.accessors import (
        get_committee_count_per_slot,
    )

    return get_committee_count_per_slot(state, epoch)


def _duty(state, v, lo, hi):
    """(slot, index, committee, pos) of v's committee seat in [lo, hi)."""
    for slot in range(lo, hi):
        for index in range(_cps(state, slot // params.SLOTS_PER_EPOCH)):
            com = get_beacon_committee(state, slot, index)
            for pos, m in enumerate(com):
                if int(m) == v:
                    return slot, index, com, pos
    return None


def _pick_equivocator(state):
    """A validator with duties in BOTH gossipable epochs: epoch 2 at
    slots 64..head+1 and epoch 1 inside the window (slots 33-63)."""
    spe = params.SLOTS_PER_EPOCH
    for slot2 in range(2 * spe, ANCHOR_SLOT + 2):
        for index in range(_cps(state, 2)):
            for v in get_beacon_committee(state, slot2, index):
                duty1 = _duty(state, int(v), ANCHOR_SLOT - spe + 1, 2 * spe)
                if duty1 is not None:
                    duty2 = _duty(state, int(v), slot2, slot2 + 1)
                    return int(v), duty1, duty2
    pytest.skip("no validator with duties in both window epochs")


def _subnet(state, slot, index):
    return (
        (slot % params.SLOTS_PER_EPOCH)
        * _cps(state, slot // params.SLOTS_PER_EPOCH)
        + index
    ) % params.ATTESTATION_SUBNET_COUNT


def _gossip_att(w, validator, duty, source, target_root=None):
    slot, index, committee, pos = duty
    head_root = w["chain"].get_head_root()
    data = {
        "slot": slot,
        "index": index,
        "beacon_block_root": head_root,
        "source": {"epoch": source, "root": b"\x00" * 32},
        # spec rule: target epoch == the slot's epoch
        "target": {
            "epoch": slot // params.SLOTS_PER_EPOCH,
            "root": target_root or head_root,
        },
    }
    store = ValidatorStore(w["cfg"], dict(enumerate(w["sks"])))
    sig = store.sign_attestation(validator, data)
    bits = [i == pos for i in range(len(committee))]
    return {"aggregation_bits": bits, "data": data, "signature": sig}


def test_forged_surround_via_gossip_roundtrip(world):
    w = world
    v, duty1, duty2 = _pick_equivocator(w["state"])

    # two individually-valid gossip attestations forming a surround:
    # (source 1, target 1) in epoch 1, then (source 0, target 2) in
    # epoch 2 — the second SURROUNDS the first (and the first is the
    # source==target edge, live); target epochs match their slots
    att1 = _gossip_att(w, v, duty1, source=1)
    sub1 = _subnet(w["state"], duty1[0], duty1[1])
    assert (
        _publish(w, GossipTopicName.beacon_attestation, T.Attestation, att1, sub1)
        == 1
    )
    att2 = _gossip_att(w, v, duty2, source=0)
    sub2 = _subnet(w["state"], duty2[0], duty2[1])
    assert (
        _publish(w, GossipTopicName.beacon_attestation, T.Attestation, att2, sub2)
        == 1
    )
    results = w["handlers"].results
    n_accepts = sum(
        r.get("accept", 0)
        for t, r in results.items()
        if t.startswith("beacon_attestation_")
    )
    assert n_accepts == 2

    # ONE batch flush detects, validates (full STF dry-run), pools
    assert w["slasher"].flush() == 1
    assert w["slasher"].detections["surround"] == 1
    pool = w["chain"].op_pool
    assert any(v in key for key in pool._attester_slashings)
    assert v in w["chain"].fork_choice._equivocating

    # API view: the spec pool route and the slasher status route
    from lodestar_tpu.api.routes import match
    from lodestar_tpu.api.server import DefaultHandlers

    api = DefaultHandlers(chain=w["chain"], slasher=w["slasher"])
    route, _params = match("GET", "/eth/v1/beacon/pool/attester_slashings")
    code, body = getattr(api, route.handler)({}, None)
    assert code == 200 and len(body["data"]) == 1
    route, _params = match("GET", "/eth/v1/lodestar/slasher")
    code, body = getattr(api, route.handler)({}, None)
    assert code == 200
    assert body["data"]["detections"]["surround"] == 1

    # block inclusion round-trip: the pooled slashing lands in a block
    # and the offender leaves slashed after a FULLY verified transition
    from lodestar_tpu.chain.op_pools import AggregatedAttestationPool
    from lodestar_tpu.chain.produce_block import produce_block_from_pools
    from lodestar_tpu.ssz import uint64
    from lodestar_tpu.state_transition import process_slots
    from lodestar_tpu.state_transition.accessors import (
        get_beacon_proposer_index,
    )

    slot = ANCHOR_SLOT + 1
    pre = w["state"].clone()
    process_slots(pre, slot)
    proposer = get_beacon_proposer_index(pre)
    domain = w["cfg"].get_domain(slot, params.DOMAIN_RANDAO)
    reveal = B.sign_bytes(
        w["sks"][proposer],
        w["cfg"].compute_signing_root(
            uint64.hash_tree_root(slot // params.SLOTS_PER_EPOCH), domain
        ),
    )
    block, _post = produce_block_from_pools(
        w["state"],
        slot,
        reveal,
        aggregated_attestation_pool=AggregatedAttestationPool(),
        op_pool=pool,
        contribution_pool=w["chain"].sync_contribution_pool,
        head_root=w["chain"].get_head_root(),
    )
    assert len(block["body"]["attester_slashings"]) == 1
    proot = w["cfg"].compute_signing_root(
        T.BeaconBlockAltair.hash_tree_root(block),
        w["cfg"].get_domain(slot, params.DOMAIN_BEACON_PROPOSER),
    )
    signed = {
        "message": block,
        "signature": B.sign_bytes(w["sks"][proposer], proot),
    }
    post = state_transition(
        w["state"],
        signed,
        verify_state_root=True,
        verify_proposer=True,
        verify_signatures=True,
    )
    assert bool(post.slashed[v])

    # the slasher re-submitting the same offence is a pool no-op
    n = len(pool._attester_slashings)
    w["slasher"].ingest_attestation(
        w["chain"].op_pool._attester_slashings[
            next(iter(pool._attester_slashings))
        ]["attestation_1"]
    )
    w["slasher"].flush()
    assert len(pool._attester_slashings) == n


def test_suppressed_double_vote_recovered_from_seen_cache(world):
    """A double vote shares its target epoch, so the second gossip
    attestation IGNOREs at the seen-attester cache — the handler's
    recovery path must still verify and ingest it (the duplicate IS the
    equivocation, same as the duplicate-proposer block branch)."""
    w = world
    v, duty1, _duty2 = _pick_equivocator(w["state"])
    subnet = _subnet(w["state"], duty1[0], duty1[1])
    assert w["slasher"].attester.has_conflicting_target(v, 1, b"\x00" * 32)

    # same slot/target epoch as the recorded vote, different target
    # root => different data root; the seen cache IGNOREs it
    # pre-signature
    att_b = _gossip_att(w, v, duty1, source=1, target_root=b"\x99" * 32)
    assert (
        _publish(w, GossipTopicName.beacon_attestation, T.Attestation, att_b, subnet)
        == 1
    )
    assert (
        w["handlers"].results[f"beacon_attestation_{subnet}"]["ignore"] >= 1
    )
    w["slasher"].flush()
    assert w["slasher"].detections["double_vote"] == 1
    # v's offence was already covered by the pooled surround slashing:
    # the pool stays deduped while the detection still counts
    assert any(v in key for key in w["chain"].op_pool._attester_slashings)


def test_forged_double_proposal_via_gossip(world):
    w = world
    from lodestar_tpu.chain.produce_block import produce_block
    from lodestar_tpu.ssz import uint64
    from lodestar_tpu.state_transition import process_slots
    from lodestar_tpu.state_transition.accessors import (
        get_beacon_proposer_index,
    )

    slot = ANCHOR_SLOT + 1
    pre = w["state"].clone()
    process_slots(pre, slot)
    proposer = get_beacon_proposer_index(pre)
    domain = w["cfg"].get_domain(slot, params.DOMAIN_RANDAO)
    reveal = B.sign_bytes(
        w["sks"][proposer],
        w["cfg"].compute_signing_root(
            uint64.hash_tree_root(slot // params.SLOTS_PER_EPOCH), domain
        ),
    )

    def sign_block(block):
        proot = w["cfg"].compute_signing_root(
            T.BeaconBlockAltair.hash_tree_root(block),
            w["cfg"].get_domain(slot, params.DOMAIN_BEACON_PROPOSER),
        )
        return {
            "message": block,
            "signature": B.sign_bytes(w["sks"][proposer], proot),
        }

    b1, _ = produce_block(w["state"], slot, reveal)
    b2, _ = produce_block(w["state"], slot, reveal, graffiti=b"\x42" * 32)
    assert (
        _publish(
            w, GossipTopicName.beacon_block, T.SignedBeaconBlockAltair, sign_block(b1)
        )
        == 1
    )
    assert w["handlers"].results["beacon_block"]["accept"] == 1
    # the equivocating second block IGNOREs at gossip but STILL reaches
    # the slasher, which detects within the (immediate) header index
    assert (
        _publish(
            w, GossipTopicName.beacon_block, T.SignedBeaconBlockAltair, sign_block(b2)
        )
        == 1
    )
    assert w["handlers"].results["beacon_block"]["ignore"] == 1
    assert w["slasher"].detections["double_propose"] == 1
    assert int(proposer) in w["chain"].op_pool._proposer_slashings

    from lodestar_tpu.api.routes import match
    from lodestar_tpu.api.server import DefaultHandlers

    api = DefaultHandlers(chain=w["chain"], slasher=w["slasher"])
    route, _params = match("GET", "/eth/v1/beacon/pool/proposer_slashings")
    code, body = getattr(api, route.handler)({}, None)
    assert code == 200 and len(body["data"]) == 1


def test_block_body_attestation_feeds_surround_detection(world):
    """Regression for the ingestion gap: one half of a surround pair
    arrives ONLY inside an imported block body (never via gossip on
    this node) — the import pipeline must translate it to indices and
    feed the span window, or the equivocation goes undetected."""
    w = world
    from lodestar_tpu.chain.op_pools import attester_slashing_intersection
    from lodestar_tpu.ssz import uint64
    from lodestar_tpu.state_transition import process_slots
    from lodestar_tpu.state_transition.accessors import (
        get_beacon_proposer_index,
    )

    head = w["chain"].head_state
    slot = int(head.slot) + 3
    prev_equivocator, _d1, _d2 = _pick_equivocator(w["state"])

    # a validator with an epoch-2 committee seat strictly before `slot`
    # (prefer a fresh offender; tiny registries may only seat the
    # earlier equivocator, whose detection still counts via the
    # covered-offenders fast path)
    duty = None
    for cand in sorted(range(N_KEYS), key=lambda c: c == prev_equivocator):
        d = _duty(head, cand, 2 * params.SLOTS_PER_EPOCH, slot)
        if d is not None:
            v, duty = cand, d
            break
    assert duty is not None, "no epoch-2 duty before the block slot"
    att_slot, att_index, committee, pos = duty

    # inner attestation (source 1, target 1): reaches the slasher via
    # the verified-gossip path only — signed for real so the emission
    # dry-run's signature check passes.  Fresh stores per half: the
    # store's OWN slashing protection rightly refuses to sign an
    # equivocation it has history for.
    store = ValidatorStore(w["cfg"], dict(enumerate(w["sks"])))
    store_b = ValidatorStore(w["cfg"], dict(enumerate(w["sks"])))
    inner_data = {
        "slot": (params.SLOTS_PER_EPOCH + 5),
        "index": 0,
        "beacon_block_root": b"\x21" * 32,
        "source": {"epoch": 1, "root": b"\x22" * 32},
        "target": {"epoch": 1, "root": b"\x23" * 32},
    }
    w["slasher"].ingest_attestation(
        {
            "attesting_indices": [v],
            "data": inner_data,
            "signature": store.sign_attestation(v, inner_data),
        }
    )
    w["slasher"].flush()
    before = dict(w["slasher"].detections)

    # outer attestation (source 0, target 2) SURROUNDS the inner one;
    # it rides a block body only — an includable, honestly-signed vote
    outer_data = {
        "slot": att_slot,
        "index": att_index,
        "beacon_block_root": w["chain"].get_head_root(),
        "source": {"epoch": 0, "root": b"\x00" * 32},
        "target": {"epoch": 2, "root": w["chain"].get_head_root()},
    }
    outer = {
        "aggregation_bits": [i == pos for i in range(len(committee))],
        "data": outer_data,
        "signature": store_b.sign_attestation(v, outer_data),
    }

    pre = head.clone()
    process_slots(pre, slot)
    proposer = get_beacon_proposer_index(pre)
    reveal = B.sign_bytes(
        w["sks"][proposer],
        w["cfg"].compute_signing_root(
            uint64.hash_tree_root(slot // params.SLOTS_PER_EPOCH),
            w["cfg"].get_domain(slot, params.DOMAIN_RANDAO),
        ),
    )
    body = _empty_altair_body()
    body["randao_reveal"] = reveal
    body["attestations"] = [outer]
    block = {
        "slot": slot,
        "proposer_index": int(proposer),
        "parent_root": w["chain"].get_head_root(),
        "state_root": b"\x00" * 32,
        "body": body,
    }
    post = state_transition(
        head,
        {"message": block, "signature": b"\x00" * 96},
        verify_state_root=False,
    )
    block["state_root"] = post.hash_tree_root()
    proot = w["cfg"].compute_signing_root(
        T.BeaconBlockAltair.hash_tree_root(block),
        w["cfg"].get_domain(slot, params.DOMAIN_BEACON_PROPOSER),
    )
    signed = {
        "message": block,
        "signature": B.sign_bytes(w["sks"][proposer], proot),
    }
    w["chain"].process_block(signed)

    # the import alone queued the body attestation; the flush detects
    assert w["slasher"].flush() >= 1
    assert (
        w["slasher"].detections["surround"] == before["surround"] + 1
    )
    assert any(
        v in attester_slashing_intersection(entry)
        for entry in w["chain"].op_pool._attester_slashings.values()
    )
