"""BeaconChain composition: import via STF, head, duties, pools, events.

Reference: packages/beacon-node/src/chain/chain.ts + blocks/importBlock.ts
(fork-choice insert, head update, event emission, finalization pruning)
and api/impl/validator (duty computation).
"""

import hashlib

import pytest

from lodestar_tpu import params
from lodestar_tpu import types as T
from lodestar_tpu.chain.chain import BeaconChain
from lodestar_tpu.chain.emitter import ChainEvent
from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
from lodestar_tpu.crypto import bls as B
from lodestar_tpu.crypto import curves as C
from lodestar_tpu.params import ForkName
from lodestar_tpu.ssz import uint64
from lodestar_tpu.state_transition import create_genesis_state, process_slots
from lodestar_tpu.state_transition.accessors import (
    get_beacon_committee,
    get_beacon_proposer_index,
    get_block_root_at_slot,
    get_committee_count_per_slot,
)

P = params.ACTIVE_PRESET
N_KEYS = 16


@pytest.fixture(scope="module")
def chain_world():
    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG, fork_epochs={ForkName.altair: 0}
    )
    sks = [B.keygen(b"chain-%d" % i) for i in range(N_KEYS)]
    pks = [C.g1_compress(B.sk_to_pk(sk)) for sk in sks]
    genesis = create_genesis_state(cfg, pks, genesis_time=11)
    chain = BeaconChain(cfg, genesis)
    events = {"block": [], "head": [], "attestation": []}
    chain.emitter.on(ChainEvent.block, lambda s, r: events["block"].append(r))
    chain.emitter.on(
        ChainEvent.head, lambda r, s: events["head"].append((r, s))
    )
    chain.emitter.on(
        ChainEvent.attestation, lambda a: events["attestation"].append(a)
    )
    return cfg, sks, pks, genesis, chain, events


def _sign_and_import(chain, cfg, sks, block):
    domain = cfg.get_domain(
        block["slot"], params.DOMAIN_BEACON_PROPOSER, block["slot"]
    )
    root = cfg.compute_signing_root(
        T.BeaconBlockAltair.hash_tree_root(block), domain
    )
    return chain.process_block(
        {
            "message": block,
            "signature": B.sign_bytes(sks[block["proposer_index"]], root),
        }
    )


def _randao(chain, cfg, sks, slot):
    head = chain.head_state.clone()
    if head.slot < slot:
        process_slots(head, slot)
    proposer = get_beacon_proposer_index(head)
    epoch = slot // P.SLOTS_PER_EPOCH
    domain = cfg.get_domain(slot, params.DOMAIN_RANDAO)
    root = cfg.compute_signing_root(uint64.hash_tree_root(epoch), domain)
    return B.sign_bytes(sks[proposer], root)


def test_chain_import_and_head(chain_world):
    cfg, sks, pks, genesis, chain, events = chain_world

    b1 = chain.produce_block(1, _randao(chain, cfg, sks, 1))
    r1 = _sign_and_import(chain, cfg, sks, b1)
    assert chain.head_root_hex == r1.hex()
    assert chain.imported_blocks == 1
    assert events["block"] and events["head"]

    # duplicate import is a no-op
    assert _sign_and_import(chain, cfg, sks, b1) == r1
    assert chain.imported_blocks == 1

    # gossip attestations -> pool -> aggregation -> next block
    head_state = chain.head_state
    data = None
    for index in range(get_committee_count_per_slot(head_state, 0)):
        committee = get_beacon_committee(head_state, 1, index)
        data = {
            "slot": 1,
            "index": index,
            "beacon_block_root": r1,
            "source": dict(head_state.current_justified_checkpoint),
            "target": {"epoch": 0, "root": get_block_root_at_slot(head_state, 0)},
        }
        n = len(committee)
        for pos, vidx in enumerate(committee):
            domain = cfg.get_domain(1, params.DOMAIN_BEACON_ATTESTER, 1)
            sroot = cfg.compute_signing_root(
                T.AttestationData.hash_tree_root(data), domain
            )
            chain.add_attestation(
                {
                    "aggregation_bits": [i == pos for i in range(n)],
                    "data": data,
                    "signature": B.sign_bytes(sks[int(vidx)], sroot),
                }
            )
        agg = chain.attestation_pool.get_aggregate(
            1, T.AttestationData.hash_tree_root(data)
        )
        chain.aggregated_attestation_pool.add(agg)
    assert events["attestation"]

    b2 = chain.produce_block(2, _randao(chain, cfg, sks, 2))
    assert len(b2["body"]["attestations"]) >= 1
    r2 = _sign_and_import(chain, cfg, sks, b2)
    assert chain.head_root_hex == r2.hex()

    # db-less chain still serves head state from the regen cache
    post = chain.head_state
    assert post.slot == 2
    assert post.current_epoch_participation.sum() > 0


def test_chain_rejects_bad_signature(chain_world):
    cfg, sks, pks, genesis, chain, events = chain_world
    block = chain.produce_block(3, _randao(chain, cfg, sks, 3))
    bad = {"message": block, "signature": b"\x11" * 96}
    with pytest.raises(Exception):
        chain.process_block(bad)
    assert chain.head_root_hex != T.BeaconBlockAltair.hash_tree_root(block).hex()


def test_proposer_duties(chain_world):
    cfg, sks, pks, genesis, chain, events = chain_world
    duties = chain.get_proposer_duties(0)
    assert len(duties) == P.SLOTS_PER_EPOCH
    by_slot = {d["slot"]: d for d in duties}
    # the block we imported at slot 1 was proposed by the duty holder
    head = chain.head_state
    st = genesis.clone()
    process_slots(st, 1)
    assert by_slot[1]["validator_index"] == get_beacon_proposer_index(st)
    assert by_slot[1]["pubkey"] == pks[by_slot[1]["validator_index"]]


def test_attester_duties_cover_registry(chain_world):
    cfg, sks, pks, genesis, chain, events = chain_world
    duties = chain.get_attester_duties(0, list(range(N_KEYS)))
    # every active validator attests exactly once per epoch
    assert sorted(d["validator_index"] for d in duties) == list(range(N_KEYS))
    for d in duties:
        assert 0 <= d["validator_committee_index"] < d["committee_length"]


def test_sync_committee_duties(chain_world):
    cfg, sks, pks, genesis, chain, events = chain_world
    duties = chain.get_sync_committee_duties(0, list(range(N_KEYS)))
    total_positions = sum(len(d["positions"]) for d in duties)
    assert total_positions == P.SYNC_COMMITTEE_SIZE
    for d in duties:
        pk = pks[d["validator_index"]]
        for pos in d["positions"]:
            assert (
                chain.head_state.current_sync_committee["pubkeys"][pos] == pk
            )


def test_next_epoch_duties_via_checkpoint_state(chain_world):
    cfg, sks, pks, genesis, chain, events = chain_world
    duties = chain.get_proposer_duties(1)
    assert len(duties) == P.SLOTS_PER_EPOCH
    assert all(
        d["slot"] // P.SLOTS_PER_EPOCH == 1 for d in duties
    )
