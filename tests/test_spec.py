"""Spec-test vectors: bls, hash_to_curve, operations, epoch, ssz_static.

Mirror of the reference's spec runners (reference:
packages/beacon-node/test/spec/{bls/bls.ts,presets/operations.ts,
presets/epoch_processing.ts,presets/ssz_static.ts} via the enforcing
iterator in spec/utils/specTestIterator.ts:22-30): absent fixtures are
FAILURES, every fixture directory must be consumed, every runner must
find cases.  See tests/fixtures/README.md for vector provenance.
"""

import dataclasses

import pytest

from lodestar_tpu import params
from lodestar_tpu import types as T
from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
from lodestar_tpu.crypto import bls as B
from lodestar_tpu.crypto import curves as C
from lodestar_tpu.crypto.hash_to_curve import hash_to_g2
from lodestar_tpu.params import ForkName
from lodestar_tpu.spec_test_util import (
    check_all_consumed,
    iter_case_dirs,
    iter_json_cases,
    maybe_read_ssz_snappy,
    read_json_roots,
    read_meta,
    read_ssz_snappy,
)
from lodestar_tpu.state_transition.state import BeaconState

pytestmark = [pytest.mark.smoke, pytest.mark.spec]

CFG = dataclasses.replace(
    create_chain_config(MAINNET_CHAIN_CONFIG, fork_epochs={ForkName.altair: 0}),
    SHARD_COMMITTEE_PERIOD=0,
)


def _unhex(s: str) -> bytes:
    return bytes.fromhex(s[2:] if s.startswith("0x") else s)


# -- bls (reference: test/spec/bls/bls.ts runners) --------------------------


def test_bls_sign_vectors():
    for name, case in iter_json_cases("bls", "sign"):
        sk = int.from_bytes(_unhex(case["input"]["privkey"]), "big")
        msg = _unhex(case["input"]["message"])
        sig = C.g2_compress(B.sign(sk, msg))
        assert sig == _unhex(case["output"]), name


def test_bls_verify_vectors():
    for name, case in iter_json_cases("bls", "verify"):
        try:
            pk = C.g1_decompress(_unhex(case["input"]["pubkey"]))
            sig = C.g2_decompress(_unhex(case["input"]["signature"]))
            ok = (
                pk is not None
                and sig is not None
                and C.g1_subgroup_check(pk)
                and C.g2_subgroup_check(sig)
                and B.verify(pk, _unhex(case["input"]["message"]), sig)
            )
        except ValueError:
            ok = False
        assert ok == case["output"], name


def test_bls_aggregate_vectors():
    for name, case in iter_json_cases("bls", "aggregate"):
        sigs = [C.g2_decompress(_unhex(s)) for s in case["input"]]
        if not sigs:
            assert case["output"] is None, name
            continue
        agg = C.g2_compress(B.aggregate_signatures(sigs))
        assert agg == _unhex(case["output"]), name


def test_bls_fast_aggregate_verify_vectors():
    for name, case in iter_json_cases("bls", "fast_aggregate_verify"):
        inp = case["input"]
        try:
            pks = [C.g1_decompress(_unhex(p)) for p in inp["pubkeys"]]
            sig = C.g2_decompress(_unhex(inp["signature"]))
            if any(p is None for p in pks) or sig is None:
                ok = False
            else:
                agg = B.aggregate_pubkeys(pks)
                ok = B.verify(agg, _unhex(inp["message"]), sig)
        except ValueError:
            ok = False
        assert ok == case["output"], name


def test_bls_aggregate_verify_vectors():
    from lodestar_tpu.crypto import pairing as CP

    for name, case in iter_json_cases("bls", "aggregate_verify"):
        inp = case["input"]
        pks = [C.g1_decompress(_unhex(p)) for p in inp["pubkeys"]]
        sig = C.g2_decompress(_unhex(inp["signature"]))
        pairs = [
            (pk, hash_to_g2(_unhex(m)))
            for pk, m in zip(pks, inp["messages"])
        ]
        ok = CP.multi_pairing_is_one(
            [(pk, hm) for pk, hm in pairs] + [(B.NEG_G1_GEN, sig)]
        )
        assert ok == case["output"], name


def test_hash_to_curve_vectors():
    for name, case in iter_json_cases("hash_to_curve"):
        msg = case["input"]["msg"].encode()
        x, y = hash_to_g2(msg)
        ex = [int(v, 16) for v in case["output"]["x"].split(",")]
        ey = [int(v, 16) for v in case["output"]["y"].split(",")]
        assert [x[0], x[1]] == ex and [y[0], y[1]] == ey, name


# -- consensus: operations (reference: presets/operations.ts) ---------------

OPERATION_TYPES = {
    "attestation": (T.Attestation, "process_attestation"),
    "proposer_slashing": (T.ProposerSlashing, "process_proposer_slashing"),
    "attester_slashing": (T.AttesterSlashing, "process_attester_slashing"),
    "voluntary_exit": (T.SignedVoluntaryExit, "process_voluntary_exit"),
    "sync_aggregate": (T.SyncAggregate, "process_sync_aggregate"),
}


def test_operations_vectors():
    from lodestar_tpu.state_transition import block as BL

    consumed = {}
    for op_name, (typ, fn_name) in OPERATION_TYPES.items():
        fn = getattr(BL, fn_name)
        consumed[op_name] = 0
        for case_dir in iter_case_dirs(
            "consensus", "altair", "operations", op_name
        ):
            consumed[op_name] += 1
            pre = BeaconState.deserialize(
                read_ssz_snappy(case_dir, "pre"), CFG
            )
            op = typ.deserialize(read_ssz_snappy(case_dir, op_name))
            post_bytes = maybe_read_ssz_snappy(case_dir, "post")
            if post_bytes is None:
                # the op must fail with the STF's own validation error —
                # an unrelated crash (TypeError etc.) must NOT pass
                from lodestar_tpu.state_transition.block import (
                    BlockProcessError,
                )

                with pytest.raises(BlockProcessError):
                    fn(pre, op, True)
            else:
                fn(pre, op, True)
                assert pre.serialize() == post_bytes, case_dir
    check_all_consumed(consumed, "consensus", "altair", "operations")


# -- consensus: capella operations (withdrawals + address changes) ----------

CFG_CAPELLA = dataclasses.replace(
    create_chain_config(
        MAINNET_CHAIN_CONFIG,
        fork_epochs={
            ForkName.altair: 0,
            ForkName.bellatrix: 0,
            ForkName.capella: 0,
        },
    ),
    SHARD_COMMITTEE_PERIOD=0,
)

# op_name -> (operation file name, ssz type, apply) — the upstream
# capella case shapes (operations/withdrawals carries the payload as
# `execution_payload`, bls_to_execution_change as `address_change`)
CAPELLA_OPERATION_TYPES = {
    "withdrawals": (
        "execution_payload",
        T.ExecutionPayloadCapella,
        lambda BL, st, op: BL.process_withdrawals(st, op),
    ),
    "bls_to_execution_change": (
        "address_change",
        T.SignedBLSToExecutionChange,
        lambda BL, st, op: BL.process_bls_to_execution_change(st, op, True),
    ),
}


def test_capella_operations_vectors():
    from lodestar_tpu.state_transition import block as BL
    from lodestar_tpu.state_transition.block import BlockProcessError

    consumed = {}
    for op_name, (op_file, typ, apply_fn) in CAPELLA_OPERATION_TYPES.items():
        consumed[op_name] = 0
        for case_dir in iter_case_dirs(
            "consensus", "capella", "operations", op_name
        ):
            consumed[op_name] += 1
            pre = BeaconState.deserialize(
                read_ssz_snappy(case_dir, "pre"), CFG_CAPELLA
            )
            assert pre.next_withdrawal_index is not None, (
                "capella pre state lost its withdrawal fields"
            )
            op = typ.deserialize(read_ssz_snappy(case_dir, op_file))
            post_bytes = maybe_read_ssz_snappy(case_dir, "post")
            if post_bytes is None:
                with pytest.raises(BlockProcessError):
                    apply_fn(BL, pre, op)
            else:
                apply_fn(BL, pre, op)
                assert pre.serialize() == post_bytes, case_dir
    check_all_consumed(consumed, "consensus", "capella", "operations")


# -- consensus: epoch processing (reference: presets/epoch_processing.ts) ---


def test_epoch_processing_vectors():
    from lodestar_tpu.state_transition import epoch as EP

    consumed = {}
    steps = (
        "justification_and_finalization",
        "rewards_and_penalties",
        "registry_updates",
        "slashings",
        "effective_balance_updates",
        "sync_committee_updates",
    )
    for step in steps:
        fn = getattr(EP, f"process_{step}")
        consumed[step] = 0
        for case_dir in iter_case_dirs(
            "consensus", "altair", "epoch_processing", step
        ):
            consumed[step] += 1
            pre = BeaconState.deserialize(
                read_ssz_snappy(case_dir, "pre"), CFG
            )
            fn(pre, EP.EpochTransitionCache(pre))
            assert pre.serialize() == read_ssz_snappy(case_dir, "post"), (
                case_dir
            )
    check_all_consumed(consumed, "consensus", "altair", "epoch_processing")


# -- consensus: ssz_static (reference: presets/ssz_static.ts) ---------------


def test_ssz_static_vectors():
    consumed = {}
    for type_name in (
        "AttestationData",
        "Attestation",
        "Checkpoint",
        "BeaconBlockHeader",
        "SyncCommitteeMessage",
        "SyncAggregatorSelectionData",
        "VoluntaryExit",
        "Fork",
        "BeaconStateAltair",
    ):
        consumed[type_name] = 0
        for case_dir in iter_case_dirs(
            "consensus", "altair", "ssz_static", type_name
        ):
            consumed[type_name] += 1
            data = read_ssz_snappy(case_dir, "serialized")
            root = _unhex(read_json_roots(case_dir)["root"])
            if type_name == "BeaconStateAltair":
                state = BeaconState.deserialize(data, CFG)
                assert state.hash_tree_root() == root, case_dir
                assert state.serialize() == data, case_dir
            else:
                typ = getattr(T, type_name)
                value = typ.deserialize(data)
                assert typ.hash_tree_root(value) == root, case_dir
                assert typ.serialize(value) == data, case_dir
    check_all_consumed(consumed, "consensus", "altair", "ssz_static")


# -- consensus: phase0 (PendingAttestation-era operations + the altair
# upgrade transition) -------------------------------------------------------

CFG_PHASE0 = dataclasses.replace(
    create_chain_config(
        MAINNET_CHAIN_CONFIG, fork_epochs={ForkName.altair: 1}
    ),
    SHARD_COMMITTEE_PERIOD=0,
)


def test_phase0_attestation_vectors():
    from lodestar_tpu.state_transition.block import (
        BlockProcessError,
        process_attestation_phase0,
    )

    consumed = {"attestation": 0}
    for case_dir in iter_case_dirs(
        "consensus", "phase0", "operations", "attestation"
    ):
        consumed["attestation"] += 1
        pre = BeaconState.deserialize(
            read_ssz_snappy(case_dir, "pre"), CFG_PHASE0
        )
        assert pre.previous_epoch_attestations is not None
        att = T.Attestation.deserialize(
            read_ssz_snappy(case_dir, "attestation")
        )
        post_bytes = maybe_read_ssz_snappy(case_dir, "post")
        if post_bytes is None:
            with pytest.raises(BlockProcessError):
                process_attestation_phase0(pre, att, True)
        else:
            process_attestation_phase0(pre, att, True)
            assert pre.serialize() == post_bytes, case_dir
    check_all_consumed(consumed, "consensus", "phase0", "operations")


def test_phase0_fork_upgrade_vectors():
    """The phase0 epoch transition + scheduled upgrade_to_altair must
    land byte-exactly on the post state (participation translation,
    inactivity bootstrap, sync committees)."""
    from lodestar_tpu.state_transition.slot import process_slots

    consumed = {"upgrade_to_altair": 0}
    for case_dir in iter_case_dirs("consensus", "phase0", "fork"):
        consumed["upgrade_to_altair"] += 1
        pre = BeaconState.deserialize(
            read_ssz_snappy(case_dir, "pre"), CFG_PHASE0
        )
        assert pre.fork_name == ForkName.phase0
        target = (int(pre.slot) // params.SLOTS_PER_EPOCH + 1) * (
            params.SLOTS_PER_EPOCH
        )
        process_slots(pre, target)
        assert pre.fork_name == ForkName.altair
        assert pre.previous_epoch_attestations is None
        post = read_ssz_snappy(case_dir, "post")
        assert pre.serialize() == post, case_dir
    check_all_consumed(consumed, "consensus", "phase0", "fork")


CFG_PHASE0_EP = dataclasses.replace(
    create_chain_config(
        MAINNET_CHAIN_CONFIG, fork_epochs={ForkName.altair: 10}
    ),
    SHARD_COMMITTEE_PERIOD=0,
)


def test_phase0_epoch_processing_vectors():
    """phase0-specific epoch steps over PendingAttestation records:
    attestation-derived justification, getAttestationDeltas rewards,
    multiplier-1 slashings, record rotation."""
    from lodestar_tpu.state_transition import phase0 as P0

    steps = {
        "justification_and_finalization": (
            P0.process_justification_and_finalization_phase0
        ),
        "rewards_and_penalties": P0.process_rewards_and_penalties_phase0,
        "slashings": P0.process_slashings_phase0,
        "participation_record_updates": (
            P0.process_participation_record_updates
        ),
    }
    consumed = {}
    for name, fn in steps.items():
        consumed[name] = 0
        for case_dir in iter_case_dirs(
            "consensus", "phase0", "epoch_processing", name
        ):
            consumed[name] += 1
            pre = BeaconState.deserialize(
                read_ssz_snappy(case_dir, "pre"), CFG_PHASE0_EP
            )
            assert pre.previous_epoch_attestations is not None
            fn(pre)
            post = read_ssz_snappy(case_dir, "post")
            assert pre.serialize() == post, case_dir
    check_all_consumed(consumed, "consensus", "phase0", "epoch_processing")
