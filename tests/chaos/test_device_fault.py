"""Chaos scenario: device fault mid-flood (the ISSUE 14 acceptance).

With the breaker enabled, an injected device-dispatch failure during a
sustained stub flood must produce ZERO lost verdicts (every submitted
set resolves; host-path verdicts bit-identical to the oracle), the SLO
engine must report `degraded` then `ok`, exactly ONE flight bundle must
be written, and the node must return to device-path dispatch after the
canary re-probe — all deterministic under a fixed seed and reproducible
through the harness's record/replay.
"""

import pytest

from lodestar_tpu.observability import flight_recorder as FR

from chaos.harness import FloodWorld, ScenarioTrace, assert_replay

pytestmark = pytest.mark.smoke

SEED = 1234


def _run(trace, fr_dir):
    world = FloodWorld(fr_dir, seed=trace.seed)
    try:
        # healthy flood: two waves, a few invalid signatures mixed in
        world.submit_wave(32, wave=0, invalid_every=7)
        world.submit_wave(32, wave=1)
        s = world.drain()
        trace.emit(
            "healthy", **s, breaker=world.supervisor.status()["state"]
        )
        world.tick_slot()
        trace.emit("slo_healthy", status=world.slo.status()["status"])

        # the fault lands MID-flood: wave 2 is in flight when the
        # device path starts failing; wave 3 is submitted after
        world.submit_wave(24, wave=2, invalid_every=5)
        world.verifier.fault = {"finish": "backend"}
        world.submit_wave(24, wave=3, invalid_every=5)
        s = world.drain()
        trace.emit(
            "during_fault",
            **s,
            breaker=world.supervisor.status()["state"],
            host_fallback_used=world.verifier.host_sets > 0,
        )

        # next tick drains the trip anomaly into ONE bundle; health is
        # degraded through the breaker source (not a breach)
        world.tick_slot()
        st = world.slo.status()
        trace.emit(
            "slo_degraded",
            status=st["status"],
            breaker_source=st["degraded_sources"]["bls_breaker"],
        )
        bundles = FR.list_bundles(world.recorder.directory)
        trace.emit(
            "bundles",
            n=len(bundles),
            reason=bundles[0]["reason"] if bundles else None,
        )

        # degraded mode keeps verdicts flowing (zero dropped sets)
        world.submit_wave(16, wave=4, invalid_every=4)
        s = world.drain()
        trace.emit("degraded_flood", **s)

        # heal the device; the canary is not due before the backoff
        world.verifier.heal()
        world.supervisor.poll()
        trace.emit(
            "probe_not_due", breaker=world.supervisor.status()["state"]
        )
        world.fake.advance(10.0)  # past the 2 s (+/- jitter) backoff
        world.supervisor.poll()
        trace.emit(
            "recovered",
            breaker=world.supervisor.status()["state"],
            degraded_time_counted=world.supervisor.time_in_degraded_s() > 0,
        )
        world.tick_slot()
        trace.emit("slo_ok", status=world.slo.status()["status"])

        # and the device path actually carries jobs again
        before = world.verifier.device_jobs
        world.submit_wave(16, wave=5)
        s = world.drain()
        trace.emit(
            "device_resumed",
            **s,
            device_jobs_grew=world.verifier.device_jobs > before,
        )
    finally:
        world.close()


def test_device_fault_mid_flood_acceptance(tmp_path):
    trace = ScenarioTrace(SEED)
    _run(trace, tmp_path / "fr-record")
    ev = {e["kind"]: e for e in trace.events}

    # zero lost verdicts, bit-identical host-path verdicts, at every stage
    for stage in ("healthy", "during_fault", "degraded_flood",
                  "device_resumed"):
        assert ev[stage]["mismatches"] == [], (stage, ev[stage])
        assert (
            ev[stage]["valid_confirmed"] + ev[stage]["invalid_rejected"]
            == ev[stage]["submitted"]
        ), stage
    assert ev["healthy"]["breaker"] == "closed"
    assert ev["slo_healthy"]["status"] == "ok"
    # the trip: breaker open, host fallback carried the flood
    assert ev["during_fault"]["breaker"] == "open"
    assert ev["during_fault"]["host_fallback_used"] is True
    # SLO degraded through the breaker source, exactly one bundle
    assert ev["slo_degraded"]["status"] == "degraded"
    assert ev["slo_degraded"]["breaker_source"] is True
    assert ev["bundles"]["n"] == 1
    assert ev["bundles"]["reason"] == "event.bls_breaker_trip"
    # canary-gated recovery: not before the backoff, then closed
    assert ev["probe_not_due"]["breaker"] == "open"
    assert ev["recovered"]["breaker"] == "closed"
    assert ev["recovered"]["degraded_time_counted"] is True
    assert ev["slo_ok"]["status"] == "ok"
    assert ev["device_resumed"]["device_jobs_grew"] is True

    # record/replay: the saved scenario reproduces bit-for-bit
    record = trace.save(tmp_path / "scenario_device_fault.json")
    assert_replay(record, lambda t: _run(t, tmp_path / "fr-replay"))


def test_breaker_bundle_carries_breaker_status(tmp_path):
    """The flight bundle written on a trip includes the breaker
    provider's status payload (node.py registers the same provider)."""
    world = FloodWorld(tmp_path / "fr", seed=7)
    try:
        world.submit_wave(8, wave=0)
        world.drain()
        world.verifier.fault = {"finish": "raise"}
        world.submit_wave(8, wave=1)
        world.drain()
        world.tick_slot()
        bundles = FR.list_bundles(world.recorder.directory)
        assert len(bundles) == 1
        loaded = FR.load_bundle(bundles[0]["path"])
        breaker = loaded["files"]["breaker.json"]
        assert breaker["state"] == "open"
        assert breaker["trips"] == 1
        assert breaker["last_failure"]["outcome"] == "error"
        assert breaker["last_failure"]["seam"] == "finish_job"
    finally:
        world.close()
