"""Chaos scenario: device fault mid-merkle-sweep (the ISSUE 16 leg).

With the breaker enabled, an injected device fault during the per-slot
incremental state-root cadence must cost ZERO roots — every slot's
root stays bit-identical to the merkleize_chunks oracle, carried by
the host hash path while the breaker is open.  The SLO engine must
report `degraded` then `ok` through the breaker source, exactly ONE
flight bundle must be written for the trip, and the sweep must return
to device dispatch after the canary re-probe — all deterministic under
a fixed seed and reproducible through the harness's record/replay.
"""

import random

import numpy as np
import pytest

from lodestar_tpu.chain.clock import Clock
from lodestar_tpu.observability import flight_recorder as FR
from lodestar_tpu.observability.flight_recorder import FlightRecorder
from lodestar_tpu.observability.slo import SloEngine
from lodestar_tpu.ssz import ChunkTree
from lodestar_tpu.ssz import device_backend as DB
from lodestar_tpu.utils.metrics import Registry

from chaos.harness import FakeClock, ScenarioTrace, assert_replay

pytest.importorskip("jax")

from lodestar_tpu.bls.supervisor import DeviceSupervisor  # noqa: E402

pytestmark = pytest.mark.smoke

SEED = 4242
LIMIT = 1 << 10  # depth-10 tree: a real multi-level sweep plan


class HtrWorld:
    """DeviceMerkleBackend + breaker + SLO engine + flight recorder,
    wired the way node.py wires the BLS breaker (degraded source, trip
    anomaly -> rate-limited bundle) — the state-root analog of
    FloodWorld."""

    def __init__(self, flightrec_dir, seed: int = 0, backoff_s: float = 2.0):
        self.fake = FakeClock()
        self.registry = Registry()
        self.supervisor = DeviceSupervisor(
            registry=self.registry,
            clock=self.fake,
            auto_probe=False,
            backoff_initial_s=backoff_s,
            enabled=True,
            rng=random.Random(seed),
        )
        self.backend = DB.DeviceMerkleBackend(
            supervisor=self.supervisor,
            registry=self.registry,
            min_level_rows=1,
            use_export=False,
        )
        DB.set_backend(self.backend)
        self.clock = Clock(genesis_time=0.0)
        self.recorder = FlightRecorder(
            str(flightrec_dir), registry=self.registry
        )
        self.recorder.add_provider("breaker", self.supervisor.status)
        self.slo = SloEngine(
            self.clock, registry=self.registry, recorder=self.recorder
        )
        # node.py's breaker wiring pattern, applied to the HTR plane
        self.slo.add_degraded_source("htr_breaker", self.supervisor.is_open)
        self.supervisor.on_trip = lambda info: self.slo.anomaly(
            "htr_breaker_trip", info
        )
        self.supervisor.on_recover = lambda info: self.slo.anomaly(
            "htr_breaker_recovery", info
        )
        self.clock.on_slot(self.slo.on_slot)
        self._slot = 0
        self.rng = np.random.default_rng(seed)
        self.tree = ChunkTree(LIMIT)
        # 384 leaves: the whole cold build fits one sweep dispatch
        # (every level's parent count <= HTR_SWEEP_LANES)
        self.leaves = self.rng.integers(
            0, 256, (384, 32), dtype=np.uint8
        )

    # -- drivers -----------------------------------------------------------

    def tick_slot(self) -> int:
        from lodestar_tpu import params

        self._slot += 1
        self.clock.set_time(self._slot * params.SECONDS_PER_SLOT)
        return self._slot

    def slot_sweep(self, touched: int) -> dict:
        """One slot's worth of leaf churn + incremental re-root.
        Returns the zero-lost-roots summary: the root, whether it
        matches the host merkleize oracle, and whether the device
        carried it."""
        idx = self.rng.integers(0, self.leaves.shape[0], touched)
        self.leaves[idx] = self.rng.integers(
            0, 256, (touched, 32), dtype=np.uint8
        )
        before = self.backend.dispatches
        self.tree.update(self.leaves)
        return {
            "root": self.tree.root.hex(),
            "oracle_ok": self.tree.root == self.tree.full_root_reference(),
            "device_dispatched": self.backend.dispatches > before,
        }

    def close(self) -> None:
        DB.reset_backend()


def _run(trace, fr_dir):
    world = HtrWorld(fr_dir, seed=trace.seed)
    try:
        # cold build: the whole dirty plane, one device round-trip
        world.tree.update(world.leaves)
        trace.emit(
            "cold_build",
            root=world.tree.root.hex(),
            oracle_ok=world.tree.root == world.tree.full_root_reference(),
            dispatches=world.backend.dispatches,
            breaker=world.supervisor.status()["state"],
        )
        s = world.slot_sweep(24)
        trace.emit("healthy", **s)
        world.tick_slot()
        trace.emit("slo_healthy", status=world.slo.status()["status"])

        # the fault lands MID-cadence: the next sweep's dispatch fails,
        # the breaker trips, the host per-level loop carries the root
        world.backend.fault = "backend"
        s = world.slot_sweep(24)
        trace.emit(
            "during_fault",
            **s,
            breaker=world.supervisor.status()["state"],
            host_fallback_used=(
                world.supervisor.m_host_fallback_sets.value > 0
            ),
        )

        # next tick drains the trip anomaly into ONE bundle; health is
        # degraded through the breaker source (not a breach)
        world.tick_slot()
        st = world.slo.status()
        trace.emit(
            "slo_degraded",
            status=st["status"],
            breaker_source=st["degraded_sources"]["htr_breaker"],
        )
        bundles = FR.list_bundles(world.recorder.directory)
        trace.emit(
            "bundles",
            n=len(bundles),
            reason=bundles[0]["reason"] if bundles else None,
        )

        # degraded mode keeps roots flowing, still bit-identical
        s = world.slot_sweep(16)
        trace.emit("degraded_sweep", **s)

        # heal the device; the canary is not due before the backoff
        world.backend.heal()
        world.supervisor.poll()
        trace.emit(
            "probe_not_due", breaker=world.supervisor.status()["state"]
        )
        world.fake.advance(10.0)  # past the 2 s (+/- jitter) backoff
        world.supervisor.poll()
        trace.emit(
            "recovered",
            breaker=world.supervisor.status()["state"],
            degraded_time_counted=world.supervisor.time_in_degraded_s() > 0,
        )
        world.tick_slot()
        trace.emit("slo_ok", status=world.slo.status()["status"])

        # and the sweep actually dispatches to the device again
        s = world.slot_sweep(16)
        trace.emit("device_resumed", **s)
    finally:
        world.close()


def test_htr_device_fault_mid_sweep_acceptance(tmp_path):
    trace = ScenarioTrace(SEED)
    _run(trace, tmp_path / "fr-record")
    ev = {e["kind"]: e for e in trace.events}

    # zero lost roots: every stage's root matches the host oracle
    for stage in ("cold_build", "healthy", "during_fault",
                  "degraded_sweep", "device_resumed"):
        assert ev[stage]["oracle_ok"] is True, (stage, ev[stage])
    assert ev["cold_build"]["dispatches"] == 1
    assert ev["cold_build"]["breaker"] == "closed"
    assert ev["healthy"]["device_dispatched"] is True
    assert ev["slo_healthy"]["status"] == "ok"
    # the trip: breaker open, host path carried the root
    assert ev["during_fault"]["breaker"] == "open"
    assert ev["during_fault"]["device_dispatched"] is False
    assert ev["during_fault"]["host_fallback_used"] is True
    # SLO degraded through the breaker source, exactly one bundle
    assert ev["slo_degraded"]["status"] == "degraded"
    assert ev["slo_degraded"]["breaker_source"] is True
    assert ev["bundles"]["n"] == 1
    assert ev["bundles"]["reason"] == "event.htr_breaker_trip"
    assert ev["degraded_sweep"]["device_dispatched"] is False
    # canary-gated recovery: not before the backoff, then closed
    assert ev["probe_not_due"]["breaker"] == "open"
    assert ev["recovered"]["breaker"] == "closed"
    assert ev["recovered"]["degraded_time_counted"] is True
    assert ev["slo_ok"]["status"] == "ok"
    assert ev["device_resumed"]["device_dispatched"] is True

    # record/replay: the saved scenario reproduces bit-for-bit
    record = trace.save(tmp_path / "scenario_htr_device_fault.json")
    assert_replay(record, lambda t: _run(t, tmp_path / "fr-replay"))


def test_htr_bundle_carries_breaker_status(tmp_path):
    """The flight bundle written on an HTR trip includes the breaker
    provider's status payload, with the sweep seam and the classified
    outcome attributed."""
    world = HtrWorld(tmp_path / "fr", seed=7)
    try:
        world.tree.update(world.leaves)
        world.backend.fault = "bad_output"
        s = world.slot_sweep(8)
        assert s["oracle_ok"] is True  # host carried it anyway
        world.tick_slot()
        bundles = FR.list_bundles(world.recorder.directory)
        assert len(bundles) == 1
        loaded = FR.load_bundle(bundles[0]["path"])
        breaker = loaded["files"]["breaker.json"]
        assert breaker["state"] == "open"
        assert breaker["trips"] == 1
        assert breaker["last_failure"]["outcome"] == "bad_output"
        assert breaker["last_failure"]["seam"] == "htr_forest_sweep"
    finally:
        world.close()
