"""Chaos scenario: mass-equivocation wave (ROADMAP scenario-diversity
item).

Dozens of proposers sign competing blocks inside a single epoch — the
coordinated-slashing-event shape, far past the one-offender fork storm.
Every node must convict every offender through the live gossip stack
(duplicate-proposer verification -> slasher), the proposer slashings
must land on chain (16-per-block cap forces multi-block inclusion)
until every offender's state.slashed flips everywhere, the BLS breaker
must never trip (equivocation is valid-signature traffic, not a device
fault), and slasher memory must stay bounded by its configured window.

Attestations are deliberately sparse here: the wave targets the
proposer plane, and block-only traffic keeps the 3-node x 64-validator
x real-crypto cost inside the slow tier.  Justification-under-storm is
fork_storm's assertion; bounded conviction at scale is this one's.
"""

import pytest

from lodestar_tpu.state_transition.accessors import (
    get_beacon_proposer_index,
)
from lodestar_tpu.state_transition.slot import process_slots

from chaos.harness import (
    ScenarioTrace,
    build_devnet,
    close_devnet,
    heads,
    produce_signed_block,
    publish_block,
    set_clocks,
)

SEED = 2424
N_KEYS = 64
TARGET_OFFENDERS = 24  # two dozen equivocators in one epoch


@pytest.mark.slow
def test_mass_equivocation_wave_convicts_all_offenders():
    from lodestar_tpu import params
    from lodestar_tpu.bls.supervisor import breaker_snapshot
    from lodestar_tpu.validator import ValidatorStore

    trace = ScenarioTrace(SEED)
    world = build_devnet(3, n_keys=N_KEYS)
    names, nodes = world["names"], world["nodes"]
    ref = nodes[names[0]].chain
    cfg = world["cfg"]
    P = params.ACTIVE_PRESET

    offenders = set()
    try:
        # the wave: through epoch 0 every not-yet-caught proposer
        # double-signs until two dozen distinct offenders exist; the
        # chain keeps marching while slashings accumulate on it
        total_slots = P.SLOTS_PER_EPOCH + 16  # wave epoch + inclusion tail
        for slot in range(1, total_slots + 1):
            set_clocks(world, slot)
            st = ref.head_state.clone()
            if st.slot < slot:
                process_slots(st, slot)
            proposer = int(get_beacon_proposer_index(st))
            if bool(st.slashed[proposer]):
                continue  # a slashed proposer cannot produce: skip slot
            signed, _ = produce_signed_block(world, ref, slot)
            competing = None
            in_wave = slot <= P.SLOTS_PER_EPOCH
            if (
                in_wave
                and len(offenders) < TARGET_OFFENDERS
                and proposer not in offenders
            ):
                rogue = ValidatorStore(
                    cfg, {proposer: world["sks"][proposer]}
                )
                block2 = ref.produce_block(
                    slot,
                    rogue.sign_randao(proposer, slot),
                    graffiti=b"\x66" * 32,
                )
                competing = {
                    "message": block2,
                    "signature": rogue.sign_block(proposer, block2),
                }
                offenders.add(proposer)
            assert publish_block(world, signed, slot) == 3
            if competing is not None:
                publish_block(
                    world, competing, slot, from_node="rogue", ledger=False
                )
            # per-slot convergence holds through the whole wave
            assert len(set(heads(world).values())) == 1, slot
        trace.emit(
            "wave",
            offenders=len(offenders),
            converged=True,
        )
        # dozens, not a handful — a thin epoch would gut the scenario
        assert len(offenders) >= 12, len(offenders)

        for name, node in nodes.items():
            # every node convicted EVERY offender
            st = node.slasher.status()
            assert st["detections"]["double_propose"] >= len(offenders), (
                name,
                st["detections"],
            )
            head = node.chain.head_state
            for v in sorted(offenders):
                assert bool(head.slashed[v]), (name, v)
            # bounded slasher memory: the records and queue stay inside
            # the configured window — a 24-offender wave must not grow
            # state past what one epoch of traffic implies
            assert st["queue_length"] == 0, (name, st["queue_length"])
            assert st["proposer_records"] <= 4 * total_slots, (
                name,
                st["proposer_records"],
            )
            assert st["span_history_length"] == 4096, name
        # the breaker never tripped: equivocation is consensus traffic,
        # not a device fault
        breaker = breaker_snapshot()
        assert breaker["trips"] == 0, breaker
        for name, node in nodes.items():
            assert not any(
                node.slo.status()["degraded_sources"].values()
            ), name
        trace.emit(
            "convicted",
            all_slashed=True,
            breaker_trips=int(breaker["trips"]),
        )
    finally:
        close_devnet(world)
