# tests/chaos — deterministic fault-scenario harness (ISSUE 14).
