"""Chaos scenario: an adversarial aggregator attacks the
aggregate-forward plane (ISSUE 19).

The adversary ships contributions designed to poison the pre-verify
aggregation layers that feed the re-publication path: a forged
signature on a FRESH committee index (lands inside the honest layer and
poisons its sum) and a forged signature OVERLAPPING an honest index
(forced into its own layer by the disjointness planner).  Three
guarantees, all replay-asserted:

  1. contributor-wise bisection isolates both forgeries — every honest
     attestation still verifies (zero lost) and each forgery charges
     its publisher through the scorer;
  2. the surviving honest sub-layer STILL re-publishes as a packed
     aggregate, each honest index appears in at most one pack (zero
     double-forwarded), the publisher never sees its own pack echo
     back, and an echoed copy of the pack serves from the preagg
     seen-map with zero device work;
  3. a deferral flood past the deferred-forward queue's capacity sheds
     the adversary's entries and charges it on the gossipsub BEHAVIOUR
     penalty (P7) — honest peers stay unpenalized.
"""

import hashlib
import time

import pytest

from lodestar_tpu.bls.pipeline import BlsVerificationPipeline
from lodestar_tpu.bls.signature_set import WireSignatureSet
from lodestar_tpu.bls.verifier import VerifyOptions
from lodestar_tpu.network.forwarding import (
    PACKED_AGGREGATOR_INDEX,
    AggregateForwarder,
    DeferredForwardQueue,
    DeferredVerdict,
)
from lodestar_tpu.network.gossip import (
    GossipTopicName,
    InMemoryGossipBus,
    decode_message,
    topic_string,
)
from lodestar_tpu.network.scoring import GossipPeerScorer, PeerScoreParams

from chaos.harness import ChaosVerifier, ScenarioTrace, assert_replay, chaos_sig

pytestmark = pytest.mark.smoke

SEED = 1909
DIGEST = b"\x19\x09\x00\x01"
ROOT = b"adversarial aggregator root 32by"
COMMITTEE = (0, 1, 2, 3, 9)
SLOT = 1


def _token(payload: bytes) -> bytes:
    """A 96-byte signature token that PASSES the aggregator's cheap
    wire parse (compression bit set, x coordinate < p) — chaos_sig's
    raw digests do not, and an unparsable signature short-circuits to
    a False verdict before ever reaching a layer."""
    b = bytearray(96)
    b[0] = 0x80
    b[1:33] = hashlib.sha256(payload).digest()
    return bytes(b)


def agg_sig(root: bytes, indices) -> bytes:
    """THE valid (parse-ok) signature for (root, indices) under this
    scenario's oracle — the aggregation-plane analogue of chaos_sig."""
    return _token(b"agg-sig" + bytes(root) + bytes(list(indices)))


class ChaosSumVerifier(ChaosVerifier):
    """ChaosVerifier + an agg_sig-consistent oracle G2 sum, so the
    pre-verify aggregation stage (and the aggregate-forward hook behind
    it) runs over the oracle: summing all-valid member signatures
    yields exactly agg_sig(root, concatenated indices) — the token the
    device/host truth accepts for the union set — while any invalid
    member poisons the sum (the almost-sure behaviour of real point
    addition)."""

    def __init__(self, capacity: int = 64):
        super().__init__(capacity=capacity)
        self.oracle = {}  # signature token -> (root, indices, ok)
        self.sum_calls = 0

    def _truth(self, s) -> bool:
        if isinstance(s, WireSignatureSet):
            return s.signature == agg_sig(s.signing_root, s.indices)
        return super()._truth(s)

    def sig(self, root, indices, ok=True) -> bytes:
        if ok:
            s = agg_sig(root, indices)
        else:  # forged: parse-valid bytes the truth accepts for nothing
            s = _token(b"forged" + bytes(root) + bytes(list(indices)))
        self.oracle[s] = (bytes(root), tuple(indices), bool(ok))
        return s

    def aggregate_wire_signatures(self, groups):
        self.sum_calls += len(groups)
        out = []
        for g in groups:
            infos = [self.oracle.get(bytes(s)) for s in g]
            if any(i is None for i in infos):
                out.append(None)
                continue
            root = infos[0][0]
            idx = tuple(i for info in infos for i in info[1])
            if all(i[2] for i in infos) and all(i[0] == root for i in infos):
                out.append(agg_sig(root, idx))
            else:  # a poisoned sum: parse-valid, accepted by nothing
                out.append(_token(b"poisoned-sum" + root + bytes(list(idx))))
        return out


class ScorerSpy:
    def __init__(self):
        self.charged = []

    def on_invalid_message(self, peer, topic):
        self.charged.append((peer, topic))


def _data(slot=SLOT):
    zero = b"\x00" * 32
    return {
        "slot": slot,
        "index": 0,
        "beacon_block_root": zero,
        "source": {"epoch": 0, "root": zero},
        "target": {"epoch": 0, "root": zero},
    }


def _wait_for(pred, timeout=20.0) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.005)
    return False


def _run_adversarial_aggregator(trace: ScenarioTrace) -> None:
    verifier = ChaosSumVerifier()
    spy = ScorerSpy()
    # a wide coalescing window: all six contributions must land in ONE
    # stage flush regardless of cold-start jitter, or the layer split
    # (and therefore the trace) would depend on wall-clock timing
    pipe = BlsVerificationPipeline(
        verifier, preagg=True, standard_wait_ms=250.0, scorer=spy
    )
    bus = InMemoryGossipBus()
    agg_topic = topic_string(
        DIGEST, GossipTopicName.beacon_aggregate_and_proof
    )
    received = []
    echoes = []
    bus.subscribe("downstream", agg_topic, lambda t, d: received.append(d))
    bus.subscribe("self", agg_topic, lambda t, d: echoes.append(d))
    fwd = AggregateForwarder(bus=bus, node_id="self", fork_digest=DIGEST)
    fwd.register_root(ROOT, SLOT, _data(), COMMITTEE)
    pipe.set_layer_forward(fwd.on_layer_verified)
    try:
        # -- leg 1: the poisoned flood --------------------------------
        # honest contributions on indices 0..3, then the two attacks:
        # a forgery on the FRESH index 9 (packs into the honest layer,
        # poisons its sum) and a forgery OVERLAPPING index 0 (the
        # disjointness planner exiles it to its own layer)
        futures = []
        for i in range(4):
            ws = WireSignatureSet.single(i, ROOT, verifier.sig(ROOT, (i,)))
            futures.append(
                (
                    f"honest-{i}",
                    True,
                    pipe.verify_signature_sets_async(
                        [ws],
                        VerifyOptions(
                            batchable=True,
                            peer_id=f"honest-{i}",
                            topic="beacon_attestation",
                        ),
                    ),
                )
            )
        for label, idx in (("fresh", 9), ("overlap", 0)):
            ws = WireSignatureSet.single(
                idx, ROOT, verifier.sig(ROOT, (idx,), ok=False)
            )
            futures.append(
                (
                    f"adversary/{label}",
                    False,
                    pipe.verify_signature_sets_async(
                        [ws],
                        VerifyOptions(
                            batchable=True,
                            peer_id="adversary",
                            topic="beacon_attestation",
                        ),
                    ),
                )
            )
        mismatches = []
        for label, expected, fut in futures:
            if fut.result(timeout=30.0) != expected:
                mismatches.append(label)
        # the surviving honest sub-layer re-publishes asynchronously on
        # the resolver thread — wait for the pack to land downstream
        assert _wait_for(lambda: len(received) >= 1)
        packs = []
        seen_indices = []
        for payload in received:
            from lodestar_tpu import types as T

            signed = T.SignedAggregateAndProof.deserialize(
                decode_message(payload)
            )
            assert (
                int(signed["message"]["aggregator_index"])
                == PACKED_AGGREGATOR_INDEX
            )
            bits = list(signed["message"]["aggregate"]["aggregation_bits"])
            members = [v for v, b in zip(COMMITTEE, bits) if b]
            packs.append(members)
            seen_indices.extend(members)
        trace.emit(
            "forgery_isolated",
            submitted=len(futures),
            mismatches=mismatches,
            charges=sorted({"%s:%s" % c for c in spy.charged}),
            bisections=pipe.agg_stats()["bisections"],
            packs=sorted(packs),
            double_forwarded=len(seen_indices) - len(set(seen_indices)),
            self_echoes=len(echoes),
        )

        # -- leg 2: the echoed pack serves from the seen-map ----------
        pack = packs[0]
        union = WireSignatureSet.aggregate(
            tuple(pack), ROOT, agg_sig(ROOT, tuple(pack))
        )
        jobs_before = verifier.device_jobs
        served = pipe.preagg_verdict(union)
        trace.emit(
            "echo_served",
            served=bool(served),
            device_jobs_spent=verifier.device_jobs - jobs_before,
        )

        # -- leg 3: deferral flood -> shed -> P7 ----------------------
        scorer = GossipPeerScorer(
            PeerScoreParams(
                behaviour_penalty_weight=-100.0,
                behaviour_penalty_threshold=2.0,
                behaviour_penalty_decay=0.2,
                decay_to_zero=0.01,
            )
        )
        queue = DeferredForwardQueue(scorer=scorer, max_entries=2)
        honest_deferred = DeferredVerdict(slot=SLOT)
        queue.register(
            honest_deferred,
            peer_id="honest-0",
            topic="beacon_attestation_0",
        )
        honest_deferred.resolve(None)  # resolves inside the window
        for _ in range(5):
            queue.register(
                DeferredVerdict(slot=SLOT),
                peer_id="adversary",
                topic="beacon_attestation_0",
            )
        trace.emit(
            "shed_charges_p7",
            in_flight=len(queue),
            shed=queue.stats_snapshot()["shed"],
            adversary_penalized=scorer.behaviour_penalty("adversary") > 0,
            honest_penalized=scorer.behaviour_penalty("honest-0") > 0,
        )
    finally:
        pipe.close()


def test_adversarial_aggregator_isolated_charged_replayed(tmp_path):
    trace = ScenarioTrace(SEED)
    _run_adversarial_aggregator(trace)
    forgery, echo, shed = trace.events

    # every honest attestation verified, both forgeries rejected
    assert forgery["mismatches"] == []
    # bisection ran and the charges hit ONLY the adversary's publisher
    assert forgery["bisections"] >= 1
    assert forgery["charges"] == ["adversary:beacon_attestation"]
    # the honest sub-layer still re-packed; no index forwarded twice,
    # and the publisher never saw its own pack echo back
    assert forgery["packs"] and all(
        len(p) >= 2 for p in forgery["packs"]
    )
    assert forgery["double_forwarded"] == 0
    assert forgery["self_echoes"] == 0

    # an echoed copy of our own pack costs zero device work
    assert echo["served"] is True and echo["device_jobs_spent"] == 0

    # the flood shed charged the adversary on P7, honest peers clean
    assert shed["shed"] == 3 and shed["in_flight"] == 2
    assert shed["adversary_penalized"] is True
    assert shed["honest_penalized"] is False

    record = trace.save(tmp_path / "scenario_adversarial_aggregator.json")
    assert_replay(record, _run_adversarial_aggregator)
