"""Chaos scenario: node crash + restart resuming from db.

A two-node devnet where node-1 persists to disk.  Mid-run it crashes
(dropped from the bus, process state discarded); the network keeps
producing blocks.  On restart, the node re-opens the SAME db — the
pre-crash blocks must still be there — rejoins the bus, range-syncs
back to the live head (its own db serving the blocks it already had),
and follows subsequent gossip in lockstep with the survivor.
"""

import pytest

from chaos.harness import (
    LedgerSource,
    ScenarioTrace,
    build_devnet,
    close_devnet,
    heads,
    produce_signed_block,
    publish_attestations,
    publish_block,
    set_clocks,
)


@pytest.mark.slow
def test_crash_restart_resumes_from_db_and_reconverges(tmp_path):
    from lodestar_tpu.node import FullBeaconNode, NodeOptions

    trace = ScenarioTrace(55)
    db_path = str(tmp_path / "node-1-db")
    world = build_devnet(2, db_paths={"node-1": db_path})
    names, nodes = world["names"], world["nodes"]
    ref = nodes[names[0]].chain
    crashed_name = names[1]
    try:
        # healthy run: slots 1..3 reach both nodes
        for slot in (1, 2, 3):
            set_clocks(world, slot)
            signed, _ = produce_signed_block(world, ref, slot)
            assert publish_block(world, signed, slot) == 2
            publish_attestations(world, ref, slot)
        assert len(set(heads(world).values())) == 1
        pre_crash_head = nodes[crashed_name].chain.head_root_hex
        trace.emit("healthy", converged=True)

        # CRASH: node-1 vanishes (bus drop + close, which flushes db);
        # it also leaves the tick loop — a dead process gets no slots
        world["bus"].drop_node(crashed_name)
        world["nodes"].pop(crashed_name).close()
        for slot in (4, 5, 6):
            set_clocks(world, slot)
            signed, _ = produce_signed_block(world, ref, slot)
            assert publish_block(world, signed, slot) == 1  # only node-0
            publish_attestations(world, ref, slot)
        trace.emit(
            "crashed",
            survivor_head_slot=int(nodes[names[0]].chain.head_state.slot),
        )

        # RESTART from the same db: the pre-crash blocks are still
        # there (resume-from-db), the node rejoins the bus fresh
        from lodestar_tpu.bls.single_thread import CpuBlsVerifier

        restarted = FullBeaconNode.init(
            world["cfg"],
            world["genesis"],
            NodeOptions(
                serve_api=False,
                verifier=CpuBlsVerifier(pubkeys=world["pk_points"]),
                gossip_bus=world["bus"],
                node_id=crashed_name,
                active_validator_count_hint=len(world["sks"]),
                subscribe_all_subnets=True,
                db_path=db_path,
            ),
        )
        nodes[crashed_name] = restarted
        world["nodes"][crashed_name] = restarted
        pre_root = bytes.fromhex(pre_crash_head)
        persisted = restarted.db.get_block_anywhere(pre_root)
        trace.emit("restarted", db_resumed=persisted is not None)
        assert persisted is not None, (
            "restart lost the pre-crash blocks from db"
        )

        # catch up: range sync from the survivor's serving surface —
        # the restarted node's own db covers what it already had.  The
        # clock sits two slots past the head, so the catch-up imports
        # are judged as historical (no deadline breaches for downtime).
        set_clocks(world, 8)
        source = LedgerSource(world, db=restarted.db)
        target = int(nodes[names[0]].chain.head_state.slot)
        imported = restarted.range_sync.sync_to(
            {"node-0": source}, target
        )
        trace.emit(
            "synced",
            imported=imported,
            converged=len(set(heads(world).values())) == 1,
        )
        assert imported == 6
        assert len(set(heads(world).values())) == 1

        # back in lockstep: live gossip reaches the restarted node
        set_clocks(world, 9)
        signed, _ = produce_signed_block(world, ref, 9)
        assert publish_block(world, signed, 9) == 2
        publish_attestations(world, ref, 9)
        assert len(set(heads(world).values())) == 1
        # the restarted node's SLO history is clean: the crash outage
        # replayed as historical sync, and live slots meet deadlines
        assert restarted.slo.status()["status"] == "ok"
        trace.emit("final", converged=True)
    finally:
        close_devnet(world)
