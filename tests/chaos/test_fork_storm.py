"""Chaos scenario: fork storm — competing blocks from equivocating
proposers.

At two slots the proposer signs a SECOND, conflicting block (different
graffiti — a genuine double-proposal, signed by a protection-less rogue
store).  Every node must: keep converging on one head each slot, detect
the double proposal through the live gossip stack (duplicate-proposer
verification -> slasher), include the proposer slashing in a later
block, and slash the offender in the final state — while justification
still progresses.
"""

import pytest

from lodestar_tpu.state_transition.accessors import (
    get_beacon_proposer_index,
)
from lodestar_tpu.state_transition.slot import process_slots

from chaos.harness import (
    ScenarioTrace,
    build_devnet,
    close_devnet,
    heads,
    produce_signed_block,
    publish_attestations,
    publish_block,
    set_clocks,
)


@pytest.mark.slow
def test_fork_storm_competing_proposers_slashed_and_converged():
    from lodestar_tpu import params
    from lodestar_tpu.validator import ValidatorStore

    trace = ScenarioTrace(99)
    world = build_devnet(3)
    names, nodes = world["names"], world["nodes"]
    ref = nodes[names[0]].chain
    cfg = world["cfg"]
    P = params.ACTIVE_PRESET

    offenders = set()
    included_at = None
    try:
        total_slots = 3 * P.SLOTS_PER_EPOCH
        storm_slots = {3, 5}
        for slot in range(1, total_slots + 1):
            set_clocks(world, slot)
            st = ref.head_state.clone()
            if st.slot < slot:
                process_slots(st, slot)
            proposer = int(get_beacon_proposer_index(st))
            if bool(st.slashed[proposer]):
                continue  # a slashed proposer cannot produce: skip slot
            signed, _ = produce_signed_block(world, ref, slot)
            if signed["message"]["body"]["proposer_slashings"] and (
                included_at is None
            ):
                included_at = slot
            competing = None
            if slot in storm_slots and not offenders:
                # the storm: the SAME proposer signs a competing block
                # for the SAME slot (protection-less rogue signer; the
                # honest store would refuse the double sign).  Both
                # blocks build on the pre-slot head — produce before
                # either is published/imported.
                rogue = ValidatorStore(
                    cfg, {proposer: world["sks"][proposer]}
                )
                block2 = ref.produce_block(
                    slot,
                    rogue.sign_randao(proposer, slot),
                    graffiti=b"\x42" * 32,
                )
                competing = {
                    "message": block2,
                    "signature": rogue.sign_block(proposer, block2),
                }
                offenders.add(proposer)
            assert publish_block(world, signed, slot) == 3
            if competing is not None:
                publish_block(
                    world, competing, slot, from_node="rogue", ledger=False
                )
            publish_attestations(world, ref, slot, quiet=offenders)
            # convergence holds THROUGH the storm, not just at the end
            assert len(set(heads(world).values())) == 1, slot
        trace.emit(
            "storm",
            offenders=sorted(offenders),
            included_at=included_at,
            converged=True,
        )

        assert offenders, "no storm was mounted"
        assert included_at is not None, (
            "proposer slashing never included in a block"
        )
        offender = next(iter(offenders))
        for name, node in nodes.items():
            # slasher coverage: every node detected the double proposal
            assert node.slasher.detections["double_propose"] >= 1, name
            # and the offender is slashed in the head state everywhere
            assert bool(node.chain.head_state.slashed[offender]), name
        # justification progressed despite the storm
        for name, node in nodes.items():
            je = int(
                node.chain.head_state.current_justified_checkpoint["epoch"]
            )
            assert je >= 1, (name, je)
        # liveness: no node reports degraded health at the end
        for name, node in nodes.items():
            assert node.slo.status()["status"] in ("ok", "degraded"), name
        trace.emit("final", offender_slashed=True, justified=True)
    finally:
        close_devnet(world)
