"""Chaos scenario: memory squeeze under a light-client horde (ISSUE 17
acceptance).

A churn phase with participating sync aggregates makes the
LightClientServer produce plane-served updates; a synthetic horde of
light clients then hammers the ProofService with mixed request shapes
(bootstrap / updates-by-range / optimistic / state proofs).  The budget
is tightened mid-horde: the governor must drain the proof-bundle cache
FIRST (the "drain" ladder tier fires before any state demotes for the
aux bytes), the service degrades to host-path serving with ZERO wrong
proofs (every branch still verifies against its anchoring root), the
SLO reports exactly one degraded source for the episode, and the whole
scenario replays bit-for-bit from its trace.
"""

import hashlib

import pytest

from lodestar_tpu import params
from lodestar_tpu import types as T
from lodestar_tpu.chain.light_client_server import LightClientServer
from lodestar_tpu.light_client.lightclient import (
    NEXT_SYNC_COMMITTEE_DEPTH,
    NEXT_SYNC_COMMITTEE_INDEX,
)
from lodestar_tpu.observability import flight_recorder as FR
from lodestar_tpu.proofs import ProofService, verify_multiproof
from lodestar_tpu.ssz import is_valid_merkle_branch

from chaos.harness import ScenarioTrace, StateWorld, assert_replay

pytestmark = pytest.mark.smoke

P = params.ACTIVE_PRESET
SEED = 1701
CHURN_SLOTS = 6
HORDE_CLIENTS = 8

STATE_PROOF_SHAPES = [
    [["finalized_checkpoint", "root"]],
    [["slot"], ["next_sync_committee"]],
    [["balances", "0"], ["finalized_checkpoint", "epoch"], ["slot"]],
]


def _sync_block(world, slot):
    """A head block with FULL sync participation (fake signature — the
    world's stub verifier owns crypto) so the LightClientServer
    produces an update for it."""
    from lodestar_tpu.chain.produce_block import produce_block

    parent_hex = world.chain.head_root_hex
    parent_state = world.chain.regen._get_post_state(parent_hex)
    randao = hashlib.sha256(b"horde randao %d" % slot).digest() * 3
    block, _post = produce_block(
        parent_state,
        slot,
        randao,
        sync_aggregate={
            "sync_committee_bits": [True] * P.SYNC_COMMITTEE_SIZE,
            "sync_committee_signature": bytes([0xC0]) + b"\x00" * 95,
        },
    )
    signed = {"message": block, "signature": b"\x00" * 96}
    root = world.chain.process_block(signed)
    world.expected_roots[root.hex()] = block["state_root"].hex()
    return root


def _verify_update(upd) -> bool:
    """The light client's own acceptance math: the produced
    next-sync-committee branch must bind to the attested state root."""
    leaf = T.SyncCommittee.hash_tree_root(upd.next_sync_committee)
    return is_valid_merkle_branch(
        leaf,
        upd.next_sync_committee_branch,
        NEXT_SYNC_COMMITTEE_DEPTH,
        NEXT_SYNC_COMMITTEE_INDEX,
        upd.attested_header["state_root"],
    )


def _verify_state_proof_data(data, root) -> bool:
    """Every proof in a state_proof_data payload verifies against the
    reported state root (single- and multi-path shapes)."""
    if data["state_root"] != "0x" + root.hex():
        return False
    singles = data["proofs"] if "proofs" in data else [data]
    for p in singles:
        ok = is_valid_merkle_branch(
            bytes.fromhex(p["leaf"][2:]),
            [bytes.fromhex(b[2:]) for b in p["branch"]],
            p["depth"],
            p["index"],
            root,
        )
        if not ok:
            return False
    if "multiproof" in data:
        leaves = {
            int(x["gindex"]): bytes.fromhex(x["node"][2:])
            for x in data["multiproof"]["leaves"]
        }
        helpers = [
            (int(x["gindex"]), bytes.fromhex(x["node"][2:]))
            for x in data["multiproof"]["helpers"]
        ]
        if not verify_multiproof(leaves, helpers, root):
            return False
    return True


def _horde_round(world, service, trace, label):
    """One pass of the synthetic horde: each client issues a mixed
    request shape; every served proof is verified.  Emits one event
    with the wrong-proof count (must be 0) and the served totals."""
    lc = service.lc
    head_root = world.chain.get_head_root()
    head_state = world.chain.head_state
    state_root = head_state.hash_tree_root()
    wrong = served = 0
    for i in range(HORDE_CLIENTS):
        shape = i % 4
        if shape == 0:  # bootstrap from the trusted head root
            boot = service.bootstrap(head_root)
            if boot is not None:
                served += 1
                host = lc.get_bootstrap(head_root)
                leaf = T.SyncCommittee.hash_tree_root(
                    host["current_sync_committee"]
                )
                if not is_valid_merkle_branch(
                    leaf,
                    host["current_sync_committee_branch"],
                    NEXT_SYNC_COMMITTEE_DEPTH,
                    NEXT_SYNC_COMMITTEE_INDEX - 1,
                    bytes(host["header"]["state_root"]),
                ):
                    wrong += 1
        elif shape == 1:  # updates by range
            items = service.light_client_updates(0, 2)
            for _item in items:
                served += 1
            if not all(
                _verify_update(lc.get_update(p))
                for p in lc.best_update_by_period
            ):
                wrong += 1
        elif shape == 2:  # optimistic (finality pre-finalization: 404)
            item = service.optimistic_update()
            if item is not None:
                served += 1
                if not _verify_update(lc.get_optimistic_update()):
                    wrong += 1
            if service.finality_update() is not None:
                served += 1
        else:  # state-field proofs, rotating shapes
            paths = STATE_PROOF_SHAPES[i % len(STATE_PROOF_SHAPES)]
            data = service.state_proof_data(head_state, paths)
            served += 1
            if not _verify_state_proof_data(data, state_root):
                wrong += 1
    trace.emit(
        label,
        served=served,
        wrong_proofs=wrong,
        sources=dict(service.sources),
        cache_entries=service.cache.stats()["entries"],
    )


def _run(trace, fr_dir):
    world = StateWorld(fr_dir, seed=trace.seed)
    gov = world.governor
    assert gov is not None, "governor must be default-on"
    lc = LightClientServer(world.chain)
    service = ProofService(
        world.chain, light_client_server=lc, governor=gov
    )
    try:
        # phase 1: churn with full sync participation -> the
        # LightClientServer extracts branches off the warm planes
        for _ in range(CHURN_SLOTS):
            slot = world.tick_slot()
            _sync_block(world, slot)
        trace.emit(
            "produced",
            updates=lc.produced,
            plane_proofs=lc.plane_proofs,
            host_proofs=lc.host_proofs,
            aux_accounted=gov.status()["aux_bytes"] >= 0,
        )

        # phase 2: horde A against the warm plane + filling bundles,
        # then again so repeats hit the bundle tier
        _horde_round(world, service, trace, "horde_warm")
        _horde_round(world, service, trace, "horde_repeat")

        # phase 3: the squeeze — budget to half the CURRENT total; the
        # bundle cache must drain before any live state demotes
        working_set = gov.ledger.resident_bytes
        bundle_bytes = service.cache.resident_bytes()
        budget = working_set // 2
        gov.set_budget(budget)
        st = world.slo.status()
        degraded = [
            k for k, v in st["degraded_sources"].items() if v
        ]
        trace.emit(
            "squeeze",
            bundle_bytes_before=bundle_bytes > 0,
            cache_drained=service.cache.resident_bytes() == 0,
            drain_tier_fired=gov.evictions["drain"] > 0,
            within_budget=(
                gov.ledger.resident_bytes + service.cache.resident_bytes()
                <= budget
            ),
            episode_open=gov.pressure_active,
            slo_status=st["status"],
            degraded_sources=degraded,
        )

        # phase 4: horde B under pressure — bundles are gone, old
        # states may be demoted; everything re-serves (host tier rises)
        # and still verifies
        host_before = service.sources["host"]
        _horde_round(world, service, trace, "horde_squeezed")
        trace.emit(
            "degraded_serving",
            host_grew=service.sources["host"] > host_before,
            total_plane=service.sources["plane"] + lc.plane_proofs,
        )

        # phase 5: quiet ticks close the episode; one bundle for the
        # whole squeeze
        world.tick_slot()
        world.tick_slot()
        st = world.slo.status()
        bundles = FR.list_bundles(world.recorder.directory)
        trace.emit(
            "settled",
            slo_status=st["status"],
            episode_open=gov.pressure_active,
            pressure_events=gov._pressure_events,
            flight_bundles=len(bundles),
            bundle_reason=bundles[0]["reason"] if bundles else None,
        )
    finally:
        world.close()


def test_proof_horde_memory_squeeze(tmp_path):
    trace = ScenarioTrace(SEED)
    _run(trace, tmp_path / "fr-record")
    ev = {e["kind"]: e for e in trace.events}

    # churn produced plane-served updates (zero host fallbacks while
    # the engines are warm)
    assert ev["produced"]["updates"] == CHURN_SLOTS
    assert ev["produced"]["plane_proofs"] == CHURN_SLOTS
    assert ev["produced"]["host_proofs"] == 0
    assert ev["produced"]["aux_accounted"] is True

    # horde A: zero wrong proofs; the repeat round served bundles
    assert ev["horde_warm"]["wrong_proofs"] == 0
    assert ev["horde_warm"]["served"] > 0
    assert ev["horde_warm"]["sources"]["plane"] > 0
    assert ev["horde_repeat"]["wrong_proofs"] == 0
    assert ev["horde_repeat"]["sources"]["bundle"] > 0
    assert ev["horde_repeat"]["cache_entries"] > 0

    # the squeeze: bundles drained FIRST and completely, the drain
    # ladder tier fired, total residency (ledger + aux) converged
    assert ev["squeeze"]["bundle_bytes_before"] is True
    assert ev["squeeze"]["cache_drained"] is True
    assert ev["squeeze"]["drain_tier_fired"] is True
    assert ev["squeeze"]["within_budget"] is True
    assert ev["squeeze"]["episode_open"] is True
    # exactly ONE degraded source reports the whole episode
    assert ev["squeeze"]["slo_status"] == "degraded"
    assert ev["squeeze"]["degraded_sources"] == ["state_memory"]

    # horde B: still zero wrong proofs, host tier absorbed the misses
    assert ev["horde_squeezed"]["wrong_proofs"] == 0
    assert ev["degraded_serving"]["host_grew"] is True

    # the episode closed, health returned, one flight bundle
    assert ev["settled"]["slo_status"] == "ok"
    assert ev["settled"]["episode_open"] is False
    assert ev["settled"]["pressure_events"] == 1
    assert ev["settled"]["flight_bundles"] == 1
    assert ev["settled"]["bundle_reason"] == "event.state_memory_pressure"

    # record/replay: the saved scenario reproduces bit-for-bit
    record = trace.save(tmp_path / "scenario_proof_horde.json")
    assert_replay(record, lambda t: _run(t, tmp_path / "fr-replay"))
