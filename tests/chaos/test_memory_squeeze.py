"""Chaos scenario: memory squeeze on the state plane (ISSUE 15
acceptance).

A fork-churn burst builds the regen LRU + checkpoint-cache working set;
the budget is then tightened to <= 0.5x of it.  The governor must
converge residency to the budget within 4 slots of continued churn with
ZERO lost or incorrect regen results (every block root ever imported —
including demoted-then-touched and evicted-then-replayed states —
regenerates bit-identical to its never-evicted twin), zero
NO_ANCHOR_STATE errors, SLO `degraded` while the pressure episode is
open and `ok` after it closes, and exactly ONE flight bundle for the
whole episode.  With the escape hatch set the governor is absent and
the pre-governor count-based cache bounds apply unchanged.
"""

import pytest

from lodestar_tpu.chain.memory_governor import SpilledState
from lodestar_tpu.chain.regen import RegenError
from lodestar_tpu.observability import flight_recorder as FR

from chaos.harness import ScenarioTrace, StateWorld, assert_replay

pytestmark = pytest.mark.smoke

SEED = 1501
CHURN_SLOTS = 10
SQUEEZE_SLOTS = 4


def _run(trace, fr_dir):
    world = StateWorld(fr_dir, seed=trace.seed)
    gov = world.governor
    assert gov is not None, "governor must be default-on"
    try:
        # phase 1: fork churn builds the working set (no pressure —
        # the default budget is generous)
        for _ in range(CHURN_SLOTS):
            slot = world.tick_slot()
            world.churn_slot(slot)
        world.warm_checkpoint(1)  # the epoch-boundary precompute entry
        working_set = gov.ledger.resident_bytes
        trace.emit(
            "working_set",
            nonzero=working_set > 0,
            entries=len(gov.ledger),
            pressure=gov.pressure_active,
        )
        world.tick_slot()
        trace.emit("slo_healthy", status=world.slo.status()["status"])

        # phase 2: the squeeze — budget to half the working set; the
        # first eviction wave opens the pressure episode
        budget = working_set // 2
        gov.set_budget(budget)
        st = world.slo.status()
        trace.emit(
            "squeeze",
            within_budget=gov.ledger.resident_bytes <= budget,
            episode_open=gov.pressure_active,
            evicted=sum(gov.evictions.values()) > 0,
            slo_status=st["status"],
            degraded_source=st["degraded_sources"]["state_memory"],
        )

        # phase 3: churn continues under the tight budget; residency
        # must hold at-or-under budget at EVERY slot boundary
        no_anchor = memory_pressure = 0
        within = []
        for _ in range(SQUEEZE_SLOTS):
            slot = world.tick_slot()
            try:
                world.churn_slot(slot)
            except RegenError as e:  # pragma: no cover - must not happen
                if e.code == "NO_ANCHOR_STATE":
                    no_anchor += 1
                elif e.code == "MEMORY_PRESSURE":
                    memory_pressure += 1
                else:
                    raise
            within.append(gov.ledger.resident_bytes <= budget)
        trace.emit(
            "converged",
            all_within_budget=all(within),
            slots=len(within),
            no_anchor_errors=no_anchor,
            memory_pressure_errors=memory_pressure,
            episode_still_open=gov.pressure_active,
        )

        # phase 4: zero lost/incorrect regen — EVERY imported block's
        # post-state regenerates bit-identical to its recorded twin
        # root (spilled entries rehydrate, evicted ones replay from db)
        spilled_before = sum(
            isinstance(e, SpilledState)
            for e in world.chain.regen.state_cache.states()
        )
        results = {}
        for root_hex in sorted(world.expected_roots):
            try:
                results[root_hex] = world.verify_regen(root_hex)
            except RegenError as e:
                results[root_hex] = f"regen-error:{e.code}"
        trace.emit(
            "regen_check",
            total=len(results),
            all_identical=all(v is True for v in results.values()),
            failures=sorted(
                r for r, v in results.items() if v is not True
            ),
        )
        # the replays re-added fully-owned engines -> the next waves
        # demote them (the economic tier-1 path) and evict the cold
        # tail; both ladder tiers must have fired by now
        trace.emit(
            "ladder",
            demotes=gov.evictions["demote"] > 0,
            evicts=gov.evictions["evict"] > 0,
            spilled_entries_seen=spilled_before >= 0,
            within_budget=gov.ledger.resident_bytes <= budget,
        )

        # phase 5: the churn stops.  The first tick absorbs the
        # eviction wave the regen sweep triggered; the next tick is
        # quiet AND compliant, which closes the episode and returns
        # health to ok
        world.tick_slot()
        world.tick_slot()
        st = world.slo.status()
        trace.emit(
            "slo_ok",
            status=st["status"],
            episode_open=gov.pressure_active,
            pressure_events=gov._pressure_events,
        )
        bundles = FR.list_bundles(world.recorder.directory)
        trace.emit(
            "bundles",
            n=len(bundles),
            reason=bundles[0]["reason"] if bundles else None,
        )
        # the ledger's incremental accounting still matches the full
        # walk (the reconciliation invariant, here end-to-end)
        trace.emit(
            "ledger_reconciled",
            exact=gov.ledger.plane_bytes == world.chain.regen.engine_bytes(),
        )
    finally:
        world.close()


def test_memory_squeeze_acceptance(tmp_path):
    trace = ScenarioTrace(SEED)
    _run(trace, tmp_path / "fr-record")
    ev = {e["kind"]: e for e in trace.events}

    assert ev["working_set"]["nonzero"] is True
    assert ev["working_set"]["pressure"] is False
    assert ev["slo_healthy"]["status"] == "ok"
    # the squeeze: eviction converged IMMEDIATELY (well inside the
    # 4-slot acceptance bound), the episode opened, health is degraded
    # through the live source
    assert ev["squeeze"]["within_budget"] is True
    assert ev["squeeze"]["episode_open"] is True
    assert ev["squeeze"]["evicted"] is True
    assert ev["squeeze"]["slo_status"] == "degraded"
    assert ev["squeeze"]["degraded_source"] is True
    # sustained churn under the budget: every slot boundary compliant,
    # zero anchor losses, no thrash-rejection at this pressure level
    assert ev["converged"]["all_within_budget"] is True
    assert ev["converged"]["no_anchor_errors"] == 0
    assert ev["converged"]["memory_pressure_errors"] == 0
    assert ev["converged"]["episode_still_open"] is True
    # zero lost/incorrect regen results, bit-identical to the twins
    assert ev["regen_check"]["all_identical"] is True, (
        ev["regen_check"]["failures"]
    )
    assert ev["regen_check"]["total"] > CHURN_SLOTS
    # both ladder tiers fired and the budget still holds
    assert ev["ladder"]["demotes"] is True
    assert ev["ladder"]["evicts"] is True
    assert ev["ladder"]["within_budget"] is True
    # episode closed on the quiet tick; exactly ONE bundle for the
    # whole episode; the ledger matches the walk
    assert ev["slo_ok"]["status"] == "ok"
    assert ev["slo_ok"]["episode_open"] is False
    assert ev["slo_ok"]["pressure_events"] == 1
    assert ev["bundles"]["n"] == 1
    assert ev["bundles"]["reason"] == "event.state_memory_pressure"
    assert ev["ledger_reconciled"]["exact"] is True

    # record/replay: the saved scenario reproduces bit-for-bit
    record = trace.save(tmp_path / "scenario_memory_squeeze.json")
    assert_replay(record, lambda t: _run(t, tmp_path / "fr-replay"))


def test_squeeze_bundle_carries_memory_status(tmp_path):
    """The flight bundle written at episode start includes the governor
    provider's status payload (node.py registers the same provider)."""
    world = StateWorld(tmp_path / "fr", seed=7)
    gov = world.governor
    try:
        for _ in range(6):
            slot = world.tick_slot()
            world.churn_slot(slot)
        gov.set_budget(gov.ledger.resident_bytes // 2)
        world.tick_slot()  # drains the parked anomaly into the bundle
        bundles = FR.list_bundles(world.recorder.directory)
        assert len(bundles) == 1
        loaded = FR.load_bundle(bundles[0]["path"])
        mem = loaded["files"]["memory.json"]
        assert mem["budget_bytes"] == gov.budget
        assert mem["pressure_events"] == 1
        assert mem["evictions"]["demote"] + mem["evictions"]["evict"] > 0
    finally:
        world.close()


def test_escape_hatch_restores_count_bounds(tmp_path):
    """LODESTAR_TPU_STATE_BUDGET=0: no governor — the chain runs the
    pre-governor count-based LRU exactly as before this PR."""
    world = StateWorld(tmp_path / "fr", seed=3, budget_bytes=0)
    try:
        assert world.chain.memory_governor is None
        assert world.chain.regen.state_cache.governor is None
        assert world.chain.regen.checkpoint_cache.governor is None
        for _ in range(4):
            slot = world.tick_slot()
            world.churn_slot(slot)
        cache = world.chain.regen.state_cache
        # count-bounded, never spilled, and the walk is the metric path
        assert len(cache) <= cache.max_states
        assert not any(
            isinstance(e, SpilledState) for e in cache.states()
        )
        assert world.chain.regen.resident_bytes() == (
            world.chain.regen.engine_bytes()
        )
        # every import still regenerates bit-identical
        for root_hex in world.expected_roots:
            assert world.verify_regen(root_hex)
    finally:
        world.close()
