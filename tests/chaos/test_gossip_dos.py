"""Chaos scenario: gossip DoS — invalid-signature floods.

Three legs of the ROADMAP "gossip DoS" scenario, all fast and seeded:

  1. an invalid-signature flood through the pipeline resolves every
     verdict correctly and must NOT trip the device breaker (bad
     signatures are protocol inputs, not device faults) — with
     record/replay;
  2. the RLC bisection floor bounds the verification cost of a flood
     that poisons large batches (O(log N) batch checks per bad set);
  3. queue overflow under a flood charges the flooding peer through the
     gossip scorer (rejection caps) while the SLO queue-drop watcher
     books the anomaly — and the peer recovers after decay.
"""

import pytest

from lodestar_tpu.bls.verifier import _DeviceJob
from lodestar_tpu.network.gossip_queues import (
    DropByCount,
    GossipQueue,
    GossipQueueOpts,
    GossipType,
    QueueType,
)
from lodestar_tpu.network.processor import (
    NetworkProcessor,
    PendingGossipMessage,
)
from lodestar_tpu.network.scoring import GossipPeerScorer, PeerScoreParams
from lodestar_tpu.utils.metrics import Registry

from chaos.harness import (
    FloodWorld,
    OkSet,
    RlcOracleVerifier,
    ScenarioTrace,
    assert_replay,
)

pytestmark = pytest.mark.smoke

SEED = 4242


def _run_invalid_flood(trace, fr_dir):
    world = FloodWorld(fr_dir, seed=trace.seed)
    try:
        # sustained flood: half the traffic carries garbage signatures
        for wave in range(4):
            world.submit_wave(32, wave=wave, invalid_every=2)
        s = world.drain()
        world.tick_slot()
        trace.emit(
            "flood",
            **s,
            breaker=world.supervisor.status()["state"],
            trips=world.supervisor.trip_count,
            slo=world.slo.status()["status"],
            device_path_used=world.verifier.device_jobs > 0,
        )
    finally:
        world.close()


def test_invalid_signature_flood_does_not_trip_breaker(tmp_path):
    trace = ScenarioTrace(SEED)
    _run_invalid_flood(trace, tmp_path / "fr")
    ev = trace.events[0]
    assert ev["mismatches"] == []
    assert ev["invalid_rejected"] == 64  # every second message
    assert ev["valid_confirmed"] == 64
    # protocol-level garbage is NOT a device fault
    assert ev["breaker"] == "closed" and ev["trips"] == 0
    assert ev["slo"] == "ok"
    assert ev["device_path_used"] is True
    record = trace.save(tmp_path / "scenario_gossip_dos.json")
    assert_replay(record, lambda t: _run_invalid_flood(t, tmp_path / "fr2"))


def test_bisection_floor_bounds_flood_verification_cost():
    """A flood that poisons every 512-set batch with a few bad sets
    costs O(bad * log N) batch checks, and per-set sweeps only at the
    one-tile floor — the DoS amplification bound of PR 10's fallback."""
    v = RlcOracleVerifier(bisect_leaf=16)
    total_sets = 0
    for batch_i in range(4):
        sets = [OkSet(True) for _ in range(512)]
        sets[37 * (batch_i + 1) % 512].ok = False  # one poisoned set
        total_sets += len(sets)
        job = _DeviceJob(sets, True, True, wire=False)
        job.batch_ok = False  # the merged batch check failed
        import numpy as np

        job.decodable = np.ones(len(sets), bool)
        job.n_bucket = 512
        assert v._finish_job(job) is False
        assert int(job.verdicts.sum()) == 511
    # each poisoned 512-batch bisects in <= 2*log2(512/16) batches and
    # sweeps per-set only at the 16-lane leaves
    assert len(v.batch_calls) <= 4 * 2 * 5
    assert sum(v.leaf_calls) <= 4 * 2 * 16
    assert total_sets == 2048


def test_flood_overflow_charges_flooder_and_peer_recovers():
    """Rejection caps: a flooding peer's overflow drops charge ITS
    score (gossipsub P7), honest peers keep flowing, the SLO drop
    watcher books the anomaly, and decay rehabilitates the flooder."""
    from lodestar_tpu.chain.clock import Clock
    from lodestar_tpu.observability.slo import SloEngine
    from lodestar_tpu.utils.metrics import Registry as _Reg

    registry = Registry()
    scorer = GossipPeerScorer(
        PeerScoreParams(
            behaviour_penalty_weight=-100.0,
            behaviour_penalty_threshold=2.0,
            behaviour_penalty_decay=0.2,
            decay_to_zero=0.01,
        )
    )
    done = []
    accept = {"ok": False}  # backpressure holds while the flood lands
    topic = GossipType.beacon_attestation
    proc = NetworkProcessor(
        lambda m: done.append(m),
        [lambda: accept["ok"]],
        registry=registry,
        scorer=scorer,
    )
    proc.queues[topic] = GossipQueue(
        GossipQueueOpts(QueueType.LIFO, 8, DropByCount(1)),
        topic=topic.value,
        metrics=proc.queues[topic].metrics,
        on_drop=proc._on_queue_drop,
    )
    clock = Clock(genesis_time=0.0)
    slo = SloEngine(clock, registry=_Reg())
    from lodestar_tpu.observability.timeseries import labeled_total

    slo.add_watcher(
        "queue_drop_burst",
        lambda: labeled_total(
            registry.get("lodestar_gossip_queue_dropped_total")
        ),
        threshold=8.0,
    )
    clock.on_slot(slo.on_slot)
    clock.set_time(12.0)  # baseline watcher read (slot 1)

    # the flood: 24 attacker messages into an 8-deep queue + 2 honest
    for i in range(24):
        proc.on_gossip_message(
            PendingGossipMessage(topic, ("atk", i), peer_id="flooder")
        )
    for i in range(2):
        proc.on_gossip_message(
            PendingGossipMessage(topic, ("honest", i), peer_id="friend")
        )
    assert scorer.behaviour_penalty("flooder") > 0
    assert scorer.behaviour_penalty("friend") == 0.0
    clock.set_time(24.0)  # next slot: the drop-burst anomaly books
    assert slo.m_anomalies.get("queue_drop_burst") == 1

    # backpressure releases: the surviving queue drains to the worker
    accept["ok"] = True
    while proc.execute_work():
        pass
    assert len(done) > 0
    # rehabilitation: decay clears the penalty
    for _ in range(200):
        scorer.decay()
    assert scorer.behaviour_penalty("flooder") == 0.0
    assert not scorer.is_banned("flooder")
