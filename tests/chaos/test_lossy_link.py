"""Chaos scenario: lossy-link soak (ROADMAP scenario-diversity item).

Three nodes over a bus whose every delivery is dropped with seeded 10%
probability — not a clean partition but the grinding packet loss a real
overlay degrades into.  Blocks that slip past a node are recovered
through the unknown-block walk-back (the gossip-miss recovery path a
production node runs); attestation losses are simply absorbed.  Over
three epochs every node must keep finalizing and every head must
reconverge once links heal.
"""

import numpy as np
import pytest

from chaos.harness import (
    LedgerSource,
    ScenarioTrace,
    build_devnet,
    close_devnet,
    heads,
    produce_signed_block,
    publish_attestations,
    publish_block,
    set_clocks,
)

SEED = 1010
DROP_RATE = 0.10


@pytest.mark.slow
def test_lossy_link_soak_finalizes_and_reconverges():
    from lodestar_tpu import params

    trace = ScenarioTrace(SEED)
    world = build_devnet(3)
    names, nodes = world["names"], world["nodes"]
    ref = nodes[names[0]].chain
    P = params.ACTIVE_PRESET
    rng = np.random.default_rng(SEED)
    dropped = {"n": 0}

    def lossy(_src: str, _dst: str, _topic: str) -> bool:
        if rng.random() < DROP_RATE:
            dropped["n"] += 1
            return False
        return True

    world["bus"].set_link_filter(lossy)
    try:
        # finalization needs ~4 epochs even at full participation
        # (justify E-1/E at each boundary, finalize two boundaries
        # later); aggregates-only publishing keeps the real-crypto cost
        # of the long soak inside the slow-tier budget — the drops
        # still grind the consensus-relevant deliveries (blocks +
        # aggregates)
        total_slots = 4 * P.SLOTS_PER_EPOCH + 6
        recovered_total = 0
        for slot in range(1, total_slots + 1):
            set_clocks(world, slot)
            signed, _ = produce_signed_block(world, ref, slot)
            root = world["cfg"].get_fork_types(slot)[0].hash_tree_root(
                signed["message"]
            )
            publish_block(world, signed, slot)
            # gossip-miss recovery: a node the block never reached
            # walks it back from a peer (the ledger stands in for the
            # peer's by-root server) — drops must degrade latency, not
            # consensus
            source = LedgerSource(world)
            for name in names:
                node = world["nodes"][name]
                if not node.chain.fork_choice.has_block(root.hex()):
                    recovered_total += node.unknown_block_sync.on_unknown_block(
                        source, bytes(root)
                    )
            publish_attestations(world, ref, slot, individuals=False)
        trace.emit(
            "soak",
            slots=total_slots,
            losses_injected=dropped["n"] > 0,
            recoveries_ran=recovered_total > 0,
        )
        assert dropped["n"] > 0, "the lossy link never dropped anything"
        assert recovered_total > 0, (
            "10% loss over 3 epochs should have forced at least one "
            "walk-back recovery"
        )

        # heal; the next slot's block reaches everyone directly
        world["bus"].heal()
        final_slot = total_slots + 1
        set_clocks(world, final_slot)
        signed, _ = produce_signed_block(world, ref, final_slot)
        assert publish_block(world, signed, final_slot) == 3
        publish_attestations(world, ref, final_slot, individuals=False)

        converged = len(set(heads(world).values())) == 1
        fin = {
            name: int(
                node.chain.head_state.finalized_checkpoint["epoch"]
            )
            for name, node in nodes.items()
        }
        trace.emit("healed", converged=converged, finalized=fin)
        assert converged, heads(world)
        # every node finalized through the loss (3 justified epochs in
        # a row finalize at least epoch 1)
        for name, epoch in fin.items():
            assert epoch >= 1, (name, epoch)
        # and the soak never tripped a device breaker or faked a
        # degraded source — loss is a network fault, not a device one
        for name, node in nodes.items():
            assert not any(
                node.slo.status()["degraded_sources"].values()
            ), name
        trace.emit("final", ok=True)
    finally:
        close_devnet(world)
