"""Deterministic chaos-scenario harness (ISSUE 14 tentpole, part 2).

ROADMAP's "scenario diversity" item asks for seeded, replayable fault
scripts over the real node stack.  This module provides the shared
machinery; the `test_*.py` scenarios in this package drive it:

  - **ScenarioTrace** — the record/replay spine.  A scenario emits
    deterministic checkpoint events (verdict summaries, breaker states,
    SLO statuses — never wall-clock values); `save()` writes the trace,
    `assert_replay()` re-runs the scenario against a fresh trace and
    asserts a bit-identical event stream.  A failing scenario therefore
    reproduces from its artifact alone.
  - **FakeClock** — injectable monotonic time for the breaker's backoff
    arithmetic, so re-probe schedules are script-driven, not
    sleep-driven.
  - **ChaosVerifier** — `TpuBlsVerifier` with the crypto replaced by a
    deterministic truth oracle (`chaos_sig`): the device path and the
    host ground-truth path (`_verify_set_host`) read the SAME oracle,
    so degraded-mode verdicts are bit-identical *by construction of the
    real routing code* — begin/finish supervision, breaker gating, and
    host fallback are the production seams, only the pairing is
    stubbed.  Faults inject per stage (`begin` / `finish` / `canary`):
    ``raise`` (generic error), ``backend`` (backend-init-classified
    error), ``hang`` (blocks until the watchdog deadline fires),
    ``truncated`` (malformed verdict plane -> bad_output).
  - **FloodWorld** — ChaosVerifier + DeviceSupervisor +
    BlsVerificationPipeline + SloEngine + FlightRecorder wired exactly
    as node.py wires them (degraded source, trip anomaly ->
    rate-limited bundle), for the fast data-plane scenarios.
  - **build_devnet** — N FullBeaconNodes over one InMemoryGossipBus
    with real crypto (the consensus-level slow scenarios: fork storm,
    partition/heal, crash/restart).
"""

from __future__ import annotations

import hashlib
import json
import threading

import numpy as np

from lodestar_tpu.bls.pipeline import BlsVerificationPipeline
from lodestar_tpu.bls.pubkey_table import PubkeyTable
from lodestar_tpu.bls.signature_set import WireSignatureSet
from lodestar_tpu.bls.supervisor import DeviceSupervisor, check_verdict_plane
from lodestar_tpu.bls.verifier import (
    TpuBlsVerifier,
    VerifyOptions,
    _DeviceJob,
)
from lodestar_tpu.chain.clock import Clock
from lodestar_tpu.observability.flight_recorder import FlightRecorder
from lodestar_tpu.observability.slo import SloEngine
from lodestar_tpu.utils.metrics import BlsPoolMetrics


# ---------------------------------------------------------------------------
# record / replay
# ---------------------------------------------------------------------------


class ScenarioTrace:
    """Ordered checkpoint events + a content digest.  Everything a
    scenario emits must be deterministic under its seed."""

    def __init__(self, seed: int):
        self.seed = int(seed)
        self.events = []

    def emit(self, kind: str, **data) -> None:
        self.events.append({"kind": kind, **data})

    def digest(self) -> str:
        blob = json.dumps(
            {"seed": self.seed, "events": self.events},
            sort_keys=True,
            default=str,
        )
        return hashlib.sha256(blob.encode()).hexdigest()

    def save(self, path) -> str:
        with open(path, "w") as f:
            json.dump(
                {
                    "seed": self.seed,
                    "digest": self.digest(),
                    "events": self.events,
                },
                f,
                indent=1,
                default=str,
            )
        return str(path)

    @staticmethod
    def load(path) -> dict:
        with open(path) as f:
            return json.load(f)


def assert_replay(record_path, scenario_fn) -> None:
    """Re-run `scenario_fn(trace)` against the saved record: the replay
    must reproduce the recorded event stream bit-for-bit."""
    rec = ScenarioTrace.load(record_path)
    fresh = ScenarioTrace(rec["seed"])
    scenario_fn(fresh)
    assert fresh.events == rec["events"], (
        "replay diverged from the recorded scenario"
    )
    assert fresh.digest() == rec["digest"]


# ---------------------------------------------------------------------------
# deterministic time
# ---------------------------------------------------------------------------


class FakeClock:
    """Injectable monotonic clock for the breaker's backoff schedule."""

    def __init__(self, t: float = 0.0):
        self.t = float(t)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# ---------------------------------------------------------------------------
# the oracle verifier
# ---------------------------------------------------------------------------


def chaos_sig(signing_root: bytes, indices) -> bytes:
    """The oracle's notion of THE valid signature for a statement —
    deterministic 96 bytes derived from (root, indices)."""
    h = hashlib.sha256(
        b"chaos-sig" + bytes(signing_root) + bytes(list(indices))
    ).digest()
    return (h * 3)[:96]


class ChaosVerifier(TpuBlsVerifier):
    """TpuBlsVerifier with an oracle replacing the crypto (see module
    docstring).  `fault` maps stage -> mode; clear it to heal."""

    def __init__(self, capacity: int = 64, supervisor=None, metrics=None):
        metrics = metrics or BlsPoolMetrics()
        super().__init__(
            PubkeyTable(capacity=capacity),
            metrics=metrics,
            rng=np.random.default_rng(0),
            supervisor=supervisor,
        )
        self.capacity = capacity
        self.fault = {}
        self.hang_release = threading.Event()
        self.device_jobs = 0  # jobs that finished via the device path
        self.host_sets = 0  # sets resolved via the host fallback seam

    # -- fault injection ---------------------------------------------------

    def _maybe_fault(self, stage: str) -> None:
        mode = self.fault.get(stage)
        if mode is None:
            return
        if mode == "raise":
            raise RuntimeError("injected device fault (chaos)")
        if mode == "backend":
            raise RuntimeError(
                "injected: TPU backend UNAVAILABLE, tunnel down"
            )
        if mode == "hang":
            # blocks until released or the watchdog deadline fires (the
            # supervisor abandons this thread); bounded for safety
            self.hang_release.wait(timeout=30.0)
            raise RuntimeError("injected hang released without recovery")

    def heal(self) -> None:
        self.fault = {}
        self.hang_release.set()

    # -- oracle truth ------------------------------------------------------

    def _truth(self, s) -> bool:
        if isinstance(s, WireSignatureSet):
            return s.signature == chaos_sig(s.signing_root, s.indices)
        return bool(getattr(s, "ok", False))

    # -- the device seams, oracle-stubbed ----------------------------------

    def _begin_job(self, sets, batchable, span=None) -> "_DeviceJob":
        self._maybe_fault("begin")
        wire = bool(sets) and isinstance(sets[0], WireSignatureSet)
        job = _DeviceJob(list(sets), batchable, True, wire)
        job.decodable = np.ones(len(sets), bool)
        return job

    def _finish_job(self, job) -> bool:
        self._maybe_fault("finish")
        plane = np.array([self._truth(s) for s in job.sets], bool)
        if self.fault.get("output") == "truncated":
            plane = plane[: max(len(job.sets) - 1, 0)]
        v = check_verdict_plane(plane, len(job.sets), "chaos-device")
        job.verdicts = v
        self.device_jobs += 1
        good = int(v.sum())
        self.metrics.success_jobs.inc(good)
        self.metrics.invalid_sets.inc(len(job.sets) - good)
        return bool(v.all())

    def _verify_set_host(self, s) -> bool:
        # the degraded-mode seam: same oracle -> bit-identical verdicts
        self.host_sets += 1
        return self._truth(s)

    def _device_canary(self) -> bool:
        def _probe() -> bool:
            self._maybe_fault("canary")
            self._maybe_fault("begin")
            self._maybe_fault("finish")
            return True

        return bool(self.supervisor.run_guarded(_probe, "canary"))


class OkSet:
    """Truth-flagged stand-in set for the RLC bisection planner."""

    __slots__ = ("ok",)

    def __init__(self, ok: bool):
        self.ok = bool(ok)


class RlcOracleVerifier(TpuBlsVerifier):
    """The REAL RLC bisection machinery over an ok-flag oracle — the
    gossip-DoS scenarios' bisection-floor leg (an invalid-signature
    flood must cost O(log N) batch checks per bad set, not a full
    per-set sweep)."""

    def __init__(self, bisect_leaf: int = 16):
        super().__init__(
            PubkeyTable(capacity=2),
            rng=np.random.default_rng(0),
            bisect_leaf=bisect_leaf,
        )
        self.batch_calls = []
        self.leaf_calls = []

    def _dispatch_batch(self, sets, wire):
        self.batch_calls.append(len(sets))
        return all(s.ok for s in sets)

    def _batch_verdict(self, handle):
        return handle

    def _per_set_verdicts(self, sets, wire):
        self.leaf_calls.append(len(sets))
        return np.array([s.ok for s in sets])


# ---------------------------------------------------------------------------
# the fast data-plane world
# ---------------------------------------------------------------------------


class FloodWorld:
    """ChaosVerifier + breaker + pipeline + SLO engine + flight
    recorder, wired the way node.py wires a FullBeaconNode (degraded
    source, trip/recovery anomalies, breaker bundle provider)."""

    def __init__(
        self,
        flightrec_dir,
        seed: int = 0,
        backoff_s: float = 2.0,
        standard_wait_ms: float = 30.0,
        job_deadline_s=None,
    ):
        import random

        self.fake = FakeClock()
        self.metrics = BlsPoolMetrics()
        self.registry = self.metrics.registry
        self.supervisor = DeviceSupervisor(
            registry=self.registry,
            clock=self.fake,
            auto_probe=False,  # scenarios drive poll() deterministically
            backoff_initial_s=backoff_s,
            job_deadline_s=job_deadline_s,
            enabled=True,
            rng=random.Random(seed),
        )
        self.verifier = ChaosVerifier(
            supervisor=self.supervisor, metrics=self.metrics
        )
        # the aggregation stage's breaker interplay is covered at the
        # agg seam directly (tests/test_supervisor.py); the flood
        # scenarios keep preagg off so the oracle's fake signature
        # bytes never hit real G2 decompression
        self.pipeline = BlsVerificationPipeline(
            self.verifier, preagg=False, standard_wait_ms=standard_wait_ms
        )
        self.clock = Clock(genesis_time=0.0)
        self.recorder = FlightRecorder(
            str(flightrec_dir), registry=self.registry
        )
        self.recorder.add_provider("breaker", self.supervisor.status)
        self.slo = SloEngine(
            self.clock,
            registry=self.registry,
            recorder=self.recorder,
            pipeline=self.pipeline,
        )
        # node.py's breaker wiring, reproduced verbatim
        self.slo.add_degraded_source(
            "bls_breaker", self.supervisor.is_open
        )
        self.supervisor.on_trip = lambda info: self.slo.anomaly(
            "bls_breaker_trip", info
        )
        self.supervisor.on_recover = lambda info: self.slo.anomaly(
            "bls_breaker_recovery", info
        )
        self.clock.on_slot(self.slo.on_slot)
        self._slot = 0
        self.futures = []  # (label, expected, future)

    # -- drivers -----------------------------------------------------------

    def tick_slot(self) -> int:
        """Advance the node clock one slot (drains SLO captures)."""
        from lodestar_tpu import params

        self._slot += 1
        self.clock.set_time(self._slot * params.SECONDS_PER_SLOT)
        return self._slot

    def submit_wave(
        self, n: int, wave: int, invalid_every: int = 0, priority=False
    ) -> None:
        """One flood wave: `n` distinct wire sets (every
        `invalid_every`-th carries a wrong signature)."""
        cap = self.verifier.capacity
        for j in range(n):
            vi = (wave * n + j) % cap
            root = b"chaos root %04d/%04d" % (wave, j)
            sig = chaos_sig(root, (vi,))
            expected = True
            if invalid_every and j % invalid_every == 0:
                sig = b"\x99" * 96
                expected = False
            ws = WireSignatureSet.single(vi, root, sig)
            fut = self.pipeline.verify_signature_sets_async(
                [ws],
                VerifyOptions(
                    batchable=True,
                    priority=priority,
                    peer_id="chaos-peer-%d" % (j % 4),
                ),
            )
            self.futures.append((f"w{wave}m{j}", expected, fut))

    def drain(self, timeout: float = 60.0) -> dict:
        """Resolve every outstanding future.  Returns the zero-lost-
        verdicts summary: counts + any mismatches (deterministic)."""
        total = len(self.futures)
        mismatches = []
        ok_true = ok_false = 0
        for label, expected, fut in self.futures:
            got = fut.result(timeout=timeout)
            if got != expected:
                mismatches.append(label)
            elif expected:
                ok_true += 1
            else:
                ok_false += 1
        self.futures = []
        return {
            "submitted": total,
            "valid_confirmed": ok_true,
            "invalid_rejected": ok_false,
            "mismatches": mismatches,
        }

    def close(self) -> None:
        self.pipeline.close()


# ---------------------------------------------------------------------------
# the consensus-level world (slow scenarios)
# ---------------------------------------------------------------------------


def build_devnet(
    n_nodes: int,
    n_keys: int = 8,
    db_paths=None,
    flightrec_dirs=None,
    genesis_time: int = 10,
):
    """N FullBeaconNodes with real crypto over one InMemoryGossipBus —
    the consensus-level chaos world (fork storms, partitions,
    crash/restart).  Returns a dict world."""
    from lodestar_tpu.bls.single_thread import CpuBlsVerifier
    from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
    from lodestar_tpu.crypto import bls as B
    from lodestar_tpu.crypto import curves as C
    from lodestar_tpu.network.gossip import InMemoryGossipBus
    from lodestar_tpu.node import FullBeaconNode, NodeOptions
    from lodestar_tpu.params import ForkName
    from lodestar_tpu.state_transition import create_genesis_state
    from lodestar_tpu.validator import ValidatorStore

    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG,
        fork_epochs={ForkName.altair: 0},
        genesis_time=genesis_time,
    )
    sks = [B.keygen(b"chaos-%d" % i) for i in range(n_keys)]
    pk_points = [B.sk_to_pk(sk) for sk in sks]
    pks = [C.g1_compress(p) for p in pk_points]
    genesis = create_genesis_state(cfg, pks, genesis_time=genesis_time)
    bus = InMemoryGossipBus()
    digest = cfg.fork_digest(0)

    nodes = {}
    names = [f"node-{i}" for i in range(n_nodes)]
    for i, name in enumerate(names):
        nodes[name] = FullBeaconNode.init(
            cfg,
            genesis,
            NodeOptions(
                serve_api=False,
                verifier=CpuBlsVerifier(pubkeys=pk_points),
                gossip_bus=bus,
                node_id=name,
                active_validator_count_hint=n_keys,
                subscribe_all_subnets=True,
                db_path=(db_paths or {}).get(name),
                flightrec_dir=(flightrec_dirs or {}).get(name),
            ),
        )
    owners = {i: names[i % n_nodes] for i in range(n_keys)}
    stores = {
        name: ValidatorStore(
            cfg, {i: sks[i] for i in range(n_keys) if owners[i] == name}
        )
        for name in names
    }
    return {
        "cfg": cfg,
        "genesis": genesis,
        "bus": bus,
        "digest": digest,
        "nodes": nodes,
        "names": names,
        "owners": owners,
        "stores": stores,
        "sks": sks,
        "pk_points": pk_points,
        "genesis_time": genesis_time,
        "block_ledger": {},  # slot -> signed block (the publish log)
    }


def set_clocks(world, slot: int, frac: float = 0.0) -> None:
    from lodestar_tpu import params

    t = world["genesis_time"] + (slot + frac) * params.SECONDS_PER_SLOT
    for n in world["nodes"].values():
        n.clock.set_time(t)


def produce_signed_block(world, ref_chain, slot: int, graffiti=None):
    """Produce + sign one block for `slot` on `ref_chain`'s head."""
    from lodestar_tpu.state_transition.accessors import (
        get_beacon_proposer_index,
    )
    from lodestar_tpu.state_transition.slot import process_slots

    st = ref_chain.head_state.clone()
    if st.slot < slot:
        process_slots(st, slot)
    proposer = int(get_beacon_proposer_index(st))
    owner = world["stores"][world["owners"][proposer]]
    kwargs = {}
    if graffiti is not None:
        kwargs["graffiti"] = graffiti
    block = ref_chain.produce_block(
        slot, owner.sign_randao(proposer, slot), **kwargs
    )
    return (
        {"message": block, "signature": owner.sign_block(proposer, block)},
        proposer,
    )


def publish_block(
    world, signed, slot: int, from_node="proposer", ledger: bool = True
) -> int:
    from lodestar_tpu.network.gossip import (
        GossipTopicName,
        encode_message,
        topic_string,
    )

    if ledger:
        world["block_ledger"][slot] = signed
    return world["bus"].publish(
        from_node,
        topic_string(world["digest"], GossipTopicName.beacon_block),
        encode_message(
            world["cfg"].get_fork_types(slot)[1].serialize(signed)
        ),
    )


def publish_attestations(
    world,
    ref_chain,
    slot: int,
    quiet=(),
    aggregates: bool = True,
    individuals: bool = True,
) -> int:
    """Every committee member (minus `quiet`) attests over gossip; the
    first member aggregates (block production packs the aggregated
    pool, so justification needs this leg).  Publisher ids are the
    OWNING node names, so bus partitions apply to validator traffic.
    `individuals=False` publishes only the aggregates — the consensus-
    relevant leg — which long soak scenarios use to keep N-epoch
    real-crypto runs inside the slow-tier budget (each node otherwise
    pays one pairing per member per slot for subnet copies that never
    feed the pools)."""
    from lodestar_tpu import types as T
    from lodestar_tpu.crypto import bls as B
    from lodestar_tpu.crypto import curves as C
    from lodestar_tpu.network.gossip import (
        GossipTopicName,
        encode_message,
        topic_string,
    )
    from lodestar_tpu.network.subnets import compute_subnet_for_attestation
    from lodestar_tpu.state_transition.accessors import (
        get_beacon_committee,
        get_committee_count_per_slot,
    )
    from lodestar_tpu.state_transition.util import compute_epoch_at_slot

    epoch = compute_epoch_at_slot(slot)
    st = ref_chain.head_state
    committees = int(get_committee_count_per_slot(st, epoch))
    published = 0
    for ci in range(committees):
        committee = get_beacon_committee(st, slot, ci)
        if len(committee) == 0:
            continue
        data = ref_chain.produce_attestation_data(ci, slot)
        subnet = compute_subnet_for_attestation(committees, slot, ci)
        member_sigs = {}
        for pos, v in enumerate(committee):
            v = int(v)
            if v in quiet:
                continue
            sig = world["stores"][world["owners"][v]].sign_attestation(
                v, data
            )
            member_sigs[pos] = sig
            if not individuals:
                continue
            att = {
                "aggregation_bits": [p == pos for p in range(len(committee))],
                "data": data,
                "signature": sig,
            }
            world["bus"].publish(
                f"{world['owners'][v]}:val-{v}",
                topic_string(
                    world["digest"],
                    GossipTopicName.beacon_attestation,
                    subnet=subnet,
                ),
                encode_message(T.Attestation.serialize(att)),
            )
            published += 1
        if not aggregates or not member_sigs:
            continue
        aggregator = int(committee[0])
        if aggregator in quiet:
            continue
        agg_sig = C.g2_compress(
            B.aggregate_signatures(
                [C.g2_decompress(s) for s in member_sigs.values()]
            )
        )
        agg_store = world["stores"][world["owners"][aggregator]]
        message = {
            "aggregator_index": aggregator,
            "aggregate": {
                "aggregation_bits": [
                    p in member_sigs for p in range(len(committee))
                ],
                "data": data,
                "signature": agg_sig,
            },
            "selection_proof": agg_store.sign_selection_proof(
                aggregator, slot
            ),
        }
        signed_agg = {
            "message": message,
            "signature": agg_store.sign_aggregate_and_proof(
                aggregator, message
            ),
        }
        world["bus"].publish(
            f"{world['owners'][aggregator]}:agg-{aggregator}",
            topic_string(
                world["digest"], GossipTopicName.beacon_aggregate_and_proof
            ),
            encode_message(T.SignedAggregateAndProof.serialize(signed_agg)),
        )
    return published


class LedgerSource:
    """BlockSource over the world's publish ledger (+ optionally a
    restarted node's own re-opened db, the crash/restart scenario's
    resume-from-db leg).  The harness's stand-in for a peer's req/resp
    server — the wire layer itself is covered by test_reqresp."""

    def __init__(self, world, db=None):
        self.world = world
        self.db = db
        self._roots = {}
        for slot, signed in world["block_ledger"].items():
            root = world["cfg"].get_fork_types(slot)[0].hash_tree_root(
                signed["message"]
            )
            self._roots[bytes(root)] = signed

    def get_blocks_by_range(self, start_slot: int, count: int):
        out = []
        for slot in sorted(self.world["block_ledger"]):
            if start_slot <= slot < start_slot + count:
                signed = None
                if self.db is not None:
                    root = self.world["cfg"].get_fork_types(slot)[
                        0
                    ].hash_tree_root(
                        self.world["block_ledger"][slot]["message"]
                    )
                    signed = self.db.get_block_anywhere(bytes(root))
                out.append(
                    signed or self.world["block_ledger"][slot]
                )
        return out

    def get_blocks_by_root(self, roots):
        out = []
        for r in roots:
            signed = self._roots.get(bytes(r))
            if signed is not None:
                out.append(signed)
        return out


def close_devnet(world) -> None:
    for n in world["nodes"].values():
        n.close()


# ---------------------------------------------------------------------------
# the state-plane world (memory-squeeze scenarios, ISSUE 15)
# ---------------------------------------------------------------------------


class _StubBlsService:
    """Always-true signature service: the squeeze scenarios stress the
    STATE plane (regen, caches, the governor's ladder); with a service
    injected the chain skips in-STF signature checks — the exact
    contract regen replay already runs under."""

    def verify_signature_sets(self, sets):
        return True

    def close(self) -> None:
        pass


class StateWorld:
    """BeaconChain + StateMemoryGovernor + SLO engine + flight
    recorder, wired the way node.py wires them (degraded source,
    pressure anomaly -> rate-limited bundle, governor on the slot
    tick) — the state-plane analog of FloodWorld.  Fork churn is
    scripted: each slot imports a head block plus (optionally) a
    competing side-fork block on the previous head, which keeps extra
    branch states resident exactly like a real churn burst."""

    GRAFFITI_FORK = b"\x42" * 32

    def __init__(
        self,
        flightrec_dir,
        seed: int = 0,
        n_keys: int = 16,
        budget_bytes=None,
        db_path=None,
    ):
        from lodestar_tpu.chain.chain import BeaconChain
        from lodestar_tpu.config import (
            MAINNET_CHAIN_CONFIG,
            create_chain_config,
        )
        from lodestar_tpu.crypto import bls as B
        from lodestar_tpu.crypto import curves as C
        from lodestar_tpu.db import BeaconDb
        from lodestar_tpu.observability.timeseries import (
            MetricsSampler,
            TimeSeriesRing,
        )
        from lodestar_tpu.params import ForkName
        from lodestar_tpu.state_transition import create_genesis_state
        from lodestar_tpu.utils.metrics import Registry

        self.seed = int(seed)
        self.cfg = create_chain_config(
            MAINNET_CHAIN_CONFIG,
            fork_epochs={ForkName.altair: 0},
            genesis_time=0,
        )
        # real pubkey points (genesis decompresses them for the sync
        # committee); every SIGNATURE stays stubbed — the scenarios
        # stress the state plane, not the pairing
        pks = [
            C.g1_compress(B.sk_to_pk(B.keygen(b"squeeze-%d" % i)))
            for i in range(n_keys)
        ]
        genesis = create_genesis_state(self.cfg, pks, genesis_time=0)
        self.registry = Registry()
        self.db = BeaconDb(db_path)
        self.chain = BeaconChain(
            self.cfg,
            genesis,
            db=self.db,
            bls_verifier=_StubBlsService(),
            state_budget_bytes=budget_bytes,
            registry=self.registry,
        )
        self.governor = self.chain.memory_governor
        self.clock = Clock(genesis_time=0.0)
        self.recorder = FlightRecorder(
            str(flightrec_dir), registry=self.registry
        )
        ring = TimeSeriesRing()
        sampler = MetricsSampler(ring)
        if self.governor is not None:
            self.recorder.add_provider("memory", self.governor.status)
            sampler.add_gauge(
                "state_resident_bytes",
                lambda: float(self.governor.ledger.resident_bytes),
            )
        self.slo = SloEngine(
            self.clock, registry=self.registry, recorder=self.recorder,
            sampler=sampler,
        )
        # node.py's governor wiring, reproduced verbatim
        if self.governor is not None:
            gov = self.governor
            self.slo.add_degraded_source(
                "state_memory", lambda: gov.pressure_active
            )
            gov.on_pressure = lambda info: self.slo.anomaly(
                "state_memory_pressure", info
            )
            self.clock.on_slot(gov.on_slot)
        self.clock.on_slot(self.slo.on_slot)
        self.chain.on_import_complete = self.slo.on_block_imported
        self._slot = 0
        self._prev_head = self.chain.head_root_hex
        # block_root_hex -> expected post-state root hex: the
        # never-evicted twin ledger every regen result checks against
        self.expected_roots = {
            self.chain.anchor_root_hex: genesis.hash_tree_root().hex()
        }

    # -- drivers -----------------------------------------------------------

    def tick_slot(self) -> int:
        from lodestar_tpu import params

        self._slot += 1
        self.clock.set_time(self._slot * params.SECONDS_PER_SLOT)
        return self._slot

    def _attestations_for(self, parent_root_hex: str):
        """Full-participation attestations voting the parent block as
        head (fake signatures — the stub service accepts, the STF skips
        sig checks): enough FFG weight to justify and finalize, so the
        scenarios can exercise the finalization sweeps."""
        from lodestar_tpu import params as _p
        from lodestar_tpu.state_transition.accessors import (
            get_beacon_committee,
            get_block_root_at_slot,
            get_committee_count_per_slot,
        )
        from lodestar_tpu.state_transition.util import (
            compute_epoch_at_slot,
        )

        post = self.chain.regen._get_post_state(parent_root_hex)
        slot = int(post.slot)
        if slot == 0:
            return []
        head_root = bytes.fromhex(parent_root_hex)
        epoch = compute_epoch_at_slot(slot)
        start = epoch * _p.ACTIVE_PRESET.SLOTS_PER_EPOCH
        target_root = (
            head_root
            if start >= slot
            else get_block_root_at_slot(post, start)
        )
        atts = []
        for index in range(get_committee_count_per_slot(post, epoch)):
            committee = get_beacon_committee(post, slot, index)
            atts.append(
                {
                    "aggregation_bits": [True] * len(committee),
                    "data": {
                        "slot": slot,
                        "index": index,
                        "beacon_block_root": head_root,
                        "source": dict(post.current_justified_checkpoint),
                        "target": {"epoch": epoch, "root": target_root},
                    },
                    "signature": bytes([0xC0]) + b"\x00" * 95,
                }
            )
        return atts

    def _produce_on(
        self, parent_root_hex: str, slot: int, graffiti, attest=False
    ):
        import hashlib as _hl

        from lodestar_tpu.chain.produce_block import produce_block

        parent_state = self.chain.regen._get_post_state(parent_root_hex)
        randao = (
            _hl.sha256(b"squeeze randao %d" % slot).digest() * 3
        )
        block, _post = produce_block(
            parent_state,
            slot,
            randao,
            graffiti=graffiti,
            attestations=(
                self._attestations_for(parent_root_hex) if attest else None
            ),
        )
        return {"message": block, "signature": b"\x00" * 96}

    def churn_slot(
        self, slot: int, fork: bool = True, attest: bool = False
    ) -> dict:
        """Import one head block (+ one side-fork block on the previous
        head when `fork`).  Returns deterministic import stats."""
        prev_head = self.chain.head_root_hex
        signed = self._produce_on(prev_head, slot, b"\x00" * 32, attest)
        root = self.chain.process_block(signed)
        self.expected_roots[root.hex()] = (
            signed["message"]["state_root"].hex()
        )
        forked = False
        if fork and self._prev_head != prev_head:
            signed2 = self._produce_on(
                self._prev_head, slot, self.GRAFFITI_FORK
            )
            root2 = self.chain.process_block(signed2)
            self.expected_roots[root2.hex()] = (
                signed2["message"]["state_root"].hex()
            )
            forked = True
        self._prev_head = prev_head
        return {"slot": slot, "forked": forked}

    def warm_checkpoint(self, epoch: int) -> None:
        """Populate the checkpoint cache on the head chain (the entry
        attestation validation would create)."""
        self.chain.regen.get_checkpoint_state(
            {"epoch": epoch, "root": self.chain.get_head_root()}
        )

    def verify_regen(self, block_root_hex: str) -> bool:
        """Regen the block's post-state (possibly rehydrating a spill
        or replaying from db) and check it against the never-evicted
        twin's recorded root — bit-identical or bust."""
        st = self.chain.regen._get_post_state(block_root_hex)
        return (
            st.hash_tree_root().hex() == self.expected_roots[block_root_hex]
        )

    def close(self) -> None:
        self.db.close()


def heads(world) -> dict:
    return {
        name: n.chain.head_root_hex for name, n in world["nodes"].items()
    }
