"""Chaos scenario: partition/heal of the InMemoryGossipBus.

Fast leg: the bus's fault-injection semantics themselves (link filter,
partition groups with owner-aliased publishers, heal, crash-drop).
Slow leg: a three-node devnet partitioned mid-run — the minority node
diverges, the heal + unknown-block walk-back reconverges every head,
and gossip flows to everyone again afterward.
"""

import pytest

from lodestar_tpu.network.gossip import InMemoryGossipBus

from chaos.harness import (
    LedgerSource,
    ScenarioTrace,
    build_devnet,
    close_devnet,
    heads,
    produce_signed_block,
    publish_attestations,
    publish_block,
    set_clocks,
)


@pytest.mark.smoke
def test_bus_partition_heal_and_crash_semantics():
    bus = InMemoryGossipBus()
    got = {n: [] for n in ("a", "b", "c")}
    for n in got:
        bus.subscribe(n, "t", lambda _t, d, n=n: got[n].append(d))

    assert bus.publish("a", "t", b"m1") == 2  # b and c

    bus.set_partitions([["a", "b"], ["c"]])
    assert bus.publish("a", "t", b"m2") == 1  # only b
    assert bus.partitioned == 1
    # owner-aliased publishers partition with their node: "c:val-7"
    # resolves to c's group, so only c receives
    assert bus.publish("c:val-7", "t", b"m3") == 1
    assert got["c"][-1] == b"m3"
    assert all(b"m3" not in msgs for n, msgs in got.items() if n != "c")
    # unknown publishers keep full connectivity
    assert bus.publish("outsider", "t", b"m4") == 3

    bus.heal()
    assert bus.publish("a", "t", b"m5") == 2
    assert got["c"][-1] == b"m5"

    # crash: a dropped node receives nothing; a fresh subscribe rejoins
    # with an empty seen cache (restart semantics)
    bus.drop_node("c")
    assert bus.publish("a", "t", b"m6") == 1
    rejoined = []
    bus.subscribe("c", "t", lambda _t, d: rejoined.append(d))
    assert bus.publish("a", "t", b"m6") == 1  # a+b saw m6 already; c fresh
    assert rejoined == [b"m6"]


@pytest.mark.slow
def test_partition_heal_full_nodes_reconverge(tmp_path):
    """Three nodes; the minority node is cut off for two slots of real
    block traffic, diverges, then heals and reconverges through the
    unknown-block walk-back — and the next slot's gossip reaches
    everyone.  Seeded + event-traced for replayability."""
    trace = ScenarioTrace(77)
    world = build_devnet(3)
    names, nodes = world["names"], world["nodes"]
    ref = nodes[names[0]].chain
    try:
        for slot in (1, 2):
            set_clocks(world, slot)
            signed, _ = produce_signed_block(world, ref, slot)
            assert publish_block(world, signed, slot) == 3
            publish_attestations(world, ref, slot)
        trace.emit("healthy", converged=len(set(heads(world).values())) == 1)

        # partition: node-2 (and its validators) alone
        world["bus"].set_partitions(
            [[names[0], names[1], "proposer"], [names[2]]]
        )
        for slot in (3, 4):
            set_clocks(world, slot)
            signed, _ = produce_signed_block(world, ref, slot)
            publish_block(world, signed, slot)
            publish_attestations(world, ref, slot)
        h = heads(world)
        trace.emit(
            "partitioned",
            minority_diverged=h[names[2]] != h[names[0]],
            suppressed=world["bus"].partitioned > 0,
        )
        assert h[names[2]] != h[names[0]]
        assert world["bus"].partitioned > 0

        # heal + catch up: the minority node resolves the unknown head
        # by walking back to its last known ancestor
        world["bus"].heal()
        source = LedgerSource(world)
        head_root = bytes.fromhex(nodes[names[0]].chain.head_root_hex)
        n = nodes[names[2]].unknown_block_sync.on_unknown_block(
            source, head_root
        )
        trace.emit(
            "healed",
            blocks_recovered=n,
            converged=len(set(heads(world).values())) == 1,
        )
        assert n == 2
        assert len(set(heads(world).values())) == 1

        # the mesh is whole again: the next block reaches every node
        set_clocks(world, 5)
        signed, _ = produce_signed_block(world, ref, 5)
        assert publish_block(world, signed, 5) == 3
        publish_attestations(world, ref, 5)
        assert len(set(heads(world).values())) == 1
        # SLO coverage of the fault: the minority node's catch-up
        # imports landed past their slots' deadlines, so ITS breach
        # counters recorded the partition (and its health is
        # breach-degraded for the window); the majority stayed clean.
        from lodestar_tpu.observability.slo import OBJ_IMPORT_BOUNDARY

        minority = nodes[names[2]].slo
        assert minority.breach_count(OBJ_IMPORT_BOUNDARY) >= 1
        assert minority.status()["status"] == "degraded"
        # no device fault was involved: every degraded *source* is clear
        assert not any(
            minority.status()["degraded_sources"].values()
        )
        for name in names[:2]:
            assert nodes[name].slo.status()["status"] == "ok", name
        trace.emit("final", converged=True)
    finally:
        close_devnet(world)
