"""JAX jacobian point ops (G1/G2) vs the pure-Python ground truth."""

import random

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lodestar_tpu.crypto import curves as C
from lodestar_tpu.crypto import fields as GT
from lodestar_tpu.ops import curve as K

rng = random.Random(0x61)


def rand_g1(n):
    return [C.scalar_mul(C.FP_OPS, C.G1_GEN, rng.randrange(1, GT.R)) for _ in range(n)]


def rand_g2(n):
    return [C.scalar_mul(C.FP2_OPS, C.G2_GEN, rng.randrange(1, GT.R)) for _ in range(n)]


CASES = [
    (K.FP_OPS, C.FP_OPS, rand_g1, C.G1_GEN),
    (K.FP2_OPS, C.FP2_OPS, rand_g2, C.G2_GEN),
]


@pytest.mark.parametrize("fo,gt_ops,rand_pts,gen", CASES, ids=["g1", "g2"])
def test_add_dbl_exceptional(fo, gt_ops, rand_pts, gen):
    n = 6
    ps = rand_pts(n - 2) + [gen, None]
    qs = rand_pts(n - 4) + [None, ps[1], C.affine_neg(gt_ops, ps[2]), gen]
    a = K.batch_points(fo, ps)
    b = K.batch_points(fo, qs)

    @jax.jit
    def run(a, b):
        return (
            K.jac_add(fo, a, b),
            K.jac_dbl(fo, a),
            K.is_on_curve(fo, a),
            K.jac_eq(fo, a, b),
            K.jac_eq(fo, a, a),
        )

    add, dbl, onc, eqab, eqaa = run(a, b)
    assert K.decode_points(fo, add) == [
        C.affine_add(gt_ops, p, q) for p, q in zip(ps, qs)
    ]
    assert K.decode_points(fo, dbl) == [C.affine_dbl(gt_ops, p) for p in ps]
    assert all(np.asarray(onc))
    assert list(np.asarray(eqab)) == [
        C.affine_eq(gt_ops, p, q) for p, q in zip(ps, qs)
    ]
    assert all(np.asarray(eqaa))


@pytest.mark.parametrize("fo,gt_ops,rand_pts,gen", CASES, ids=["g1", "g2"])
def test_scalar_mul(fo, gt_ops, rand_pts, gen):
    n = 4
    ps = rand_pts(n)
    ks = [rng.randrange(1 << 64) | 1 for _ in range(n)]
    a = K.batch_points(fo, ps)
    bits = jnp.asarray(K.scalars_to_bits(ks, 64))
    kstat = 0xD201000000010000

    @jax.jit
    def run(a, bits):
        return (
            K.scalar_mul_bits(fo, a, bits),
            K.scalar_mul_static(fo, a, kstat),
        )

    dyn, stat = run(a, bits)
    assert K.decode_points(fo, dyn) == [
        C.scalar_mul(gt_ops, p, k) for p, k in zip(ps, ks)
    ]
    assert K.decode_points(fo, stat) == [
        C.scalar_mul(gt_ops, p, kstat) for p in ps
    ]


@pytest.mark.parametrize("fo,gt_ops,rand_pts,gen", CASES, ids=["g1", "g2"])
def test_sum_points_and_affine(fo, gt_ops, rand_pts, gen):
    n = 7  # odd, exercises the padding path
    ps = rand_pts(n - 1) + [None]
    valid_mask = np.array([True] * (n - 2) + [False, True])
    a = K.batch_points(fo, ps)

    @jax.jit
    def run(a, valid):
        return (
            K.sum_points(fo, a),
            K.sum_points(fo, a, valid=valid),
            K.to_affine(fo, a),
        )

    total, masked, (aff, inf) = run(a, jnp.asarray(valid_mask))
    assert K.decode_point(fo, total) == C.multi_add(gt_ops, ps)
    want_masked = C.multi_add(
        gt_ops, [p for p, v in zip(ps, valid_mask) if v]
    )
    assert K.decode_point(fo, masked) == want_masked
    assert list(np.asarray(inf)) == [p is None for p in ps]
    for i, p in enumerate(ps):
        if p is None:
            continue
        got = (
            fo.decode(jax.tree_util.tree_map(lambda x: np.asarray(x)[i], aff[0])),
            fo.decode(jax.tree_util.tree_map(lambda x: np.asarray(x)[i], aff[1])),
        )
        assert got == p


def test_subgroup_check_g2():
    # in-subgroup points pass; an on-curve point outside G2 fails
    ps = rand_g2(2)
    k = 1
    while True:
        k += 1
        x = (k, 1)
        y2 = GT.fp2_add(GT.fp2_mul(GT.fp2_sqr(x), x), C.FP2_OPS.b_coeff)
        y = GT.fp2_sqrt(y2)
        if y is not None:
            probe = (x, y)
            if not C.g2_subgroup_check(probe):
                break
    pts = K.batch_points(K.FP2_OPS, ps + [probe])
    got = jax.jit(lambda p: K.in_subgroup(K.FP2_OPS, p))(pts)
    assert list(np.asarray(got)) == [True, True, False]
