"""KZG polynomial commitments (the c-kzg replacement).

Reference behaviors: packages/beacon-node/src/util/kzg.ts (the c-kzg
surface the node consumes) and the deneb polynomial-commitments spec
(blob_to_kzg_commitment / compute_kzg_proof / verify_blob_kzg_proof).
Runs at a small domain width — the math is width-independent and the
dev setup's known tau lets tests cross-check commitments white-box.
"""

import hashlib

import pytest

from lodestar_tpu.crypto import curves as C
from lodestar_tpu.crypto import kzg as K

pytestmark = pytest.mark.smoke

WIDTH = 8


@pytest.fixture(scope="module")
def setup():
    return K.insecure_dev_setup(WIDTH)


def _blob(seed: bytes) -> bytes:
    evals = [
        int.from_bytes(hashlib.sha256(seed + bytes([i])).digest(), "big")
        % K.R
        for i in range(WIDTH)
    ]
    return K.polynomial_to_blob(evals)


def test_roots_of_unity_and_brp():
    roots = K.compute_roots_of_unity(WIDTH)
    assert len(set(roots)) == WIDTH
    assert all(pow(w, WIDTH, K.R) == 1 for w in roots)
    brp = K.bit_reversal_permutation(list(range(8)))
    assert brp == [0, 4, 2, 6, 1, 5, 3, 7]


def test_commitment_matches_known_tau(setup):
    """White-box: MSM over the Lagrange setup must equal [p(tau)]G1."""
    blob = _blob(b"wb")
    evals = K.blob_to_polynomial(blob, WIDTH)
    commitment = K.blob_to_kzg_commitment(blob, setup)
    tau = (
        int.from_bytes(hashlib.sha256(b"lodestar-tpu-dev-kzg").digest(), "big")
        % K.R
    )
    y = K.evaluate_polynomial_in_evaluation_form(evals, tau, setup)
    direct = C.scalar_mul(C.FP_OPS, C.G1_GEN, y)
    assert C.g1_compress(direct) == commitment


def test_kzg_proof_roundtrip_off_domain(setup):
    blob = _blob(b"p1")
    commitment = K.blob_to_kzg_commitment(blob, setup)
    z = (12345).to_bytes(32, "big")
    proof, y = K.compute_kzg_proof(blob, z, setup)
    assert K.verify_kzg_proof(commitment, z, y, proof, setup)
    # wrong y rejects
    bad_y = ((int.from_bytes(y, "big") + 1) % K.R).to_bytes(32, "big")
    assert not K.verify_kzg_proof(commitment, z, bad_y, proof, setup)
    # wrong z rejects
    z2 = (54321).to_bytes(32, "big")
    assert not K.verify_kzg_proof(commitment, z2, y, proof, setup)


def test_kzg_proof_at_domain_point(setup):
    """z ON the evaluation domain exercises the quotient's L'Hopital
    branch; y must equal the blob's stored evaluation."""
    blob = _blob(b"p2")
    evals = K.blob_to_polynomial(blob, WIDTH)
    commitment = K.blob_to_kzg_commitment(blob, setup)
    k = 3
    z = int(setup.roots_brp[k]).to_bytes(32, "big")
    proof, y = K.compute_kzg_proof(blob, z, setup)
    assert int.from_bytes(y, "big") == evals[k]
    assert K.verify_kzg_proof(commitment, z, y, proof, setup)


def test_blob_proof_accept_and_reject(setup):
    blob = _blob(b"p3")
    commitment = K.blob_to_kzg_commitment(blob, setup)
    proof = K.compute_blob_kzg_proof(blob, commitment, setup)
    assert K.verify_blob_kzg_proof(blob, commitment, proof, setup)
    # tampered blob fails
    tampered = bytearray(blob)
    tampered[-1] ^= 1
    assert not K.verify_blob_kzg_proof(bytes(tampered), commitment, proof, setup)
    # commitment of a different blob fails
    other = K.blob_to_kzg_commitment(_blob(b"p4"), setup)
    assert not K.verify_blob_kzg_proof(blob, other, proof, setup)
    # garbage proof bytes fail (not a curve point)
    assert not K.verify_blob_kzg_proof(blob, commitment, b"\x01" * 48, setup)


def test_blob_batch_verify(setup):
    blobs = [_blob(b"b%d" % i) for i in range(3)]
    commitments = [K.blob_to_kzg_commitment(b, setup) for b in blobs]
    proofs = [
        K.compute_blob_kzg_proof(b, c, setup)
        for b, c in zip(blobs, commitments)
    ]
    assert K.verify_blob_kzg_proof_batch(blobs, commitments, proofs, setup)
    # one bad proof poisons the batch
    proofs_bad = list(proofs)
    proofs_bad[1] = proofs[0]
    assert not K.verify_blob_kzg_proof_batch(
        blobs, commitments, proofs_bad, setup
    )
    # length mismatch rejects
    assert not K.verify_blob_kzg_proof_batch(blobs[:2], commitments, proofs, setup)


def test_constant_polynomial_infinity_proof(setup):
    """A constant polynomial's quotient is zero — the proof is the
    point at infinity and must still verify."""
    evals = [7] * WIDTH
    blob = K.polynomial_to_blob(evals)
    commitment = K.blob_to_kzg_commitment(blob, setup)
    z = (99).to_bytes(32, "big")
    proof, y = K.compute_kzg_proof(blob, z, setup)
    assert int.from_bytes(y, "big") == 7
    assert proof == bytes([0xC0]) + b"\x00" * 47
    assert K.verify_kzg_proof(commitment, z, y, proof, setup)


def test_non_canonical_blob_rejected(setup):
    bad = K.polynomial_to_blob([K.R] + [0] * (WIDTH - 1))  # == modulus
    with pytest.raises(K.KzgError, match="canonical"):
        K.blob_to_polynomial(bad, WIDTH)
    assert not K.verify_blob_kzg_proof(
        bad, bytes([0xC0]) + b"\x00" * 47, bytes([0xC0]) + b"\x00" * 47, setup
    )
