"""Device ingest kernels vs the host oracle.

canonical signs, Fp2 sqrt, G2 decompression, and SSWU hash-to-curve must
match crypto/{fields,curves,hash_to_curve}.py bit-for-bit — the host
path is the consensus-critical reference (reference ingest behavior:
blst uncompress + hash inside packages/beacon-node/src/chain/bls/).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lodestar_tpu.crypto import bls as B
from lodestar_tpu.crypto import curves as GC
from lodestar_tpu.crypto import fields as GT
from lodestar_tpu.crypto import hash_to_curve as HC
from lodestar_tpu.kernels import canonical as CN
from lodestar_tpu.kernels import ingest as IN
from lodestar_tpu.kernels import layout as LY
from lodestar_tpu.kernels import sqrt as SQ

pytestmark = pytest.mark.slow

P = GT.P


def enc_mont(vals):
    return jnp.asarray(LY.encode_batch(vals))


def enc_plain(vals):
    return jnp.asarray(LY.encode_plain_batch(vals))


def dec(arr):
    return LY.decode_batch(np.asarray(arr))


def test_canonical_signs_match_host():
    rng = np.random.default_rng(1)
    vals = [0, 1, 2, P - 1, (P - 1) // 2, (P + 1) // 2, P - 2] + [
        int(rng.integers(0, 1 << 62)) ** 3 % P for _ in range(9)
    ]
    x = enc_mont(vals)
    sgn = jax.jit(CN.fp_sgn)(x)
    sgn0 = jax.jit(CN.fp_sgn0)(x)
    for i, v in enumerate(vals):
        assert bool(sgn[i]) == (v > P - v if v else False), (i, v)
        assert bool(sgn0[i]) == (v % 2 == 1), (i, v)

    pairs = [(0, 0), (0, 1), (1, 0), (P - 1, 0), (0, P - 1), (3, P - 1)] + [
        (int(rng.integers(0, 1 << 62)) ** 3 % P,
         int(rng.integers(0, 1 << 62)) ** 3 % P)
        for _ in range(6)
    ]
    x0 = enc_mont([p[0] for p in pairs])
    x1 = enc_mont([p[1] for p in pairs])
    s2 = jax.jit(lambda a, b: CN.fp2_sgn((a, b)))(x0, x1)
    s20 = jax.jit(lambda a, b: CN.fp2_sgn0((a, b)))(x0, x1)
    for i, v in enumerate(pairs):
        assert bool(s2[i]) == bool(GT.fp2_sgn(v)), (i, v)
        exp0 = (v[0] % 2) | ((v[0] == 0) and (v[1] % 2))
        assert bool(s20[i]) == bool(exp0), (i, v)


def test_fp2_sqrt_matches_host():
    rng = np.random.default_rng(2)
    squares = []
    for i in range(6):
        a = (int(rng.integers(1, 1 << 62)), int(rng.integers(0, 1 << 62)))
        squares.append(GT.fp2_sqr(a))
    # a1 == 0 cases: real square, real non-residue (sqrt purely imaginary)
    squares.append((4, 0))
    nonres = None
    v = 2
    while nonres is None:
        if GT.fp_sqrt(v) is None:
            nonres = (v, 0)
        v += 1
    cases = squares + [nonres, (5, 7)]  # last may or may not be square
    x0 = enc_mont([c[0] for c in cases])
    x1 = enc_mont([c[1] for c in cases])
    root, ok = jax.jit(lambda a, b: SQ.fp2_sqrt((a, b)))(x0, x1)
    r0, r1 = dec(root[0]), dec(root[1])
    for i, c in enumerate(cases):
        host = GT.fp2_sqrt(c)
        if host is None:
            assert not bool(ok[i]), (i, c)
        else:
            assert bool(ok[i]), (i, c)
            got = (r0[i], r1[i])
            assert GT.fp2_eq(GT.fp2_sqr(got), c), (i, c)


def test_g2_decompress_matches_host():
    sks = [B.keygen(b"ing-%d" % i) for i in range(8)]
    sigs = [B.sign(sk, b"m%d" % i) for i, sk in enumerate(sks)]
    comp = [GC.g2_compress(s) for s in sigs]
    n = 128
    xs, signs, infs, hosts = [], [], [], []
    i = 0
    while len(xs) < n:
        c = bytearray(comp[i % len(comp)])
        if i % 5 == 4:
            c[5] ^= 0x40  # corrupt x -> usually off-curve
        i += 1
        x1 = int.from_bytes(bytes([c[0] & 0x1F]) + bytes(c[1:48]), "big")
        x0 = int.from_bytes(bytes(c[48:]), "big")
        if x0 >= P or x1 >= P:
            # out-of-range x is rejected by the HOST byte-range check
            # before limbs ever reach the device; not a device case
            continue
        xs.append((x0, x1))
        signs.append(1 if c[0] & 0x20 else 0)
        infs.append(0)
        try:
            hosts.append(GC.g2_decompress(bytes(c)))
        except ValueError:
            hosts.append("invalid")
    flag_bits = jnp.asarray(
        np.stack([np.asarray(signs, np.int32), np.asarray(infs, np.int32)])
    )
    (mx0, mx1, y0, y1), ok = IN.g2_decompress_device(
        enc_plain([x[0] for x in xs]), enc_plain([x[1] for x in xs]), flag_bits
    )
    assert dec(mx0) == [x[0] for x in xs]  # mont x planes round-trip
    d0, d1 = dec(y0), dec(y1)
    for i, h in enumerate(hosts):
        if h == "invalid":
            assert not bool(ok[i]), i
        else:
            assert bool(ok[i]), i
            assert (d0[i], d1[i]) == h[1], i


def test_g1_keyvalidate_device():
    """Device KeyValidate vs the host: valid keys pass; off-curve,
    out-of-subgroup, infinity, and malformed keys fail."""
    import jax.numpy as jnp

    from lodestar_tpu.bls.ingest import encode_pubkey_planes

    valid = [GC.g1_compress(B.sk_to_pk(B.keygen(b"kv-%d" % i))) for i in range(6)]
    # out-of-subgroup: a random on-curve point (full group order w.h.p.)
    x = 5
    while GT.fp_sqrt((x * x * x + 4) % P) is None:
        x += 1
    y = GT.fp_sqrt((x * x * x + 4) % P)
    assert not GC.g1_subgroup_check((x, y))
    out_of_subgroup = GC.g1_compress((x, y))
    # off-curve x
    xc = x
    while GT.fp_sqrt((xc * xc * xc + 4) % P) is not None:
        xc += 1
    off_curve = bytearray(GC.g1_compress((x, y)))
    off = xc.to_bytes(48, "big")
    off_curve = bytes([0x80 | off[0]]) + off[1:]
    inf = bytes([0xC0]) + b"\x00" * 47
    keys = (valid + [out_of_subgroup, off_curve, inf]) * 15  # 135 keys
    keys = keys[:128]
    planes, flags, host_bad = encode_pubkey_planes(keys)
    from lodestar_tpu.kernels import ingest as IN2

    (mx, my), ok = IN2.g1_keyvalidate_device(
        jnp.asarray(planes), jnp.asarray(flags)
    )
    ok = np.asarray(ok) & ~host_bad
    for i, k in enumerate(keys):
        try:
            pt = GC.g1_decompress(k)
            expect = pt is not None and GC.g1_subgroup_check(pt)
        except ValueError:
            expect = False
        assert bool(ok[i]) == expect, (i, expect)
        if expect:
            assert (dec(mx)[i], dec(my)[i]) == pt, i


def test_register_compressed_device():
    from lodestar_tpu.bls.pubkey_table import PubkeyTable

    pts = [B.sk_to_pk(B.keygen(b"rc-%d" % i)) for i in range(5)]
    keys = [GC.g1_compress(p) for p in pts]
    t = PubkeyTable(capacity=8)
    idxs = t.register_compressed(keys)
    assert idxs == list(range(5))
    for i, p in enumerate(pts):
        assert t.host_affine(i) == p
    bad = PubkeyTable(capacity=8)
    with pytest.raises(ValueError):
        bad.register_compressed(keys[:2] + [b"\x00" * 48])


def test_hash_to_g2_device_matches_host():
    n = 128
    msgs = [b"ingest message %d" % (i % 7) for i in range(n)]
    u_pairs = [HC.hash_to_field_fp2(m, 2, HC.DST_G2) for m in msgs]
    host_map = {m: HC.hash_to_g2(m) for m in set(msgs)}
    sgn = np.zeros((2, n), np.int32)
    for i, (u0, u1) in enumerate(u_pairs):
        sgn[0, i] = HC._sgn0_fp2(u0)
        sgn[1, i] = HC._sgn0_fp2(u1)
    planes, ok = IN.hash_to_g2_device(
        enc_plain([u[0][0] for u in u_pairs]),
        enc_plain([u[0][1] for u in u_pairs]),
        enc_plain([u[1][0] for u in u_pairs]),
        enc_plain([u[1][1] for u in u_pairs]),
        jnp.asarray(sgn),
    )
    assert bool(np.asarray(ok).all())
    X0, X1, Y0, Y1, Z0, Z1 = (dec(p) for p in planes)
    for i, m in enumerate(msgs):
        z = (Z0[i], Z1[i])
        zi = GT.fp2_inv(z)
        zi2 = GT.fp2_sqr(zi)
        x = GT.fp2_mul((X0[i], X1[i]), zi2)
        y = GT.fp2_mul((Y0[i], Y1[i]), GT.fp2_mul(zi2, zi))
        assert (x, y) == host_map[m], i
