"""Blob data plane: db persistence, reqresp serving, sync fetching.

Reference behaviors: db/repositories/blobsSidecar.ts (+archive),
network/reqresp handlers for blob_sidecars_by_range/by_root (p2p spec
deneb), and the sync path feeding the import DA gate with verified
sidecars.
"""

import hashlib as _hl

import pytest

from lodestar_tpu import params
from lodestar_tpu import types as T
from lodestar_tpu.chain import blobs as BL
from lodestar_tpu.crypto import bls as B
from lodestar_tpu.crypto import curves as C
from lodestar_tpu.crypto import kzg as K
from lodestar_tpu.db import BeaconDb
from lodestar_tpu.db.beacon_db import BlobSidecarListCodec

pytestmark = pytest.mark.smoke


def _mk_sidecars(n_blobs=2, slot=1, proposer=0, sk=None):
    setup = K.insecure_dev_setup(8)
    blobs = [
        K.polynomial_to_blob(
            [
                int.from_bytes(_hl.sha256(b"bp-%d-%d" % (j, i)).digest(), "big")
                % K.R
                for i in range(8)
            ]
        )
        for j in range(n_blobs)
    ]
    commitments = [K.blob_to_kzg_commitment(b, setup) for b in blobs]
    body = T.BeaconBlockBodyDeneb.default()
    body["blob_kzg_commitments"] = list(commitments)
    block = {
        "slot": slot,
        "proposer_index": proposer,
        "parent_root": b"\x01" * 32,
        "state_root": b"\x02" * 32,
        "body": body,
    }
    sk = sk or B.keygen(b"bp")
    signed = {"message": block, "signature": b"\x00" * 96}
    sidecars = BL.make_blob_sidecars(
        signed, T.BeaconBlockBodyDeneb, blobs, setup
    )
    header = dict(block)
    del header["body"]
    header["body_root"] = T.BeaconBlockBodyDeneb.hash_tree_root(body)
    root = T.BeaconBlockHeader.hash_tree_root(header)
    return sidecars, bytes(root), setup, signed


def test_codec_roundtrip():
    sidecars, root, _setup, _signed = _mk_sidecars()
    codec = BlobSidecarListCodec()
    back = codec.deserialize(codec.serialize(sidecars))
    assert len(back) == len(sidecars)
    for a, b in zip(sidecars, back):
        assert int(a["index"]) == int(b["index"])
        assert bytes(a["blob"]) == bytes(b["blob"])
        assert bytes(a["kzg_commitment"]) == bytes(b["kzg_commitment"])
        assert bytes(a["kzg_proof"]) == bytes(b["kzg_proof"])
        am, bm = (
            a["signed_block_header"]["message"],
            b["signed_block_header"]["message"],
        )
        assert {k: int(v) if isinstance(v, int) else bytes(v) for k, v in am.items()} == {
            k: int(v) if isinstance(v, int) else bytes(v) for k, v in bm.items()
        }
        assert [bytes(x) for x in a["kzg_commitment_inclusion_proof"]] == [
            bytes(x) for x in b["kzg_commitment_inclusion_proof"]
        ]
        # the roundtripped sidecar still proves inclusion
        assert BL.verify_blob_inclusion(b, T.BeaconBlockBodyDeneb)


def test_codec_rejects_hostile_input():
    """The codec decodes untrusted peer responses: hostile counts and
    lengths must error out, never loop or misalign (review r5)."""
    codec = BlobSidecarListCodec()
    with pytest.raises(ValueError):
        codec.deserialize(b"\xff\xff\xff\xff")  # count = 4 billion
    with pytest.raises(ValueError):
        codec.deserialize(b"\x01\x00\x00\x00" + b"\x00" * 8)  # truncated
    sidecars, _root, _setup, _signed = _mk_sidecars(n_blobs=1)
    good = codec.serialize(sidecars)
    # corrupt the blob length field to a huge value
    bad = good[:12] + (2**31).to_bytes(4, "little") + good[16:]
    with pytest.raises(ValueError):
        codec.deserialize(bad)
    with pytest.raises(ValueError):
        codec.deserialize(good[: len(good) // 2])  # truncated tail


def test_db_hot_and_archive():
    sidecars, root, _setup, _signed = _mk_sidecars()
    db = BeaconDb()
    db.put_blob_sidecars(root, sidecars)
    assert len(db.get_blob_sidecars(root)) == 2
    # archive migration: hot row deleted, archive served via root index
    db.block_archive_root_index.put(root, (1).to_bytes(8, "big"))
    db.archive_blob_sidecars(1, sidecars, root=root)
    assert db.blobs_sidecar.get(root) is None
    assert len(db.get_blob_sidecars(root)) == 2


def test_reqresp_blob_protocols_end_to_end():
    """Server with a db of sidecars serves by_root and by_range to an
    in-memory-connected client."""
    from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
    from lodestar_tpu.network.reqresp import ReqResp, connect_inmemory
    from lodestar_tpu.network.reqresp_protocols import (
        ReqRespBeaconNode,
        blob_sidecars_by_root_protocol,
    )
    from lodestar_tpu.params import ForkName

    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG, fork_epochs={ForkName.altair: 0}
    )
    sidecars, root, _setup, signed = _mk_sidecars()
    db = BeaconDb()
    db.put_blob_sidecars(root, sidecars)

    class ChainStub:
        config = cfg
        _sidecar_bodies = {}

        class head_state:
            slot = 1
            finalized_checkpoint = {"epoch": 0, "root": b"\x00" * 32}

        @staticmethod
        def get_head_root():
            return b"\x00" * 32

    server, client = ReqResp(), ReqResp()
    ReqRespBeaconNode(server, cfg, chain=ChainStub, db=db)
    connect_inmemory(client, "client", server, "server")
    proto = blob_sidecars_by_root_protocol(cfg)
    chunks = client.send_request(
        "server",
        proto,
        [{"block_root": root, "index": 1}, {"block_root": root, "index": 0}],
    )
    got = [proto.decode_response(d, ctx) for d, ctx in chunks]
    assert [int(sc["index"]) for sc in got] == [1, 0]
    assert bytes(got[0]["blob"]) == bytes(sidecars[1]["blob"])


def test_sync_chain_fetches_and_registers_blobs():
    """A batch whose blocks carry commitments downloads sidecars,
    verifies them, and registers availability before importing."""
    from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
    from lodestar_tpu.params import ForkName
    from lodestar_tpu.sync import SyncChain, SyncChainError

    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG,
        fork_epochs={
            ForkName.altair: 0,
            ForkName.bellatrix: 0,
            ForkName.capella: 0,
            ForkName.deneb: 0,
        },
    )
    sidecars, root, setup, signed = _mk_sidecars()

    class FakeChain:
        config = cfg

        def __init__(self):
            self.registered = []
            self.imported = []

        def on_blob_sidecar(self, block_root, index, commitment, slot=None, sidecar=None):
            self.registered.append((bytes(block_root), index))

        def process_block(self, sb):
            # the DA gate would consult availability here; order matters
            assert len(self.registered) == 2, "sidecars must register first"
            self.imported.append(sb)

    class Source:
        def get_blocks_by_range(self, start, count):
            return [signed] if start <= 1 < start + count else []

        def get_blocks_by_root(self, roots):
            return []

        def get_blob_sidecars_by_range(self, start, count):
            return list(sidecars)

    chain = FakeChain()
    sc = SyncChain(chain, 1, 1, kzg_setup=setup)
    sc.add_peer("p", Source())
    assert sc.run() == 1
    assert chain.registered == [(root, 0), (root, 1)]

    # a peer serving deneb blocks WITHOUT a blob endpoint is a fault
    class BloblessSource:
        def get_blocks_by_range(self, start, count):
            return [signed] if start <= 1 < start + count else []

        def get_blocks_by_root(self, roots):
            return []

    chain2 = FakeChain()
    sc2 = SyncChain(chain2, 1, 1, max_download_attempts=1)
    sc2.add_peer("p", BloblessSource())
    with pytest.raises(SyncChainError):
        sc2.run()

    # corrupted blob -> verification fails the batch
    class CorruptSource(Source):
        def get_blob_sidecars_by_range(self, start, count):
            bad = dict(sidecars[0])
            bad["blob"] = bytes(len(bytes(bad["blob"])))
            return [bad, sidecars[1]]

    chain3 = FakeChain()
    sc3 = SyncChain(chain3, 1, 1, kzg_setup=setup, max_processing_attempts=1, max_download_attempts=1)
    sc3.add_peer("p", CorruptSource())
    with pytest.raises(SyncChainError):
        sc3.run()
    assert not chain3.imported


def test_reqresp_adapter_serves_blob_batches_to_sync():
    """The wire loop for deneb ranges: server db -> blob chunks ->
    ReqRespBlockSource.get_blob_sidecars_by_range -> SyncChain verifies
    + registers + imports (the adapter's blob decode path)."""
    from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
    from lodestar_tpu.network.reqresp import ReqResp, connect_inmemory
    from lodestar_tpu.network.reqresp_protocols import (
        ReqRespBeaconNode,
        ReqRespBlockSource,
    )
    from lodestar_tpu.params import ForkName
    from lodestar_tpu.sync import SyncChain

    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG,
        fork_epochs={
            ForkName.altair: 0,
            ForkName.bellatrix: 0,
            ForkName.capella: 0,
            ForkName.deneb: 0,
        },
    )
    sidecars, root, setup, signed = _mk_sidecars(slot=1)
    db = BeaconDb(config=cfg)
    db.archive_block(1, signed, root=root)
    db.put_blob_sidecars(root, sidecars)

    class ChainStub:
        config = cfg

        @staticmethod
        def get_blob_sidecars(r):
            return None

        class head_state:
            slot = 1
            finalized_checkpoint = {"epoch": 0, "root": b"\x00" * 32}

        @staticmethod
        def get_head_root():
            return b"\x00" * 32

    server, client = ReqResp(), ReqResp()
    ReqRespBeaconNode(server, cfg, chain=ChainStub, db=db)
    connect_inmemory(client, "syncer", server, "server")
    source = ReqRespBlockSource(client, "server", cfg)

    # the adapter decodes wire chunks back to value-shaped sidecars
    got = source.get_blob_sidecars_by_range(0, 4)
    assert [int(s["index"]) for s in got] == [0, 1]
    assert bytes(got[0]["blob"]) == bytes(sidecars[0]["blob"])

    class FakeChain:
        config = cfg

        def __init__(self):
            self.registered = []
            self.imported = []

        def on_blob_sidecar(self, block_root, index, commitment, slot=None, sidecar=None):
            self.registered.append((bytes(block_root), int(index)))

        def process_block(self, sb):
            assert len(self.registered) == 2, "sidecars must register first"
            self.imported.append(sb)

    chain = FakeChain()
    sc = SyncChain(chain, 1, 1, kzg_setup=setup)
    sc.add_peer("server", source)
    assert sc.run() == 1
    assert chain.registered == [(root, 0), (root, 1)]


def test_unknown_block_sync_fetches_blobs_by_root():
    """A by-root resolved deneb block fetches + verifies + registers its
    sidecars before import (review r5 follow-up: the DA gate otherwise
    rejects UnknownBlockSync's deneb imports)."""
    from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
    from lodestar_tpu.params import ForkName
    from lodestar_tpu.sync import UnknownBlockSync

    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG,
        fork_epochs={
            ForkName.altair: 0,
            ForkName.bellatrix: 0,
            ForkName.capella: 0,
            ForkName.deneb: 0,
        },
    )
    sidecars, root, setup, signed = _mk_sidecars(slot=4)
    block_root = cfg.get_fork_types(4)[0].hash_tree_root(signed["message"])

    class FakeChain:
        config = cfg

        def __init__(self):
            self.registered = []
            self.imported = []

            class FC:
                @staticmethod
                def has_block(h):
                    # the parent is known; the target block is not
                    return h == (b"\x01" * 32).hex()

            self.fork_choice = FC()

        def on_blob_sidecar(self, br, i, c, slot=None, sidecar=None):
            self.registered.append(int(i))

        def process_block(self, sb):
            assert len(self.registered) == 2, "blobs must register first"
            self.imported.append(sb)

    class Source:
        def __init__(self):
            self.root_queries = []

        def get_blocks_by_root(self, roots):
            return [signed] if bytes(roots[0]) == bytes(block_root) else []

        def get_blocks_by_range(self, a, b):
            return []

        def get_blob_sidecars_by_root(self, identifiers):
            self.root_queries.append(identifiers)
            return list(sidecars)

    chain = FakeChain()
    ub = UnknownBlockSync(chain, kzg_setup=setup)
    n = ub.on_unknown_block(Source(), bytes(block_root))
    assert n == 1 and chain.registered == [0, 1] and chain.imported

    # a blob-less source cannot serve deneb segments
    class BloblessSource:
        def get_blocks_by_root(self, roots):
            return [signed]

        def get_blocks_by_range(self, a, b):
            return []

    chain2 = FakeChain()
    ub2 = UnknownBlockSync(chain2, kzg_setup=setup)
    with pytest.raises(LookupError, match="blob_sidecars_by_root"):
        ub2.on_unknown_block(BloblessSource(), bytes(block_root))
    assert not chain2.imported


def test_unknown_block_sync_validates_peer_responses():
    """Short or foreign by-root answers are PEER faults at fetch time,
    and locally-available data skips the network entirely (review r5)."""
    from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
    from lodestar_tpu.params import ForkName
    from lodestar_tpu.sync import UnknownBlockSync

    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG,
        fork_epochs={
            ForkName.altair: 0,
            ForkName.bellatrix: 0,
            ForkName.capella: 0,
            ForkName.deneb: 0,
        },
    )
    sidecars, root, setup, signed = _mk_sidecars(slot=4)
    block_root = cfg.get_fork_types(4)[0].hash_tree_root(signed["message"])

    class FakeChain:
        config = cfg

        def __init__(self, local=None):
            self.registered = []
            self.imported = []
            self._local = local

            class FC:
                @staticmethod
                def has_block(h):
                    return h == (b"\x01" * 32).hex()

            self.fork_choice = FC()

        def get_blob_sidecars(self, r):
            return self._local

        def on_blob_sidecar(self, br, i, c, slot=None, sidecar=None):
            self.registered.append(int(i))

        def process_block(self, sb):
            self.imported.append(sb)

    class Source:
        def __init__(self, answer):
            self.answer = answer
            self.fetches = 0

        def get_blocks_by_root(self, roots):
            return [signed]

        def get_blocks_by_range(self, a, b):
            return []

        def get_blob_sidecars_by_root(self, identifiers):
            self.fetches += 1
            return self.answer

    # short answer -> peer fault, block NOT imported
    chain = FakeChain()
    with pytest.raises(LookupError, match="1/2 sidecars"):
        UnknownBlockSync(chain, kzg_setup=setup).on_unknown_block(
            Source(sidecars[:1]), bytes(block_root)
        )
    assert not chain.imported

    # a validly-proven sidecar for a DIFFERENT block -> peer fault
    other_sidecars, _oroot, _s, _osigned = _mk_sidecars(slot=9)
    chain2 = FakeChain()
    with pytest.raises(LookupError, match="different block"):
        UnknownBlockSync(chain2, kzg_setup=setup).on_unknown_block(
            Source(list(other_sidecars)), bytes(block_root)
        )
    assert not chain2.imported

    # gossip already delivered the data: zero network fetches
    chain3 = FakeChain(local=list(sidecars))
    src = Source([])
    n = UnknownBlockSync(chain3, kzg_setup=setup).on_unknown_block(
        src, bytes(block_root)
    )
    assert n == 1 and src.fetches == 0 and chain3.imported
