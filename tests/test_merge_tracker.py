"""Eth1MergeBlockTracker: TTD search, override, and the transition block.

Reference behaviors: packages/beacon-node/src/eth1/
eth1MergeBlockTracker.ts (status machine, backward TTD walk, terminal
block hash override) and produceBlockBody.ts prepareExecutionPayload
(the transition block's payload parent comes from the tracker).
"""

import pytest

from lodestar_tpu import params
from lodestar_tpu import types as T
from lodestar_tpu.chain.chain import BeaconChain
from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
from lodestar_tpu.crypto import bls as B
from lodestar_tpu.crypto import curves as C
from lodestar_tpu.eth1 import (
    Eth1MergeBlockTracker,
    MergeTrackerStatus,
    PowMergeBlock,
)
from lodestar_tpu.execution import ExecutionEngineMock
from lodestar_tpu.params import ForkName
from lodestar_tpu.state_transition import create_genesis_state
from lodestar_tpu.state_transition.accessors import get_beacon_proposer_index
from lodestar_tpu.state_transition.block import is_merge_transition_complete
from lodestar_tpu.state_transition.slot import process_slots
from lodestar_tpu.validator import ValidatorStore

pytestmark = pytest.mark.smoke

P = params.ACTIVE_PRESET


class PowChain:
    """A fake eth1 provider over a linear PoW chain."""

    def __init__(self, tds):
        """tds: list of total difficulties, block i has hash ii*32."""
        self.blocks = {}
        prev = "00" * 32
        for i, td in enumerate(tds, start=1):
            h = ("%02x" % i) * 32
            self.blocks[h] = PowMergeBlock(
                number=i, block_hash=h, parent_hash=prev, total_difficulty=td
            )
            prev = h
        self.head = prev
        self.calls = 0

    def get_pow_block_by_hash(self, block_hash):
        self.calls += 1
        return self.blocks.get(block_hash)

    def get_pow_block_latest(self):
        return self.blocks[self.head]


def test_ttd_walk_finds_first_crossing_block():
    # tds: 4, 9, 15, 22 with TTD=10 -> block 3 (first >= 10)
    chain = PowChain([4, 9, 15, 22])
    tr = Eth1MergeBlockTracker(chain, terminal_total_difficulty=10)
    found = tr.get_terminal_pow_block()
    assert found is not None and found.number == 3
    assert tr.status == MergeTrackerStatus.FOUND
    # latched: later calls return the cached block without re-walking
    calls = chain.calls
    assert tr.get_terminal_pow_block().number == 3
    assert chain.calls == calls


def test_ttd_not_reached_returns_none():
    chain = PowChain([4, 9])
    tr = Eth1MergeBlockTracker(chain, terminal_total_difficulty=100)
    assert tr.get_terminal_pow_block() is None
    assert tr.status == MergeTrackerStatus.STOPPED
    assert tr.get_td_progress() == {
        "ttd_hit": False,
        "ttd": 100,
        "td": 9,
        "td_diff": 91,
    }


def test_polling_status_machine():
    chain = PowChain([4, 9])
    tr = Eth1MergeBlockTracker(chain, terminal_total_difficulty=10)
    tr.start_polling_merge_block()
    assert tr.status == MergeTrackerStatus.SEARCHING
    # while SEARCHING, the on-demand getter defers to the poller
    assert tr.get_terminal_pow_block() is None
    assert tr.on_tick() is None  # TTD not crossed yet
    # the PoW chain advances past TTD
    chain.blocks["03" * 32] = PowMergeBlock(3, "03" * 32, "02" * 32, 12)
    chain.head = "03" * 32
    found = tr.on_tick()
    assert found is not None and found.number == 3
    assert tr.status == MergeTrackerStatus.FOUND
    assert tr.get_terminal_pow_block().number == 3


def test_terminal_block_hash_override():
    chain = PowChain([4, 9, 15])
    override = bytes.fromhex("02" * 32)
    tr = Eth1MergeBlockTracker(
        chain, terminal_total_difficulty=10, terminal_block_hash=override
    )
    found = tr.get_terminal_pow_block()
    assert found.number == 2  # the override wins regardless of TTD


def test_genesis_block_may_reach_ttd():
    chain = PowChain([50])
    tr = Eth1MergeBlockTracker(chain, terminal_total_difficulty=10)
    assert tr.get_terminal_pow_block().number == 1


def test_transition_block_uses_discovered_terminal_block():
    """The merge-transition proposal's payload parent is DISCOVERED by
    the tracker, not handed in (VERDICT done-criterion)."""
    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG,
        fork_epochs={ForkName.altair: 0, ForkName.bellatrix: 1},
    )
    sks = [B.keygen(b"mt-%d" % i) for i in range(8)]
    pks = [C.g1_compress(B.sk_to_pk(sk)) for sk in sks]
    genesis = create_genesis_state(cfg, pks, genesis_time=2)

    el = ExecutionEngineMock()
    chain = BeaconChain(cfg, genesis, execution=el)
    store = ValidatorStore(cfg, dict(enumerate(sks)))

    # a PoW chain crossing TTD at block 2; the EL knows those blocks
    pow_chain = PowChain([5, 11, 20])
    for h, blk in pow_chain.blocks.items():
        el.valid_blocks[bytes.fromhex(h)] = (
            bytes.fromhex(blk.parent_hash)
            if blk.parent_hash != "00" * 32
            else b"\x00" * 32
        )
    tracker = Eth1MergeBlockTracker(pow_chain, terminal_total_difficulty=10)
    chain.merge_block_tracker = tracker

    slot = P.SLOTS_PER_EPOCH + 1  # first bellatrix slot
    st = genesis.clone()
    process_slots(st, slot)
    proposer = get_beacon_proposer_index(st)
    block = chain.produce_block(slot, store.sign_randao(proposer, slot))
    payload = block["body"]["execution_payload"]
    # the payload extends the TERMINAL PoW block (number 2, td 11)
    assert bytes(payload["parent_hash"]).hex() == "02" * 32
    assert tracker.status == MergeTrackerStatus.FOUND

    root = cfg.compute_signing_root(
        T.BeaconBlockBellatrix.hash_tree_root(block),
        cfg.get_domain(slot, params.DOMAIN_BEACON_PROPOSER, slot),
    )
    signed = {
        "message": block,
        "signature": C.g2_compress(B.sign(sks[proposer], root)),
    }
    chain.process_block(signed)
    assert is_merge_transition_complete(chain.head_state)
