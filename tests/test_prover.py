"""Prover: keccak256 vectors, RLP, MPT proof verification.

Reference: packages/prover/src — account/storage/code verification
against eth_getProof-shaped data.  Tries here are constructed by hand
from the MPT spec so the proofs are exact.
"""

import pytest

from lodestar_tpu.prover import (
    ProofError,
    keccak256,
    rlp_decode,
    rlp_encode,
    verify_account_proof,
    verify_code,
    verify_proof,
    verify_storage_proof,
)
from lodestar_tpu.prover.mpt import _decode_hp, _nibbles

pytestmark = pytest.mark.smoke


def test_keccak256_vectors():
    assert keccak256(b"").hex() == (
        "c5d2460186f7233c927e7db2dcc703c0e500b653ca82273b7bfad8045d85a470"
    )
    assert keccak256(b"abc").hex() == (
        "4e03657aea45a94fc7d47ba826c8d667c0d1e6e33a64a036ec44f58fa12d6c45"
    )
    # > rate-sized input exercises multi-block absorption
    assert keccak256(b"a" * 200).hex() == keccak256(b"a" * 200).hex()
    assert keccak256(b"a" * 135) != keccak256(b"a" * 136)


def test_rlp_roundtrip():
    cases = [
        b"",
        b"\x01",
        b"\x7f",
        b"\x80",
        b"dog",
        b"x" * 60,
        [b"cat", b"dog"],
        [b"", [b"a", [b"b"]], b"c" * 56],
    ]
    for case in cases:
        assert rlp_decode(rlp_encode(case)) == case
    # canonical single bytes
    assert rlp_encode(b"\x05") == b"\x05"
    assert rlp_encode(b"dog") == b"\x83dog"


def _hp(nibbles, is_leaf):
    flag = 2 if is_leaf else 0
    if len(nibbles) % 2:
        first = bytes([((flag | 1) << 4) | nibbles[0]])
        rest = nibbles[1:]
    else:
        first = bytes([flag << 4])
        rest = nibbles
    body = bytes(
        (rest[i] << 4) | rest[i + 1] for i in range(0, len(rest), 2)
    )
    return first + body


def test_single_leaf_trie_proof():
    key = b"\x11" * 20
    value = rlp_encode([b"\x01", b"\x64", b"\xaa" * 32, b"\xbb" * 32])
    path = _nibbles(keccak256(key))
    leaf = rlp_encode([_hp(path, True), value])
    root = keccak256(leaf)

    assert verify_proof(root, keccak256(key), [leaf]) == value
    account = verify_account_proof(root, key, [leaf])
    assert account == {
        "nonce": 1,
        "balance": 100,
        "storage_hash": b"\xaa" * 32,
        "code_hash": b"\xbb" * 32,
    }
    # absent key: leaf path diverges -> None
    other = b"\x22" * 20
    assert verify_account_proof(root, other, [leaf]) is None
    # missing node raises
    with pytest.raises(ProofError):
        verify_proof(b"\x00" * 32, keccak256(key), [leaf])


def test_branch_trie_proof():
    # two keys whose hashed paths differ at the first nibble
    keys = [b"k1", b"k2", b"k3", b"k4", b"k5"]
    k1 = keys[0]
    k2 = next(
        k
        for k in keys[1:]
        if _nibbles(keccak256(k))[0] != _nibbles(keccak256(k1))[0]
    )
    v1, v2 = rlp_encode(b"value-one"), rlp_encode(b"value-two")

    n1, n2 = _nibbles(keccak256(k1)), _nibbles(keccak256(k2))
    leaf1 = rlp_encode([_hp(n1[1:], True), v1])
    leaf2 = rlp_encode([_hp(n2[1:], True), v2])
    branch = [b""] * 17
    branch[n1[0]] = keccak256(leaf1)
    branch[n2[0]] = keccak256(leaf2)
    branch_rlp = rlp_encode(branch)
    root = keccak256(branch_rlp)

    assert verify_proof(root, keccak256(k1), [branch_rlp, leaf1]) == v1
    assert verify_proof(root, keccak256(k2), [branch_rlp, leaf2]) == v2
    # a key into an empty branch slot is proven absent
    empty_slot_key = next(
        k
        for k in (b"q%d" % i for i in range(100))
        if not branch[_nibbles(keccak256(k))[0]]
    )
    assert verify_proof(root, keccak256(empty_slot_key), [branch_rlp]) is None


def test_storage_and_code():
    slot = b"\x00" * 32
    value = rlp_encode(b"\x2a")  # 42
    path = _nibbles(keccak256(slot))
    leaf = rlp_encode([_hp(path, True), value])
    root = keccak256(leaf)
    assert verify_storage_proof(root, slot, [leaf]) == 42
    # absent slot -> 0
    assert verify_storage_proof(root, b"\x01" + b"\x00" * 31, [leaf]) == 0

    code = b"\x60\x80\x60\x40"
    assert verify_code(code, keccak256(code))
    assert not verify_code(code, b"\x00" * 32)


def test_hex_prefix_roundtrip():
    for nibs in ([], [5], [1, 2, 3], [0xF, 0xE, 0xD, 0xC]):
        for leaf in (True, False):
            decoded, is_leaf = _decode_hp(_hp(nibs, leaf))
            assert decoded == nibs
            assert is_leaf == leaf
