"""Multi-node devnet simulation: N full nodes over one gossip bus.

Mirror of the reference's in-repo simulation framework (reference:
cli/test/utils/simulation/ SimulationEnvironment + SimulationTracker
with declarative per-slot assertions — head consistency, finality/
justification progression; and beacon-node/test/utils/node/simTest.ts
for the in-process flavor).  Here: three FullBeaconNodes share an
InMemoryGossipBus and a req/resp mesh; every validator attests every
slot through the REAL gossip topics; proposers publish real signed
blocks; the tracker asserts, per slot, that

  - every node converges to the same head,
  - blocks and attestations ACCEPT on every node (no REJECTs), and
  - by the end of epoch 2 every node's state justifies epoch >= 1.
"""

import pytest

from lodestar_tpu import params
from lodestar_tpu import types as T
from lodestar_tpu.bls.single_thread import CpuBlsVerifier
from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
from lodestar_tpu.crypto import bls as B
from lodestar_tpu.crypto import curves as C
from lodestar_tpu.network.gossip import (
    GossipTopicName,
    InMemoryGossipBus,
    encode_message,
    topic_string,
)
from lodestar_tpu.network.reqresp import connect_inmemory
from lodestar_tpu.network.subnets import compute_subnet_for_attestation
from lodestar_tpu.node import FullBeaconNode, NodeOptions
from lodestar_tpu.params import ForkName
from lodestar_tpu.state_transition import create_genesis_state
from lodestar_tpu.state_transition.accessors import (
    get_beacon_committee,
    get_beacon_proposer_index,
    get_committee_count_per_slot,
)
from lodestar_tpu.state_transition.slot import process_slots
from lodestar_tpu.state_transition.util import compute_epoch_at_slot
from lodestar_tpu.validator import ValidatorStore

N_KEYS = 8
N_NODES = 3
# the spec skips justification while current_epoch <= 1, so the FIRST
# possible justification lands at the end of epoch 2 — run three epochs
EPOCHS = 3

P = params.ACTIVE_PRESET


class SimulationTracker:
    """Per-slot assertion ledger (reference: simulation/tracker.ts +
    assertions/)."""

    def __init__(self, nodes):
        self.nodes = nodes
        self.failures = []

    def assert_slot(self, slot):
        heads = {name: n.chain.head_root_hex for name, n in self.nodes.items()}
        if len(set(heads.values())) != 1:
            self.failures.append((slot, "head divergence", heads))
        for name, n in self.nodes.items():
            for topic, res in n.handlers.results.items():
                if res.get("reject"):
                    self.failures.append(
                        (slot, f"{name} rejected {topic}", dict(res))
                    )

    def assert_justified(self, min_epoch):
        for name, n in self.nodes.items():
            je = int(
                n.chain.head_state.current_justified_checkpoint["epoch"]
            )
            if je < min_epoch:
                self.failures.append(
                    ("end", f"{name} justified epoch {je} < {min_epoch}", None)
                )


@pytest.fixture()
def sim_tracing():
    """Tracing on for the sim, restored OFF even when the sim body
    fails mid-run (an enabled global tracer must not leak into later
    tests in the same process)."""
    from lodestar_tpu import observability as OB

    OB.configure(enabled=True)
    OB.get_tracer().clear()
    try:
        yield OB
    finally:
        OB.configure(enabled=False)
        OB.get_tracer().clear()


@pytest.mark.slow
def test_three_node_sim_reaches_justification(tmp_path, sim_tracing):
    # ISSUE 8 acceptance: with tracing on, this sim run must yield a
    # loadable Chrome trace whose gossip->verify->import spans NEST
    # (asserted at the end); the equivalent fast-path assertion lives in
    # tests/test_observability.py::test_gossip_verify_import_nested_span_tree
    OB = sim_tracing
    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG,
        fork_epochs={ForkName.altair: 0},
        genesis_time=10,  # the node Clock reads CONFIG genesis time
    )
    sks = [B.keygen(b"sim-%d" % i) for i in range(N_KEYS)]
    pk_points = [B.sk_to_pk(sk) for sk in sks]
    pks = [C.g1_compress(p) for p in pk_points]
    genesis = create_genesis_state(cfg, pks, genesis_time=10)
    bus = InMemoryGossipBus()
    digest = cfg.fork_digest(0)

    nodes = {}
    for i in range(N_NODES):
        name = f"node-{i}"
        nodes[name] = FullBeaconNode.init(
            cfg,
            genesis,
            NodeOptions(
                serve_api=False,
                verifier=CpuBlsVerifier(pubkeys=pk_points),
                gossip_bus=bus,
                node_id=name,
                active_validator_count_hint=N_KEYS,
                subscribe_all_subnets=True,
            ),
        )
    # req/resp mesh (status exchange exercises the peer layer too)
    names = list(nodes)
    for i, a in enumerate(names):
        for b in names[i + 1 :]:
            connect_inmemory(nodes[a].reqresp, a, nodes[b].reqresp, b)
            nodes[a].peer_manager.on_connect(
                b, "outbound",
                # bind BOTH loop vars: a late-bound `a` would attribute
                # every post-handshake request to the last node
                lambda pid, req, aa=a, bb=b: nodes[bb].reqresp.handle_request(
                    aa, pid, req
                ),
            )

    # each node "runs" a disjoint slice of the validators
    owners = {i: names[i % N_NODES] for i in range(N_KEYS)}
    stores = {
        name: ValidatorStore(
            cfg, {i: sks[i] for i in range(N_KEYS) if owners[i] == name}
        )
        for name in names
    }

    tracker = SimulationTracker(nodes)
    # a mirror state for duty computation only (proposer/committee
    # schedules depend on imported randao, so track a real node's chain)
    ref = nodes[names[0]].chain

    total_slots = EPOCHS * P.SLOTS_PER_EPOCH
    for slot in range(1, total_slots + 1):
        epoch = compute_epoch_at_slot(slot)
        # clocks tick on every node
        for n in nodes.values():
            n.clock.set_time(10 + slot * params.SECONDS_PER_SLOT)
        st = ref.head_state.clone()
        if st.slot < slot:
            process_slots(st, slot)
        # 1. the slot's proposer (whoever owns it) publishes a block
        proposer = int(get_beacon_proposer_index(st))
        owner = stores[owners[proposer]]
        block = ref.produce_block(slot, owner.sign_randao(proposer, slot))
        # sign through the owning store: slashing protection +
        # fork-aware domain dispatch live there
        signed = {
            "message": block,
            "signature": owner.sign_block(proposer, block),
        }
        n_recv = bus.publish(
            "proposer",
            topic_string(digest, GossipTopicName.beacon_block),
            encode_message(
                cfg.get_fork_types(slot)[1].serialize(signed)
            ),
        )
        assert n_recv == N_NODES
        # 2. every committee member attests to the new head over gossip
        committees = int(get_committee_count_per_slot(st, epoch))
        head_after = ref.head_state
        for ci in range(committees):
            committee = get_beacon_committee(head_after, slot, ci)
            if len(committee) == 0:
                continue  # tiny registries leave most slots empty
            data = ref.produce_attestation_data(ci, slot)
            subnet = compute_subnet_for_attestation(committees, slot, ci)
            member_sigs = {}
            for pos, v in enumerate(committee):
                v = int(v)
                bits = [p_ == pos for p_ in range(len(committee))]
                sig = stores[owners[v]].sign_attestation(v, data)
                member_sigs[pos] = sig
                att = {
                    "aggregation_bits": bits,
                    "data": data,
                    "signature": sig,
                }
                bus.publish(
                    f"val-{v}",
                    topic_string(
                        digest,
                        GossipTopicName.beacon_attestation,
                        subnet=subnet,
                    ),
                    encode_message(T.Attestation.serialize(att)),
                )
            # the committee's aggregator publishes the aggregate — THIS
            # is what block production packs (aggregated pool), exactly
            # like the reference's aggregate_and_proof leg
            aggregator = int(committee[0])
            agg_sig = C.g2_compress(
                B.aggregate_signatures(
                    [C.g2_decompress(s) for s in member_sigs.values()]
                )
            )
            agg_store = stores[owners[aggregator]]
            proof = agg_store.sign_selection_proof(aggregator, slot)
            message = {
                "aggregator_index": aggregator,
                "aggregate": {
                    "aggregation_bits": [True] * len(committee),
                    "data": data,
                    "signature": agg_sig,
                },
                "selection_proof": proof,
            }
            signed_agg = {
                "message": message,
                "signature": agg_store.sign_aggregate_and_proof(
                    aggregator, message
                ),
            }
            bus.publish(
                f"agg-{aggregator}",
                topic_string(
                    digest, GossipTopicName.beacon_aggregate_and_proof
                ),
                encode_message(T.SignedAggregateAndProof.serialize(signed_agg)),
            )
        tracker.assert_slot(slot)

    tracker.assert_justified(1)
    assert not tracker.failures, tracker.failures
    # the peer layer stayed healthy through the run
    for name, n in nodes.items():
        for peer in n.peer_manager.connected_peers:
            assert n.score_book.state(peer).value == "Healthy"
    for n in nodes.values():
        n.close()

    # -- the trace the run produced (ISSUE 8 acceptance) -------------------
    import json as _json

    path = OB.write_chrome_trace(str(tmp_path / "sim_trace.json"))
    doc = _json.loads(open(path).read())
    events = doc["traceEvents"]
    by_id = {e["args"]["span_id"]: e for e in events}
    imports = [e for e in events if e["name"] == "chain.import"]
    assert imports, "no chain.import spans traced"
    # at least one import nests under a gossip.handle span (blocks
    # published over the bus), with verify + phase spans below it
    nested = [
        e for e in imports
        if e["args"]["parent_id"] in by_id
        and by_id[e["args"]["parent_id"]]["name"] == "gossip.handle"
    ]
    assert nested, "chain.import never nested under gossip.handle"
    roots = {e["args"]["span_id"] for e in nested}
    phase_names = {
        e["name"]
        for e in events
        if e["args"].get("parent_id") in roots
    }
    assert {
        "import.validation", "import.signature_verify", "import.stf",
        "import.state_root",
    } <= phase_names, phase_names
    assert any(e["name"] == "bls.verify" for e in events)


@pytest.mark.slow
def test_sim_equivocating_node_gets_slashed():
    """One node's validator double-votes (a second, conflicting
    attestation for the same duty — the reference's slashable-offence
    drill): every OTHER node must detect it through the live gossip
    stack (seen-cache recovery -> slasher batch -> op pool), the next
    proposer must include the attester slashing in a block, and every
    node's head state must show the offender slashed."""
    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG,
        fork_epochs={ForkName.altair: 0},
        genesis_time=10,
    )
    sks = [B.keygen(b"sim-%d" % i) for i in range(N_KEYS)]
    pk_points = [B.sk_to_pk(sk) for sk in sks]
    pks = [C.g1_compress(p) for p in pk_points]
    genesis = create_genesis_state(cfg, pks, genesis_time=10)
    bus = InMemoryGossipBus()
    digest = cfg.fork_digest(0)

    nodes = {}
    for i in range(N_NODES):
        name = f"node-{i}"
        nodes[name] = FullBeaconNode.init(
            cfg,
            genesis,
            NodeOptions(
                serve_api=False,
                verifier=CpuBlsVerifier(pubkeys=pk_points),
                gossip_bus=bus,
                node_id=name,
                active_validator_count_hint=N_KEYS,
                subscribe_all_subnets=True,
            ),
        )
    names = list(nodes)
    owners = {i: names[i % N_NODES] for i in range(N_KEYS)}
    stores = {
        name: ValidatorStore(
            cfg, {i: sks[i] for i in range(N_KEYS) if owners[i] == name}
        )
        for name in names
    }
    ref = nodes[names[0]].chain

    equivocator = None
    included_at = None
    for slot in range(1, 17):
        for n in nodes.values():
            n.clock.set_time(10 + slot * params.SECONDS_PER_SLOT)
        st = ref.head_state.clone()
        if st.slot < slot:
            process_slots(st, slot)
        proposer = int(get_beacon_proposer_index(st))
        if equivocator is not None and bool(st.slashed[equivocator]) and (
            proposer == equivocator
        ):
            continue  # a slashed proposer cannot produce; empty slot
        owner = stores[owners[proposer]]
        block = ref.produce_block(slot, owner.sign_randao(proposer, slot))
        if block["body"]["attester_slashings"]:
            included_at = slot
        signed = {
            "message": block,
            "signature": owner.sign_block(proposer, block),
        }
        assert (
            bus.publish(
                "proposer",
                topic_string(digest, GossipTopicName.beacon_block),
                encode_message(cfg.get_fork_types(slot)[1].serialize(signed)),
            )
            == N_NODES
        )
        if included_at is not None:
            break  # the slashing landed; nothing further to drive

        # every committee member attests; the chosen offender publishes
        # a SECOND, conflicting vote for the same duty
        epoch = compute_epoch_at_slot(slot)
        committees = int(get_committee_count_per_slot(st, epoch))
        head_after = ref.head_state
        for ci in range(committees):
            committee = get_beacon_committee(head_after, slot, ci)
            if len(committee) == 0:
                continue
            data = ref.produce_attestation_data(ci, slot)
            subnet = compute_subnet_for_attestation(committees, slot, ci)
            for pos, v in enumerate(committee):
                v = int(v)
                if equivocator is not None and v == equivocator:
                    continue  # the offender goes quiet after the crime
                bits = [p_ == pos for p_ in range(len(committee))]
                att = {
                    "aggregation_bits": bits,
                    "data": data,
                    "signature": stores[owners[v]].sign_attestation(v, data),
                }
                bus.publish(
                    f"val-{v}",
                    topic_string(
                        digest,
                        GossipTopicName.beacon_attestation,
                        subnet=subnet,
                    ),
                    encode_message(T.Attestation.serialize(att)),
                )
                if equivocator is None and slot >= 2 and owners[v] == names[-1]:
                    # the equivocation: same duty, different target root,
                    # signed by a second (protection-less) signer — the
                    # seen cache suppresses it, the recovery path must
                    # still convict
                    rogue = ValidatorStore(cfg, {v: sks[v]})
                    forged = {
                        "aggregation_bits": bits,
                        "data": {
                            **dict(data),
                            "source": dict(data["source"]),
                            "target": {
                                "epoch": data["target"]["epoch"],
                                "root": b"\x66" * 32,
                            },
                        },
                    }
                    forged["signature"] = rogue.sign_attestation(
                        v, forged["data"]
                    )
                    bus.publish(
                        f"val-{v}-rogue",
                        topic_string(
                            digest,
                            GossipTopicName.beacon_attestation,
                            subnet=subnet,
                        ),
                        encode_message(T.Attestation.serialize(forged)),
                    )
                    equivocator = v

    assert equivocator is not None, "no committee seat for the last node"
    assert included_at is not None, "slashing never included in a block"
    for name, n in nodes.items():
        assert n.slasher.detections["double_vote"] >= 1, name
        assert bool(n.chain.head_state.slashed[equivocator]), name
    for n in nodes.values():
        n.close()


@pytest.mark.slow
def test_sim_flight_recorder_captures_induced_late_import(tmp_path):
    """ISSUE 12 acceptance: an induced anomaly in a live multi-node sim
    produces one end-to-end flight-record bundle.  Slot 3's proposer
    withholds its block past the slot boundary (the clocks advance into
    slot 4 before the publish); every node's SLO engine books the
    attestation-head + import-boundary breaches, and the node with a
    recorder directory leaves a loadable bundle."""
    from lodestar_tpu.observability import flight_recorder as FR

    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG,
        fork_epochs={ForkName.altair: 0},
        genesis_time=10,
    )
    sks = [B.keygen(b"sim-%d" % i) for i in range(N_KEYS)]
    pk_points = [B.sk_to_pk(sk) for sk in sks]
    pks = [C.g1_compress(p) for p in pk_points]
    genesis = create_genesis_state(cfg, pks, genesis_time=10)
    bus = InMemoryGossipBus()
    digest = cfg.fork_digest(0)

    nodes = {}
    for i in range(2):
        name = f"node-{i}"
        nodes[name] = FullBeaconNode.init(
            cfg,
            genesis,
            NodeOptions(
                serve_api=False,
                verifier=CpuBlsVerifier(pubkeys=pk_points),
                gossip_bus=bus,
                node_id=name,
                active_validator_count_hint=N_KEYS,
                subscribe_all_subnets=True,
                # only node-0 records to disk; both evaluate SLOs
                flightrec_dir=(
                    str(tmp_path / "fr") if i == 0 else None
                ),
            ),
        )
    names = list(nodes)
    # the wiring the satellite closed: gossip validators route
    # block-critical verification through the node's service
    for n in nodes.values():
        assert n.handlers.validators.service is n.bls
        assert n.slo is not None
    recorder = nodes[names[0]].flight_recorder
    assert recorder is not None

    owners = {i: names[i % 2] for i in range(N_KEYS)}
    stores = {
        name: ValidatorStore(
            cfg, {i: sks[i] for i in range(N_KEYS) if owners[i] == name}
        )
        for name in names
    }
    ref = nodes[names[0]].chain

    def publish_block(slot):
        st = ref.head_state.clone()
        if st.slot < slot:
            process_slots(st, slot)
        proposer = int(get_beacon_proposer_index(st))
        owner = stores[owners[proposer]]
        block = ref.produce_block(slot, owner.sign_randao(proposer, slot))
        signed = {
            "message": block,
            "signature": owner.sign_block(proposer, block),
        }
        assert (
            bus.publish(
                "proposer",
                topic_string(digest, GossipTopicName.beacon_block),
                encode_message(cfg.get_fork_types(slot)[1].serialize(signed)),
            )
            == 2
        )

    # two healthy slots: block published right at the slot start
    for slot in (1, 2):
        for n in nodes.values():
            n.clock.set_time(10 + slot * params.SECONDS_PER_SLOT)
        publish_block(slot)

    # the induced anomaly: the clocks cross into slot 4 BEFORE slot 3's
    # block goes out — its import completes past the slot-3 boundary
    for n in nodes.values():
        n.clock.set_time(10 + (4 + 0.2) * params.SECONDS_PER_SLOT)
    publish_block(3)
    # captures are deferred off the import path: the next tick drains
    # the breach queue into the recorder
    for n in nodes.values():
        n.clock.set_time(10 + 5 * params.SECONDS_PER_SLOT)

    from lodestar_tpu.observability.slo import (
        OBJ_ATTESTATION_HEAD,
        OBJ_IMPORT_BOUNDARY,
    )

    for name, n in nodes.items():
        assert n.slo.breach_count(OBJ_IMPORT_BOUNDARY) == 1, name
        assert n.slo.breach_count(OBJ_ATTESTATION_HEAD) == 1, name
        assert n.slo.status()["status"] == "degraded", name
        # the healthy slots booked clean evaluations too
        assert n.slo.m_evaluations.get(OBJ_IMPORT_BOUNDARY) == 3, name

    # ONE end-to-end bundle on node-0 (the second breach of the same
    # anomaly is rate-limit suppressed — that is the recorder working)
    bundles = FR.list_bundles(recorder.directory)
    assert len(bundles) == 1, bundles
    assert bundles[0]["reason"].startswith("slo.")
    loaded = FR.load_bundle(bundles[0]["path"])
    files = loaded["files"]
    # the capture spans the whole node: trace ring, time-series window,
    # metrics exposition, pipeline flush stats, peer scores, head
    assert isinstance(files["trace.json"]["traceEvents"], list)
    assert len(files["timeseries.json"]) >= 1
    assert "lodestar_slo_breaches_total" in files["metrics.txt"]
    assert isinstance(files["flush_stats.json"], list)
    assert isinstance(files["scoring.json"], dict)
    assert files["head.json"]["head_slot"] >= 2
    assert files["slo.json"]["status"] == "degraded"
    for n in nodes.values():
        n.close()
