"""ValidatorMonitor: tracked-validator performance from imported blocks.

Reference behaviors: packages/beacon-node/src/metrics/
validatorMonitor.ts:1-558 (registration, attestation-in-block
accounting, proposals, sync participation, historic-window pruning,
missed-duty accounting at epoch close).
"""

import pytest

from lodestar_tpu import params
from lodestar_tpu.utils.validator_monitor import (
    HISTORIC_EPOCHS,
    ValidatorMonitor,
)

pytestmark = pytest.mark.smoke


def _indexed(indices, slot=1, root=b"\x01" * 32):
    return {
        "attesting_indices": list(indices),
        "data": {
            "slot": slot,
            "index": 0,
            "beacon_block_root": root,
            "source": {"epoch": 0, "root": b"\x00" * 32},
            "target": {"epoch": slot // params.SLOTS_PER_EPOCH, "root": root},
        },
        "signature": b"\x00" * 96,
    }


def test_attestation_accounting_tracked_only():
    mon = ValidatorMonitor()
    mon.register_local_validator(3)
    mon.register_local_validator(5)
    # indices 3 (tracked) and 9 (untracked) attest at slot 1, included at 2
    mon.register_attestation_in_block(
        _indexed([3, 9], slot=1), parent_slot=1, correct_head=True
    )
    s = mon.summary_dict(3, 0)
    assert s["attestations_included"] == 1
    assert s["attestation_min_delay_slots"] == 1
    assert s["attestation_correct_head"] == 1
    assert mon.summary_dict(9, 0)["attestations_included"] == 0  # untracked
    assert mon.summary_dict(5, 0)["attestations_included"] == 0
    assert mon.m_attestations.value == 1
    # a later, worse inclusion does not overwrite the best delay
    mon.register_attestation_in_block(
        _indexed([3], slot=1), parent_slot=4, correct_head=False
    )
    assert mon.summary_dict(3, 0)["attestation_min_delay_slots"] == 1
    assert mon.summary_dict(3, 0)["attestations_included"] == 2


def test_blocks_and_sync_signals():
    mon = ValidatorMonitor()
    mon.register_local_validator(7)
    mon.register_beacon_block(7, slot=5)
    mon.register_beacon_block(8, slot=5)  # untracked
    assert mon.summary_dict(7, 0)["blocks_proposed"] == 1
    assert mon.m_blocks.value == 1
    mon.register_local_validator_in_sync_committee(7, until_epoch=10)
    mon.register_sync_aggregate_in_block(0, [7, 8])
    assert mon.summary_dict(7, 0)["sync_signals_included"] == 1
    assert mon.m_sync_signals.value == 1


def test_epoch_close_accounts_missed():
    mon = ValidatorMonitor()
    mon.register_local_validator(1)
    mon.register_local_validator(2)
    mon.register_attestation_in_block(
        _indexed([1], slot=1), parent_slot=1, correct_head=True
    )
    summaries = mon.on_epoch_close(0)
    assert {s["index"]: s["attestations_included"] for s in summaries} == {
        1: 1,
        2: 0,
    }
    assert mon.m_missed.value == 1  # validator 2 missed epoch 0


def test_historic_window_pruned():
    mon = ValidatorMonitor()
    mon.register_local_validator(1)
    for epoch in range(HISTORIC_EPOCHS + 3):
        mon.register_attestation_in_block(
            _indexed([1], slot=epoch * params.SLOTS_PER_EPOCH + 1),
            parent_slot=epoch * params.SLOTS_PER_EPOCH + 1,
            correct_head=True,
        )
    v = mon._validators[1]
    assert len(v.summaries) <= HISTORIC_EPOCHS


def test_chain_feeds_monitor_on_import():
    """End-to-end: a real imported block with attestations + sync
    aggregate lands in the monitor (reference: imported data, not the
    validator client's submissions)."""
    from lodestar_tpu.chain.chain import BeaconChain
    from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
    from lodestar_tpu.crypto import bls as B
    from lodestar_tpu.crypto import curves as C
    from lodestar_tpu.params import ForkName
    from lodestar_tpu.state_transition import create_genesis_state
    from lodestar_tpu.state_transition.accessors import (
        get_beacon_committee,
        get_beacon_proposer_index,
    )
    from lodestar_tpu.state_transition.slot import process_slots
    from lodestar_tpu.validator import ValidatorStore

    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG, fork_epochs={ForkName.altair: 0}
    )
    sks = [B.keygen(b"vm-%d" % i) for i in range(32)]
    pks = [C.g1_compress(B.sk_to_pk(sk)) for sk in sks]
    genesis = create_genesis_state(cfg, pks, genesis_time=2)
    mon = ValidatorMonitor()
    for i in range(32):
        mon.register_local_validator(i)
    chain = BeaconChain(cfg, genesis, monitor=mon)
    store = ValidatorStore(cfg, dict(enumerate(sks)))

    def propose(slot):
        st = genesis.clone()
        process_slots(st, slot)
        proposer = get_beacon_proposer_index(st)
        block = chain.produce_block(slot, store.sign_randao(proposer, slot))
        signed = {
            "message": block,
            "signature": store.sign_block(proposer, block),
        }
        chain.process_block(signed)
        return proposer

    p1 = propose(1)
    assert mon.summary_dict(p1, 0)["blocks_proposed"] >= 1

    # attest at slot 1 (full-committee aggregate into the block pool),
    # then import a slot-2 block carrying it
    committee = get_beacon_committee(chain.head_state, 1, 0)
    data = chain.produce_attestation_data(0, 1)
    sigs = [
        C.g2_decompress(store.sign_attestation(int(v), data))
        for v in committee
    ]
    chain.add_aggregate(
        {
            "aggregation_bits": [True] * len(committee),
            "data": data,
            "signature": C.g2_compress(B.aggregate_signatures(sigs)),
        }
    )
    propose(2)
    attester = int(committee[0])
    s = mon.summary_dict(attester, 0)
    assert s["attestations_included"] >= 1
    assert s["attestation_min_delay_slots"] == 1
    assert s["attestation_correct_head"] >= 1
