"""kernels/curve.py (jacobian group law, scalar muls, psi test) vs crypto/."""

import random

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax

from lodestar_tpu.crypto import curves as GC
from lodestar_tpu.crypto import fields as GT
from lodestar_tpu.crypto import hash_to_curve as GH
from lodestar_tpu.kernels import curve as CV
from lodestar_tpu.kernels import layout as LY

pytestmark = pytest.mark.smoke

random.seed(0xCAFE)
P = LY.P


def enc1(xs):
    return jnp.asarray(LY.encode_batch(xs))


def enc2(vals):
    return (
        jnp.asarray(LY.encode_batch([v[0] for v in vals])),
        jnp.asarray(LY.encode_batch([v[1] for v in vals])),
    )


def enc_g1_aff(pts):
    return (enc1([p[0] for p in pts]), enc1([p[1] for p in pts]))


def enc_g2_aff(pts):
    return (enc2([p[0] for p in pts]), enc2([p[1] for p in pts]))


def dec1(t):
    return LY.decode_batch(np.asarray(t))


def dec2(t):
    return list(zip(dec1(t[0]), dec1(t[1])))


def jac_to_affine_g1(X, Y, Z, inf):
    out = []
    for x, y, z, i in zip(dec1(X), dec1(Y), dec1(Z), np.asarray(inf)):
        if i:
            out.append(None)
            continue
        zi = pow(z, P - 2, P)
        out.append((x * zi * zi % P, y * zi * zi * zi % P))
    return out


def jac_to_affine_g2(X, Y, Z, inf):
    out = []
    for x, y, z, i in zip(dec2(X), dec2(Y), dec2(Z), np.asarray(inf)):
        if i:
            out.append(None)
            continue
        zi = GT.fp2_inv(z)
        z2 = GT.fp2_mul(zi, zi)
        out.append((GT.fp2_mul(x, z2), GT.fp2_mul(y, GT.fp2_mul(z2, zi))))
    return out


def rand_g1(n):
    return [
        GC.scalar_mul(GC.FP_OPS, GC.G1_GEN, random.randrange(2, GT.R))
        for _ in range(n)
    ]


def rand_g2(n):
    return [
        GC.scalar_mul(GC.FP2_OPS, GC.G2_GEN, random.randrange(2, GT.R))
        for _ in range(n)
    ]


def test_add_full_edge_cases():
    """Generic, doubling, inverse, and infinity cases in one batch."""
    a, b = rand_g1(2)
    na = GC.affine_neg(GC.FP_OPS, a)
    # lanes: a+b, a+a, a+(-a), O+b, a+O, O+O
    ps = [a, a, a, a, a, a]
    qs = [b, a, na, b, b, b]
    p_inf = jnp.asarray([False, False, False, True, False, True])
    q_inf = jnp.asarray([False, False, False, False, True, True])
    px, py = enc_g1_aff(ps)
    qx, qy = enc_g1_aff(qs)
    one = CV._one_plane_like(CV.FP_OPS, px)

    @jax.jit
    def f(px, py, qx, qy, p_inf, q_inf):
        return CV.jac_add_full(
            CV.FP_OPS, (px, py, one), p_inf, (qx, qy, one), q_inf
        )

    (X, Y, Z), inf = f(px, py, qx, qy, p_inf, q_inf)
    got = jac_to_affine_g1(X, Y, Z, inf)
    want = [
        GC.affine_add(GC.FP_OPS, a, b),
        GC.affine_dbl(GC.FP_OPS, a),
        None,
        b,
        a,
        None,
    ]
    assert got == want


def _bit_planes(scalars, nbits=64):
    out = np.zeros((nbits, len(scalars)), np.int32)
    for i in range(nbits):
        out[nbits - 1 - i] = [(s >> i) & 1 for s in scalars]
    return jnp.asarray(out)


def test_scalar_mul_bits_g1_g2():
    n = 4
    g1s, g2s = rand_g1(n), rand_g2(n)
    ks = [random.getrandbits(63) * 2 + 1 for _ in range(n - 1)] + [0]
    bits = _bit_planes(ks)
    px, py = enc_g1_aff(g1s)
    qx, qy = enc_g2_aff(g2s)
    one1 = CV._one_plane_like(CV.FP_OPS, px)
    one2 = CV._one_plane_like(CV.FP2_OPS, qx)
    inf0 = jnp.zeros((n,), bool)

    @jax.jit
    def f(px, py, qx, qy, bits):
        gb = lambda i: lax.dynamic_index_in_dim(bits, i, 0, keepdims=False)
        r1 = CV.scalar_mul_bits_jac(CV.FP_OPS, (px, py, one1), inf0, gb, 64)
        r2 = CV.scalar_mul_bits_jac(CV.FP2_OPS, (qx, qy, one2), inf0, gb, 64)
        return r1, r2

    ((X1, Y1, Z1), i1), ((X2, Y2, Z2), i2) = f(px, py, qx, qy, bits)
    got1 = jac_to_affine_g1(X1, Y1, Z1, i1)
    got2 = jac_to_affine_g2(X2, Y2, Z2, i2)
    assert got1 == [GC.scalar_mul(GC.FP_OPS, p, k) for p, k in zip(g1s, ks)]
    assert got2 == [GC.scalar_mul(GC.FP2_OPS, q, k) for q, k in zip(g2s, ks)]


def test_scalar_mul_bits_jacobian_base():
    """Aggregate-style base: Z != 1 (the doubled representation)."""
    n = 2
    g1s = rand_g1(n)
    ks = [random.getrandbits(63) * 2 + 1 for _ in range(n)]
    bits = _bit_planes(ks)
    # encode P as (X, Y, Z) = (x*4, y*8, 2) — same point, Z=2
    two = enc1([2] * n)
    px = enc1([p[0] * 4 % P for p in g1s])
    py = enc1([p[1] * 8 % P for p in g1s])
    inf0 = jnp.zeros((n,), bool)

    @jax.jit
    def f(px, py, two, bits):
        gb = lambda i: lax.dynamic_index_in_dim(bits, i, 0, keepdims=False)
        return CV.scalar_mul_bits_jac(CV.FP_OPS, (px, py, two), inf0, gb, 64)

    (X, Y, Z), inf = f(px, py, two, bits)
    got = jac_to_affine_g1(X, Y, Z, inf)
    assert got == [GC.scalar_mul(GC.FP_OPS, p, k) for p, k in zip(g1s, ks)]


def test_scalar_mul_static():
    n = 3
    g2s = rand_g2(n)
    k = -GT.X_PARAM
    qx, qy = enc_g2_aff(g2s)

    @jax.jit
    def f(qx, qy):
        return CV.scalar_mul_static(CV.FP2_OPS, (qx, qy), k)

    X, Y, Z = f(qx, qy)
    got = jac_to_affine_g2(X, Y, Z, np.zeros(n, bool))
    assert got == [GC.scalar_mul(GC.FP2_OPS, q, k) for q in g2s]


def test_g2_subgroup_check():
    good = rand_g2(3)
    # on-curve but (overwhelmingly likely) outside the r-subgroup:
    # SvdW-mapped curve points before cofactor clearing
    bad = [
        GH.map_to_curve_svdw(GC.FP2_OPS, GH.hash_to_field_fp2(b"x%d" % i, 1, b"T")[0])
        for i in range(3)
    ]
    for b in bad:
        assert GC.is_on_curve(GC.FP2_OPS, b) and not GC.g2_subgroup_check(b)
    pts = good + bad
    qx, qy = enc_g2_aff(pts)
    inf = jnp.zeros((6,), bool)

    @jax.jit
    def f(qx, qy, inf):
        return CV.g2_subgroup_check((qx, qy), inf)

    got = list(np.asarray(f(qx, qy, inf)))
    assert got == [True] * 3 + [False] * 3


def test_sum_points_axis0_and_lanes():
    k, n = 5, 4
    pts = [rand_g1(n) for _ in range(k)]
    rng = np.random.default_rng(5)
    mask = rng.random((k, n)) < 0.7
    mask[0, :] = True
    xs = jnp.stack([enc1([p[0] for p in row]) for row in pts])
    ys = jnp.stack([enc1([p[1] for p in row]) for row in pts])
    ones = jnp.broadcast_to(
        CV._one_plane_like(CV.FP_OPS, xs[0]), xs.shape
    )
    inf = jnp.asarray(~mask)

    @jax.jit
    def f(xs, ys, ones, inf):
        return CV.sum_points_axis0(CV.FP_OPS, (xs, ys, ones), inf)

    (X, Y, Z), oinf = f(xs, ys, ones, inf)
    got = jac_to_affine_g1(X, Y, Z, oinf)
    want = [
        GC.multi_add(GC.FP_OPS, [pts[i][j] for i in range(k) if mask[i, j]])
        for j in range(n)
    ]
    assert got == want

    # lane-axis sum of one row
    row = pts[0]
    x0, y0 = enc1([p[0] for p in row]), enc1([p[1] for p in row])
    one = CV._one_plane_like(CV.FP_OPS, x0)

    @jax.jit
    def g(x0, y0, one):
        return CV.sum_points_lanes(
            CV.FP_OPS, (x0, y0, one), jnp.zeros((n,), bool)
        )

    (X, Y, Z), oinf = g(x0, y0, one)
    got = jac_to_affine_g1(X, Y, Z, oinf)[0]
    assert got == GC.multi_add(GC.FP_OPS, row)
