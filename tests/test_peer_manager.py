"""PeerManager lifecycle: handshake, heartbeat, pruning, ping cadence.

Reference behaviors: packages/beacon-node/src/network/peers/
peerManager.ts (heartbeat loop, ping/status timeouts, goodbye reasons)
and utils/prioritizePeers.ts (excess pruning, duty-peer protection).
"""

import pytest

from lodestar_tpu import params
from lodestar_tpu import types as T
from lodestar_tpu.network.peer_manager import (
    GOODBYE_BANNED,
    GOODBYE_TOO_MANY_PEERS,
    PeerManager,
    prioritize_peers,
)
from lodestar_tpu.network.peers import PeerAction, PeerScoreBook
from lodestar_tpu.network.reqresp import ReqResp, connect_inmemory
from lodestar_tpu.network.reqresp_protocols import (
    METADATA_TYPE,
    ReqRespBeaconNode,
)

pytestmark = pytest.mark.smoke


def test_prioritize_peers_below_target():
    n, drop = prioritize_peers([("a", 0.0, [])], [], target_peers=5, max_peers=8)
    assert n == 4 and drop == []


def test_prioritize_peers_prunes_worst_but_protects_subnet_providers():
    connected = [
        ("good", 5.0, []),
        ("bad", -20.0, []),
        ("provider", -30.0, [7]),  # worst score BUT serves subnet 7
        ("mid", -1.0, []),
    ]
    n, drop = prioritize_peers(connected, [7], target_peers=2, max_peers=3)
    assert n == 0
    assert drop == ["bad", "mid"]  # worst unprotected first; provider kept


class _World:
    """A server node + a factory of client peers over in-memory wires."""

    def __init__(self):
        from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
        from lodestar_tpu.params import ForkName

        self.cfg = create_chain_config(
            MAINNET_CHAIN_CONFIG, fork_epochs={ForkName.altair: 0}
        )

        class _St:
            slot = 3
            finalized_checkpoint = {"epoch": 0, "root": b"\x00" * 32}

        class _Chain:
            config = self.cfg
            head_state = _St()

            def get_head_root(self):
                return b"\x07" * 32

        self.now = [1000.0]
        self.md_seq = [5]
        self.server = ReqResp(clock=lambda: self.now[0])
        self.node = ReqRespBeaconNode(
            self.server,
            self.cfg,
            chain=_Chain(),
            metadata_fn=lambda: {
                "seq_number": self.md_seq[0],
                "attnets": [i == 7 for i in range(params.ATTESTATION_SUBNET_COUNT)],
                "syncnets": [False] * params.SYNC_COMMITTEE_SUBNET_COUNT,
            },
        )

    def make_peer(self, name):
        """A remote peer node; returns (send_fn_for_manager, its ReqResp)."""
        remote = ReqResp(clock=lambda: self.now[0])
        ReqRespBeaconNode(
            remote,
            self.cfg,
            chain=self.node.chain,
            metadata_fn=self.node.metadata_fn,
        )
        # manager-side transport into the remote; remote can answer back
        remote.connect(
            "manager", lambda pid, req: self.server.handle_request(name, pid, req)
        )
        return (
            lambda pid, req: remote.handle_request("manager", pid, req),
            remote,
        )


def test_handshake_heartbeat_and_pruning():
    w = _World()
    book = PeerScoreBook(clock=lambda: w.now[0])
    candidates = {}
    for name in ("p1", "p2", "p3", "p4"):
        send, _remote = w.make_peer(name)
        candidates[name] = send

    def discover(n):
        # a discovery source yields a candidate stream; the manager
        # filters (connected/banned) and dials until satisfied
        return [
            (name, lambda s=send: s)
            for name, send in candidates.items()
        ]

    mgr = PeerManager(
        w.node,
        score_book=book,
        target_peers=3,
        max_peers=4,
        discover=discover,
        clock=lambda: w.now[0],
    )
    # heartbeat dials up to target
    actions = mgr.heartbeat()
    assert actions["dialed"] == 3
    assert len(mgr.connected_peers) == 3
    # the handshake recorded status + fetched metadata (seq 5 > -1)
    p = mgr.peers[mgr.connected_peers[0]]
    assert book.status_of(mgr.connected_peers[0]).head_slot == 3
    assert p.metadata is not None and int(p.metadata["seq_number"]) == 5

    # a banned peer is dropped on the next heartbeat
    banned = mgr.connected_peers[0]
    book.apply_action(banned, PeerAction.fatal)
    actions = mgr.heartbeat()
    assert banned in actions["banned"]
    assert banned not in mgr.peers
    # ...and the heartbeat refilled toward target from candidates
    assert len(mgr.connected_peers) == 3

    # over-target pruning drops the worst score
    extra = [n for n in candidates if n not in mgr.peers][0]
    mgr.on_connect(extra, "inbound", candidates[extra])
    mgr.target_peers = 2
    worst = mgr.connected_peers[0]
    book.add(worst, -5.0)  # worst, but still above the disconnect gate
    actions = mgr.heartbeat()
    assert worst in actions["pruned"]
    assert len(mgr.connected_peers) == 2


def test_ping_seq_bump_triggers_metadata_refetch():
    w = _World()
    send, _remote = w.make_peer("px")
    mgr = PeerManager(w.node, clock=lambda: w.now[0])
    mgr.on_connect("px", "outbound", send)
    assert int(mgr.peers["px"].metadata["seq_number"]) == 5
    # bump the remote's metadata seq; cadence re-ping sees it
    w.md_seq[0] = 6
    w.now[0] += 25.0  # past PING_INTERVAL_OUTBOUND_S
    mgr.ping_and_status_timeouts()
    assert int(mgr.peers["px"].metadata["seq_number"]) == 6


def test_close_sends_goodbyes():
    from lodestar_tpu.network.peer_manager import GOODBYE_CLIENT_SHUTDOWN

    w = _World()
    send, remote = w.make_peer("pz")
    # intercept the goodbye on the REMOTE node's handler
    seen = []
    gp = "/eth2/beacon_chain/req/goodbye/1/ssz_snappy"
    orig = remote._handlers[gp]
    remote._handlers[gp] = lambda peer, reason: (
        seen.append(int(reason)),
        orig(peer, reason),
    )[1]
    mgr = PeerManager(w.node, clock=lambda: w.now[0])
    mgr.on_connect("pz", "outbound", send)
    mgr.close()
    assert mgr.connected_peers == []
    assert seen == [GOODBYE_CLIENT_SHUTDOWN]


def test_remote_goodbye_forgets_without_reply():
    """forget() drops a remote-goodbyed peer without sending a goodbye
    back (the remote already left)."""
    w = _World()
    send, remote = w.make_peer("pq")
    mgr = PeerManager(w.node, clock=lambda: w.now[0])
    mgr.on_connect("pq", "outbound", send)
    sent = []
    gp = "/eth2/beacon_chain/req/goodbye/1/ssz_snappy"
    remote._handlers[gp] = lambda peer, reason: (
        sent.append(reason), [(b"\x00" * 8, None)])[1]
    mgr.forget("pq")
    assert mgr.connected_peers == []
    assert sent == []  # no goodbye traveled


def test_max_peers_hard_cap():
    # inbound connections beyond max_peers are refused outright
    w = _World()
    sends = {n: w.make_peer(n)[0] for n in ("q1", "q2", "q3")}
    mgr = PeerManager(
        w.node, target_peers=2, max_peers=2, clock=lambda: w.now[0]
    )
    mgr.on_connect("q1", "inbound", sends["q1"])
    mgr.on_connect("q2", "inbound", sends["q2"])
    mgr.on_connect("q3", "inbound", sends["q3"])  # over the hard cap
    assert "q3" not in mgr.peers
    assert len(mgr.connected_peers) == 2


def test_prioritize_hard_cap_overrides_protection():
    # each peer is the SOLE best provider of a needed subnet, so normal
    # excess pruning finds no unprotected candidates — only the max_peers
    # hard-cap branch can bring the count down, and it drops the
    # worst-scored protected peer
    connected = [
        ("a", 5.0, [1]),
        ("b", 4.0, [2]),
        ("c", 3.0, [3]),
    ]
    n, drop = prioritize_peers(
        connected, [1, 2, 3], target_peers=1, max_peers=2
    )
    assert n == 0
    assert drop == ["c"]  # worst-scored goes despite protection
    # without the cap pressure nothing is dropped (all protected)
    n2, drop2 = prioritize_peers(
        connected, [1, 2, 3], target_peers=1, max_peers=3
    )
    assert drop2 == []
