"""Slot-anchored SLO engine + flight recorder (ISSUE 12 acceptance).

Fast stub tests of the tentpole contract:

  - a replayed HEALTHY slot sequence produces zero breaches (no false
    positives) while every objective still evaluates,
  - an induced late-import + backpressure-trip scenario produces the
    correct breach counters AND a loadable flight-record bundle
    (Chrome-trace JSON parses, time-series window non-empty),
  - per-slot SLO evaluation + time-series sampling stay under 1 ms,
  - the recorder honors its rate limit and on-disk caps under a
    breach storm,
  - the health surface: SloEngine.status(), breach_snapshot(), the
    GET /eth/v1/lodestar/health handler, and the CLI subcommands.
"""

import json
import threading
import time

import pytest

from lodestar_tpu import params
from lodestar_tpu.chain.clock import Clock
from lodestar_tpu.observability import flight_recorder as FR
from lodestar_tpu.observability.slo import (
    ALL_OBJECTIVES,
    OBJ_AGGREGATE_INPUTS,
    OBJ_ATTESTATION_HEAD,
    OBJ_COMPILE_STALL,
    OBJ_CRITICAL_P99,
    OBJ_IMPORT_BOUNDARY,
    SloEngine,
    breach_snapshot,
)
from lodestar_tpu.observability.timeseries import (
    MetricsSampler,
    TimeSeriesRing,
    histogram_totals,
)
from lodestar_tpu.utils.metrics import Registry

pytestmark = pytest.mark.smoke

SPS = params.SECONDS_PER_SLOT


class PipelineStub:
    """flush_stats()-shaped record feed (bls/pipeline.py)."""

    def __init__(self):
        self.records = []
        self._seq = 0

    def add(self, lane, oldest_wait_s):
        self._seq += 1
        self.records.append(
            {
                "seq": self._seq,
                "lane": lane,
                "reason": "deadline",
                "sets": 1,
                "n_bucket": 128,
                "fill_ratio": 1 / 128,
                "oldest_wait_s": oldest_wait_s,
            }
        )

    def flush_stats(self):
        return list(self.records)


def make_engine(tmp_path=None, pipeline=None, recorder_kwargs=None, **kw):
    clock = Clock(genesis_time=0.0)
    registry = Registry()
    recorder = None
    if tmp_path is not None:
        recorder = FR.FlightRecorder(
            str(tmp_path / "flightrec"),
            registry=registry,
            **(recorder_kwargs or {"min_interval_s": 0.0}),
        )
    ring = TimeSeriesRing()
    if recorder is not None:
        recorder.timeseries = ring
    sampler = MetricsSampler(ring)
    state = {"gauge": 0.0}
    sampler.add_gauge("pending_sets", lambda: state["gauge"])
    sampler.add_delta("drops", lambda: state.get("drops", 0.0))
    engine = SloEngine(
        clock,
        registry=registry,
        recorder=recorder,
        sampler=sampler,
        pipeline=pipeline,
        **kw,
    )
    clock.on_slot(engine.on_slot)
    return clock, engine, recorder, ring, state


def drive_healthy_slot(clock, engine, slot, pipeline=None):
    """Advance into `slot`, then replay its events at healthy phase
    offsets (import at 0.2 slot, first attestation at 0.45 slot)."""
    start = clock.slot_start(slot)
    clock.set_time(start)
    engine.on_attestation(slot, t=start + 0.45 * SPS)
    engine.on_block_imported(slot, t=start + 0.2 * SPS)
    if pipeline is not None:
        pipeline.add("critical", 0.010)  # inside the 40 ms budget


# ---------------------------------------------------------------------------
# acceptance: healthy sequence -> zero breaches; induced anomaly -> breaches
# ---------------------------------------------------------------------------


def test_healthy_slot_sequence_produces_zero_breaches(tmp_path):
    pipeline = PipelineStub()
    clock, engine, recorder, ring, _ = make_engine(tmp_path, pipeline)
    n_slots = 8
    for slot in range(1, n_slots + 1):
        drive_healthy_slot(clock, engine, slot, pipeline)
    clock.set_time(clock.slot_start(n_slots + 1))  # close the last slot
    for obj in ALL_OBJECTIVES:
        assert engine.breach_count(obj) == 0, engine.status()
    st = engine.status()
    assert st["status"] == "ok"
    assert st["last_breach_slot"] == -1
    # every objective actually evaluated (no vacuous pass)
    assert st["objectives"][OBJ_ATTESTATION_HEAD]["evaluations"] == n_slots
    assert st["objectives"][OBJ_IMPORT_BOUNDARY]["evaluations"] == n_slots
    assert st["objectives"][OBJ_AGGREGATE_INPUTS]["evaluations"] == n_slots
    assert st["objectives"][OBJ_CRITICAL_P99]["evaluations"] == n_slots
    # compile-stall needs one baseline read before it can evaluate
    assert st["objectives"][OBJ_COMPILE_STALL]["evaluations"] >= n_slots - 1
    # no anomaly: nothing was captured
    assert FR.list_bundles(recorder.directory) == []
    # the per-slot sampler filled the ring (one row per tick; the
    # first set_time also emits slot 0)
    assert len(ring) == n_slots + 2


def test_late_import_and_backpressure_trip_breach_and_bundle(tmp_path):
    from lodestar_tpu import observability as OB
    from lodestar_tpu.network.processor import (
        NetworkProcessor,
        PendingGossipMessage,
    )
    from lodestar_tpu.network.gossip_queues import GossipType

    OB.configure(enabled=True)
    OB.get_tracer().clear()
    try:
        pipeline = PipelineStub()
        clock, engine, recorder, ring, _ = make_engine(tmp_path, pipeline)
        with OB.trace_span("test.import", slot=2):
            pass  # something in the ring for the bundle's trace.json
        drive_healthy_slot(clock, engine, 1, pipeline)
        # slot 2's block limps in 1.2 slots late: both import-side
        # objectives breach the moment the import completes
        start2 = clock.slot_start(2)
        clock.set_time(start2)
        engine.on_attestation(2, t=start2 + 0.4 * SPS)
        clock.set_time(clock.slot_start(3) + 0.2 * SPS)
        engine.on_block_imported(2)  # t = clock.now, past the boundary
        assert engine.breach_count(OBJ_ATTESTATION_HEAD) == 1
        assert engine.breach_count(OBJ_IMPORT_BOUNDARY) == 1
        assert engine.breach_count(OBJ_AGGREGATE_INPUTS) == 0

        # backpressure trip: the processor's edge-triggered hook fires
        # ONCE per slot while downstream reports saturation
        proc = NetworkProcessor(
            lambda msg: None, [lambda: False], registry=Registry()
        )
        proc.on_backpressure_trip = lambda slot: engine.anomaly(
            "backpressure_trip", {"slot": slot}
        )
        for _ in range(3):
            proc.on_gossip_message(
                PendingGossipMessage(GossipType.beacon_attestation, b"x")
            )
        assert engine.m_anomalies.get("backpressure_trip") == 1.0
        proc.on_clock_slot(5)  # re-arms the edge trigger
        proc.on_gossip_message(
            PendingGossipMessage(GossipType.beacon_attestation, b"x")
        )
        assert engine.m_anomalies.get("backpressure_trip") == 2.0

        # captures are DEFERRED off the import/gossip paths: nothing on
        # disk until the next clock tick drains the queue
        assert FR.list_bundles(recorder.directory) == []
        clock.set_time(clock.slot_start(4))

        # the bundles: breaches + anomalies each captured one
        bundles = FR.list_bundles(recorder.directory)
        reasons = [b["reason"] for b in bundles]
        assert f"slo.{OBJ_ATTESTATION_HEAD}" in reasons
        assert f"slo.{OBJ_IMPORT_BOUNDARY}" in reasons
        assert "event.backpressure_trip" in reasons
        # loadable: the Chrome trace parses, the time-series window is
        # non-empty, the manifest names every file
        loaded = FR.load_bundle(bundles[-1]["path"])
        trace = loaded["files"]["trace.json"]
        assert isinstance(trace["traceEvents"], list)
        assert any(
            e["name"] == "test.import" for e in trace["traceEvents"]
        )
        ts = loaded["files"]["timeseries.json"]
        assert len(ts) >= 1 and "t" in ts[0] and "pending_sets" in ts[0]
        assert set(loaded["manifest"]["files"]) == set(loaded["files"])
        assert loaded["manifest"]["schema"] == FR.SCHEMA
    finally:
        OB.configure(enabled=False)
        OB.get_tracer().clear()


def test_status_degrades_then_recovers(tmp_path):
    clock, engine, _rec, _ring, _ = make_engine()
    clock.set_time(clock.slot_start(2) + 1.5 * SPS)  # slots 0..3 tick
    engine.on_block_imported(2)  # late -> breach
    assert engine.status()["status"] == "degraded"
    # one epoch of clean slots later the verdict recovers
    clock.set_time(clock.slot_start(2 + params.SLOTS_PER_EPOCH + 2))
    assert engine.status()["status"] == "ok"
    # the counters, unlike the verdict, never forget
    assert engine.breach_count(OBJ_IMPORT_BOUNDARY) == 1


# ---------------------------------------------------------------------------
# per-objective units
# ---------------------------------------------------------------------------


def test_critical_lane_p99_objective_is_seq_incremental():
    pipeline = PipelineStub()
    clock, engine, _rec, _ring, _ = make_engine(pipeline=pipeline)
    pipeline.add("critical", 0.200)  # way past the 40 ms budget
    pipeline.add("standard", 5.000)  # standard lane is NOT judged
    clock.set_time(clock.slot_start(1))
    clock.set_time(clock.slot_start(2))
    assert engine.breach_count(OBJ_CRITICAL_P99) == 1
    evals = engine.m_evaluations.get(OBJ_CRITICAL_P99)
    # no NEW flush records -> no new evaluation (seq cursor moved on)
    clock.set_time(clock.slot_start(3))
    assert engine.m_evaluations.get(OBJ_CRITICAL_P99) == evals
    pipeline.add("critical", 0.005)
    clock.set_time(clock.slot_start(4))
    assert engine.m_evaluations.get(OBJ_CRITICAL_P99) == evals + 1
    assert engine.breach_count(OBJ_CRITICAL_P99) == 1  # healthy flush


def test_compile_stall_objective(monkeypatch):
    from lodestar_tpu.observability import sinks

    compile_s = {"v": 0.0}
    monkeypatch.setattr(
        sinks,
        "kernel_compile_snapshot",
        lambda: {
            "ops_jit_compile_seconds": compile_s["v"],
            "export_trace_seconds": 0.0,
        },
    )
    clock, engine, _rec, _ring, _ = make_engine()
    clock.set_time(clock.slot_start(1))  # baseline read
    compile_s["v"] = 0.2  # under the 1 s threshold
    clock.set_time(clock.slot_start(2))
    assert engine.breach_count(OBJ_COMPILE_STALL) == 0
    compile_s["v"] = 2.5  # +2.3 s inside one slot: a stall
    clock.set_time(clock.slot_start(3))
    assert engine.breach_count(OBJ_COMPILE_STALL) == 1


def test_anomaly_watcher_fires_on_delta(tmp_path):
    clock, engine, recorder, _ring, _ = make_engine(tmp_path)
    dropped = {"v": 0.0}
    engine.add_watcher("queue_drop_burst", lambda: dropped["v"], threshold=64)
    clock.set_time(clock.slot_start(1))  # baseline
    dropped["v"] = 10.0  # small churn: no event
    clock.set_time(clock.slot_start(2))
    assert engine.m_anomalies.get("queue_drop_burst") == 0.0
    dropped["v"] = 200.0  # +190 in one slot: burst
    clock.set_time(clock.slot_start(3))
    assert engine.m_anomalies.get("queue_drop_burst") == 1.0
    reasons = [b["reason"] for b in FR.list_bundles(recorder.directory)]
    assert "event.queue_drop_burst" in reasons


def test_historical_sync_imports_are_skipped_not_breached():
    """Review fix: range-sync/backfill replay thousands of old blocks
    through the same import path; judging them against deadlines that
    expired hours ago would flood the counters with breaches that say
    nothing about the live pipeline."""
    clock, engine, _rec, _ring, _ = make_engine()
    clock.set_time(clock.slot_start(100))
    for slot in range(10, 60):  # a range-sync batch, all far behind
        engine.on_block_imported(slot)
    assert engine.m_evaluations.get(OBJ_IMPORT_BOUNDARY) == 0
    assert engine.breach_count(OBJ_IMPORT_BOUNDARY) == 0
    # the live edge still evaluates: head-1 and head are judged
    engine.on_block_imported(99, t=clock.slot_start(99) + 0.1 * SPS)
    engine.on_block_imported(100, t=clock.slot_start(100) + 0.1 * SPS)
    assert engine.m_evaluations.get(OBJ_IMPORT_BOUNDARY) == 2


def test_side_fork_reimport_is_judged_once():
    clock, engine, _rec, _ring, _ = make_engine()
    clock.set_time(clock.slot_start(1))
    engine.on_block_imported(1, t=clock.slot_start(1) + 0.1 * SPS)
    engine.on_block_imported(1, t=clock.slot_start(1) + 5.0 * SPS)  # late dup
    assert engine.m_evaluations.get(OBJ_IMPORT_BOUNDARY) == 1
    assert engine.breach_count(OBJ_IMPORT_BOUNDARY) == 0


def test_attestation_less_slot_is_skipped_not_breached():
    clock, engine, _rec, _ring, _ = make_engine()
    clock.set_time(clock.slot_start(1))
    clock.set_time(clock.slot_start(2))  # slot 1 had no attestations
    assert engine.m_evaluations.get(OBJ_AGGREGATE_INPUTS) == 0
    assert engine.breach_count(OBJ_AGGREGATE_INPUTS) == 0


# ---------------------------------------------------------------------------
# acceptance: bounded cost
# ---------------------------------------------------------------------------


def test_per_slot_evaluation_and_sampling_under_1ms():
    pipeline = PipelineStub()
    clock, engine, _rec, _ring, _ = make_engine(pipeline=pipeline)
    n = 500
    # warm the code paths once before timing
    drive_healthy_slot(clock, engine, 1, pipeline)
    t0 = time.perf_counter()
    for slot in range(2, n + 2):
        start = clock.slot_start(slot)
        clock.set_time(start)
        engine.on_attestation(slot, t=start + 0.4 * SPS)
        engine.on_block_imported(slot, t=start + 0.2 * SPS)
        if slot % 8 == 0:
            pipeline.add("critical", 0.01)
    per_slot = (time.perf_counter() - t0) / n
    assert per_slot < 1e-3, f"SLO tick cost {per_slot * 1e3:.3f} ms/slot"
    for obj in ALL_OBJECTIVES:
        assert engine.breach_count(obj) == 0


# ---------------------------------------------------------------------------
# acceptance: recorder bounds under a breach storm
# ---------------------------------------------------------------------------


def test_recorder_rate_limit_suppresses_storm(tmp_path):
    rec = FR.FlightRecorder(
        str(tmp_path / "fr"), min_interval_s=3600.0, registry=Registry()
    )
    first = rec.record("slo.import_before_boundary", {"slot": 1})
    assert first is not None
    for i in range(20):
        assert rec.record("slo.import_before_boundary", {"slot": i}) is None
    assert len(FR.list_bundles(rec.directory)) == 1
    assert rec.m_suppressed.value == 20
    assert rec.status()["suppressed"] == 20


def test_recorder_bundle_count_cap(tmp_path):
    rec = FR.FlightRecorder(
        str(tmp_path / "fr"),
        min_interval_s=0.0,
        max_bundles=3,
        registry=Registry(),
    )
    for i in range(9):
        assert rec.record(f"reason-{i}") is not None
    bundles = FR.list_bundles(rec.directory)
    assert len(bundles) == 3
    # oldest pruned, newest kept
    assert bundles[-1]["reason"] == "reason-8"
    assert bundles[0]["reason"] == "reason-6"


def test_recorder_byte_cap_keeps_newest(tmp_path):
    rec = FR.FlightRecorder(
        str(tmp_path / "fr"),
        min_interval_s=0.0,
        max_bundles=1000,
        max_total_bytes=20_000,
        registry=Registry(),
    )
    rec.add_provider("blob", lambda: {"pad": "x" * 8_000})
    for i in range(10):
        assert rec.record(f"big-{i}") is not None
    bundles = FR.list_bundles(rec.directory)
    total = sum(b["bytes"] for b in bundles)
    assert total <= 20_000 + 10_000  # cap + at most one newest bundle over
    assert len(bundles) < 10
    assert bundles[-1]["reason"] == "big-9"


def test_recorder_failed_write_releases_rate_limit_window(tmp_path, monkeypatch):
    """Review fix: a failed bundle write must not burn the whole
    rate-limit window — the next trigger retries, so a storm's first
    DIAGNOSTIC bundle is not lost to a transient disk error."""
    rec = FR.FlightRecorder(
        str(tmp_path / "fr"), min_interval_s=3600.0, registry=Registry()
    )
    real_makedirs = FR.os.makedirs
    boom = {"on": True}

    def flaky_makedirs(path, *a, **kw):
        if boom["on"] and "fr-" in str(path):
            raise OSError("disk full")
        return real_makedirs(path, *a, **kw)

    monkeypatch.setattr(FR.os, "makedirs", flaky_makedirs)
    assert rec.record("slo.breach") is None  # write failed
    boom["on"] = False
    assert rec.record("slo.breach") is not None  # window released: retry lands
    assert len(FR.list_bundles(rec.directory)) == 1
    # and the window is CLAIMED again after the success
    assert rec.record("slo.breach") is None
    assert rec.m_suppressed.value == 1


def test_recorder_status_is_ledger_backed(tmp_path):
    rec = FR.FlightRecorder(
        str(tmp_path / "fr"), min_interval_s=0.0, registry=Registry()
    )
    rec.record("a")
    rec.record("b")
    st = rec.status()
    assert st["bundles"] == 2
    assert st["total_bytes"] == sum(
        b["bytes"] for b in FR.list_bundles(rec.directory)
    )
    # a fresh recorder over the same directory rebuilds the ledger
    rec2 = FR.FlightRecorder(
        str(tmp_path / "fr"), min_interval_s=0.0, registry=Registry()
    )
    assert rec2.status()["bundles"] == 2


def test_recorder_provider_fault_is_captured_not_fatal(tmp_path):
    rec = FR.FlightRecorder(
        str(tmp_path / "fr"), min_interval_s=0.0, registry=Registry()
    )

    def broken():
        raise RuntimeError("provider died")

    rec.add_provider("broken", broken)
    rec.add_provider("text", lambda: "plain exposition\n")
    path = rec.record("anomaly")
    assert path is not None
    loaded = FR.load_bundle(path)
    assert "provider died" in loaded["files"]["broken.json"]["error"]
    assert loaded["files"]["text.txt"] == "plain exposition\n"


# ---------------------------------------------------------------------------
# surfaces: snapshot, REST handler, CLI
# ---------------------------------------------------------------------------


def test_breach_snapshot_reads_registry():
    clock, engine, _rec, _ring, _ = make_engine()
    assert breach_snapshot(engine.registry)["breaches"] == {}
    clock.set_time(clock.slot_start(3) + 1.5 * SPS)
    engine.on_block_imported(3)  # late
    snap = breach_snapshot(engine.registry)
    assert snap["breaches"][OBJ_IMPORT_BOUNDARY] == 1.0
    assert snap["breaches"][OBJ_ATTESTATION_HEAD] == 1.0
    assert snap["last_breach_slot"] == 3
    # a registry with no engine reads as zeros, same shape
    empty = breach_snapshot(Registry())
    assert empty == {
        "breaches": {},
        "evaluations": {},
        "anomaly_events": {},
        "last_breach_slot": -1,
    }


def test_health_handler_and_cli(tmp_path):
    from lodestar_tpu.api.server import BeaconApiServer, DefaultHandlers
    from lodestar_tpu.observability.__main__ import main as obs_main

    clock, engine, recorder, _ring, _ = make_engine(tmp_path)
    handlers = DefaultHandlers(slo=engine, flight_recorder=recorder)
    code, body = handlers.get_lodestar_health({}, None)
    assert code == 200
    assert body["data"]["status"] == "ok"
    assert set(body["data"]["objectives"]) == set(ALL_OBJECTIVES)
    assert body["data"]["flight_recorder"]["bundles"] == 0
    # without an engine the route answers 501 like other absent parts
    assert DefaultHandlers().get_lodestar_health({}, None)[0] == 501

    api = BeaconApiServer(handlers, port=0)
    api.listen()
    try:
        url = f"http://127.0.0.1:{api.port}"
        assert obs_main(["health", "--url", url]) == 0
        assert obs_main(["health", "--url", url, "--json"]) == 0
        # a breach inside the degraded window flips the exit code
        clock.set_time(clock.slot_start(2) + 1.5 * SPS)
        engine.on_block_imported(2)
        assert obs_main(["health", "--url", url]) == 1
    finally:
        api.close()
    assert obs_main(["health"]) == 2  # --url is required


def test_degraded_source_flips_status_and_recovers(tmp_path):
    """ISSUE 14: a live degraded source (the BLS breaker's is_open)
    reports `degraded` NOW and clears the moment the source does —
    unlike a breach, which lingers for the whole degraded window."""
    clock, engine, _rec, _ring, _ = make_engine(tmp_path)
    state = {"open": False}
    engine.add_degraded_source("bls_breaker", lambda: state["open"])
    st = engine.status()
    assert st["status"] == "ok"
    assert st["degraded_sources"] == {"bls_breaker": False}
    state["open"] = True
    st = engine.status()
    assert st["status"] == "degraded"
    assert st["degraded_sources"] == {"bls_breaker": True}
    assert st["last_breach_slot"] == -1  # no breach involved
    state["open"] = False
    assert engine.status()["status"] == "ok"  # immediate recovery

    # a raising source reads as not-degraded, never a crash
    def boom():
        raise RuntimeError("probe died")

    engine.add_degraded_source("dead_probe", boom)
    st = engine.status()
    assert st["degraded_sources"]["dead_probe"] is False
    assert st["status"] == "ok"


def test_health_handler_and_cli_report_breaker(tmp_path):
    """ISSUE 14 satellite: GET /eth/v1/lodestar/health reports
    `degraded` + the breaker block while the breaker is open, and the
    CLI exit code follows."""
    from lodestar_tpu.api.server import BeaconApiServer, DefaultHandlers
    from lodestar_tpu.bls.service import BlsVerifierService
    from lodestar_tpu.observability.__main__ import main as obs_main

    from chaos.harness import ChaosVerifier, FakeClock

    from lodestar_tpu.bls.supervisor import DeviceSupervisor
    from lodestar_tpu.utils.metrics import BlsPoolMetrics

    metrics = BlsPoolMetrics()
    sup = DeviceSupervisor(
        registry=metrics.registry,
        clock=FakeClock(),
        auto_probe=False,
        enabled=True,
    )
    verifier = ChaosVerifier(supervisor=sup, metrics=metrics)
    service = BlsVerifierService(verifier)
    clock, engine, _rec, _ring, _ = make_engine(tmp_path)
    engine.add_degraded_source("bls_breaker", sup.is_open)
    handlers = DefaultHandlers(slo=engine, bls_service=service)
    api = BeaconApiServer(handlers, port=0)
    api.listen()
    try:
        url = f"http://127.0.0.1:{api.port}"
        code, body = handlers.get_lodestar_health({}, None)
        assert code == 200
        assert body["data"]["status"] == "ok"
        assert body["data"]["breaker"]["state"] == "closed"
        assert obs_main(["health", "--url", url]) == 0

        sup.record_failure("error", "finish_job", "chaos")
        code, body = handlers.get_lodestar_health({}, None)
        assert body["data"]["status"] == "degraded"
        assert body["data"]["degraded_sources"]["bls_breaker"] is True
        assert body["data"]["breaker"]["state"] == "open"
        assert body["data"]["breaker"]["trips"] == 1
        # degraded -> exit 1 (both human and --json output paths)
        assert obs_main(["health", "--url", url]) == 1
        assert obs_main(["health", "--url", url, "--json"]) == 1
    finally:
        api.close()
        service.close()


def test_full_node_wires_breaker_into_slo_and_recorder(tmp_path):
    """node.py wiring: a FullBeaconNode with a supervised verifier gets
    the degraded source, the trip anomaly -> flight bundle, and the
    breaker provider — asserted end to end on a real node composition
    (no consensus driving needed)."""
    from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
    from lodestar_tpu.crypto import bls as B
    from lodestar_tpu.crypto import curves as C
    from lodestar_tpu.node import FullBeaconNode, NodeOptions
    from lodestar_tpu.params import ForkName
    from lodestar_tpu.state_transition import create_genesis_state

    from chaos.harness import ChaosVerifier, FakeClock

    from lodestar_tpu.bls.supervisor import DeviceSupervisor
    from lodestar_tpu.utils.metrics import BlsPoolMetrics

    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG,
        fork_epochs={ForkName.altair: 0},
        genesis_time=10,
    )
    sks = [B.keygen(b"wire-%d" % i) for i in range(4)]
    pks = [C.g1_compress(B.sk_to_pk(sk)) for sk in sks]
    genesis = create_genesis_state(cfg, pks, genesis_time=10)
    metrics = BlsPoolMetrics()
    sup = DeviceSupervisor(
        registry=metrics.registry,
        clock=FakeClock(),
        auto_probe=False,
        enabled=True,
    )
    verifier = ChaosVerifier(supervisor=sup, metrics=metrics)
    node = FullBeaconNode.init(
        cfg,
        genesis,
        NodeOptions(
            serve_api=False,
            verifier=verifier,
            flightrec_dir=str(tmp_path / "fr"),
        ),
    )
    try:
        assert node.slo is not None and node.flight_recorder is not None
        assert node.slo.status()["degraded_sources"] == {
            "bls_breaker": False,
            # the state-plane memory governor registers alongside the
            # breaker (ISSUE 15); no pressure episode is open here
            "state_memory": False,
        }
        # review fix: the production node arms the range-sync stall
        # deadline (a silent peer cannot wedge the sync worker)
        assert node.range_sync.download_timeout_s == 30.0
        sup.record_failure("error", "finish_job", "induced")
        assert node.slo.status()["status"] == "degraded"
        # the trip anomaly was parked; the next slot tick writes ONE
        # bundle carrying the breaker provider's status
        node.clock.set_time(10 + params.SECONDS_PER_SLOT)
        bundles = FR.list_bundles(node.flight_recorder.directory)
        assert len(bundles) == 1
        assert bundles[0]["reason"] == "event.bls_breaker_trip"
        loaded = FR.load_bundle(bundles[0]["path"])
        assert loaded["files"]["breaker.json"]["state"] == "open"
        assert (
            node.slo.m_anomalies.get("bls_breaker_trip") == 1
        )
    finally:
        node.close()


def test_flightrec_cli_lists_and_inspects(tmp_path, capsys):
    rec = FR.FlightRecorder(
        str(tmp_path / "fr"), min_interval_s=0.0, registry=Registry()
    )
    rec.timeseries = TimeSeriesRing()
    rec.timeseries.append(1.0, {"pending_sets": 3.0})
    path = rec.record("slo.import_before_boundary", {"slot": 7})
    from lodestar_tpu.observability.__main__ import main as obs_main

    assert obs_main(["flightrec", rec.directory]) == 0
    out = capsys.readouterr().out
    assert "slo.import_before_boundary" in out
    assert obs_main(["flightrec", path, "--json"]) == 0
    summary = json.loads(capsys.readouterr().out)
    assert summary["manifest"]["context"] == {"slot": 7}
    assert summary["timeseries_rows"] == 1
    assert obs_main(["flightrec", str(tmp_path / "empty")]) == 0


# ---------------------------------------------------------------------------
# time-series ring mechanics
# ---------------------------------------------------------------------------


def test_sampler_gauge_and_delta_sources():
    ring = TimeSeriesRing(capacity=4)
    sampler = MetricsSampler(ring)
    state = {"level": 5.0, "total": 100.0}
    sampler.add_gauge("level", lambda: state["level"])
    sampler.add_delta("total", lambda: state["total"])

    def broken():
        raise RuntimeError("source died")

    sampler.add_gauge("broken", broken)
    sampler.sample(1.0)
    state.update(level=7.0, total=130.0)
    sampler.sample(2.0)
    rows = ring.window()
    assert rows[0] == {"t": 1.0, "level": 5.0, "total": 0.0, "broken": None}
    assert rows[1] == {"t": 2.0, "level": 7.0, "total": 30.0, "broken": None}
    # capacity bound: the ring keeps the newest rows only
    for t in range(3, 9):
        sampler.sample(float(t))
    assert len(ring) == 4
    assert ring.window(since=7.0)[0]["t"] == 7.0
    assert ring.latest()["t"] == 8.0


def test_histogram_totals_helper():
    from lodestar_tpu.utils.metrics import Histogram, LabeledHistogram

    h = Histogram("lodestar_x_seconds", "x", [0.1, 1.0])
    h.observe(0.05)
    h.observe(0.5)
    assert histogram_totals(h) == (2.0, 0.55)
    lh = LabeledHistogram("lodestar_y_seconds", "y", "phase", [0.1])
    lh.observe("a", 0.2)
    lh.observe("b", 0.3)
    count, total = histogram_totals(lh)
    assert count == 2.0 and total == pytest.approx(0.5)
    assert histogram_totals(None) == (0.0, 0.0)


def test_timeseries_ring_concurrent_appends():
    ring = TimeSeriesRing(capacity=256)
    stop = threading.Event()

    def writer(k):
        i = 0
        while not stop.is_set():
            ring.append(float(i), {"w": float(k)})
            i += 1

    threads = [threading.Thread(target=writer, args=(k,)) for k in range(4)]
    for th in threads:
        th.start()
    try:
        for _ in range(200):
            rows = ring.window()
            assert len(rows) <= 256
            assert all("t" in r for r in rows)
    finally:
        stop.set()
        for th in threads:
            th.join(timeout=5)


def test_late_first_attestation_is_breached_not_skipped():
    """Review fix: a first attestation arriving AFTER the slot
    boundary is the worst 2/3-objective breach — it must be judged on
    arrival, not recorded as an empty-subnet skip."""
    clock, engine, _rec, _ring, _ = make_engine()
    clock.set_time(clock.slot_start(1))
    clock.set_time(clock.slot_start(2))  # slot 1's boundary: no data yet
    assert engine.m_evaluations.get(OBJ_AGGREGATE_INPUTS) == 0
    engine.on_attestation(1)  # lands mid-slot-2, way past 2/3 of slot 1
    assert engine.m_evaluations.get(OBJ_AGGREGATE_INPUTS) == 1
    assert engine.breach_count(OBJ_AGGREGATE_INPUTS) == 1
    # a SECOND late attestation for the same slot does not re-judge
    engine.on_attestation(1)
    assert engine.m_evaluations.get(OBJ_AGGREGATE_INPUTS) == 1


def test_p99_selects_worst_sample_for_small_n():
    """Review fix: nearest-rank p99 must include the maximum for small
    sample counts — one pathological flush per slot must trip the
    critical-lane objective."""
    pipeline = PipelineStub()
    clock, engine, _rec, _ring, _ = make_engine(pipeline=pipeline)
    pipeline.add("critical", 0.001)
    pipeline.add("critical", 0.500)  # 12x over budget — the worst one
    clock.set_time(clock.slot_start(1))
    clock.set_time(clock.slot_start(2))
    assert engine.breach_count(OBJ_CRITICAL_P99) == 1
