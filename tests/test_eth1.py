"""Eth1 deposit tracking + voting, wired into block production + STF.

Reference: packages/beacon-node/src/eth1/ — the tracker follows a mock
provider, builds the deposit tree, serves {eth1_data, deposits} whose
proofs must pass process_deposit's merkle branch check.
"""

import pytest

from lodestar_tpu import params
from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
from lodestar_tpu.crypto import bls as B
from lodestar_tpu.crypto import curves as C
from lodestar_tpu.eth1 import (
    DepositEvent,
    Eth1Block,
    Eth1DataCache,
    Eth1DepositDataTracker,
    get_eth1_vote,
)
from lodestar_tpu.eth1.deposit_tracker import (
    ETH1_FOLLOW_DISTANCE,
    SECONDS_PER_ETH1_BLOCK,
)
from lodestar_tpu.params import ForkName
from lodestar_tpu.state_transition import create_genesis_state
from lodestar_tpu.state_transition.block import (
    get_deposit_signing_root,
    process_deposit,
)

P = params.ACTIVE_PRESET


class MockProvider:
    def __init__(self, head: int, events):
        self.head = head
        self.events = list(events)

    def get_block_number(self):
        return self.head

    def get_block_by_number(self, number):
        return Eth1Block(
            block_number=number,
            block_hash=number.to_bytes(4, "big") * 8,
            timestamp=number * SECONDS_PER_ETH1_BLOCK,
        )

    def get_deposit_events(self, from_block, to_block):
        return [
            e for e in self.events if from_block <= e.block_number <= to_block
        ]


def _deposit_event(cfg, index, block_number, seed):
    sk = B.keygen(seed)
    pk = C.g1_compress(B.sk_to_pk(sk))
    data = {
        "pubkey": pk,
        "withdrawal_credentials": b"\x00" * 32,
        "amount": P.MAX_EFFECTIVE_BALANCE,
        "signature": b"\x00" * 96,
    }
    data["signature"] = B.sign_bytes(sk, get_deposit_signing_root(cfg, data))
    return DepositEvent(
        index=index,
        block_number=block_number,
        pubkey=pk,
        withdrawal_credentials=data["withdrawal_credentials"],
        amount=data["amount"],
        signature=data["signature"],
    )


@pytest.fixture(scope="module")
def tracker_world():
    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG, fork_epochs={ForkName.altair: 0}
    )
    events = [
        _deposit_event(cfg, i, 10 + i, b"eth1-dep-%d" % i) for i in range(3)
    ]
    provider = MockProvider(head=ETH1_FOLLOW_DISTANCE + 100, events=events)
    tracker = Eth1DepositDataTracker(provider)
    assert tracker.update() > 0
    return cfg, tracker, events


def test_tracker_ingests_deposits(tracker_world):
    cfg, tracker, events = tracker_world
    assert tracker.deposits.highest_index == 2
    # follow distance respected
    assert tracker.last_processed_block == 100
    # incremental update is a no-op without new blocks
    assert tracker.update() == 0


def test_deposit_proofs_pass_state_transition(tracker_world):
    cfg, tracker, events = tracker_world
    sks = [B.keygen(b"eth1-val-%d" % i) for i in range(4)]
    pks = [C.g1_compress(B.sk_to_pk(sk)) for sk in sks]
    state = create_genesis_state(cfg, pks, genesis_time=0, deposit_count=4)

    bundle = tracker.get_eth1_data_and_deposits(state)
    # genesis state voted nothing yet: current eth1_data has count 4,
    # beyond the tracker's events -> craft the effective data directly
    count = 3
    state.eth1_data = {
        "deposit_root": tracker.deposits.root_at_count(count),
        "deposit_count": count,
        "block_hash": b"\x22" * 32,
    }
    state.eth1_deposit_index = 0
    deposits = tracker.deposits.get_deposits(0, count)
    assert len(deposits) == 3
    n0 = state.num_validators
    for dep in deposits:
        process_deposit(state, dep)
    assert state.num_validators == n0 + 3


def test_eth1_vote_majority(tracker_world):
    cfg, tracker, events = tracker_world
    sks = [B.keygen(b"eth1-vote-%d" % i) for i in range(2)]
    pks = [C.g1_compress(B.sk_to_pk(sk)) for sk in sks]
    state = create_genesis_state(cfg, pks, genesis_time=10**6)
    state.eth1_data = dict(state.eth1_data, deposit_count=0)

    cache = Eth1DataCache()
    period_start = state.genesis_time  # slot 0
    in_range = (
        period_start - ETH1_FOLLOW_DISTANCE * SECONDS_PER_ETH1_BLOCK - 1
    )
    candidate_a = {
        "deposit_root": b"\xaa" * 32,
        "deposit_count": 1,
        "block_hash": b"\xaa" * 32,
    }
    candidate_b = {
        "deposit_root": b"\xbb" * 32,
        "deposit_count": 2,
        "block_hash": b"\xbb" * 32,
    }
    cache.add(in_range - 10, candidate_a)
    cache.add(in_range, candidate_b)

    # no votes yet: freshest candidate wins
    assert get_eth1_vote(state, cache) == candidate_b
    # majority of existing votes wins
    state.eth1_data_votes = [dict(candidate_a), dict(candidate_a)]
    assert get_eth1_vote(state, cache) == candidate_a
    # out-of-range cache: falls back to the state's eth1_data
    empty = Eth1DataCache()
    assert get_eth1_vote(state, empty) == state.eth1_data


def test_tracker_persistence_roundtrip(tracker_world):
    """Deposit events + eth1 data survive a restart through the db
    repositories; the restored tracker serves identical roots without
    the provider re-serving history (reference:
    db/repositories/{depositEvent,depositDataRoot,eth1Data}.ts)."""
    from lodestar_tpu.db import BeaconDb

    cfg, _tracker, events = tracker_world
    db = BeaconDb()
    provider = MockProvider(head=ETH1_FOLLOW_DISTANCE + 100, events=events)
    t1 = Eth1DepositDataTracker(provider, db=db)
    assert t1.update() > 0
    root1 = t1.deposits.tree.root()

    # "restart": a fresh tracker over the same db and a DEAD provider
    class DeadProvider:
        def get_block_number(self):
            return 0  # nothing new

        def get_block_by_number(self, number):
            raise AssertionError("restore must not hit the provider")

        def get_deposit_events(self, a, b):
            raise AssertionError("restore must not hit the provider")

    t2 = Eth1DepositDataTracker(DeadProvider(), db=db)
    assert t2.deposits.highest_index == t1.deposits.highest_index
    assert t2.deposits.tree.root() == root1
    assert t2.last_processed_block >= 100
    assert len(t2.data_cache.by_timestamp) == len(t1.data_cache.by_timestamp)
    # persisted deposit data roots match the SSZ of the events
    from lodestar_tpu.types import DepositDataType

    stored = db.deposit_data_root.get((0).to_bytes(8, "big"))
    assert stored == DepositDataType.hash_tree_root(events[0].deposit_data())
