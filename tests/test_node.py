"""BeaconNode composition: gossip bytes -> queues -> verifier -> verdict,
with the REST API observing the system.

Reference: packages/beacon-node/src/node/nodejs.ts (wiring) + the
SURVEY.md §3.2 hot loop.  Uses a CPU-oracle verifier double so the test
runs without device time.
"""

import pytest

from lodestar_tpu.api import ApiClient
from lodestar_tpu.config import MAINNET_CHAIN_CONFIG
from lodestar_tpu.crypto import bls as B
from lodestar_tpu.crypto import curves as C
from lodestar_tpu.node import BeaconNode, NodeOptions
from lodestar_tpu.utils.metrics import BlsPoolMetrics

pytestmark = pytest.mark.smoke

N_KEYS = 4


class OracleVerifier:
    """IBlsVerifier double: host-CPU verification of wire sets."""

    def __init__(self, pks):
        self.metrics = BlsPoolMetrics()
        self.pks = pks
        self.max_job_sets = 128

    def verify_signature_sets(self, sets, opts=None):
        return all(self._one(s) for s in sets)

    def _one(self, ws):
        dec = ws.decode()
        if dec.signature is None:
            return False
        from lodestar_tpu.crypto import pairing as P

        agg = B.aggregate_pubkeys([self.pks[i] for i in dec.indices])
        return P.multi_pairing_is_one(
            [(agg, dec.message), (B.NEG_G1_GEN, dec.signature)]
        )

    def close(self):
        pass


@pytest.fixture
def node():
    sks = [B.keygen(b"node-%d" % i) for i in range(N_KEYS)]
    pks = [B.sk_to_pk(sk) for sk in sks]
    n = BeaconNode(
        MAINNET_CHAIN_CONFIG,
        pubkey_table=None,
        opts=NodeOptions(verifier=OracleVerifier(pks)),
    )
    n.start()
    yield n, sks
    n.close()


def test_end_to_end_gossip_flow(node):
    n, sks = node
    root = b"node root".ljust(32, b"\x00")
    for i in range(N_KEYS):
        sig = C.g2_compress(B.sign(sks[i], root))
        n.on_gossip_attestation(i, 0, b"data-0", root, sig)
    # one bad signature (wrong root)
    bad = C.g2_compress(B.sign(sks[0], b"other".ljust(32, b"\x00")))
    n.on_gossip_attestation(1, 0, b"data-0", root, bad)  # seen: dropped
    assert n.drain_verdicts() == N_KEYS  # the dup was deduped, all valid
    # a genuinely new validator with a bad signature fails
    n2 = 2  # already seen -> need fresh index
    n.on_gossip_attestation(3, 1, b"data-1", root, bad)  # epoch 0 slot 1?
    # slot 1 is epoch 0; validator 3 already attested in epoch 0 -> deduped
    assert n.drain_verdicts() == 0


def test_api_observes_node(node):
    n, _sks = node
    c = ApiClient([f"http://127.0.0.1:{n.api.port}"])
    assert c.get_version().startswith("lodestar-tpu")
    q = c.dump_gossip_queue("beacon_attestation")
    assert q["length"] == 0  # drained by execute_work
    m = c.get_bls_metrics()
    assert "queue_length" in m


def test_seen_attesters_dedup_and_backpressure_gate(node):
    n, sks = node
    root = b"r2".ljust(32, b"\x00")
    sig = C.g2_compress(B.sign(sks[0], root))
    n.on_gossip_attestation(0, 0, b"d", root, sig)
    n.on_gossip_attestation(0, 0, b"d", root, sig)  # dup in same epoch
    assert n.drain_verdicts() == 1
