"""TpuBlsVerifier service semantics vs the reference's IBlsVerifier contract.

Covers: single + aggregate sets against the device pubkey table, RLC batch
accept, batch-failure -> individual retry accounting, per-set verdicts,
backpressure counter, undecodable-signature handling.
Reference semantics: packages/beacon-node/src/chain/bls/{interface.ts,
maybeBatch.ts, multithread/worker.ts:52-96}.
"""

import numpy as np
import pytest

from lodestar_tpu.bls import PubkeyTable, SignatureSet, TpuBlsVerifier, VerifyOptions
from lodestar_tpu.crypto import bls as GTB
from lodestar_tpu.crypto import curves as C
from lodestar_tpu.crypto.hash_to_curve import hash_to_g2

# SLOW TIER: these drive the REAL device pipeline (eager interpret mode
# on CPU hosts — pathological per-op dispatch, dev/NOTES.md "CPU-host
# costs"; round-4 measurement: >400 s on the 1-core driver host).  The
# IBlsVerifier CONTRACT stays covered in the default tier by
# test_service/test_validation over CpuBlsVerifier; the wire-path device
# tests (test_verifier_wire) were always slow-tier for the same reason.
pytestmark = pytest.mark.slow

N_KEYS = 6


def make_world():
    sks = [GTB.keygen(b"verifier-%d" % i) for i in range(N_KEYS)]
    pks = [GTB.sk_to_pk(sk) for sk in sks]
    table = PubkeyTable(capacity=N_KEYS)
    idxs = table.register(pks)
    assert idxs == list(range(N_KEYS))
    verifier = TpuBlsVerifier(table, rng=np.random.default_rng(7))
    return sks, table, verifier


def single_set(sks, i, msg: bytes, tamper=False) -> SignatureSet:
    sig = GTB.sign(sks[i], msg)
    if tamper:
        sig = C.scalar_mul(C.FP2_OPS, sig, 2)
    return SignatureSet.single(i, hash_to_g2(msg), sig)


def agg_set(sks, idxs, msg: bytes) -> SignatureSet:
    sig = GTB.aggregate_signatures([GTB.sign(sks[i], msg) for i in idxs])
    return SignatureSet.aggregate(idxs, hash_to_g2(msg), sig)


def test_batchable_accepts_valid_mixed_sets():
    sks, _table, verifier = make_world()
    sets = [
        single_set(sks, 0, b"root-0"),
        single_set(sks, 1, b"root-1"),
        agg_set(sks, [2, 3, 4], b"root-agg"),
    ]
    assert verifier.verify_signature_sets(sets, VerifyOptions(batchable=True))
    m = verifier.metrics
    assert m.batch_sigs_success.value == 3
    assert m.batch_retries.value == 0
    assert m.success_jobs.value == 3


def test_batch_failure_retries_individually():
    sks, _table, verifier = make_world()
    sets = [
        single_set(sks, 0, b"root-0"),
        single_set(sks, 1, b"root-1", tamper=True),
        single_set(sks, 2, b"root-2"),
    ]
    assert not verifier.verify_signature_sets(sets, VerifyOptions(batchable=True))
    m = verifier.metrics
    assert m.batch_retries.value == 1
    assert m.success_jobs.value == 2      # the two honest sets still count
    assert m.invalid_sets.value == 1


def test_individual_verdicts():
    sks, _table, verifier = make_world()
    sets = [
        single_set(sks, 0, b"root-0"),
        single_set(sks, 1, b"root-1", tamper=True),
        agg_set(sks, [0, 5], b"root-agg"),
    ]
    assert verifier.verify_signature_sets_individually(sets) == [True, False, True]


def test_undecodable_signature_fails_fast():
    sks, _table, verifier = make_world()
    bad = SignatureSet.single(0, hash_to_g2(b"m"), None)
    good = single_set(sks, 1, b"root-1")
    assert not verifier.verify_signature_sets([good, bad], VerifyOptions(batchable=True))
    assert verifier.verify_signature_sets_individually([good, bad]) == [True, False]


def test_non_batchable_small_job():
    sks, _table, verifier = make_world()
    assert verifier.verify_signature_sets([single_set(sks, 3, b"solo")])
    assert not verifier.verify_signature_sets(
        [single_set(sks, 3, b"solo", tamper=True)]
    )


def test_can_accept_work():
    _sks, _table, verifier = make_world()
    assert verifier.can_accept_work()
    verifier._pending_jobs = 512
    assert not verifier.can_accept_work()


def test_undecodable_signature_still_retries_decodable_sets():
    """One undecodable sig must not swallow honest sets' accounting
    (reference: multithread/worker.ts:74-96 retry semantics)."""
    sks, _table, verifier = make_world()
    bad = SignatureSet.single(0, hash_to_g2(b"m"), None)
    good1 = single_set(sks, 1, b"root-1")
    good2 = single_set(sks, 2, b"root-2")
    assert not verifier.verify_signature_sets(
        [good1, bad, good2], VerifyOptions(batchable=True)
    )
    m = verifier.metrics
    assert m.batch_retries.value == 1      # batch implicitly failed
    assert m.success_jobs.value == 2       # honest sets credited
    assert m.invalid_sets.value == 1


def test_bisection_isolates_tampered_set_on_device():
    """A failed RLC batch above the bisection leaf re-verifies through
    REAL device sub-batches: halves re-dispatch as smaller RLC jobs and
    the leaf runs per-set verdicts — the tampered set is isolated, the
    honest ones are credited (host-oracle bisection semantics are
    covered at scale in test_verifier_rlc.py)."""
    sks = [GTB.keygen(b"verifier-%d" % i) for i in range(N_KEYS)]
    pks = [GTB.sk_to_pk(sk) for sk in sks]
    table = PubkeyTable(capacity=N_KEYS)
    table.register(pks)
    # leaf=1 forces genuine sub-batch dispatches even on a 3-set job
    verifier = TpuBlsVerifier(
        table, rng=np.random.default_rng(7), bisect_leaf=1
    )
    sets = [
        single_set(sks, 0, b"bis-0"),
        single_set(sks, 1, b"bis-1"),
        single_set(sks, 2, b"bis-2", tamper=True),
    ]
    assert not verifier.verify_signature_sets(sets, VerifyOptions(batchable=True))
    m = verifier.metrics
    assert m.batch_retries.value == 1
    assert m.rlc_fallback.value == 1
    assert m.rlc_bisect_depth.count == 1
    assert m.success_jobs.value == 2
    assert m.invalid_sets.value == 1
    # the honest half cleared by its sub-batch counts as batch success
    assert m.batch_sigs_success.value >= 2


def test_verify_on_main_thread_cpu_path():
    """The latency fast path (reference: validation/block.ts:146) verifies
    synchronously on the host CPU ground truth."""
    sks, _table, verifier = make_world()
    opts = VerifyOptions(verify_on_main_thread=True)
    assert verifier.verify_signature_sets([single_set(sks, 0, b"blk")], opts)
    assert not verifier.verify_signature_sets(
        [single_set(sks, 0, b"blk", tamper=True)], opts
    )
    assert not verifier.verify_signature_sets(
        [SignatureSet.single(0, hash_to_g2(b"blk"), None)], opts
    )


def test_oversized_aggregate_falls_back_to_cpu():
    """An aggregate with more participants than the largest device bucket
    (> 2048, e.g. a full mainnet committee with duplicates) must still get
    a verdict instead of raising."""
    sks, _table, verifier = make_world()
    reps = 342  # 6 keys x 342 = 2052 > MAX_AGG_INDICES
    idxs = list(range(N_KEYS)) * reps
    msg = b"committee-root"
    sig_each = [GTB.sign(sk, msg) for sk in sks]
    agg_once = GTB.aggregate_signatures(sig_each)
    sig = C.scalar_mul(C.FP2_OPS, agg_once, reps)
    big = SignatureSet.aggregate(idxs, hash_to_g2(msg), sig)
    small = single_set(sks, 1, b"root-1")
    assert verifier.verify_signature_sets([big, small], VerifyOptions(batchable=True))
    # tampered oversized aggregate -> False, and no exception
    bad = SignatureSet.aggregate(
        idxs, hash_to_g2(msg), C.scalar_mul(C.FP2_OPS, sig, 2)
    )
    assert not verifier.verify_signature_sets([bad], VerifyOptions(batchable=True))
