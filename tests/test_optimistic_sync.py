"""Chain-level optimistic sync: SYNCING imports, EL verdicts, eviction.

Reference behaviors: packages/beacon-node/src/chain/blocks/
verifyBlocksExecutionPayloads.ts (SYNCING -> optimistic import,
INVALID -> invalidSegmentLHV) and chain/blocks/index.ts:86
(forkChoice.validateLatestHash) — an EL-invalid payload must
retroactively evict its optimistically-imported ancestors from head
candidacy.
"""

import pytest

from lodestar_tpu import params
from lodestar_tpu import types as T
from lodestar_tpu.chain.chain import BeaconChain
from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
from lodestar_tpu.crypto import bls as B
from lodestar_tpu.crypto import curves as C
from lodestar_tpu.execution import ExecutePayloadStatus, ExecutionEngineMock
from lodestar_tpu.fork_choice import ExecutionStatus
from lodestar_tpu.params import ForkName
from lodestar_tpu.state_transition import create_genesis_state
from lodestar_tpu.state_transition.accessors import get_beacon_proposer_index
from lodestar_tpu.state_transition.slot import process_slots
from lodestar_tpu.validator import ValidatorStore

pytestmark = pytest.mark.smoke

P = params.ACTIVE_PRESET
N_KEYS = 8


@pytest.fixture(scope="module")
def world():
    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG,
        fork_epochs={ForkName.altair: 0, ForkName.bellatrix: 1},
    )
    sks = [B.keygen(b"opt-%d" % i) for i in range(N_KEYS)]
    pks = [C.g1_compress(B.sk_to_pk(sk)) for sk in sks]
    genesis = create_genesis_state(cfg, pks, genesis_time=2)
    return cfg, sks, genesis


def _make_proposer(cfg, sks, genesis, chain):
    store = ValidatorStore(cfg, dict(enumerate(sks)))

    def propose(slot):
        st = genesis.clone()
        process_slots(st, slot)
        proposer = get_beacon_proposer_index(st)
        block = chain.produce_block(slot, store.sign_randao(proposer, slot))
        block_type = (
            T.BeaconBlockBellatrix
            if "execution_payload" in block["body"]
            else T.BeaconBlockAltair
        )
        root = cfg.compute_signing_root(
            block_type.hash_tree_root(block),
            cfg.get_domain(slot, params.DOMAIN_BEACON_PROPOSER, slot),
        )
        return {
            "message": block,
            "signature": C.g2_compress(B.sign(sks[proposer], root)),
        }

    return propose


def test_invalid_verdict_evicts_optimistic_branch(world):
    """Import two payload blocks optimistically (EL syncing), then the
    EL rules the branch INVALID on fcU: both nodes flip Invalid and the
    head falls back to the pre-merge block."""
    cfg, sks, genesis = world
    el_build = ExecutionEngineMock()  # fully-working EL builds payloads
    builder = BeaconChain(cfg, genesis, execution=el_build)
    propose = _make_proposer(cfg, sks, genesis, builder)

    el = ExecutionEngineMock()
    chain = BeaconChain(cfg, genesis, execution=el)

    # altair block: PreMerge node
    b_alt = propose(1)
    builder.process_block(b_alt)
    r_alt = chain.process_block(b_alt)
    alt_hex = bytes(r_alt).hex()
    assert (
        chain.fork_choice.get_execution_status(alt_hex)
        == ExecutionStatus.PreMerge
    )

    # merge block M: force the chain's EL into syncing -> optimistic
    b_merge = propose(P.SLOTS_PER_EPOCH + 1)
    builder.process_block(b_merge)
    el.fail_with = ExecutePayloadStatus.SYNCING
    r_merge = chain.process_block(b_merge)
    el.fail_with = None
    m_hex = bytes(r_merge).hex()
    assert m_hex in chain.optimistic_roots
    assert (
        chain.fork_choice.get_execution_status(m_hex)
        == ExecutionStatus.Syncing
    )

    # child C: EL has unknown ancestry -> SYNCING organically
    b_child = propose(P.SLOTS_PER_EPOCH + 2)
    builder.process_block(b_child)
    r_child = chain.process_block(b_child)
    c_hex = bytes(r_child).hex()
    assert c_hex in chain.optimistic_roots
    assert chain.head_root_hex == c_hex

    # EL finishes syncing: the whole payload branch is INVALID
    p1 = chain._execution_block_hash[m_hex]
    p2 = chain._execution_block_hash[c_hex]
    el.invalid_hashes = {p1, p2}
    chain._notify_forkchoice()

    assert (
        chain.fork_choice.get_execution_status(m_hex)
        == ExecutionStatus.Invalid
    )
    assert (
        chain.fork_choice.get_execution_status(c_hex)
        == ExecutionStatus.Invalid
    )
    # head fell back to the last pre-merge block
    assert chain.head_root_hex == alt_hex


def test_valid_fcu_resolves_optimistic_branch(world):
    """The EL confirming the head flips the whole Syncing branch Valid
    and empties optimistic_roots (reference: importBlock.ts fcU VALID ->
    validateLatestHash)."""
    cfg, sks, genesis = world
    el_build = ExecutionEngineMock()
    builder = BeaconChain(cfg, genesis, execution=el_build)
    propose = _make_proposer(cfg, sks, genesis, builder)

    el = ExecutionEngineMock()
    chain = BeaconChain(cfg, genesis, execution=el)

    b_alt = propose(1)
    builder.process_block(b_alt)
    chain.process_block(b_alt)

    b_merge = propose(P.SLOTS_PER_EPOCH + 1)
    builder.process_block(b_merge)
    el.fail_with = ExecutePayloadStatus.SYNCING
    r_merge = chain.process_block(b_merge)
    el.fail_with = None
    m_hex = bytes(r_merge).hex()
    assert m_hex in chain.optimistic_roots

    # EL catches up: it now knows the payload chain end-to-end
    p1 = chain._execution_block_hash[m_hex]
    el.valid_blocks[p1] = b"\x00" * 32
    chain._notify_forkchoice()

    assert (
        chain.fork_choice.get_execution_status(m_hex) == ExecutionStatus.Valid
    )
    assert not chain.optimistic_roots
