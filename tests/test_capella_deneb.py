"""Capella/deneb slice: withdrawals, credential rotation, historical
summaries, blob-era block shapes.

Reference behaviors: packages/state-transition capella processing
(processWithdrawals, processBlsToExecutionChange,
upgradeStateToCapella/Deneb — the reference spreads these across
block/ and slot/), engine API v2/v3 payload shapes
(packages/beacon-node/src/execution/engine/http.ts), and the capella
signature-set extractor (signatureSets/blsToExecutionChange.ts).
"""

import hashlib
from types import SimpleNamespace

import pytest

from lodestar_tpu import params
from lodestar_tpu import types as T
from lodestar_tpu.chain.chain import BeaconChain
from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
from lodestar_tpu.crypto import bls as B
from lodestar_tpu.crypto import curves as C
from lodestar_tpu.execution import ExecutionEngineMock, PayloadAttributes
from lodestar_tpu.params import ForkName
from lodestar_tpu.state_transition import create_genesis_state
from lodestar_tpu.state_transition.accessors import (
    get_beacon_proposer_index,
)
from lodestar_tpu.state_transition.block import (
    BlockProcessError,
    get_expected_withdrawals,
    process_bls_to_execution_change,
    process_withdrawals,
)
from lodestar_tpu.state_transition.epoch import (
    process_historical_roots_update,
)
from lodestar_tpu.state_transition.slot import process_slots
from lodestar_tpu.state_transition.state import BeaconState
from lodestar_tpu.validator import ValidatorStore

pytestmark = pytest.mark.smoke

P = params.ACTIVE_PRESET
N_KEYS = 8


def make_cfg(bellatrix=1, capella=2, deneb=3):
    return create_chain_config(
        MAINNET_CHAIN_CONFIG,
        fork_epochs={
            ForkName.altair: 0,
            ForkName.bellatrix: bellatrix,
            ForkName.capella: capella,
            ForkName.deneb: deneb,
        },
    )


@pytest.fixture(scope="module")
def world():
    cfg = make_cfg()
    sks = [B.keygen(b"cap-%d" % i) for i in range(N_KEYS)]
    pks = [C.g1_compress(B.sk_to_pk(sk)) for sk in sks]
    # validator 0 carries a 1-ETH excess balance: once its credentials
    # rotate to 0x01 it becomes partially withdrawable (effective stays
    # MAX; the excess out-lives a few epochs of missed-duty penalties)
    balances = [
        P.MAX_EFFECTIVE_BALANCE + 10**9
    ] + [P.MAX_EFFECTIVE_BALANCE] * (N_KEYS - 1)
    genesis = create_genesis_state(cfg, pks, genesis_time=2, balances=balances)
    return cfg, sks, pks, genesis


def _eth1_creds(address: bytes) -> bytes:
    return params.ETH1_ADDRESS_WITHDRAWAL_PREFIX + b"\x00" * 11 + address


def test_fork_upgrades_capella_then_deneb(world):
    cfg, sks, pks, genesis = world
    st = genesis.clone()
    process_slots(st, 2 * P.SLOTS_PER_EPOCH)  # epoch 2 = capella
    assert st.next_withdrawal_index == 0
    assert st.next_withdrawal_validator_index == 0
    assert st.historical_summaries == []
    assert "withdrawals_root" in st.latest_execution_payload_header
    assert st.fork["current_version"] == cfg.fork_versions[ForkName.capella]
    process_slots(st, 3 * P.SLOTS_PER_EPOCH)  # epoch 3 = deneb
    assert st.latest_execution_payload_header["blob_gas_used"] == 0
    assert st.fork["current_version"] == cfg.fork_versions[ForkName.deneb]
    assert st.fork_name == ForkName.deneb


def test_state_ssz_roundtrip_capella_and_deneb(world):
    cfg, sks, pks, genesis = world
    for slot in (2 * P.SLOTS_PER_EPOCH + 1, 3 * P.SLOTS_PER_EPOCH + 1):
        st = genesis.clone()
        process_slots(st, slot)
        data = st.serialize()
        back = BeaconState.deserialize(data, cfg)
        assert back.next_withdrawal_index == st.next_withdrawal_index
        assert back.hash_tree_root() == st.hash_tree_root()
        assert back.serialize() == data


def test_expected_withdrawals_sweep(world):
    cfg, sks, pks, genesis = world
    st = genesis.clone()
    process_slots(st, 2 * P.SLOTS_PER_EPOCH)
    # nobody withdrawable yet: all creds still 0x00 BLS
    assert get_expected_withdrawals(st) == []
    # validator 3: rotated creds + excess balance -> partial withdrawal
    st.withdrawal_credentials[3] = _eth1_creds(b"\x33" * 20)
    st.balances[3] = P.MAX_EFFECTIVE_BALANCE + 5
    # validator 5: rotated creds + withdrawable epoch passed -> full
    st.withdrawal_credentials[5] = _eth1_creds(b"\x55" * 20)
    st.withdrawable_epoch[5] = 0
    ws = get_expected_withdrawals(st)
    assert [w["validator_index"] for w in ws] == [3, 5]
    assert ws[0]["amount"] == 5
    assert ws[0]["address"] == b"\x33" * 20
    assert ws[1]["amount"] == int(st.balances[5])
    assert [w["index"] for w in ws] == [0, 1]


def test_process_withdrawals_debits_and_advances(world):
    cfg, sks, pks, genesis = world
    st = genesis.clone()
    process_slots(st, 2 * P.SLOTS_PER_EPOCH)
    st.withdrawal_credentials[2] = _eth1_creds(b"\x22" * 20)
    st.balances[2] = P.MAX_EFFECTIVE_BALANCE + 9
    expected = get_expected_withdrawals(st)
    payload = {"withdrawals": expected}
    process_withdrawals(st, payload)
    assert int(st.balances[2]) == P.MAX_EFFECTIVE_BALANCE
    assert st.next_withdrawal_index == 1
    # partial sweep: cursor jumps past the whole (8-validator) window
    assert st.next_withdrawal_validator_index == 0  # 0+8 % 8
    # mismatching payload list REJECTS
    st2 = genesis.clone()
    process_slots(st2, 2 * P.SLOTS_PER_EPOCH)
    st2.withdrawal_credentials[2] = _eth1_creds(b"\x22" * 20)
    st2.balances[2] = P.MAX_EFFECTIVE_BALANCE + 9
    bad = [dict(w, amount=w["amount"] + 1) for w in get_expected_withdrawals(st2)]
    with pytest.raises(BlockProcessError, match="withdrawals"):
        process_withdrawals(st2, {"withdrawals": bad})


def test_bls_to_execution_change(world):
    cfg, sks, pks, genesis = world
    st = genesis.clone()
    process_slots(st, 2 * P.SLOTS_PER_EPOCH)
    index = 4
    change = {
        "validator_index": index,
        "from_bls_pubkey": pks[index],  # genesis creds hash this key
        "to_execution_address": b"\x44" * 20,
    }
    domain = cfg.compute_domain(
        params.DOMAIN_BLS_TO_EXECUTION_CHANGE,
        cfg.fork_versions[ForkName.phase0],
        st.genesis_validators_root,
    )
    root = cfg.compute_signing_root(
        T.BLSToExecutionChange.hash_tree_root(change), domain
    )
    signed = {
        "message": change,
        "signature": C.g2_compress(B.sign(sks[index], root)),
    }
    process_bls_to_execution_change(st, signed, verify_signatures=True)
    assert st.withdrawal_credentials[index] == _eth1_creds(b"\x44" * 20)
    # second application REJECTS (already rotated)
    with pytest.raises(BlockProcessError, match="rotated"):
        process_bls_to_execution_change(st, signed, verify_signatures=True)
    # wrong withdrawal key REJECTS
    st2 = genesis.clone()
    process_slots(st2, 2 * P.SLOTS_PER_EPOCH)
    bad = dict(change, from_bls_pubkey=pks[(index + 1) % N_KEYS])
    with pytest.raises(BlockProcessError, match="credentials"):
        process_bls_to_execution_change(
            st2, {"message": bad, "signature": signed["signature"]}, True
        )


def test_historical_summaries_replace_roots(world):
    cfg, sks, pks, genesis = world
    st = genesis.clone()
    process_slots(st, 2 * P.SLOTS_PER_EPOCH)
    period = P.SLOTS_PER_HISTORICAL_ROOT // P.SLOTS_PER_EPOCH
    cache = SimpleNamespace(current_epoch=period - 1)  # next_epoch hits it
    n_roots = len(st.historical_roots)
    process_historical_roots_update(st, cache)
    assert len(st.historical_summaries) == 1
    assert len(st.historical_roots) == n_roots  # frozen after capella
    s = st.historical_summaries[0]
    assert T.HistoricalSummary.hash_tree_root(s)  # well-formed


def test_deneb_exit_domain_is_pinned_to_capella(world):
    """EIP-7044: a deneb-era exit verifies against the capella fork
    domain, independent of the current fork."""
    import dataclasses

    cfg, sks, pks, genesis = world
    cfg0 = dataclasses.replace(cfg, SHARD_COMMITTEE_PERIOD=0)
    st = genesis.clone()
    st.config = cfg0
    process_slots(st, 3 * P.SLOTS_PER_EPOCH + 1)
    assert st.fork_name == ForkName.deneb
    index = 1
    exit_msg = {"epoch": 0, "validator_index": index}
    # signed against the CAPELLA domain although the state is in deneb
    domain = cfg0.compute_domain(
        params.DOMAIN_VOLUNTARY_EXIT,
        cfg0.fork_versions[ForkName.capella],
        st.genesis_validators_root,
    )
    root = cfg0.compute_signing_root(
        T.VoluntaryExit.hash_tree_root(exit_msg), domain
    )
    from lodestar_tpu.state_transition.block import process_voluntary_exit

    signed = {
        "message": exit_msg,
        "signature": C.g2_compress(B.sign(sks[index], root)),
    }
    process_voluntary_exit(st, signed, verify_signatures=True)
    assert int(st.exit_epoch[index]) != params.FAR_FUTURE_EPOCH
    # the deneb-fork domain (pre-7044 rule) must NOT verify
    st2 = genesis.clone()
    st2.config = cfg0
    process_slots(st2, 3 * P.SLOTS_PER_EPOCH + 1)
    bad_domain = cfg0.compute_domain(
        params.DOMAIN_VOLUNTARY_EXIT,
        cfg0.fork_versions[ForkName.deneb],
        st2.genesis_validators_root,
    )
    bad_root = cfg0.compute_signing_root(
        T.VoluntaryExit.hash_tree_root(exit_msg), bad_domain
    )
    bad = {
        "message": exit_msg,
        "signature": C.g2_compress(B.sign(sks[index], bad_root)),
    }
    with pytest.raises(BlockProcessError, match="signature"):
        process_voluntary_exit(st2, bad, verify_signatures=True)


def test_chain_crosses_merge_capella_deneb_end_to_end(world):
    """Produce+import real signed blocks across bellatrix -> capella ->
    deneb; capella payloads carry protocol-expected withdrawals built by
    the mock EL from engine-v2 attributes; a bls-to-execution change
    rides a capella block from the op pool."""
    cfg, sks, pks, genesis = world
    el = ExecutionEngineMock()
    chain = BeaconChain(cfg, genesis, execution=el)
    store = ValidatorStore(cfg, dict(enumerate(sks)))

    def propose(slot):
        # proposer from the REAL head chain (randao mixes diverge from an
        # empty-chain replay once imported reveals land)
        st = chain.head_state.clone()
        if st.slot < slot:
            process_slots(st, slot)
        proposer = get_beacon_proposer_index(st)
        block = chain.produce_block(slot, store.sign_randao(proposer, slot))
        block_type, _signed_t, _body_t = cfg.get_fork_types(slot)
        root = cfg.compute_signing_root(
            block_type.hash_tree_root(block),
            cfg.get_domain(slot, params.DOMAIN_BEACON_PROPOSER, slot),
        )
        signed = {
            "message": block,
            "signature": C.g2_compress(B.sign(sks[proposer], root)),
        }
        return chain.process_block(signed)


    # bellatrix: the merge block
    propose(P.SLOTS_PER_EPOCH + 1)
    # capella: rotate validator 0's creds in-block, then withdraw
    index = 0
    change = {
        "validator_index": index,
        "from_bls_pubkey": pks[index],
        "to_execution_address": b"\xaa" * 20,
    }
    domain = cfg.compute_domain(
        params.DOMAIN_BLS_TO_EXECUTION_CHANGE,
        cfg.fork_versions[ForkName.phase0],
        genesis.genesis_validators_root,
    )
    change_root = cfg.compute_signing_root(
        T.BLSToExecutionChange.hash_tree_root(change), domain
    )
    signed_change = {
        "message": change,
        "signature": C.g2_compress(B.sign(sks[index], change_root)),
    }
    # the change rides the op pool into the next produced block
    chain.op_pool.insert_bls_to_execution_change(signed_change)
    slot_cap = 2 * P.SLOTS_PER_EPOCH + 1
    root_cap = propose(slot_cap)
    assert chain.head_root_hex == bytes(root_cap).hex()
    head = chain.head_state
    assert bytes(head.withdrawal_credentials[index][:1]) == (
        params.ETH1_ADDRESS_WITHDRAWAL_PREFIX
    )
    # validator 0 has carried an excess balance since genesis; with the
    # credentials rotated the NEXT payload must skim it

    # deneb block: body carries (empty) blob commitments and the payload
    # the blob gas fields; the withdrawal executes
    slot_deneb = 3 * P.SLOTS_PER_EPOCH + 1
    st = chain.head_state.clone()
    if st.slot < slot_deneb:
        process_slots(st, slot_deneb)
    proposer = get_beacon_proposer_index(st)
    block = chain.produce_block(slot_deneb, store.sign_randao(proposer, slot_deneb))
    assert "blob_kzg_commitments" in block["body"]
    payload = block["body"]["execution_payload"]
    assert "blob_gas_used" in payload
    assert [w["validator_index"] for w in payload["withdrawals"]] == [index]
    assert payload["withdrawals"][0]["amount"] > 0  # the excess skim
    block_type, _s, _b = cfg.get_fork_types(slot_deneb)
    root = cfg.compute_signing_root(
        block_type.hash_tree_root(block),
        cfg.get_domain(slot_deneb, params.DOMAIN_BEACON_PROPOSER, slot_deneb),
    )
    signed = {
        "message": block,
        "signature": C.g2_compress(B.sign(sks[proposer], root)),
    }
    root_deneb = chain.process_block(signed)
    assert chain.head_root_hex == bytes(root_deneb).hex()
    # the skim leaves exactly MAX, minus the same-block empty-sync-
    # aggregate penalties (every validator sits in the tiny committee)
    final = int(chain.head_state.balances[index])
    assert P.MAX_EFFECTIVE_BALANCE - 10**7 < final <= P.MAX_EFFECTIVE_BALANCE
    assert not chain.optimistic_roots
