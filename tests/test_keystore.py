"""EIP-2335 keystores: cipher seal, container roundtrip, keymanager
import/delete over the REST API (reference: @chainsafe/bls-keystore +
packages/cli/src/cmds/validator/keymanager/ importKeystores flow)."""

import json
import urllib.request

import pytest

from lodestar_tpu.validator import keystore as K

pytestmark = pytest.mark.smoke

FAST_SCRYPT = {"n": 1024, "r": 8, "p": 1}


def test_aes128_fips197_vector():
    """FIPS-197 Appendix C.1 seals the whole cipher (computed S-box,
    key schedule, rounds)."""
    key = bytes.fromhex("000102030405060708090a0b0c0d0e0f")
    pt = bytes.fromhex("00112233445566778899aabbccddeeff")
    ct = K._encrypt_block(K._expand_key(key), pt)
    assert ct.hex() == "69c4e0d86a7b0430d8cdb78070b4c55a"


def test_ctr_keystream_xor_roundtrip():
    key, iv = b"k" * 16, b"\x00" * 15 + b"\xff"  # crosses a block carry
    data = bytes(range(48))  # 3 blocks
    ct = K.aes128_ctr(key, iv, data)
    assert ct != data
    assert K.aes128_ctr(key, iv, ct) == data


def test_keystore_roundtrip_both_kdfs():
    secret = bytes(range(32))
    for kdf, params in (
        ("scrypt", FAST_SCRYPT),
        ("pbkdf2", {"c": 1000}),
    ):
        ks = K.create_keystore(
            secret, "p@ssw0rd", kdf=kdf, kdf_params=params
        )
        assert ks["version"] == 4
        assert K.decrypt_keystore(ks, "p@ssw0rd") == secret
        with pytest.raises(K.KeystoreError, match="checksum"):
            K.decrypt_keystore(ks, "wrong")


def test_password_normalization_nfkd_and_control_strip():
    # EIP-2335: NFKD first (fraktur letters decompose to ASCII), then
    # control codes (C0, C1, DEL) stripped
    fancy = "\U0001d531\U0001d522\U0001d530\U0001d531"  # 𝔱𝔢𝔰𝔱
    assert K.normalize_password(fancy) == b"test"
    assert K.normalize_password("a\x00b\x1fc\x7fd\x9de") == b"abcde"
    secret = b"\x42" * 32
    ks = K.create_keystore(secret, fancy, kdf_params=FAST_SCRYPT)
    # a keystore made with the fancy password opens with the plain one
    assert K.decrypt_keystore(ks, "test") == secret


def test_keymanager_import_and_delete_over_rest():
    """End-to-end: POST /eth/v1/keystores adds a working signer (the
    index resolved from the head-state registry), duplicate and
    bad-password imports get per-key statuses, DELETE removes the key
    and hands back its slashing-protection interchange."""
    from lodestar_tpu.api.server import BeaconApiServer, DefaultHandlers
    from lodestar_tpu.chain.chain import BeaconChain
    from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
    from lodestar_tpu.crypto import bls as B
    from lodestar_tpu.crypto import curves as C
    from lodestar_tpu.params import ForkName
    from lodestar_tpu.state_transition import create_genesis_state
    from lodestar_tpu.validator import ValidatorStore

    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG, fork_epochs={ForkName.altair: 0}
    )
    sks = [B.keygen(b"km-%d" % i) for i in range(4)]
    pks = [C.g1_compress(B.sk_to_pk(sk)) for sk in sks]
    genesis = create_genesis_state(cfg, pks, genesis_time=2)
    chain = BeaconChain(cfg, genesis)
    # the store starts with validator 0 only; we import validator 1
    store = ValidatorStore(cfg, {0: sks[0]})
    server = BeaconApiServer(
        DefaultHandlers(
            chain=chain, validator_store=store, keymanager_token="kmtok"
        ),
        port=0,
    )
    server.listen()
    try:
        base = f"http://127.0.0.1:{server.port}/eth/v1/keystores"

        def call(method, payload):
            req = urllib.request.Request(
                base,
                data=json.dumps(payload).encode(),
                headers={
                    "Content-Type": "application/json",
                    "Authorization": "Bearer kmtok",
                },
                method=method,
            )
            with urllib.request.urlopen(req, timeout=30) as r:
                return json.loads(r.read())

        secret1 = sks[1].to_bytes(32, "big")
        ks1 = K.create_keystore(secret1, "pw1", kdf_params=FAST_SCRYPT)
        # an sk NOT in the registry, and a wrong-password import
        stranger = B.keygen(b"stranger").to_bytes(32, "big")
        ks_stranger = K.create_keystore(
            stranger, "pw", kdf_params=FAST_SCRYPT
        )
        out = call(
            "POST",
            {
                "keystores": [
                    json.dumps(ks1),
                    json.dumps(ks_stranger),
                    json.dumps(ks1),
                ],
                "passwords": ["pw1", "pw", "BAD"],
            },
        )
        assert [s["status"] for s in out["data"]] == [
            "imported",
            "error",
            "error",
        ]
        assert "registry" in out["data"][1]["message"]
        # the imported signer WORKS and records slashing history
        store.sign_attestation(
            1,
            {
                "slot": 5,
                "index": 0,
                "beacon_block_root": b"\x00" * 32,
                "source": {"epoch": 0, "root": b"\x00" * 32},
                "target": {"epoch": 1, "root": b"\x00" * 32},
            },
        )
        # re-import of a live key is a duplicate, not an error
        out = call(
            "POST",
            {"keystores": [json.dumps(ks1)], "passwords": ["pw1"]},
        )
        assert out["data"][0]["status"] == "duplicate"
        # DELETE returns the key's interchange and removes the signer
        out = call("DELETE", {"pubkeys": ["0x" + pks[1].hex()]})
        assert [s["status"] for s in out["data"]] == ["deleted"]
        interchange = json.loads(out["slashing_protection"])
        assert interchange["data"][0]["pubkey"] == "0x" + pks[1].hex()
        assert interchange["data"][0]["signed_attestations"]
        assert 1 not in store.sks
        # the key is gone but its slashing history remains: the spec's
        # not_active status tells the caller to keep the interchange
        out = call("DELETE", {"pubkeys": ["0x" + pks[1].hex()]})
        assert [s["status"] for s in out["data"]] == ["not_active"]
    finally:
        server.close()


def test_delete_unregisters_doppelganger_and_reimport_rewatches():
    """A deleted key signs elsewhere legitimately — the doppelganger
    service must stop watching it, and a re-import must get a FRESH
    watch window rather than inherited state."""
    from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
    from lodestar_tpu.crypto import bls as B
    from lodestar_tpu.params import ForkName
    from lodestar_tpu.validator import ValidatorStore
    from lodestar_tpu.validator.doppelganger import (
        DoppelgangerService,
        DoppelgangerStatus,
    )

    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG, fork_epochs={ForkName.altair: 0}
    )
    dg = DoppelgangerService(
        liveness_fn=lambda epoch, idxs: {i: False for i in idxs},
        current_epoch_fn=lambda: 0,
    )
    sk = B.keygen(b"dg-key")
    store = ValidatorStore(cfg, {}, doppelganger=dg)
    store.import_local_key(7, sk)
    assert dg.status(7) == DoppelgangerStatus.UNVERIFIED
    store.remove_local_key(7)
    # no longer watched: its liveness elsewhere is expected, and
    # status() for unknown keys reads VERIFIED (not a false alarm)
    assert 7 not in dg._keys
    store.import_local_key(7, sk)
    assert dg.status(7) == DoppelgangerStatus.UNVERIFIED
