"""phase0 STF: PendingAttestation processing, epoch transition, upgrade.

Reference behaviors: state-transition/src/block/
processAttestationPhase0.ts (record append + FFG source check),
epoch/getAttestationDeltas.ts (phase0 reward components), and
slot/upgradeStateToAltair.ts (participation translation + sync
committee bootstrap).  The VERDICT done-criterion: a chain started at
phase0 crosses to altair in-test.
"""

import pytest

from lodestar_tpu import params
from lodestar_tpu import types as T
from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
from lodestar_tpu.crypto import bls as B
from lodestar_tpu.crypto import curves as C
from lodestar_tpu.params import ForkName
from lodestar_tpu.state_transition import create_genesis_state
from lodestar_tpu.state_transition.accessors import (
    get_beacon_committee,
    get_block_root,
    get_block_root_at_slot,
    get_committee_count_per_slot,
    get_beacon_proposer_index,
)
from lodestar_tpu.state_transition.block import (
    BlockProcessError,
    process_attestation_phase0,
)
from lodestar_tpu.state_transition.phase0 import attesting_mask
from lodestar_tpu.state_transition.slot import process_slots
from lodestar_tpu.state_transition.state import BeaconState, BeaconStatePhase0
from lodestar_tpu.state_transition.transition import state_transition

pytestmark = pytest.mark.smoke

P = params.ACTIVE_PRESET
N_KEYS = 16


def make_cfg(altair_epoch=2):
    return create_chain_config(
        MAINNET_CHAIN_CONFIG, fork_epochs={ForkName.altair: altair_epoch}
    )


@pytest.fixture(scope="module")
def world():
    cfg = make_cfg()
    sks = [B.keygen(b"p0-%d" % i) for i in range(N_KEYS)]
    pks = [C.g1_compress(B.sk_to_pk(sk)) for sk in sks]
    genesis = create_genesis_state(cfg, pks, genesis_time=7)
    return cfg, sks, genesis


def _attestations_for_slot(state, att_slot):
    """Full-participation attestations for `att_slot` built on a state
    advanced past it."""
    out = []
    epoch = att_slot // P.SLOTS_PER_EPOCH
    current_epoch = state.slot // P.SLOTS_PER_EPOCH
    source = (
        state.current_justified_checkpoint
        if epoch == current_epoch
        else state.previous_justified_checkpoint
    )
    target_root = (
        get_block_root(state, epoch)
        if state.slot > epoch * P.SLOTS_PER_EPOCH
        else get_block_root_at_slot(state, att_slot)
    )
    for ci in range(get_committee_count_per_slot(state, epoch)):
        committee = get_beacon_committee(state, att_slot, ci)
        out.append(
            {
                "aggregation_bits": [True] * len(committee),
                "data": {
                    "slot": att_slot,
                    "index": ci,
                    "beacon_block_root": get_block_root_at_slot(
                        state, att_slot
                    ),
                    "source": dict(source),
                    "target": {"epoch": epoch, "root": target_root},
                },
                "signature": b"\x00" * 96,
            }
        )
    return out


def _advance_with_blocks(cfg, state, to_slot):
    """Import one (unverified-signature) block per slot, each carrying
    full attestations for its parent slot."""
    st = state
    while st.slot < to_slot:
        slot = st.slot + 1
        pre = st.clone()
        process_slots(pre, slot)
        atts = (
            _attestations_for_slot(pre, slot - 1)
            if slot >= 1 + P.MIN_ATTESTATION_INCLUSION_DELAY
            else []
        )
        from lodestar_tpu.chain.produce_block import produce_block

        block, post = produce_block(
            st, slot, b"\x00" * 96, attestations=atts
        )
        signed = {"message": block, "signature": b"\x00" * 96}
        st = state_transition(
            st,
            signed,
            verify_state_root=True,
            verify_proposer=False,
            verify_signatures=False,
        )
    return st


def test_phase0_genesis_shape(world):
    cfg, sks, genesis = world
    assert genesis.fork_name == ForkName.phase0
    assert genesis.previous_epoch_attestations == []
    data = genesis.serialize()
    back = BeaconState.deserialize(data, cfg)
    assert back.fork_name == ForkName.phase0
    assert back.previous_epoch_attestations == []
    assert back.hash_tree_root() == genesis.hash_tree_root()
    assert back.serialize() == data


def test_pending_attestation_appended_and_source_checked(world):
    cfg, sks, genesis = world
    st = genesis.clone()
    process_slots(st, 2)
    atts = _attestations_for_slot(st, 1)
    assert atts
    process_attestation_phase0(st, atts[0], verify_signatures=False)
    assert len(st.current_epoch_attestations) == 1
    rec = st.current_epoch_attestations[0]
    assert int(rec["inclusion_delay"]) == 1
    assert int(rec["proposer_index"]) == get_beacon_proposer_index(st)
    # wrong FFG source -> reject
    bad = dict(atts[0])
    bad["data"] = {
        **atts[0]["data"],
        "source": {"epoch": 0, "root": b"\x13" * 32},
    }
    with pytest.raises(BlockProcessError, match="source"):
        process_attestation_phase0(st, bad, verify_signatures=False)


def test_phase0_chain_justifies_and_crosses_to_altair(world):
    """Two phase0 epochs of full participation justify epoch 1; the
    scheduled upgrade translates participation and starts the sync
    committees; an altair block then imports on top."""
    cfg, sks, genesis = world
    st = _advance_with_blocks(cfg, genesis, 2 * P.SLOTS_PER_EPOCH - 1)
    assert st.fork_name == ForkName.phase0
    # entering epoch 2 runs the phase0 epoch transition then upgrades
    last_phase0 = st.clone()
    process_slots(st, 2 * P.SLOTS_PER_EPOCH)
    assert st.fork_name == ForkName.altair
    assert st.previous_epoch_attestations is None
    # participation translated: epoch-1 attesters carry the target flag
    mask = attesting_mask(
        last_phase0, last_phase0.current_epoch_attestations
    )
    flags = st.previous_epoch_participation
    target_bit = 1 << params.TIMELY_TARGET_FLAG_INDEX
    assert all(
        (flags[i] & target_bit) != 0 for i in range(N_KEYS) if mask[i]
    )
    # sync committees bootstrapped
    assert any(
        bytes(pk) != b"\x00" * 48
        for pk in st.current_sync_committee["pubkeys"]
    )
    # the state now serializes as altair
    back = BeaconState.deserialize(st.serialize(), cfg)
    assert back.fork_name == ForkName.altair
    # and an altair block imports on top
    st2 = _advance_with_blocks(cfg, st, 2 * P.SLOTS_PER_EPOCH + 2)
    assert st2.slot == 2 * P.SLOTS_PER_EPOCH + 2
    # the TRANSLATED phase0 participation feeds altair justification:
    # crossing into epoch 3 weighs epoch-2 (altair) flags, but epoch-1's
    # justification bit came from the phase0-era translation
    st3 = _advance_with_blocks(cfg, st2, 3 * P.SLOTS_PER_EPOCH)
    assert int(st3.current_justified_checkpoint["epoch"]) >= 1


def test_phase0_rewards_full_participation_gain(world):
    """Across an epoch boundary with full attestation coverage, active
    validators' balances grow (phase0 get_attestation_deltas)."""
    cfg, sks, genesis = world
    st = _advance_with_blocks(cfg, genesis, P.SLOTS_PER_EPOCH - 1)
    before = st.balances.copy()
    process_slots(st, P.SLOTS_PER_EPOCH + 1)
    import numpy as np

    assert (st.balances.astype(np.int64) - before.astype(np.int64) >= 0).all()


def test_phase0_spec_containers_roundtrip():
    """PendingAttestation SSZ shape."""
    rec = {
        "aggregation_bits": [True, False, True],
        "data": T.AttestationData.default(),
        "inclusion_delay": 3,
        "proposer_index": 7,
    }
    data = T.PendingAttestation.serialize(rec)
    back = T.PendingAttestation.deserialize(data)
    assert list(back["aggregation_bits"]) == [True, False, True]
    assert int(back["inclusion_delay"]) == 3
    assert int(back["proposer_index"]) == 7
