"""kernels/ Fp2-Fp6-Fp12 tower vs the crypto/ CPU ground truth.

Runs the value-level tower under plain jit (identical int32 semantics to
the in-kernel path) and checks exact field results, including the lazy
public-class limb bounds the pallas kernels rely on.
"""

import random

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lodestar_tpu.crypto import fields as GT
from lodestar_tpu.kernels import core as C
from lodestar_tpu.kernels import fp2 as F2
from lodestar_tpu.kernels import layout as LY
from lodestar_tpu.kernels import tower as TW

pytestmark = pytest.mark.smoke

random.seed(0xF00D)
P = LY.P
B = 16


def r2():
    return (random.randrange(P), random.randrange(P))


def r6():
    return (r2(), r2(), r2())


def r12():
    return (r6(), r6())


def enc2(vals):
    a = jnp.asarray(LY.encode_batch([v[0] for v in vals]))
    b = jnp.asarray(LY.encode_batch([v[1] for v in vals]))
    return (a, b)


def dec2(t):
    return list(zip(LY.decode_batch(np.asarray(t[0])), LY.decode_batch(np.asarray(t[1]))))


def enc6(vals):
    return tuple(enc2([v[i] for v in vals]) for i in range(3))


def dec6(t):
    parts = [dec2(c) for c in t]
    return list(zip(*parts))


def enc12(vals):
    return tuple(enc6([v[i] for v in vals]) for i in range(2))


def dec12(t):
    parts = [dec6(c) for c in t]
    return list(zip(*parts))


def assert_bounds(tree):
    for leaf in jax.tree_util.tree_leaves(tree):
        a = np.asarray(leaf)
        assert a.min() >= -4103 and a.max() <= 4103, (a.min(), a.max())


def test_fp2_mul_sqr_xi_conj():
    xs, ys = [r2() for _ in range(B)], [r2() for _ in range(B)]
    a, b = enc2(xs), enc2(ys)

    @jax.jit
    def f(a, b):
        return (
            F2.mul2(a, b),
            F2.sqr2(a),
            F2.mul2_xi(F2.sub2(a, b)),
            F2.conj2(F2.add2(a, b)),
        )

    m, s, x, c = f(a, b)
    assert dec2(m) == [GT.fp2_mul(u, v) for u, v in zip(xs, ys)]
    assert dec2(s) == [GT.fp2_sqr(u) for u in xs]
    assert dec2(x) == [GT.fp2_mul_xi(GT.fp2_sub(u, v)) for u, v in zip(xs, ys)]
    assert dec2(c) == [GT.fp2_conj(GT.fp2_add(u, v)) for u, v in zip(xs, ys)]
    assert_bounds((m, s, x, c))


def test_fp2_const_and_fp_mul():
    xs = [r2() for _ in range(B)]
    k = r2()
    kfp = random.randrange(P)
    a = enc2(xs)
    kc = F2.const2(k)
    kv = jnp.asarray(LY.encode_batch([kfp] * B))

    @jax.jit
    def f(a, kv):
        return F2.mul2_const(a, kc), F2.mul2_fp(a, kv), F2.mul2_fp_const(
            a, [int(v) for v in LY.const_mont(kfp)]
        )

    mc, mf, mfc = f(a, kv)
    assert dec2(mc) == [GT.fp2_mul(u, k) for u in xs]
    want_fp = [GT.fp2_mul_fp(u, kfp) for u in xs]
    assert dec2(mf) == want_fp
    assert dec2(mfc) == want_fp


def test_fp6_mul_sqr():
    xs, ys = [r6() for _ in range(B)], [r6() for _ in range(B)]
    a, b = enc6(xs), enc6(ys)

    @jax.jit
    def f(a, b):
        return TW.mul6(a, b), TW.sqr6(a), TW.mul6_by_v(b)

    m, s, v = f(a, b)
    assert dec6(m) == [GT.fp6_mul(u, w) for u, w in zip(xs, ys)]
    assert dec6(s) == [GT.fp6_sqr(u) for u in xs]
    assert dec6(v) == [GT.fp6_mul_by_v(w) for w in ys]
    assert_bounds((m, s, v))


def test_fp12_mul_sqr_deep_chain():
    xs, ys = [r12() for _ in range(B)], [r12() for _ in range(B)]
    a, b = enc12(xs), enc12(ys)

    @jax.jit
    def f(a, b):
        m = TW.mul12(a, b)
        s = TW.sqr12(m)
        return m, TW.mul12(s, TW.conj12(a))

    m, chain = f(a, b)
    want_m = [GT.fp12_mul(u, w) for u, w in zip(xs, ys)]
    assert dec12(m) == want_m
    want = [
        GT.fp12_mul(GT.fp12_sqr(wm), GT.fp12_conj(u))
        for wm, u in zip(want_m, xs)
    ]
    assert dec12(chain) == want
    assert_bounds(chain)


def test_fp12_frobenius():
    xs = [r12() for _ in range(B)]
    a = enc12(xs)

    @jax.jit
    def f(a):
        return TW.frob12(a, 1), TW.frob12(a, 2), TW.frob12(a, 3)

    f1, f2, f3 = f(a)
    assert dec12(f1) == [GT.fp12_frobenius(u, 1) for u in xs]
    assert dec12(f2) == [GT.fp12_frobenius(u, 2) for u in xs]
    assert dec12(f3) == [GT.fp12_frobenius(u, 3) for u in xs]


def test_is_one_and_select():
    xs = [r12() for _ in range(4)]
    ones = [GT.FP12_ONE] * 2
    vals = xs[:2] + ones + xs[2:]
    a = enc12(vals)

    @jax.jit
    def f(a):
        mask = jnp.asarray([True, False, True, False, True, False])
        o = TW.one12(a[0][0][0])
        return TW.is_one12(a), TW.is_one12(TW.select12(mask, a, o))

    raw, sel = f(a)
    assert list(np.asarray(raw)) == [False, False, True, True, False, False]
    # slots where mask False were replaced by one
    assert list(np.asarray(sel)) == [False, True, True, True, False, True]


def _cyclotomic_sample(n):
    """Random elements of the cyclotomic subgroup: m^(p^6-1)(p^2+1)."""
    out = []
    for _ in range(n):
        f = r12()
        m = GT.fp12_mul(GT.fp12_conj(f), GT.fp12_inv(f))
        m = GT.fp12_mul(GT.fp12_frobenius(m, 2), m)
        out.append(m)
    return out


def test_cyclotomic_square_and_pow_x():
    cs = _cyclotomic_sample(4)
    a = enc12(cs)

    @jax.jit
    def f(a):
        return TW.cyclo_sqr(a), TW.cyclo_pow_x_neg(a)

    s, px = f(a)
    assert dec12(s) == [GT.fp12_sqr(u) for u in cs]
    x = GT.X_PARAM
    want = [GT.fp12_pow(u, (-x)) for u in cs]
    want = [GT.fp12_conj(w) for w in want]  # inverse == conj in cyclo group
    assert dec12(px) == want
    assert_bounds((s, px))


def test_inversion_chain():
    xs = [r2() for _ in range(B)]
    x6 = [r6() for _ in range(4)]
    x12 = [r12() for _ in range(2)]
    a2, a6, a12 = enc2(xs), enc6(x6), enc12(x12)

    @jax.jit
    def f(a2, a6, a12):
        return TW.inv2(a2), TW.inv6(a6), TW.inv12(a12)

    i2, i6, i12 = f(a2, a6, a12)
    assert dec2(i2) == [GT.fp2_inv(u) for u in xs]
    assert dec6(i6) == [GT.fp6_inv(u) for u in x6]
    assert dec12(i12) == [GT.fp12_inv(u) for u in x12]


def test_pow_static_fp():
    xs = [random.randrange(P) for _ in range(B)]
    a = jnp.asarray(LY.encode_batch(xs))
    e = 0xDEADBEEF_CAFEBABE_0123456789ABCDEF

    @jax.jit
    def f(a):
        return TW.pow_static(a, e, C.mont_sqr, C.mont_mul, None)

    got = LY.decode_batch(np.asarray(f(a)))
    assert got == [pow(x, e, P) for x in xs]
