"""BlsVerificationPipeline: shape-bucketed accumulate-and-flush feed.

Stub-verifier (host-only) tests of the ISSUE 11 tentpole contract:
per-(kind, K, lane) accumulators, exact-N-bucket immediate flush,
oldest-set-anchored deadlines, priority lanes, set-based high-water
backpressure, flush-reason/fill-ratio observability, the escape hatch —
plus the acceptance oracle: mean bucket occupancy >= 2x the PR 10 flat
coalescer at equal p99 submit->verdict latency for block-critical sets.
"""

import threading
import time

import pytest

from lodestar_tpu.bls.pipeline import (
    BlsVerificationPipeline,
    create_bls_service,
)
from lodestar_tpu.bls.service import BlsVerifierService
from lodestar_tpu.bls.signature_set import SignatureSet, WireSignatureSet
from lodestar_tpu.bls.verifier import VerifyOptions
from lodestar_tpu.utils.metrics import BlsPoolMetrics

pytestmark = pytest.mark.smoke


class HandleStub:
    """IBlsVerifier with the begin/finish device-handle protocol; every
    begun job is recorded as (n_sets, batchable, t_begin)."""

    max_job_sets = 512

    class _Handle:
        def __init__(self, sets):
            self.sets = sets
            self.ok_big = True
            self.batch_retries = 0
            self.batch_sigs_success = len(sets)
            self.verdicts = None

    def __init__(self, finish_delay=0.0):
        self.metrics = BlsPoolMetrics()
        self.calls = []
        self.finish_delay = finish_delay
        self._lock = threading.Lock()

    def verify_signature_sets(self, sets, opts=None):
        with self._lock:
            self.calls.append((len(sets), True, time.perf_counter()))
        return True

    def begin_job(self, sets, batchable):
        with self._lock:
            self.calls.append((len(sets), batchable, time.perf_counter()))
        return self._Handle(sets)

    def finish_job(self, handle):
        if self.finish_delay:
            time.sleep(self.finish_delay)
        return True

    def close(self):
        pass


def single(i):
    return SignatureSet.single(i, ("m", i), ("s", i))


def wire_single(i):
    return WireSignatureSet.single(i, b"m" * 32, b"\xc0" + b"\x00" * 95)


def agg(i, k=10):
    return SignatureSet.aggregate(list(range(k)), ("m", i), ("s", i))


def submit(svc, s, priority=False):
    return svc.verify_signature_sets_async(
        [s], VerifyOptions(batchable=True, priority=priority)
    )


def test_exact_bucket_fill_flushes_immediately():
    stub = HandleStub()
    svc = BlsVerificationPipeline(stub, standard_wait_ms=10_000)
    t0 = time.perf_counter()
    futs = [submit(svc, single(i)) for i in range(128)]
    assert all(f.result(timeout=5) for f in futs)
    assert time.perf_counter() - t0 < 5  # did not wait out the window
    svc.close()
    assert [c[0] for c in stub.calls] == [128]
    stats = svc.flush_stats()
    assert len(stats) == 1 and stats[0]["reason"] == "fill"
    assert stats[0]["fill_ratio"] == 1.0
    assert stub.metrics.flush_reason.get("fill") == 1.0


def test_shape_buckets_accumulate_separately():
    """Wire vs decoded and K=1 vs K=16 sets land in DIFFERENT buckets:
    127 decoded singles + 1 aggregate total 128 but neither bucket
    fills, so nothing dispatches until the 128th single arrives."""
    stub = HandleStub()
    svc = BlsVerificationPipeline(stub, standard_wait_ms=8_000)
    futs = [submit(svc, single(i)) for i in range(127)]
    futs.append(submit(svc, agg(0)))
    futs.append(submit(svc, wire_single(0)))
    time.sleep(0.05)
    assert stub.calls == []  # three partial buckets, no fill
    futs.append(submit(svc, single(999)))  # singles bucket: 127 -> 128
    assert futs[0].result(timeout=5)
    time.sleep(0.1)
    svc.close()
    # exactly the singles bucket dispatched (one 128-set job); the
    # aggregate and wire buckets flushed only at close
    fill_calls = [c for c in stub.calls if c[0] == 128]
    assert len(fill_calls) == 1
    reasons = [r["reason"] for r in svc.flush_stats()]
    assert reasons.count("fill") == 1
    assert reasons.count("close") == 2


def test_deadline_flush_reports_reason_and_ratio():
    stub = HandleStub()
    svc = BlsVerificationPipeline(stub, standard_wait_ms=40)
    futs = [submit(svc, single(i)) for i in range(32)]
    assert all(f.result(timeout=5) for f in futs)
    svc.close()
    stats = svc.flush_stats()
    assert stats and stats[0]["reason"] == "deadline"
    assert stats[0]["sets"] == 32 and stats[0]["n_bucket"] == 128
    assert stats[0]["fill_ratio"] == pytest.approx(0.25)
    assert stub.metrics.flush_reason.get("deadline") >= 1.0
    assert stub.metrics.bucket_fill_ratio.count >= 1


def test_deadline_anchors_on_oldest_set():
    """Regression (ISSUE 11 satellite): staggered submits into one
    bucket must flush when the OLDEST set's window expires — a timer
    re-anchored on the newest submit would stretch p99 submit->flush
    beyond the window."""
    stub = HandleStub()
    svc = BlsVerificationPipeline(stub, standard_wait_ms=400)
    t0 = time.perf_counter()
    fa = submit(svc, single(0))
    time.sleep(0.35)  # inside the window
    fb = submit(svc, single(1))
    assert fa.result(timeout=5) and fb.result(timeout=5)
    elapsed = time.perf_counter() - t0
    svc.close()
    # correct anchor: ~0.40s from the first submit; re-anchored-on-B
    # would be ~0.75s
    assert elapsed < 0.62, f"flush took {elapsed:.3f}s — deadline re-anchored?"
    assert sum(c[0] for c in stub.calls) == 2


def test_critical_lane_is_not_starved_by_standard_fill():
    stub = HandleStub()
    svc = BlsVerificationPipeline(
        stub, critical_wait_ms=30, standard_wait_ms=10_000
    )
    std = [submit(svc, single(i)) for i in range(20)]
    crit = submit(svc, agg(0, k=3), priority=True)
    assert crit.result(timeout=5)  # short lane flushed by deadline
    assert all(not f.done() for f in std)  # standard lane still filling
    svc.close()
    lanes = {r["lane"]: r["reason"] for r in svc.flush_stats()}
    assert lanes.get("critical") == "deadline"
    assert lanes.get("standard") == "close"


def test_high_water_backpressure_counts_sets():
    stub = HandleStub()
    svc = BlsVerificationPipeline(
        stub, standard_wait_ms=150, high_water_sets=8
    )
    assert svc.can_accept_work()
    futs = [submit(svc, single(i)) for i in range(10)]
    assert not svc.can_accept_work()  # 10 buffered sets >= 8
    assert svc.pending_sets() == 10
    assert all(f.result(timeout=5) for f in futs)
    deadline = time.time() + 5
    while not svc.can_accept_work() and time.time() < deadline:
        time.sleep(0.01)
    assert svc.can_accept_work()  # drained below the high-water mark
    assert svc.pending_sets() == 0
    svc.close()


def test_escape_hatch_falls_back_to_flat_buffer(monkeypatch):
    monkeypatch.setenv("LODESTAR_TPU_BLS_PIPELINE", "0")
    svc = create_bls_service(HandleStub())
    assert type(svc) is BlsVerifierService
    svc.close()
    monkeypatch.setenv("LODESTAR_TPU_BLS_PIPELINE", "1")
    svc = create_bls_service(HandleStub())
    assert isinstance(svc, BlsVerificationPipeline)
    svc.close()


def test_non_batchable_jobs_bypass_buckets():
    stub = HandleStub()
    svc = BlsVerificationPipeline(stub, standard_wait_ms=10_000)
    fut = svc.verify_signature_sets_async([single(0)], VerifyOptions())
    assert fut.result(timeout=5)
    svc.close()
    assert stub.calls and stub.calls[0][0] == 1
    assert svc.flush_stats() == []  # never touched an accumulator


def _p99(latencies):
    xs = sorted(latencies)
    return xs[min(len(xs) - 1, int(0.99 * (len(xs) - 1)))] if xs else None


def test_occupancy_beats_flat_coalescer_at_equal_critical_p99():
    """ISSUE 11 acceptance oracle (fast stub): a trickling multi-subnet
    flood — 16 waves of 8 attestations, one block-critical aggregate on
    waves 0 and 8 — through BOTH feeds concurrently.

      - the PR 10 flat coalescer's 40 ms window flushes each wave as its
        own ~8-set job padded to the 128 bucket (occupancy ~0.06),
      - the pipeline accumulates the standard lane across waves to an
        exact 128 fill (occupancy 1.0 there), criticals riding the
        short lane,

    asserting set-weighted mean occupancy >= 2x the coalescer while the
    critical sets' p99 submit->verdict latency stays equal (the short
    lane undercuts the flat window)."""
    old_stub, new_stub = HandleStub(), HandleStub()
    old = BlsVerifierService(old_stub, buffer_wait_ms=40, max_buffered_sigs=512)
    new = BlsVerificationPipeline(
        new_stub, critical_wait_ms=30, standard_wait_ms=5_000
    )
    crit_lat = {"old": [], "new": []}
    futs = []

    def track(svc, s, bucket_key=None, priority=False):
        t0 = time.perf_counter()
        f = svc.verify_signature_sets_async(
            [s], VerifyOptions(batchable=True, priority=priority)
        )
        if bucket_key is not None:
            f.add_done_callback(
                lambda _f, t0=t0: crit_lat[bucket_key].append(
                    time.perf_counter() - t0
                )
            )
        futs.append(f)

    idx = 0
    for wave in range(16):
        for _ in range(8):  # 8 subnet atts per wave
            track(old, single(idx))
            track(new, single(idx), priority=False)
            idx += 1
        if wave in (0, 8):  # a block-critical aggregate
            track(old, agg(wave, k=3), bucket_key="old")
            track(new, agg(wave, k=3), bucket_key="new", priority=True)
        time.sleep(0.08)
    assert all(f.result(timeout=10) for f in futs)

    # flat-coalescer occupancy from its job records (sets per padded
    # 128-lane bucket, set-weighted)
    from lodestar_tpu.bls.pipeline import _pad_bucket

    old_jobs = old.job_timings()
    assert old_jobs, "flat coalescer dispatched nothing"
    occ_old = sum(j["sig_sets"] for j in old_jobs) / sum(
        _pad_bucket(j["sig_sets"]) for j in old_jobs
    )
    occ_new = new.mean_fill_ratio()
    old.close()
    new.close()
    assert occ_new is not None
    assert occ_new >= 2 * occ_old, (
        f"pipeline occupancy {occ_new:.3f} < 2x coalescer {occ_old:.3f}"
    )
    # the standard lane filled at least one exact bucket
    assert any(r["reason"] == "fill" for r in new.flush_stats())
    # equal (or better) p99 submit->verdict for block-critical sets;
    # generous slack absorbs scheduler jitter
    p99_old, p99_new = _p99(crit_lat["old"]), _p99(crit_lat["new"])
    assert p99_old is not None and p99_new is not None
    assert p99_new <= p99_old + 0.20, (
        f"critical p99 regressed: pipeline {p99_new:.3f}s vs "
        f"coalescer {p99_old:.3f}s"
    )


def test_close_rejects_buffered_jobs_and_records_close_flush():
    stub = HandleStub()
    svc = BlsVerificationPipeline(stub, standard_wait_ms=60_000)
    fut = submit(svc, single(0))
    svc.close()
    with pytest.raises(RuntimeError):
        fut.result(timeout=5)
    assert [r["reason"] for r in svc.flush_stats()] == ["close"]
    assert svc.pending_sets() == 0


def test_closed_rejection_settles_outside_lock(monkeypatch):
    """Regression (tpulint async-lock-safety): submitting to a closed
    service used to settle the job future INSIDE `with self._lock:`.
    set_exception runs done-callbacks synchronously, so a continuation
    (DeferredVerdict, aggregate-forward fan-out) would execute under
    the service Condition — re-entering the service deadlocks."""
    import lodestar_tpu.bls.service as service_mod

    violations = []
    locks = []

    class ProbeFuture(service_mod.Future):
        def set_exception(self, exc):
            if any(lk._is_owned() for lk in locks):
                violations.append(repr(exc))
            super().set_exception(exc)

    monkeypatch.setattr(service_mod, "Future", ProbeFuture)
    stub = HandleStub()
    svc = BlsVerificationPipeline(stub, standard_wait_ms=60_000)
    locks.append(svc._lock)
    svc.close()
    fut = svc.verify_signature_sets_async(
        [single(0)], VerifyOptions(batchable=True)
    )
    with pytest.raises(RuntimeError, match="verifier closed"):
        fut.result(timeout=5)
    # the continuation can even re-enter the service safely
    reentered = []
    fut2 = svc.verify_signature_sets_async(
        [single(1)], VerifyOptions(batchable=True)
    )
    fut2.add_done_callback(
        lambda f: reentered.append(
            svc.verify_signature_sets_async(
                [single(2)], VerifyOptions(batchable=True)
            )
        )
    )
    assert len(reentered) == 1
    assert violations == []


def test_bench_pipeline_probe_skip_semantics(capsys):
    """bench.py's `bls_pipeline_verified_atts_per_s` probe: any failure
    emits ONE machine-readable skip record (value null, skipped true) —
    never a traceback-only exit and never a measured-looking zero."""
    import json

    import bench

    class Broken:
        _use_rlc = True
        table = []  # len() == 0 -> the probe blows up deterministically

    bench._probe_pipeline(Broken())
    out = capsys.readouterr().out.strip().splitlines()
    recs = [json.loads(l) for l in out if l.startswith("{")]
    assert len(recs) == 1
    rec = recs[0]
    assert rec["metric"] == "bls_pipeline_verified_atts_per_s"
    assert rec["value"] is None and rec["skipped"] is True
    assert rec["unit"] == "atts/s"
    assert "pipeline-probe" in rec["error"]
    assert "phases" in rec


def test_bench_pipeline_probe_respects_rlc_escape_hatch(capsys):
    import json

    import bench

    class RlcOff:
        _use_rlc = False

    bench._probe_pipeline(RlcOff())
    recs = [
        json.loads(l)
        for l in capsys.readouterr().out.strip().splitlines()
        if l.startswith("{")
    ]
    assert len(recs) == 1 and recs[0]["skipped"] is True
    assert "RLC disabled" in recs[0]["error"]


def test_bench_pipeline_probe_happy_path_emits_record(capsys, monkeypatch):
    """The probe's gossip->processor->pipeline loop end-to-end with a
    stub device: one measured JSON record with throughput, occupancy,
    and critical-lane p99 populated."""
    import json

    import bench

    class FakeMessages:
        def get_many(self, roots):
            return [None] * len(roots)

    class FakeVerifier(HandleStub):
        _use_rlc = True
        table = list(range(512))
        messages = FakeMessages()

    monkeypatch.setattr(bench, "BENCH_PIPELINE_ATTS", 32)
    monkeypatch.setattr(bench, "BENCH_PIPELINE_SUBNETS", 4)
    monkeypatch.setattr(bench, "BENCH_PIPELINE_WAVES", 2)
    bench._probe_pipeline(FakeVerifier())
    recs = [
        json.loads(l)
        for l in capsys.readouterr().out.strip().splitlines()
        if l.startswith("{")
    ]
    assert len(recs) == 1
    rec = recs[0]
    assert rec["metric"] == "bls_pipeline_verified_atts_per_s"
    assert rec.get("skipped") is None and rec["value"] > 0
    assert rec["unit"] == "atts/s"
    assert 0 < rec["bucket_occupancy_mean"] <= 1.0
    assert rec["critical_p99_submit_to_verdict_s"] > 0
    assert sum(rec["flush_reasons"].values()) >= 1


def test_flush_emits_pipeline_span():
    from lodestar_tpu import observability as OB

    OB.configure(enabled=True)
    OB.get_tracer().clear()
    try:
        stub = HandleStub()
        svc = BlsVerificationPipeline(stub, standard_wait_ms=10_000)
        futs = [submit(svc, single(i)) for i in range(128)]
        assert all(f.result(timeout=5) for f in futs)
        svc.close()
        spans = [
            r
            for r in OB.get_tracer().snapshot()
            if r.name == "bls.pipeline.flush"
        ]
        assert spans, "no bls.pipeline.flush span recorded"
        attrs = spans[0].attrs
        assert attrs["reason"] == "fill" and attrs["sets"] == 128
        assert attrs["n_bucket"] == 128 and attrs["lane"] == "standard"
    finally:
        OB.configure(enabled=False)
        OB.get_tracer().clear()


def test_multi_set_job_crossing_a_boundary_flushes_prefix():
    """Review fix: a 3-set job arriving on a 127-set bucket overshoots
    the 128 boundary — the near-boundary jobs dispatch immediately
    (occupancy ~1.0) and the new job starts a fresh accumulation,
    instead of the whole bucket stranding until the deadline at half
    occupancy."""
    stub = HandleStub()
    svc = BlsVerificationPipeline(stub, standard_wait_ms=10_000)
    futs = [submit(svc, single(i)) for i in range(127)]
    fut3 = svc.verify_signature_sets_async(
        [single(200), single(201), single(202)],
        VerifyOptions(batchable=True),
    )
    assert all(f.result(timeout=5) for f in futs)  # prefix dispatched
    assert not fut3.done()  # the overshooting job keeps accumulating
    stats = svc.flush_stats()
    assert stats and stats[-1]["reason"] == "spill"
    assert stats[-1]["sets"] == 127
    assert stats[-1]["fill_ratio"] == pytest.approx(127 / 128)
    svc.close()


def test_job_exactly_filling_a_bucket_after_spill_flushes_immediately():
    """Review fix: after a spill the fresh accumulator re-runs the fill
    check, so a job that alone exactly fills a bucket dispatches now
    instead of waiting out the lane deadline."""
    stub = HandleStub()
    svc = BlsVerificationPipeline(stub, standard_wait_ms=10_000)
    futs = [submit(svc, single(i)) for i in range(100)]
    big = svc.verify_signature_sets_async(
        [single(1000 + i) for i in range(128)],
        VerifyOptions(batchable=True),
    )
    assert all(f.result(timeout=5) for f in futs)
    assert big.result(timeout=5)  # did NOT wait for the 10s window
    svc.close()
    reasons = [(r["reason"], r["sets"]) for r in svc.flush_stats()]
    assert ("spill", 100) in reasons and ("fill", 128) in reasons


def test_padded_lanes_splits_oversized_flushes():
    from lodestar_tpu.bls.pipeline import _padded_lanes

    assert _padded_lanes(1, 512) == 128
    assert _padded_lanes(128, 512) == 128
    assert _padded_lanes(130, 512) == 256
    assert _padded_lanes(512, 512) == 512
    assert _padded_lanes(513, 512) == 512 + 128  # 512-run + padded 1
    assert _padded_lanes(1024, 512) == 1024


def test_pending_sets_gauge_tracks_transitions():
    """Review fix: the lodestar_bls_pipeline_pending_sets gauge follows
    every transition (submit/resolve), not just flushes — an idle
    pipeline reads 0."""
    stub = HandleStub()
    svc = BlsVerificationPipeline(stub, standard_wait_ms=10_000)
    futs = [submit(svc, single(i)) for i in range(128)]
    assert all(f.result(timeout=5) for f in futs)
    deadline = time.time() + 5
    while stub.metrics.pipeline_pending_sets.value != 0 and time.time() < deadline:
        time.sleep(0.01)
    assert stub.metrics.pipeline_pending_sets.value == 0
    svc.close()


def test_job_cap_does_not_bind_before_set_high_water():
    """Review fix: backpressure is counted in SETS — 600 buffered
    single-set gossip jobs must NOT trip the inherited 512-job cap when
    the set high-water mark (1000) has headroom."""
    stub = HandleStub()
    svc = BlsVerificationPipeline(
        stub, standard_wait_ms=60_000, high_water_sets=1000
    )
    futs = [submit(svc, single(i)) for i in range(600)]
    del futs
    # 512 flushed on exact fills resolve; the 88-set remainder stays
    # buffered toward the (long) deadline
    deadline = time.time() + 5
    while svc.pending_sets() > 600 - 512 and time.time() < deadline:
        time.sleep(0.01)
    assert svc.pending_sets() == 600 - 512
    # top back up past the old job cap with fresh buffered jobs
    futs2 = [submit(svc, single(1000 + i)) for i in range(520)]
    del futs2
    assert svc.can_accept_work()  # < 1000 sets: still accepting
    svc.close()


# ---------------------------------------------------------------------------
# ISSUE 12: gossip handlers route block-critical verification through the
# service's critical lane (PR 11 ROADMAP leftover) + flush-record telemetry
# ---------------------------------------------------------------------------


class RawSpy:
    """Raw-verifier stand-in that counts synchronous calls."""

    def __init__(self):
        self.calls = 0

    def verify_signature_sets(self, sets, opts=None):
        self.calls += 1
        return True


def test_gossip_validators_priority_rides_critical_lane_under_flood():
    """Regression (ISSUE 12 satellite): a flood of subnet attestations
    filling the standard lane cannot starve an aggregate verification
    past the critical window.  GossipValidators with a wired service
    routes `priority=True` verifications through the pipeline's 25 ms
    lane; the raw verifier is NOT called for them."""
    from lodestar_tpu.chain.validation import GossipValidators

    stub = HandleStub()
    pipe = BlsVerificationPipeline(
        stub, critical_wait_ms=30, standard_wait_ms=10_000
    )
    raw = RawSpy()
    v = GossipValidators(chain=None, verifier=raw, bls_service=pipe)
    # the flood: 100 subnet attestations parked on the standard lane
    # (far from the 128 fill, 10 s window — they are going nowhere)
    std = [submit(pipe, single(i)) for i in range(100)]
    t0 = time.perf_counter()
    v._verify([agg(0, k=3)], priority=True)  # no exception = verified
    dt = time.perf_counter() - t0
    assert dt < 2.0, f"critical verification took {dt:.3f}s — starved?"
    assert raw.calls == 0  # routed through the service, not raw
    assert all(not f.done() for f in std)  # flood still parked
    # the flush that carried it rode the critical lane within window
    lanes = [r for r in pipe.flush_stats() if r["lane"] == "critical"]
    assert lanes and lanes[0]["oldest_wait_s"] < 1.0
    # non-priority verification still uses the raw verifier (subnet
    # attestations must not pay the standard lane's window here)
    v._verify([single(999)], priority=False)
    assert raw.calls == 1
    pipe.close()


def test_gossip_validators_without_service_keep_raw_path():
    from lodestar_tpu.chain.validation import GossipValidators

    raw = RawSpy()
    v = GossipValidators(chain=None, verifier=raw)
    v._verify([single(0)], priority=True)  # no service: raw fallback
    assert raw.calls == 1


def test_flush_records_carry_seq_and_oldest_wait():
    """The SLO engine consumes flush records incrementally by `seq` and
    judges the critical lane by `oldest_wait_s` (ISSUE 12)."""
    stub = HandleStub()
    svc = BlsVerificationPipeline(stub, standard_wait_ms=40)
    futs = [submit(svc, single(i)) for i in range(128)]  # exact fill
    assert all(f.result(timeout=5) for f in futs)
    fut = submit(svc, single(999))  # deadline flush
    assert fut.result(timeout=5)
    svc.close()
    stats = svc.flush_stats()
    assert [r["seq"] for r in stats] == sorted(
        r["seq"] for r in stats
    ) and len({r["seq"] for r in stats}) == len(stats)
    fill = next(r for r in stats if r["reason"] == "fill")
    assert 0.0 <= fill["oldest_wait_s"] < 5.0
    deadline_rec = next(r for r in stats if r["reason"] == "deadline")
    # the deadline flush waited out (about) the 40 ms window
    assert deadline_rec["oldest_wait_s"] >= 0.035


def test_bench_failure_records_carry_slo_snapshot_and_flight_record(
    capsys, monkeypatch, tmp_path
):
    """ISSUE 12 acceptance: a bench skip/failure record carries the SLO
    snapshot and a flight-record path, so a future r06 backend-init
    failure leaves a forensic artifact instead of a bare null."""
    import json
    import os

    import bench

    monkeypatch.setenv("BENCH_FLIGHTREC_DIR", str(tmp_path / "fr"))
    monkeypatch.setattr(bench, "_FLIGHT_RECORDER", None)
    bench._emit_failure("backend-init-probe", "stub tunnel death")
    out = capsys.readouterr().out.strip().splitlines()
    rec = json.loads(out[-1])
    assert rec["skipped"] is True and rec["value"] is None
    assert "slo" in rec and "breaches" in rec["slo"]
    assert rec["flight_record"] is not None
    assert os.path.isdir(rec["flight_record"])
    from lodestar_tpu.observability.flight_recorder import load_bundle

    bundle = load_bundle(rec["flight_record"])
    assert bundle["manifest"]["reason"] == "bench.backend-init-probe"
    assert "stub tunnel death" in bundle["manifest"]["context"]["detail"]
    # the bundle carries the phase timings + SLO counters
    assert "phases.json" in bundle["files"]
    assert "slo.json" in bundle["files"]
    # traces parse even with tracing off (empty event list)
    assert isinstance(
        bundle["files"]["trace.json"]["traceEvents"], list
    )


def test_bench_failure_without_recorder_env_writes_nothing(
    capsys, monkeypatch, tmp_path
):
    import json

    import bench

    monkeypatch.delenv("BENCH_FLIGHTREC_DIR", raising=False)
    monkeypatch.setattr(bench, "_FLIGHT_RECORDER", None)
    monkeypatch.setattr(bench, "_FLIGHTREC_ON", False)
    monkeypatch.chdir(tmp_path)
    bench._emit_failure("run", "in-process stub failure")
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["flight_record"] is None
    assert "slo" in rec  # the snapshot still attaches
    assert not (tmp_path / "flightrec_bench").exists()


def test_bench_measured_records_carry_slo_snapshot(capsys, monkeypatch):
    import json

    import bench

    class FakeMessages:
        def get_many(self, roots):
            return [None] * len(roots)

    class FakeVerifier(HandleStub):
        _use_rlc = True
        table = list(range(512))
        messages = FakeMessages()

    monkeypatch.setattr(bench, "BENCH_PIPELINE_ATTS", 16)
    monkeypatch.setattr(bench, "BENCH_PIPELINE_SUBNETS", 4)
    monkeypatch.setattr(bench, "BENCH_PIPELINE_WAVES", 1)
    bench._probe_pipeline(FakeVerifier())
    recs = [
        json.loads(l)
        for l in capsys.readouterr().out.strip().splitlines()
        if l.startswith("{")
    ]
    assert len(recs) == 1 and recs[0].get("skipped") is None
    assert "slo" in recs[0] and "breaches" in recs[0]["slo"]


def test_lone_critical_job_flushes_immediately_when_idle():
    """Review fix: a critical job submitted into an otherwise-idle
    pipeline must NOT serialize the full lane window — the synchronous
    gossip loop verifies aggregates one at a time, and a pure 25 ms
    wait per message would add >1 s/slot of idle to the scheduler."""
    stub = HandleStub()
    svc = BlsVerificationPipeline(
        stub, critical_wait_ms=5_000, standard_wait_ms=10_000
    )
    t0 = time.perf_counter()
    fut = submit(svc, agg(0, k=3), priority=True)
    assert fut.result(timeout=5)
    dt = time.perf_counter() - t0
    assert dt < 1.0, f"idle critical job waited {dt:.3f}s (lane window?)"
    stats = svc.flush_stats()
    assert stats and stats[0]["reason"] == "idle"
    assert stats[0]["lane"] == "critical"
    # with standard work ACCUMULATING, criticals coalesce toward the
    # deadline as before (the idle fast path must not fire under load)
    futs = [submit(svc, single(i)) for i in range(8)]
    crit = submit(svc, agg(1, k=3), priority=True)
    time.sleep(0.05)
    assert not crit.done()  # parked on the (long) critical deadline
    svc.close()
    del futs
    lanes = [r for r in svc.flush_stats() if r["lane"] == "critical"]
    assert [r["reason"] for r in lanes] == ["idle", "close"]


# -- ISSUE 14: device-fault recovery probe ----------------------------------


class FaultableProbeVerifier:
    """bench breaker-probe stub: the supervised begin/finish protocol
    over a no-crypto oracle whose device leg goes through
    `_device_call` — the exact seam the probe wraps to inject the
    mid-flood fault — with a real DeviceSupervisor + auto re-probe."""

    max_job_sets = 512
    _use_rlc = True
    table = list(range(64))

    class _Messages:
        def get_many(self, roots):
            return [None] * len(roots)

    class _Handle:
        def __init__(self, sets, host):
            self.sets = sets
            self.ok_big = True
            self.batch_retries = 0
            self.batch_sigs_success = len(sets)
            self.verdicts = None
            self.host = host

    def __init__(self):
        from lodestar_tpu.bls.supervisor import DeviceSupervisor

        self.metrics = BlsPoolMetrics()
        self.messages = self._Messages()
        self.supervisor = DeviceSupervisor(
            registry=self.metrics.registry,
            enabled=True,
            auto_probe=True,
            backoff_initial_s=0.05,
            canary=self._canary,
        )

    def _device_call(self, name, fn, args):
        return fn(*args)

    def _canary(self):
        return bool(self._device_call("canary", lambda: True, ()))

    def begin_job(self, sets, batchable):
        return self._Handle(sets, host=not self.supervisor.device_allowed())

    def finish_job(self, handle):
        from lodestar_tpu.bls.supervisor import classify_failure

        if handle.host:
            self.supervisor.note_host_fallback(len(handle.sets))
            return True  # host oracle: all probe atts are valid
        try:
            self._device_call("each", lambda: True, ())
            self.supervisor.record_success()
            return True
        except Exception as e:  # noqa: BLE001 — the production seam
            self.supervisor.record_failure(
                classify_failure(e), "finish_job", str(e)
            )
            return True  # host fallback verdict

    def verify_signature_sets(self, sets, opts=None):
        job = self.begin_job(list(sets), True)
        return self.finish_job(job)

    def can_accept_work(self):
        return True

    def close(self):
        self.supervisor.close()


def test_bench_breaker_probe_measures_recovery(capsys, monkeypatch):
    """ISSUE 14 satellite: the bls_device_fault_recovery_seconds probe
    injects a fault mid-flood, loses zero verdicts, and reports the
    trip->device-verdict wall clock once the auto canary restores the
    device path."""
    import json

    import bench

    monkeypatch.setattr(bench, "BENCH_BREAKER_FLOOD_ATTS", 32)
    v = FaultableProbeVerifier()
    bench._probe_breaker_recovery(v)
    recs = [
        json.loads(l)
        for l in capsys.readouterr().out.strip().splitlines()
        if l.startswith("{")
    ]
    assert len(recs) == 1, recs
    rec = recs[0]
    assert rec["metric"] == "bls_device_fault_recovery_seconds"
    assert rec.get("skipped") is None, rec
    assert rec["unit"] == "s" and rec["value"] > 0
    assert rec["breaker_trips"] == 1
    assert rec["time_in_degraded_s"] > 0
    assert rec["breaker"]["trips"] >= 1  # the per-record snapshot
    v.close()


def test_bench_breaker_probe_skips_when_disabled(capsys):
    import json

    import bench

    class NoSup:
        supervisor = None

    bench._probe_breaker_recovery(NoSup())
    recs = [
        json.loads(l)
        for l in capsys.readouterr().out.strip().splitlines()
        if l.startswith("{")
    ]
    assert len(recs) == 1
    assert recs[0]["metric"] == "bls_device_fault_recovery_seconds"
    assert recs[0]["skipped"] is True
    assert "disabled" in recs[0]["error"]


def test_bench_records_carry_breaker_snapshot(capsys, monkeypatch):
    """Every bench record — measured and skipped — carries the
    `breaker` snapshot (state, trips, time-in-degraded)."""
    import json

    import bench

    monkeypatch.delenv("BENCH_FLIGHTREC_DIR", raising=False)
    monkeypatch.setattr(bench, "_FLIGHTREC_ON", False)
    bench._emit_failure("backend-init-probe", "stub death")
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[0])
    assert rec["breaker"]["state"] in ("closed", "half_open", "open")
    assert "trips" in rec["breaker"]
    assert "time_in_degraded_s" in rec["breaker"]
