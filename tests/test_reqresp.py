"""Req/resp protocol layer: chunk streams, rate limiting, handlers.

Reference behaviors: packages/reqresp/src/ReqResp.ts (request/response
flow), rate_limiter/rateLimiterGRCA.ts (GCRA), encodingStrategies
(ssz_snappy chunks), and the beacon-node bindings protocols.ts:8-87 +
rateLimit.ts + handlers/.
"""

import pytest

from lodestar_tpu import params
from lodestar_tpu import types as T
from lodestar_tpu.network import snappy as SN
from lodestar_tpu.network.reqresp import (
    ContextBytes,
    InboundRateLimitQuota,
    Protocol,
    RateLimiterGRCA,
    RateLimiterQuota,
    ReqResp,
    ReqRespError,
    ReqRespMethod,
    RespCode,
    connect_inmemory,
    decode_response_chunks,
    encode_error_chunk,
    encode_response_chunks,
)
from lodestar_tpu.network.reqresp_protocols import (
    BeaconBlocksByRangeRequest,
    LightClientUpdateType,
    METADATA_TYPE,
    ReqRespBeaconNode,
    StatusType,
    decode_block_chunks,
    light_client_update_from_value,
    light_client_update_to_value,
    ping_protocol,
    status_protocol,
)

pytestmark = pytest.mark.smoke

P = params.ACTIVE_PRESET


# -- chunk stream codec -----------------------------------------------------


def test_response_chunk_stream_roundtrip():
    chunks = [(b"a" * 40, None), (b"", None), (b"b" * 100_000, None)]
    stream = encode_response_chunks(chunks)
    back = decode_response_chunks(stream, ContextBytes.empty)
    assert [c[0] for c in back] == [c[0] for c in chunks]


def test_response_chunk_stream_with_context_bytes():
    chunks = [(b"x" * 10, b"\x01\x02\x03\x04"), (b"y" * 20, b"\xaa\xbb\xcc\xdd")]
    stream = encode_response_chunks(chunks)
    back = decode_response_chunks(stream, ContextBytes.fork_digest)
    assert back == chunks


def test_error_chunk_raises():
    stream = encode_error_chunk(RespCode.RESOURCE_UNAVAILABLE, "nope")
    with pytest.raises(ReqRespError, match="nope"):
        decode_response_chunks(stream, ContextBytes.empty)


def test_chunk_at_decodes_concatenation():
    a = SN.encode_reqresp_chunk(b"first")
    b = SN.encode_reqresp_chunk(b"")
    c = SN.encode_reqresp_chunk(b"third" * 1000)
    data = a + b + c
    p0, pos = SN.decode_reqresp_chunk_at(data, 0)
    p1, pos = SN.decode_reqresp_chunk_at(data, pos)
    p2, pos = SN.decode_reqresp_chunk_at(data, pos)
    assert (p0, p1, p2) == (b"first", b"", b"third" * 1000)
    assert pos == len(data)


# -- GCRA rate limiter ------------------------------------------------------


def test_gcra_allows_burst_then_limits():
    t = [0.0]
    rl = RateLimiterGRCA(RateLimiterQuota(5, 15_000), clock=lambda: t[0])
    for _ in range(5):
        assert rl.allows("peer-a")
    assert not rl.allows("peer-a")
    assert rl.allows("peer-b")  # per-key isolation
    t[0] += 3.0  # one token replenished (15s / 5)
    assert rl.allows("peer-a")
    assert not rl.allows("peer-a")


def test_gcra_token_counts():
    t = [0.0]
    rl = RateLimiterGRCA(RateLimiterQuota(100, 10_000), clock=lambda: t[0])
    assert rl.allows("p", 80)
    assert not rl.allows("p", 40)  # 80 + 40 > 100
    assert rl.allows("p", 20)


# -- node-to-node flows -----------------------------------------------------


def _two_nodes(clock=None):
    kwargs = {"clock": clock} if clock is not None else {}
    a, b = ReqResp(**kwargs), ReqResp(**kwargs)
    connect_inmemory(a, "A", b, "B")
    return a, b


def test_status_handshake_between_nodes():
    a, b = _two_nodes()
    seen = {}
    status_b = {
        "fork_digest": b"\x01\x00\x00\x00",
        "finalized_root": b"\x11" * 32,
        "finalized_epoch": 7,
        "head_root": b"\x22" * 32,
        "head_slot": 321,
    }
    proto = status_protocol()

    def handler(peer, req):
        seen[peer] = req
        return [(StatusType.serialize(status_b), None)]

    b.register_protocol(proto, handler)
    my_status = dict(status_b, head_slot=99)
    chunks = a.send_request("B", proto, my_status)
    got = StatusType.deserialize(chunks[0][0])
    assert got["head_slot"] == 321
    assert seen["A"]["head_slot"] == 99


def test_rate_limited_peer_gets_error_chunk():
    t = [0.0]
    a, b = _two_nodes(clock=lambda: t[0])
    proto = ping_protocol()
    b.register_protocol(proto, lambda peer, seq: [(b"\x00" * 8, None)])
    # quota: 2 per 10s
    a.send_request("B", proto, 1)
    a.send_request("B", proto, 2)
    with pytest.raises(ReqRespError, match="rate limited"):
        a.send_request("B", proto, 3)
    t[0] += 5.0
    a.send_request("B", proto, 4)  # replenished


def test_unknown_protocol_and_handler_crash():
    a, b = _two_nodes()
    bogus = Protocol(
        method=ReqRespMethod.ping, version=9,
        context_bytes=ContextBytes.empty,
        encode_request=lambda x: b"\x00" * 8,
        decode_request=lambda d: d,
    )
    with pytest.raises(ReqRespError, match="unsupported"):
        a.send_request("B", bogus, 0)
    crash = ping_protocol()

    def boom(peer, req):
        raise RuntimeError("kaboom")

    b.register_protocol(crash, boom)
    with pytest.raises(ReqRespError, match="kaboom"):
        a.send_request("B", crash, 1)


# -- beacon-node bindings ---------------------------------------------------


class _FakeChain:
    def __init__(self, cfg, head_state, head_root, blocks):
        self.config = cfg
        self._head_state = head_state
        self._head_root = head_root
        self._blocks = blocks  # root -> signed block

    @property
    def head_state(self):
        return self._head_state

    def get_head_root(self):
        return self._head_root

    def get_block(self, root):
        return self._blocks.get(bytes(root))


def _mini_world():
    from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
    from lodestar_tpu.params import ForkName

    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG, fork_epochs={ForkName.altair: 0}
    )

    class _St:
        slot = 5
        finalized_checkpoint = {"epoch": 0, "root": b"\x00" * 32}

    def mk_block(slot):
        blk = T.BeaconBlockAltair.default()
        blk["slot"] = slot
        return {
            "message": blk,
            "signature": b"\x00" * 96,
        }

    blocks = {bytes([i]) * 32: mk_block(i) for i in range(1, 4)}
    chain = _FakeChain(cfg, _St(), b"\x03" * 32, blocks)
    return cfg, chain, blocks


def test_beacon_node_bindings_end_to_end():
    cfg, chain, blocks = _mini_world()
    server = ReqResp()
    client = ReqResp()
    connect_inmemory(client, "C", server, "S")
    md = {"seq_number": 3, "attnets": [False] * 64, "syncnets": [True] * 4}
    node = ReqRespBeaconNode(
        server, cfg, chain=chain, metadata_fn=lambda: md
    )
    # status
    chunks = client.send_request("S", node.protocols["status"], {
        "fork_digest": cfg.fork_digest(0),
        "finalized_root": b"\x00" * 32,
        "finalized_epoch": 0,
        "head_root": b"\x01" * 32,
        "head_slot": 1,
    })
    st = StatusType.deserialize(chunks[0][0])
    assert st["head_slot"] == 5
    # ping answers the metadata seq number
    chunks = client.send_request("S", node.protocols["ping"], 0)
    assert int.from_bytes(chunks[0][0], "little") == 3
    # metadata (no request body)
    chunks = client.send_request("S", node.protocols["metadata"])
    got = METADATA_TYPE.deserialize(chunks[0][0])
    assert got["seq_number"] == 3 and got["syncnets"] == [True] * 4
    # blocks by root (fork digest context bytes attached)
    chunks = client.send_request(
        "S", node.protocols["blocks_by_root"], [bytes([2]) * 32, b"\x99" * 32]
    )
    assert len(chunks) == 1  # unknown root skipped
    decoded = decode_block_chunks(cfg, chunks)
    assert decoded[0]["message"]["slot"] == 2
    assert chunks[0][1] == cfg.fork_digest(2)


def test_blocks_by_range_from_archive(tmp_path):
    from lodestar_tpu.db.beacon_db import BeaconDb

    cfg, chain, blocks = _mini_world()
    db = BeaconDb(str(tmp_path / "db"))
    for root, signed in blocks.items():
        db.archive_block(int(signed["message"]["slot"]), signed, root)
    server = ReqResp()
    client = ReqResp()
    connect_inmemory(client, "C", server, "S")
    node = ReqRespBeaconNode(server, cfg, chain=chain, db=db)
    chunks = client.send_request(
        "S",
        node.protocols["blocks_by_range"],
        {"start_slot": 1, "count": 10, "step": 1},
    )
    decoded = decode_block_chunks(cfg, chunks)
    assert [b["message"]["slot"] for b in decoded] == [1, 2, 3]
    # count-weighted rate limiting: a huge request burns the quota
    client.send_request(
        "S",
        node.protocols["blocks_by_range"],
        {"start_slot": 0, "count": 1000, "step": 1},
    )
    with pytest.raises(ReqRespError, match="rate limited"):
        client.send_request(
            "S",
            node.protocols["blocks_by_range"],
            {"start_slot": 0, "count": 100, "step": 1},
        )
    db.close()


def test_light_client_update_wire_roundtrip():
    from lodestar_tpu.light_client.lightclient import LightClientUpdate

    upd = LightClientUpdate(
        attested_header={
            "slot": 40, "proposer_index": 2, "parent_root": b"\x01" * 32,
            "state_root": b"\x02" * 32, "body_root": b"\x03" * 32,
        },
        sync_committee_bits=[True] * P.SYNC_COMMITTEE_SIZE,
        sync_committee_signature=b"\x05" * 96,
        signature_slot=41,
        finalized_header={
            "slot": 8, "proposer_index": 0, "parent_root": b"\x04" * 32,
            "state_root": b"\x05" * 32, "body_root": b"\x06" * 32,
        },
        finality_branch=[bytes([i]) * 32 for i in range(1, 7)],
    )
    value = light_client_update_to_value(upd)
    data = LightClientUpdateType.serialize(value)
    back = light_client_update_from_value(
        LightClientUpdateType.deserialize(data)
    )
    assert back.attested_header == upd.attested_header
    assert back.finality_branch == upd.finality_branch
    assert back.next_sync_committee is None  # zero branch -> absent
    assert back.signature_slot == 41


def test_db_fork_aware_block_codec(tmp_path):
    """Post-altair blocks keep their execution payload through the db
    (an altair-typed repository would silently drop it on put)."""
    from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
    from lodestar_tpu.db.beacon_db import BeaconDb
    from lodestar_tpu.params import ForkName

    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG,
        fork_epochs={ForkName.altair: 0, ForkName.bellatrix: 1},
    )
    db = BeaconDb(str(tmp_path / "db"), config=cfg)
    blk = T.BeaconBlockBellatrix.default()
    blk["slot"] = P.SLOTS_PER_EPOCH + 2  # a bellatrix-era slot
    blk["body"]["execution_payload"]["block_number"] = 77
    signed = {"message": blk, "signature": b"\x01" * 96}
    root = b"\x42" * 32
    db.put_block(root, signed)
    back = db.get_block_anywhere(root)
    assert back["message"]["body"]["execution_payload"]["block_number"] == 77
    # archive path too
    db.archive_block(int(blk["slot"]), signed, root=b"\x43" * 32)
    arch = db.block_archive.get(int(blk["slot"]).to_bytes(8, "big"))
    assert arch["message"]["body"]["execution_payload"]["block_number"] == 77
    db.close()


def test_unknown_error_code_maps_to_server_error():
    # the p2p spec reserves EVERY nonzero result byte as an error
    stream = bytes([4]) + SN.encode_reqresp_chunk(b"weird")
    with pytest.raises(ReqRespError, match="error code 4"):
        decode_response_chunks(stream, ContextBytes.empty)


def test_total_quota_caps_across_peers():
    t = [0.0]
    limits = {
        ReqRespMethod.ping: InboundRateLimitQuota(
            RateLimiterQuota(2, 10_000), total=RateLimiterQuota(3, 10_000)
        )
    }
    server = ReqResp(rate_limits=limits, clock=lambda: t[0])
    proto = ping_protocol()
    server.register_protocol(proto, lambda p, s: [(b"\x00" * 8, None)])
    clients = []
    for name in ("p1", "p2"):
        c = ReqResp(clock=lambda: t[0])
        c.connect("S", lambda pid, req, n=name: server.handle_request(n, pid, req))
        clients.append(c)
    clients[0].send_request("S", proto, 1)
    clients[0].send_request("S", proto, 2)
    clients[1].send_request("S", proto, 3)  # third TOTAL token
    # peer p2 is under its per-peer quota but the node-wide cap trips
    with pytest.raises(ReqRespError, match="rate limited"):
        clients[1].send_request("S", proto, 4)


# -- retry + timeout demotion (ISSUE 14 satellite) --------------------------


def test_stalling_peer_times_out_instead_of_wedging():
    """A transport that never answers costs one bounded wait, not a
    wedged caller (the stalled thread is abandoned)."""
    import threading

    from lodestar_tpu.network.reqresp import ReqRespTimeout

    a = ReqResp()
    stall = threading.Event()
    a.connect("staller", lambda pid, req: stall.wait(timeout=10.0) or b"")
    proto = ping_protocol()
    import time as _time

    t0 = _time.perf_counter()
    with pytest.raises(ReqRespTimeout, match="timed out"):
        a.send_request("staller", proto, 1, timeout_s=0.05)
    assert _time.perf_counter() - t0 < 2.0
    stall.set()


def test_retry_rotates_off_stalling_peer_and_demotes_it():
    """request_with_retry: the timed-out peer is demoted and the retry
    lands on the OTHER peer after a jittered exponential backoff."""
    import random as _random
    import threading

    from lodestar_tpu.network.reqresp import (
        PeerDemotion,
        ReqRespTimeout,
        RetryPolicy,
        request_with_retry,
    )

    server = ReqResp()
    proto = ping_protocol()
    server.register_protocol(proto, lambda p, s: [(b"\x00" * 8, None)])
    client = ReqResp()
    stall = threading.Event()
    client.connect("slow", lambda pid, req: stall.wait(timeout=10.0) or b"")
    client.connect(
        "good", lambda pid, req: server.handle_request("good", pid, req)
    )
    t = [0.0]
    demotion = PeerDemotion(cooldown_initial_s=5.0, clock=lambda: t[0])
    sleeps = []
    peer, chunks = request_with_retry(
        client,
        ["slow", "good"],
        proto,
        body=1,
        timeout_s=0.05,
        policy=RetryPolicy(attempts=3, backoff_initial_s=0.01),
        demotion=demotion,
        rng=_random.Random(0),
        sleep=sleeps.append,
    )
    assert peer == "good" and len(chunks) == 1
    assert len(sleeps) == 1 and 0.005 <= sleeps[0] <= 0.02
    assert demotion.is_demoted("slow") and not demotion.is_demoted("good")
    # demotion orders healthy peers first while the cooldown holds
    assert demotion.order(["slow", "good"]) == ["good", "slow"]
    snap = demotion.snapshot()
    assert snap["slow"]["consecutive_faults"] == 1
    # cooldown expiry rehabilitates; a repeat fault doubles the cooldown
    t[0] += 6.0
    assert not demotion.is_demoted("slow")
    assert demotion.demote("slow") == pytest.approx(10.0)
    # success fully resets the ledger
    demotion.restore("slow")
    assert demotion.snapshot() == {}
    # every peer stalling -> the last error propagates, bounded attempts
    client2 = ReqResp()
    client2.connect(
        "slow", lambda pid, req: stall.wait(timeout=10.0) or b""
    )
    with pytest.raises(ReqRespTimeout):
        request_with_retry(
            client2,
            ["slow"],
            proto,
            body=1,
            timeout_s=0.05,
            policy=RetryPolicy(attempts=2, backoff_initial_s=0.0),
            rng=_random.Random(0),
            sleep=lambda _s: None,
        )
    stall.set()
