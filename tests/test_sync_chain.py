"""SyncChain: batch state machine, peer rotation, download/import overlap.

Reference behaviors: packages/beacon-node/src/sync/range/chain.ts
(SyncChain: batch buffer ahead of the processing cursor, per-batch peer
rotation on failure) and sync/range/batch.ts (download/processing
attempt limits, failed-peer tracking).
"""

import threading
import time

import pytest

from lodestar_tpu import params
from lodestar_tpu import types as T
from lodestar_tpu.chain.chain import BeaconChain
from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
from lodestar_tpu.crypto import bls as B
from lodestar_tpu.crypto import curves as C
from lodestar_tpu.params import ForkName
from lodestar_tpu.ssz import uint64
from lodestar_tpu.state_transition import create_genesis_state, process_slots
from lodestar_tpu.state_transition.accessors import get_beacon_proposer_index
from lodestar_tpu.sync import (
    BatchState,
    RangeSync,
    SyncChain,
    SyncChainError,
)

pytestmark = pytest.mark.smoke

P = params.ACTIVE_PRESET
N_KEYS = 16


@pytest.fixture(scope="module")
def world():
    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG, fork_epochs={ForkName.altair: 0}
    )
    sks = [B.keygen(b"sc-%d" % i) for i in range(N_KEYS)]
    pks = [C.g1_compress(B.sk_to_pk(sk)) for sk in sks]
    genesis = create_genesis_state(cfg, pks, genesis_time=31)
    # a canonical donor chain covering 2+ batches of slots
    donor = BeaconChain(cfg, genesis)
    blocks = [
        _import_block(donor, cfg, sks, s)
        for s in range(1, 2 * P.SLOTS_PER_EPOCH + 3)
    ]
    return cfg, sks, genesis, donor, blocks


def _import_block(chain, cfg, sks, slot):
    head = chain.head_state
    pre = head.clone()
    if pre.slot < slot:
        process_slots(pre, slot)
    proposer = get_beacon_proposer_index(pre)
    epoch = slot // P.SLOTS_PER_EPOCH
    reveal = B.sign_bytes(
        sks[proposer],
        cfg.compute_signing_root(
            uint64.hash_tree_root(epoch),
            cfg.get_domain(slot, params.DOMAIN_RANDAO),
        ),
    )
    from lodestar_tpu.chain.produce_block import produce_block

    block, _post = produce_block(head, slot, reveal)
    root = cfg.compute_signing_root(
        T.BeaconBlockAltair.hash_tree_root(block),
        cfg.get_domain(slot, params.DOMAIN_BEACON_PROPOSER, slot),
    )
    signed = {
        "message": block,
        "signature": B.sign_bytes(sks[proposer], root),
    }
    chain.process_block(signed)
    return signed


class Source:
    """An instrumented peer source over a block list."""

    def __init__(self, signed_blocks, delay=0.0, fail_ranges=0):
        self.blocks = list(signed_blocks)
        self.delay = delay
        self.fail_ranges = fail_ranges  # fail the first N range requests
        self.range_calls = 0
        self.served_threads = set()

    def get_blocks_by_range(self, start_slot, count):
        self.range_calls += 1
        if self.fail_ranges > 0:
            self.fail_ranges -= 1
            raise ConnectionError("peer dropped mid-download")
        if self.delay:
            time.sleep(self.delay)
        self.served_threads.add(threading.get_ident())
        return [
            s
            for s in self.blocks
            if start_slot <= s["message"]["slot"] < start_slot + count
        ]

    def get_blocks_by_root(self, roots):
        return []


def test_bad_peer_rotated_out_mid_sync(world):
    """A peer that drops every download is rotated out: the good peer
    serves its batches and the sync completes; the bad peer is reported
    through on_peer_fault (reference: chain.ts peer scoring)."""
    cfg, sks, genesis, donor, blocks = world
    chain = BeaconChain(cfg, genesis)
    target = 2 * P.SLOTS_PER_EPOCH + 2
    sc = SyncChain(chain, 1, target)
    bad = Source(blocks, fail_ranges=10**9)  # always fails
    good = Source(blocks)
    sc.add_peer("bad", bad)
    sc.add_peer("good", good)
    faults = []
    sc.on_peer_fault = lambda peer, why: faults.append(peer)
    n = sc.run()
    assert n == len(blocks)
    assert chain.head_root_hex == donor.head_root_hex
    # every batch that hit the bad peer retried elsewhere
    assert all(b.state == BatchState.processed for b in sc.batches)
    assert all(p == "bad" for p in faults) and faults
    assert good.range_calls >= len(sc.batches)


def test_download_overlaps_import(world):
    """While the cursor imports batch k, later batches download on
    worker threads (reference: chain.ts BATCH_BUFFER_SIZE lookahead)."""
    cfg, sks, genesis, donor, blocks = world
    chain = BeaconChain(cfg, genesis)
    target = 2 * P.SLOTS_PER_EPOCH + 2
    sc = SyncChain(chain, 1, target)
    src = Source(blocks, delay=0.05)
    sc.add_peer("a", src)
    sc.add_peer("b", Source(blocks, delay=0.05))
    main = threading.get_ident()
    n = sc.run()
    assert n == len(blocks)
    # downloads ran off the importing thread
    assert main not in src.served_threads
    assert len(sc.batches) >= 2


def test_batch_exhaustion_fails_chain(world):
    cfg, sks, genesis, donor, blocks = world
    chain = BeaconChain(cfg, genesis)
    sc = SyncChain(chain, 1, P.SLOTS_PER_EPOCH, max_download_attempts=2)
    sc.add_peer("bad", Source(blocks, fail_ranges=10**9))
    with pytest.raises(SyncChainError):
        sc.run()
    assert sc.batches[0].state == BatchState.failed
    assert sc.batches[0].download_attempts == 2


def test_corrupt_batch_redownloads_from_other_peer(world):
    """An import failure re-downloads the batch from a different peer
    (the blocks themselves may be bad), and the sync still completes."""
    cfg, sks, genesis, donor, blocks = world

    class CorruptSource(Source):
        def get_blocks_by_range(self, start_slot, count):
            out = super().get_blocks_by_range(start_slot, count)
            return [
                {"message": s["message"], "signature": b"\x99" * 96}
                for s in out
            ]

    chain = BeaconChain(cfg, genesis)
    target = P.SLOTS_PER_EPOCH
    sc = SyncChain(chain, 1, target)
    corrupt = CorruptSource(blocks)
    sc.add_peer("corrupt", corrupt)
    sc.add_peer("honest", Source(blocks))
    faults = []
    sc.on_peer_fault = lambda peer, why: faults.append((peer, why))
    n = sc.run()
    assert n == P.SLOTS_PER_EPOCH
    # if the corrupt peer served first, it was reported and rotated;
    # either way the chain landed every batch
    assert all(b.state == BatchState.processed for b in sc.batches)


def test_range_sync_facade_multi_peer(world):
    cfg, sks, genesis, donor, blocks = world
    chain = BeaconChain(cfg, genesis)
    rs = RangeSync(chain)
    n = rs.sync_to(
        {"p1": Source(blocks), "p2": Source(blocks)},
        target_slot=2 * P.SLOTS_PER_EPOCH + 2,
    )
    assert n == len(blocks)
    assert chain.head_root_hex == donor.head_root_hex


def test_sync_through_reqresp_adapter(world):
    """SyncChain pulls a real chain over the reqresp protocol layer:
    server (chain+db) -> wire chunks -> ReqRespBlockSource -> batch
    state machine -> full STF import on the syncing node."""
    from lodestar_tpu.db import BeaconDb
    from lodestar_tpu.network.reqresp import ReqResp, connect_inmemory
    from lodestar_tpu.network.reqresp_protocols import (
        ReqRespBeaconNode,
        ReqRespBlockSource,
    )

    cfg, sks, genesis, donor, blocks = world
    # serve the donor chain from a db (by-range reads the archive/hot set)
    db = BeaconDb(config=cfg)
    for signed in blocks:
        slot = int(signed["message"]["slot"])
        root = cfg.get_fork_types(slot)[0].hash_tree_root(signed["message"])
        db.archive_block(slot, signed, root=root)

    server, client = ReqResp(), ReqResp()
    ReqRespBeaconNode(server, cfg, chain=donor, db=db)
    connect_inmemory(client, "syncer", server, "server")

    fresh = BeaconChain(cfg, genesis)
    source = ReqRespBlockSource(client, "server", cfg)
    sc = SyncChain(fresh, 1, 2 * P.SLOTS_PER_EPOCH + 2)
    sc.add_peer("server", source)
    n = sc.run()
    assert n == len(blocks)
    assert fresh.head_root_hex == donor.head_root_hex


class StallingSource(Source):
    """A peer whose by-range requests never return (until released)."""

    def __init__(self, signed_blocks):
        super().__init__(signed_blocks)
        self.release = threading.Event()

    def get_blocks_by_range(self, start_slot, count):
        self.range_calls += 1
        self.release.wait(timeout=10.0)
        return super().get_blocks_by_range(start_slot, count)


def test_stalling_peer_timed_out_demoted_and_retried_elsewhere(world):
    """ISSUE 14 satellite: a peer that STALLS (no answer at all) is
    abandoned at the download timeout, demoted for a doubling cooldown,
    and its batch retries on the healthy peer after a jittered backoff
    — the sync never waits forever on a silent peer."""
    import random as _random

    from lodestar_tpu.network.reqresp import PeerDemotion, RetryPolicy

    cfg, sks, genesis, donor, blocks = world
    chain = BeaconChain(cfg, genesis)
    target = P.SLOTS_PER_EPOCH + 2  # two batches: both peers get picked
    sleeps = []
    sc = SyncChain(
        chain,
        1,
        target,
        download_timeout_s=0.05,
        demotion=PeerDemotion(cooldown_initial_s=60.0),
        retry_policy=RetryPolicy(attempts=5, backoff_initial_s=0.01),
        rng=_random.Random(7),
        sleep=sleeps.append,
    )
    staller = StallingSource(blocks)
    good = Source(blocks)
    sc.add_peer("staller", staller)
    sc.add_peer("good", good)
    faults = []
    sc.on_peer_fault = lambda peer, why: faults.append((peer, why))
    n = sc.run()
    staller.release.set()
    assert n == target
    assert all(b.state == BatchState.processed for b in sc.batches)
    # the staller was reported as TIMING OUT (not a generic error) and
    # demoted — once demoted, _pick_peer stops choosing it, so it
    # stalled at most its first pick, not one attempt per batch
    assert any("timed out" in why for _p, why in faults), faults
    assert sc.demotion.is_demoted("staller")
    assert not sc.demotion.is_demoted("good")
    assert staller.range_calls <= len(sc.batches)
    assert good.range_calls >= len(sc.batches)
    # retries backed off (jittered, nonzero) instead of busy-spinning
    assert sleeps and all(s > 0 for s in sleeps)


def test_range_sync_facade_threads_timeout_and_demotion(world):
    """RangeSync passes its download timeout + persistent demotion
    ledger into the SyncChains it builds: a peer that stalls one sync
    stays deprioritized for the next."""
    cfg, sks, genesis, donor, blocks = world
    chain = BeaconChain(cfg, genesis)
    rs = RangeSync(chain, download_timeout_s=0.05)
    staller = StallingSource(blocks)
    # the good peer fails its FIRST request, so the round-robin rotates
    # onto the staller (which then times out and is demoted) before the
    # recovered good peer serves the batch
    good = Source(blocks, fail_ranges=1)
    n = rs.sync_to({"staller": staller, "good": good}, 4)
    staller.release.set()
    assert n == 4
    assert rs.demotion.is_demoted("staller")
    assert chain.head_state.slot == 4
