"""kernels/pairing.py (Miller loop + final exp) vs the crypto/ oracle.

The kernel pairing returns e(P,Q)^3 (see pairing.py docstring), so oracle
values are cubed before comparison; is-one checks need no adjustment.
"""

import random

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lodestar_tpu.crypto import bls as GB
from lodestar_tpu.crypto import curves as GC
from lodestar_tpu.crypto import fields as GT
from lodestar_tpu.crypto import pairing as GP
from lodestar_tpu.crypto.hash_to_curve import hash_to_g2
from lodestar_tpu.kernels import curve as CV
from lodestar_tpu.kernels import layout as LY
from lodestar_tpu.kernels import pairing as KP
from lodestar_tpu.kernels import tower as TW

pytestmark = pytest.mark.slow

random.seed(0xBEEF)
P = LY.P


def enc1(xs):
    return jnp.asarray(LY.encode_batch(xs))


def enc2(vals):
    return (
        jnp.asarray(LY.encode_batch([v[0] for v in vals])),
        jnp.asarray(LY.encode_batch([v[1] for v in vals])),
    )


def dec1(t):
    return LY.decode_batch(np.asarray(t))


def dec2(t):
    return list(zip(dec1(t[0]), dec1(t[1])))


def dec12(t):
    def dec6(c):
        return list(zip(*[dec2(x) for x in c]))

    return list(zip(*[dec6(c) for c in t]))


def enc_g1_aff(pts):
    return (enc1([p[0] for p in pts]), enc1([p[1] for p in pts]))


def enc_g2_aff(pts):
    return (enc2([p[0] for p in pts]), enc2([p[1] for p in pts]))


def test_pairing_matches_oracle_cubed():
    n = 2
    ps = [
        GC.scalar_mul(GC.FP_OPS, GC.G1_GEN, random.randrange(2, GT.R))
        for _ in range(n)
    ]
    qs = [
        GC.scalar_mul(GC.FP2_OPS, GC.G2_GEN, random.randrange(2, GT.R))
        for _ in range(n)
    ]
    px, py = enc_g1_aff(ps)
    qx, qy = enc_g2_aff(qs)
    one1 = CV._one_plane_like(CV.FP_OPS, px)

    @jax.jit
    def f(px, py, qx, qy):
        ml = KP.miller_loop((px, py, one1), (qx, qy))
        return KP.final_exponentiation(ml)

    got = dec12(f(px, py, qx, qy))
    want = [
        GT.fp12_pow(GP.pairing(p, q), 3) for p, q in zip(ps, qs)
    ]
    assert got == want


def test_pairing_jacobian_p_scaling():
    """P given in non-normalized jacobian form gives the same pairing."""
    p = GC.scalar_mul(GC.FP_OPS, GC.G1_GEN, 0xABCDE)
    q = GC.scalar_mul(GC.FP2_OPS, GC.G2_GEN, 0x12345)
    # (X, Y, Z) = (x z^2, y z^3, z) for z = 7
    z = 7
    px = enc1([p[0] * z * z % P])
    py = enc1([p[1] * z**3 % P])
    pz = enc1([z])
    qx, qy = enc_g2_aff([q])

    @jax.jit
    def f(px, py, pz, qx, qy):
        return KP.final_exponentiation(KP.miller_loop((px, py, pz), (qx, qy)))

    got = dec12(f(px, py, pz, qx, qy))[0]
    want = GT.fp12_pow(GP.pairing(p, q), 3)
    assert got == want


def test_signature_relation_and_batch_product():
    """e(pk, H(m)) * e(-G1, sig) == 1 through the lane-product path."""
    sks = [GB.keygen(b"kp-%d" % i) for i in range(2)]
    msgs = [b"kernel pairing %d" % i for i in range(2)]
    pks = [GB.sk_to_pk(sk) for sk in sks]
    hms = [hash_to_g2(m) for m in msgs]
    sigs = [GB.sign(sk, m) for sk, m in zip(sks, msgs)]
    bad_sigs = [sigs[0], GC.scalar_mul(GC.FP2_OPS, sigs[1], 2)]

    neg_g1 = GC.affine_neg(GC.FP_OPS, GC.G1_GEN)
    # lanes: pk0, pk1, -G1, -G1  paired with  H0, H1, sig0, sig1
    px, py = enc_g1_aff(pks + [neg_g1, neg_g1])
    one1 = CV._one_plane_like(CV.FP_OPS, px)

    @jax.jit
    def f(px, py, qx, qy):
        ml = KP.miller_loop((px, py, one1), (qx, qy))
        prod = KP.product12_lanes(ml, jnp.ones((4,), bool))
        fe = KP.final_exponentiation(prod)
        return TW.is_one12(fe)

    qx, qy = enc_g2_aff(hms + sigs)
    assert bool(np.asarray(f(px, py, qx, qy))[0])
    qx, qy = enc_g2_aff(hms + bad_sigs)
    assert not bool(np.asarray(f(px, py, qx, qy))[0])


def test_to_affine_g2():
    q = GC.scalar_mul(GC.FP2_OPS, GC.G2_GEN, 0xF00)
    z = (3, 5)
    z2 = GT.fp2_sqr(z)
    qx = enc2([GT.fp2_mul(q[0], z2), (1, 0)])
    qy = enc2([GT.fp2_mul(q[1], GT.fp2_mul(z2, z)), (1, 0)])
    qz = enc2([z, (0, 0)])

    @jax.jit
    def f(qx, qy, qz):
        return KP.to_affine_g2((qx, qy, qz))

    (x, y), inf = f(qx, qy, qz)
    assert list(np.asarray(inf)) == [False, True]
    assert dec2(x)[0] == q[0] and dec2(y)[0] == q[1]
