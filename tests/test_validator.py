"""Validator client: slashing protection, signing, duty execution.

Reference: packages/validator/src/services/{validatorStore,attestation,
attestationDuties}.ts and slashingProtection/.
"""

import pytest

from lodestar_tpu.config import MAINNET_CHAIN_CONFIG
from lodestar_tpu.crypto import bls as B
from lodestar_tpu.crypto import curves as C
from lodestar_tpu.crypto import pairing as P
from lodestar_tpu.validator import (
    AttestationService,
    SlashingError,
    SlashingProtection,
    ValidatorStore,
)

pytestmark = pytest.mark.smoke


def att_data(slot=32, index=0, source=0, target=1):
    return {
        "slot": slot,
        "index": index,
        "beacon_block_root": b"\x01" * 32,
        "source": {"epoch": source, "root": bytes(32)},
        "target": {"epoch": target, "root": b"\x02" * 32},
    }


def make_store(n=2):
    sks = {i: B.keygen(b"val-%d" % i) for i in range(n)}
    return ValidatorStore(MAINNET_CHAIN_CONFIG, sks)


# -- slashing protection ----------------------------------------------------


def test_double_vote_rejected():
    sp = SlashingProtection()
    sp.check_attestation(b"k", 0, 5)
    with pytest.raises(SlashingError):
        sp.check_attestation(b"k", 1, 5)  # same target
    with pytest.raises(SlashingError):
        sp.check_attestation(b"k", 0, 4)  # older target


def test_surround_vote_rejected():
    sp = SlashingProtection()
    sp.check_attestation(b"k", 3, 5)
    with pytest.raises(SlashingError):
        sp.check_attestation(b"k", 2, 6)  # surrounds (3,5)


def test_block_double_proposal_rejected():
    sp = SlashingProtection()
    sp.check_block(b"k", 10)
    with pytest.raises(SlashingError):
        sp.check_block(b"k", 10)
    sp.check_block(b"k", 11)


def test_interchange_round_trip():
    sp = SlashingProtection()
    sp.check_attestation(b"\x01" * 48, 2, 7)
    sp.check_block(b"\x01" * 48, 99)
    data = sp.export_interchange()
    sp2 = SlashingProtection()
    sp2.import_interchange(data)
    with pytest.raises(SlashingError):
        sp2.check_attestation(b"\x01" * 48, 2, 7)  # already signed
    with pytest.raises(SlashingError):
        sp2.check_block(b"\x01" * 48, 99)


# -- store signing ----------------------------------------------------------


def test_sign_attestation_verifies_and_protects():
    store = make_store(1)
    data = att_data()
    sig_bytes = store.sign_attestation(0, data)
    # the signature verifies under the same domain/root
    from lodestar_tpu import params, types as T

    root = MAINNET_CHAIN_CONFIG.compute_signing_root(
        T.AttestationData.hash_tree_root(data),
        MAINNET_CHAIN_CONFIG.get_domain(32, params.DOMAIN_BEACON_ATTESTER, 32),
    )
    from lodestar_tpu.crypto.hash_to_curve import hash_to_g2

    pk = B.sk_to_pk(store.sks[0])
    sig = C.g2_decompress(sig_bytes)
    assert P.multi_pairing_is_one(
        [(pk, hash_to_g2(root)), (B.NEG_G1_GEN, sig)]
    )
    # re-signing the same target is slashable
    with pytest.raises(SlashingError):
        store.sign_attestation(0, data)


# -- attestation service ----------------------------------------------------


class StubApi:
    def __init__(self):
        self.duty_calls = []
        self.submitted = []

    def get_attester_duties(self, epoch, indices):
        self.duty_calls.append((epoch, tuple(indices)))
        return [
            {"validator_index": i, "committee_index": i % 2, "slot": 32}
            for i in indices
        ]

    def produce_attestation_data(self, committee_index, slot):
        return att_data(slot=slot, index=committee_index)

    def submit_pool_attestations(self, atts):
        self.submitted.extend(atts)


def test_attestation_duty_flow():
    store = make_store(4)
    api = StubApi()
    svc = AttestationService(store, api)
    svc.poll_duties(1)
    assert api.duty_calls == [(1, (0, 1, 2, 3))]
    n = svc.run_attestation_tasks(1, 32)
    assert n == 4 and len(api.submitted) == 4
    # repeated slot: every duty is now slashable -> nothing submitted
    n2 = svc.run_attestation_tasks(1, 32)
    assert n2 == 0 and svc.skipped_slashable == 4
