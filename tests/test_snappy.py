"""Native snappy codec + eth2 framing round trips and known vectors.

Reference surfaces: @chainsafe/snappy-stream (reqresp ssz_snappy) +
snappyjs (gossip raw blocks); crc32c vectors are the RFC 3720 check
values.
"""

import os
import random

import pytest

from lodestar_tpu.network import snappy as S

pytestmark = pytest.mark.smoke

if not S.native_available():  # pragma: no cover
    pytest.skip("libsnappy_tpu.so not built", allow_module_level=True)


def test_crc32c_known_vectors():
    # RFC 3720 / common crc32c check values
    assert S.crc32c(b"") == 0
    assert S.crc32c(b"123456789") == 0xE3069283
    assert S.crc32c(b"\x00" * 32) == 0x8A9136AA


def test_raw_roundtrip_various():
    rng = random.Random(7)
    cases = [
        b"",
        b"a",
        b"ab" * 3,
        b"hello hello hello hello hello",  # repetitive -> copies
        bytes(rng.randrange(256) for _ in range(1000)),  # incompressible
        b"\x00" * 100000,  # highly compressible, multi-64KB-block
        os.urandom(70000),
    ]
    for data in cases:
        comp = S.compress(data)
        assert S.decompress(comp) == data
    # compressible data actually shrinks (snappy copies cap at 64 bytes,
    # so ~3 bytes per 64 -> ~21x on constant input)
    assert len(S.compress(b"\x00" * 100000)) < 6000


def test_decompress_rejects_garbage():
    with pytest.raises(S.SnappyError):
        S.decompress(b"\xff" * 40)
    # declared length beyond cap
    big = S.compress(b"x" * 1000)
    with pytest.raises(S.SnappyError):
        S.decompress(big, max_len=10)


def test_framed_roundtrip():
    for data in (b"", b"tiny", b"z" * 200000, os.urandom(100000)):
        framed = S.frame_compress(data)
        assert framed.startswith(b"\xff\x06\x00\x00sNaPpY")
        assert S.frame_decompress(framed) == data


def test_framed_checksum_detects_corruption():
    framed = bytearray(S.frame_compress(b"payload payload payload"))
    framed[-1] ^= 0x01
    with pytest.raises(S.SnappyError):
        S.frame_decompress(bytes(framed))


def test_reqresp_chunk_roundtrip():
    from lodestar_tpu import types as T

    att = T.AttestationData.default()
    ssz = T.AttestationData.serialize(att)
    chunk = S.encode_reqresp_chunk(ssz)
    assert S.decode_reqresp_chunk(chunk) == ssz
    assert T.AttestationData.deserialize(S.decode_reqresp_chunk(chunk)) == att

    # declared-length mismatch rejected
    tampered = S._uvarint(len(ssz) + 1) + S.frame_compress(ssz)
    with pytest.raises(S.SnappyError):
        S.decode_reqresp_chunk(tampered)
