"""DB layer: native KV store, repositories, BeaconDb round trips.

Reference: packages/db/src/controller/level.ts (controller surface),
abstractRepository.ts (bucket prefixing), beacon-node/src/db (BeaconDb).
"""

import os

import pytest

from lodestar_tpu import types as T
from lodestar_tpu.db import BeaconDb, Bucket, KvController, Repository
from lodestar_tpu.db.controller import native_available

pytestmark = pytest.mark.smoke


@pytest.fixture(params=["native", "memory"])
def controller(request, tmp_path):
    if request.param == "native":
        if not native_available():
            pytest.skip("libkvstore.so not built")
        c = KvController(str(tmp_path / "kv.db"))
    else:
        c = KvController(None)
    yield c
    c.close()


def test_point_ops(controller):
    c = controller
    assert c.get(b"missing") is None
    c.put(b"a", b"1")
    c.put(b"b", b"22")
    assert c.get(b"a") == b"1" and c.get(b"b") == b"22"
    c.put(b"a", b"111")
    assert c.get(b"a") == b"111"
    c.delete(b"a")
    assert c.get(b"a") is None
    assert len(c) == 1


def test_range_scans_ordered(controller):
    c = controller
    for i in [5, 1, 9, 3]:
        c.put(bytes([i]), b"v%d" % i)
    assert list(c.keys()) == [bytes([1]), bytes([3]), bytes([5]), bytes([9])]
    assert list(c.keys(gte=bytes([3]), lt=bytes([9]))) == [
        bytes([3]),
        bytes([5]),
    ]
    assert list(c.values(gte=bytes([9]))) == [b"v9"]


def test_large_values(controller):
    c = controller
    big = os.urandom(300_000)
    c.put(b"big", big)
    assert c.get(b"big") == big
    assert list(c.entries())[0][1] == big


@pytest.mark.skipif(not native_available(), reason="needs libkvstore.so")
def test_native_durability_and_compaction(tmp_path):
    path = str(tmp_path / "dur.db")
    c = KvController(path)
    for i in range(50):
        c.put(b"k%02d" % i, b"v%d" % i)
    for i in range(0, 50, 2):
        c.delete(b"k%02d" % i)
    c.put(b"k01", b"updated")
    c.flush()
    c.close()
    # reopen: replay reconstructs exactly the live state
    c2 = KvController(path)
    assert len(c2) == 25
    assert c2.get(b"k01") == b"updated"
    assert c2.get(b"k00") is None
    c2.compact()
    c2.close()
    c3 = KvController(path)
    assert len(c3) == 25 and c3.get(b"k03") == b"v3"
    c3.close()


def test_repository_bucket_isolation(controller):
    r1 = Repository(controller, Bucket.block)
    r2 = Repository(controller, Bucket.block_archive)
    r1.put(b"x", b"from-r1")
    r2.put(b"x", b"from-r2")
    assert r1.get(b"x") == b"from-r1"
    assert r2.get(b"x") == b"from-r2"
    assert list(r1.keys()) == [b"x"]
    r1.delete(b"x")
    assert r1.get(b"x") is None and r2.get(b"x") == b"from-r2"


def test_beacon_db_ssz_round_trip(tmp_path):
    db = BeaconDb(
        str(tmp_path / "beacon.db") if native_available() else None
    )
    block = T.BeaconBlockAltair.default()
    block["slot"] = 42
    signed = {"message": block, "signature": b"\x05" * 96}
    root = T.BeaconBlockAltair.hash_tree_root(block)
    db.put_block(root, signed)
    got = db.block.get(root)
    assert got["message"]["slot"] == 42
    db.archive_block(42, signed)
    assert db.block_archive.first_key() == (42).to_bytes(8, "big")
    # slot ordering through big-endian keys
    db.archive_block(7, signed)
    db.archive_block(100, signed)
    slots = [int.from_bytes(k, "big") for k in db.block_archive.keys()]
    assert slots == [7, 42, 100]
    db.close()
