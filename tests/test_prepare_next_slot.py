"""PrepareNextSlotScheduler + BeaconProposerCache.

Reference behaviors: packages/beacon-node/src/chain/prepareNextSlot.ts
(epoch-boundary state precompute + fcU payload preparation for local
proposers) and chain/beaconProposerCache.ts (fee-recipient registry
with epoch expiry), registered via
/eth/v1/validator/prepare_beacon_proposer (routes/validator.ts).
"""

import json
import urllib.request

import pytest

from lodestar_tpu import params
from lodestar_tpu.api.server import BeaconApiServer, DefaultHandlers
from lodestar_tpu.chain.chain import BeaconChain
from lodestar_tpu.chain.prepare_next_slot import (
    BeaconProposerCache,
    PrepareNextSlotScheduler,
)
from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
from lodestar_tpu.crypto import bls as B
from lodestar_tpu.crypto import curves as C
from lodestar_tpu.execution import ExecutionEngineMock
from lodestar_tpu.params import ForkName
from lodestar_tpu.state_transition import create_genesis_state
from lodestar_tpu.state_transition.accessors import get_beacon_proposer_index
from lodestar_tpu.state_transition.slot import process_slots
from lodestar_tpu.validator import ValidatorStore

pytestmark = pytest.mark.smoke

P = params.ACTIVE_PRESET
N_KEYS = 8


def test_proposer_cache_expiry():
    cache = BeaconProposerCache()
    cache.add(epoch=5, proposer_index=1, fee_recipient=b"\x01" * 20)
    cache.add(epoch=7, proposer_index=2, fee_recipient=b"\x02" * 20)
    assert cache.get(1) == b"\x01" * 20
    cache.prune(epoch=8)  # preserve window = 2 epochs
    assert cache.get(1) is None  # registered at 5, expired
    assert cache.get(2) == b"\x02" * 20


@pytest.fixture(scope="module")
def world():
    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG,
        fork_epochs={ForkName.altair: 0, ForkName.bellatrix: 0},
    )
    sks = [B.keygen(b"pns-%d" % i) for i in range(N_KEYS)]
    pks = [C.g1_compress(B.sk_to_pk(sk)) for sk in sks]
    genesis = create_genesis_state(cfg, pks, genesis_time=2)
    from lodestar_tpu.state_transition.slot import upgrade_to_bellatrix

    upgrade_to_bellatrix(genesis)
    el = ExecutionEngineMock()
    chain = BeaconChain(cfg, genesis, execution=el)
    store = ValidatorStore(cfg, dict(enumerate(sks)))
    return cfg, sks, chain, el, store


def _propose(cfg, sks, chain, store, slot):
    st = chain.head_state.clone()
    if st.slot < slot:
        process_slots(st, slot)
    proposer = get_beacon_proposer_index(st)
    block = chain.produce_block(slot, store.sign_randao(proposer, slot))
    bt = cfg.get_fork_types(slot)[0]
    root = cfg.compute_signing_root(
        bt.hash_tree_root(block),
        cfg.get_domain(slot, params.DOMAIN_BEACON_PROPOSER, slot),
    )
    return chain.process_block(
        {
            "message": block,
            "signature": C.g2_compress(B.sign(sks[proposer], root)),
        }
    )


def test_epoch_precompute_lands_in_checkpoint_cache(world):
    cfg, sks, chain, el, store = world
    sched = PrepareNextSlotScheduler(chain)
    boundary = P.SLOTS_PER_EPOCH  # slot 32 = epoch-1 boundary
    head_root = chain.get_head_root()
    # a mid-epoch head update precomputes nothing epoch-wise
    sched.on_head(head_root, 3)
    assert sched.prepared_epochs == 0
    # a head update in the epoch's LAST slot precomputes the boundary
    sched.on_head(head_root, boundary - 1)
    assert sched.prepared_epochs == 1
    checkpoint = {"epoch": 1, "root": chain.get_head_root()}
    cached = chain.regen.checkpoint_cache.get(checkpoint)
    assert cached is not None and cached.slot == boundary
    # idempotent: a repeat is a cache hit, no recompute
    sched.on_head(head_root, boundary - 1)
    assert sched.prepared_epochs == 1
    # the empty-slot fallback also prepares (head is behind the clock)
    sched.on_slot(boundary)
    assert sched.prepared_epochs == 1  # same boundary, still cached


def test_payload_preparation_for_registered_proposer(world):
    cfg, sks, chain, el, store = world
    # cross the merge so the head has an execution block hash
    root1 = _propose(cfg, sks, chain, store, 1)
    assert chain.head_root_hex in chain._execution_block_hash
    sched = PrepareNextSlotScheduler(chain)
    # next slot's proposer, from the duty shuffle
    duties = chain.get_proposer_duties(0)
    nxt = int(duties[2]["validator_index"])
    # unregistered: the head update must NOT prepare a payload
    before = len(el.preparing)
    sched.on_head(root1, 1)
    assert sched.payloads_prepared == 0 and len(el.preparing) == before
    # registered: the head update fires fcU with attributes; the EL
    # starts building with the registered fee recipient and the
    # ADVANCED state's randao (matching produce_block's attributes)
    sched.proposer_cache.add(0, nxt, b"\xfe" * 20)
    sched.on_head(root1, 1)
    assert sched.payloads_prepared == 1
    assert len(el.preparing) == before + 1
    payload = list(el.preparing.values())[-1]
    assert bytes(payload["fee_recipient"]) == b"\xfe" * 20
    from lodestar_tpu.state_transition.accessors import get_randao_mix

    adv = chain.regen.get_block_slot_state(bytes(root1).hex(), 2)
    assert bytes(payload["prev_randao"]) == bytes(get_randao_mix(adv, 0))


def test_prepare_beacon_proposer_endpoint(world):
    cfg, sks, chain, el, store = world
    cache = BeaconProposerCache()
    server = BeaconApiServer(
        DefaultHandlers(chain=chain, proposer_cache=cache)
    )
    server.listen()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}"
            "/eth/v1/validator/prepare_beacon_proposer",
            data=json.dumps(
                [
                    {
                        "validator_index": "3",
                        "fee_recipient": "0x" + "ab" * 20,
                    }
                ]
            ).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.status == 200
        assert cache.get(3) == bytes.fromhex("ab" * 20)
    finally:
        server.close()
