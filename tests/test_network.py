"""Gossip queues + NetworkProcessor scheduling semantics.

Reference behaviors mirrored: packages/beacon-node/src/network/processor/
gossipQueues.ts (drop discipline) and index.ts (priority order, per-tick
job cap, backpressure gating, unknown-root parking).
"""

import pytest

from lodestar_tpu.network.gossip_queues import (
    DropByCount,
    DropByRatio,
    GossipQueue,
    GossipQueueOpts,
    GossipType,
    QueueType,
    create_gossip_queues,
)
from lodestar_tpu.network.processor import (
    EXECUTE_GOSSIP_WORK_ORDER,
    NetworkProcessor,
    PendingGossipMessage,
)

pytestmark = pytest.mark.smoke


# ---------------------------------------------------------------------------
# GossipQueue
# ---------------------------------------------------------------------------


def test_fifo_lifo_order():
    fifo = GossipQueue(GossipQueueOpts(QueueType.FIFO, 10, DropByCount(1)))
    lifo = GossipQueue(GossipQueueOpts(QueueType.LIFO, 10, DropByCount(1)))
    for i in range(3):
        fifo.add(i)
        lifo.add(i)
    assert [fifo.next() for _ in range(3)] == [0, 1, 2]
    assert [lifo.next() for _ in range(3)] == [2, 1, 0]
    assert fifo.next() is None and lifo.next() is None


def test_drop_by_count_keeps_freshest_for_lifo():
    q = GossipQueue(GossipQueueOpts(QueueType.LIFO, 3, DropByCount(1)))
    for i in range(4):
        dropped = q.add(i)
    assert dropped == 1
    assert len(q) == 3
    # LIFO drops the OLDEST (left end): 0 gone, 3 served first
    assert q.next() == 3
    assert q.get_all() == [1, 2]


def test_drop_by_count_keeps_oldest_for_fifo():
    q = GossipQueue(GossipQueueOpts(QueueType.FIFO, 3, DropByCount(1)))
    for i in range(4):
        q.add(i)
    # FIFO drops the NEWEST: 3 was evicted right after being added
    assert q.get_all() == [0, 1, 2]


def _fill_until_drop(q):
    """Add items until the queue overflows; return the dropped count."""
    while True:
        d = q.add(0)
        if d:
            return d


def test_ratio_drop_escalates_and_caps():
    q = GossipQueue(GossipQueueOpts(QueueType.LIFO, 100, DropByRatio(0.10, 0.10)))
    for i in range(101):
        d1 = q.add(i)
    assert d1 == 10  # 10% of 101
    assert q.drop_ratio == pytest.approx(0.20)
    # fill to overflow again: drop 20% (of the 101 items present at overflow)
    assert _fill_until_drop(q) == 20
    # escalation caps at 95%
    for _ in range(20):
        _fill_until_drop(q)
    assert q.drop_ratio <= 0.95
    assert _fill_until_drop(q) == 95


def test_ratio_resets_only_after_sustained_drain():
    q = GossipQueue(GossipQueueOpts(QueueType.LIFO, 8, DropByRatio(0.25, 0.25)))
    for i in range(9):
        q.add(i)  # overflow: drop 2 (25% of 9), escalate
    assert q.drop_ratio == pytest.approx(0.50)
    # drain to empty: only 7 items processed (< max_length) so the drop is
    # still "recent" -> ratio NOT reset on next add
    while q.next() is not None:
        pass
    q.add(0)
    assert q.drop_ratio == pytest.approx(0.50)
    # process a full max_length of items without overflow -> reset allowed
    for _ in range(8):
        q.add(1)
        q.next()
    while q.next() is not None:
        pass
    q.add(2)
    assert q.drop_ratio == pytest.approx(0.25)


def test_default_queue_shapes_match_reference():
    qs = create_gossip_queues()
    att = qs[GossipType.beacon_attestation]
    assert att.opts.max_length == 24576 and att.opts.type is QueueType.LIFO
    assert isinstance(att.opts.drop, DropByRatio)
    agg = qs[GossipType.beacon_aggregate_and_proof]
    assert agg.opts.max_length == 5120 and agg.opts.type is QueueType.LIFO
    blk = qs[GossipType.beacon_block]
    assert blk.opts.max_length == 1024 and blk.opts.type is QueueType.FIFO


# ---------------------------------------------------------------------------
# NetworkProcessor
# ---------------------------------------------------------------------------


def msg(topic, slot=None, root=None):
    return PendingGossipMessage(topic, data=None, slot=slot, block_root=root)


def test_priority_order_blocks_first():
    done = []
    proc = NetworkProcessor(lambda m: done.append(m.topic), [lambda: False])
    # backpressure ON: only bypass topics (blocks) flow
    proc.queues[GossipType.beacon_attestation].add(msg(GossipType.beacon_attestation))
    proc.queues[GossipType.beacon_block].add(msg(GossipType.beacon_block))
    proc.execute_work()
    assert done == [GossipType.beacon_block]
    assert proc.queue_lengths()["beacon_attestation"] == 1


def test_aggregates_before_attestations():
    done = []
    proc = NetworkProcessor(lambda m: done.append(m.topic), [lambda: True])
    proc.queues[GossipType.beacon_attestation].add(msg(GossipType.beacon_attestation))
    proc.queues[GossipType.beacon_aggregate_and_proof].add(
        msg(GossipType.beacon_aggregate_and_proof)
    )
    proc.execute_work()
    assert done == [
        GossipType.beacon_aggregate_and_proof,
        GossipType.beacon_attestation,
    ]


def test_per_tick_job_cap():
    done = []
    proc = NetworkProcessor(
        lambda m: done.append(1), [lambda: True], max_jobs_per_tick=5
    )
    for _ in range(20):
        proc.queues[GossipType.beacon_attestation].add(
            msg(GossipType.beacon_attestation)
        )
    assert proc.execute_work() == 5
    assert len(done) == 5


def test_backpressure_flips_mid_tick():
    # accept work for the first 3 pulls, then downstream fills up
    state = {"n": 0}

    def can_accept():
        return state["n"] < 3

    def worker(m):
        state["n"] += 1

    proc = NetworkProcessor(worker, [can_accept])
    for _ in range(10):
        proc.queues[GossipType.beacon_attestation].add(
            msg(GossipType.beacon_attestation)
        )
    n = proc.execute_work()
    assert n == 3
    assert proc.queue_lengths()["beacon_attestation"] == 7


def test_unknown_root_parked_and_reprocessed():
    done = []
    proc = NetworkProcessor(
        lambda m: done.append(m),
        [lambda: True],
        has_block_root=lambda r: r == "known",
    )
    proc.current_slot = 10
    proc.on_gossip_message(msg(GossipType.beacon_attestation, slot=10, root="abc"))
    assert done == [] and proc.stats.reprocess_parked == 1
    proc.on_block_processed(10, "abc")
    assert len(done) == 1


def test_unknown_root_expires_on_slot():
    proc = NetworkProcessor(
        lambda m: None, [lambda: True], has_block_root=lambda r: False
    )
    proc.current_slot = 10
    proc.on_gossip_message(msg(GossipType.beacon_attestation, slot=10, root="abc"))
    proc.on_clock_slot(11)
    assert proc.stats.reprocess_expired == 1


def test_past_slot_dropped():
    proc = NetworkProcessor(lambda m: None, [lambda: True])
    proc.current_slot = 100
    proc.on_gossip_message(msg(GossipType.beacon_attestation, slot=10))
    assert proc.stats.past_slot == 1


def test_work_order_covers_all_queue_topics():
    topics = {t for t, _ in EXECUTE_GOSSIP_WORK_ORDER}
    assert topics == set(create_gossip_queues().keys())
