"""BackfillSync + checkpoint-sync bootstrap + resume-from-archive.

Reference behaviors: packages/beacon-node/src/sync/backfill/
{backfill.ts,verify.ts}, cli/src/cmds/beacon/initBeaconState.ts:85-131.

World: node A grows a real chain (self-proposed signed blocks).  Node B
bootstraps from A's checkpoint state over the REST debug endpoint, then
backfills A's history backward with linkage + batched proposer-signature
verification; a restarted composition resumes from its state archive.
"""

import pytest

from lodestar_tpu import params
from lodestar_tpu import types as T
from lodestar_tpu.bls.single_thread import CpuBlsVerifier
from lodestar_tpu.chain.chain import BeaconChain
from lodestar_tpu.chain.init_state import (
    init_beacon_state,
    state_from_checkpoint_bytes,
)
from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
from lodestar_tpu.crypto import bls as B
from lodestar_tpu.crypto import curves as C
from lodestar_tpu.db.beacon_db import BeaconDb
from lodestar_tpu.params import ForkName
from lodestar_tpu.state_transition import create_genesis_state, process_slots
from lodestar_tpu.state_transition.accessors import get_beacon_proposer_index
from lodestar_tpu.ssz import uint64
from lodestar_tpu.sync import BackfillError, BackfillSync
from lodestar_tpu.validator import ValidatorStore

pytestmark = pytest.mark.smoke

N_KEYS = 16
N_SLOTS = 5


@pytest.fixture(scope="module")
def world():
    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG, fork_epochs={ForkName.altair: 0}
    )
    sks = [B.keygen(b"bf-%d" % i) for i in range(N_KEYS)]
    pk_points = [B.sk_to_pk(sk) for sk in sks]
    pks = [C.g1_compress(p) for p in pk_points]
    genesis = create_genesis_state(cfg, pks, genesis_time=2)
    chain_a = BeaconChain(cfg, genesis)
    store = ValidatorStore(cfg, dict(enumerate(sks)))

    blocks = {}  # root -> signed block
    for slot in range(1, N_SLOTS + 1):
        reveal = store.sign_randao(
            get_beacon_proposer_index(_advance(genesis, slot)), slot
        )
        block = chain_a.produce_block(slot, reveal)
        signed = {
            "message": block,
            "signature": store.sign_block(block["proposer_index"], block),
        }
        root = chain_a.process_block(signed)
        blocks[bytes(root)] = signed
    return {
        "cfg": cfg,
        "sks": sks,
        "pk_points": pk_points,
        "chain_a": chain_a,
        "blocks": blocks,
    }


def _advance(genesis, slot):
    st = genesis.clone()
    process_slots(st, slot)
    return st


class DictSource:
    """BlockSource over node A's block map."""

    def __init__(self, blocks):
        self.blocks = blocks

    def get_blocks_by_root(self, roots):
        return [self.blocks[bytes(r)] for r in roots if bytes(r) in self.blocks]

    def get_blocks_by_range(self, start_slot, count):
        out = [
            s
            for s in self.blocks.values()
            if start_slot <= s["message"]["slot"] < start_slot + count
        ]
        return sorted(out, key=lambda s: s["message"]["slot"])


def test_checkpoint_bootstrap_and_backfill(world, tmp_path):
    w = world
    chain_a = w["chain_a"]
    # -- checkpoint state via serialization (the wire shape) --------------
    ckpt_bytes = chain_a.head_state.serialize()
    state_b = state_from_checkpoint_bytes(w["cfg"], ckpt_bytes)
    assert state_b.slot == chain_a.head_state.slot

    chain_b = BeaconChain(w["cfg"], state_b)
    # B has no history: the anchor header declares the parent chain
    anchor_parent = bytes(state_b.latest_block_header["parent_root"])
    anchor_slot = int(state_b.latest_block_header["slot"])

    db = BeaconDb(str(tmp_path / "b.db"))
    backfill = BackfillSync(
        w["cfg"], db, CpuBlsVerifier(pubkeys=w["pk_points"]), batch_size=2
    )
    n = backfill.backfill(
        DictSource(w["blocks"]), anchor_parent, anchor_slot, target_slot=1
    )
    # every historical block before the anchor was verified + archived
    assert n == N_SLOTS - 1
    assert backfill.lowest_backfilled_slot == 1
    for root, signed in w["blocks"].items():
        if signed["message"]["slot"] == anchor_slot:
            continue  # the anchor itself is not backfilled
        stored = db.get_block_anywhere(root)
        assert stored is not None
        assert T.SignedBeaconBlockAltair.serialize(stored) == (
            T.SignedBeaconBlockAltair.serialize(signed)
        )
    # the completed range is recorded for restart resume
    assert db.backfilled_ranges.get(anchor_slot.to_bytes(8, "big")) == (
        (1).to_bytes(8, "big")
    )
    db.close()


def test_backfill_rejects_tampered_history(world, tmp_path):
    w = world
    state_b = state_from_checkpoint_bytes(
        w["cfg"], w["chain_a"].head_state.serialize()
    )
    anchor_parent = bytes(state_b.latest_block_header["parent_root"])
    anchor_slot = int(state_b.latest_block_header["slot"])

    # tamper: swap in a block whose content does not match its root
    blocks = dict(w["blocks"])
    victim = anchor_parent
    forged = {
        "message": dict(
            blocks[victim]["message"], state_root=b"\x66" * 32
        ),
        "signature": blocks[victim]["signature"],
    }
    blocks[victim] = forged
    db = BeaconDb(str(tmp_path / "t.db"))
    backfill = BackfillSync(
        w["cfg"], db, CpuBlsVerifier(pubkeys=w["pk_points"])
    )
    with pytest.raises(BackfillError, match="linkage"):
        backfill.backfill(
            DictSource(blocks), anchor_parent, anchor_slot, target_slot=1
        )
    db.close()


def test_backfill_rejects_bad_signature(world, tmp_path):
    w = world
    state_b = state_from_checkpoint_bytes(
        w["cfg"], w["chain_a"].head_state.serialize()
    )
    anchor_parent = bytes(state_b.latest_block_header["parent_root"])
    anchor_slot = int(state_b.latest_block_header["slot"])

    # keep content (so linkage holds) but corrupt a proposer signature
    blocks = dict(w["blocks"])
    victim = anchor_parent
    sig = bytearray(blocks[victim]["signature"])
    sig[-1] ^= 1
    blocks[victim] = {
        "message": blocks[victim]["message"],
        "signature": bytes(sig),
    }
    db = BeaconDb(str(tmp_path / "s.db"))
    backfill = BackfillSync(
        w["cfg"], db, CpuBlsVerifier(pubkeys=w["pk_points"])
    )
    with pytest.raises(BackfillError, match="signature"):
        backfill.backfill(
            DictSource(blocks), anchor_parent, anchor_slot, target_slot=1
        )
    db.close()


def test_checkpoint_sync_over_rest_wire(world):
    """fetchWeakSubjectivityState over the real REST debug endpoint."""
    from lodestar_tpu.api.server import BeaconApiServer, DefaultHandlers
    from lodestar_tpu.chain.init_state import fetch_checkpoint_state

    w = world
    server = BeaconApiServer(
        DefaultHandlers(genesis_time=2, chain=w["chain_a"]), port=0
    )
    server.listen()
    try:
        state = fetch_checkpoint_state(
            w["cfg"], f"http://127.0.0.1:{server.port}"
        )
        assert state.slot == w["chain_a"].head_state.slot
        assert state.hash_tree_root() == (
            w["chain_a"].head_state.hash_tree_root()
        )
    finally:
        server.close()


def test_resume_from_state_archive(world, tmp_path):
    """Restart path: the db's archived state wins over checkpoint and
    genesis (initBeaconState.ts:85-100), and the node keeps importing."""
    w = world
    db = BeaconDb(str(tmp_path / "r.db"))
    mid_state = None
    # archive the state as of slot 3 (mid-chain)
    chain_tmp = BeaconChain(
        w["cfg"],
        state_from_checkpoint_bytes(
            w["cfg"],
            w["chain_a"].regen._get_post_state(
                _root_at_slot(w, 3).hex()
            ).serialize(),
        ),
    )
    db.archive_state(3, chain_tmp.head_state.serialize())

    state, source = init_beacon_state(
        w["cfg"], db=db, genesis_fn=lambda: (_ for _ in ()).throw(
            AssertionError("genesis must not be used")
        )
    )
    assert source == "resume" and state.slot == 3
    # the resumed chain range-syncs forward to A's head
    from lodestar_tpu.sync import RangeSync

    chain_b = BeaconChain(w["cfg"], state)
    rs = RangeSync(chain_b)
    rs.sync_to(DictSource(w["blocks"]), N_SLOTS)
    assert chain_b.head_root_hex == w["chain_a"].head_root_hex
    db.close()


def _root_at_slot(w, slot):
    for root, signed in w["blocks"].items():
        if signed["message"]["slot"] == slot:
            return root
    raise KeyError(slot)
