"""Remote signer: Web3Signer-API client/server + ValidatorStore wiring.

Reference behaviors: packages/validator/src/util/externalSignerClient.ts
and validatorStore.ts SignerType.Remote — remote-keyed validators sign
through REST while slashing protection stays local.
"""

import pytest

from lodestar_tpu import params
from lodestar_tpu import types as T
from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
from lodestar_tpu.crypto import bls as B
from lodestar_tpu.crypto import curves as C
from lodestar_tpu.params import ForkName
from lodestar_tpu.validator import ValidatorStore
from lodestar_tpu.validator.external_signer import (
    ExternalSignerClient,
    ExternalSignerError,
    ExternalSignerServer,
)
from lodestar_tpu.validator.store import SlashingError

pytestmark = pytest.mark.smoke


@pytest.fixture(scope="module")
def world():
    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG, fork_epochs={ForkName.altair: 0}
    )
    sks = [B.keygen(b"ext-%d" % i) for i in range(3)]
    pks = [C.g1_compress(B.sk_to_pk(sk)) for sk in sks]
    # keys 1 and 2 live in the remote signer; key 0 is local
    server = ExternalSignerServer({pks[1]: sks[1], pks[2]: sks[2]})
    server.start()
    yield cfg, sks, pks, server
    server.close()


def test_client_upcheck_and_keys(world):
    cfg, sks, pks, server = world
    client = ExternalSignerClient(server.url)
    assert client.upcheck()
    assert set(client.public_keys()) == {pks[1], pks[2]}
    assert not ExternalSignerClient("http://127.0.0.1:1").upcheck()


def test_client_sign_roundtrip(world):
    cfg, sks, pks, server = world
    client = ExternalSignerClient(server.url)
    root = b"\x42" * 32
    sig = client.sign(pks[1], root)
    assert B.verify(B.sk_to_pk(sks[1]), root, C.g2_decompress(sig))
    with pytest.raises(ExternalSignerError, match="404|unknown"):
        client.sign(pks[0], root)  # not held by the signer


def test_store_routes_remote_keys_through_signer(world):
    cfg, sks, pks, server = world
    client = ExternalSignerClient(server.url)
    store = ValidatorStore(
        cfg,
        {0: sks[0]},  # local key
        external_signer=client,
        remote_keys={1: pks[1], 2: pks[2]},
    )
    data = {
        "slot": 1,
        "index": 0,
        "beacon_block_root": b"\x01" * 32,
        "source": {"epoch": 0, "root": b"\x00" * 32},
        "target": {"epoch": 1, "root": b"\x02" * 32},
    }
    # remote-keyed validator signs via REST; the signature verifies
    # against the real domain-separated signing root
    sig = store.sign_attestation(1, data)
    slot = data["target"]["epoch"] * params.SLOTS_PER_EPOCH
    root = cfg.compute_signing_root(
        T.AttestationData.hash_tree_root(data),
        cfg.get_domain(slot, params.DOMAIN_BEACON_ATTESTER, slot),
    )
    assert B.verify(B.sk_to_pk(sks[1]), root, C.g2_decompress(sig))
    # local key still signs locally
    assert store.sign_attestation(0, data)
    # slashing protection guards remote keys too (double vote)
    with pytest.raises(SlashingError, match="double"):
        store.sign_attestation(1, data)
    # randao via the shared signing point
    sig_r = store.sign_randao(2, 5)
    assert len(sig_r) == 96


def test_store_without_signer_rejects_remote_keys(world):
    cfg, sks, pks, server = world
    with pytest.raises(ValueError, match="external_signer"):
        ValidatorStore(cfg, {}, remote_keys={1: pks[1]})
    store = ValidatorStore(cfg, {0: sks[0]})
    with pytest.raises(KeyError, match="no signer"):
        store.sign_randao(7, 1)
