"""JAX Fp2 layer vs the pure-Python ground truth (`crypto.fields`)."""

import random

import numpy as np

import jax
import jax.numpy as jnp

from lodestar_tpu.crypto import fields as GT
from lodestar_tpu.ops import fp2

rng = random.Random(0xF92)

N = 8


def rand_fp2(n):
    return [(rng.randrange(GT.P), rng.randrange(GT.P)) for _ in range(n)]


def enc(xs):
    return jnp.asarray(fp2.stack_consts(xs))


def dec(a):
    a = np.asarray(a)
    return [fp2.decode(a[i]) for i in range(a.shape[0])]


@jax.jit
def _suite(a, b):
    from lodestar_tpu.ops import fp
    k = jnp.asarray(fp.const(7))
    return (
        fp2.mul(a, b),
        fp2.sqr(a),
        fp2.add(a, b),
        fp2.sub(a, b),
        fp2.neg(a),
        fp2.conj(a),
        fp2.mul_xi(a),
        fp2.mul_small(a, 3),
        fp2.mul_fp(a, k),
        fp2.inv(a),
        fp2.is_zero(a),
        fp2.eq(a, b),
        fp2.eq(a, a),
    )


def test_fp2_ops():
    xs = rand_fp2(N - 2) + [GT.FP2_ZERO, GT.FP2_ONE]
    ys = rand_fp2(N - 2) + [(5, 9), GT.FP2_ONE]
    a, b = enc(xs), enc(ys)
    mul, sqr, add, sub, neg, conj, xi, m3, mfp, inv, isz, eqab, eqaa = _suite(a, b)
    assert dec(mul) == [GT.fp2_mul(x, y) for x, y in zip(xs, ys)]
    assert dec(sqr) == [GT.fp2_sqr(x) for x in xs]
    assert dec(add) == [GT.fp2_add(x, y) for x, y in zip(xs, ys)]
    assert dec(sub) == [GT.fp2_sub(x, y) for x, y in zip(xs, ys)]
    assert dec(neg) == [GT.fp2_neg(x) for x in xs]
    assert dec(conj) == [GT.fp2_conj(x) for x in xs]
    assert dec(xi) == [GT.fp2_mul_xi(x) for x in xs]
    assert dec(m3) == [GT.fp2_mul_fp(x, 3) for x in xs]
    assert dec(mfp) == [GT.fp2_mul_fp(x, 7) for x in xs]
    want_inv = [
        GT.fp2_inv(x) if not GT.fp2_is_zero(x) else GT.FP2_ZERO for x in xs
    ]
    assert dec(inv) == want_inv
    assert list(np.asarray(isz)) == [GT.fp2_is_zero(x) for x in xs]
    assert list(np.asarray(eqab)) == [GT.fp2_eq(x, y) for x, y in zip(xs, ys)]
    assert all(np.asarray(eqaa))
