"""Proto-array fork choice: weights, head selection, reorgs, viability.

Reference behaviors: packages/fork-choice/src/protoArray/protoArray.ts
(best-child/descendant maintenance), computeDeltas.ts (vote movement),
forkChoice/forkChoice.ts (latest messages, updateHead).
"""

import numpy as np
import pytest

from lodestar_tpu.fork_choice import (
    ForkChoice,
    ProtoArray,
    compute_deltas,
)
from lodestar_tpu.fork_choice.proto_array import ProtoArrayError

pytestmark = pytest.mark.smoke


def make_chain():
    """genesis -> a -> (b, c); b and c compete."""
    pa = ProtoArray("genesis")
    pa.on_block(1, "a", "genesis", 0, 0)
    pa.on_block(2, "b", "a", 0, 0)
    pa.on_block(2, "c", "a", 0, 0)
    return pa


def test_head_follows_weight():
    pa = make_chain()
    fc = ForkChoice(pa, "genesis", np.array([10, 10, 10], np.int64))
    fc.on_attestation(0, 1, "b")
    fc.on_attestation(1, 1, "c")
    fc.on_attestation(2, 1, "c")
    assert fc.update_head() == "c"
    # votes move: two validators switch to b at a later epoch
    fc.on_attestation(1, 2, "b")
    fc.on_attestation(2, 2, "b")
    assert fc.update_head() == "b"


def test_stale_message_ignored():
    pa = make_chain()
    fc = ForkChoice(pa, "genesis", np.array([1, 1], np.int64))
    fc.on_attestation(0, 5, "c")
    fc.on_attestation(0, 3, "b")  # older epoch: ignored
    assert fc.update_head() == "c"


def test_deep_chain_head_descends():
    pa = ProtoArray("genesis")
    for i in range(1, 20):
        pa.on_block(i, f"n{i}", "genesis" if i == 1 else f"n{i-1}", 0, 0)
    fc = ForkChoice(pa, "genesis", np.array([5], np.int64))
    fc.on_attestation(0, 1, "n19")
    assert fc.update_head() == "n19"
    # head from a mid root also reaches the tip
    assert pa.find_head("n7") == "n19"


def test_balance_changes_move_weight():
    pa = make_chain()
    fc = ForkChoice(pa, "genesis", np.array([10, 1], np.int64))
    fc.on_attestation(0, 1, "b")
    fc.on_attestation(1, 1, "c")
    assert fc.update_head() == "b"
    fc.set_balances(np.array([1, 10], np.int64))
    assert fc.update_head() == "c"


def test_unknown_parent_rejected():
    pa = ProtoArray("genesis")
    with pytest.raises(ProtoArrayError):
        pa.on_block(1, "x", "nope", 0, 0)


def test_viability_filters_wrong_justification():
    pa = ProtoArray("genesis")
    pa.on_block(1, "a", "genesis", 0, 0)
    pa.on_block(2, "good", "a", 1, 0)
    pa.on_block(2, "bad", "a", 0, 0)
    # move to justified epoch 1: only "good" is viable
    pa.apply_score_changes([0, 0, 0, 100], 1, 0)  # all weight on "bad"
    assert pa.find_head("a") == "good"


def test_compute_deltas_scatter():
    old = np.array([0, 1, -1], np.int64)
    new = np.array([1, 1, 2], np.int64)
    ob = np.array([5, 5, 5], np.int64)
    nb = np.array([5, 7, 5], np.int64)
    d = compute_deltas(3, old, new, ob, nb)
    assert d == [-5, 5 - 5 + 7, 5]


def test_weights_accumulate_to_ancestors():
    pa = make_chain()
    pa.apply_score_changes([0, 0, 3, 7], 0, 0)
    # a's weight includes both children; genesis includes everything
    assert pa.nodes[pa.indices["a"]].weight == 10
    assert pa.nodes[pa.indices["genesis"]].weight == 10
    assert pa.find_head("genesis") == "c"
