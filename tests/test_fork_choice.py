"""Proto-array fork choice: weights, head selection, reorgs, viability.

Reference behaviors: packages/fork-choice/src/protoArray/protoArray.ts
(best-child/descendant maintenance), computeDeltas.ts (vote movement),
forkChoice/forkChoice.ts (latest messages, updateHead).
"""

import numpy as np
import pytest

from lodestar_tpu.fork_choice import (
    ForkChoice,
    ProtoArray,
    compute_deltas,
)
from lodestar_tpu.fork_choice.proto_array import ProtoArrayError

pytestmark = pytest.mark.smoke


def make_chain():
    """genesis -> a -> (b, c); b and c compete."""
    pa = ProtoArray("genesis")
    pa.on_block(1, "a", "genesis", 0, 0)
    pa.on_block(2, "b", "a", 0, 0)
    pa.on_block(2, "c", "a", 0, 0)
    return pa


def test_head_follows_weight():
    pa = make_chain()
    fc = ForkChoice(pa, "genesis", np.array([10, 10, 10], np.int64))
    fc.on_attestation(0, 1, "b")
    fc.on_attestation(1, 1, "c")
    fc.on_attestation(2, 1, "c")
    assert fc.update_head() == "c"
    # votes move: two validators switch to b at a later epoch
    fc.on_attestation(1, 2, "b")
    fc.on_attestation(2, 2, "b")
    assert fc.update_head() == "b"


def test_stale_message_ignored():
    pa = make_chain()
    fc = ForkChoice(pa, "genesis", np.array([1, 1], np.int64))
    fc.on_attestation(0, 5, "c")
    fc.on_attestation(0, 3, "b")  # older epoch: ignored
    assert fc.update_head() == "c"


def test_deep_chain_head_descends():
    pa = ProtoArray("genesis")
    for i in range(1, 20):
        pa.on_block(i, f"n{i}", "genesis" if i == 1 else f"n{i-1}", 0, 0)
    fc = ForkChoice(pa, "genesis", np.array([5], np.int64))
    fc.on_attestation(0, 1, "n19")
    assert fc.update_head() == "n19"
    # head from a mid root also reaches the tip
    assert pa.find_head("n7") == "n19"


def test_balance_changes_move_weight():
    pa = make_chain()
    fc = ForkChoice(pa, "genesis", np.array([10, 1], np.int64))
    fc.on_attestation(0, 1, "b")
    fc.on_attestation(1, 1, "c")
    assert fc.update_head() == "b"
    fc.set_balances(np.array([1, 10], np.int64))
    assert fc.update_head() == "c"


def test_unknown_parent_rejected():
    pa = ProtoArray("genesis")
    with pytest.raises(ProtoArrayError):
        pa.on_block(1, "x", "nope", 0, 0)


def test_viability_filters_wrong_justification():
    pa = ProtoArray("genesis")
    pa.on_block(1, "a", "genesis", 0, 0)
    pa.on_block(2, "good", "a", 1, 0)
    pa.on_block(2, "bad", "a", 0, 0)
    # move to justified epoch 1: only "good" is viable
    pa.apply_score_changes([0, 0, 0, 100], 1, 0)  # all weight on "bad"
    assert pa.find_head("a") == "good"


def test_compute_deltas_scatter():
    old = np.array([0, 1, -1], np.int64)
    new = np.array([1, 1, 2], np.int64)
    ob = np.array([5, 5, 5], np.int64)
    nb = np.array([5, 7, 5], np.int64)
    d = compute_deltas(3, old, new, ob, nb)
    assert d == [-5, 5 - 5 + 7, 5]


def test_weights_accumulate_to_ancestors():
    pa = make_chain()
    pa.apply_score_changes([0, 0, 3, 7], 0, 0)
    # a's weight includes both children; genesis includes everything
    assert pa.nodes[pa.indices["a"]].weight == 10
    assert pa.nodes[pa.indices["genesis"]].weight == 10
    assert pa.find_head("genesis") == "c"


# -- round-4 hardening: proposer boost, equivocation, prune ----------------


def test_proposer_boost_tips_balanced_fork():
    """Balancing attack: two equal-weight forks; the timely proposal on
    the lighter side wins via the transient boost, then loses it
    (reference: protoArray.ts currentBoost/previousBoost)."""
    pa = make_chain()
    fc = ForkChoice(
        pa, "genesis", np.array([32, 32], np.int64), slots_per_epoch=1
    )
    fc.on_attestation(0, 1, "b")
    fc.on_attestation(1, 1, "c")
    # equal vote weight: tiebreak (root order) picks c
    assert fc.update_head() == "c"
    # a timely proposal builds on b: boost (40% of 64) tips the fork
    fc.on_timely_block("b")
    assert fc.update_head() == "b"
    # next slot: boost cleared, applied boost backed out -> c again
    fc.on_tick_slot()
    assert fc.update_head() == "c"


def test_proposer_boost_is_transient():
    """The boost never persists in node weights."""
    pa = make_chain()
    fc = ForkChoice(pa, "genesis", np.array([10], np.int64), slots_per_epoch=1)
    fc.on_attestation(0, 1, "c")
    fc.on_timely_block("b")
    fc.update_head()
    boosted = pa.nodes[pa.indices["b"]].weight
    assert boosted > 0
    fc.on_tick_slot()
    fc.update_head()
    assert pa.nodes[pa.indices["b"]].weight == 0


def test_equivocating_validator_removed_permanently():
    """A slashed validator's standing vote is backed out once and its
    later messages are ignored (reference: computeDeltas.ts:47-63)."""
    pa = make_chain()
    fc = ForkChoice(pa, "genesis", np.array([10, 1], np.int64))
    fc.on_attestation(0, 1, "b")
    fc.on_attestation(1, 1, "c")
    assert fc.update_head() == "b"
    fc.on_attester_slashing([0])
    assert fc.update_head() == "c"
    assert pa.nodes[pa.indices["b"]].weight == 0
    # the equivocator's new vote is dead on arrival
    fc.on_attestation(0, 9, "b")
    assert fc.update_head() == "c"
    # double-slash is a no-op (process once)
    fc.on_attester_slashing([0])
    assert fc.update_head() == "c"


def test_equivocation_balancing_attack():
    """An attacker flip-flopping between forks cannot keep both heavy
    once slashed: all its weight vanishes."""
    pa = make_chain()
    fc = ForkChoice(pa, "genesis", np.array([100, 1, 1], np.int64))
    fc.on_attestation(1, 1, "b")
    fc.on_attestation(2, 1, "c")
    fc.on_attestation(0, 1, "b")
    assert fc.update_head() == "b"
    fc.on_attestation(0, 2, "c")  # flip
    assert fc.update_head() == "c"
    fc.on_attester_slashing([0])
    # honest weights only: 1 vs 1, tiebreak -> c; attacker gone from both
    fc.update_head()
    assert pa.nodes[pa.indices["b"]].weight == 1
    assert pa.nodes[pa.indices["c"]].weight == 1


def test_prune_below_finalized():
    pa = ProtoArray("genesis", prune_threshold=0)
    for i in range(1, 10):
        pa.on_block(i, f"n{i}", "genesis" if i == 1 else f"n{i-1}", 0, 0)
    pa.on_block(10, "tip_a", "n9", 0, 0)
    pa.on_block(10, "tip_b", "n9", 0, 0)
    fc = ForkChoice(pa, "genesis", np.array([3, 2], np.int64))
    fc.on_attestation(0, 1, "tip_a")
    fc.on_attestation(1, 1, "tip_b")
    assert fc.update_head() == "tip_a"
    removed = fc.prune("n5")
    assert [n.root for n in removed] == ["genesis"] + [f"n{i}" for i in range(1, 5)]
    assert "genesis" not in pa
    assert pa.nodes[0].root == "n5" and pa.nodes[0].parent is None
    # votes still tracked; head from the new anchor still works
    fc.justified_root = "n5"
    assert fc.update_head() == "tip_a"
    # vote movement after prune applies deltas at remapped indices
    fc.on_attestation(1, 2, "tip_a")
    assert fc.update_head() == "tip_a"
    assert pa.nodes[pa.indices["tip_b"]].weight == 0


def test_prune_threshold_noop():
    pa = ProtoArray("genesis")  # default threshold 256
    pa.on_block(1, "a", "genesis", 0, 0)
    assert pa.maybe_prune("a") == []
    assert "genesis" in pa


def test_prune_drops_votes_for_pruned_roots():
    pa = ProtoArray("genesis", prune_threshold=0)
    pa.on_block(1, "a", "genesis", 0, 0)
    pa.on_block(2, "b", "a", 0, 0)
    fc = ForkChoice(pa, "genesis", np.array([5], np.int64))
    fc.on_attestation(0, 1, "a")
    fc.update_head()
    fc.prune("b")
    fc.justified_root = "b"
    # the old vote's root is gone; no negative-weight explosion
    fc.on_attestation(0, 2, "b")
    assert fc.update_head() == "b"
    assert pa.nodes[pa.indices["b"]].weight == 5
