"""Config, shuffling, epoch cache, and signature-set extractors.

End-to-end check: build a small registry, sign a block's statements with
the CPU BLS oracle, extract wire sets via get_block_signature_sets, and
verify every set decodes + verifies (reference behavior:
packages/state-transition/src/signatureSets/index.ts:26-73).
"""

import numpy as np
import pytest

from lodestar_tpu import params
from lodestar_tpu import types as T
from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
from lodestar_tpu.crypto import bls as B
from lodestar_tpu.crypto import curves as C
from lodestar_tpu.params import ForkName
from lodestar_tpu.state_transition import (
    EpochCache,
    get_block_signature_sets,
)
from lodestar_tpu.state_transition.signature_sets import (
    BeaconStateView,
    get_aggregate_and_proof_signature_set,
)
from lodestar_tpu.state_transition.util import (
    compute_shuffled_index,
    shuffle_list,
    shuffled_positions,
    unshuffle_list,
)

pytestmark = pytest.mark.smoke

CFG = create_chain_config(
    MAINNET_CHAIN_CONFIG,
    genesis_validators_root=b"\x42" * 32,
    # make altair active from genesis so blocks carry sync aggregates
    fork_epochs={ForkName.altair: 0},
)


# ---------------------------------------------------------------------------
# config
# ---------------------------------------------------------------------------


def test_fork_schedule_and_domains():
    assert CFG.get_fork_name(0) == ForkName.altair
    assert MAINNET_CHAIN_CONFIG.get_fork_name(0) == ForkName.phase0
    assert MAINNET_CHAIN_CONFIG.get_fork_name(74240 * 32) == ForkName.altair
    d = CFG.get_domain(0, params.DOMAIN_BEACON_PROPOSER, 0)
    assert len(d) == 32 and d[:4] == params.DOMAIN_BEACON_PROPOSER
    # domain depends on fork version active at the message slot
    d_phase0 = MAINNET_CHAIN_CONFIG.get_domain(0, params.DOMAIN_RANDAO, 0)
    d_altair = MAINNET_CHAIN_CONFIG.get_domain(0, params.DOMAIN_RANDAO, 74240 * 32)
    assert d_phase0 != d_altair
    # digest is 4 bytes and fork-dependent
    assert len(CFG.fork_digest(0)) == 4


def test_signing_root_is_signingdata_htr():
    obj_root = b"\x01" * 32
    domain = CFG.get_domain(0, params.DOMAIN_RANDAO, 0)
    import hashlib

    expect = hashlib.sha256(obj_root + domain).digest()
    assert CFG.compute_signing_root(obj_root, domain) == expect


# ---------------------------------------------------------------------------
# shuffling
# ---------------------------------------------------------------------------


def test_vectorized_shuffle_matches_scalar_spec():
    seed = b"\x05" * 32
    n = 100
    pos = shuffled_positions(n, seed)
    for j in [0, 1, 17, 50, 99]:
        assert pos[j] == compute_shuffled_index(j, n, seed)


def test_shuffle_round_trip_and_permutation():
    seed = b"\x09" * 32
    idx = np.arange(211)
    s = shuffle_list(idx, seed)
    assert sorted(s.tolist()) == idx.tolist()  # a permutation
    assert not np.array_equal(s, idx)  # that actually moves things
    assert np.array_equal(unshuffle_list(s, seed), idx)


# ---------------------------------------------------------------------------
# epoch cache + extractors
# ---------------------------------------------------------------------------


def make_registry(n=64):
    sks = [B.keygen(b"st-%d" % i) for i in range(n)]
    pks = [C.g1_compress(B.sk_to_pk(sk)) for sk in sks]
    return sks, pks


def make_state(sks, pks, slot=1):
    cache = EpochCache(pks, epoch=0, seed=b"\x07" * 32)
    return BeaconStateView(
        config=CFG,
        slot=slot,
        epoch_cache=cache,
        block_roots={0: b"\x33" * 32},
    )


def _sign(sks, idx, root):
    return C.g2_compress(B.sign(sks[idx], root))


def test_epoch_cache_committees_partition_registry():
    _, pks = make_registry(64)
    cache = EpochCache(pks, epoch=0, seed=b"\x01" * 32)
    seen = []
    for slot in range(params.SLOTS_PER_EPOCH):
        for ci in range(cache.committees_per_slot):
            seen.extend(cache.get_beacon_committee(slot, ci).tolist())
    assert sorted(seen) == list(range(64))


def test_block_signature_sets_verify_with_cpu_oracle():
    sks, pks = make_registry(64)
    state = make_state(sks, pks)
    cache = state.epoch_cache

    slot = 1
    proposer = 3
    # attestation by committee 0 at slot (all members participate)
    committee = cache.get_beacon_committee(slot, 0)
    att_data = {
        "slot": slot,
        "index": 0,
        "beacon_block_root": b"\x33" * 32,
        "source": {"epoch": 0, "root": bytes(32)},
        "target": {"epoch": 0, "root": b"\x33" * 32},
    }
    from lodestar_tpu.state_transition.signature_sets import (
        get_attestation_data_signing_root,
    )

    att_root = get_attestation_data_signing_root(state, att_data)
    att_sig = B.aggregate_signatures(
        [B.sign(sks[int(v)], att_root) for v in committee]
    )
    attestation = {
        "aggregation_bits": [True] * len(committee),
        "data": att_data,
        "signature": C.g2_compress(att_sig),
    }

    # randao
    epoch_root = T.Epoch.hash_tree_root(0)
    randao_root = CFG.compute_signing_root(
        epoch_root, CFG.get_domain(slot, params.DOMAIN_RANDAO, slot)
    )
    randao = _sign(sks, proposer, randao_root)

    # sync aggregate: first 4 sync-committee members sign prev block root
    sync_bits = [False] * params.SYNC_COMMITTEE_SIZE
    for i in range(4):
        sync_bits[i] = True
    participants = [cache.sync_committee_indices[i] for i in range(4)]
    prev_root = state.get_block_root_at_slot(slot - 1)
    sync_signing = CFG.compute_signing_root(
        T.Root.hash_tree_root(prev_root),
        CFG.get_domain(slot, params.DOMAIN_SYNC_COMMITTEE, slot - 1),
    )
    sync_sig = B.aggregate_signatures(
        [B.sign(sks[int(v)], sync_signing) for v in participants]
    )

    body = T.BeaconBlockBodyAltair.default()
    body["randao_reveal"] = randao
    body["attestations"] = [attestation]
    body["sync_aggregate"] = {
        "sync_committee_bits": sync_bits,
        "sync_committee_signature": C.g2_compress(sync_sig),
    }
    block = {
        "slot": slot,
        "proposer_index": proposer,
        "parent_root": b"\x33" * 32,
        "state_root": bytes(32),
        "body": body,
    }
    block_root = T.BeaconBlockAltair.hash_tree_root(block)
    proposer_root = CFG.compute_signing_root(
        block_root, CFG.get_domain(slot, params.DOMAIN_BEACON_PROPOSER, slot)
    )
    signed_block = {
        "message": block,
        "signature": _sign(sks, proposer, proposer_root),
    }

    sets = get_block_signature_sets(state, signed_block)
    # randao + attestation + proposer + sync = 4
    assert len(sets) == 4
    for ws in sets:
        dec = ws.decode()
        pk = B.aggregate_pubkeys([B.sk_to_pk(sks[i]) for i in dec.indices])
        hm = dec.message
        assert dec.signature is not None
        from lodestar_tpu.crypto import pairing as P

        assert P.multi_pairing_is_one(
            [(pk, hm), (B.NEG_G1_GEN, dec.signature)]
        )

    # flipping one byte of the proposer signature fails that set
    bad = bytearray(signed_block["signature"])
    bad[10] ^= 1
    signed_block["signature"] = bytes(bad)
    sets_bad = get_block_signature_sets(state, signed_block)
    dec = sets_bad[-2].decode()  # proposer set (sync set is last)
    if dec.signature is not None:
        from lodestar_tpu.crypto import pairing as P

        pk = B.sk_to_pk(sks[proposer])
        assert not P.multi_pairing_is_one(
            [(pk, dec.message), (B.NEG_G1_GEN, dec.signature)]
        )


def test_aggregate_and_proof_set_roundtrip():
    sks, pks = make_registry(4)
    state = make_state(sks, pks)
    att = {
        "aggregation_bits": [True],
        "data": {
            "slot": 1,
            "index": 0,
            "beacon_block_root": bytes(32),
            "source": {"epoch": 0, "root": bytes(32)},
            "target": {"epoch": 0, "root": bytes(32)},
        },
        "signature": b"\x00" * 96,
    }
    msg = {"aggregator_index": 2, "aggregate": att, "selection_proof": b"\x00" * 96}
    root = T.AggregateAndProof.hash_tree_root(msg)
    signing = CFG.compute_signing_root(
        root, CFG.get_domain(1, params.DOMAIN_AGGREGATE_AND_PROOF, 1)
    )
    signed = {"message": msg, "signature": _sign(sks, 2, signing)}
    ws = get_aggregate_and_proof_signature_set(state, signed)
    dec = ws.decode()
    from lodestar_tpu.crypto import pairing as P

    assert P.multi_pairing_is_one(
        [(B.sk_to_pk(sks[2]), dec.message), (B.NEG_G1_GEN, dec.signature)]
    )
