"""Durable slashing protection + doppelganger protection.

Reference behaviors: packages/validator/src/slashingProtection/
(repo-backed records, EIP-3076 interchange) and
services/doppelgangerService.ts (watch-window liveness gate).
"""

import os

import pytest

from lodestar_tpu.config import MAINNET_CHAIN_CONFIG
from lodestar_tpu.crypto import bls as B
from lodestar_tpu.validator import (
    DoppelgangerDetected,
    DoppelgangerService,
    DoppelgangerStatus,
    DoppelgangerUnverified,
    SlashingError,
    SlashingProtection,
    ValidatorStore,
)

pytestmark = pytest.mark.smoke

DATA1 = {
    "slot": 1,
    "index": 0,
    "beacon_block_root": b"\x01" * 32,
    "source": {"epoch": 0, "root": b"\x00" * 32},
    "target": {"epoch": 1, "root": b"\x02" * 32},
}
DATA2 = dict(DATA1, beacon_block_root=b"\x03" * 32)  # same target, new root


def test_slashing_protection_survives_restart(tmp_path):
    """THE restart test: a double-sign attempt after process restart
    must be blocked by the on-disk records."""
    db = os.path.join(str(tmp_path), "slashing.db")
    sks = {0: B.keygen(b"safety-0")}

    store = ValidatorStore(MAINNET_CHAIN_CONFIG, sks, slashing_db_path=db)
    store.sign_attestation(0, DATA1)
    store.sign_block(0, {"slot": 5, "proposer_index": 0,
                         "parent_root": b"\x00" * 32,
                         "state_root": b"\x00" * 32,
                         "body": None} | _block_body())
    store.slashing.close()

    # "restart": a fresh process loads the same DB
    store2 = ValidatorStore(MAINNET_CHAIN_CONFIG, sks, slashing_db_path=db)
    with pytest.raises(SlashingError):
        store2.sign_attestation(0, DATA2)  # double vote at target 1
    with pytest.raises(SlashingError):
        store2.sign_block(0, {"slot": 5, "proposer_index": 0,
                              "parent_root": b"\x00" * 32,
                              "state_root": b"\x00" * 32} | _block_body())
    # moving forward is still allowed
    store2.sign_attestation(
        0, dict(DATA1, target={"epoch": 2, "root": b"\x04" * 32})
    )
    store2.slashing.close()


def _block_body():
    return {
        "body": {
            "randao_reveal": b"\x00" * 96,
            "eth1_data": {
                "deposit_root": b"\x00" * 32,
                "deposit_count": 0,
                "block_hash": b"\x00" * 32,
            },
            "graffiti": b"\x00" * 32,
            "proposer_slashings": [],
            "attester_slashings": [],
            "attestations": [],
            "deposits": [],
            "voluntary_exits": [],
            "sync_aggregate": {
                "sync_committee_bits": [False] * 512,
                "sync_committee_signature": b"\x00" * 96,
            },
        }
    }


def test_interchange_roundtrip_persists(tmp_path):
    db1 = os.path.join(str(tmp_path), "a.db")
    db2 = os.path.join(str(tmp_path), "b.db")
    sp1 = SlashingProtection(db_path=db1)
    sp1.check_attestation(b"\xaa" * 48, 3, 7)
    sp1.check_block(b"\xaa" * 48, 42)
    exported = sp1.export_interchange()
    sp1.close()

    sp2 = SlashingProtection(db_path=db2)
    sp2.import_interchange(exported)
    sp2.close()
    sp3 = SlashingProtection(db_path=db2)  # reload from disk
    with pytest.raises(SlashingError):
        sp3.check_attestation(b"\xaa" * 48, 3, 7)  # same target
    with pytest.raises(SlashingError):
        sp3.check_block(b"\xaa" * 48, 42)
    sp3.close()


def test_doppelganger_state_machine():
    live: dict = {}
    epoch = [10]
    detected_cb = []
    svc = DoppelgangerService(
        liveness_fn=lambda ep, idx: {i: live.get((ep, i), False) for i in idx},
        current_epoch_fn=lambda: epoch[0],
        on_detected=detected_cb.append,
    )
    svc.register(1)
    assert svc.status(1) == DoppelgangerStatus.UNVERIFIED
    with pytest.raises(DoppelgangerUnverified):
        svc.assert_safe(1)
    # the registration epoch itself never counts (our own pre-restart
    # duties live there); then two observed-silent epochs -> verified
    svc.on_epoch(11)  # would probe epoch 10 = registration: skipped
    assert svc.status(1) == DoppelgangerStatus.UNVERIFIED
    svc.on_epoch(12)  # probes epoch 11: silent
    assert svc.status(1) == DoppelgangerStatus.UNVERIFIED
    svc.on_epoch(13)  # probes epoch 12: silent -> verified
    assert svc.status(1) == DoppelgangerStatus.VERIFIED
    svc.assert_safe(1)  # no raise
    # a probe outage must NOT count as observed silence
    svc2 = DoppelgangerService(
        liveness_fn=lambda ep, idx: None,
        current_epoch_fn=lambda: 0,
    )
    svc2.register(9)
    svc2.on_epoch(2)
    svc2.on_epoch(3)
    svc2.on_epoch(4)
    assert svc2.status(9) == DoppelgangerStatus.UNVERIFIED

    # a second key sees liveness -> DETECTED forever
    svc.register(2)
    live[(11, 2)] = True  # our key attested at epoch 11 (not by us!)
    svc.on_epoch(12)  # probes epoch 11 (> registration epoch 10)
    assert svc.status(2) == DoppelgangerStatus.DETECTED
    assert detected_cb == [[2]]
    with pytest.raises(DoppelgangerDetected):
        svc.assert_safe(2)
    # detection is permanent, no matter how many silent epochs follow
    svc.on_epoch(13)
    svc.on_epoch(14)
    with pytest.raises(DoppelgangerDetected):
        svc.assert_safe(2)


def test_doppelganger_blocks_store_signing():
    svc = DoppelgangerService(
        liveness_fn=lambda ep, idx: {},
        current_epoch_fn=lambda: 0,
    )
    store = ValidatorStore(
        MAINNET_CHAIN_CONFIG, {0: B.keygen(b"dopp-0")}, doppelganger=svc
    )
    with pytest.raises(DoppelgangerUnverified):
        store.sign_attestation(0, DATA1)
    svc.on_epoch(1)  # registration epoch: skipped
    svc.on_epoch(2)
    svc.on_epoch(3)
    store.sign_attestation(0, DATA1)  # verified now


def test_liveness_endpoint_and_client():
    """The doppelganger probe over the real REST wire."""
    from lodestar_tpu import params
    from lodestar_tpu.api.client import ApiClient
    from lodestar_tpu.api.server import BeaconApiServer, DefaultHandlers
    from lodestar_tpu.chain.chain import BeaconChain
    from lodestar_tpu.config import create_chain_config
    from lodestar_tpu.crypto import curves as C
    from lodestar_tpu.params import ForkName
    from lodestar_tpu.state_transition import create_genesis_state

    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG, fork_epochs={ForkName.altair: 0}
    )
    sks = [B.keygen(b"lv-%d" % i) for i in range(4)]
    pks = [C.g1_compress(B.sk_to_pk(sk)) for sk in sks]
    genesis = create_genesis_state(cfg, pks, genesis_time=2)
    chain = BeaconChain(cfg, genesis)
    chain.head_state.current_epoch_participation[1] = 0b111  # index 1 live
    server = BeaconApiServer(
        DefaultHandlers(genesis_time=2, chain=chain), port=0
    )
    server.listen()
    try:
        client = ApiClient([f"http://127.0.0.1:{server.port}"], timeout=30)
        live = client.get_liveness(0, [0, 1, 2])
        assert live == {0: False, 1: True, 2: False}
    finally:
        server.close()
