"""Bellatrix slice: fork upgrade, payload processing, chain import.

Reference behaviors: packages/state-transition/src/slot/
upgradeStateToBellatrix.ts, block/processExecutionPayload.ts, and the
payload leg of chain/blocks/verifyBlock.ts — wired against the mock EL.
"""

import dataclasses

import pytest

from lodestar_tpu import params
from lodestar_tpu import types as T
from lodestar_tpu.chain.chain import BeaconChain
from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
from lodestar_tpu.crypto import bls as B
from lodestar_tpu.crypto import curves as C
from lodestar_tpu.execution import ExecutionEngineMock, PayloadAttributes
from lodestar_tpu.params import ForkName
from lodestar_tpu.state_transition import create_genesis_state
from lodestar_tpu.state_transition.accessors import (
    get_beacon_proposer_index,
    get_randao_mix,
)
from lodestar_tpu.state_transition.block import (
    BlockProcessError,
    is_merge_transition_complete,
    payload_to_header,
    process_execution_payload,
)
from lodestar_tpu.state_transition.slot import process_slots
from lodestar_tpu.state_transition.state import BeaconState
from lodestar_tpu.validator import ValidatorStore

pytestmark = pytest.mark.smoke

P = params.ACTIVE_PRESET
N_KEYS = 8


def make_cfg(bellatrix_epoch=1):
    return create_chain_config(
        MAINNET_CHAIN_CONFIG,
        fork_epochs={ForkName.altair: 0, ForkName.bellatrix: bellatrix_epoch},
    )


@pytest.fixture(scope="module")
def world():
    cfg = make_cfg()
    sks = [B.keygen(b"bel-%d" % i) for i in range(N_KEYS)]
    pks = [C.g1_compress(B.sk_to_pk(sk)) for sk in sks]
    genesis = create_genesis_state(cfg, pks, genesis_time=2)
    return cfg, sks, pks, genesis


def test_fork_upgrade_at_scheduled_epoch(world):
    cfg, sks, pks, genesis = world
    st = genesis.clone()
    assert st.latest_execution_payload_header is None
    process_slots(st, P.SLOTS_PER_EPOCH)  # enter epoch 1 = bellatrix
    assert st.latest_execution_payload_header is not None
    assert st.fork["current_version"] == cfg.fork_versions[ForkName.bellatrix]
    assert st.fork["previous_version"] == cfg.fork_versions[ForkName.altair]
    assert not is_merge_transition_complete(st)  # default header = pre-merge


def test_state_ssz_roundtrip_across_forks(world):
    cfg, sks, pks, genesis = world
    st = genesis.clone()
    process_slots(st, P.SLOTS_PER_EPOCH + 2)
    data = st.serialize()
    back = BeaconState.deserialize(data, cfg)  # fork-version dispatch
    assert back.latest_execution_payload_header is not None
    assert back.hash_tree_root() == st.hash_tree_root()
    assert back.serialize() == data
    # altair states still round-trip through the altair container
    st0 = genesis.clone()
    process_slots(st0, 2)
    back0 = BeaconState.deserialize(st0.serialize(), cfg)
    assert back0.latest_execution_payload_header is None
    assert back0.hash_tree_root() == st0.hash_tree_root()


def _build_payload(el, state, parent_hash):
    r = el.notify_forkchoice_update(
        parent_hash,
        parent_hash,
        b"\x00" * 32,
        PayloadAttributes(
            timestamp=int(state.genesis_time)
            + state.slot * params.SECONDS_PER_SLOT,
            prev_randao=get_randao_mix(
                state, state.slot // P.SLOTS_PER_EPOCH
            ),
            suggested_fee_recipient=b"\x0b" * 20,
        ),
    )
    return el.get_payload(r.payload_id)


def test_process_execution_payload_checks(world):
    cfg, sks, pks, genesis = world
    st = genesis.clone()
    process_slots(st, P.SLOTS_PER_EPOCH + 1)
    el = ExecutionEngineMock()
    payload = _build_payload(el, st, b"\x00" * 32)
    # valid: transitions the header (the merge block)
    st2 = st.clone()
    process_execution_payload(st2, payload)
    assert is_merge_transition_complete(st2)
    assert bytes(st2.latest_execution_payload_header["block_hash"]) == bytes(
        payload["block_hash"]
    )
    # wrong randao
    bad = dict(payload, prev_randao=b"\x55" * 32)
    with pytest.raises(BlockProcessError, match="randao"):
        process_execution_payload(st.clone(), bad)
    # wrong timestamp
    bad = dict(payload, timestamp=int(payload["timestamp"]) + 1)
    with pytest.raises(BlockProcessError, match="timestamp"):
        process_execution_payload(st.clone(), bad)
    # post-merge: parent must extend the header chain
    bad = dict(payload, parent_hash=b"\x66" * 32)
    with pytest.raises(BlockProcessError, match="parent"):
        process_execution_payload(st2, bad)


def test_payload_header_conversion_matches_ssz(world):
    el = ExecutionEngineMock()
    r = el.notify_forkchoice_update(
        b"\x00" * 32, b"\x00" * 32, b"\x00" * 32,
        PayloadAttributes(7, b"\x01" * 32, b"\x02" * 20),
    )
    payload = el.get_payload(r.payload_id)
    header = payload_to_header(payload)
    assert T.ExecutionPayloadHeader.serialize(header)  # well-formed
    assert bytes(header["block_hash"]) == bytes(payload["block_hash"])


def test_chain_imports_bellatrix_blocks_end_to_end(world):
    """The full loop: altair genesis -> fork upgrade -> produce+import
    bellatrix blocks whose payloads come from (and are verified by) the
    mock EL."""
    cfg, sks, pks, genesis = world
    el = ExecutionEngineMock()
    chain = BeaconChain(cfg, genesis, execution=el)
    store = ValidatorStore(cfg, dict(enumerate(sks)))

    def propose(slot):
        st = genesis.clone()
        process_slots(st, slot)
        proposer = get_beacon_proposer_index(st)
        # the produce pipeline fetches the payload from the wired EL
        block = chain.produce_block(slot, store.sign_randao(proposer, slot))
        if st.latest_execution_payload_header is not None:
            assert "execution_payload" in block["body"]
        # proposer signature over the FORK-AWARE container
        block_type = (
            T.BeaconBlockBellatrix
            if "execution_payload" in block["body"]
            else T.BeaconBlockAltair
        )
        root = cfg.compute_signing_root(
            block_type.hash_tree_root(block),
            cfg.get_domain(slot, params.DOMAIN_BEACON_PROPOSER, slot),
        )
        signed = {
            "message": block,
            "signature": C.g2_compress(B.sign(sks[proposer], root)),
        }
        return chain.process_block(signed)

    # altair block, then cross the fork, then two bellatrix blocks
    propose(1)
    root_merge = propose(P.SLOTS_PER_EPOCH + 1)  # the merge block
    assert chain.head_root_hex == bytes(root_merge).hex()
    head = chain.head_state
    assert is_merge_transition_complete(head)
    # the EL knows the merge payload now; the next block extends it
    root2 = propose(P.SLOTS_PER_EPOCH + 2)
    assert chain.head_root_hex == bytes(root2).hex()
    assert chain.head_root_hex in chain._execution_block_hash
    assert not chain.optimistic_roots  # EL validated everything
