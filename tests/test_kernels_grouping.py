"""Distinct-message grouping: segmented G1 sum + the grouped batch path.

The grouping collapses per-set Miller loops to per-distinct-signing-root
Miller loops via bilinearity (kernels/verify.py rationale block; the
host-side cadence matches the reference's SeenAttestationDatas cache,
packages/beacon-node/src/chain/seenCache/seenAttestationData.ts).

The segmented-scan unit test runs at tiny lane widths in plain XLA on
the CPU platform (fast); the full grouped pipeline equivalence runs in
pallas interpret mode (slow tier, like the other kernel tests).
"""

import random

import numpy as np
import pytest

import jax.numpy as jnp

from lodestar_tpu.crypto import bls as GB
from lodestar_tpu.crypto import curves as GC
from lodestar_tpu.crypto import fields as GF
from lodestar_tpu.crypto.hash_to_curve import hash_to_g2
from lodestar_tpu.kernels import layout as LY
from lodestar_tpu.kernels import verify as KV

random.seed(0xB1E55)


def _jac_decode(planes):
    """[NL, B] Montgomery jacobian planes -> list of affine oracle points."""
    xs = LY.decode_batch(np.asarray(planes[0]))
    ys = LY.decode_batch(np.asarray(planes[1]))
    zs = LY.decode_batch(np.asarray(planes[2]))
    out = []
    for x, y, z in zip(xs, ys, zs):
        if z == 0:
            out.append(None)
            continue
        zi = GF.fp_inv(z)
        zi2 = GF.fp_mul(zi, zi)
        out.append((GF.fp_mul(x, zi2), GF.fp_mul(y, GF.fp_mul(zi2, zi))))
    return out


@pytest.mark.smoke
def test_segmented_g1_sum_matches_oracle():
    n = 8
    ks = [3, 5, 7, 11, 13, 17, 19, 23]
    pts = [GC.scalar_mul(GC.FP_OPS, GC.G1_GEN, k) for k in ks]
    group = np.asarray([0, 0, 0, 1, 1, 2, 3, 3], np.int32)
    dead = np.zeros(n, bool)
    dead[4] = True  # excluded from group 1's sum
    px = jnp.asarray(LY.encode_batch([p[0] for p in pts]))
    py = jnp.asarray(LY.encode_batch([p[1] for p in pts]))
    pz = jnp.asarray(LY.encode_batch([1] * n))
    out_pts, out_inf = KV._j_seg_sum_g1(
        px, py, pz, jnp.asarray(dead), jnp.asarray(group)
    )
    decoded = _jac_decode(out_pts)
    inf = list(np.asarray(out_inf))
    # segment totals at the LAST lane of each segment
    expected = {
        2: [0, 1, 2],        # group 0
        4: [3],              # group 1 (lane 4 dead)
        5: [5],              # group 2
        7: [6, 7],           # group 3
    }
    for head, members in expected.items():
        want = GC.multi_add(GC.FP_OPS, [pts[i] for i in members])
        assert not inf[head]
        assert decoded[head] == want, f"head lane {head}"
    # an all-dead segment sums to infinity
    dead2 = np.ones(n, bool)
    _, inf2 = KV._j_seg_sum_g1(
        px, py, pz, jnp.asarray(dead2), jnp.asarray(group)
    )
    assert all(np.asarray(inf2))


def _jac_decode_g2(planes):
    """[NL, B] Montgomery jacobian FP2 planes -> affine oracle points."""
    x0 = LY.decode_batch(np.asarray(planes[0]))
    x1 = LY.decode_batch(np.asarray(planes[1]))
    y0 = LY.decode_batch(np.asarray(planes[2]))
    y1 = LY.decode_batch(np.asarray(planes[3]))
    z0 = LY.decode_batch(np.asarray(planes[4]))
    z1 = LY.decode_batch(np.asarray(planes[5]))
    out = []
    for i in range(len(x0)):
        z = (z0[i], z1[i])
        if z == (0, 0):
            out.append(None)
            continue
        zi = GF.fp2_inv(z)
        zi2 = GF.fp2_sqr(zi)
        out.append(
            (
                GF.fp2_mul((x0[i], x1[i]), zi2),
                GF.fp2_mul((y0[i], y1[i]), GF.fp2_mul(zi2, zi)),
            )
        )
    return out


@pytest.mark.slow
def test_segmented_g2_sum_matches_oracle():
    """The pre-verify aggregation stage's G2 scan (ISSUE 13,
    KV._j_seg_sum_g2): segment totals at head lanes == the host
    jacobian-add oracle, dead lanes excluded, all-dead segments at
    infinity — the FP2 twin of the G1 test above, at tiny width.

    Slow tier: the FP2 jac_add_full rounds trace ~160 s of XLA graph
    on the 1-core host EVERY run (tracing is uncacheable — dev/NOTES
    round 4), which the tier-1 budget cannot absorb; the algorithm is
    the G1 twin's (fast tier above), only the field ops differ."""
    n = 8
    ks = [3, 5, 7, 11, 13, 17, 19, 23]
    pts = [GC.scalar_mul(GC.FP2_OPS, GC.G2_GEN, k) for k in ks]
    group = np.asarray([0, 0, 0, 1, 1, 2, 3, 3], np.int32)
    dead = np.zeros(n, bool)
    dead[4] = True  # excluded from group 1's sum
    px0 = jnp.asarray(LY.encode_batch([p[0][0] for p in pts]))
    px1 = jnp.asarray(LY.encode_batch([p[0][1] for p in pts]))
    py0 = jnp.asarray(LY.encode_batch([p[1][0] for p in pts]))
    py1 = jnp.asarray(LY.encode_batch([p[1][1] for p in pts]))
    out = KV._j_seg_sum_g2(
        px0, px1, py0, py1, jnp.asarray(dead), jnp.asarray(group)
    )
    decoded = _jac_decode_g2(out[:6])
    inf = list(np.asarray(out[6]))
    expected = {
        2: [0, 1, 2],        # group 0
        4: [3],              # group 1 (lane 4 dead)
        5: [5],              # group 2
        7: [6, 7],           # group 3
    }
    for head, members in expected.items():
        want = GC.multi_add(GC.FP2_OPS, [pts[i] for i in members])
        assert not inf[head]
        assert decoded[head] == want, f"head lane {head}"
    dead2 = np.ones(n, bool)
    out2 = KV._j_seg_sum_g2(
        px0, px1, py0, py1, jnp.asarray(dead2), jnp.asarray(group)
    )
    assert all(np.asarray(out2[6]))


# -- full grouped pipeline (interpret mode, one lane tile) ------------------

pytestmark_slow = pytest.mark.slow
N = 128


def _wire_planes(sets, n):
    """sets: list of (index, root, sig_bytes) single-pubkey wire sets."""
    from lodestar_tpu.bls.ingest import MessageCache, encode_wire_planes

    idx = np.zeros((n, 1), np.int32)
    kmask = np.zeros((n, 1), np.int32)
    valid = np.zeros((n,), np.int32)
    for i, (vi, _root, _sig) in enumerate(sets):
        idx[i, 0] = vi
        kmask[i, 0] = 1
        valid[i] = 1
    msgs = MessageCache().get_many([s[1] for s in sets])
    msgs = msgs + [GC.G2_GEN] * (n - len(sets))
    sig_x0, sig_x1, flags, host_bad = encode_wire_planes(
        [s[2] for s in sets], n
    )
    assert not host_bad.any()

    def enc(vals):
        return jnp.asarray(LY.encode_plain_batch(vals))

    return (
        jnp.asarray(idx), jnp.asarray(kmask),
        enc([m[0][0] for m in msgs]), enc([m[0][1] for m in msgs]),
        enc([m[1][0] for m in msgs]), enc([m[1][1] for m in msgs]),
        jnp.asarray(sig_x0), jnp.asarray(sig_x1), jnp.asarray(flags),
        jnp.asarray(valid),
    )


@pytest.mark.slow
def test_grouped_batch_matches_ungrouped():
    from lodestar_tpu.ops import bls_kernels as BK

    v = 6
    sks = [GB.keygen(b"grp-%d" % i) for i in range(v)]
    pks = [GB.sk_to_pk(sk) for sk in sks]
    tx = jnp.asarray(LY.encode_batch([p[0] for p in pks]))
    ty = jnp.asarray(LY.encode_batch([p[1] for p in pks]))

    # 6 sets over 2 distinct roots (sorted by root), all valid
    roots = [b"\x0a" * 32, b"\x0b" * 32]
    sets = [
        (i, roots[0 if i < 4 else 1], GC.g2_compress(
            GB.sign(sks[i], roots[0 if i < 4 else 1])))
        for i in range(v)
    ]
    sets.sort(key=lambda s: s[1])
    idx, kmask, m0, m1, m2, m3, sx0, sx1, flags, valid = _wire_planes(sets, N)
    group = np.zeros(N, np.int32)
    g = 0
    for i in range(1, v):
        if sets[i][1] != sets[i - 1][1]:
            g += 1
        group[i] = g
    group[v:] = np.arange(g + 1, g + 1 + N - v, dtype=np.int32)
    heads = np.zeros(KV.BT, np.int32)
    heads[0] = 3 if sets[0][1] == roots[0] else 1
    heads[1] = v - 1
    glive = np.zeros(KV.BT, np.int32)
    glive[:2] = 1
    rand = jnp.asarray(BK.make_rand_words(N, np.random.default_rng(9)))

    ok_g, sub_g = KV.verify_batch_device_wire_grouped(
        tx, ty, idx, kmask, m0, m1, m2, m3, sx0, sx1, flags,
        jnp.asarray(group), jnp.asarray(heads), jnp.asarray(glive),
        rand, valid,
    )
    ok_u, sub_u = KV.verify_batch_device_wire(
        tx, ty, idx, kmask, m0, m1, m2, m3, sx0, sx1, flags, rand, valid
    )
    assert bool(ok_g) and bool(ok_u)
    assert list(np.asarray(sub_g)) == list(np.asarray(sub_u))

    # one tampered signature fails the grouped batch too
    bad_sig = GC.g2_compress(
        GC.scalar_mul(GC.FP2_OPS, GB.sign(sks[2], sets[2][1]), 2)
    )
    sets_bad = list(sets)
    sets_bad[2] = (sets[2][0], sets[2][1], bad_sig)
    idx, kmask, m0, m1, m2, m3, sx0, sx1, flags, valid = _wire_planes(
        sets_bad, N
    )
    ok_bad, _ = KV.verify_batch_device_wire_grouped(
        tx, ty, idx, kmask, m0, m1, m2, m3, sx0, sx1, flags,
        jnp.asarray(group), jnp.asarray(heads), jnp.asarray(glive),
        rand, valid,
    )
    assert not bool(ok_bad)


@pytest.mark.slow
def test_verifier_uses_grouped_path_with_duplicate_roots():
    """The TpuBlsVerifier end-to-end: duplicate signing roots trigger the
    grouped batch; verdict order survives the sort (unsort mapping)."""
    from lodestar_tpu.bls.pubkey_table import PubkeyTable
    from lodestar_tpu.bls.signature_set import WireSignatureSet
    from lodestar_tpu.bls.verifier import TpuBlsVerifier

    v = 6
    sks = [GB.keygen(b"vgrp-%d" % i) for i in range(v)]
    pks = [GB.sk_to_pk(sk) for sk in sks]
    table = PubkeyTable(capacity=v)
    table.register_points_unchecked(pks, tile_to=v)
    verifier = TpuBlsVerifier(table, rng=np.random.default_rng(5))

    # UNSORTED roots so begin_job must sort + unsort
    roots = [b"\x0c" * 32, b"\x0d" * 32]
    order = [1, 0, 1, 1, 0, 1]
    sets = [
        WireSignatureSet.single(
            i, roots[order[i]],
            GC.g2_compress(GB.sign(sks[i], roots[order[i]])),
        )
        for i in range(v)
    ]
    assert verifier.verify_signature_sets(
        sets, __import__("lodestar_tpu.bls.verifier", fromlist=["VerifyOptions"]).VerifyOptions(batchable=True)
    )

    # tamper set #3 (root group 1): batch fails -> per-set retry; the
    # verdict must land on position 3 after the unsort
    bad = GC.g2_compress(
        GC.scalar_mul(GC.FP2_OPS, GB.sign(sks[3], roots[1]), 2)
    )
    sets_bad = list(sets)
    sets_bad[3] = WireSignatureSet.single(3, roots[1], bad)
    job = verifier.begin_job(sets_bad, batchable=True)
    assert not verifier.finish_job(job)
    assert list(job.verdicts) == [True, True, True, False, True, True]


@pytest.mark.smoke
def test_group_heads_gather_and_liveness():
    """_j_group_heads: each group's last-lane total + its message gather
    onto the BT tile; dead groups (padding or all-dead segments) become
    generator pairs excluded by the live row."""
    n = 8
    ks = [3, 5, 7, 11, 13, 17, 19, 23]
    pts = [GC.scalar_mul(GC.FP_OPS, GC.G1_GEN, k) for k in ks]
    px = jnp.asarray(LY.encode_batch([p[0] for p in pts]))
    py = jnp.asarray(LY.encode_batch([p[1] for p in pts]))
    pz = jnp.asarray(LY.encode_batch([1] * n))
    group = np.asarray([0, 0, 1, 1, 2, 2, 3, 3], np.int32)
    dead = np.zeros(n, bool)
    dead[4] = dead[5] = True  # group 2 entirely dead
    seg_pts, seg_inf = KV._j_seg_sum_g1(
        px, py, pz, jnp.asarray(dead), jnp.asarray(group)
    )

    # two distinct messages riding lanes (group i uses msg i % 2)
    msgs = [hash_to_g2(b"gh-%d" % (i % 2)) for i in range(n)]
    m = [
        jnp.asarray(LY.encode_batch(v))
        for v in (
            [p[0][0] for p in msgs],
            [p[0][1] for p in msgs],
            [p[1][0] for p in msgs],
            [p[1][1] for p in msgs],
        )
    ]
    head_lanes = np.zeros(KV.BT, np.int32)
    head_lanes[:4] = [1, 3, 5, 7]  # last lane of each group
    glive = np.zeros(KV.BT, np.int32)
    glive[:4] = 1
    gx, gy, gz, qx0, qx1, qy0, qy1, live_row = KV._j_group_heads(
        seg_pts, seg_inf, *m, jnp.asarray(head_lanes), jnp.asarray(glive)
    )
    live = np.asarray(live_row)[0]
    # groups 0, 1, 3 live; group 2 all-dead; padding lanes dead
    assert list(live[:4]) == [1, 1, 0, 1]
    assert not live[4:].any()
    decoded = _jac_decode((gx, gy, gz))
    assert decoded[0] == GC.multi_add(GC.FP_OPS, [pts[0], pts[1]])
    assert decoded[1] == GC.multi_add(GC.FP_OPS, [pts[2], pts[3]])
    assert decoded[3] == GC.multi_add(GC.FP_OPS, [pts[6], pts[7]])
    # dead lanes carry the generator (excluded by live anyway)
    assert decoded[2] == GC.G1_GEN and decoded[4] == GC.G1_GEN
    # the gathered G2 messages match each group's own message
    qx0_d = LY.decode_batch(np.asarray(qx0))
    for g, lane in ((0, 1), (1, 3), (3, 7)):
        assert qx0_d[g] == msgs[lane][0][0], g
