"""Test configuration: run the suite on a virtual 8-device CPU platform so
multi-chip sharding paths compile and execute without TPU hardware (the
driver separately dry-runs `__graft_entry__.dryrun_multichip`).

The axon sitecustomize imports jax and registers the TPU backend before
conftest runs, so env-var edits to `JAX_PLATFORMS` are too late; instead
select the platform via `jax.config` (backend *clients* are created lazily,
so this still takes effect).  `XLA_FLAGS` is amended before the CPU client
exists for the same reason.

Set LODESTAR_TPU_TEST_PLATFORM=tpu to intentionally run tests on the real
chip instead.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402  (import order is the point here)

if os.environ.get("LODESTAR_TPU_TEST_PLATFORM", "cpu") == "cpu":
    jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the pairing kernels are compile-heavy, and
# the cache makes repeat test runs start in seconds instead of minutes.
jax.config.update("jax_compilation_cache_dir", "/tmp/lodestar_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
