"""Test configuration: run the suite on a virtual 8-device CPU platform so
multi-chip sharding paths compile and execute without TPU hardware (the
driver separately dry-runs `__graft_entry__.dryrun_multichip`).

The axon sitecustomize imports jax and registers the TPU backend before
conftest runs, so env-var edits to `JAX_PLATFORMS` are too late; instead
select the platform via `jax.config` (backend *clients* are created lazily,
so this still takes effect).  `XLA_FLAGS` is amended before the CPU client
exists for the same reason.

Set LODESTAR_TPU_TEST_PLATFORM=tpu to intentionally run tests on the real
chip instead.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Persistent compilation cache under a REPO-LOCAL dir (ISSUE 11
# satellite; dev/NOTES.md round-7): the fast tier's budget goes to
# XLA:CPU `jax.jit` compiles of the ops/-layer glue, and /tmp caches
# are wiped between driver sessions — a repo-local cache survives, so
# repeat tier-1 runs start warm.  JAX_COMPILATION_CACHE_DIR overrides
# (CI can point it at a shared volume).
_JAX_CACHE_DIR = os.environ.get("JAX_COMPILATION_CACHE_DIR") or os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), ".jax_cache"
)
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _JAX_CACHE_DIR)

import jax  # noqa: E402  (import order is the point here)

if os.environ.get("LODESTAR_TPU_TEST_PLATFORM", "cpu") == "cpu":
    jax.config.update("jax_platforms", "cpu")

jax.config.update("jax_compilation_cache_dir", _JAX_CACHE_DIR)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
