"""Proof-serving data plane (proofs/): plane reads vs the host oracle.

The load-bearing invariant: every proof served off the warm engine
planes is BIT-IDENTICAL to `container_branch`/`container_branches`,
and every situation the planes cannot serve returns None (never a
wrong proof) so the host path completes the request.
"""

import random

import pytest

from lodestar_tpu import params
from lodestar_tpu.chain.memory_governor import StateMemoryGovernor
from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
from lodestar_tpu.crypto import bls as B
from lodestar_tpu.crypto import curves as C
from lodestar_tpu.params import ForkName
from lodestar_tpu.proofs import (
    ProofBundleCache,
    ProofService,
    estimate_bytes,
    pack_multiproof,
    state_multiproof,
    state_proof,
    verify_multiproof,
)
from lodestar_tpu.ssz import is_valid_merkle_branch
from lodestar_tpu.ssz.core import container_branch, container_branches
from lodestar_tpu.state_transition import create_genesis_state, process_slots
from lodestar_tpu.utils.metrics import Registry

P = params.ACTIVE_PRESET
N_KEYS = 16


@pytest.fixture(scope="module")
def warm_state():
    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG, fork_epochs={ForkName.altair: 0}
    )
    pks = [
        C.g1_compress(B.sk_to_pk(B.keygen(b"proofs-%d" % i)))
        for i in range(N_KEYS)
    ]
    state = create_genesis_state(cfg, pks, genesis_time=7)
    process_slots(state, 3)  # populate block/state root history
    state.hash_tree_root()  # warm the engine planes
    return state


# paths the planes serve directly: top-level leaves, packed-cell chunk
# indices (with and without a length mix-in), and nested memo fields
PLANE_PATHS = [
    ["slot"],
    ["genesis_time"],
    ["validators"],
    ["balances"],
    ["current_sync_committee"],
    ["next_sync_committee"],
    ["finalized_checkpoint"],
    ["finalized_checkpoint", "root"],
    ["finalized_checkpoint", "epoch"],
    ["latest_block_header", "state_root"],
    ["fork", "current_version"],
    ["balances", "0"],
    ["validators", "3"],
    ["block_roots", "5"],
    ["state_roots", "0"],
    ["randao_mixes", "7"],
    ["slashings", "0"],
    ["inactivity_scores", "0"],
    ["previous_epoch_participation", "0"],
]


def test_plane_proofs_bit_identical_to_host(warm_state):
    st = warm_state
    value = st.to_value()
    ctype = st._container()
    root = st.hash_tree_root()
    for path in PLANE_PATHS:
        got = state_proof(st, path)
        assert got is not None, f"plane path unservable: {path}"
        want = container_branch(ctype, value, path)
        assert got == want, f"mismatch at {path}"
        leaf, branch, depth, index = got
        assert is_valid_merkle_branch(leaf, branch, depth, index, root), path


def test_multiproof_matches_container_branches(warm_state):
    st = warm_state
    paths = [
        ["next_sync_committee"],
        ["finalized_checkpoint", "root"],
        ["current_sync_committee"],
    ]
    got = state_multiproof(st, paths)
    assert got is not None
    want = container_branches(st._container(), st.to_value(), paths)
    assert got == want


def test_plane_proof_random_chunk_indices(warm_state):
    """Random leaf indices across each packed field's PADDED leaf
    space — beyond-live indices prove zero chunks, exactly like the
    host oracle."""
    st = warm_state
    value = st.to_value()
    ctype = st._container()
    root = st.hash_tree_root()
    rng = random.Random(17)
    engine = st._root_engine
    for fname in (
        "balances",
        "validators",
        "block_roots",
        "randao_mixes",
        "slashings",
        "inactivity_scores",
    ):
        tree, _length, _mixin = engine.leaf_cell(fname)
        pad = 1 << tree.depth
        for ci in {0, pad - 1, rng.randrange(pad), rng.randrange(pad)}:
            path = [fname, str(ci)]
            got = state_proof(st, path)
            assert got is not None, path
            assert got == container_branch(ctype, value, path), path
            leaf, branch, depth, index = got
            assert is_valid_merkle_branch(
                leaf, branch, depth, index, root
            ), path


def test_plane_proof_stays_current_after_mutation(warm_state):
    """Advance the state (dirty tracking -> incremental resync): plane
    proofs must follow the NEW root, still bit-identical to host."""
    st = warm_state.clone()
    process_slots(st, int(st.slot) + 2)
    root = st.hash_tree_root()
    for path in (["slot"], ["state_roots", "1"], ["latest_block_header"]):
        got = state_proof(st, path)
        assert got is not None
        assert got == container_branch(st._container(), st.to_value(), path)
        leaf, branch, depth, index = got
        assert is_valid_merkle_branch(leaf, branch, depth, index, root)


def test_unservable_paths_return_none_not_wrong(warm_state):
    st = warm_state
    # unknown field, deep path into a packed cell, out-of-tree index
    assert state_proof(st, ["no_such_field"]) is None
    assert state_proof(st, ["balances", "0", "x"]) is None
    engine = st._root_engine
    tree, _, _ = engine.leaf_cell("balances")
    assert state_proof(st, ["balances", str(1 << tree.depth)]) is None
    # all-or-nothing multiproof: one bad path fails the whole batch
    assert state_multiproof(st, [["slot"], ["no_such_field"]]) is None
    # expected-root mismatch (serving a stale snapshot is worse than
    # falling through)
    assert state_proof(st, ["slot"], expected_root=b"\x00" * 32) is None


def test_released_planes_fall_through_to_host(warm_state):
    """The post-eviction contract: a state whose engine planes were
    released (the governor's demote path calls release_planes) serves
    None from the plane reader while the host path still completes."""
    st = warm_state.clone()
    st.hash_tree_root()
    assert state_proof(st, ["slot"]) is not None
    st._root_engine.release_planes()
    assert state_proof(st, ["slot"]) is None
    st2 = warm_state.clone()
    st2._root_engine = None  # fully evicted engine
    assert state_proof(st2, ["slot"]) is None
    # host oracle still serves the request
    leaf, branch, depth, index = container_branch(
        st2._container(), st2.to_value(), ["slot"]
    )
    assert is_valid_merkle_branch(
        leaf, branch, depth, index, st2.hash_tree_root()
    )


def test_full_htr_mode_stale_engine_returns_none(warm_state, monkeypatch):
    """LODESTAR_TPU_HTR=full bypasses the engine: after a mutation the
    planes are stale, and the reader must refuse to serve them."""
    st = warm_state.clone()
    st.hash_tree_root()
    monkeypatch.setenv("LODESTAR_TPU_HTR", "full")
    process_slots(st, int(st.slot) + 1)
    assert state_proof(st, ["slot"]) is None


# -- descending multiproof ---------------------------------------------------


def test_multiproof_pack_dedupes_and_verifies(warm_state):
    st = warm_state
    paths = [
        ["finalized_checkpoint", "root"],
        ["finalized_checkpoint", "epoch"],
        ["next_sync_committee"],
        ["slot"],
    ]
    proofs = state_multiproof(st, paths)
    assert proofs is not None
    packed = pack_multiproof(proofs)
    total_branch_nodes = sum(len(b) for _, b, _, _ in proofs)
    # shared ancestry (two checkpoint leaves, common upper levels) must
    # dedupe: strictly fewer helper nodes than the naive concatenation
    assert len(packed["helpers"]) < total_branch_nodes
    # descending gindex order
    helper_g = [g for g, _ in packed["helpers"]]
    assert helper_g == sorted(helper_g, reverse=True)
    leaf_g = list(packed["leaves"])
    assert leaf_g == sorted(leaf_g, reverse=True)
    root = st.hash_tree_root()
    assert verify_multiproof(packed["leaves"], packed["helpers"], root)
    # tampered leaf fails (bit-flip: some genesis leaves are all-zero)
    bad = dict(packed["leaves"])
    g0 = next(iter(bad))
    bad[g0] = bytes(b ^ 0xFF for b in bad[g0])
    assert not verify_multiproof(bad, packed["helpers"], root)
    # incomplete helper set fails closed, does not raise
    assert not verify_multiproof(
        packed["leaves"], packed["helpers"][:-1], root
    )


def test_multiproof_rejects_on_path_helpers():
    """Forged leaves must not verify by planting helpers ON the leaf
    paths (which would shadow the honest recomputation): the verifier
    rejects any helper at a leaf's gindex or an ancestor of one, and
    any helper it could never consume."""
    from lodestar_tpu.ssz.hasher import digest

    n4, n5, n6, n7 = (bytes([i]) * 32 for i in (4, 5, 6, 7))
    n2, n3 = digest(n4 + n5), digest(n6 + n7)
    root = digest(n2 + n3)
    fake = b"\xaa" * 32
    # honest round-trip as the baseline
    assert verify_multiproof({4: n4, 5: n5}, [(3, n3)], root)
    # helper at the leaves' shared ancestor short-circuits the fold:
    # forged leaves would verify against the real root
    assert not verify_multiproof(
        {4: fake, 5: fake}, [(2, n2), (3, n3)], root
    )
    # helper at a leaf's own gindex must not shadow the leaf
    assert not verify_multiproof(
        {4: fake}, [(4, n4), (5, n5), (3, n3)], root
    )
    # helper the fold could never consume (sibling off every leaf path)
    assert not verify_multiproof({4: n4, 5: n5}, [(3, n3), (6, n6)], root)
    # duplicate helper gindex
    assert not verify_multiproof({4: n4, 5: n5}, [(3, n3), (3, n3)], root)
    # no leaves at all
    assert not verify_multiproof({}, [(2, n2), (3, n3)], root)


def test_multiproof_verifies_ancestor_leaves():
    """A requested leaf that is an ancestor of another requested leaf
    is still verified — its claimed value must match the value
    recomputed from the deeper leaf, in BOTH directions."""
    from lodestar_tpu.ssz.hasher import digest

    n4, n5, n3 = bytes([4]) * 32, bytes([5]) * 32, bytes([3]) * 32
    n2 = digest(n4 + n5)
    root = digest(n2 + n3)
    fake = b"\xbb" * 32
    assert verify_multiproof({2: n2, 4: n4}, [(5, n5), (3, n3)], root)
    # forged ancestor leaf, honest deeper leaf
    assert not verify_multiproof({2: fake, 4: n4}, [(5, n5), (3, n3)], root)
    # honest ancestor leaf, forged deeper leaf
    assert not verify_multiproof({2: n2, 4: fake}, [(5, n5), (3, n3)], root)


def test_multiproof_pack_ancestor_leaf_roundtrip(warm_state):
    """pack_multiproof output with one requested path an ancestor of
    another still round-trips through the strict verifier, and forging
    either leaf fails."""
    st = warm_state
    paths = [["finalized_checkpoint"], ["finalized_checkpoint", "root"]]
    proofs = state_multiproof(st, paths)
    assert proofs is not None
    packed = pack_multiproof(proofs)
    root = st.hash_tree_root()
    assert verify_multiproof(packed["leaves"], packed["helpers"], root)
    for g in packed["leaves"]:
        bad = dict(packed["leaves"])
        bad[g] = bytes(b ^ 0xFF for b in bad[g])
        assert not verify_multiproof(bad, packed["helpers"], root), g


# -- bundle cache ------------------------------------------------------------


def test_bundle_cache_bounds_and_lru():
    c = ProofBundleCache(max_entries=3, max_bytes=1 << 20)
    for i in range(4):
        c.put("k", i, {"v": i})
    assert c.get("k", 0) is None  # LRU-evicted at the entry bound
    assert c.get("k", 3) == {"v": 3}
    assert c.evicted == 1
    # byte bound: one oversized payload evicts the rest
    c2 = ProofBundleCache(max_entries=100, max_bytes=200)
    c2.put("k", "small", "x")
    c2.put("k", "big", b"\x00" * 500, nbytes=500)
    assert c2.resident_bytes() <= 500  # small one evicted first
    assert c2.get("k", "small") is None


def test_bundle_cache_invalidate_and_peek():
    c = ProofBundleCache()
    c.put("lc_update", 1, "a")
    c.put("lc_update", 2, "b")
    c.put("finality", "latest", "c")
    assert c.invalidate("lc_update", 1) == 1
    assert c.invalidate("lc_update") == 1  # the remaining period
    assert c.get("finality", "latest") == "c"
    hits, misses = c.hits, c.misses
    assert c.peek("finality", "latest") == "c"
    assert (c.hits, c.misses) == (hits, misses)  # peek leaves stats alone
    assert c.invalidate() == 1  # drop everything
    assert c.resident_bytes() == 0


def test_bundle_cache_drain_and_stats():
    c = ProofBundleCache()
    for i in range(10):
        c.put("k", i, b"\x00" * 100, nbytes=100)
    assert c.resident_bytes() == 1000
    freed = c.drain(target_bytes=250)
    assert freed == 800 and c.resident_bytes() == 200
    assert c.drained == 8
    assert c.get("k", 9) is not None  # LRU drained first, MRU survives
    s = c.stats()
    assert s["entries"] == 2 and s["bytes"] == 200
    assert c.drain() == 200 and c.resident_bytes() == 0


def test_estimate_bytes_shapes():
    assert estimate_bytes(b"\x00" * 100) == 132
    assert estimate_bytes({"a": [1, 2]}) > estimate_bytes({"a": []})
    assert estimate_bytes(None) == 8


# -- governor integration: aux drain + leases --------------------------------


class _FakeDrainable:
    def __init__(self, nbytes):
        self.nbytes = nbytes
        self.drain_calls = []

    def resident_bytes(self):
        return self.nbytes

    def drain(self, target_bytes=0):
        self.drain_calls.append(target_bytes)
        freed = max(0, self.nbytes - target_bytes)
        self.nbytes -= freed
        return freed


def test_governor_drains_aux_before_states():
    gov = StateMemoryGovernor(1000, registry=Registry())
    aux = _FakeDrainable(1500)
    gov.register_aux("proof_bundles", aux)  # triggers enforce
    assert aux.nbytes <= 1000  # drained down to the budget
    assert gov.evictions["drain"] == 1
    assert gov.status()["aux_bytes"] == aux.nbytes
    gov.unregister_aux("proof_bundles")
    assert gov.status()["aux_bytes"] == 0


def test_governor_aux_under_budget_not_drained():
    gov = StateMemoryGovernor(1 << 20, registry=Registry())
    aux = _FakeDrainable(100)
    gov.register_aux("proof_bundles", aux)
    gov.enforce()
    assert aux.drain_calls == []  # no squeeze, no drain
    assert gov.evictions["drain"] == 0


def test_governor_lease_refcounts():
    gov = StateMemoryGovernor(None, registry=Registry())
    key = ("state", "ab" * 32)
    with gov.lease(key):
        assert gov.status()["leases"] == 1
        with gov.lease(key):  # reentrant
            assert gov.status()["leases"] == 1
    assert gov.status()["leases"] == 0


# -- ProofService ------------------------------------------------------------


class _StubUpdate:
    def __init__(self, slot):
        self.attested_header = {"slot": slot}


class _StubLc:
    def __init__(self):
        self.updates = {}
        self.plane_proofs = 0
        self.get_update_calls = 0

    def get_update(self, period):
        self.get_update_calls += 1
        return self.updates.get(period)

    def get_finality_update(self):
        return self.updates.get("finality")

    def get_optimistic_update(self):
        return self.updates.get("optimistic")


class _StubChain:
    def __init__(self):
        from lodestar_tpu.chain.emitter import ChainEventEmitter

        self.emitter = ChainEventEmitter()
        self.config = None
        self.head_root_hex = "cd" * 32
        self.memory_governor = None


@pytest.fixture()
def svc():
    chain = _StubChain()
    lc = _StubLc()
    service = ProofService(chain, light_client_server=lc)
    # rendering needs real LightClientUpdate values; these unit tests
    # cover routing/caching/accounting, so stub the renderer
    service._render_update = lambda upd: {
        "slot": str(upd.attested_header["slot"])
    }
    return chain, lc, service


def test_service_update_serving_and_invalidation(svc):
    from lodestar_tpu.chain.emitter import ChainEvent
    from lodestar_tpu.light_client.lightclient import sync_period

    chain, lc, service = svc
    period_slots = P.EPOCHS_PER_SYNC_COMMITTEE_PERIOD * P.SLOTS_PER_EPOCH
    lc.updates[0] = _StubUpdate(5)
    lc.updates[2] = _StubUpdate(2 * period_slots + 1)
    out = service.light_client_updates(0, 4)
    assert len(out) == 2  # empty periods skipped
    assert out[0] == {"version": "altair", "data": {"slot": "5"}}
    assert service.sources == {"bundle": 0, "plane": 0, "host": 2}
    out2 = service.light_client_updates(0, 4)
    assert out2 == out
    assert service.sources["bundle"] == 2  # both served from bundles
    # a better update for period 0 invalidates exactly that bundle
    upd = _StubUpdate(7)
    assert sync_period(7) == 0
    lc.updates[0] = upd
    chain.emitter.emit(ChainEvent.light_client_update, upd)
    out3 = service.light_client_updates(0, 4)
    assert out3[0]["data"] == {"slot": "7"}
    assert service.sources["host"] == 3  # period 0 re-rendered, 2 cached


def test_service_latest_and_head_invalidation(svc):
    from lodestar_tpu.chain.emitter import ChainEvent

    chain, lc, service = svc
    assert service.finality_update() is None  # nothing produced yet
    lc.updates["finality"] = _StubUpdate(9)
    lc.updates["optimistic"] = _StubUpdate(11)
    assert service.finality_update() == {"slot": "9"}
    assert service.finality_update() == {"slot": "9"}
    assert service.optimistic_update() == {"slot": "11"}
    assert service.sources["bundle"] == 1
    chain.emitter.emit(ChainEvent.head, b"\x01" * 32, 12)
    stats_before = service.cache.stats()["entries"]
    assert stats_before == 0  # head event dropped both latest bundles
    assert service.finality_update() == {"slot": "9"}
    assert service.sources["host"] == 3


def test_service_period_rollover_warming(svc):
    chain, lc, service = svc
    period_slots = P.EPOCHS_PER_SYNC_COMMITTEE_PERIOD * P.SLOTS_PER_EPOCH
    lc.updates[0] = _StubUpdate(3)
    service.on_slot(1)  # first tick just anchors the period
    assert service.batch_generated == 0
    service.on_slot(period_slots + 1)  # rollover into period 1
    assert service.batch_generated == 1
    assert service.cache.peek("lc_update", 0) is not None
    calls = lc.get_update_calls
    service.on_slot(period_slots + 2)  # same period: no re-warm
    assert lc.get_update_calls == calls
    st = service.status()
    assert st["batch_generated"] == 1
    assert set(st["sources"]) == {"bundle", "plane", "host"}


def test_service_state_proofs_plane_then_bundle(warm_state):
    chain = _StubChain()
    service = ProofService(chain)
    paths = [["slot"], ["finalized_checkpoint", "root"]]
    data = service.state_proof_data(warm_state, paths)
    assert service.sources["plane"] == 1
    root = warm_state.hash_tree_root()
    assert data["state_root"] == "0x" + root.hex()
    assert len(data["proofs"]) == 2
    for p in data["proofs"]:
        assert is_valid_merkle_branch(
            bytes.fromhex(p["leaf"][2:]),
            [bytes.fromhex(b[2:]) for b in p["branch"]],
            p["depth"],
            p["index"],
            root,
        )
    leaves = {
        int(x["gindex"]): bytes.fromhex(x["node"][2:])
        for x in data["multiproof"]["leaves"]
    }
    helpers = [
        (int(x["gindex"]), bytes.fromhex(x["node"][2:]))
        for x in data["multiproof"]["helpers"]
    ]
    assert verify_multiproof(leaves, helpers, root)
    # second request: the rendered bundle
    assert service.state_proof_data(warm_state, paths) == data
    assert service.sources["bundle"] == 1
    # single path keeps the original response shape
    one = service.state_proof_data(warm_state, [["slot"]])
    assert set(one) == {"leaf", "branch", "depth", "index", "state_root"}
    # bad path raises for the handler's 400
    with pytest.raises((KeyError, ValueError, TypeError)):
        service.state_proof_data(warm_state, [["nope"]])


def test_service_state_proofs_host_fallback(warm_state):
    chain = _StubChain()
    service = ProofService(chain)
    st = warm_state.clone()
    st._root_engine = None  # evicted: plane reader refuses
    data = service.state_proof_data(st, [["slot"]])
    assert service.sources == {"bundle": 0, "plane": 0, "host": 1}
    assert is_valid_merkle_branch(
        bytes.fromhex(data["leaf"][2:]),
        [bytes.fromhex(b[2:]) for b in data["branch"]],
        data["depth"],
        data["index"],
        st.hash_tree_root(),
    )


def test_service_bootstrap_attribution(svc, monkeypatch):
    chain, lc, service = svc
    import lodestar_tpu.api.encoding as encoding

    monkeypatch.setattr(encoding, "to_json", lambda _t, v: dict(v))
    boots = {b"\x01" * 32: {"who": 1}, b"\x02" * 32: {"who": 2}}

    def get_bootstrap(root):
        lc.plane_proofs += 1 if root == b"\x01" * 32 else 0
        return boots.get(root)

    lc.get_bootstrap = get_bootstrap
    assert service.bootstrap(b"\x01" * 32) == {"who": 1}
    assert service.sources["plane"] == 1
    assert service.bootstrap(b"\x02" * 32) == {"who": 2}
    assert service.sources["host"] == 1
    assert service.bootstrap(b"\x01" * 32) == {"who": 1}  # bundle hit
    assert service.sources["bundle"] == 1
    assert service.bootstrap(b"\x03" * 32) is None  # unknown root -> 404
