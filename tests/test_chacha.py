"""ChaCha20-Poly1305 against the RFC 8439 test vectors."""

import os

import pytest

from lodestar_tpu.network.chacha import (
    _chacha20_block,
    _poly1305,
    chacha20_xor,
    open_,
    seal,
)

pytestmark = pytest.mark.smoke


def test_chacha20_block_rfc_vector():
    # RFC 8439 §2.3.2
    key = bytes(range(32))
    nonce = bytes.fromhex("000000090000004a00000000")
    block = _chacha20_block(key, 1, nonce)
    assert block.hex().startswith("10f1e7e4d13b5915500fdd1fa32071c4")


def test_chacha20_encrypt_rfc_vector():
    # RFC 8439 §2.4.2
    key = bytes(range(32))
    nonce = bytes.fromhex("000000000000004a00000000")
    plaintext = (
        b"Ladies and Gentlemen of the class of '99: If I could offer you "
        b"only one tip for the future, sunscreen would be it."
    )
    ct = chacha20_xor(key, 1, nonce, plaintext)
    assert ct.hex().startswith("6e2e359a2568f98041ba0728dd0d6981")
    assert chacha20_xor(key, 1, nonce, ct) == plaintext


def test_poly1305_rfc_vector():
    # RFC 8439 §2.5.2
    key = bytes.fromhex(
        "85d6be7857556d337f4452fe42d506a8"
        "0103808afb0db2fd4abff6af4149f51b"
    )
    msg = b"Cryptographic Forum Research Group"
    assert _poly1305(key, msg).hex() == "a8061dc1305136c6c22b8baf0c0127a9"


def test_aead_rfc_vector():
    # RFC 8439 §2.8.2
    key = bytes.fromhex(
        "808182838485868788898a8b8c8d8e8f"
        "909192939495969798999a9b9c9d9e9f"
    )
    nonce = bytes.fromhex("070000004041424344454647")
    aad = bytes.fromhex("50515253c0c1c2c3c4c5c6c7")
    plaintext = (
        b"Ladies and Gentlemen of the class of '99: If I could offer you "
        b"only one tip for the future, sunscreen would be it."
    )
    sealed = seal(key, nonce, plaintext, aad)
    assert sealed[-16:].hex() == "1ae10b594f09e26a7e902ecbd0600691"
    assert open_(key, nonce, sealed, aad) == plaintext


def test_aead_rejects_tampering():
    key, nonce = os.urandom(32), os.urandom(12)
    sealed = bytearray(seal(key, nonce, b"secret message", b"aad"))
    sealed[0] ^= 1
    assert open_(key, nonce, bytes(sealed), b"aad") is None
    # wrong aad
    good = seal(key, nonce, b"secret message", b"aad")
    assert open_(key, nonce, good, b"wrong") is None
    assert open_(key, nonce, good, b"aad") == b"secret message"
