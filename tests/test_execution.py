"""Execution layer: engine mock semantics, HTTP+JWT wire, chain leg.

Reference behaviors: packages/beacon-node/src/execution/engine/
{mock.ts,http.ts,interface.ts} and the payload leg of
chain/blocks/verifyBlock.ts:87-104.
"""

import pytest

from lodestar_tpu import types as T
from lodestar_tpu.execution import (
    EngineApiServer,
    ExecutePayloadStatus,
    ExecutionEngineHttp,
    ExecutionEngineMock,
    PayloadAttributes,
)
from lodestar_tpu.execution.engine_http import (
    EngineHttpError,
    jwt_encode_hs256,
    jwt_verify_hs256,
)
from lodestar_tpu.execution.engine_mock import ZERO_HASH, compute_block_hash

pytestmark = pytest.mark.smoke

ATTRS = PayloadAttributes(
    timestamp=1234, prev_randao=b"\x07" * 32,
    suggested_fee_recipient=b"\x0a" * 20,
)


def test_mock_build_then_import_payload():
    el = ExecutionEngineMock()
    r = el.notify_forkchoice_update(ZERO_HASH, ZERO_HASH, ZERO_HASH, ATTRS)
    assert r.status == ExecutePayloadStatus.VALID and r.payload_id
    payload = el.get_payload(r.payload_id)
    # payload ids are one-shot
    with pytest.raises(ValueError):
        el.get_payload(r.payload_id)
    # the built payload imports as VALID and extends the tree
    st = el.notify_new_payload(payload)
    assert st.status == ExecutePayloadStatus.VALID
    assert bytes(payload["block_hash"]) in el.valid_blocks
    # fcU to the new head
    r2 = el.notify_forkchoice_update(
        payload["block_hash"], payload["block_hash"], ZERO_HASH
    )
    assert r2.status == ExecutePayloadStatus.VALID
    assert el.head == bytes(payload["block_hash"])


def test_mock_rejects_corrupt_hash_and_syncs_unknown_parent():
    el = ExecutionEngineMock()
    r = el.notify_forkchoice_update(ZERO_HASH, ZERO_HASH, ZERO_HASH, ATTRS)
    payload = el.get_payload(r.payload_id)
    bad = dict(payload, block_hash=b"\xff" * 32)
    assert (
        el.notify_new_payload(bad).status
        == ExecutePayloadStatus.INVALID_BLOCK_HASH
    )
    orphan = dict(payload, parent_hash=b"\xee" * 32)
    orphan["block_hash"] = compute_block_hash(orphan)
    assert el.notify_new_payload(orphan).status == ExecutePayloadStatus.SYNCING
    # fcU to an unknown head also reports SYNCING
    assert (
        el.notify_forkchoice_update(b"\xdd" * 32, ZERO_HASH, ZERO_HASH).status
        == ExecutePayloadStatus.SYNCING
    )


def test_payload_ssz_roundtrip_from_mock():
    el = ExecutionEngineMock()
    r = el.notify_forkchoice_update(ZERO_HASH, ZERO_HASH, ZERO_HASH, ATTRS)
    payload = el.get_payload(r.payload_id)
    data = T.ExecutionPayload.serialize(payload)
    back = T.ExecutionPayload.deserialize(data)
    assert T.ExecutionPayload.serialize(back) == data
    assert bytes(back["block_hash"]) == bytes(payload["block_hash"])


def test_jwt_roundtrip_and_rejections():
    import time

    secret = b"\x42" * 32
    tok = jwt_encode_hs256(secret, {"iat": int(time.time())})
    assert "iat" in jwt_verify_hs256(secret, tok)
    with pytest.raises(ValueError):
        jwt_verify_hs256(b"\x43" * 32, tok)  # wrong secret
    stale = jwt_encode_hs256(secret, {"iat": int(time.time()) - 3600})
    with pytest.raises(ValueError):
        jwt_verify_hs256(secret, stale)


@pytest.fixture
def wired():
    secret = b"\x11" * 32
    el = ExecutionEngineMock()
    server = EngineApiServer(el, secret)
    server.listen()
    client = ExecutionEngineHttp(
        f"http://127.0.0.1:{server.port}", secret, timeout=10
    )
    yield el, server, client
    server.close()


def test_http_client_full_flow(wired):
    el, server, client = wired
    r = client.notify_forkchoice_update(ZERO_HASH, ZERO_HASH, ZERO_HASH, ATTRS)
    assert r.status == ExecutePayloadStatus.VALID and r.payload_id
    payload = client.get_payload(r.payload_id)
    st = client.notify_new_payload(payload)
    assert st.status == ExecutePayloadStatus.VALID
    assert st.latest_valid_hash == "0x" + bytes(payload["block_hash"]).hex()
    # errors surface as EngineHttpError (one-shot payload id)
    with pytest.raises(EngineHttpError):
        client.get_payload(r.payload_id)


def test_http_rejects_bad_jwt(wired):
    el, server, client = wired
    bad = ExecutionEngineHttp(
        f"http://127.0.0.1:{server.port}", b"\x99" * 32, timeout=10
    )
    import urllib.error

    with pytest.raises(urllib.error.HTTPError):
        bad.notify_forkchoice_update(ZERO_HASH, ZERO_HASH, ZERO_HASH)


def test_chain_execution_leg_optimistic_and_invalid():
    """The chain-side payload leg, driven directly (altair bodies carry
    no payload; this exercises the bellatrix-ready plumbing)."""
    from lodestar_tpu.chain.chain import BeaconChain
    from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
    from lodestar_tpu.crypto import bls as B
    from lodestar_tpu.crypto import curves as C
    from lodestar_tpu.params import ForkName
    from lodestar_tpu.state_transition import create_genesis_state

    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG, fork_epochs={ForkName.altair: 0}
    )
    sks = [B.keygen(b"el-%d" % i) for i in range(4)]
    pks = [C.g1_compress(B.sk_to_pk(sk)) for sk in sks]
    el = ExecutionEngineMock()
    chain = BeaconChain(
        cfg, create_genesis_state(cfg, pks, genesis_time=2), execution=el
    )

    def shell(slot, payload):
        return {
            "slot": slot,
            "proposer_index": 0,
            "parent_root": b"\x00" * 32,
            "state_root": b"\x00" * 32,
            "body": {
                "randao_reveal": b"\x00" * 96,
                "eth1_data": {
                    "deposit_root": b"\x00" * 32,
                    "deposit_count": 0,
                    "block_hash": b"\x00" * 32,
                },
                "graffiti": b"\x00" * 32,
                "proposer_slashings": [],
                "attester_slashings": [],
                "attestations": [],
                "deposits": [],
                "voluntary_exits": [],
                "sync_aggregate": {
                    "sync_committee_bits": [False] * 512,
                    "sync_committee_signature": b"\x00" * 96,
                },
                "execution_payload": payload,
            },
        }

    r = el.notify_forkchoice_update(ZERO_HASH, ZERO_HASH, ZERO_HASH, ATTRS)
    payload = el.get_payload(r.payload_id)
    # VALID payload -> (hash, optimistic=False); the CALLER records the
    # bookkeeping only after a full successful import
    assert chain._verify_execution_payload(shell(1, payload)) == (
        bytes(payload["block_hash"]),
        False,
    )
    assert not chain._execution_block_hash  # no residue pre-import

    orphan = dict(payload, parent_hash=b"\xee" * 32)
    orphan["block_hash"] = compute_block_hash(orphan)
    # SYNCING -> optimistic=True
    assert chain._verify_execution_payload(shell(2, orphan)) == (
        bytes(orphan["block_hash"]),
        True,
    )

    bad = dict(payload, block_hash=b"\xff" * 32)
    with pytest.raises(ValueError):
        chain._verify_execution_payload(shell(3, bad))

    # EL outage is retryable, never invalidity
    from lodestar_tpu.execution import ExecutionEngineUnavailable

    el.fail_with = ExecutePayloadStatus.UNAVAILABLE
    with pytest.raises(ExecutionEngineUnavailable):
        chain._verify_execution_payload(shell(4, payload))
    el.fail_with = None
    # payload-less (altair) blocks are a no-op
    no_payload = shell(5, payload)
    del no_payload["body"]["execution_payload"]
    assert chain._verify_execution_payload(no_payload) is None


def test_bellatrix_block_types_roundtrip():
    """Bellatrix SSZ block family (body carries the execution payload);
    the STF consuming it is the next fork milestone — the engine layer,
    payload types, and verification leg are ready (see COVERAGE.md)."""
    el = ExecutionEngineMock()
    r = el.notify_forkchoice_update(ZERO_HASH, ZERO_HASH, ZERO_HASH, ATTRS)
    payload = el.get_payload(r.payload_id)
    body = {
        "randao_reveal": b"\x00" * 96,
        "eth1_data": {
            "deposit_root": b"\x00" * 32,
            "deposit_count": 0,
            "block_hash": b"\x00" * 32,
        },
        "graffiti": b"\x00" * 32,
        "proposer_slashings": [],
        "attester_slashings": [],
        "attestations": [],
        "deposits": [],
        "voluntary_exits": [],
        "sync_aggregate": {
            "sync_committee_bits": [False] * 512,
            "sync_committee_signature": b"\x00" * 96,
        },
        "execution_payload": payload,
    }
    block = {
        "slot": 1,
        "proposer_index": 0,
        "parent_root": b"\x01" * 32,
        "state_root": b"\x02" * 32,
        "body": body,
    }
    signed = {"message": block, "signature": b"\x00" * 96}
    data = T.SignedBeaconBlockBellatrix.serialize(signed)
    back = T.SignedBeaconBlockBellatrix.deserialize(data)
    assert T.SignedBeaconBlockBellatrix.serialize(back) == data
    assert bytes(
        back["message"]["body"]["execution_payload"]["block_hash"]
    ) == bytes(payload["block_hash"])
