"""AOT export cache: trace-once reload, keying, staleness, fallback.

Reference rationale: the per-process trace cost of the unrolled limb
pipeline (~10 min on the 1-core driver host, dev/NOTES.md) is removed
by persisting the traced computation with jax.export and reloading it
without re-tracing (kernels/export_cache.py).
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental import pallas as pl

from lodestar_tpu.kernels import export_cache as EC

pytestmark = pytest.mark.smoke


def _toy_pipeline():
    """A small pallas-backed function standing in for the verify
    pipeline (full-pipeline artifacts are TPU-platform; XLA:CPU cannot
    compile the monolithic graph — dev/NOTES.md)."""

    def k(x_ref, o_ref):
        acc = x_ref[...]
        for _ in range(8):
            acc = acc * 3 + 1
        o_ref[...] = acc

    call = pl.pallas_call(
        k,
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.int32),
        interpret=True,
    )

    def fn(x, y):
        return call(x) + y

    return fn


def test_export_reload_matches_direct(tmp_path):
    fn = _toy_pipeline()
    x = jnp.arange(8 * 128, dtype=jnp.int32).reshape(8, 128)
    y = jnp.ones((8, 128), jnp.int32)
    specs = [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in (x, y)]
    call = EC.load_or_export(
        "toy", fn, specs, platform="cpu", cache_dir=str(tmp_path)
    )
    got = call(x, y)
    want = fn(x, y)
    assert np.array_equal(np.asarray(got), np.asarray(want))
    # artifact landed on disk
    files = list(tmp_path.glob("toy-cpu-*.jaxexport"))
    assert len(files) == 1


def test_reload_skips_tracing(tmp_path):
    """The second load must come from disk: the builder is never traced
    again (we prove it with a trace-counting wrapper)."""
    traces = []

    def make_fn():
        def fn(x):
            traces.append(1)  # runs at TRACE time only
            return x * 2 + 1

        return fn

    x = jnp.ones((4,), jnp.int32)
    specs = [jax.ShapeDtypeStruct(x.shape, x.dtype)]
    EC._LOADED.clear()
    c1 = EC.load_or_export(
        "trace-count", make_fn(), specs, platform="cpu", cache_dir=str(tmp_path)
    )
    n_after_first = len(traces)
    assert n_after_first >= 1
    EC._LOADED.clear()  # force the disk path
    c2 = EC.load_or_export(
        "trace-count", make_fn(), specs, platform="cpu", cache_dir=str(tmp_path)
    )
    assert len(traces) == n_after_first  # no new trace
    assert np.array_equal(np.asarray(c2(x)), np.asarray(c1(x)))


def test_key_varies_with_shape_and_platform():
    s1 = [jax.ShapeDtypeStruct((8, 128), jnp.int32)]
    s2 = [jax.ShapeDtypeStruct((8, 256), jnp.int32)]
    assert EC.artifact_key("a", s1, "cpu") != EC.artifact_key("a", s2, "cpu")
    assert EC.artifact_key("a", s1, "cpu") != EC.artifact_key("a", s1, "tpu")
    assert EC.artifact_key("a", s1, "cpu") != EC.artifact_key("b", s1, "cpu")


def test_corrupt_artifact_falls_back(tmp_path):
    fn = _toy_pipeline()
    x = jnp.ones((8, 128), jnp.int32)
    specs = [
        jax.ShapeDtypeStruct(x.shape, x.dtype),
        jax.ShapeDtypeStruct(x.shape, x.dtype),
    ]
    key = EC.artifact_key("corrupt", specs, "cpu")
    (tmp_path / f"{key}.jaxexport").write_bytes(b"garbage")
    EC._LOADED.clear()
    assert EC.load("corrupt", specs, "cpu", cache_dir=str(tmp_path)) is None
    # load_or_export recovers by re-exporting
    call = EC.load_or_export(
        "corrupt", fn, specs, platform="cpu", cache_dir=str(tmp_path)
    )
    assert call(x, x) is not None


def test_cross_platform_tpu_export_from_cpu_host(tmp_path):
    """A REAL (non-interpret) Mosaic kernel exports for the tpu platform
    from this CPU host — the pre-trace workflow the bench relies on."""
    from lodestar_tpu.kernels import launch

    def k(x_ref, o_ref):
        o_ref[...] = x_ref[...] + 7

    def fn(x):
        return launch.cached(
            ("export-test-k", x.shape),
            lambda: pl.pallas_call(
                k,
                out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                interpret=launch.interpret(),
            ),
        )(x)

    x = jnp.zeros((8, 128), jnp.int32)
    specs = [jax.ShapeDtypeStruct(x.shape, x.dtype)]
    call = EC.load_or_export(
        "mosaic-x", fn, specs, platform="tpu", cache_dir=str(tmp_path)
    )
    assert call is not None
    files = list(tmp_path.glob("mosaic-x-tpu-*.jaxexport"))
    assert len(files) == 1 and files[0].stat().st_size > 0
    # the artifact declares its platform; running it here would need a
    # TPU — reload only
    EC._LOADED.clear()
    assert EC.load("mosaic-x", specs, "tpu", cache_dir=str(tmp_path)) is not None


def test_verifier_export_dispatch_fallback(monkeypatch):
    """_device_call never lets the export layer break verification."""
    from lodestar_tpu.bls.pubkey_table import PubkeyTable
    from lodestar_tpu.bls.verifier import TpuBlsVerifier
    from lodestar_tpu.crypto import bls as B

    pks = [B.sk_to_pk(B.keygen(b"ec-%d" % i)) for i in range(4)]
    table = PubkeyTable(capacity=8)
    table.register_points_unchecked(pks, tile_to=8)
    v = TpuBlsVerifier(table)
    v._use_export = True

    def boom(*a, **k):
        raise RuntimeError("export layer down")

    monkeypatch.setattr(EC, "load_or_export", boom)
    # falls back to the direct path and still verifies
    out = v._device_call("x", lambda a, b: a + b, (jnp.ones(2), jnp.ones(2)))
    assert np.allclose(np.asarray(out), 2.0)


def test_staged_artifacts_match_verifier_contract():
    """When the staged TPU artifacts exist (driver host), their input
    signature must match what the verifier dispatches at bench shapes —
    a drift between verifier args and artifacts would silently fall
    back to the ~10-minute trace at bench time.  Skips on hosts
    without the artifact cache (fresh checkouts)."""
    import pathlib

    from jax import export as jexport

    hits = list(
        pathlib.Path(EC.DEFAULT_DIR).glob("batch_wire_grouped-tpu-*.jaxexport")
    )
    if not hits:
        pytest.skip("no staged artifacts on this host")
    from lodestar_tpu.kernels import verify as KV

    for path in hits:  # one artifact per (job width x table capacity)
        exp = jexport.deserialize(path.read_bytes())
        avals = list(exp.in_avals)
        # 16 positional args; lane width divides the tile; grouping
        # rows are BT-wide (verify_batch_device_wire_grouped); the
        # TABLE planes carry the capacity (bench 512, replay 500k/1M)
        assert len(avals) == 16, path.name
        n = avals[-1].shape[0]
        if avals[14].shape[0] != KV.RAND_WORDS:
            # a pre-128-bit-randomizer artifact left on disk: its
            # fingerprint key is stale so it can never LOAD — only the
            # current-generation contract is asserted
            continue
        assert n % KV.BT == 0
        assert avals[0].shape[0] == KV.NL    # table planes [NL, cap]
        assert avals[1].shape == avals[0].shape
        assert avals[4].shape == (KV.NL, n)  # msg planes ride the job
        assert avals[11].shape == (n,)       # group
        assert avals[12].shape == (KV.BT,)   # head_lanes
        assert avals[13].shape == (KV.BT,)   # glive
        assert avals[14].shape == (KV.RAND_WORDS, n)  # rwords
        assert all(str(a.dtype) == "int32" for a in avals)


# ---------------------------------------------------------------------------
# standalone-entry source fingerprinting (tpulint fingerprint-completeness
# runtime backstop)
# ---------------------------------------------------------------------------


def _entry_cleanup(*names):
    for n in names:
        EC._ENTRY_BUILDERS.pop(n, None)
        EC._ENTRY_SOURCES.pop(n, None)


def _toy_specs():
    def fn(x):
        return x + 1

    return fn, [jax.ShapeDtypeStruct((4,), jnp.int32)]


def test_uncovered_entry_warns_at_registration(caplog):
    """An entry tracing a function outside kernels/ with no registered
    source must warn when its builder runs — the module's edits would
    otherwise never invalidate the cached artifact."""
    import logging

    EC.register_entry("fx-uncovered", _toy_specs)
    try:
        with caplog.at_level(logging.WARNING, logger="lodestar_tpu"):
            fn, specs = EC.registered_entries()["fx-uncovered"]()
        assert any(
            "fx-uncovered" in r.message and "_ENTRY_SOURCES" in r.message
            for r in caplog.records
        ), [r.message for r in caplog.records]
        assert fn(jnp.zeros((4,), jnp.int32)) is not None
    finally:
        _entry_cleanup("fx-uncovered")


def test_covered_entry_does_not_warn(caplog):
    import logging

    EC.register_entry(
        "fx-covered", _toy_specs, sources=(_toy_specs.__module__,)
    )
    try:
        with caplog.at_level(logging.WARNING, logger="lodestar_tpu"):
            EC.registered_entries()["fx-covered"]()
        assert not any(
            "fx-covered" in r.message for r in caplog.records
        ), [r.message for r in caplog.records]
    finally:
        _entry_cleanup("fx-covered")


def test_builtin_slasher_entry_declares_its_import_graph(caplog):
    """The shipped slasher entry must cover device.py AND batch.py (the
    module device.py imports) so an edit to either invalidates the span
    artifact — and must therefore pass the runtime backstop silently."""
    import logging

    declared = EC._ENTRY_SOURCES["slasher_span_update"]
    assert "lodestar_tpu.slasher.device" in declared
    assert "lodestar_tpu.slasher.batch" in declared
    for src in declared:
        p = EC._source_path(src)
        assert p is not None and p.exists(), src
    with caplog.at_level(logging.WARNING, logger="lodestar_tpu"):
        EC.registered_entries()["slasher_span_update"]()
    assert not any(
        "slasher_span_update" in r.message for r in caplog.records
    )


def test_builtin_rlc_entries_cover_every_dispatch_name():
    """Every device entry name bls/verifier._device_call dispatches must
    be a REGISTERED entry (pre-traceable offline) declaring the crypto
    constant modules its trace bakes in — so a curve-constant edit
    invalidates the artifacts and export_registered() covers the RLC
    pipeline without replaying the bench world."""
    from lodestar_tpu.kernels import verify as KV

    names = (
        "batch_wire", "batch_wire_grouped", "each_wire",
        "batch_decoded", "each_decoded",
    )
    registered = EC.registered_entries()
    for name in names:
        assert name in registered, name
        declared = EC._ENTRY_SOURCES[name]
        assert "lodestar_tpu.crypto.curves" in declared, name
        assert "lodestar_tpu.crypto.fields" in declared, name
        for src in declared:
            p = EC._source_path(src)
            assert p is not None and p.exists(), (name, src)
        fn, specs = registered[name]()
        # the traced fn is the verifier's dispatch target and the specs
        # carry the 128-bit randomizer rows on batch entries
        assert fn.__module__ == "lodestar_tpu.kernels.verify", name
        if name.startswith("batch"):
            assert tuple(specs[-2].shape)[0] == KV.RAND_WORDS, name
        assert all(str(s.dtype) == "int32" for s in specs), name


def test_artifact_key_tracks_every_declared_source(tmp_path):
    """Editing ANY registered source must change the entry's artifact
    key (multi-source entries: device.py edit AND batch.py edit both
    invalidate)."""
    a = tmp_path / "dep_a.py"
    b = tmp_path / "dep_b.py"
    a.write_text("A = 1\n")
    b.write_text("B = 1\n")
    specs = [jax.ShapeDtypeStruct((4,), jnp.int32)]
    EC.register_entry("fx-multi", _toy_specs, sources=(str(a), str(b)))
    try:
        k0 = EC.artifact_key("fx-multi", specs, "cpu")
        a.write_text("A = 2\n")
        k1 = EC.artifact_key("fx-multi", specs, "cpu")
        assert k1 != k0
        b.write_text("B = 2\n")
        k2 = EC.artifact_key("fx-multi", specs, "cpu")
        assert k2 != k1
    finally:
        _entry_cleanup("fx-multi")


def test_module_name_sources_resolve_without_import():
    p = EC._source_path("lodestar_tpu.slasher.batch")
    assert p is not None and p.name == "batch.py" and p.exists()
    p = EC._source_path("lodestar_tpu.slasher")
    assert p is not None and p.name == "__init__.py"
    assert EC._source_path("lodestar_tpu.no.such.module") is None


def test_reregistration_without_sources_drops_stale_declaration():
    EC.register_entry("fx-restale", _toy_specs, sources=("lodestar_tpu.slasher.batch",))
    try:
        assert "fx-restale" in EC._ENTRY_SOURCES
        EC.register_entry("fx-restale", _toy_specs)  # no sources now
        assert "fx-restale" not in EC._ENTRY_SOURCES
    finally:
        _entry_cleanup("fx-restale")


def test_export_stage_error_carries_stage_and_classifies():
    """ISSUE 14: a backend death during export trace re-raises as
    ExportStageError naming the stage, and the breaker's classifier
    reads it as a backend-init outcome (the r03-r05 failure shape)."""
    import jax
    import pytest

    from lodestar_tpu.bls.supervisor import (
        OUTCOME_BACKEND_INIT,
        classify_failure,
    )
    from lodestar_tpu.kernels.export_cache import (
        ExportStageError,
        load_or_export,
    )

    def dead_backend(_x):
        raise RuntimeError("TPU backend UNAVAILABLE: tunnel down")

    spec = jax.ShapeDtypeStruct((4,), "int32")
    with pytest.raises(ExportStageError) as ei:
        load_or_export("chaos_dead_entry", dead_backend, [spec])
    assert ei.value.stage == "trace"
    assert ei.value.entry == "chaos_dead_entry"
    assert classify_failure(ei.value) == OUTCOME_BACKEND_INIT
