"""Pre-verify attestation aggregation (ISSUE 13, bls/aggregator.py).

Stub-verifier (host-only) tests of the tentpole contract: signing-root
bucketing, exact-duplicate dedupe + the resolved-verdict seen-map,
disjoint-layer packing (unique gather indices), contributor-wise
bisection with publisher attribution, the escape hatch, the randomized
verdict-equivalence property (aggregated-then-bisected == per-message),
and the acceptance oracle: mean aggregation factor >= 3 under a
duplicate-heavy flood at an unchanged critical-lane p99.  The slow tier
(test_kernels_verify-style real crypto) exercises the device G2-sum.
"""

import hashlib
import threading
import time

import pytest

from lodestar_tpu.bls.pipeline import BlsVerificationPipeline
from lodestar_tpu.bls.pubkey_table import plan_disjoint_gathers
from lodestar_tpu.bls.signature_set import WireSignatureSet
from lodestar_tpu.bls.verifier import VerifyOptions
from lodestar_tpu.utils.metrics import BlsPoolMetrics

pytestmark = pytest.mark.smoke


def _multiset(xs):
    return tuple(sorted(xs))


class StubAggVerifier:
    """IBlsVerifier stub that models BLS aggregation semantics without
    curve math: signatures are opaque 96-byte tokens bound to a
    (root, index-multiset, valid) oracle entry; aggregating tokens
    produces a token whose validity is the AND of its members (the
    almost-sure behaviour of real point addition for honestly-formed
    invalid signatures).  begin/finish expose per-set verdicts so the
    service's positional slicing works exactly as with the device."""

    max_job_sets = 512

    class _Handle:
        def __init__(self, sets, verdicts):
            self.sets = sets
            self.ok_big = True
            self.batch_retries = 0
            self.batch_sigs_success = sum(verdicts)
            self.verdicts = verdicts

    def __init__(self):
        self.metrics = BlsPoolMetrics()
        self.oracle = {}
        self.begun = []
        self.sum_calls = 0
        self._lock = threading.Lock()

    def sig(self, root, indices, ok=True):
        payload = repr((root, _multiset(indices), ok)).encode()
        b = bytearray(96)
        b[0] = 0x80  # compression bit; x coords stay < p
        b[1:33] = hashlib.sha256(payload).digest()
        s = bytes(b)
        self.oracle[s] = (root, _multiset(indices), ok)
        return s

    def aggregate_wire_signatures(self, groups):
        out = []
        with self._lock:
            self.sum_calls += 1
        for g in groups:
            infos = [self.oracle.get(s) for s in g]
            if any(i is None for i in infos):
                out.append(None)
                continue
            root = infos[0][0]
            idx = tuple(i for info in infos for i in info[1])
            ok = all(info[2] for info in infos) and all(
                info[0] == root for info in infos
            )
            out.append(self.sig(root, idx, ok))
        return out

    def _verdict(self, s):
        o = self.oracle.get(s.signature)
        return bool(
            o is not None
            and o[0] == s.signing_root
            and o[1] == _multiset(s.indices)
            and o[2]
        )

    def verify_signature_sets(self, sets, opts=None):
        return all(self._verdict(s) for s in sets)

    def begin_job(self, sets, batchable):
        v = [self._verdict(s) for s in sets]
        with self._lock:
            self.begun.append(list(sets))
        return self._Handle(list(sets), v)

    def finish_job(self, handle):
        return all(handle.verdicts)

    def close(self):
        pass


def wire(v, root, indices, ok=True, sig=None):
    indices = tuple(indices)
    s = sig if sig is not None else v.sig(root, indices, ok)
    if len(indices) == 1:
        return WireSignatureSet.single(indices[0], root, s)
    return WireSignatureSet.aggregate(indices, root, s)


def submit(pipe, ws, priority=False, peer_id=None):
    return pipe.verify_signature_sets_async(
        [ws],
        VerifyOptions(
            batchable=True,
            priority=priority,
            peer_id=peer_id,
            topic="beacon_attestation",
        ),
    )


ROOT = b"r" * 32
ROOT2 = b"q" * 32


def make_pipe(v=None, wait_ms=60, **kw):
    v = v or StubAggVerifier()
    pipe = BlsVerificationPipeline(v, standard_wait_ms=wait_ms, **kw)
    return v, pipe


# -- bucketing + layering ----------------------------------------------------


def test_same_root_messages_verify_as_one_aggregated_set():
    v, pipe = make_pipe()
    assert pipe._agg is not None
    futs = [submit(pipe, wire(v, ROOT, (i,))) for i in range(6)]
    assert all(f.result(timeout=10) for f in futs)
    pipe.close()
    # ONE begun device job carrying ONE 6-index aggregate set
    agg_sets = [s for g in v.begun for s in g]
    assert len(agg_sets) == 1
    assert _multiset(agg_sets[0].indices) == (0, 1, 2, 3, 4, 5)
    assert agg_sets[0].signing_root == ROOT
    assert pipe.mean_aggregation_factor() == pytest.approx(6.0)
    assert v.metrics.aggregation_factor.count == 1


def test_distinct_roots_bucket_separately_but_share_one_device_job():
    v, pipe = make_pipe()
    futs = [submit(pipe, wire(v, ROOT, (i,))) for i in range(3)]
    futs += [submit(pipe, wire(v, ROOT2, (10 + i,))) for i in range(3)]
    assert all(f.result(timeout=10) for f in futs)
    pipe.close()
    assert len(v.begun) == 1  # one flush group -> one merged device job
    roots = {s.signing_root for s in v.begun[0]}
    assert roots == {ROOT, ROOT2}
    assert len(v.begun[0]) == 2  # one aggregate per bucket


def test_overlapping_bits_split_into_disjoint_layers_with_unique_indices():
    """ISSUE 13 satellite regression (heavy-overlap bits): every
    aggregated set's gather indices are UNIQUE — overlapping
    contributors go to separate layers instead of fetching (and
    point-adding) the same pubkey row with the wrong multiplicity."""
    v, pipe = make_pipe()
    # five 3-bit aggregates, all containing validator 7
    futs = [
        submit(pipe, wire(v, ROOT, (7, 100 + 2 * i, 101 + 2 * i)))
        for i in range(5)
    ]
    assert all(f.result(timeout=10) for f in futs)
    pipe.close()
    sets = [s for g in v.begun for s in g]
    for s in sets:
        assert len(set(s.indices)) == len(s.indices), s.indices
    # validator 7 appears once per layer, never twice in one set
    assert sum(s.indices.count(7) for s in sets) == 5
    assert len(sets) == 5  # pairwise overlap => one layer each


def test_plan_disjoint_gathers_unit():
    # disjoint contributors pack into one layer
    assert plan_disjoint_gathers([(1, 2), (3, 4), (5,)], 64) == [[0, 1, 2]]
    # overlap forces a second layer
    assert plan_disjoint_gathers([(1, 2), (2, 3)], 64) == [[0], [1]]
    # the second layer still packs disjoint latecomers
    assert plan_disjoint_gathers([(1,), (1, 2), (3,)], 64) == [[0, 2], [1]]
    # max_indices bounds a layer
    assert plan_disjoint_gathers([(1, 2), (3, 4)], 3) == [[0], [1]]
    # a contributor with self-repeated indices is isolated (poisoned
    # layer: nothing may join it)
    plan = plan_disjoint_gathers([(1, 1), (2,), (3,)], 64)
    assert [0] in plan and any(set(l) == {1, 2} for l in plan)


# -- dedupe + seen-map -------------------------------------------------------


def test_exact_duplicates_share_one_contribution():
    v, pipe = make_pipe()
    s0 = v.sig(ROOT, (0,))
    futs = [submit(pipe, wire(v, ROOT, (0,), sig=s0)) for _ in range(5)]
    futs.append(submit(pipe, wire(v, ROOT, (1,))))
    assert all(f.result(timeout=10) for f in futs)
    pipe.close()
    stats = pipe.agg_stats()
    assert stats["dedup"] == 4  # four followers of the first copy
    assert stats["contributions"] == 6
    # the device saw ONE 2-index aggregate, not 6 sets
    sets = [s for g in v.begun for s in g]
    assert len(sets) == 1 and _multiset(sets[0].indices) == (0, 1)
    assert pipe.mean_aggregation_factor() == pytest.approx(6.0)


def test_seen_map_serves_resolved_duplicates_with_zero_work():
    v, pipe = make_pipe()
    s0 = v.sig(ROOT, (0,))
    ws = wire(v, ROOT, (0,), sig=s0)
    assert submit(pipe, ws).result(timeout=10) is True
    begun_before = len(v.begun)
    # an identical replay resolves instantly from the seen-map
    fut = submit(pipe, wire(v, ROOT, (0,), sig=s0))
    assert fut.result(timeout=1) is True
    assert len(v.begun) == begun_before  # no new device work
    assert pipe.agg_stats()["seen_served"] == 1
    # the public lookup the gossip handlers use — exact match only
    assert pipe.preagg_verdict(ws) is True
    forged = wire(v, ROOT, (0,), ok=False)  # same (root, index), new sig
    assert pipe.preagg_verdict(forged) is None
    pipe.close()


def test_negative_verdicts_are_remembered_too():
    v, pipe = make_pipe()
    bad = v.sig(ROOT, (3,), ok=False)
    ws = wire(v, ROOT, (3,), sig=bad)
    assert submit(pipe, ws).result(timeout=10) is False
    fut = submit(pipe, wire(v, ROOT, (3,), sig=bad))
    assert fut.result(timeout=1) is False
    assert pipe.preagg_verdict(ws) is False
    pipe.close()


# -- bisection + attribution -------------------------------------------------


def test_failed_aggregate_bisects_to_the_single_bad_contributor():
    v, pipe = make_pipe()
    futs = [
        submit(pipe, wire(v, ROOT, (i,), ok=(i != 5))) for i in range(8)
    ]
    res = [f.result(timeout=10) for f in futs]
    pipe.close()
    assert res == [True] * 5 + [False] + [True] * 2
    stats = pipe.agg_stats()
    assert stats["bisections"] >= 1
    assert v.metrics.preagg_bisections.value >= 1
    # O(log k): the bad contributor was isolated in ~2*log2(8) extra
    # sets, not a full per-message sweep
    assert stats["sets"] <= 1 + 2 * 3


def test_bisection_attributes_invalid_contributor_to_its_publisher():
    class ScorerSpy:
        def __init__(self):
            self.charged = []

        def on_invalid_message(self, peer, topic):
            self.charged.append((peer, topic))

    scorer = ScorerSpy()
    v, pipe = make_pipe(scorer=scorer)
    futs = [
        submit(pipe, wire(v, ROOT, (i,), ok=(i != 2)), peer_id=f"peer-{i}")
        for i in range(4)
    ]
    res = [f.result(timeout=10) for f in futs]
    pipe.close()
    assert res == [True, True, False, True]
    assert scorer.charged == [("peer-2", "beacon_attestation")]


def test_set_scorer_late_binds():
    class ScorerSpy:
        def __init__(self):
            self.charged = []

        def on_invalid_message(self, peer, topic):
            self.charged.append(peer)

    v, pipe = make_pipe()
    scorer = ScorerSpy()
    pipe.set_scorer(scorer)
    fut = submit(pipe, wire(v, ROOT, (0,), ok=False), peer_id="px")
    assert fut.result(timeout=10) is False
    pipe.close()
    assert scorer.charged == ["px"]


def test_unparsable_and_infinity_signatures_fail_without_poisoning():
    v, pipe = make_pipe()
    good = submit(pipe, wire(v, ROOT, (0,)))
    garbage = WireSignatureSet.single(1, ROOT, b"\x00" * 96)  # no C bit
    inf = WireSignatureSet.single(2, ROOT, bytes([0xC0]) + b"\x00" * 95)
    f_garbage = submit(pipe, garbage)
    f_inf = submit(pipe, inf)
    assert f_garbage.result(timeout=10) is False
    assert f_inf.result(timeout=10) is False
    assert good.result(timeout=10) is True
    pipe.close()
    # neither reached the aggregate (verdicts were immediate)
    sets = [s for g in v.begun for s in g]
    assert all(len(s.indices) == 1 and s.indices[0] == 0 for s in sets)


# -- escape hatch + eligibility ----------------------------------------------


def test_escape_hatch_disables_the_stage(monkeypatch):
    monkeypatch.setenv("LODESTAR_TPU_BLS_PREAGG", "0")
    v = StubAggVerifier()
    pipe = BlsVerificationPipeline(v, standard_wait_ms=40)
    assert pipe._agg is None
    futs = [submit(pipe, wire(v, ROOT, (i,))) for i in range(4)]
    assert all(f.result(timeout=10) for f in futs)
    pipe.close()
    # every message verified as its own set (PR 11 behaviour)
    assert sorted(len(g) for g in v.begun) and sum(
        len(g) for g in v.begun
    ) == 4
    assert pipe.mean_aggregation_factor() is None
    assert pipe.preagg_verdict(wire(v, ROOT, (0,))) is None


def test_verifier_without_sum_seam_disables_the_stage():
    from tests.test_bls_pipeline import HandleStub

    pipe = BlsVerificationPipeline(HandleStub(), standard_wait_ms=40)
    assert pipe._agg is None
    pipe.close()


def test_priority_and_nonwire_jobs_bypass_the_stage():
    from lodestar_tpu.bls.signature_set import SignatureSet

    v, pipe = make_pipe(wait_ms=10_000, critical_wait_ms=30)
    crit = submit(pipe, wire(v, ROOT, (0,)), priority=True)
    assert crit.result(timeout=10) is True  # critical lane, no 10s wait
    decoded = pipe.verify_signature_sets_async(
        [SignatureSet.single(0, ("m", 0), ("s", 0))],
        VerifyOptions(batchable=True),
    )
    time.sleep(0.05)
    assert pipe.agg_stats()["contributions"] == 0
    pipe.close()
    del decoded


# -- the property test (ISSUE 13 satellite) ----------------------------------


@pytest.mark.parametrize("preagg", [True, False])
def test_verdict_equivalence_randomized(preagg, monkeypatch):
    """Aggregated-then-bisected verdicts == per-message individual
    verification across valid/invalid mixes, overlapping aggregation
    bits, duplicates, and odd bucket sizes — with the stage on AND off
    (the acceptance criterion's both-ways run)."""
    import random

    monkeypatch.setenv("LODESTAR_TPU_BLS_PREAGG", "1" if preagg else "0")
    rng = random.Random(1337)
    v = StubAggVerifier()
    pipe = BlsVerificationPipeline(v, standard_wait_ms=30)
    assert (pipe._agg is not None) == preagg
    roots = [bytes([r]) * 32 for r in range(5)]
    messages = []
    for _ in range(90):
        root = rng.choice(roots)
        k = rng.choice([1, 1, 1, 2, 3])
        indices = tuple(rng.sample(range(12), k))
        ok = rng.random() > 0.25
        ws = wire(v, root, indices, ok=ok)
        for _dup in range(rng.choice([1, 1, 2])):
            messages.append((ws, ok))
    futs = [(submit(pipe, ws), ok) for ws, ok in messages]
    got = [f.result(timeout=30) for f, _ok in futs]
    want = [ok for _f, ok in futs]
    pipe.close()
    assert got == want


# -- acceptance oracle -------------------------------------------------------


def _p99(xs):
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(0.99 * (len(xs) - 1)))] if xs else None


def test_duplicate_flood_meets_aggregation_factor_acceptance():
    """ISSUE 13 acceptance (fast stub): a duplicate-heavy 8-wave flood —
    each distinct message published twice, 8 attesters per root — must
    deliver effective atts >= 3x verified sets (mean aggregation factor
    >= 3) while block-critical sets keep the PR 11 critical-lane p99
    (30 ms window + scheduler slack)."""
    v, pipe = make_pipe(wait_ms=120, critical_wait_ms=30)
    crit_lat, futs = [], []
    lock = threading.Lock()

    def track_crit(ws):
        t0 = time.perf_counter()
        f = submit(pipe, ws, priority=True)
        f.add_done_callback(
            lambda _f, t0=t0: crit_lat.append(time.perf_counter() - t0)
        )
        futs.append(f)

    roots = [bytes([r]) * 32 for r in range(8)]
    j = 0
    for wave in range(8):
        for r, root in enumerate(roots):
            for a in range(8):  # 8 attesters per root per wave
                ws = wire(v, root, (wave * 64 + r * 8 + a,))
                for _dup in range(2):  # duplicate-heavy: every message x2
                    futs.append(submit(pipe, ws))
                j += 2
        track_crit(wire(v, bytes([100 + wave]) * 32, (999,)))
        time.sleep(0.02)
    assert all(f.result(timeout=30) for f in futs)
    factor = pipe.mean_aggregation_factor()
    stats = pipe.agg_stats()
    pipe.close()
    assert factor is not None and factor >= 3.0, (factor, stats)
    assert stats["dedup"] + stats["seen_served"] >= j // 4
    p99 = _p99(crit_lat)
    assert p99 is not None and p99 <= 0.03 + 0.20, p99
    del lock


# -- observability -----------------------------------------------------------


def test_preagg_flush_emits_span_and_factor_histogram():
    from lodestar_tpu import observability as OB

    OB.configure(enabled=True)
    OB.get_tracer().clear()
    try:
        v, pipe = make_pipe()
        futs = [submit(pipe, wire(v, ROOT, (i,))) for i in range(4)]
        assert all(f.result(timeout=10) for f in futs)
        pipe.close()
        spans = [
            r
            for r in OB.get_tracer().snapshot()
            if r.name == "bls.preagg.flush"
        ]
        assert spans, "no bls.preagg.flush span recorded"
        attrs = spans[0].attrs
        assert attrs["buckets"] == 1 and attrs["contributions"] == 4
        assert attrs["sets"] == 1 and attrs["factor"] == pytest.approx(4.0)
        assert attrs["reason"] == "deadline"
        assert 0.0 <= attrs["oldest_wait_s"] < 5.0
        assert v.metrics.aggregation_factor.count == 1
        assert v.metrics.aggregation_factor.sum == pytest.approx(4.0)
        assert v.metrics.preagg_contributions.value == 4
        assert v.metrics.preagg_sets.value == 1
    finally:
        OB.configure(enabled=False)
        OB.get_tracer().clear()


def test_close_rejects_buffered_contributions():
    v, pipe = make_pipe(wait_ms=60_000)
    fut = submit(pipe, wire(v, ROOT, (0,)))
    pipe.close()
    with pytest.raises(RuntimeError):
        fut.result(timeout=5)
    assert pipe.pending_sets() == 0


def test_pending_sets_counts_buffered_contributions():
    v, pipe = make_pipe(wait_ms=60_000, high_water_sets=8)
    futs = [submit(pipe, wire(v, ROOT, (i,))) for i in range(10)]
    assert pipe.pending_sets() == 10
    assert not pipe.can_accept_work()  # backpressure sees the stage
    pipe.close()
    del futs


# -- suppressed-double-vote fast path (ISSUE 13 satellite) -------------------


def _recovery_world(monkeypatch, pipe, v, ws):
    """A GossipHandlers wired to stubs, with the signature-set builder
    pinned to `ws` (the wire set whose verdict may sit in the
    aggregation seen-map)."""
    from lodestar_tpu.network.gossip_handlers import GossipHandlers
    from lodestar_tpu.state_transition import signature_sets as SS

    class RawSpy:
        def __init__(self):
            self.calls = 0

        def verify_signature_sets(self, sets, opts=None):
            self.calls += 1
            return True

    class SlasherStub:
        def __init__(self):
            self.probes = []
            self.ingested = []

        def should_check_equivocation(self, i, target, root):
            return True

        def record_equivocation_probe(self, idxs, target, root, ok):
            self.probes.append((tuple(int(i) for i in idxs), bool(ok)))

        def ingest_attestation(self, indexed):
            self.ingested.append(indexed)

    class ViewStub:
        @staticmethod
        def get_indexed_attestation(att):
            return {
                "attesting_indices": list(ws.indices),
                "data": att["data"],
                "signature": ws.signature,
            }

    raw = RawSpy()
    handlers = GossipHandlers(chain=None, verifier=raw, bls_service=pipe)
    handlers.slasher = SlasherStub()
    monkeypatch.setattr(handlers.validators, "_view", lambda: ViewStub())
    monkeypatch.setattr(
        SS, "get_indexed_attestation_signature_set", lambda view, idx: ws
    )
    attestation = {
        "data": {
            "slot": 8,
            "index": 0,
            "beacon_block_root": b"\x00" * 32,
            "source": {"epoch": 0, "root": b"\x00" * 32},
            "target": {"epoch": 1, "root": b"\x11" * 32},
        }
    }
    return handlers, raw, attestation


def test_suppressed_double_vote_served_from_aggregation_seen_map(monkeypatch):
    v, pipe = make_pipe()
    ws = wire(v, ROOT, (7,))
    assert submit(pipe, ws).result(timeout=10) is True  # seeds the seen-map
    handlers, raw, att = _recovery_world(monkeypatch, pipe, v, ws)
    handlers._recover_suppressed_double_vote(att)
    assert raw.calls == 0  # verdict served, no standalone verification
    assert handlers.slasher.probes == [((7,), True)]
    assert len(handlers.slasher.ingested) == 1
    pipe.close()


def test_suppressed_double_vote_falls_back_on_seen_map_miss(monkeypatch):
    v, pipe = make_pipe()
    ws = wire(v, ROOT, (7,))  # never submitted -> not in the seen-map
    handlers, raw, att = _recovery_world(monkeypatch, pipe, v, ws)
    handlers._recover_suppressed_double_vote(att)
    assert raw.calls == 1  # standalone verification paid as before
    assert handlers.slasher.probes == [((7,), True)]
    pipe.close()


# -- bench probe (CI satellite) ----------------------------------------------


def test_bench_effective_probe_skip_semantics(capsys):
    import json

    import bench

    class Broken:
        _use_rlc = True
        table = []

    bench._probe_effective_atts(Broken())
    recs = [
        json.loads(l)
        for l in capsys.readouterr().out.strip().splitlines()
        if l.startswith("{")
    ]
    assert len(recs) == 1
    rec = recs[0]
    assert rec["metric"] == "bls_pipeline_effective_atts_per_s"
    assert rec["value"] is None and rec["skipped"] is True
    assert rec["unit"] == "atts/s"
    assert "preagg-probe" in rec["error"]


def test_bench_effective_probe_respects_escape_hatches(capsys, monkeypatch):
    import json

    import bench

    class RlcOff:
        _use_rlc = False

    bench._probe_effective_atts(RlcOff())
    monkeypatch.setenv("LODESTAR_TPU_BLS_PREAGG", "0")

    class PreaggOff:
        _use_rlc = True

    bench._probe_effective_atts(PreaggOff())
    recs = [
        json.loads(l)
        for l in capsys.readouterr().out.strip().splitlines()
        if l.startswith("{")
    ]
    assert len(recs) == 2 and all(r["skipped"] for r in recs)
    assert "RLC disabled" in recs[0]["error"]
    assert "stage disabled" in recs[1]["error"]


def test_bench_effective_probe_happy_path_emits_record(capsys, monkeypatch):
    """The probe's duplicate-heavy gossip->processor->pipeline loop
    end-to-end with the stub verifier: one measured record carrying
    effective atts/s, verified sets/s, and a mean aggregation factor
    meeting the >= 3 acceptance bound."""
    import json

    import bench

    stub = StubAggVerifier()

    # root-keyed stub tokens replace real signing: verdicts/sums ignore
    # indices (the probe's flood is all-valid)
    def _verdict(s):
        o = stub.oracle.get(s.signature)
        return bool(o is not None and o[0] == s.signing_root and o[2])

    stub._verdict = _verdict

    def agg(groups):
        out = []
        for g in groups:
            infos = [stub.oracle.get(s) for s in g]
            if any(i is None for i in infos):
                out.append(None)
                continue
            out.append(stub.sig(infos[0][0], (), all(i[2] for i in infos)))
        return out

    class FakeMessages:
        def get_many(self, roots):
            return [None] * len(roots)

    class FakeVerifier:
        _use_rlc = True
        table = list(range(512))
        messages = FakeMessages()
        metrics = stub.metrics
        max_job_sets = 512
        aggregate_wire_signatures = staticmethod(agg)
        verify_signature_sets = stub.verify_signature_sets
        begin_job = stub.begin_job
        finish_job = stub.finish_job

        def close(self):
            pass

    monkeypatch.setattr(bench, "BENCH_PREAGG_ATTS", 64)
    monkeypatch.setattr(bench, "BENCH_PREAGG_SUBNETS", 4)
    monkeypatch.setattr(bench, "BENCH_PREAGG_DUP", 2)
    monkeypatch.setattr(bench, "BENCH_PREAGG_WAVES", 2)
    monkeypatch.setattr(bench.GTB, "keygen", lambda seed: seed)
    monkeypatch.setattr(bench.GTB, "sign", lambda sk, root: (sk, root))
    monkeypatch.setattr(
        bench.GCC, "g2_compress", lambda pt: stub.sig(pt[1], (), True)
    )

    bench._probe_effective_atts(FakeVerifier())
    recs = [
        json.loads(l)
        for l in capsys.readouterr().out.strip().splitlines()
        if l.startswith("{")
    ]
    assert len(recs) == 1, recs
    rec = recs[0]
    assert rec["metric"] == "bls_pipeline_effective_atts_per_s"
    assert rec.get("skipped") is None and rec["value"] > 0
    assert rec["unit"] == "atts/s"
    assert rec["aggregation_factor_mean"] >= 3.0
    assert rec["verified_sets_per_s"] > 0
    assert rec["value"] >= 3 * rec["verified_sets_per_s"] * 0.99
    assert "slo" in rec


def test_bench_aggfwd_probe_skip_semantics(capsys):
    """ISSUE 19 satellite: a broken probe run skips BOTH aggregate-
    forward metrics with null values (never a measured zero)."""
    import json

    import bench

    class Broken:
        _use_rlc = True
        table = []

    bench._probe_aggregate_forward(Broken())
    recs = [
        json.loads(l)
        for l in capsys.readouterr().out.strip().splitlines()
        if l.startswith("{")
    ]
    assert len(recs) == 2
    assert [r["metric"] for r in recs] == [
        "gossip_bytes_per_verified_att",
        "aggregate_forward_factor",
    ]
    assert all(r["value"] is None and r["skipped"] for r in recs)
    assert recs[0]["unit"] == "bytes/att" and recs[1]["unit"] == "ratio"
    assert all("aggfwd-probe" in r["error"] for r in recs)


def test_bench_aggfwd_probe_respects_escape_hatches(capsys, monkeypatch):
    import json

    import bench

    class RlcOff:
        _use_rlc = False

    bench._probe_aggregate_forward(RlcOff())

    class On:
        _use_rlc = True

    monkeypatch.setenv("LODESTAR_TPU_BLS_PREAGG", "0")
    bench._probe_aggregate_forward(On())
    monkeypatch.delenv("LODESTAR_TPU_BLS_PREAGG")
    monkeypatch.setenv("LODESTAR_TPU_BLS_AGGFWD", "0")
    bench._probe_aggregate_forward(On())
    recs = [
        json.loads(l)
        for l in capsys.readouterr().out.strip().splitlines()
        if l.startswith("{")
    ]
    # three hatches x two metric records each, all skips
    assert len(recs) == 6 and all(r["skipped"] for r in recs)
    assert "RLC disabled" in recs[0]["error"]
    assert "stage disabled" in recs[2]["error"]
    assert "aggregate-forward disabled" in recs[4]["error"]


def test_bench_aggfwd_probe_happy_path_emits_records(capsys, monkeypatch):
    """The probe's flood end-to-end with the stub verifier: packed
    re-publication measured downstream, bytes-per-verified-att emitted,
    and the aggregate-forward factor meeting the >= 3 acceptance bound
    against the raw-sync baseline."""
    import json

    import bench

    stub = StubAggVerifier()

    def _verdict(s):
        o = stub.oracle.get(s.signature)
        return bool(o is not None and o[0] == s.signing_root and o[2])

    stub._verdict = _verdict

    def agg(groups):
        out = []
        for g in groups:
            infos = [stub.oracle.get(s) for s in g]
            if any(i is None for i in infos):
                out.append(None)
                continue
            out.append(stub.sig(infos[0][0], (), all(i[2] for i in infos)))
        return out

    class FakeMessages:
        def get_many(self, roots):
            return [None] * len(roots)

    class FakeVerifier:
        _use_rlc = True
        table = list(range(512))
        messages = FakeMessages()
        metrics = stub.metrics
        max_job_sets = 512
        aggregate_wire_signatures = staticmethod(agg)
        verify_signature_sets = stub.verify_signature_sets
        begin_job = stub.begin_job
        finish_job = stub.finish_job

        def close(self):
            pass

    monkeypatch.setattr(bench, "BENCH_PREAGG_ATTS", 256)
    monkeypatch.setattr(bench, "BENCH_PREAGG_SUBNETS", 4)
    monkeypatch.setattr(bench, "BENCH_PREAGG_DUP", 2)
    monkeypatch.setattr(bench, "BENCH_PREAGG_WAVES", 2)
    monkeypatch.setattr(bench.GTB, "keygen", lambda seed: seed)
    monkeypatch.setattr(bench.GTB, "sign", lambda sk, root: (sk, root))
    monkeypatch.setattr(
        bench.GCC, "g2_compress", lambda pt: stub.sig(pt[1], (), True)
    )

    bench._probe_aggregate_forward(FakeVerifier())
    recs = [
        json.loads(l)
        for l in capsys.readouterr().out.strip().splitlines()
        if l.startswith("{")
    ]
    assert len(recs) == 2, recs
    by_metric = {r["metric"]: r for r in recs}
    bpa = by_metric["gossip_bytes_per_verified_att"]
    assert bpa.get("skipped") is None and bpa["unit"] == "bytes/att"
    assert 0 < bpa["value"] < bpa["raw_bytes_per_att"]
    factor = by_metric["aggregate_forward_factor"]
    assert factor.get("skipped") is None and factor["unit"] == "ratio"
    assert factor["value"] >= 3.0
    # every pack published crossed the in-memory wire exactly once
    assert factor["downstream_msgs"] == factor["packs_published"] > 0
    assert factor["atts_covered_by_packs"] > 0
    assert "slo" in factor and "critical_p99_submit_to_verdict_s" in factor


# -- slow tier: real crypto + real kernels -----------------------------------


def _real_world(n_keys=4):
    import numpy as np

    from lodestar_tpu.bls import PubkeyTable, TpuBlsVerifier
    from lodestar_tpu.crypto import bls as GTB

    sks = [GTB.keygen(b"preagg-%d" % i) for i in range(n_keys)]
    pks = [GTB.sk_to_pk(sk) for sk in sks]
    table = PubkeyTable(capacity=n_keys)
    table.register(pks)
    return sks, TpuBlsVerifier(table, rng=np.random.default_rng(3))


@pytest.mark.slow
def test_device_g2_sum_matches_host_ground_truth():
    """kernels/verify.aggregate_g2_sum_device == the host decompress+
    jacobian-add oracle, including multi-group dispatch, duplicate
    members, and the undecodable-member None contract."""
    from lodestar_tpu.crypto import bls as GTB
    from lodestar_tpu.crypto import curves as GCC

    sks, v = _real_world(4)
    root = b"m" * 32
    sigs = [GCC.g2_compress(GTB.sign(sk, root)) for sk in sks]
    groups = [sigs[:3], sigs[3:4], [sigs[0], sigs[0]]]
    host = [v._aggregate_wire_host(g) for g in groups]
    dev = v._aggregate_wire_device(groups)
    assert host == dev
    ref = GCC.multi_add(
        GCC.FP2_OPS, [GCC.g2_decompress(s) for s in groups[0]]
    )
    assert GCC.g2_decompress(host[0]) == ref
    # an undecodable member voids the whole group (the caller then
    # dispatches unaggregated)
    bad = bytes([0x80]) + b"\xff" * 95
    assert v._aggregate_wire_device([[sigs[0], bad]]) == [None]


@pytest.mark.slow
def test_preagg_real_crypto_verdicts_match_individual():
    """End-to-end on the real verifier (host G2 sums on the CPU
    backend, real RLC verification kernels): aggregated-then-bisected
    verdicts equal per-message individual verification for a bucket
    mixing valid signatures, a tampered one, and a duplicate."""
    from lodestar_tpu.crypto import bls as GTB
    from lodestar_tpu.crypto import curves as GCC

    sks, v = _real_world(4)
    root = b"real preagg root".ljust(32, b"\x00")
    sigs = [GCC.g2_compress(GTB.sign(sk, root)) for sk in sks]
    tampered = bytearray(sigs[2])
    tampered[-1] ^= 0x01  # still decodable with overwhelming probability
    messages = [
        WireSignatureSet.single(0, root, sigs[0]),
        WireSignatureSet.single(1, root, sigs[1]),
        WireSignatureSet.single(2, root, bytes(tampered)),
        WireSignatureSet.single(3, root, sigs[3]),
        WireSignatureSet.single(0, root, sigs[0]),  # exact duplicate
    ]
    expected = v.verify_signature_sets_individually(list(messages))
    pipe = BlsVerificationPipeline(v, standard_wait_ms=60)
    assert pipe._agg is not None
    futs = [submit(pipe, ws) for ws in messages]
    got = [f.result(timeout=1200) for f in futs]
    pipe.close()
    assert got == expected
    assert got == [True, True, False, True, True]
    assert pipe.agg_stats()["dedup"] == 1


def test_aggregate_chunk_device_wrapper_round_trips(monkeypatch):
    """The verifier's `agg_g2_sum` host wrapper (fast, stubbed device):
    group/padding layout handed to the dispatch, Montgomery->int->
    compress conversion of the head planes, infinity groups, and the
    None contract for groups with an undecodable member."""
    import numpy as np

    import jax.numpy as jnp

    from lodestar_tpu.bls.pubkey_table import PubkeyTable
    from lodestar_tpu.bls.verifier import TpuBlsVerifier
    from lodestar_tpu.crypto import bls as GTB
    from lodestar_tpu.crypto import curves as GCC
    from lodestar_tpu.kernels import layout as LY
    from lodestar_tpu.kernels import verify as KV

    sks = [GTB.keygen(b"wrap-%d" % i) for i in range(3)]
    root = b"w" * 32
    pts = [GTB.sign(sk, root) for sk in sks]
    sigs = [GCC.g2_compress(p) for p in pts]
    neg = GCC.g2_compress((pts[0][0], GCC.F.fp2_neg(pts[0][1])))
    groups = [sigs[:2], [sigs[2]], [sigs[0], neg]]  # last sums to O

    v = TpuBlsVerifier(PubkeyTable(capacity=1), rng=np.random.default_rng(0))
    seen = {}

    def fake_device_call(name, fn, args):
        assert name == "agg_g2_sum"
        sig_x0, sig_x1, flags, group, head_lanes, glive = (
            np.asarray(a) for a in args
        )
        n = flags.shape[1]
        seen["layout"] = (group.copy(), head_lanes.copy(), glive.copy(), n)
        # padding lanes carry fresh group ids and the inert flag
        total = sum(len(g) for g in groups)
        assert n % 128 == 0 and (flags[1, total:] == 1).all()
        assert len(np.unique(group)) == len(groups) + (n - total)
        # host-computed expected sums, emitted in the device layout
        # (Montgomery planes, generator-substituted infinity lanes)
        ax = np.zeros((KV.NL, KV.BT), np.int32)
        ax1 = np.zeros((KV.NL, KV.BT), np.int32)
        ay = np.zeros((KV.NL, KV.BT), np.int32)
        ay1 = np.zeros((KV.NL, KV.BT), np.int32)
        g_inf = np.zeros((1, KV.BT), np.int32)
        g_inf[0, :] = 1
        for gi, g in enumerate(groups):
            agg = GCC.multi_add(GCC.FP2_OPS, [GCC.g2_decompress(s) for s in g])
            if agg is None:
                g_inf[0, gi] = 1
                continue
            g_inf[0, gi] = 0
            ax[:, gi] = LY.to_limbs(agg[0][0] * LY.R_MOD_P % LY.P)
            ax1[:, gi] = LY.to_limbs(agg[0][1] * LY.R_MOD_P % LY.P)
            ay[:, gi] = LY.to_limbs(agg[1][0] * LY.R_MOD_P % LY.P)
            ay1[:, gi] = LY.to_limbs(agg[1][1] * LY.R_MOD_P % LY.P)
        ok = np.zeros((1, n), np.int32)
        ok[0, :total] = 1
        return tuple(
            jnp.asarray(a) for a in (ax, ax1, ay, ay1, g_inf, ok)
        )

    monkeypatch.setattr(v, "_device_call", fake_device_call)
    out = v._aggregate_wire_device([list(g) for g in groups])
    assert out == [v._aggregate_wire_host(g) for g in groups]
    assert out[1] == sigs[2]  # singleton group round-trips exactly
    assert out[2] == GCC.g2_compress(None)  # cancelling pair -> infinity
    # an undecodable member -> that group degrades to None (host path
    # refuses too), others unaffected
    bad = bytes([0x80]) + b"\xff" * 95

    def fake_bad_call(name, fn, args):
        res = list(fake_device_call(name, fn, args))
        ok = np.asarray(res[5]).copy()
        ok[0, 2] = 0  # the bad member's lane
        res[5] = jnp.asarray(ok)
        return tuple(res)

    monkeypatch.setattr(v, "_device_call", fake_bad_call)
    out = v._aggregate_wire_device([sigs[:2], [sigs[2], bad]])
    assert out[0] is not None and out[1] is None


def test_pending_sets_never_double_counts_through_flush(monkeypatch):
    """Review fix: when the stage flushes, the contributor-side set
    units HAND OFF to the layer jobs' own accounting — a blocked
    dispatcher must never show submissions counted twice (before the
    fix, 6 in-flight submissions read 7+, tripping backpressure at
    ~half the configured high-water mark)."""
    gate = threading.Event()
    v = StubAggVerifier()
    orig_begin = v.begin_job

    def slow_begin(sets, batchable):
        gate.wait(5)  # hold the device leg so layer jobs stay in flight
        return orig_begin(sets, batchable)

    v.begin_job = slow_begin
    pipe = BlsVerificationPipeline(v, standard_wait_ms=30)
    futs = [submit(pipe, wire(v, ROOT, (i,))) for i in range(3)]
    futs += [submit(pipe, wire(v, ROOT2, (10 + i,))) for i in range(3)]
    peak = 0
    t0 = time.time()
    while time.time() - t0 < 0.3:
        peak = max(peak, pipe.pending_sets())
        time.sleep(0.005)
    gate.set()
    assert all(f.result(timeout=10) for f in futs)
    deadline = time.time() + 5
    while pipe.pending_sets() != 0 and time.time() < deadline:
        time.sleep(0.01)
    assert pipe.pending_sets() == 0
    pipe.close()
    assert peak <= 6, f"pending_sets peaked at {peak} for 6 submissions"
