"""LightClientServer: produced updates validate in the Lightclient.

Reference: packages/beacon-node/src/chain/lightClient/index.ts producing
what packages/light-client/src consumes — the round trip proves the
merkle branches (ssz.container_branch) and committee handling are
mutually consistent.
"""

import pytest

from lodestar_tpu import params
from lodestar_tpu import types as T
from lodestar_tpu.chain.chain import BeaconChain
from lodestar_tpu.chain.light_client_server import LightClientServer
from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
from lodestar_tpu.crypto import bls as B
from lodestar_tpu.crypto import curves as C
from lodestar_tpu.db import BeaconDb
from lodestar_tpu.light_client.lightclient import Lightclient, ValidationError
from lodestar_tpu.params import ForkName
from lodestar_tpu.ssz import is_valid_merkle_branch
from lodestar_tpu.ssz.core import container_branch
from lodestar_tpu.state_transition import create_genesis_state
from lodestar_tpu.state_transition.state import BeaconStateAltair

P = params.ACTIVE_PRESET
N_KEYS = 16


@pytest.fixture(scope="module")
def lc_world():
    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG, fork_epochs={ForkName.altair: 0}
    )
    sks = [B.keygen(b"lcs-%d" % i) for i in range(N_KEYS)]
    pks = [C.g1_compress(B.sk_to_pk(sk)) for sk in sks]
    genesis = create_genesis_state(cfg, pks, genesis_time=21)
    chain = BeaconChain(cfg, genesis, db=BeaconDb())
    server = LightClientServer(chain)
    return cfg, sks, pks, genesis, chain, server


def _import_block(chain, cfg, sks, slot, sync_signers=None):
    """Produce + sign + import a block; optionally with a full sync
    aggregate signed by `sync_signers` (pubkey->sk map)."""
    from lodestar_tpu.chain.produce_block import produce_block
    from lodestar_tpu.ssz import uint64
    from lodestar_tpu.state_transition import process_slots
    from lodestar_tpu.state_transition.accessors import (
        get_beacon_proposer_index,
    )

    head = chain.head_state
    pre = head.clone()
    if pre.slot < slot:
        process_slots(pre, slot)
    proposer = get_beacon_proposer_index(pre)
    epoch = slot // P.SLOTS_PER_EPOCH
    reveal = B.sign_bytes(
        sks[proposer],
        cfg.compute_signing_root(
            uint64.hash_tree_root(epoch), cfg.get_domain(slot, params.DOMAIN_RANDAO)
        ),
    )
    sync_aggregate = None
    if sync_signers is not None:
        prev_root = chain.get_head_root()
        domain = cfg.get_domain(slot, params.DOMAIN_SYNC_COMMITTEE, slot - 1)
        sroot = cfg.compute_signing_root(prev_root, domain)
        committee = head.current_sync_committee["pubkeys"]
        sig = B.aggregate_signatures(
            [B.sign(sync_signers[pk], sroot) for pk in committee]
        )
        sync_aggregate = {
            "sync_committee_bits": [True] * P.SYNC_COMMITTEE_SIZE,
            "sync_committee_signature": C.g2_compress(sig),
        }
    block, _post = produce_block(
        head, slot, reveal, sync_aggregate=sync_aggregate
    )
    domain = cfg.get_domain(slot, params.DOMAIN_BEACON_PROPOSER, slot)
    root = cfg.compute_signing_root(
        T.BeaconBlockAltair.hash_tree_root(block), domain
    )
    return chain.process_block(
        {"message": block, "signature": B.sign_bytes(sks[proposer], root)}
    )


def test_container_branch_spec_gindices(lc_world):
    cfg, sks, pks, genesis, chain, server = lc_world
    value = genesis.to_value()
    root = genesis.hash_tree_root()
    leaf, branch, depth, index = container_branch(
        BeaconStateAltair, value, ["next_sync_committee"]
    )
    # spec NEXT_SYNC_COMMITTEE gindex 55 = 2**5 + 23
    assert (depth, index) == (5, 23)
    assert is_valid_merkle_branch(leaf, branch, depth, index, root)

    leaf, branch, depth, index = container_branch(
        BeaconStateAltair, value, ["finalized_checkpoint", "root"]
    )
    # spec FINALIZED_ROOT gindex 105 = 2**6 + 41
    assert (depth, index) == (6, 41)
    assert is_valid_merkle_branch(leaf, branch, depth, index, root)


def test_server_update_validates_in_client(lc_world):
    cfg, sks, pks, genesis, chain, server = lc_world
    sk_of = {pks[i]: sks[i] for i in range(N_KEYS)}

    _import_block(chain, cfg, sks, 1)  # parent for the attested header
    assert server.produced == 0  # empty sync aggregate: nothing produced
    _import_block(chain, cfg, sks, 2, sync_signers=sk_of)
    assert server.produced == 1

    update = server.get_optimistic_update()
    assert update is not None
    assert update.signature_slot == 2
    assert update.attested_header["slot"] == 1
    assert update.next_sync_committee_branch is not None

    # bootstrap the client at genesis and feed it the produced update
    anchor_header = dict(genesis.latest_block_header)
    anchor_header["state_root"] = genesis.hash_tree_root()
    client = Lightclient(
        cfg, anchor_header, genesis.current_sync_committee["pubkeys"]
    )
    client.process_update(update)
    assert client.optimistic_header["slot"] == 1
    # the committee rotation was installed for the next period
    assert len(client.committees) == 2

    # a tampered committee branch must be rejected
    bad = LightClientUpdateCopy(update)
    bad.next_sync_committee_branch = [
        b"\x00" * 32 for _ in update.next_sync_committee_branch
    ]
    with pytest.raises(ValidationError):
        client.process_update(bad)


def LightClientUpdateCopy(u):
    from dataclasses import replace

    return replace(u)


def test_bootstrap(lc_world):
    cfg, sks, pks, genesis, chain, server = lc_world
    head_root = chain.get_head_root()
    boot = server.get_bootstrap(head_root)
    assert boot is not None
    state = chain.regen._get_post_state(head_root.hex())
    assert is_valid_merkle_branch(
        T.SyncCommittee.hash_tree_root(boot["current_sync_committee"]),
        boot["current_sync_committee_branch"],
        5,
        22,  # current_sync_committee is field 22 of the altair state
        state.hash_tree_root(),
    )


def test_transports_bootstrap_and_update(lc_world):
    """Both transports (req/resp + REST) bootstrap from a trusted root
    and deliver the server's updates into a validating Lightclient
    (reference: light-client/src/transport/{p2p,rest}.ts)."""
    from lodestar_tpu.api.server import BeaconApiServer, DefaultHandlers
    from lodestar_tpu.light_client.transport import (
        ReqRespLightClientTransport,
        RestLightClientTransport,
        bootstrap_lightclient,
    )
    from lodestar_tpu.network.reqresp import ReqResp, connect_inmemory
    from lodestar_tpu.network.reqresp_protocols import ReqRespBeaconNode

    cfg, sks, pks, genesis, chain, server = lc_world
    sk_of = {pks[i]: sks[i] for i in range(N_KEYS)}
    # ensure an update exists (idempotent when the earlier test ran)
    if server.get_optimistic_update() is None:
        _import_block(chain, cfg, sks, 1)
        _import_block(chain, cfg, sks, 2, sync_signers=sk_of)
    update = server.get_optimistic_update()
    head_root = chain.get_head_root()

    # -- req/resp transport
    server_rr = ReqResp()
    client_rr = ReqResp()
    connect_inmemory(client_rr, "lc-client", server_rr, "lc-server")
    node = ReqRespBeaconNode(
        server_rr, cfg, chain=chain, db=chain.db, light_client_server=server
    )
    # a peer-side node only for protocol definitions
    peer_node = ReqRespBeaconNode(client_rr, cfg, chain=chain)
    peer_node.protocols.update(
        {k: v for k, v in node.protocols.items() if k.startswith("lc_")}
    )
    t_rr = ReqRespLightClientTransport(client_rr, peer_node, "lc-server")
    boot = t_rr.get_bootstrap(head_root)
    assert bytes(boot["header"]["state_root"]) != b"\x00" * 32
    lc = bootstrap_lightclient(cfg, t_rr, head_root)
    assert lc.finalized_header["slot"] == boot["header"]["slot"]
    updates = t_rr.get_updates(0, 1)
    assert updates and updates[0].signature_slot == update.signature_slot

    # -- REST transport
    api = BeaconApiServer(
        DefaultHandlers(chain=chain, light_client_server=server)
    )
    api.listen()
    try:
        t_rest = RestLightClientTransport(f"http://127.0.0.1:{api.port}")
        boot2 = t_rest.get_bootstrap(head_root)
        assert boot2["header"] == {
            k: boot["header"][k] for k in boot2["header"]
        }
        ups = t_rest.get_updates(0, 1)
        assert ups and ups[0].attested_header == updates[0].attested_header
        opt = t_rest.get_optimistic_update()
        assert opt is not None and opt.signature_slot == update.signature_slot
        # a fresh client validates the REST-delivered update end-to-end
        anchor_header = dict(genesis.latest_block_header)
        anchor_header["state_root"] = genesis.hash_tree_root()
        lc2 = Lightclient(
            cfg, anchor_header, genesis.current_sync_committee["pubkeys"]
        )
        lc2.process_update(opt)
        assert lc2.optimistic_header["slot"] == opt.attested_header["slot"]
    finally:
        api.close()


def test_best_updates_persist_across_restart(lc_world):
    """Per-period best updates restore from the db on boot (reference:
    db/repositories/lightclientBestUpdate.ts)."""
    cfg, sks, pks, genesis, chain, server = lc_world
    if not server.best_update_by_period:
        # self-contained: produce a sync-aggregate block so an update
        # exists even when this test runs standalone
        signers = {pks[i]: sks[i] for i in range(len(sks))}
        _import_block(
            chain, cfg, sks, chain.head_state.slot + 1, sync_signers=signers
        )
        _import_block(
            chain, cfg, sks, chain.head_state.slot + 1, sync_signers=signers
        )
    assert server.best_update_by_period, "no updates produced"
    # a fresh server over the same chain/db restores the periods
    server2 = LightClientServer(chain)
    assert set(server2.best_update_by_period) == set(
        server.best_update_by_period
    )
    for period, upd in server.best_update_by_period.items():
        got = server2.get_update(period)
        assert got is not None
        assert got.attested_header["slot"] == upd.attested_header["slot"]
        assert bytes(got.sync_committee_signature) == bytes(
            upd.sync_committee_signature
        )
