"""JAX Miller loop / final exponentiation vs the pure-Python oracle."""

import random

import numpy as np

import jax
import jax.numpy as jnp

from lodestar_tpu.crypto import bls as GTB
from lodestar_tpu.crypto import curves as C
from lodestar_tpu.crypto import fields as GT
from lodestar_tpu.crypto import pairing as GTP
from lodestar_tpu.crypto.hash_to_curve import hash_to_g2
from lodestar_tpu.ops import fp, fp2, fp12
from lodestar_tpu.ops import pairing as KP

rng = random.Random(0xA7E)


def enc_g1_affine(pts):
    xs = jnp.asarray(np.stack([fp.const(p[0]) for p in pts]))
    ys = jnp.asarray(np.stack([fp.const(p[1]) for p in pts]))
    return (xs, ys)


def enc_g2_affine(pts):
    return (
        jnp.asarray(fp2.stack_consts([p[0] for p in pts])),
        jnp.asarray(fp2.stack_consts([p[1] for p in pts])),
    )


def dec12(a):
    leaves = jax.tree_util.tree_leaves(a)
    n = leaves[0].shape[0]
    return [
        fp12.decode12(jax.tree_util.tree_map(lambda l: np.asarray(l)[i], a))
        for i in range(n)
    ]


def rand_pairs(n):
    out = []
    for _ in range(n):
        p = C.scalar_mul(C.FP_OPS, C.G1_GEN, rng.randrange(1, GT.R))
        q = C.scalar_mul(C.FP2_OPS, C.G2_GEN, rng.randrange(1, GT.R))
        out.append((p, q))
    return out


def test_miller_loop_matches_oracle_up_to_subfield():
    # The twisted loop scales each line by an Fp2 factor (killed by the
    # easy part of the final exponentiation — see ops/pairing.py), so the
    # raw Miller value equals the affine oracle's up to an Fp2 factor.
    pairs = rand_pairs(2) + [(C.G1_GEN, C.G2_GEN)]
    ps = enc_g1_affine([p for p, _ in pairs])
    qs = enc_g2_affine([q for _, q in pairs])
    got = dec12(jax.jit(KP.miller_loop)(ps, qs))
    for (p, q), g in zip(pairs, got):
        want = GTP.miller_loop(p, q)
        ratio = GT.fp12_mul(g, GT.fp12_inv(want))
        c0, c1 = ratio
        assert c1 == GT.FP6_ZERO and c0[1] == GT.FP2_ZERO and c0[2] == GT.FP2_ZERO
        assert not GT.fp2_is_zero(c0[0])


def test_final_exponentiation_is_cubed_oracle():
    pairs = rand_pairs(2)
    ps = enc_g1_affine([p for p, _ in pairs])
    qs = enc_g2_affine([q for _, q in pairs])
    got = dec12(
        jax.jit(lambda p, q: KP.final_exponentiation(KP.miller_loop(p, q)))(
            ps, qs
        )
    )
    for (p, q), g in zip(pairs, got):
        e = GTP.pairing(p, q)
        assert g == GT.fp12_pow(e, 3)


def test_pairing_product_bilinearity():
    # e(aP, Q) * e(-P, aQ) == 1
    a = rng.randrange(2, GT.R)
    p = C.scalar_mul(C.FP_OPS, C.G1_GEN, rng.randrange(1, GT.R))
    q = C.scalar_mul(C.FP2_OPS, C.G2_GEN, rng.randrange(1, GT.R))
    ap = C.scalar_mul(C.FP_OPS, p, a)
    aq = C.scalar_mul(C.FP2_OPS, q, a)
    ps = enc_g1_affine([ap, C.affine_neg(C.FP_OPS, p)])
    qs = enc_g2_affine([q, aq])
    ok = jax.jit(KP.pairing_product_is_one)(ps, qs)
    assert bool(ok)
    # and the same with a mismatched scalar fails
    ps_bad = enc_g1_affine([ap, C.affine_neg(C.FP_OPS, p)])
    qs_bad = enc_g2_affine([q, C.scalar_mul(C.FP2_OPS, q, a + 1)])
    assert not bool(jax.jit(KP.pairing_product_is_one)(ps_bad, qs_bad))


def test_bls_verify_relation_on_device():
    # Full BLS verification relation: e(-G1, sig) * e(pk, H(m)) == 1.
    sk = GTB.keygen(b"pairing-test")
    pk = GTB.sk_to_pk(sk)
    msg = b"attestation signing root"
    sig = GTB.sign(sk, msg)
    hm = hash_to_g2(msg)
    ps = enc_g1_affine([GTB.NEG_G1_GEN, pk])
    qs = enc_g2_affine([sig, hm])
    assert bool(jax.jit(KP.pairing_product_is_one)(ps, qs))
    # wrong message fails
    hm_bad = hash_to_g2(b"different root")
    qs_bad = enc_g2_affine([sig, hm_bad])
    assert not bool(jax.jit(KP.pairing_product_is_one)(ps, qs_bad))
