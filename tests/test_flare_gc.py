"""flare self-slashing over the API + GC stats.

Reference: packages/flare/src/cmds/selfSlash{Proposer,Attester}.ts —
the slashing lands in the pool, gets included in a block, and the
offender ends up slashed through the full state transition; gc-stats
equivalent (utils/gc_stats.py).
"""

import gc

import pytest

from lodestar_tpu import params
from lodestar_tpu import types as T
from lodestar_tpu.api.client import ApiClient
from lodestar_tpu.api.server import BeaconApiServer, DefaultHandlers
from lodestar_tpu.chain.chain import BeaconChain
from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
from lodestar_tpu.crypto import bls as B
from lodestar_tpu.crypto import curves as C
from lodestar_tpu.flare import self_slash_attester, self_slash_proposer
from lodestar_tpu.params import ForkName
from lodestar_tpu.state_transition import create_genesis_state
from lodestar_tpu.utils.gc_stats import GcStats

P = params.ACTIVE_PRESET


@pytest.fixture(scope="module")
def flare_world():
    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG, fork_epochs={ForkName.altair: 0}
    )
    sks = [B.keygen(b"flare-%d" % i) for i in range(16)]
    pks = [C.g1_compress(B.sk_to_pk(sk)) for sk in sks]
    genesis = create_genesis_state(cfg, pks, genesis_time=1)
    chain = BeaconChain(cfg, genesis)
    server = BeaconApiServer(DefaultHandlers(chain=chain))
    server.listen()
    client = ApiClient([f"http://127.0.0.1:{server.port}"], timeout=30)
    yield cfg, sks, chain, client
    server.close()


def test_self_slash_proposer_end_to_end(flare_world):
    cfg, sks, chain, client = flare_world
    victim = 3
    self_slash_proposer(cfg, client, sks[victim], victim, slot=1)
    ps, _, _ = chain.op_pool.get_slashings_and_exits(chain.head_state)
    assert len(ps) == 1

    # the slashing flows from the pool into a produced block and the
    # state transition slashes the offender
    block = chain.produce_block(1, b"\x07" * 96)
    assert len(block["body"]["proposer_slashings"]) == 1
    from lodestar_tpu.state_transition import state_transition

    post = state_transition(
        chain.head_state,
        {"message": block, "signature": b"\x00" * 96},
        verify_state_root=True,
        verify_signatures=False,
    )
    assert bool(post.slashed[victim])


def test_self_slash_attester_over_api(flare_world):
    cfg, sks, chain, client = flare_world
    indices = [5, 6]
    slashing = self_slash_attester(
        cfg, client, [sks[i] for i in indices], indices, target_epoch=0
    )
    # valid double vote: both indexed attestations verify
    from lodestar_tpu.state_transition.block import (
        is_slashable_attestation_data,
    )

    assert is_slashable_attestation_data(
        slashing["attestation_1"]["data"], slashing["attestation_2"]["data"]
    )
    _, atts, _ = chain.op_pool.get_slashings_and_exits(chain.head_state)
    assert len(atts) == 1


def test_voluntary_exit_pool_route_validates(flare_world):
    from lodestar_tpu.api.client import ApiError

    cfg, sks, chain, client = flare_world
    # unsigned + too-young exit: rejected at ingress, pool stays clean
    with pytest.raises(ApiError) as exc:
        client.submit_voluntary_exit(
            {
                "message": {"epoch": 0, "validator_index": 9},
                "signature": b"\x00" * 96,
            }
        )
    assert exc.value.status == 400
    _, _, exits = chain.op_pool.get_slashings_and_exits(chain.head_state)
    assert exits == []
    # block production keeps working after the rejected submission
    block = chain.produce_block(2, b"\x09" * 96)
    assert block["body"]["voluntary_exits"] == []


def test_gc_stats():
    stats = GcStats().install()
    try:
        junk = [[object() for _ in range(100)] for _ in range(100)]
        del junk
        gc.collect()
        snap = stats.snapshot()
        assert sum(snap["gc_runs_total"].values()) >= 1
        assert sum(snap["gc_pause_seconds_total"].values()) >= 0
    finally:
        stats.uninstall()
    before = sum(stats.collections.values())
    gc.collect()
    assert sum(stats.collections.values()) == before  # uninstalled
