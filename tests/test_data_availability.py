"""Deneb data-availability gate: imports require validated sidecars.

Reference behavior: the reference gates importBlock on blob availability
(beacon-node blockInput handling) — versioned hashes only bind
commitments to EL transactions; the blobs themselves must be present and
KZG-verified (ADVICE r4 medium).
"""

import pytest

from lodestar_tpu import params
from lodestar_tpu.chain.chain import BeaconChain, BlobsUnavailableError
from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
from lodestar_tpu.crypto import bls as B
from lodestar_tpu.crypto import curves as C
from lodestar_tpu.params import ForkName
from lodestar_tpu.state_transition import create_genesis_state

pytestmark = pytest.mark.smoke


@pytest.fixture(scope="module")
def chain():
    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG, fork_epochs={ForkName.altair: 0}
    )
    sks = [B.keygen(b"da-%d" % i) for i in range(4)]
    pks = [C.g1_compress(B.sk_to_pk(sk)) for sk in sks]
    return BeaconChain(cfg, create_genesis_state(cfg, pks, genesis_time=2))


def _block_with_commitments(commitments):
    return {"body": {"blob_kzg_commitments": commitments}}


def test_import_blocked_until_all_sidecars_available(chain):
    root = b"\x11" * 32
    c0, c1 = b"\xaa" * 48, b"\xbb" * 48
    block = _block_with_commitments([c0, c1])
    with pytest.raises(BlobsUnavailableError):
        chain._check_data_availability(block, root)
    chain.on_blob_sidecar(root, 0, c0, slot=5)
    with pytest.raises(BlobsUnavailableError, match="blob 1"):
        chain._check_data_availability(block, root)
    chain.on_blob_sidecar(root, 1, c1, slot=5)
    chain._check_data_availability(block, root)  # now passes


def test_commitment_mismatch_is_hard_failure(chain):
    root = b"\x22" * 32
    block = _block_with_commitments([b"\xaa" * 48])
    chain.on_blob_sidecar(root, 0, b"\xcc" * 48, slot=5)
    with pytest.raises(ValueError, match="mismatch"):
        chain._check_data_availability(block, root)


def test_commitment_free_blocks_unaffected(chain):
    chain._check_data_availability({"body": {}}, b"\x33" * 32)
    chain._check_data_availability(
        _block_with_commitments([]), b"\x33" * 32
    )


def test_availability_pruned_by_clock(chain):
    root = b"\x44" * 32
    chain.on_blob_sidecar(root, 0, b"\xaa" * 48, slot=3)
    chain.prune_pools(3 + params.SLOTS_PER_EPOCH + 1)
    with pytest.raises(BlobsUnavailableError):
        chain._check_data_availability(
            _block_with_commitments([b"\xaa" * 48]), root
        )
