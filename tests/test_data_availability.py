"""Deneb data-availability gate: imports require validated sidecars.

Reference behavior: the reference gates importBlock on blob availability
(beacon-node blockInput handling) — versioned hashes only bind
commitments to EL transactions; the blobs themselves must be present and
KZG-verified (ADVICE r4 medium).
"""

import pytest

from lodestar_tpu import params
from lodestar_tpu.chain.chain import BeaconChain, BlobsUnavailableError
from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
from lodestar_tpu.crypto import bls as B
from lodestar_tpu.crypto import curves as C
from lodestar_tpu.params import ForkName
from lodestar_tpu.state_transition import create_genesis_state

pytestmark = pytest.mark.smoke


@pytest.fixture(scope="module")
def chain():
    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG, fork_epochs={ForkName.altair: 0}
    )
    sks = [B.keygen(b"da-%d" % i) for i in range(4)]
    pks = [C.g1_compress(B.sk_to_pk(sk)) for sk in sks]
    return BeaconChain(cfg, create_genesis_state(cfg, pks, genesis_time=2))


def _block_with_commitments(commitments):
    return {"body": {"blob_kzg_commitments": commitments}}


def test_import_blocked_until_all_sidecars_available(chain):
    root = b"\x11" * 32
    c0, c1 = b"\xaa" * 48, b"\xbb" * 48
    block = _block_with_commitments([c0, c1])
    with pytest.raises(BlobsUnavailableError):
        chain._check_data_availability(block, root)
    chain.on_blob_sidecar(root, 0, c0, slot=5)
    with pytest.raises(BlobsUnavailableError, match="blob 1"):
        chain._check_data_availability(block, root)
    chain.on_blob_sidecar(root, 1, c1, slot=5)
    chain._check_data_availability(block, root)  # now passes


def test_commitment_mismatch_is_hard_failure(chain):
    root = b"\x22" * 32
    block = _block_with_commitments([b"\xaa" * 48])
    chain.on_blob_sidecar(root, 0, b"\xcc" * 48, slot=5)
    with pytest.raises(ValueError, match="mismatch"):
        chain._check_data_availability(block, root)


def test_commitment_free_blocks_unaffected(chain):
    chain._check_data_availability({"body": {}}, b"\x33" * 32)
    chain._check_data_availability(
        _block_with_commitments([]), b"\x33" * 32
    )


def test_availability_pruned_by_clock(chain):
    root = b"\x44" * 32
    chain.on_blob_sidecar(root, 0, b"\xaa" * 48, slot=3)
    chain.prune_pools(3 + params.SLOTS_PER_EPOCH + 1)
    with pytest.raises(BlobsUnavailableError):
        chain._check_data_availability(
            _block_with_commitments([b"\xaa" * 48]), root
        )


def test_parked_blocks_expire_with_the_window(chain):
    """Stale parked blocks must free their (bounded) parking slots
    (review r5 follow-up: _da_pending was never pruned)."""
    chain._da_pending.clear()
    chain._da_pending["aa" * 32] = {"message": {"slot": 3, "body": {}}}
    chain._da_pending["bb" * 32] = {
        "message": {"slot": 3 + 2 * params.SLOTS_PER_EPOCH, "body": {}}
    }
    chain.prune_pools(3 + params.SLOTS_PER_EPOCH + 1)
    assert "aa" * 32 not in chain._da_pending  # expired
    assert "bb" * 32 in chain._da_pending      # still in the window
    chain._da_pending.clear()


def test_parking_is_bounded(chain, monkeypatch):
    """The PRODUCTION import path refuses the N+1th park (drives
    _process_block_inner's guard, not a test-side simulation)."""
    monkeypatch.setattr(chain, "_da_pending", {})
    monkeypatch.setattr(chain, "_da_pending_max", 2)
    for i in range(3):
        body = {"blob_kzg_commitments": [bytes([i]) * 48]}
        block = {"slot": 9, "body": body}
        with pytest.raises(BlobsUnavailableError):
            chain._process_block_inner(
                {"message": block}, block, bytes([i]) * 32, timely=False
            )
    assert len(chain._da_pending) == 2  # third park refused by the guard
