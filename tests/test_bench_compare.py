"""dev/bench_compare.py: run-over-run trajectory diff (ISSUE 12
satellite) — per-metric delta table, explicit skipped/null handling
(the r03–r05 shapes), nonzero exit on regression."""

import importlib.util
import json
import sys
from pathlib import Path

import pytest

pytestmark = pytest.mark.smoke

_SPEC = importlib.util.spec_from_file_location(
    "bench_compare",
    Path(__file__).resolve().parent.parent / "dev" / "bench_compare.py",
)
bench_compare = importlib.util.module_from_spec(_SPEC)
sys.modules.setdefault("bench_compare", bench_compare)
_SPEC.loader.exec_module(bench_compare)

REPO = Path(__file__).resolve().parent.parent


def _round(path, tail_records=None, parsed=None, rc=0):
    tail = ""
    if tail_records is not None:
        tail = "\n".join(
            ["# some stderr noise"] + [json.dumps(r) for r in tail_records]
        )
    path.write_text(
        json.dumps({"n": 1, "cmd": "bench", "rc": rc, "tail": tail,
                    "parsed": parsed})
    )
    return str(path)


def test_extracts_multi_record_tail_and_parsed_fallback(tmp_path):
    multi = _round(
        tmp_path / "BENCH_r07.json",
        tail_records=[
            {"metric": "a_per_s", "value": 10.0, "unit": "s"},
            {"metric": "b_per_s", "value": None, "skipped": True,
             "error": "probe: dead"},
        ],
    )
    legacy = _round(
        tmp_path / "BENCH_r01.json",
        parsed={"metric": "a_per_s", "value": 9.0, "unit": "s"},
    )
    recs = bench_compare.extract_records(json.loads(Path(multi).read_text()))
    assert recs["a_per_s"]["value"] == 10.0
    assert recs["b_per_s"]["skipped"] and recs["b_per_s"]["value"] is None
    recs = bench_compare.extract_records(json.loads(Path(legacy).read_text()))
    assert recs["a_per_s"]["value"] == 9.0


def test_legacy_error_zero_counts_as_skip():
    """r04/r05 published value 0.0 WITH an error field before the skip
    schema existed; treating that as a measured zero would claim a
    100% regression."""
    rec = bench_compare._normalize(
        {"metric": "x", "value": 0.0, "error": "backend-init-probe: dead"}
    )
    assert rec["skipped"] and rec["value"] is None
    # an honestly measured zero (no error) stays a measurement
    rec = bench_compare._normalize({"metric": "x", "value": 0.0})
    assert not rec["skipped"] and rec["value"] == 0.0


def test_malformed_value_degrades_to_skip_not_crash(tmp_path):
    """Review fix: a record whose value is a non-numeric string (or a
    dict) must become a skip cell, not a traceback."""
    rec = bench_compare._normalize({"metric": "m", "value": "err"})
    assert rec["skipped"] and rec["value"] is None
    assert "unparseable value" in rec["error"]
    rec = bench_compare._normalize({"metric": "m", "value": {"nested": 1}})
    assert rec["skipped"]
    r1 = _round(
        tmp_path / "BENCH_r01.json", parsed={"metric": "m", "value": "err"}
    )
    r2 = _round(
        tmp_path / "BENCH_r02.json", parsed={"metric": "m", "value": 5.0}
    )
    assert bench_compare.main([r1, r2]) == 0  # one measurement, no delta


def test_dead_and_skip_rounds_excluded_from_delta(tmp_path):
    r1 = _round(
        tmp_path / "BENCH_r01.json",
        parsed={"metric": "m", "value": 100.0},
    )
    r2 = _round(tmp_path / "BENCH_r02.json", parsed=None, rc=1)  # r03 shape
    r3 = _round(
        tmp_path / "BENCH_r03.json",
        tail_records=[
            {"metric": "m", "value": None, "skipped": True, "error": "x"}
        ],
    )
    r4 = _round(
        tmp_path / "BENCH_r04.json",
        tail_records=[{"metric": "m", "value": 101.0}],
    )
    table = bench_compare.build_table([r1, r2, r3, r4])
    states = [c["state"] for c in table["metrics"]["m"]]
    assert states == ["measured", "dead", "skip", "measured"]
    d = bench_compare.deltas(table)["m"]
    # the delta steps over the dead/skip rounds: r01 -> r04
    assert d["prev_round"] == "r01" and d["last_round"] == "r04"
    assert d["ratio"] == pytest.approx(1.01)


def test_regression_beyond_threshold_exits_nonzero(tmp_path, capsys):
    r1 = _round(
        tmp_path / "BENCH_r01.json", parsed={"metric": "m", "value": 100.0}
    )
    r2 = _round(
        tmp_path / "BENCH_r02.json", parsed={"metric": "m", "value": 80.0}
    )
    rc = bench_compare.main([r1, r2, "--threshold", "0.05"])
    assert rc == 1
    err = capsys.readouterr().err
    assert "REGRESSION m" in err and "-20.0%" in err
    # a generous threshold tolerates the same drop
    assert bench_compare.main([r1, r2, "--threshold", "0.25"]) == 0
    # improvements always pass
    assert bench_compare.main([r2, r1, "--threshold", "0.05"]) == 0


def test_time_metrics_gate_inverts_direction(tmp_path, capsys):
    """Review fix: bls_rlc_bisect_seconds (unit 's') is lower-is-better
    — growing is the regression, shrinking is the improvement."""
    r1 = _round(
        tmp_path / "BENCH_r01.json",
        tail_records=[
            {"metric": "bls_rlc_bisect_seconds", "value": 1.0, "unit": "s"}
        ],
    )
    r2 = _round(
        tmp_path / "BENCH_r02.json",
        tail_records=[
            {"metric": "bls_rlc_bisect_seconds", "value": 2.0, "unit": "s"}
        ],
    )
    assert bench_compare.main([r1, r2, "--threshold", "0.05"]) == 1
    assert "time grew" in capsys.readouterr().err
    # the same ratio the other way round is an improvement
    assert bench_compare.main([r2, r1, "--threshold", "0.05"]) == 0


def test_json_output_shape(tmp_path, capsys):
    r1 = _round(
        tmp_path / "BENCH_r01.json", parsed={"metric": "m", "value": 100.0}
    )
    r2 = _round(
        tmp_path / "BENCH_r02.json", parsed={"metric": "m", "value": 50.0}
    )
    rc = bench_compare.main([r1, r2, "--json"])
    assert rc == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["rounds"] == ["r01", "r02"]
    assert doc["regressions"] == ["m"]
    assert doc["deltas"]["m"]["ratio"] == pytest.approx(0.5)
    assert doc["metrics"]["m"][0]["state"] == "measured"


def test_no_files_is_usage_error(tmp_path, monkeypatch, capsys):
    monkeypatch.chdir(tmp_path)
    assert bench_compare.main([]) == 2


def test_single_measurement_yields_no_delta(tmp_path):
    r1 = _round(
        tmp_path / "BENCH_r01.json", parsed={"metric": "m", "value": 100.0}
    )
    table = bench_compare.build_table([r1])
    assert bench_compare.deltas(table)["m"] is None
    assert bench_compare.main([r1]) == 0


def test_real_repo_rounds_parse_clean():
    """The archived r01–r05 artifacts themselves: r03 dead, r04/r05
    legacy-error-zero skips, r01→r02 measured delta, exit 0."""
    paths = sorted(str(p) for p in REPO.glob("BENCH_r0*.json"))
    if len(paths) < 5:  # future re-anchors may prune artifacts
        pytest.skip("archived bench rounds not present")
    table = bench_compare.build_table(paths)
    row = table["metrics"]["bls_signature_sets_verified_per_s"]
    states = [c["state"] for c in row]
    assert states[:5] == ["measured", "measured", "dead", "skip", "skip"]
    d = bench_compare.deltas(table)["bls_signature_sets_verified_per_s"]
    assert d["prev_round"] == "r01" and d["last_round"] == "r02"
    assert bench_compare.main(paths) == 0


def test_effective_atts_metric_direction_registered(tmp_path, capsys):
    """ISSUE 13 satellite: `bls_pipeline_effective_atts_per_s` is a
    throughput metric — a drop beyond threshold exits 1, a rise exits 0,
    and the direction holds even when archived cells lost their unit
    (the _METRIC_UNITS registry pins it)."""
    m = "bls_pipeline_effective_atts_per_s"
    assert bench_compare._METRIC_UNITS[m] == "atts/s"
    drop = [
        _round(tmp_path / "BENCH_r01.json",
               tail_records=[{"metric": m, "value": 9000.0,
                              "unit": "atts/s"}]),
        _round(tmp_path / "BENCH_r02.json",
               tail_records=[{"metric": m, "value": 4000.0,
                              "unit": "atts/s"}]),
    ]
    assert bench_compare.main(drop + ["--threshold", "0.05"]) == 1
    capsys.readouterr()
    rise = [
        _round(tmp_path / "BENCH_r03.json",
               tail_records=[{"metric": m, "value": 4000.0}]),  # no unit
        _round(tmp_path / "BENCH_r04.json",
               tail_records=[{"metric": m, "value": 9000.0}]),
    ]
    assert bench_compare.main(rise + ["--threshold", "0.05"]) == 0
    capsys.readouterr()
    unitless_drop = [
        _round(tmp_path / "BENCH_r05.json",
               tail_records=[{"metric": m, "value": 9000.0}]),
        _round(tmp_path / "BENCH_r06.json",
               tail_records=[{"metric": m, "value": 4000.0}]),
    ]
    assert bench_compare.main(unitless_drop + ["--threshold", "0.05"]) == 1
    capsys.readouterr()


def test_state_roots_device_metric_direction_registered(tmp_path, capsys):
    """ISSUE 16 satellite: `state_roots_per_s_device` is a throughput
    metric — a drop beyond threshold exits 1, a rise exits 0, even when
    archived cells lost their unit (the registry pins roots/s)."""
    m = "state_roots_per_s_device"
    assert bench_compare._METRIC_UNITS[m] == "roots/s"
    drop = [
        _round(tmp_path / "BENCH_r01.json",
               tail_records=[{"metric": m, "value": 50.0}]),  # no unit
        _round(tmp_path / "BENCH_r02.json",
               tail_records=[{"metric": m, "value": 20.0}]),
    ]
    assert bench_compare.main(drop + ["--threshold", "0.05"]) == 1
    capsys.readouterr()
    rise = [
        _round(tmp_path / "BENCH_r03.json",
               tail_records=[{"metric": m, "value": 20.0,
                              "unit": "roots/s"}]),
        _round(tmp_path / "BENCH_r04.json",
               tail_records=[{"metric": m, "value": 50.0}]),
    ]
    assert bench_compare.main(rise + ["--threshold", "0.05"]) == 0
    capsys.readouterr()


def test_regen_pressure_metric_direction_registered(tmp_path, capsys):
    """ISSUE 15 satellite: `regen_under_pressure_states_per_s` is a
    throughput floor — a drop beyond threshold exits 1 even when the
    archived cells lost their unit (the registry pins states/s)."""
    m = "regen_under_pressure_states_per_s"
    assert bench_compare._METRIC_UNITS[m] == "states/s"
    drop = [
        _round(tmp_path / "BENCH_r01.json",
               tail_records=[{"metric": m, "value": 20.0}]),  # no unit
        _round(tmp_path / "BENCH_r02.json",
               tail_records=[{"metric": m, "value": 8.0}]),
    ]
    assert bench_compare.main(drop + ["--threshold", "0.05"]) == 1
    capsys.readouterr()
    rise = [
        _round(tmp_path / "BENCH_r03.json",
               tail_records=[{"metric": m, "value": 8.0,
                              "unit": "states/s"}]),
        _round(tmp_path / "BENCH_r04.json",
               tail_records=[{"metric": m, "value": 20.0}]),
    ]
    assert bench_compare.main(rise + ["--threshold", "0.05"]) == 0
    capsys.readouterr()


def test_aggregate_forward_metric_directions_registered(tmp_path, capsys):
    """ISSUE 19 satellite: `gossip_bytes_per_verified_att` regresses UP
    (bytes are lower-is-better — a rise beyond threshold exits 1, a
    drop exits 0, even unit-less via the registry) while
    `aggregate_forward_factor` is a ratio — a drop regresses."""
    m = "gossip_bytes_per_verified_att"
    assert bench_compare._METRIC_UNITS[m] == "bytes/att"
    assert "bytes/att" in bench_compare._LOWER_IS_BETTER_UNITS
    grow = [
        _round(tmp_path / "BENCH_r01.json",
               tail_records=[{"metric": m, "value": 100.0,
                              "unit": "bytes/att"}]),
        _round(tmp_path / "BENCH_r02.json",
               tail_records=[{"metric": m, "value": 400.0,
                              "unit": "bytes/att"}]),
    ]
    assert bench_compare.main(grow + ["--threshold", "0.05"]) == 1
    capsys.readouterr()
    # the same ratio the other way round is the improvement the ISSUE
    # 19 tentpole buys; unit-less cells resolve through the registry
    shrink = [
        _round(tmp_path / "BENCH_r03.json",
               tail_records=[{"metric": m, "value": 400.0}]),  # no unit
        _round(tmp_path / "BENCH_r04.json",
               tail_records=[{"metric": m, "value": 100.0}]),
    ]
    assert bench_compare.main(shrink + ["--threshold", "0.05"]) == 0
    capsys.readouterr()
    f = "aggregate_forward_factor"
    assert bench_compare._METRIC_UNITS[f] == "ratio"
    factor_drop = [
        _round(tmp_path / "BENCH_r05.json",
               tail_records=[{"metric": f, "value": 6.0, "unit": "ratio"}]),
        _round(tmp_path / "BENCH_r06.json",
               tail_records=[{"metric": f, "value": 2.0}]),  # unit-less
    ]
    assert bench_compare.main(factor_drop + ["--threshold", "0.05"]) == 1
    capsys.readouterr()


def test_unitless_time_metric_direction_resolved_by_registry(tmp_path, capsys):
    """A unit-less bls_rlc_bisect_seconds GROWTH still gates (the
    registry knows it is lower-is-better)."""
    m = "bls_rlc_bisect_seconds"
    grow = [
        _round(tmp_path / "BENCH_r01.json",
               tail_records=[{"metric": m, "value": 1.0}]),
        _round(tmp_path / "BENCH_r02.json",
               tail_records=[{"metric": m, "value": 3.0}]),
    ]
    assert bench_compare.main(grow + ["--threshold", "0.05"]) == 1
    capsys.readouterr()

def test_breaker_recovery_metric_direction_registered(tmp_path, capsys):
    """ISSUE 14 satellite: `bls_device_fault_recovery_seconds` is a
    time metric — GROWTH beyond threshold regresses (exit 1), shrink
    passes, and the registry pins the direction for unit-less cells."""
    m = "bls_device_fault_recovery_seconds"
    assert bench_compare._METRIC_UNITS[m] == "s"
    grow = [
        _round(tmp_path / "BENCH_r01.json",
               tail_records=[{"metric": m, "value": 0.5, "unit": "s"}]),
        _round(tmp_path / "BENCH_r02.json",
               tail_records=[{"metric": m, "value": 2.0}]),  # unit-less
    ]
    assert bench_compare.main(grow + ["--threshold", "0.05"]) == 1
    capsys.readouterr()
    shrink = [
        _round(tmp_path / "BENCH_r03.json",
               tail_records=[{"metric": m, "value": 2.0}]),
        _round(tmp_path / "BENCH_r04.json",
               tail_records=[{"metric": m, "value": 0.5}]),
    ]
    assert bench_compare.main(shrink + ["--threshold", "0.05"]) == 0
    capsys.readouterr()
