"""guarded-by positives: fields written under a lock on one
thread/task root but touched lock-free from another root."""

import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0
        self._worker = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        while True:
            with self._lock:
                self._count += 1

    def snapshot(self):
        return self._count  # lock-free read raced with the worker

    def reset(self):
        self._count = 0  # lock-free write raced with the worker


class TickState:
    def __init__(self):
        self._lock = threading.Lock()
        self._slot = 0

    def on_slot(self, slot):  # clock-tick root
        with self._lock:
            self._slot = slot

    def describe(self):
        return str(self._slot)  # lock-free read from the API thread
