"""async-lock-safety positives: callback / blocking / settle inside a
critical section, and a threading lock acquired in a coroutine."""

import threading
import time


class Notifier:
    def __init__(self, on_drop):
        self.on_drop = on_drop
        self._lock = threading.Lock()
        self._dropped = 0

    def drop(self, item):
        with self._lock:
            self._dropped += 1
            self.on_drop(item)  # user callback under the lock


class Slow:
    def __init__(self):
        self._lock = threading.Lock()

    def wait_for_device(self, fut):
        with self._lock:
            time.sleep(0.1)  # blocking sleep under the lock
            return fut.result()  # device round-trip under the lock


class Settler:
    def __init__(self):
        self._lock = threading.Lock()
        self._done = 0

    def complete(self, fut):
        with self._lock:
            self._done += 1
            fut.set_result(True)  # done-callbacks run in-section


class AsyncAcquire:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    async def handle(self):
        with self._lock:  # threading lock in a coroutine
            self._n += 1
