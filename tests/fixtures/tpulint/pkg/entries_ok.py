"""fingerprint-completeness negatives: every traced out-of-kernels
module is registered (clears the entries_bad finding), and in-kernels
traced functions need no registration at all."""


def register_entry(name, builder, source=None, sources=None):
    """Stand-in registry (the rule matches the call by name)."""


def _builder():
    from .extmod import span_specs

    return span_specs()


def _kernels_builder():
    from .kernels.kmod import kernel_entry_specs

    return kernel_entry_specs()


register_entry(
    "fixture_span_update_ok",
    _builder,
    sources=("pkg.extmod", "pkg.extdep"),
)

register_entry("fixture_kernels_entry", _kernels_builder)


# RLC-style multi-entry-point registration: SEVERAL entries (batch +
# per-set retry of one pipeline) tracing the same out-of-kernels module
# graph, each declaring the complete source set independently.
def _rlc_batch_builder():
    from .extmod import span_specs

    return span_specs()


def _rlc_each_builder():
    from .extmod import span_specs

    return span_specs()


register_entry(
    "fixture_rlc_batch_ok",
    _rlc_batch_builder,
    sources=("pkg.extmod", "pkg.extdep"),
)
register_entry(
    "fixture_rlc_each_ok",
    _rlc_each_builder,
    sources=("pkg.extmod", "pkg.extdep"),
)


# bucketed-entry negatives: every statically-readable bucket-table
# spelling resolves (call-site literal with arithmetic, a module-level
# constant, and a constant imported from ANOTHER module) and a
# well-formed strictly-increasing table produces no findings.
def bucketed_entry(name, builder, buckets, source=None, sources=None):
    """Stand-in bucketed registry (the rule matches the call by name)."""


from .extmod import SPAN_BUCKETS  # noqa: E402

_LOCAL_BUCKETS = (128,) + (512, 2048)


def _bucketed_builder(bucket):
    from .extmod import span_specs

    return span_specs()


bucketed_entry(
    "fixture_bucketed_literal_ok",
    _bucketed_builder,
    buckets=(64, 2 * 128),
    sources=("pkg.extmod", "pkg.extdep"),
)
bucketed_entry(
    "fixture_bucketed_const_ok",
    _bucketed_builder,
    buckets=_LOCAL_BUCKETS,
    sources=("pkg.extmod", "pkg.extdep"),
)
bucketed_entry(
    "fixture_bucketed_imported_ok",
    _bucketed_builder,
    buckets=SPAN_BUCKETS,
    sources=("pkg.extmod", "pkg.extdep"),
)
