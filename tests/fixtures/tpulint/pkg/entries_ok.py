"""fingerprint-completeness negatives: every traced out-of-kernels
module is registered (clears the entries_bad finding), and in-kernels
traced functions need no registration at all."""


def register_entry(name, builder, source=None, sources=None):
    """Stand-in registry (the rule matches the call by name)."""


def _builder():
    from .extmod import span_specs

    return span_specs()


def _kernels_builder():
    from .kernels.kmod import kernel_entry_specs

    return kernel_entry_specs()


register_entry(
    "fixture_span_update_ok",
    _builder,
    sources=("pkg.extmod", "pkg.extdep"),
)

register_entry("fixture_kernels_entry", _kernels_builder)
