"""dtype-discipline positives."""

import jax
import jax.numpy as jnp


@jax.jit
def sloppy_ctor(x):
    pad = jnp.zeros((4, 4))  # BAD: dtype-less constructor
    lane = jnp.arange(4)  # BAD: dtype-less arange
    return x + pad + lane


@jax.jit
def wide_mask(x):
    return x & 0xFFFFFFFFFFFFFFFF  # BAD: 64-bit literal on traced value
