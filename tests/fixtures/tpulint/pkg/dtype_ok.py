"""dtype-discipline negatives."""

import jax
import jax.numpy as jnp


@jax.jit
def explicit_ctor(x):
    pad = jnp.zeros((4, 4), jnp.int32)
    lane = jnp.arange(4, dtype=jnp.int32)
    return x + pad + lane


@jax.jit
def static_mask(x, e: int):
    # python-int math on a static param stays host-side: fine
    word = (e >> 32) & 0xFFFFFFFF
    return x * jnp.int32(word & 0x7FFF)


def host_ctor():
    # not traced: implicit dtypes are numpy's problem, not Mosaic's
    return jnp.zeros((4, 4))
