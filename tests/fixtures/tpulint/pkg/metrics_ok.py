"""metric-hygiene negatives: prefixed names, parity families, bounded
labels, prefix-variable concatenation, benign plain-gauge sets."""


class _FakeRegistry:
    def counter(self, name, help_):
        return self

    def gauge(self, name, help_):
        return self

    def labeled_counter(self, name, help_, label):
        return self

    def labeled_histogram(self, name, help_, label, buckets):
        return self

    def observe(self, label_value, v):
        pass

    def set(self, v):
        pass


def register(r: _FakeRegistry, phase: str):
    p = "lodestar_fixture_"
    ok = r.counter(p + "events_total", "prefix via variable concat")
    # reference-parity families are allowlisted (dashboards expect them)
    r.gauge("beacon_head_slot_fixture", "parity family")
    r.gauge("validator_monitor_fixture_total", "parity family")
    # a bounded label dimension, observed with a bounded value
    hist = r.labeled_histogram(
        "lodestar_fixture_phase_seconds", "timings", "phase", [0.1, 1.0]
    )
    hist.observe(phase, 0.5)
    # the SAME name re-registered with the SAME signature is idempotent
    r.labeled_counter("lodestar_fixture_verdicts_total", "verdicts", "kind")
    r.labeled_counter("lodestar_fixture_verdicts_total", "verdicts", "kind")
    # a plain gauge set(value) is not a label write
    gauge = r.gauge("lodestar_fixture_depth", "queue depth")
    gauge.set(3.0)
    return ok
