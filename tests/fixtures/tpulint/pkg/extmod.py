"""Out-of-kernels traced module used by the entries_* fixtures."""

import jax.numpy as jnp

from .extdep import SENTINEL

# a shape-bucket table another module's bucketed_entry call can name
# (the engine must resolve it cross-module, arithmetic included)
SPAN_BUCKETS = (2 * 8, 64, 512)


def span_fn(mins, maxs):
    return jnp.minimum(mins, jnp.int32(SENTINEL)), maxs


def span_specs():
    import jax

    shape = (16, 16)
    return span_fn, [
        jax.ShapeDtypeStruct(shape, jnp.int32),
        jax.ShapeDtypeStruct(shape, jnp.int32),
    ]
