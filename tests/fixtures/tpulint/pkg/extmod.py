"""Out-of-kernels traced module used by the entries_* fixtures."""

import jax.numpy as jnp

from .extdep import SENTINEL


def span_fn(mins, maxs):
    return jnp.minimum(mins, jnp.int32(SENTINEL)), maxs


def span_specs():
    import jax

    shape = (16, 16)
    return span_fn, [
        jax.ShapeDtypeStruct(shape, jnp.int32),
        jax.ShapeDtypeStruct(shape, jnp.int32),
    ]
