"""kernel-purity positives: every pattern here must be flagged."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

_TABLE = np.arange(64).reshape(8, 8)  # module-level array constant


def _kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] + _TABLE  # BAD: captured array constant


def launch(x):
    return pl.pallas_call(
        _kernel, out_shape=jax.ShapeDtypeStruct((8, 8), jnp.int32)
    )(x)


@jax.jit
def scalarize(x):
    return x.item()  # BAD: host sync under trace


@jax.jit
def concretize(x):
    return int(x) + 1  # BAD: int() on a traced parameter


@jax.jit
def branchy(x):
    if x:  # BAD: Python if on traced truthiness
        return x + 1
    return x
