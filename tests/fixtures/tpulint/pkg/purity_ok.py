"""kernel-purity negatives: nothing here may be flagged.

Host-side helpers may do anything; traced code using the sanctioned
patterns (scalar np casts, static params, jnp.where) is clean.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

_HOST_TABLE = np.arange(64).reshape(8, 8)
_SCALE = np.int32(3)  # scalar constant: capturing is fine


def host_pack(vals):
    # not kernel-reachable: array constants / .item() are host business
    acc = (_HOST_TABLE * 2).sum()
    return int(acc) + vals[0].item() if hasattr(vals[0], "item") else acc


def _kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * _SCALE  # scalar capture: allowed


def launch(x):
    return pl.pallas_call(
        _kernel, out_shape=jax.ShapeDtypeStruct((8, 8), jnp.int32)
    )(x)


@jax.jit
def select(x):
    return jnp.where(x > 0, x, -x)


@jax.jit
def static_branch(x, flip: bool = False):
    if flip:  # static python param: fine
        return -x
    return x
