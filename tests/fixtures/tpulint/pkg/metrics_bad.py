"""metric-hygiene positives: unprefixed name, conflicting duplicate
registration, unbounded label name, unbounded label value."""


class _FakeRegistry:
    """Shaped like utils/metrics.Registry; the rule keys on the method
    names, not the type."""

    def counter(self, name, help_):
        return self

    def gauge(self, name, help_):
        return self

    def labeled_gauge(self, name, help_, label):
        return self

    def labeled_histogram(self, name, help_, label, buckets):
        return self

    def observe(self, label_value, v):
        pass


def register(r: _FakeRegistry, peer_id: str):
    # unprefixed name: collides with other exporters
    bad_prefix = r.counter("verified_messages_total", "no family prefix")
    # ONE name, two different instrument types: the Registry dedupes by
    # name first-wins, so the gauge site silently gets the counter
    r.counter("lodestar_dup_series_total", "first registration")
    r.gauge("lodestar_dup_series_total", "conflicting re-registration")
    # a per-peer label dimension grows the exposition without bound
    per_peer = r.labeled_gauge(
        "lodestar_peer_lag_seconds", "per-peer lag", "peer_id"
    )
    # an unbounded label VALUE on an otherwise fine dimension
    hist = r.labeled_histogram(
        "lodestar_fixture_seconds", "timings", "stage", [0.1, 1.0]
    )
    hist.observe(f"stage-{peer_id}", 0.5)
    return bad_prefix, per_peer
