"""lock-order negatives: consistent ordering, re-entrant RLock, and a
lock handed to a helper function (untracked by design)."""

import threading


class Consistent:
    def __init__(self):
        self._lock_a = threading.Lock()
        self._lock_b = threading.Lock()

    def one(self):
        with self._lock_a:
            with self._lock_b:
                return 1

    def two(self):
        with self._lock_a:
            with self._lock_b:
                return 2


class Reentrant:
    def __init__(self):
        self._lock = threading.RLock()

    def _helper(self):
        with self._lock:  # RLock: re-entry from outer() is fine
            return 1

    def outer(self):
        with self._lock:
            return self._helper()


def _sum_under(lock, items):
    # a lock received as a parameter is not tracked (documented blind
    # spot) — no self-deadlock or ordering finding may fire here
    with lock:
        return sum(items)


class Handoff:
    def __init__(self):
        self._lock = threading.Lock()
        self._values = [1, 2, 3]

    def total(self):
        return _sum_under(self._lock, self._values)
