"""node-hygiene device-dispatch-bypass negatives: the supervisor module
IS the seam — it may touch dispatch directly, even in async bodies."""


async def canary_probe(KV, args, valid):
    return KV.verify_each_device(*args, valid)  # exempt: supervisor


def sync_dispatch(KV, args, valid):
    # dispatch from a SYNC function is out of scope for this check
    return KV.verify_batch_device(*args, valid)
