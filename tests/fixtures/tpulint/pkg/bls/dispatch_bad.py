"""node-hygiene device-dispatch-bypass positives (module lives under a
bls/ segment, not exempt)."""


async def validate_aggregate(KV, args, valid):
    # BAD: direct device dispatch in async node code bypasses the
    # breaker supervisor seam
    return KV.verify_each_device_wire(*args, valid)


async def warm_artifact(load_or_export, fn, specs):
    # BAD: bare-imported export-cache dispatch, same bypass
    call = load_or_export("each_wire", fn, specs)
    return call
