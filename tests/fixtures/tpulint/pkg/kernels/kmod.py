"""An in-kernels traced module: entries tracing it need no sources."""

import jax.numpy as jnp


def kernel_entry_fn(x):
    return x * jnp.int32(2)


def kernel_entry_specs():
    import jax

    return kernel_entry_fn, [jax.ShapeDtypeStruct((8,), jnp.int32)]
