"""cache-hygiene positives in a proofs/ module: a proof-bundle cache
that memoizes rendered payloads per (kind, key) and never evicts,
invalidates, or drains — every head adds entries for the process
lifetime."""


class UnboundedBundleCache:
    """Bundle map grown per request, no bound, no invalidation."""

    def __init__(self):
        self.bundles = {}  # (kind, key) -> payload, grows forever
        self.recent_keys = []  # appended per request, never trimmed

    def put(self, kind, key, payload):
        self.bundles[(kind, key)] = payload
        self.recent_keys.append((kind, key))

    def get(self, kind, key):
        return self.bundles.get((kind, key))
