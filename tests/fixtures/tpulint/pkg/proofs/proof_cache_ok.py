"""cache-hygiene negatives in a proofs/ module: the bundle-cache
contract done right — bounded at insert, invalidated on events, and
drainable by a governor."""

from collections import OrderedDict


class GovernedBundleCache:
    """Count-bounded via a max_* constructor argument AND drainable."""

    def __init__(self, max_entries=128):
        self.max_entries = max_entries
        self.bundles = OrderedDict()

    def put(self, kind, key, payload):
        self.bundles[(kind, key)] = payload
        while len(self.bundles) > self.max_entries:
            self.bundles.popitem(last=False)

    def drain(self):
        self.bundles.clear()


class InvalidatedBundles:
    """Shrink methods reachable on the attribute itself."""

    def __init__(self):
        self.by_kind = {}

    def put(self, kind, key, payload):
        self.by_kind[(kind, key)] = payload

    def invalidate(self, kind):
        for k in [k for k in self.by_kind if k[0] == kind]:
            self.by_kind.pop(k)
