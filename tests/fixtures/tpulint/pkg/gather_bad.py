"""gather-hazard positives."""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _kernel(x_ref, idx_ref, o_ref):
    x = x_ref[...]
    rows = idx_ref[...]
    o_ref[...] = x[x > 0]  # BAD: boolean-mask indexing
    o_ref[...] = x[rows, rows]  # BAD: 2-D advanced indexing


def launch(x, idx):
    return pl.pallas_call(
        _kernel, out_shape=jax.ShapeDtypeStruct((8, 8), jnp.int32)
    )(x, idx)
