"""fingerprint-completeness positive: an export-cache entry whose
traced function lives outside kernels/ with NO registered sources —
an edit to pkg/extmod.py or pkg/extdep.py would silently run a stale
artifact."""


def register_entry(name, builder, source=None, sources=None):
    """Stand-in registry (the rule matches the call by name)."""


def _builder():
    from .extmod import span_specs

    return span_specs()


register_entry("fixture_span_update", _builder)  # BAD: no sources


# RLC-style multi-entry-point case: sibling entries share one traced
# module graph; the batch entry declares the COMPLETE set, the per-set
# entry forgets the transitive dep — only the incomplete sibling may be
# reported (a complete sibling must not mask it).
def _rlc_batch_builder():
    from .extmod import span_specs

    return span_specs()


def _rlc_each_builder():
    from .extmod import span_specs

    return span_specs()


register_entry(
    "fixture_rlc_batch",
    _rlc_batch_builder,
    sources=("pkg.extmod", "pkg.extdep"),
)
register_entry(
    "fixture_rlc_each",
    _rlc_each_builder,
    sources=("pkg.extmod",),  # BAD: pkg.extdep missing
)


# bucketed-entry positives: a dynamic (unreadable) bucket table, an
# empty one, and a misordered one.  Sources are complete so ONLY the
# bucket finding fires per entry.
def bucketed_entry(name, builder, buckets, source=None, sources=None):
    """Stand-in bucketed registry (the rule matches the call by name)."""


def _make_buckets():
    return (128, 512)


def _bucketed_builder(bucket):
    from .extmod import span_specs

    return span_specs()


bucketed_entry(
    "fixture_bucketed_dynamic",
    _bucketed_builder,
    buckets=_make_buckets(),  # BAD: not statically resolvable
    sources=("pkg.extmod", "pkg.extdep"),
)
bucketed_entry(
    "fixture_bucketed_empty",
    _bucketed_builder,
    buckets=(),  # BAD: no buckets to pre-trace
    sources=("pkg.extmod", "pkg.extdep"),
)
bucketed_entry(
    "fixture_bucketed_misordered",
    _bucketed_builder,
    buckets=(512, 128),  # BAD: not strictly increasing
    sources=("pkg.extmod", "pkg.extdep"),
)
