"""fingerprint-completeness positive: an export-cache entry whose
traced function lives outside kernels/ with NO registered sources —
an edit to pkg/extmod.py or pkg/extdep.py would silently run a stale
artifact."""


def register_entry(name, builder, source=None, sources=None):
    """Stand-in registry (the rule matches the call by name)."""


def _builder():
    from .extmod import span_specs

    return span_specs()


register_entry("fixture_span_update", _builder)  # BAD: no sources
