"""guarded-by negatives: locked reads, init-only config, a single
reachability root, and the `_locked`-suffix helper convention."""

import threading


class LockedCounter:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0
        self._worker = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        with self._lock:
            self._count += 1

    def snapshot(self):
        with self._lock:  # read under the same lock: no race
            return self._count


class InitOnly:
    def __init__(self):
        self._lock = threading.Lock()
        self._limit = 64  # construction-only: immutable thereafter

    def check(self, n):
        return n < self._limit


class SingleRoot:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def bump(self):
        with self._lock:
            self._n += 1

    def read(self):
        # lock-free, but every accessor runs on the same (external)
        # root — no cross-root race to report
        return self._n


class Convention:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []
        self._worker = threading.Thread(target=self._pump, daemon=True)

    def _pump(self):
        with self._lock:
            self._items.append(1)

    def _drain_locked(self):
        # runs with the lock held at every call site: the inferred
        # context makes these accesses guarded
        items = self._items
        self._items = []
        return items

    def drain(self):
        with self._lock:
            return self._drain_locked()
