"""Transitive dependency of extmod — must also be fingerprinted."""

SENTINEL = 32767
