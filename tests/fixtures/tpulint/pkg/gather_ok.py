"""gather-hazard negatives: slices, static indices, iota masks."""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _kernel(x_ref, o_ref):
    x = x_ref[...]
    acc = x[..., 0:1, :] * 2  # slices: fine
    for j in range(1, 4):
        acc = acc + x[..., j : j + 1, :]  # static loop index: fine
    mask = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1) < 3
    o_ref[...] = jnp.where(mask, acc, x)  # iota mask compare: fine


def launch(x):
    return pl.pallas_call(
        _kernel, out_shape=jax.ShapeDtypeStruct((8, 8), jnp.int32)
    )(x)


def host_gather(table, order):
    # not pallas-reachable: host-side numpy gathers are fine
    return table[order, order]
