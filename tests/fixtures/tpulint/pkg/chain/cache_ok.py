"""cache-hygiene negatives: every growth here has a reachable bound."""

# module-level: grown AND pruned
_WINDOW = {}


def record(slot, value):
    _WINDOW[slot] = value
    for old in [s for s in _WINDOW if s < slot - 8]:
        del _WINDOW[old]


# module-level: grown, rebuilt via a `global` reassignment
_RESETTABLE = {}


def fill(key, value):
    _RESETTABLE[key] = value


def reset():
    global _RESETTABLE
    _RESETTABLE = {}


class BoundedLru:
    """Count-bounded via a max_* constructor argument."""

    def __init__(self, max_entries=64):
        self.max_entries = max_entries
        self.entries = {}

    def add(self, key, value):
        self.entries[key] = value


class PrunedMap:
    """Shrink methods reachable on the attribute itself."""

    def __init__(self):
        self.seen = {}
        self.queue = []

    def add(self, key):
        self.seen[key] = True
        self.queue.append(key)

    def prune(self, horizon):
        for key in [k for k in self.seen if k < horizon]:
            self.seen.pop(key)
        self.queue.clear()


class AliasPruned:
    """Pruned through a local alias (the chain/validation.py shape)."""

    def __init__(self):
        self.lazy_seen = {}

    def add(self, key, slot):
        self.lazy_seen[key] = slot

    def prune(self, horizon):
        seen = getattr(self, "lazy_seen", None)
        if seen:
            for key in [k for k, s in seen.items() if s < horizon]:
                del seen[key]


class Rebuilt:
    """Reassigned outside the initializer: rebuilt, not unbounded."""

    def __init__(self):
        self.pending = []
        self.plain_state = {}  # never grown: state, not a cache

    def add(self, item):
        self.pending.append(item)

    def drain(self):
        out = self.pending
        self.pending = []
        return out
