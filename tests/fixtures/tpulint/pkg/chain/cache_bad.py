"""cache-hygiene positives: unbounded caches in a chain/ module."""

from collections import OrderedDict

# module-level cache grown in a function, never shrunk or rebuilt
_SEEN_ROOTS = {}


def remember(root, value):
    _SEEN_ROOTS[root] = value


def unrelated_local():
    # a LOCAL dict named like the global, pruned: must NOT bound the
    # module-level _SEEN_ROOTS above (scoping regression case)
    _SEEN_ROOTS = {"x": 1}
    _SEEN_ROOTS.pop("x")
    return _SEEN_ROOTS


class BlockIndex:
    """The block_state_roots shape: populated per import, pruned never."""

    def __init__(self):
        self.block_map = {}  # grows in on_block, no bound anywhere
        self.recent = []  # appended forever
        self.ordered = OrderedDict()  # setdefault-grown, never popped

    def on_block(self, root, state_root):
        self.block_map[root] = state_root
        self.recent.append(root)
        self.ordered.setdefault(root, state_root)

    def lookup(self, root):
        return self.block_map.get(root)
