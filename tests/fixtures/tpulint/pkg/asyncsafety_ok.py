"""async-lock-safety negatives: the swap-and-fire contract (capture
under the lock, invoke after release), slow work outside the critical
section, and Condition wait/notify."""

import threading
import time


class SwapAndFire:
    def __init__(self):
        self._lock = threading.Lock()
        self._callbacks = []

    def subscribe(self, on_done):
        with self._lock:
            self._callbacks.append(on_done)  # captured, not invoked

    def fire(self):
        with self._lock:
            callbacks, self._callbacks = self._callbacks, []
        for cb in callbacks:
            cb(None)  # fired after release


class OutsideWork:
    def __init__(self):
        self._lock = threading.Lock()
        self._pending = 0

    def run(self, fut):
        with self._lock:
            self._pending += 1
        time.sleep(0)  # blocking, but the lock is released
        res = fut.result()  # ditto
        with self._lock:
            self._pending -= 1
        return res


class ConditionWait:
    def __init__(self):
        self._cv = threading.Condition()
        self._ready = False

    def wait_ready(self):
        with self._cv:
            while not self._ready:
                self._cv.wait()

    def set_ready(self):
        with self._cv:
            self._ready = True
            self._cv.notify_all()
