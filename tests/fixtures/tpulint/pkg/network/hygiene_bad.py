"""node-hygiene positives (module lives under a network/ segment)."""

import time

import jax


def swallow_everything(fn):
    try:
        return fn()
    except:  # BAD: bare except
        return None


async def poll_peer(peer):
    time.sleep(0.1)  # BAD: blocking sleep in async body
    planes = jax.device_get(peer.planes)  # BAD: blocking transfer
    await peer.send(planes)


async def drain(queue):
    while True:
        item = queue.get()
        item.result().block_until_ready()  # BAD: device sync in async
        await queue.ack(item)


async def flush_traces(obs, path):
    doc = obs.dump_chrome_trace()  # BAD: O(ring) sink walk in async
    write_chrome_trace(path)  # BAD: blocking file IO sink (bare import)
    await obs.send(doc)


def write_chrome_trace(path):  # stand-in for the observability sink
    return path


async def handle_attestation(verifier, pipeline, ws, opts):
    fut = pipeline.verify_signature_sets_async([ws], opts)
    verdict = fut.result()  # BAD: sync verdict wait in async handler
    ok = verifier.verify_signature_sets([ws], opts)  # BAD: sync verify
    also = verify_signature_sets_individually([ws])  # BAD: bare import
    return verdict and ok and also


def verify_signature_sets_individually(sets):  # stand-in for the bare
    return [True] * len(sets)  # import form
