"""node-hygiene negatives."""

import asyncio
import time


def retry(fn):
    try:
        return fn()
    except Exception:  # named: fine
        return None


async def poll_peer(peer):
    await asyncio.sleep(0.1)
    await peer.send(b"ping")


def sync_helper():
    time.sleep(0.01)  # blocking in a SYNC function: fine


def dump_traces_sync(obs, path):
    # blocking sinks in a SYNC function: fine
    return obs.dump_chrome_trace()


async def traced_poll(peer, trace_span):
    # opening a span in async code is fine — only the SINKS block
    with trace_span("network.poll", peer=str(peer)):
        await peer.send(b"ping")


def handle_attestation_sync(verifier, ws, opts):
    # sync verify in a SYNC function (the AGGFWD=0 escape hatch's
    # raw-sync handler path): fine
    return verifier.verify_signature_sets([ws], opts)


async def handle_attestation_deferred(pipeline, deferred, ws, opts):
    # the async seam: submit, register the continuation, never block
    fut = pipeline.verify_signature_sets_async([ws], opts)
    fut.add_done_callback(lambda f: deferred.resolve(None))
    await pipeline.flush_soon()
