"""node-hygiene negatives."""

import asyncio
import time


def retry(fn):
    try:
        return fn()
    except Exception:  # named: fine
        return None


async def poll_peer(peer):
    await asyncio.sleep(0.1)
    await peer.send(b"ping")


def sync_helper():
    time.sleep(0.01)  # blocking in a SYNC function: fine
