"""lock-order positives: a direct inversion, an inversion hidden
behind a call, and a plain-Lock self-deadlock through a helper."""

import threading


class Transfer:
    def __init__(self):
        self._lock_a = threading.Lock()
        self._lock_b = threading.Lock()

    def a_then_b(self):
        with self._lock_a:
            with self._lock_b:
                return 1

    def b_then_a(self):
        with self._lock_b:
            with self._lock_a:
                return 2


class Chained:
    def __init__(self):
        self._front = threading.Lock()
        self._back = threading.Lock()

    def _take_back(self):
        with self._back:
            return 0

    def front_path(self):
        with self._front:
            return self._take_back()

    def back_path(self):
        with self._back:
            with self._front:
                return 1


class SelfDeadlock:
    def __init__(self):
        self._lock = threading.Lock()

    def _helper(self):
        with self._lock:
            return 1

    def outer(self):
        with self._lock:
            return self._helper()
