"""Suppression parsing: valid (with reason), missing reason, unknown rule."""

import jax
import jax.numpy as jnp


@jax.jit
def justified(x):
    pad = jnp.zeros((4, 4))  # tpulint: disable=dtype-discipline -- fixture: proves suppression works
    return x + pad


@jax.jit
def reasonless(x):
    pad = jnp.zeros((4, 4))  # tpulint: disable=dtype-discipline
    return x + pad


@jax.jit
def unknown_rule(x):
    # tpulint: disable=made-up-rule -- reason text present
    lane = jnp.arange(4, dtype=jnp.int32)
    return x + lane
