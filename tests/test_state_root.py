"""Incremental state-root engine: ChunkTree vs merkleize_chunks, and
randomized BeaconState equivalence (incremental == full recompute)
across mutation / clone-on-write / epoch-boundary sequences.

The invariant under test is the engine's one correctness contract
(state_transition/state_root.py): dirty tracking is conservative — any
mutation, tracked or not, must surface in the next root, bit-identical
to the cold full merkleization.
"""

import random

import numpy as np
import pytest

from lodestar_tpu import params
from lodestar_tpu.config import MAINNET_CHAIN_CONFIG, create_chain_config
from lodestar_tpu.params import ForkName
from lodestar_tpu.ssz import ChunkTree, merkleize_chunks
from lodestar_tpu.state_transition.slot import process_slots
from lodestar_tpu.state_transition.state import BeaconState

P = params.ACTIVE_PRESET
FAR_FUTURE = params.FAR_FUTURE_EPOCH


# -- ChunkTree vs the batch merkleizer --------------------------------------


@pytest.mark.parametrize("limit", [1, 2, 3, 7, 64, 1 << 10, 1 << 16])
def test_chunk_tree_matches_merkleize_chunks(limit):
    rng = random.Random(limit)
    nprng = np.random.default_rng(limit)
    tree = ChunkTree(limit)
    n = 0
    plane = np.zeros((0, 32), np.uint8)
    for _step in range(25):
        op = rng.random()
        if op < 0.4 and n < min(limit, 300):
            n = min(limit, n + rng.randint(1, 9))
            grown = nprng.integers(0, 256, (n, 32), dtype=np.uint8)
            grown[: plane.shape[0]] = plane
            plane = grown
        elif op < 0.5 and n > 0:  # shrink: conservative full rebuild
            n = rng.randint(0, n)
            plane = plane[:n].copy()
        elif n > 0:  # mutate k rows
            idx = nprng.integers(0, n, rng.randint(1, max(1, n // 4)))
            plane[idx] = nprng.integers(0, 256, (idx.size, 32), dtype=np.uint8)
        tree.update(plane)
        ref = merkleize_chunks([bytes(plane[i]) for i in range(n)], limit)
        assert tree.root == ref


def test_chunk_tree_apply_unsorted_and_duplicate_indices():
    """apply() is the public low-level entry for callers with their own
    dirty sets: unsorted scatter must land on the right leaves and a
    duplicated index must take the LAST write."""
    nprng = np.random.default_rng(3)
    plane = nprng.integers(0, 256, (6, 32), dtype=np.uint8)
    tree = ChunkTree(64)
    tree.update(plane)
    plane[5] ^= 0x11
    plane[2] ^= 0x22
    tree.apply(np.array([5, 2], np.intp), plane[[5, 2]], 6)
    assert tree.root == merkleize_chunks(
        [bytes(plane[i]) for i in range(6)], 64
    )
    newrow = nprng.integers(0, 256, (1, 32), dtype=np.uint8)[0]
    plane[3] = newrow
    tree.apply(np.array([3, 3], np.intp), np.stack([plane[0], newrow]), 6)
    assert tree.root == merkleize_chunks(
        [bytes(plane[i]) for i in range(6)], 64
    )
    with pytest.raises(ValueError):
        tree.apply(np.array([1], np.intp), plane[[1, 2]], 6)


def test_chunk_tree_clone_is_copy_on_write():
    nprng = np.random.default_rng(7)
    plane = nprng.integers(0, 256, (50, 32), dtype=np.uint8)
    tree = ChunkTree(1 << 12)
    tree.update(plane)
    base_root = tree.root
    clone = tree.clone()
    mutated = plane.copy()
    mutated[13] ^= 0xFF
    clone.update(mutated)
    assert tree.root == base_root  # original untouched by the clone's write
    assert clone.root != base_root
    assert clone.root == merkleize_chunks(
        [bytes(mutated[i]) for i in range(50)], 1 << 12
    )


# -- synthetic states --------------------------------------------------------


def _synthetic_state(n_validators: int, seed: int = 0) -> BeaconState:
    """Columnar state with random-but-plausible registry content; no BLS
    validity needed for hashing."""
    cfg = create_chain_config(
        MAINNET_CHAIN_CONFIG, fork_epochs={ForkName.altair: 0}
    )
    rng = np.random.default_rng(seed)
    st = BeaconState(config=cfg)
    raw = rng.integers(0, 256, (n_validators, 48), dtype=np.uint8).tobytes()
    st.pubkeys = [raw[i * 48 : (i + 1) * 48] for i in range(n_validators)]
    craw = rng.integers(0, 256, (n_validators, 32), dtype=np.uint8).tobytes()
    st.withdrawal_credentials = [
        craw[i * 32 : (i + 1) * 32] for i in range(n_validators)
    ]
    st.effective_balance = np.full(
        n_validators, P.MAX_EFFECTIVE_BALANCE, np.uint64
    )
    st.slashed = np.zeros(n_validators, bool)
    st.activation_eligibility_epoch = np.zeros(n_validators, np.uint64)
    st.activation_epoch = np.zeros(n_validators, np.uint64)
    st.exit_epoch = np.full(n_validators, FAR_FUTURE, np.uint64)
    st.withdrawable_epoch = np.full(n_validators, FAR_FUTURE, np.uint64)
    st.balances = rng.integers(
        31_000_000_000, 33_000_000_000, n_validators
    ).astype(np.uint64)
    st.previous_epoch_participation = rng.integers(
        0, 8, n_validators
    ).astype(np.uint8)
    st.current_epoch_participation = rng.integers(0, 8, n_validators).astype(
        np.uint8
    )
    st.inactivity_scores = np.zeros(n_validators, np.uint64)
    return st


def _full_root(st: BeaconState) -> bytes:
    return st._container().hash_tree_root(st.to_value())


def _mutate_once(st: BeaconState, rng: random.Random, nprng) -> None:
    """One randomized mutation drawn from the real mutation surface."""
    n = st.num_validators
    op = rng.randrange(10)
    if op == 0:  # balance deltas (block ops / rewards)
        idx = nprng.integers(0, n, rng.randint(1, 8))
        for i in idx:
            st.increase_balance(int(i), rng.randint(1, 10_000))
    elif op == 1:  # participation flags (attestation processing)
        idx = nprng.integers(0, n, rng.randint(1, 8))
        st.current_epoch_participation[idx] |= np.uint8(
            1 << rng.randrange(3)
        )
    elif op == 2:  # slash (block op touching 4 columns + slashings)
        i = rng.randrange(n)
        st.slashed[i] = True
        st.withdrawable_epoch[i] = rng.randrange(1 << 20)
        st.slashings[rng.randrange(P.EPOCHS_PER_SLASHINGS_VECTOR)] += np.uint64(
            32_000_000_000
        )
    elif op == 3:  # registry growth
        st.add_validator(
            bytes(nprng.integers(0, 256, 48, dtype=np.uint8)),
            bytes(nprng.integers(0, 256, 32, dtype=np.uint8)),
            32_000_000_000,
        )
    elif op == 4:  # ejection-style exit writes
        i = rng.randrange(n)
        st.exit_epoch[i] = rng.randrange(1 << 20)
        st.withdrawable_epoch[i] = int(st.exit_epoch[i]) + 256
    elif op == 5:  # credential rotation (process_bls_to_execution_change)
        i = rng.randrange(n)
        st.withdrawal_credentials[i] = (
            b"\x01" + bytes(nprng.integers(0, 256, 31, dtype=np.uint8))
        )
    elif op == 6:  # per-slot root vectors + randao mix
        st.block_roots[rng.randrange(P.SLOTS_PER_HISTORICAL_ROOT)] = bytes(
            nprng.integers(0, 256, 32, dtype=np.uint8)
        )
        st.randao_mixes[rng.randrange(P.EPOCHS_PER_HISTORICAL_VECTOR)] = bytes(
            nprng.integers(0, 256, 32, dtype=np.uint8)
        )
    elif op == 7:  # small-field churn (header / eth1 / checkpoints)
        st.latest_block_header["state_root"] = bytes(
            nprng.integers(0, 256, 32, dtype=np.uint8)
        )
        st.eth1_data_votes.append(
            {
                "deposit_root": bytes(
                    nprng.integers(0, 256, 32, dtype=np.uint8)
                ),
                "deposit_count": rng.randrange(1 << 30),
                "block_hash": b"\x00" * 32,
            }
        )
        st.current_justified_checkpoint = {
            "epoch": rng.randrange(1 << 20),
            "root": bytes(nprng.integers(0, 256, 32, dtype=np.uint8)),
        }
    elif op == 8:  # whole-column rewrite (epoch transition's shape)
        st.balances = (
            st.balances.astype(np.int64) + rng.randint(0, 1000)
        ).astype(np.uint64)
    else:  # inactivity churn
        idx = nprng.integers(0, n, rng.randint(1, 8))
        st.inactivity_scores[idx] += np.uint64(4)


def _run_equivalence(n_validators: int, steps: int, seed: int) -> None:
    rng = random.Random(seed)
    nprng = np.random.default_rng(seed)
    st = _synthetic_state(n_validators, seed)
    assert st.hash_tree_root() == _full_root(st)  # cold build
    states = [st]
    for step in range(steps):
        target = rng.choice(states)
        _mutate_once(target, rng, nprng)
        if rng.random() < 0.2 and len(states) < 4:
            # clone-on-write: both sides of the fork must stay correct
            states.append(target.clone())
        if rng.random() < 0.15:
            # empty-slot advance; crossing a boundary runs the real
            # epoch transition (incl. the participation rotation hint)
            process_slots(target, target.slot + rng.randint(1, 3))
        check = rng.sample(states, min(2, len(states)))
        for s in check:
            assert s.hash_tree_root() == _full_root(s), (
                f"divergence at step {step}"
            )
    for s in states:
        assert s.hash_tree_root() == _full_root(s)


def test_randomized_equivalence_small():
    _run_equivalence(n_validators=24, steps=40, seed=3)


def test_randomized_equivalence_epoch_boundaries():
    rng = random.Random(11)
    nprng = np.random.default_rng(11)
    st = _synthetic_state(16, 11)
    for _ in range(3):
        # drive across whole epochs with interleaved mutations
        _mutate_once(st, rng, nprng)
        process_slots(st, st.slot + P.SLOTS_PER_EPOCH)
        assert st.hash_tree_root() == _full_root(st)


def test_clone_shares_engine_copy_on_write():
    st = _synthetic_state(12, 5)
    root0 = st.hash_tree_root()
    c = st.clone()
    assert c.hash_tree_root() == root0  # warm tree inherited
    c.increase_balance(0, 1234)
    assert c.hash_tree_root() != root0
    assert st.hash_tree_root() == root0  # original cache unpoisoned
    assert c.hash_tree_root() == _full_root(c)


def test_untracked_mutation_is_still_caught():
    """The conservative-invalidation invariant: mutations that bypass
    every setter (raw attribute/array writes) must still be reflected —
    dirty tracking is diff-based, not trust-based."""
    st = _synthetic_state(10, 9)
    st.hash_tree_root()
    st.balances[7] = np.uint64(1)  # raw in-place write, no API
    st.pubkeys[3] = b"\x42" * 48  # even an "immutable" column edit
    st.genesis_time = 777
    assert st.hash_tree_root() == _full_root(st)


def test_full_mode_env_switch(monkeypatch):
    st = _synthetic_state(8, 4)
    incremental = st.hash_tree_root()
    monkeypatch.setenv("LODESTAR_TPU_HTR", "full")
    assert st.hash_tree_root() == incremental


@pytest.mark.slow
def test_randomized_equivalence_large():
    _run_equivalence(n_validators=2_000, steps=25, seed=21)


def test_engine_bytes_counts_cow_shared_planes_once():
    """state_root_engine_bytes is a RESIDENCY metric: a freshly cloned
    state shares every plane copy-on-write and must cost ~nothing; a
    diverged clone pays only for the planes it owned."""
    from lodestar_tpu.state_transition.state_root import (
        state_root_engine_bytes,
    )

    st = _synthetic_state(16, 7)
    st.hash_tree_root()
    alone = state_root_engine_bytes([st])
    assert alone > 0
    assert state_root_engine_bytes([st]) == alone  # walking is read-only

    c = st.clone()
    both = state_root_engine_bytes([st, c])
    assert both == alone  # fully COW-shared: counted once

    c.increase_balance(0, 999)
    c.hash_tree_root()  # diverge: balances planes (and tree) now owned
    diverged = state_root_engine_bytes([st, c])
    assert alone < diverged < 2 * alone

    # per-engine accounting still reports full (virtual) size
    assert c._root_engine.engine_bytes() >= alone // 2


def test_regen_engine_bytes_walks_the_lru():
    from lodestar_tpu.chain.regen import StateRegenerator

    st = _synthetic_state(12, 3)
    regen = StateRegenerator(fork_choice=object(), db=None)
    regen.on_imported_block(b"\x11" * 32, st)
    assert any(s is st for s in regen.live_states())
    first = regen.engine_bytes()
    assert first > 0
    # clones cached under other roots share planes COW: no double count
    regen.state_cache.add_with_root("ff" * 32, st.clone())
    assert regen.engine_bytes() == first


def test_randomized_equivalence_device_backend():
    """The full BeaconState engine under the DEVICE merkleization
    backend (ISSUE 16): the same randomized mutation surface, every
    root bit-identical to the cold full recompute — with the device
    actually carrying sweeps, not silently gated out."""
    pytest.importorskip("jax")
    from lodestar_tpu.bls.supervisor import DeviceSupervisor
    from lodestar_tpu.ssz import device_backend as DB
    from lodestar_tpu.utils.metrics import Registry

    reg = Registry()
    backend = DB.DeviceMerkleBackend(
        supervisor=DeviceSupervisor(
            registry=reg, auto_probe=False, enabled=True
        ),
        registry=reg,
        min_level_rows=1,
        use_export=False,
    )
    DB.set_backend(backend)
    try:
        _run_equivalence(n_validators=32, steps=12, seed=16)
        assert backend.dispatches > 0
        assert backend.supervisor.status()["state"] == "closed"
    finally:
        DB.reset_backend()
